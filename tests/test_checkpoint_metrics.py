"""Checkpoint/resume + metrics (rebuild-over-reference subsystems; the
reference has neither — SURVEY.md §5 rows "Checkpoint / resume" and
"Metrics / logging").
"""

import json
import os

import numpy as np
import pytest

from distkeras_tpu import ADAG, Dataset, OneHotTransformer
from distkeras_tpu.checkpoint import Checkpointer
from distkeras_tpu.metrics import EpochMetrics, MetricsLogger

from test_trainers import make_dataset, make_model, eval_accuracy


def test_checkpointer_roundtrip_pytree(tmp_path):
    ck = Checkpointer(str(tmp_path))
    state = {"params": [np.arange(6, dtype=np.float32).reshape(2, 3),
                        np.ones((4,), np.float32)],
             "step": np.int32(7)}
    ck.save(1, state)
    target = {"params": [np.zeros((2, 3), np.float32),
                         np.zeros((4,), np.float32)],
              "step": np.int32(0)}
    restored = ck.restore(target)
    np.testing.assert_array_equal(restored["params"][0], state["params"][0])
    assert int(restored["step"]) == 7


def test_checkpointer_retention_and_latest(tmp_path):
    ck = Checkpointer(str(tmp_path), max_to_keep=2)
    for s in (1, 2, 3, 4):
        ck.save(s, [np.full((2,), float(s))])
    assert ck.all_steps() == [3, 4]
    assert ck.latest_step() == 4
    restored = ck.restore([np.zeros((2,))], step=3)
    np.testing.assert_array_equal(restored[0], [3.0, 3.0])


def test_checkpointer_structure_mismatch_raises(tmp_path):
    ck = Checkpointer(str(tmp_path))
    ck.save(1, [np.zeros((2,))])
    with pytest.raises(ValueError, match="structure mismatch"):
        ck.restore([np.zeros((2,)), np.zeros((2,))])
    with pytest.raises(ValueError, match="shape"):
        ck.restore([np.zeros((3,))])


def test_trainer_checkpoint_resume_exact(eight_devices, tmp_path):
    """A run interrupted after epoch 1 and resumed matches the uninterrupted
    2-epoch run bit-for-bit (deterministic SPMD — SURVEY.md §5 race note)."""
    ds = make_dataset(n=512)
    kw = dict(num_workers=8, batch_size=8, num_epoch=2,
              communication_window=4, label_col="label_encoded",
              worker_optimizer="sgd", learning_rate=0.1, seed=3)

    full = ADAG(make_model(), **kw)
    fitted_full = full.train(ds)

    ck_dir = str(tmp_path / "ck")
    first = ADAG(make_model(), checkpoint_dir=ck_dir, **dict(kw, num_epoch=1))
    first.train(ds)
    assert Checkpointer(ck_dir).latest_step() == 1

    second = ADAG(make_model(), checkpoint_dir=ck_dir, **kw)
    fitted_resumed = second.train(ds, resume=True)

    for a, b in zip(fitted_full.get_weights(), fitted_resumed.get_weights()):
        np.testing.assert_allclose(a, b, atol=1e-6)


def test_metrics_logger_jsonl(tmp_path):
    path = str(tmp_path / "metrics.jsonl")
    m = EpochMetrics(MetricsLogger(path), num_chips=4)
    m.epoch(0, examples=4096, seconds=2.0, mean_loss=0.5)
    m.logger.close()
    events = [json.loads(l) for l in open(path)]
    assert events[0]["examples_per_sec"] == 2048.0
    assert events[0]["examples_per_sec_per_chip"] == 512.0
    assert events[0]["loss"] == 0.5


def test_trainer_emits_metrics(eight_devices, tmp_path):
    ds = make_dataset(n=512)
    path = str(tmp_path / "m.jsonl")
    t = ADAG(make_model(), num_workers=8, batch_size=8, num_epoch=2,
             communication_window=4, label_col="label_encoded",
             learning_rate=0.1, metrics_path=path)
    t.train(ds)
    assert len(t.metrics) == 2
    assert all(e["examples_per_sec_per_chip"] > 0 for e in t.metrics)
    assert os.path.exists(path) and len(open(path).readlines()) == 2
