"""Checkpoint/resume + metrics (rebuild-over-reference subsystems; the
reference has neither — SURVEY.md §5 rows "Checkpoint / resume" and
"Metrics / logging").
"""

import json
import os

import numpy as np
import pytest

from distkeras_tpu import ADAG, Dataset, OneHotTransformer
from distkeras_tpu.checkpoint import Checkpointer
from distkeras_tpu.metrics import EpochMetrics, MetricsLogger

from test_trainers import make_dataset, make_model, eval_accuracy


def test_checkpointer_roundtrip_pytree(tmp_path):
    ck = Checkpointer(str(tmp_path))
    state = {"params": [np.arange(6, dtype=np.float32).reshape(2, 3),
                        np.ones((4,), np.float32)],
             "step": np.int32(7)}
    ck.save(1, state)
    target = {"params": [np.zeros((2, 3), np.float32),
                         np.zeros((4,), np.float32)],
              "step": np.int32(0)}
    restored = ck.restore(target)
    np.testing.assert_array_equal(restored["params"][0], state["params"][0])
    assert int(restored["step"]) == 7


def test_checkpointer_retention_and_latest(tmp_path):
    ck = Checkpointer(str(tmp_path), max_to_keep=2)
    for s in (1, 2, 3, 4):
        ck.save(s, [np.full((2,), float(s))])
    assert ck.all_steps() == [3, 4]
    assert ck.latest_step() == 4
    restored = ck.restore([np.zeros((2,))], step=3)
    np.testing.assert_array_equal(restored[0], [3.0, 3.0])


def test_checkpointer_structure_mismatch_raises(tmp_path):
    ck = Checkpointer(str(tmp_path))
    ck.save(1, [np.zeros((2,))])
    with pytest.raises(ValueError, match="structure mismatch"):
        ck.restore([np.zeros((2,)), np.zeros((2,))])
    with pytest.raises(ValueError, match="shape"):
        ck.restore([np.zeros((3,))])


def test_trainer_checkpoint_resume_exact(eight_devices, tmp_path):
    """A run interrupted after epoch 1 and resumed matches the uninterrupted
    2-epoch run bit-for-bit (deterministic SPMD — SURVEY.md §5 race note)."""
    ds = make_dataset(n=512)
    kw = dict(num_workers=8, batch_size=8, num_epoch=2,
              communication_window=4, label_col="label_encoded",
              worker_optimizer="sgd", learning_rate=0.1, seed=3)

    full = ADAG(make_model(), **kw)
    fitted_full = full.train(ds)

    ck_dir = str(tmp_path / "ck")
    first = ADAG(make_model(), checkpoint_dir=ck_dir, **dict(kw, num_epoch=1))
    first.train(ds)
    assert Checkpointer(ck_dir).latest_step() == 1

    second = ADAG(make_model(), checkpoint_dir=ck_dir, **kw)
    fitted_resumed = second.train(ds, resume=True)

    for a, b in zip(fitted_full.get_weights(), fitted_resumed.get_weights()):
        np.testing.assert_allclose(a, b, atol=1e-6)


def _orbax_or_skip():
    try:
        import orbax.checkpoint  # noqa: F401
    except ImportError:
        pytest.skip("orbax not installed")


def test_orbax_checkpointer_roundtrip(tmp_path):
    """OrbaxCheckpointer honors the Checkpointer interface: save/restore/
    latest_step/read_meta/retention, including async-save durability."""
    _orbax_or_skip()
    from distkeras_tpu.checkpoint import OrbaxCheckpointer
    ck = OrbaxCheckpointer(str(tmp_path), max_to_keep=2)
    state = {"params": [np.arange(6, dtype=np.float32).reshape(2, 3),
                        np.ones((4,), np.float32)],
             "step": np.int32(7)}
    for s in (1, 2, 3):
        ck.save(s, state, meta={"unit": "epoch", "k": s})
    ck.wait()
    assert ck.latest_step() == 3
    assert ck.all_steps() == [2, 3]  # retention
    assert ck.read_meta(3) == {"unit": "epoch", "k": 3}
    target = {"params": [np.zeros((2, 3), np.float32),
                         np.zeros((4,), np.float32)],
              "step": np.int32(0)}
    restored = ck.restore(target)
    np.testing.assert_array_equal(restored["params"][0], state["params"][0])
    assert int(restored["step"]) == 7
    ck.close()


def test_orbax_backend_resume_matches_npz(eight_devices, tmp_path):
    """checkpoint_backend='orbax' resumes to the same weights as the npz
    backend (same interrupted-then-resumed schedule, same data/seed)."""
    _orbax_or_skip()
    ds = make_dataset(n=256)
    kw = dict(num_workers=8, batch_size=8, num_epoch=2,
              communication_window=2, label_col="label_encoded",
              worker_optimizer="sgd", learning_rate=0.1, seed=3)

    weights = {}
    for backend in ("npz", "orbax"):
        ck_dir = str(tmp_path / backend)
        first = ADAG(make_model(), checkpoint_dir=ck_dir,
                     checkpoint_backend=backend, **dict(kw, num_epoch=1))
        first.train(ds)
        second = ADAG(make_model(), checkpoint_dir=ck_dir,
                      checkpoint_backend=backend, **kw)
        weights[backend] = second.train(ds, resume=True).get_weights()

    for a, b in zip(weights["npz"], weights["orbax"]):
        np.testing.assert_allclose(a, b, atol=0)


def test_unknown_checkpoint_backend_rejected():
    with pytest.raises(ValueError, match="checkpoint_backend"):
        ADAG(make_model(), num_workers=8, checkpoint_backend="s3")


def test_resume_with_wrong_backend_refused(eight_devices, tmp_path):
    """resume=True must not silently retrain from scratch when the
    directory holds the other backend's checkpoints."""
    _orbax_or_skip()
    ds = make_dataset(n=128)
    kw = dict(num_workers=8, batch_size=4, num_epoch=1,
              communication_window=2, label_col="label_encoded",
              worker_optimizer="sgd", learning_rate=0.1, seed=3)
    ck_dir = str(tmp_path / "ck")
    ADAG(make_model(), checkpoint_dir=ck_dir, **kw).train(ds)  # npz save
    wrong = ADAG(make_model(), checkpoint_dir=ck_dir,
                 checkpoint_backend="orbax", **dict(kw, num_epoch=2))
    with pytest.raises(ValueError, match="other backend"):
        wrong.train(ds, resume=True)
    # host_ps path refuses the same way
    wrong_ps = ADAG(make_model(), checkpoint_dir=ck_dir,
                    checkpoint_backend="orbax", execution="host_ps",
                    **dict(kw, num_epoch=2))
    with pytest.raises(ValueError, match="other backend"):
        wrong_ps.train(ds, resume=True)


def test_flops_accounting_gqa_and_window():
    """GQA must shrink only the k/v projection FLOPs (round-3 VERDICT weak
    #8: k/v were counted full-width, inflating MFU on GQA models); a sliding
    window must cap the score/value matmul context."""
    from distkeras_tpu.core.layers import TransformerBlock
    from distkeras_tpu.core.model import Sequential
    from distkeras_tpu.metrics import flops_per_example

    s, d, h, dh, mlp = 64, 32, 8, 4, 128

    def flops(**kw):
        m = Sequential([TransformerBlock(h, dh, mlp, causal=True, **kw)],
                       input_shape=(s, d))
        return flops_per_example(m, backward=False)

    mha, gqa = flops(), flops(num_kv_heads=2)
    inner = h * dh
    # exact closed forms: q+o and scores are unchanged; k/v shrink by 8/2
    expected_mha = 2*s*d*(inner + 2*inner) + 2*s*inner*d + 4*s*s*inner \
        + 2*s*d*mlp*2
    expected_gqa = 2*s*d*(inner + 2*(2*dh)) + 2*s*inner*d + 4*s*s*inner \
        + 2*s*d*mlp*2
    assert mha == expected_mha
    assert gqa == expected_gqa
    assert gqa < mha
    # sliding window caps the context of the two score matmuls at w+1
    w = 15
    windowed = flops(attention_window=w)
    assert windowed == expected_mha - 4*s*inner*(s - (w + 1))
    # backward applies the standard 3x rule on top
    m = Sequential([TransformerBlock(h, dh, mlp, causal=True)],
                   input_shape=(s, d))
    assert flops_per_example(m, backward=True) == 3 * mha


def test_metrics_logger_jsonl(tmp_path):
    path = str(tmp_path / "metrics.jsonl")
    m = EpochMetrics(MetricsLogger(path), num_chips=4)
    m.epoch(0, examples=4096, seconds=2.0, mean_loss=0.5)
    m.logger.close()
    events = [json.loads(l) for l in open(path)]
    assert events[0]["examples_per_sec"] == 2048.0
    assert events[0]["examples_per_sec_per_chip"] == 512.0
    assert events[0]["loss"] == 0.5


def test_trainer_emits_metrics(eight_devices, tmp_path):
    ds = make_dataset(n=512)
    path = str(tmp_path / "m.jsonl")
    t = ADAG(make_model(), num_workers=8, batch_size=8, num_epoch=2,
             communication_window=4, label_col="label_encoded",
             learning_rate=0.1, metrics_path=path)
    t.train(ds)
    assert len(t.metrics) == 2
    assert all(e["examples_per_sec_per_chip"] > 0 for e in t.metrics)
    assert os.path.exists(path) and len(open(path).readlines()) == 2


def test_round_granular_checkpoint_resume_bit_identical(eight_devices,
                                                        tmp_path):
    """Round-2 VERDICT weak #6: mid-epoch kill/resume.  With
    checkpoint_unit='round' the trainer checkpoints on the global round
    clock; a run killed mid-epoch and resumed produces bit-identical final
    weights to the uninterrupted run."""
    ds = make_dataset(n=512)
    kw = dict(num_workers=8, batch_size=8, num_epoch=2,
              communication_window=2, label_col="label_encoded",
              worker_optimizer="adam", learning_rate=1e-3, seed=3)
    # rpe = 512 / (8*2*8) = 4 rounds/epoch -> 8 global rounds over 2 epochs

    full = ADAG(make_model(), **kw)
    fitted_full = full.train(ds, shuffle=True)

    ck_dir = str(tmp_path / "ck_round")
    first = ADAG(make_model(), checkpoint_dir=ck_dir, checkpoint_unit="round",
                 checkpoint_every=1, **kw)
    fitted_first = first.train(ds, shuffle=True)
    # round mode == epoch mode bit-for-bit (same round program)
    for a, b in zip(fitted_full.get_weights(), fitted_first.get_weights()):
        np.testing.assert_array_equal(a, b)

    ck = Checkpointer(ck_dir)
    assert ck.latest_step() == 8
    # simulate a kill after round 7 (mid-epoch 2): drop the final checkpoint
    os.unlink(ck._path(8))
    assert ck.latest_step() == 7

    resumed = ADAG(make_model(), checkpoint_dir=ck_dir,
                   checkpoint_unit="round", checkpoint_every=1, **kw)
    fitted_resumed = resumed.train(ds, shuffle=True, resume=True)
    for a, b in zip(fitted_full.get_weights(), fitted_resumed.get_weights()):
        np.testing.assert_array_equal(a, b)
    # only the one remaining round of epoch 2 was re-trained
    assert len(resumed.get_history()) == 1


def test_host_ps_checkpoint_resume(eight_devices, tmp_path):
    """host_ps checkpoint/resume (round-2 VERDICT: was NotImplementedError):
    epoch-wave checkpoints serialize PS center+clock and per-worker
    optimizer state; a resumed run continues the clock and trains to the
    same quality."""
    ds = make_dataset(n=512)
    kw = dict(num_workers=2, batch_size=8, num_epoch=4,
              communication_window=2, label_col="label_encoded",
              worker_optimizer="adam", learning_rate=5e-3, seed=3,
              execution="host_ps")

    ck_dir = str(tmp_path / "ck_psfull")
    full = ADAG(make_model(), checkpoint_dir=ck_dir, **kw)
    fitted_full = full.train(ds)
    assert Checkpointer(ck_dir).latest_step() == 4
    assert eval_accuracy(fitted_full, ds) > 0.8

    # interrupted run: 2 epochs, then resume to 4
    ck_dir2 = str(tmp_path / "ck_ps")
    first = ADAG(make_model(), checkpoint_dir=ck_dir2,
                 **dict(kw, num_epoch=2))
    first.train(ds)
    assert Checkpointer(ck_dir2).latest_step() == 2

    resumed = ADAG(make_model(), checkpoint_dir=ck_dir2, **kw)
    fitted_resumed = resumed.train(ds, resume=True)
    assert Checkpointer(ck_dir2).latest_step() == 4
    # per worker: ceil(256/(2*8)) = 16 windows/epoch, 2 remaining epochs
    assert len(resumed.get_history()) == 2 * 2 * 16
    assert eval_accuracy(fitted_resumed, ds) > 0.8

    # the PS clock continued rather than restarting: the final checkpoint's
    # clock equals windows * workers * all 4 epochs (every window commits)
    state = Checkpointer(ck_dir2).restore(
        _host_ps_state_template(resumed), 4)
    assert int(state["clock"]) == 4 * 2 * 16


def _host_ps_state_template(trainer):
    """Rebuild the host-PS checkpoint pytree structure for restore()."""
    import jax

    from distkeras_tpu.core import optimizers as opt_lib

    model = trainer.master_model
    params = model.init(jax.random.PRNGKey(0), (16,))
    tx, opt0 = opt_lib.build(trainer.worker_optimizer, params,
                             trainer.learning_rate)
    center = [np.asarray(w) for w in model.get_weights(params)]
    n = trainer.num_workers
    return {"center": center, "clock": np.int64(0),
            "workers": [(params, opt0) for _ in range(n)]}


def test_checkpoint_unit_mismatch_refused(eight_devices, tmp_path):
    """A step number only means what the saving run meant by it: resuming an
    epoch-unit directory as round-unit (or across engines) must refuse."""
    ds = make_dataset(n=512)
    kw = dict(num_workers=8, batch_size=8, num_epoch=1,
              communication_window=2, label_col="label_encoded",
              worker_optimizer="sgd", learning_rate=0.1, seed=3)
    ck_dir = str(tmp_path / "ck_unit")
    ADAG(make_model(), checkpoint_dir=ck_dir, **kw).train(ds)

    with pytest.raises(ValueError, match="checkpoint_unit"):
        ADAG(make_model(), checkpoint_dir=ck_dir, checkpoint_unit="round",
             **dict(kw, num_epoch=2)).train(ds, resume=True)
    with pytest.raises(ValueError, match="engine"):
        ADAG(make_model(), checkpoint_dir=ck_dir, execution="host_ps",
             **dict(kw, num_workers=2, num_epoch=2)).train(ds, resume=True)
