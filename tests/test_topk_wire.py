"""Tests for sparse top-k delta compression across the host-PS stack
(``wire_dtype="topk"``): device-side selection, the sparse wire node,
scatter-add apply, sharded index bisection, and the acceptance observables —
commit bytes ≤ 5% of dense at density 0.01 (byte-counting socket double),
exactly one 'u' round trip per window preserved, and an MNIST-style MLP
converging to the same loss band as dense under DOWNPOUR and ADAG at
``ps_shards`` 1 and 3."""

import threading

import jax
import numpy as np
import pytest

from distkeras_tpu import (ADAG, DOWNPOUR, Dataset, Dense, OneHotTransformer,
                           Sequential, networking)
from distkeras_tpu.core.model import serialize_model
from distkeras_tpu.parameter_servers import (DeltaParameterServer,
                                             SocketParameterServer,
                                             _scatter_add)
from distkeras_tpu.workers import DOWNPOURWorker, topk_select

from test_host_ps import make_dataset, make_model
from test_host_ps_overlap import _OpcodeRecorder


# ---------------------------------------------------------------------------
# fixtures: an MNIST-shaped MLP workload (784-dim inputs, 10 classes)
# ---------------------------------------------------------------------------

def make_mnist_like(n=768, d=784, classes=10, seed=0):
    rng = np.random.default_rng(seed)
    protos = rng.uniform(0.0, 1.0, (classes, d)) * (rng.random((classes, d))
                                                    > 0.5)
    labels = rng.integers(0, classes, n)
    x = np.clip(protos[labels] + 0.25 * rng.standard_normal((n, d)),
                0.0, 1.0).astype(np.float32)
    ds = Dataset({"features": x, "label": labels.astype(np.int64)})
    return OneHotTransformer(classes, input_col="label",
                             output_col="label_encoded").transform(ds)


def make_mlp():
    return Sequential([Dense(64, activation="relu"),
                       Dense(10, activation="softmax")],
                      input_shape=(784,), compute_dtype="float32")


def _mlp_blob():
    m = make_mlp()
    return serialize_model(m, m.init(jax.random.PRNGKey(0)))


class _WireBytesRecorder:
    """Byte-counting socket double over the worker→PS stream: every frame
    ``send_data`` ships is re-encoded through the public codec and counted
    against the opcode that preceded it on that socket."""

    def __init__(self):
        self.bytes_by_op: dict = {}
        self.frames_by_op: dict = {}
        self._last_op: dict = {}
        self._lock = threading.Lock()

    def __enter__(self):
        self._orig_op = networking.send_opcode
        self._orig_data = networking.send_data

        def rec_op(sock, op):
            with self._lock:
                self._last_op[id(sock)] = op
            self._orig_op(sock, op)

        def rec_data(sock, obj, pool=None):
            blob = networking.encode_message(obj)
            with self._lock:
                op = self._last_op.get(id(sock), b"?")
                self.bytes_by_op[op] = self.bytes_by_op.get(op, 0) \
                    + len(blob) + 1
                self.frames_by_op[op] = self.frames_by_op.get(op, 0) + 1
            sock.sendall(blob)

        networking.send_opcode = rec_op
        networking.send_data = rec_data
        return self

    def __exit__(self, *exc):
        networking.send_opcode = self._orig_op
        networking.send_data = self._orig_data


# ---------------------------------------------------------------------------
# selection semantics
# ---------------------------------------------------------------------------

def test_topk_commit_is_sparse_with_error_feedback():
    """A host-path topk commit ships a SparseDelta of exactly k = ⌈density·n⌉
    coordinates, and eff == densify(applied) + residual exactly — the unsent
    mass telescopes into the next commit (EF-SGD)."""
    blob = _mlp_blob()
    wk = DOWNPOURWorker(blob, "sgd", "mse", "127.0.0.1", 1,
                        wire_dtype="topk", wire_topk=0.01)
    sent = []
    wk._sock = object()
    orig_op, orig_send = networking.send_opcode, networking.send_data
    networking.send_opcode = lambda s, op: None
    networking.send_data = lambda s, msg: sent.append(msg)
    try:
        rng = np.random.default_rng(1)
        d1 = [rng.standard_normal(np.shape(w)).astype(np.float32) * 0.01
              for w in blob["weights"]]
        a1 = wk.commit(d1, 0)
        total = sum(int(np.prod(np.shape(w))) for w in blob["weights"])
        k = int(np.ceil(0.01 * total))
        sp = sent[0]["delta"]
        assert isinstance(sp, networking.SparseDelta)
        assert sp.nnz == k and sp.length == total
        assert sp.indices.dtype == np.int32
        assert np.all(np.diff(sp.indices) > 0)  # sorted, unique
        flat_d1 = np.concatenate([d.reshape(-1) for d in d1])
        flat_a1 = np.concatenate([a.reshape(-1) for a in a1])
        np.testing.assert_allclose(flat_d1, flat_a1 + wk._residual_flat,
                                   atol=1e-7)
        # selection is by magnitude: every selected value dominates every
        # residual (unselected) coordinate
        assert np.min(np.abs(sp.f32_values())) >= \
            np.max(np.abs(wk._residual_flat)) - 1e-7
        # second window: the residual mass rides into the next commit
        d2 = [rng.standard_normal(np.shape(w)).astype(np.float32) * 0.01
              for w in blob["weights"]]
        r1 = wk._residual_flat.copy()
        a2 = wk.commit(d2, 0)
        flat = np.concatenate([d.reshape(-1) for d in d2]) + r1
        flat_a2 = np.concatenate([a.reshape(-1) for a in a2])
        np.testing.assert_allclose(flat, flat_a2 + wk._residual_flat,
                                   atol=1e-7)
    finally:
        networking.send_opcode, networking.send_data = orig_op, orig_send


def test_device_selection_matches_host_delta():
    """The jitted device-side pass (selection inside the window program)
    agrees with the host reference: only k values + int32 indices come back,
    densify(selected) + residual reproduces the full window delta, and the
    selected magnitudes dominate the residual."""
    blob = _mlp_blob()
    wk = DOWNPOURWorker(blob, "sgd", "mse", "127.0.0.1", 1,
                        wire_dtype="topk", wire_topk=0.01, batch_size=16)
    wk._ensure_model()
    params = jax.tree_util.tree_map(jax.numpy.array, wk._params0)
    base = np.concatenate([np.asarray(w).reshape(-1)
                           for w in wk._params_to_weights(params)])
    rng = np.random.default_rng(0)
    xw = rng.standard_normal((4, 16, 784)).astype(np.float32)
    yw = np.eye(10, dtype=np.float32)[rng.integers(0, 10, (4, 16))]
    mw = np.ones((4, 16), np.float32)
    key = jax.random.PRNGKey(0)
    params, _, loss, codes, idx, scale = wk._run_topk_window(
        params, wk._tx.init(params), xw, yw, mw, key)
    sp = wk._fetch_sparse(codes, idx, scale)
    assert sp.nnz == wk._wire_k and sp.indices.dtype == np.int32
    after = np.concatenate([np.asarray(w).reshape(-1)
                            for w in wk._params_to_weights(params)])
    res = np.asarray(wk._residual_dev)
    np.testing.assert_allclose(after - base, sp.to_dense() + res, atol=1e-5)
    assert np.min(np.abs(sp.f32_values())) >= np.max(np.abs(res)) - 1e-5


@pytest.mark.parametrize("code", ["bfloat16", "int8"])
def test_topk_value_coding_error_goes_to_residual(code):
    """bf16/int8-coded values on top of the sparse node: the coding error
    lands in the residual (eff == applied + residual still holds exactly),
    and the wire values really are the coded dtype."""
    rng = np.random.default_rng(2)
    eff = rng.standard_normal(500).astype(np.float32) * 0.01
    idx, wire, applied, scale, res = topk_select(eff, 50, code)
    if code == "int8":
        assert wire.dtype == np.int8 and scale is not None
        np.testing.assert_allclose(applied, wire.astype(np.float32) * scale,
                                   rtol=1e-6)
    else:
        import ml_dtypes
        assert wire.dtype == np.dtype(ml_dtypes.bfloat16) and scale is None
    dense = np.zeros_like(eff)
    dense[idx] = applied
    np.testing.assert_allclose(eff, dense + res, atol=1e-7)
    # coded values decode identically through the wire node
    sp = networking.SparseDelta(idx, wire, eff.size, scale)
    np.testing.assert_allclose(sp.f32_values(), applied, rtol=1e-6)


def test_update_opcode_topk_roundtrip():
    """A density-1.0 topk 'u' commit is the dense commit, bit for bit at the
    apply: the reply center equals center0 + delta and the PS stays f32."""
    blob = _mlp_blob()
    ps = DeltaParameterServer(blob)
    server = SocketParameterServer(ps)
    server.start()
    try:
        wk = DOWNPOURWorker(blob, "sgd", "mse", "127.0.0.1", server.port,
                            wire_dtype="topk", wire_topk=1.0)
        wk.connect()
        center0 = [np.array(w) for w in wk.pull()]
        delta = [np.full(np.shape(w), 0.25, np.float32) for w in center0]
        applied, center = wk.update(delta, 0)
        assert wk._last_clock == 1
        for c0, c, a in zip(center0, center, applied):
            np.testing.assert_allclose(np.asarray(c), c0 + a, atol=1e-6)
            np.testing.assert_allclose(a, 0.25, atol=1e-6)
        assert all(w.dtype == np.float32 for w in ps.center)
        wk.disconnect()
    finally:
        server.stop()


def test_scatter_add_matches_dense_apply():
    """PS-side O(k) scatter-add == dense apply of the densified delta, for
    every rule scale, across tensor boundaries and row splits."""
    rng = np.random.default_rng(3)
    shapes = [(16, 32), (32,), (32, 4), (4,), ()]
    center_a = [rng.standard_normal(s).astype(np.float32) for s in shapes]
    center_b = [c.copy() for c in center_a]
    total = sum(int(np.prod(s)) for s in shapes)
    idx = np.sort(rng.choice(total, 37, replace=False)).astype(np.int32)
    vals = rng.standard_normal(37).astype(np.float32)
    sp = networking.SparseDelta(idx, vals, total)
    _scatter_add(center_a, sp, 0.5)
    dense = sp.to_dense() * 0.5
    off = 0
    for c in center_b:
        c += dense[off:off + c.size].reshape(c.shape)
        off += c.size
    for a, b in zip(center_a, center_b):
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# acceptance: bytes, round trips, convergence
# ---------------------------------------------------------------------------

def _one_commit_bytes(**wire_kw):
    blob = _mlp_blob()
    ps = DeltaParameterServer(blob)
    server = SocketParameterServer(ps)
    server.start()
    try:
        wk = DOWNPOURWorker(blob, "sgd", "mse", "127.0.0.1", server.port,
                            **wire_kw)
        wk.connect()
        rng = np.random.default_rng(0)
        delta = [rng.standard_normal(np.shape(w)).astype(np.float32) * 0.01
                 for w in blob["weights"]]
        with _WireBytesRecorder() as rec:
            wk.update(delta, 0)
        wk.disconnect()
        return rec.bytes_by_op[b"u"]
    finally:
        server.stop()


def test_topk_commit_bytes_at_most_5pct_of_dense():
    """ACCEPTANCE: at wire_topk=0.01 the measured per-window commit bytes
    (byte-counting socket double) are ≤ 5% of the dense commit."""
    dense = _one_commit_bytes()
    topk = _one_commit_bytes(wire_dtype="topk", wire_topk=0.01)
    assert topk <= 0.05 * dense, (topk, dense)
    # int8-coded values squeeze the sparse payload further still
    topk8 = _one_commit_bytes(wire_dtype="topk", wire_topk=0.01,
                              wire_topk_dtype="int8")
    assert topk8 < topk


def test_topk_overlap_one_rtt_per_window_and_byte_win():
    """ACCEPTANCE: end to end, topk keeps the pipelined transport contract —
    exactly one 'u' round trip per communication window, zero 'c'/'p' pairs
    — while the measured commit ('u') bytes stay ≤ 5% of the same run dense.
    """
    ds = make_mnist_like(n=512)

    def run(**kw):
        t = DOWNPOUR(make_mlp(), num_workers=2, batch_size=32, num_epoch=2,
                     communication_window=4, learning_rate=0.05,
                     label_col="label_encoded", execution="host_ps", **kw)
        with _OpcodeRecorder() as ops, _WireBytesRecorder() as wire:
            t.train(ds)
        return t, ops, wire

    t, ops, wire = run(wire_dtype="topk", wire_topk=0.01)
    # 512 rows / 2 workers = 256 each; window*batch = 128 → 2 windows per
    # epoch per worker × 2 epochs × 2 workers = 8 windows
    windows = 8
    assert ops.count(b"u") == windows
    assert ops.count(b"c") == 0
    assert ops.count(b"p") == 2  # one initial pull per worker
    for w in t._ps_workers:
        assert w.transport_ops == 1 + w._commits
    _, _, dense_wire = run()
    topk_per = wire.bytes_by_op[b"u"] / wire.frames_by_op[b"u"]
    dense_per = dense_wire.bytes_by_op[b"u"] / dense_wire.frames_by_op[b"u"]
    assert topk_per <= 0.05 * dense_per, (topk_per, dense_per)


_DENSE_BAND = {}


def _center_ce(fitted, ds):
    p = np.asarray(fitted.predict(ds["features"]))
    picked = p[np.arange(len(p)), np.asarray(ds["label"])]
    return float(-np.mean(np.log(np.clip(picked, 1e-9, 1.0))))


def _mlp_run(cls, lr, ds, **kw):
    t = cls(make_mlp(), num_workers=2, batch_size=32, num_epoch=3,
            communication_window=4, learning_rate=lr,
            label_col="label_encoded", execution="host_ps", **kw)
    fitted = t.train(ds)
    preds = np.argmax(np.asarray(fitted.predict(ds["features"])), axis=1)
    acc = float(np.mean(preds == np.asarray(ds["label"])))
    return _center_ce(fitted, ds), acc


@pytest.mark.parametrize("cls,lr,shards", [
    (DOWNPOUR, 0.05, 1),
    (DOWNPOUR, 0.05, 3),
    (ADAG, 0.1, 1),
    (ADAG, 0.1, 3),
])
def test_topk_mnist_mlp_converges_to_dense_loss_band(cls, lr, shards):
    """ACCEPTANCE: the MNIST-shaped MLP at wire_topk=0.01 converges to the
    same loss band as dense under DOWNPOUR and ADAG at ps_shards ∈ {1, 3}
    (fitted-center cross-entropy within a small additive band; accuracy
    matches)."""
    ds = make_mnist_like()
    key = (cls.__name__, lr)
    if key not in _DENSE_BAND:
        _DENSE_BAND[key] = _mlp_run(cls, lr, ds)
    dense_ce, dense_acc = _DENSE_BAND[key]
    ce, acc = _mlp_run(cls, lr, ds, wire_dtype="topk", wire_topk=0.01,
                       ps_shards=shards)
    assert ce <= dense_ce + 0.15, (ce, dense_ce)
    assert acc >= dense_acc - 0.02 and acc > 0.9, (acc, dense_acc)


# ---------------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------------

def test_wire_topk_validation():
    m = make_model()
    kw = dict(num_workers=2, label_col="label_encoded",
              execution="host_ps")
    t = ADAG(m, wire_dtype="topk", wire_topk=0.05, **kw)
    assert t.wire_dtype == "topk" and t.wire_topk == 0.05
    with pytest.raises(ValueError, match="wire_topk"):
        ADAG(m, wire_dtype="topk", wire_topk=0.0, **kw)
    with pytest.raises(ValueError, match="wire_topk"):
        ADAG(m, wire_dtype="topk", wire_topk=1.5, **kw)
    with pytest.raises(ValueError, match="wire_topk_dtype"):
        ADAG(m, wire_dtype="topk", wire_topk_dtype="float64", **kw)
    with pytest.raises(ValueError, match="wire_topk_dtype"):
        ADAG(m, wire_dtype="int8", wire_topk_dtype="int8", **kw)
    # worker-level eager validation too
    blob = _mlp_blob()
    with pytest.raises(ValueError, match="wire_topk"):
        DOWNPOURWorker(blob, "sgd", "mse", "127.0.0.1", 1,
                       wire_dtype="topk", wire_topk=2.0)
    wk = DOWNPOURWorker(blob, "sgd", "mse", "127.0.0.1", 1,
                        wire_dtype="topk", wire_topk=0.01)
    assert wk._topk_density == 0.01 and wk.wire_dtype is None
