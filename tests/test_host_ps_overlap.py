"""Tests for the pipelined host-PS transport: the combined ``'u'``
(commit+pull) opcode, the per-connection receive-buffer pool, connect
retry-with-backoff, and the ``comm_overlap`` double-buffered window loop —
the acceptance observable is ONE transport round trip per communication
window, counted by a test double on the opcode stream."""

import socket
import threading
import time

import numpy as np
import pytest

from distkeras_tpu import (ADAG, AEASGD, DOWNPOUR, Dataset, DynSGD, EAMSGD,
                           networking)
from distkeras_tpu.parameter_servers import (DeltaParameterServer,
                                             SocketParameterServer)
from distkeras_tpu.workers import DOWNPOURWorker

from test_host_ps import make_dataset, make_model


def _tiny_blob(n=3):
    return {"model": make_model().to_json(),
            "weights": [np.zeros((n,), np.float32)]}


# ---------------------------------------------------------------------------
# the 'u' opcode — atomic commit+pull in one round trip
# ---------------------------------------------------------------------------

def test_update_opcode_atomic_commit_plus_pull():
    """'u' applies the delta and replies with the center *including* that
    commit plus the advanced clock — one round trip, one lock acquisition."""
    ps = DeltaParameterServer(_tiny_blob())
    server = SocketParameterServer(ps)
    server.start()
    try:
        sock = networking.connect("127.0.0.1", server.port)
        networking.send_opcode(sock, b"u")
        networking.send_data(sock, {"delta": [np.ones(3, np.float32)],
                                    "worker_id": 0, "clock": 0})
        msg = networking.recv_data(sock)
        assert msg["clock"] == 1
        np.testing.assert_array_equal(msg["weights"][0], np.ones(3))
        sock.close()
    finally:
        server.stop()


@pytest.mark.parametrize("wire_dtype", ["bfloat16", "int8", "topk"])
def test_update_opcode_wire_dtypes_roundtrip(wire_dtype):
    """The compressed-commit paths (bf16 cast / int8 codes+scales / sparse
    top-k at density 1.0, where the selection is the whole delta) ride the
    'u' opcode: the PS decodes at the transport boundary, applies, and the
    reply center equals old center + the as-applied delta."""
    ps = DeltaParameterServer(_tiny_blob())
    server = SocketParameterServer(ps)
    server.start()
    try:
        kw = ({"wire_topk": 1.0} if wire_dtype == "topk" else {})
        wk = DOWNPOURWorker(_tiny_blob(), "sgd", "mse", "127.0.0.1",
                            server.port, wire_dtype=wire_dtype, **kw)
        wk.connect()
        center0 = [np.array(w) for w in wk.pull()]
        delta = [np.full(w.shape, 0.25, np.float32) for w in center0]
        applied, center = wk.update(delta, 0)
        assert wk._last_clock == 1
        for c0, c, a in zip(center0, center, applied):
            np.testing.assert_allclose(c, c0 + a, atol=1e-6)
            np.testing.assert_allclose(a, 0.25, atol=1e-2)
        # PS center stays f32 regardless of the wire dtype
        assert all(w.dtype == np.float32 for w in ps.center)
        wk.disconnect()
    finally:
        server.stop()


def test_update_torn_frame_drops_connection_server_survives():
    """A 'u' followed by a corrupt frame drops THAT connection (same
    torn-frame policy as 'c'); the server keeps serving other workers and
    the center is untouched."""
    ps = DeltaParameterServer(_tiny_blob())
    server = SocketParameterServer(ps)
    server.start()
    try:
        bad = networking.connect("127.0.0.1", server.port)
        networking.send_opcode(bad, b"u")
        bad.sendall(b"XXXX" + b"\x00" * 32)  # bad magic → ValueError → drop
        bad.settimeout(5.0)
        try:
            got = bad.recv(1)
        except (ConnectionError, OSError):
            got = b""
        assert got == b""  # server hung up on us
        bad.close()

        good = networking.connect("127.0.0.1", server.port)
        networking.send_opcode(good, b"u")
        networking.send_data(good, {"delta": [np.ones(3, np.float32)],
                                    "worker_id": 1, "clock": 0})
        msg = networking.recv_data(good)
        assert msg["clock"] == 1  # the torn frame applied nothing
        good.close()
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# receive-buffer pool
# ---------------------------------------------------------------------------

def test_buffer_pool_reuses_buffers_across_same_shape_pulls():
    pool = networking.BufferPool()
    a, b = socket.socketpair()
    msg = {"weights": [np.arange(64, dtype=np.float32).reshape(8, 8),
                       np.ones((5,), np.float32)], "clock": 2}
    try:
        for _ in range(3):
            t = threading.Thread(target=networking.send_data, args=(a, msg))
            t.start()
            out = networking.recv_data(b, pool=pool)
            t.join()
            np.testing.assert_array_equal(out["weights"][0],
                                          msg["weights"][0])
            np.testing.assert_array_equal(out["weights"][1],
                                          msg["weights"][1])
            assert out["clock"] == 2
        # same payload size every time → ONE allocation, then reuse
        assert pool.misses == 1 and pool.hits == 2
        # pooled decode is zero-copy: the arrays view the pooled buffer
        assert not out["weights"][0].flags["OWNDATA"]
    finally:
        a.close()
        b.close()


def test_buffer_pool_python_and_native_payload_decode_agree():
    payload = b"".join(len(x).to_bytes(8, "little") + x
                       for x in (b"abc", b"", b"0123456789"))
    py = [bytes(v) for v in networking._decode_payload_py(payload)]
    assert py == [b"abc", b"", b"0123456789"]
    if networking._native is not None and hasattr(networking._native,
                                                  "decode_payload"):
        nat = [bytes(v) for v in networking._native.decode_payload(payload)]
        assert nat == py
    with pytest.raises(ValueError, match="Truncated"):
        networking._decode_payload_py(payload[:-3])


def test_pooled_recv_rejects_mismatched_buffer_length():
    """The pooled path still validates each u64 prefix against the header's
    dtype*shape — a lying frame raises instead of decoding garbage."""
    good = networking.encode_message({"w": np.zeros((4,), np.float32)})
    tampered = bytearray(good)
    off = len(good) - 16 - 8
    tampered[off:off + 8] = (8).to_bytes(8, "little")  # wrong (real is 16)
    a, b = socket.socketpair()
    try:
        a.sendall(bytes(tampered))
        # depending on how the lie slices the pooled payload this surfaces
        # as a size mismatch, a count mismatch, or a truncation — all reject
        with pytest.raises(ValueError,
                           match="expects|declares|Truncated"):
            networking.recv_data(b, pool=networking.BufferPool())
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# connect retry-with-backoff
# ---------------------------------------------------------------------------

def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_connect_retries_until_ps_is_up():
    """A worker that dials before the PS listens retries instead of dying
    on the first ConnectionRefusedError."""
    port = _free_port()
    wk = DOWNPOURWorker(_tiny_blob(), "sgd", "mse", "127.0.0.1", port)
    accepted = []

    def listen_late():
        time.sleep(0.3)
        srv = socket.socket()
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", port))
        srv.listen(1)
        srv.settimeout(5.0)
        try:
            conn, _ = srv.accept()
            accepted.append(conn)
        except socket.timeout:
            pass
        srv.close()

    t = threading.Thread(target=listen_late)
    t.start()
    try:
        wk.connect(attempts=30, backoff=0.05)
    finally:
        t.join()
    assert wk._sock is not None and accepted
    wk._sock.close()
    for c in accepted:
        c.close()


def test_connect_retry_is_bounded():
    wk = DOWNPOURWorker(_tiny_blob(), "sgd", "mse", "127.0.0.1",
                        _free_port())
    t0 = time.perf_counter()
    with pytest.raises(ConnectionError, match="refused 2"):
        wk.connect(attempts=2, backoff=0.01)
    assert time.perf_counter() - t0 < 5.0


# ---------------------------------------------------------------------------
# comm_overlap — the knob and the 1-RTT-per-window acceptance criterion
# ---------------------------------------------------------------------------

def test_comm_overlap_knob_defaults_and_validation():
    m = make_model()
    kw = dict(num_workers=2, label_col="label_encoded")
    assert DOWNPOUR(m, execution="host_ps", **kw).comm_overlap is True
    assert ADAG(m, execution="host_ps", **kw).comm_overlap is True
    assert DynSGD(m, execution="host_ps", **kw).comm_overlap is True
    assert AEASGD(m, execution="host_ps", **kw).comm_overlap is False
    assert EAMSGD(m, execution="host_ps", **kw).comm_overlap is False
    assert AEASGD(m, execution="host_ps", comm_overlap=True,
                  **kw).comm_overlap is True
    assert DOWNPOUR(m, execution="host_ps", comm_overlap=False,
                    **kw).comm_overlap is False
    # the SPMD engine has no wire: an explicit setting there is config error
    with pytest.raises(ValueError, match="comm_overlap"):
        DOWNPOUR(m, comm_overlap=True, **kw)


class _OpcodeRecorder:
    """Counting test double over the worker→PS opcode stream."""

    def __init__(self):
        self.ops = []
        self._orig = networking.send_opcode
        self._lock = threading.Lock()

    def __enter__(self):
        def recording(sock, op):
            with self._lock:
                self.ops.append(op)
            self._orig(sock, op)
        networking.send_opcode = recording
        return self

    def __exit__(self, *exc):
        networking.send_opcode = self._orig

    def count(self, op: bytes) -> int:
        return self.ops.count(op)


def test_overlap_exactly_one_roundtrip_per_window():
    """ACCEPTANCE: with comm_overlap on, every communication window costs
    exactly ONE transport round trip — the opcode stream is one initial
    pull then only 'u' frames (no 'c'/'p' pairs), and the worker counters
    agree."""
    ds = make_dataset(n=1024)
    t = DOWNPOUR(make_model(), num_workers=2, batch_size=32, num_epoch=2,
                 communication_window=4, learning_rate=0.02,
                 label_col="label_encoded", execution="host_ps")
    assert t.comm_overlap
    with _OpcodeRecorder() as rec:
        t.train(ds)
    # 1024 rows / 2 workers = 512 each; window*batch = 128 → 4 windows per
    # epoch per worker × 2 epochs × 2 workers = 16 windows total
    windows = 16
    assert rec.count(b"u") == windows
    assert rec.count(b"c") == 0
    assert rec.count(b"p") == 2  # one initial pull per worker
    assert rec.count(b"q") == 2
    for w in t._ps_workers:
        assert w._commits == windows // 2
        # transport ops = initial pull + one 'u' per window — nothing else
        assert w.transport_ops == 1 + w._commits
        # every reply after the first landed in the reusable pool buffer
        assert w._pool.misses == 1
        assert w._pool.hits == w._commits


def test_serial_path_pays_two_ops_per_window():
    """The overlap-off path keeps the reference 'c'+'p' pair (the
    comparison baseline the bench reports as rtts_per_window=2)."""
    ds = make_dataset(n=1024)
    t = DOWNPOUR(make_model(), num_workers=2, batch_size=32, num_epoch=2,
                 communication_window=4, learning_rate=0.02,
                 label_col="label_encoded", execution="host_ps",
                 comm_overlap=False)
    with _OpcodeRecorder() as rec:
        t.train(ds)
    windows = 16
    assert rec.count(b"u") == 0
    assert rec.count(b"c") == windows
    assert rec.count(b"p") == 2 + windows  # initial + re-pull per window
    for w in t._ps_workers:
        assert w.transport_ops == 1 + 2 * w._commits


@pytest.mark.parametrize("cls,overlap,kw", [
    # the complement of each algorithm's default, so both overlap modes
    # stay covered for every algorithm (test_host_ps.py exercises the
    # defaults: delta family ON, elastic family OFF)
    (DOWNPOUR, False, {"communication_window": 4, "learning_rate": 0.02}),
    (ADAG, False, {"communication_window": 4, "learning_rate": 0.1}),
    (DynSGD, False, {"communication_window": 4, "learning_rate": 0.05}),
    (AEASGD, True, {"communication_window": 8, "rho": 1.0,
                    "learning_rate": 0.05}),
    (EAMSGD, True, {"communication_window": 8, "rho": 1.0,
                    "learning_rate": 0.05, "momentum": 0.9}),
])
def test_host_ps_training_learns_overlap_complement(cls, overlap, kw):
    ds = make_dataset()
    t = cls(make_model(), num_workers=2, batch_size=32, num_epoch=2,
            label_col="label_encoded", execution="host_ps",
            comm_overlap=overlap, **kw)
    fitted = t.train(ds)
    hist = t.get_history()
    assert len(hist) > 0
    assert np.mean(hist[-5:]) < np.mean(hist[:5])
    preds = fitted.predict(ds["features"][:256])
    acc = float(np.mean(np.argmax(preds, axis=1) == ds["label"][:256]))
    assert acc > 0.6, acc


def test_overlap_topk_wire_compression_learns_one_rtt():
    """Overlap composes with sparse top-k compression: device-side selection
    rides the same pipelined 'u' stream (exactly one round trip per window)
    and the error-feedback rebase still learns."""
    ds = make_dataset(n=1024)
    t = ADAG(make_model(), num_workers=2, batch_size=32, num_epoch=2,
             communication_window=4, label_col="label_encoded",
             learning_rate=0.1, execution="host_ps", wire_dtype="topk",
             wire_topk=0.1, comm_overlap=True)
    with _OpcodeRecorder() as rec:
        fitted = t.train(ds)
    windows = 16  # 1024 rows / 2 workers, window*batch=128, 2 epochs
    assert rec.count(b"u") == windows and rec.count(b"c") == 0
    assert rec.count(b"p") == 2
    preds = fitted.predict(ds["features"][:256])
    acc = float(np.mean(np.argmax(preds, axis=1) == ds["label"][:256]))
    assert acc > 0.6, acc


def test_overlap_int8_wire_compression_learns():
    """Overlap composes with int8 error-feedback compression: the rebase
    uses the as-applied delta, so the quantization error still telescopes."""
    ds = make_dataset()
    t = ADAG(make_model(), num_workers=2, batch_size=32, num_epoch=2,
             communication_window=4, label_col="label_encoded",
             learning_rate=0.1, execution="host_ps", wire_dtype="int8",
             comm_overlap=True)
    fitted = t.train(ds)
    preds = fitted.predict(ds["features"][:256])
    acc = float(np.mean(np.argmax(preds, axis=1) == ds["label"][:256]))
    assert acc > 0.6, acc
