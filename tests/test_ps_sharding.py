"""Tests for the PS sharding subsystem (``distkeras_tpu/ps_sharding.py``):
the deterministic shard plan (greedy bin-packing + row-wise splitting), the
scatter/gather ``ShardedPSClient``, the multi-server driver lifecycle, and
the end-to-end ``ps_shards=N`` trainer path.

Key invariants asserted here:
 - ``ps_shards=1`` is bit-identical to the plain single-PS path, and — since
   every apply rule is elementwise — a single-worker ``ps_shards=4`` run is
   bit-identical too.
 - With ``comm_overlap``, every communication window costs exactly ONE
   ``'u'`` round trip **per shard** (opcode-counting double).
 - A dead shard surfaces as ``PSShardDown(shard_id)``, and the driver raises
   it even under ``fault_tolerance=True`` (a lost center partition admits no
   degraded completion).
"""

import socket
import time

import numpy as np
import pytest

from distkeras_tpu import ADAG, AEASGD, DOWNPOUR, PSShardDown, networking
from distkeras_tpu.parameter_servers import (DeltaParameterServer,
                                             DynSGDParameterServer)
from distkeras_tpu.ps_sharding import (ShardedPSClient, ShardedServerGroup,
                                       make_shard_plan)
from distkeras_tpu.workers import DOWNPOURWorker

from test_host_ps import make_dataset, make_model
from test_host_ps_overlap import _OpcodeRecorder, _free_port, _tiny_blob
from test_trainers import eval_accuracy


# ---------------------------------------------------------------------------
# the shard plan
# ---------------------------------------------------------------------------

SHAPES = [(16, 32), (32,), (32, 4), (4,), ()]


def _rand_weights(shapes=SHAPES, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.standard_normal(s).astype(np.float32) for s in shapes]


def test_shard_plan_covers_every_row_exactly_once():
    plan = make_shard_plan(SHAPES, [np.float32] * len(SHAPES), 3)
    for t, shape in enumerate(SHAPES):
        rows = shape[0] if shape else 1
        pieces = sorted(s for a in plan.assignments for s in a
                        if s.tensor == t)
        assert pieces[0].start == 0 and pieces[-1].stop == rows
        for a, b in zip(pieces, pieces[1:]):
            assert a.stop == b.start  # contiguous, no overlap, no gap
    ws = _rand_weights()
    out = plan.gather(plan.scatter(ws))
    for a, b in zip(ws, out):
        assert np.array_equal(a, b) and a.dtype == b.dtype


def test_shard_plan_is_deterministic():
    a = make_shard_plan(SHAPES, [np.float32] * len(SHAPES), 4)
    b = make_shard_plan(SHAPES, [np.float32] * len(SHAPES), 4)
    assert a.assignments == b.assignments


def test_shard_plan_n1_is_identity():
    plan = make_shard_plan(SHAPES, [np.float32] * len(SHAPES), 1)
    assert plan.num_shards == 1
    assert [s.tensor for s in plan.assignments[0]] == list(range(len(SHAPES)))
    ws = _rand_weights()
    sc = plan.scatter(ws)[0]
    # whole tensors, original order, zero-copy views
    for v, w in zip(sc, ws):
        assert np.array_equal(v, w) and (v is w or v.base is w)


def test_shard_plan_splits_oversized_tensor():
    """One embedding-sized tensor can't unbalance the ring: anything larger
    than total/N is split row-wise and the split pieces cover it exactly."""
    shapes = [(1024, 256), (64,), (32, 8), ()]
    plan = make_shard_plan(shapes, [np.float32] * 4, 4)
    emb = sorted(s for a in plan.assignments for s in a if s.tensor == 0)
    assert len(emb) >= 4  # row-wise split, not one shard holding it whole
    assert emb[0].start == 0 and emb[-1].stop == 1024
    for a, b in zip(emb, emb[1:]):
        assert a.stop == b.start
    loads = plan.shard_bytes()
    assert max(loads) <= 2 * (sum(loads) // 4)  # reasonably balanced


# ---------------------------------------------------------------------------
# sharded client vs the single PS — same applies, bit for bit
# ---------------------------------------------------------------------------

def _blob(weights):
    return {"model": make_model().to_json(),
            "weights": [np.asarray(w, np.float32) for w in weights]}


def test_sharded_delta_applies_match_single_ps():
    rng = np.random.default_rng(1)
    w0 = _rand_weights(seed=2)
    single = DeltaParameterServer(_blob(w0))
    group = ShardedServerGroup("downpour", _blob(w0), num_workers=2,
                               num_shards=3)
    group.start()
    try:
        client = ShardedPSClient(group.plan, group.addrs)
        client.connect()
        for k in range(3):
            delta = [rng.standard_normal(w.shape).astype(np.float32)
                     for w in w0]
            single.handle_update({"delta": delta, "worker_id": 0,
                                  "clock": k})
            center = client.update({"delta": delta, "worker_id": 0,
                                    "clock": k})
        client.disconnect()
    finally:
        group.stop()
    gathered, clocks = group.snapshot()
    for a, b, c in zip(single.center, gathered, center):
        assert np.array_equal(a, b)
        assert np.array_equal(a, np.asarray(c))
    assert clocks == [3] * 3  # every shard saw every commit


def test_dynsgd_staleness_is_per_shard_identical():
    """Two workers interleaving through the sharded client price staleness
    exactly as the single DynSGD PS does: B commits against a clock one
    behind on EVERY shard, so every slice gets the same 1/(staleness+1)."""
    w0 = _rand_weights(seed=3)
    d1 = [np.ones_like(w) for w in w0]
    d2 = [np.full_like(w, 2.0) for w in w0]

    single = DynSGDParameterServer(_blob(w0))
    single.handle_update({"delta": d1, "worker_id": 0, "clock": 0})
    single.handle_update({"delta": d2, "worker_id": 1, "clock": 0})

    group = ShardedServerGroup("dynsgd", _blob(w0), num_workers=2,
                               num_shards=2)
    group.start()
    try:
        a = ShardedPSClient(group.plan, group.addrs)
        b = ShardedPSClient(group.plan, group.addrs)
        a.connect()
        b.connect()
        a.pull()
        b.pull()  # both see clock 0 on every shard
        a.update({"delta": d1, "worker_id": 0, "clock": 0})
        b.update({"delta": d2, "worker_id": 1, "clock": 0})  # staleness 1
        a.disconnect()
        b.disconnect()
    finally:
        group.stop()
    gathered, _ = group.snapshot()
    for s, g in zip(single.center, gathered):
        assert np.array_equal(s, g)


# ---------------------------------------------------------------------------
# end-to-end: ps_shards through the trainer
# ---------------------------------------------------------------------------

def _train_weights(cls=ADAG, n=512, **kw):
    ds = make_dataset(n=n)
    kw.setdefault("learning_rate", 0.1)
    t = cls(make_model(), num_workers=1, batch_size=32, num_epoch=2,
            communication_window=4, label_col="label_encoded",
            execution="host_ps", **kw)
    fitted = t.train(ds)
    return [np.asarray(w) for w in fitted.get_weights()], t


def test_ps_shards_bit_identical_to_single_ps():
    """ACCEPTANCE: ps_shards=1 reproduces the plain single-PS path bit for
    bit, and — the apply rules being elementwise — so does a single-worker
    ps_shards=4 run (same training, the center merely partitioned)."""
    ref, _ = _train_weights()
    one, _ = _train_weights(ps_shards=1)
    four, t4 = _train_weights(ps_shards=4)
    for a, b in zip(ref, one):
        assert np.array_equal(a, b)
    for a, b in zip(ref, four):
        assert np.array_equal(a, b)
    # the sharded transport really engaged: 4 messages per logical op
    w = t4._ps_workers[0]
    assert w._shard_client is not None
    assert w.transport_ops == 4 * (1 + w._commits)


def test_ps_shards_serial_path_bit_identical():
    """The overlap-off 'c'+'p' loop rides the sharded client too."""
    kw = dict(cls=DOWNPOUR, comm_overlap=False, learning_rate=0.02)
    ref, _ = _train_weights(**kw)
    sh, t = _train_weights(ps_shards=3, **kw)
    for a, b in zip(ref, sh):
        assert np.array_equal(a, b)
    w = t._ps_workers[0]
    assert w.transport_ops == 3 * (1 + 2 * w._commits)


def test_ps_shards_int8_wire_bit_identical():
    """int8 quantization happens on the FULL tensor before the scatter (one
    scale per parent tensor, shipped alongside each slice), so the
    as-applied delta — and with one worker the whole run — is independent
    of the sharding."""
    ref, _ = _train_weights(wire_dtype="int8")
    sh, _ = _train_weights(ps_shards=2, wire_dtype="int8")
    for a, b in zip(ref, sh):
        assert np.array_equal(a, b)


def test_split_sparse_bisection_matches_dense_scatter():
    """A flat sparse commit split by index bisection lands every coordinate
    on its owning shard in slice-local coordinates: applying each shard's
    split to its center slices and gathering equals the dense scatter —
    across tensor boundaries, row-split tensors, and 0-d scalars."""
    from distkeras_tpu.parameter_servers import _scatter_add

    shapes = [(64, 8), (16, 32), (32,), (32, 4), (4,), ()]
    plan = make_shard_plan(shapes, [np.float32] * len(shapes), 3)
    total = sum(int(np.prod(s)) for s in shapes)
    assert plan.flat_elements() == total
    assert sum(plan.shard_elements()) == total
    rng = np.random.default_rng(7)
    idx = np.sort(rng.choice(total, 101, replace=False)).astype(np.int32)
    vals = rng.standard_normal(101).astype(np.float32)
    parts = plan.split_sparse(idx, vals)
    owner = plan.shard_of_flat(idx)
    assert all((owner == j).sum() == len(parts[j][0]) for j in range(3))
    shard_centers = [[np.array(a, copy=True) for a in sl]
                     for sl in plan.scatter([np.zeros(s, np.float32)
                                             for s in shapes])]
    for j, (li, lv) in enumerate(parts):
        assert np.all(np.diff(li) > 0)  # stays sorted per shard
        _scatter_add(shard_centers[j],
                     networking.SparseDelta(li, lv,
                                            plan.shard_elements()[j]), 1.0)
    gathered = plan.gather(shard_centers)
    dense = np.zeros(total, np.float32)
    dense[idx] = vals
    flat = np.concatenate([g.reshape(-1) for g in gathered])
    np.testing.assert_array_equal(flat, dense)
    # out-of-range indices are rejected, not mis-binned
    with pytest.raises(ValueError, match="range"):
        plan.split_sparse(np.array([total], np.int64),
                          np.array([1.0], np.float32))


def test_ps_shards_topk_wire_bit_identical():
    """Top-k selection runs on the FULL flat delta before the scatter (one
    selection, one value scale), so — as with int8 — a single-worker
    sharded run is bit-identical to the single-PS run."""
    kw = dict(wire_dtype="topk", wire_topk=0.05)
    ref, _ = _train_weights(**kw)
    sh, t = _train_weights(ps_shards=3, **kw)
    for a, b in zip(ref, sh):
        assert np.array_equal(a, b)
    assert t._ps_workers[0]._shard_client is not None


def test_ps_shards_4_adag_converges_one_rtt_per_window_per_shard():
    """ACCEPTANCE: a ps_shards=4 ADAG run clears the same convergence bar
    as tests/test_trainers.py, and the opcode stream shows exactly one 'u'
    round trip per communication window PER SHARD — the PR 1 overlap
    property end to end through the sharded client."""
    ds = make_dataset(n=1024)
    t = ADAG(make_model(), num_workers=2, batch_size=32, num_epoch=3,
             communication_window=4, learning_rate=0.1,
             label_col="label_encoded", execution="host_ps", ps_shards=4)
    assert t.comm_overlap  # ADAG's default: the pipelined 'u' path
    with _OpcodeRecorder() as rec:
        fitted = t.train(ds)
    # 1024 rows / 2 workers = 512 each; window*batch = 128 → 4 windows per
    # epoch per worker × 3 epochs × 2 workers = 24 windows
    windows = 24
    assert rec.count(b"u") == windows * 4
    assert rec.count(b"c") == 0
    assert rec.count(b"p") == 2 * 4  # one initial pull per worker per shard
    assert rec.count(b"q") == 2 * 4
    for w in t._ps_workers:
        assert w.transport_ops == 4 * (1 + w._commits)
        pools = w._shard_client.pools
        assert len(pools) == 4
        for p in pools:  # per-shard pools: every reply reused one buffer
            assert p.misses == 1 and p.hits == w._commits
    assert eval_accuracy(fitted, ds) > 0.8


def test_aeasgd_overlap_through_sharded_client():
    """Elastic-family opt-in overlap composes with sharding: AEASGD with
    comm_overlap=True through 2 shards still converges and pays exactly one
    'u' RTT per window per shard."""
    ds = make_dataset()
    t = AEASGD(make_model(), num_workers=2, batch_size=32, num_epoch=2,
               communication_window=8, rho=1.0, learning_rate=0.05,
               label_col="label_encoded", execution="host_ps",
               comm_overlap=True, ps_shards=2)
    with _OpcodeRecorder() as rec:
        fitted = t.train(ds)
    # 2048 rows / 2 workers = 1024 each; window*batch = 256 → 4 windows per
    # epoch per worker × 2 epochs × 2 workers = 16 windows
    windows = 16
    assert rec.count(b"u") == windows * 2
    assert rec.count(b"c") == 0
    assert rec.count(b"p") == 2 * 2
    hist = t.get_history()
    assert np.mean(hist[-5:]) < np.mean(hist[:5])
    preds = fitted.predict(ds["features"][:256])
    acc = float(np.mean(np.argmax(preds, axis=1) == ds["label"][:256]))
    assert acc > 0.6, acc


def test_sharded_run_tolerates_worker_death():
    """fault_tolerance still covers WORKER death under sharding: the dying
    worker hard-closes all its shard sockets (plain EOF on every shard) and
    the survivors finish."""
    ds = make_dataset(n=1024)
    t = ADAG(make_model(), num_workers=4, batch_size=16, num_epoch=3,
             communication_window=4, label_col="label_encoded",
             worker_optimizer="adam", learning_rate=2e-3,
             execution="host_ps", ps_shards=2, fault_tolerance=True,
             fault_injection={1: 2})
    fitted = t.train(ds)
    assert t.failed_workers == [1]
    assert eval_accuracy(fitted, ds) > 0.8


def test_ps_shards_knob_validation():
    m = make_model()
    kw = dict(num_workers=2, label_col="label_encoded")
    assert ADAG(m, execution="host_ps", ps_shards=4, **kw).ps_shards == 4
    with pytest.raises(ValueError, match="ps_shards"):
        ADAG(m, execution="host_ps", ps_shards=0, **kw)
    with pytest.raises(ValueError, match="ps_shards"):
        ADAG(m, ps_shards=2, **kw)  # SPMD: no PS to shard
    # process_ps shards through the same wire protocol (driver-hosted group)
    assert ADAG(m, execution="process_ps", ps_shards=2, **kw).ps_shards == 2


# ---------------------------------------------------------------------------
# shard death → PSShardDown
# ---------------------------------------------------------------------------

def test_dead_shard_raises_shard_down_with_id():
    group = ShardedServerGroup("downpour", _tiny_blob(), num_workers=1,
                               num_shards=2)
    group.start()
    client = ShardedPSClient(group.plan, group.addrs)
    client.connect()
    try:
        client.pull()  # both shards alive
        group.servers[1].stop()
        time.sleep(0.05)
        with pytest.raises(PSShardDown, match="shard 1") as err:
            for _ in range(3):  # first op may still drain a buffered reply
                client.pull()
        assert err.value.shard_id == 1
        assert isinstance(err.value, ConnectionError)  # generic handlers OK
    finally:
        client.abort()
        group.stop()


def test_shard_connect_failure_is_shard_down():
    plan = make_shard_plan([(3,)], [np.float32], 2)
    addrs = [("127.0.0.1", _free_port()), ("127.0.0.1", _free_port())]
    client = ShardedPSClient(plan, addrs)
    with pytest.raises(PSShardDown, match="shard 0"):
        client.connect(attempts=2, backoff=0.01)


def test_shard_down_overrides_fault_tolerance(monkeypatch):
    """A dead SHARD loses a partition of the center — the driver re-raises
    PSShardDown even under fault_tolerance=True instead of pretending the
    survivors can complete."""
    from distkeras_tpu import ps_sharding

    def dying(self):
        raise PSShardDown(1, detail="injected shard death")

    monkeypatch.setattr(ps_sharding.ShardedPSClient, "recv_update", dying)
    ds = make_dataset(n=512)
    t = ADAG(make_model(), num_workers=2, batch_size=32, num_epoch=1,
             communication_window=4, learning_rate=0.1,
             label_col="label_encoded", execution="host_ps", ps_shards=2,
             fault_tolerance=True)
    with pytest.raises(PSShardDown, match="shard 1"):
        t.train(ds)
    assert t.failed_workers == []  # not misfiled as worker deaths


# ---------------------------------------------------------------------------
# satellite: connect() retries reset/timeout handshake faults
# ---------------------------------------------------------------------------

def test_connect_retries_reset_and_timeout(monkeypatch):
    """A shard mid-start() can accept then reset (or stall): the worker's
    bounded retry covers ConnectionResetError and socket.timeout, not just
    ConnectionRefusedError."""
    a, b = socket.socketpair()
    try:
        faults = [ConnectionResetError("peer reset mid-handshake"),
                  socket.timeout("handshake stalled")]

        def flaky(host, port, **kw):
            if faults:
                raise faults.pop(0)
            return a

        monkeypatch.setattr(networking, "connect", flaky)
        wk = DOWNPOURWorker(_tiny_blob(), "sgd", "mse", "127.0.0.1",
                            _free_port())
        wk.connect(attempts=5, backoff=0.001)
        assert wk._sock is a and not faults  # both faults were retried
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# satellite: BufferPool growth cap
# ---------------------------------------------------------------------------

def test_buffer_pool_evicts_stale_sizes():
    """A buffer unused for max_idle acquisitions is evicted, so a pull-size
    change doesn't pin the old full-weight-sized buffer forever."""
    pool = networking.BufferPool(max_idle=2)
    pool.get(100)
    pool.get(200)
    assert pool.evictions == 0  # 100 idle for 1 acquisition: kept
    pool.get(200)
    assert pool.evictions == 1 and 100 not in pool._bufs
    assert 200 in pool._bufs  # the live size survives
    pool.get(100)  # comes back as a fresh allocation
    assert pool.misses == 3 and pool.hits == 1


def test_buffer_pool_steady_state_unaffected_by_cap():
    pool = networking.BufferPool()  # default cap
    for _ in range(100):
        pool.get(4096)
    assert pool.misses == 1 and pool.hits == 99 and pool.evictions == 0
