"""Multi-tenant QoS (PR 18): quotas, SLO tiers, paged-KV preemption.

The contract pinned here, mirroring docs/serving.md's QoS section:

 - admission is weighted-fair across backlogged tenants (stride
   scheduling: pass += 1/weight per pick), interactive tier strictly
   before batch, highest ``priority`` first within a tenant — and with
   no tenants registered it degenerates to the exact FIFO the pre-QoS
   engine ran (the defaults-unchanged contract);
 - a tenant over its token-bucket quota is refused typed
   (:class:`QuotaExceeded`, a :class:`QueueFull` subclass) BEFORE the
   request counts as submitted, and per-tenant counters book every
   shed/refusal (``stats()["tenants"][t]``);
 - a preempted (swapped-out) request resumes BIT-IDENTICAL to an
   unpreempted run — same tokens, same finish — with its KV blocks
   round-tripped through host memory (d2h/h2d transfer counters move,
   swap-out and resume byte counts match) and ``kv_blocks_in_use == 0``
   while it sits suspended;
 - zero block leak across EVERY preempt/resume/cancel/deadline/
   disconnect interleaving, and ``drain()``/``declare_dead()`` fail a
   still-suspended request with a typed reason (the message names the
   swap-out) instead of hanging its waiter;
 - the wire carries ``tenant``/``priority`` on ``'q'`` and maps quota
   refusals to a distinct ``"quota"`` kind; the router spills batch-tier
   submissions off affine replicas with interactive backlog and
   ``scale_down`` composes with suspension for zero-loss failover.

Tier-1 legs run seeded traces on inline-stepped engines — no sleeps on
the fast path; the overload soak is additionally marked slow.
"""

import time
import zlib

import numpy as np
import pytest

import jax

from distkeras_tpu.core.model import FittedModel
from distkeras_tpu.models import transformer_lm
from distkeras_tpu.router import ServingRouter
from distkeras_tpu.serving import (EngineDead, QueueFull, QuotaExceeded,
                                   ServingClient, ServingEngine,
                                   ServingServer, TenantPolicy)

pytestmark = pytest.mark.qos

VOCAB = 17
P6 = np.arange(1, 7, dtype=np.int32)


@pytest.fixture(scope="module")
def fitted():
    model = transformer_lm(vocab_size=VOCAB, seq_len=32, d_model=16,
                           num_heads=2, num_layers=2, mlp_dim=32,
                           compute_dtype="float32")
    params = model.init(jax.random.PRNGKey(0), (32,))
    return FittedModel(model, params)


def _mk(fitted, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_len", 32)
    kw.setdefault("block_size", 4)
    kw.setdefault("kv_blocks", 30)
    return ServingEngine(fitted, paged=True, **kw)


def _bulk(**kw):
    return TenantPolicy("bulk", tier="batch", **kw)


def _live(**kw):
    return TenantPolicy("live", tier="interactive", **kw)


#: the request shapes the preemption legs replay — referenced by name so
#: every test compares against the SAME unpreempted rows (one reference
#: engine, one compile, module-wide)
REQS = {
    "bulk_sampled": dict(prompt=P6, num_steps=18, temperature=0.8, seed=7),
    "bulk_lo": dict(prompt=P6, num_steps=14, temperature=0.7, seed=11),
    "bulk_hi": dict(prompt=np.array([2, 9, 4, 1, 8, 5], np.int32),
                    num_steps=14, temperature=0.7, seed=23),
    "interactive": dict(prompt=np.array([1, 2, 3, 4, 5], np.int32),
                        num_steps=8),
    "wire_greedy": dict(prompt=np.array([3, 4, 5, 6], np.int32),
                        num_steps=8),
}


@pytest.fixture(scope="module")
def ref_rows(fitted):
    """Unpreempted reference rows from a plain (no-tenant) engine — the
    bit-identity baseline every preempt/resume/failover leg compares
    against."""
    eng = _mk(fitted)
    hs = {k: eng.submit(**kw) for k, kw in REQS.items()}
    eng.run_until_idle()
    assert eng.kv_blocks_in_use == 0
    assert eng.stats["preemptions"] == 0
    return {k: h.result() for k, h in hs.items()}


def _wait(pred, timeout=60.0, poll=0.005, what="condition"):
    t0 = time.perf_counter()
    while not pred():
        if time.perf_counter() - t0 > timeout:
            raise AssertionError(f"timed out waiting for {what}")
        time.sleep(poll)


def _step_until(eng, pred, max_steps=400, what="condition"):
    for _ in range(max_steps):
        if pred():
            return
        eng.step()
    assert pred(), f"never reached {what} in {max_steps} inline steps"


# ---------------------------------------------------------------------------
# policy surface: validation, registration, clone
# ---------------------------------------------------------------------------

def test_tenant_policy_validation():
    with pytest.raises(ValueError):
        TenantPolicy("")
    with pytest.raises(ValueError):
        TenantPolicy("t", weight=0.0)
    with pytest.raises(ValueError):
        TenantPolicy("t", rate=-1.0)
    with pytest.raises(ValueError):
        TenantPolicy("t", rate=1.0, burst=0.5)
    with pytest.raises(ValueError):
        TenantPolicy("t", tier="gold")
    with pytest.raises(ValueError):
        TenantPolicy("t", deadline_s=0.0)
    # QuotaExceeded IS backpressure to untyped callers
    assert issubclass(QuotaExceeded, QueueFull)


def test_register_tenant_and_clone(fitted):
    eng = _mk(fitted, tenants=[_bulk(), _live()])
    with pytest.raises(ValueError):
        eng.register_tenant("not-a-policy")
    p = TenantPolicy("metered", rate=10.0, burst=2.0)
    p._tokens = 0.0  # drained bucket
    c = p.clone()
    assert c._tokens == c.burst == 2.0  # clone never inherits bucket debt
    assert (c.name, c.rate, c.tier) == ("metered", 10.0, "batch")
    eng.register_tenant(p)
    assert eng._tenants["metered"] is p


# ---------------------------------------------------------------------------
# admission order: WFQ stride, tiers, priority, FIFO degenerate
# ---------------------------------------------------------------------------

def test_weighted_fair_pop_order(fitted):
    """Interactive tier pops strictly first; within the batch tier the
    stride schedule gives a weight-2 tenant two admissions per weight-1
    admission (deterministic sequence, not just a ratio)."""
    eng = _mk(fitted, tenants=[TenantPolicy("a", weight=2.0),
                               TenantPolicy("b", weight=1.0), _live()])
    a = [eng.submit(P6, 4, tenant="a", block=False) for _ in range(4)]
    b = [eng.submit(P6, 4, tenant="b", block=False) for _ in range(4)]
    i0 = eng.submit(P6, 4, tenant="live", block=False)
    with eng._qlock:
        order = [eng._q_pop_locked() for _ in range(9)]
        assert eng._q_pop_locked() is None
    want = [i0, a[0], b[0], a[1], a[2], b[1], a[3], b[2], b[3]]
    assert [h.id for h in order] == [h.id for h in want]


def test_priority_within_tenant(fitted):
    eng = _mk(fitted, tenants=[_bulk()])
    p0 = eng.submit(P6, 4, tenant="bulk", priority=0, block=False)
    p5a = eng.submit(P6, 4, tenant="bulk", priority=5, block=False)
    p1 = eng.submit(P6, 4, tenant="bulk", priority=1, block=False)
    p5b = eng.submit(P6, 4, tenant="bulk", priority=5, block=False)
    with eng._qlock:
        order = [eng._q_pop_locked() for _ in range(4)]
    # highest priority first, FIFO among equals
    assert [h.id for h in order] == [p5a.id, p5b.id, p1.id, p0.id]


def test_defaults_degenerate_to_fifo(fitted):
    """No tenants registered: the WFQ pop IS the pre-QoS FIFO, requests
    land under the lazily-created ``"default"`` tenant, and the load
    snapshot shows no interactive backlog."""
    eng = _mk(fitted)
    hs = [eng.submit(P6, 4, block=False) for _ in range(3)]
    assert eng.load()["queued_interactive"] == 0
    with eng._qlock:
        order = [eng._q_pop_locked() for _ in range(3)]
    assert [h.id for h in order] == [h.id for h in hs]
    assert all(h.tenant == "default" and h.priority == 0 for h in hs)
    assert eng.stats["tenants"]["default"]["submitted"] == 3


# ---------------------------------------------------------------------------
# quotas + tier deadline bands + shed accounting
# ---------------------------------------------------------------------------

def test_quota_token_bucket(fitted):
    eng = _mk(fitted, tenants=[TenantPolicy("metered", rate=0.001,
                                            burst=2.0)])
    eng.submit(P6, 4, tenant="metered", block=False)
    eng.submit(P6, 4, tenant="metered", block=False)
    # quota is policy, not backpressure: block=True raises immediately too
    with pytest.raises(QuotaExceeded):
        eng.submit(P6, 4, tenant="metered", block=True)
    s = eng.stats
    assert s["quota_refused"] == 1
    assert s["requests_submitted"] == 2  # refusal precedes the submit count
    ts = s["tenants"]["metered"]
    assert (ts["submitted"], ts["quota_refused"]) == (2, 1)
    # other tenants are unaffected (unregistered = unlimited quota)
    eng.submit(P6, 4, tenant="other", block=False)
    assert s["tenants"]["other"]["quota_refused"] == 0


def test_tier_deadline_band(fitted):
    eng = _mk(fitted, tenants=[_live(deadline_s=5.0), _bulk()])
    now = time.perf_counter()
    h = eng.submit(P6, 4, tenant="live", block=False)
    assert h.deadline is not None and 4.0 < h.deadline - now <= 5.5
    # an explicit per-request deadline still wins over the tier band
    h2 = eng.submit(P6, 4, tenant="live", deadline_s=0.5, block=False)
    assert h2.deadline - now <= 1.0
    # batch tier has no band here; engine default_deadline_s is None
    h3 = eng.submit(P6, 4, tenant="bulk", block=False)
    assert h3.deadline is None


def test_per_tenant_shed_accounting(fitted):
    eng = _mk(fitted, queue_capacity=1)
    eng.submit(P6, 4, tenant="a", block=False)  # fills the queue
    for t in ("b", "c"):
        with pytest.raises(QueueFull):
            eng.submit(P6, 4, tenant=t, block=False)
        ts = eng.stats["tenants"][t]
        # sheds are terminal, so they count as submissions too — the
        # per-tenant balance is submitted == completed + shed
        assert (ts["submitted"], ts["shed"]) == (1, 1)
    assert eng.stats["requests_rejected"] == 2
    assert eng.stats["tenants"]["a"]["shed"] == 0


# ---------------------------------------------------------------------------
# preemption: swap-out, bit-identical resume, starvation victim choice
# ---------------------------------------------------------------------------

def test_preempt_resume_bit_identical(fitted, ref_rows):
    """Explicit preempt mid-decode: blocks gather to host (d2h moves),
    the slot frees (zero blocks in use while suspended), and the resumed
    stream — reinstalled through the jitted ingest program (h2d moves) —
    matches the unpreempted reference bit for bit."""
    eng = _mk(fitted, tenants=[_bulk(), _live()])
    h = eng.submit(tenant="bulk", **REQS["bulk_sampled"])
    _step_until(eng, lambda: len(h.tokens) >= 6, what="6 decoded tokens")
    d2h0 = eng.stats["d2h_transfers"]
    assert eng.preempt(h) is True
    _step_until(eng, lambda: h.id in eng._suspended, what="suspension")
    assert h.slot is None and h.finish is None
    assert eng.kv_blocks_in_use == 0  # every block back in the pool
    s = eng.stats
    assert s["preemptions"] == 1
    assert s["kv_blocks_swapped_out"] > 0
    assert s["kv_block_bytes_swapped_out"] > 0
    assert s["d2h_transfers"] > d2h0  # the gather crossed to host
    assert len(s["preempt_swap_ms"]) == 1
    h2d0 = s["h2d_transfers"]
    eng.run_until_idle()
    assert h.finish in ("eos", "length")
    np.testing.assert_array_equal(h.result(), ref_rows["bulk_sampled"])
    assert s["resumes"] == 1
    assert s["h2d_transfers"] > h2d0  # the ingest crossed back
    assert s["kv_blocks_resumed"] == s["kv_blocks_swapped_out"]
    assert s["kv_block_bytes_resumed"] == s["kv_block_bytes_swapped_out"]
    assert len(s["preempt_resume_ms"]) == 1
    assert eng.kv_blocks_in_use == 0
    ts = s["tenants"]["bulk"]
    assert (ts["preemptions"], ts["resumes"], ts["completed"]) == (1, 1, 1)


def test_starvation_preempts_lowest_priority(fitted, ref_rows):
    """A starved interactive submission suspends a running batch-tier
    request — the LOWEST-priority one first — and every stream (victims
    included) still matches its unpreempted reference."""
    eng = _mk(fitted, tenants=[_bulk(), _live()])
    lo = eng.submit(tenant="bulk", priority=0, **REQS["bulk_lo"])
    hi = eng.submit(tenant="bulk", priority=5, **REQS["bulk_hi"])
    _step_until(eng, lambda: lo.slot is not None and hi.slot is not None,
                what="both batch requests decoding")
    it = eng.submit(tenant="live", **REQS["interactive"])
    _step_until(eng, lambda: eng._suspended, what="starvation preemption")
    assert lo.id in eng._suspended  # victim choice: lowest priority first
    eng.run_until_idle()
    for h, name in ((lo, "bulk_lo"), (hi, "bulk_hi"), (it, "interactive")):
        assert h.finish in ("eos", "length")
        np.testing.assert_array_equal(h.result(), ref_rows[name])
    s = eng.stats
    assert s["preemptions"] >= 1
    assert s["resumes"] == s["preemptions"]  # every victim came back
    assert s["tenants"]["live"]["preemptions"] == 0
    assert eng.kv_blocks_in_use == 0


def test_cancel_and_deadline_while_suspended(fitted):
    """A suspended request holds no slot and no blocks — cancel and
    deadline expiry while swapped out are pure bookkeeping: the host-side
    record drops, the handle retires typed, nothing resumes."""
    eng = _mk(fitted, tenants=[_bulk(), _live()])
    # --- cancel while suspended
    h = eng.submit(tenant="bulk", **REQS["bulk_sampled"])
    _step_until(eng, lambda: len(h.tokens) >= 2, what="decode progress")
    assert eng.preempt(h)
    _step_until(eng, lambda: h.id in eng._suspended, what="suspension")
    assert eng.cancel(h) is True
    _step_until(eng, lambda: h.finish is not None, what="cancel retire")
    assert h.finish == "cancel"
    assert not eng._suspended and eng.kv_blocks_in_use == 0
    # --- deadline expiry while suspended
    h2 = eng.submit(tenant="bulk", deadline_s=0.05, **REQS["bulk_lo"])
    _step_until(eng, lambda: len(h2.tokens) >= 2, what="decode progress")
    assert eng.preempt(h2)
    _step_until(eng, lambda: h2.id in eng._suspended, what="suspension")
    time.sleep(0.06)  # let the (tiny) deadline lapse while swapped out
    _step_until(eng, lambda: h2.finish is not None, what="deadline retire")
    assert h2.finish == "deadline"
    assert not eng._suspended and eng.kv_blocks_in_use == 0
    assert eng.stats["resumes"] == 0  # neither request ever came back
    assert eng.stats["preemptions"] == 2


# ---------------------------------------------------------------------------
# drain / shutdown with suspended requests (satellite: typed, never hangs)
# ---------------------------------------------------------------------------

def test_drain_inline_resumes_suspended(fitted, ref_rows):
    """Happy path: drain on an inline engine steps the scheduler, which
    resumes the suspended request and finishes it — clean drain, stream
    still bit-identical."""
    eng = _mk(fitted, tenants=[_bulk(), _live()])
    h = eng.submit(tenant="bulk", **REQS["bulk_sampled"])
    _step_until(eng, lambda: len(h.tokens) >= 4, what="decode progress")
    assert eng.preempt(h)
    _step_until(eng, lambda: h.id in eng._suspended, what="suspension")
    assert eng.drain(timeout=60.0) is True
    np.testing.assert_array_equal(h.result(), ref_rows["bulk_sampled"])
    assert eng.kv_blocks_in_use == 0
    assert eng.stats["resumes"] == 1


def test_declare_dead_fails_suspended_typed(fitted):
    eng = _mk(fitted, tenants=[_bulk(), _live()])
    h = eng.submit(tenant="bulk", **REQS["bulk_sampled"])
    _step_until(eng, lambda: len(h.tokens) >= 2, what="decode progress")
    assert eng.preempt(h)
    _step_until(eng, lambda: h.id in eng._suspended, what="suspension")
    eng.declare_dead("supervisor kill")
    with pytest.raises(EngineDead, match="swapped out"):
        h.result(timeout=5.0)
    assert h.finish == "error"
    assert not eng._suspended
    assert eng.stats["requests_failed"] == 1


def test_drain_timeout_fails_suspended_typed(fitted):
    """A started engine whose only slot is held by interactive work
    cannot resume the suspended batch request — drain must time out and
    fail it TYPED (the reason names the swap-out) instead of hanging the
    waiter forever."""
    eng = _mk(fitted, num_slots=1, tenants=[_bulk(), _live()])
    eng.start()
    try:
        h = eng.submit(tenant="bulk", prompt=P6, num_steps=24,
                       temperature=0.8, seed=7)
        _wait(lambda: h.slot is not None, what="decode start")
        # queue interactive work FIRST (the freed slot goes to it, so the
        # suspended request cannot resume), then preempt the batch run
        it = eng.submit(tenant="live", prompt=P6, num_steps=24)
        assert eng.preempt(h)
        _wait(lambda: h.id in eng._suspended, what="suspension")
        assert eng.drain(timeout=0.0, poll=0.001) is False
        with pytest.raises(EngineDead, match="swapped out"):
            h.result(timeout=5.0)
        with pytest.raises(EngineDead):
            it.result(timeout=5.0)
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# wire: tenant/priority on 'q', typed quota kind, disconnect-while-suspended
# ---------------------------------------------------------------------------

def test_wire_tenant_priority_quota_and_disconnect(fitted, ref_rows,
                                                    server_core):
    eng = _mk(fitted, tenants=[
        _bulk(), _live(),
        TenantPolicy("metered", rate=0.001, burst=1.0)])
    with ServingServer(eng) as srv:
        with ServingClient(*srv.addr) as c:
            # tenant + priority ride the 'q' frame into the engine handle
            rid = c.submit(tenant="live", priority=3, **REQS["wire_greedy"])
            h = srv._handles[rid]
            assert (h.tenant, h.priority) == ("live", 3)
            row = None
            for _, done in c.stream(rid):
                if done is not None:
                    row = done["row"]
            np.testing.assert_array_equal(row, ref_rows["wire_greedy"])
            # quota refusals come back as their own typed kind, distinct
            # from backpressure, and still catchable as QueueFull
            rid_m = c.submit(tenant="metered", **REQS["wire_greedy"])
            with pytest.raises(QuotaExceeded):
                c.submit(tenant="metered", **REQS["wire_greedy"])
            assert eng.stats["tenants"]["metered"]["quota_refused"] == 1
            # let the admitted metered request finish before this client
            # closes, so the disconnect leg below reclaims exactly one
            _wait(lambda: srv._handles[rid_m].finish is not None,
                  what="metered completion")
        # disconnect while suspended: the dead client's swapped-out
        # request is reclaimed like any other — cancelled, record
        # dropped, zero blocks leaked
        c2 = ServingClient(*srv.addr)
        # Pace decode while arming the preempt: a warm engine runs all 18
        # steps of bulk_sampled in ~7ms — inside one _wait poll — so an
        # unthrottled race can see the request finish before preempt
        # lands (flaky on both server cores). The throttle changes only
        # timing, never token values.
        orig_decode = eng._decode_once

        def paced_decode():
            time.sleep(0.02)
            return orig_decode()

        eng._decode_once = paced_decode
        rid2 = c2.submit(tenant="bulk", **REQS["bulk_sampled"])
        h2 = srv._handles[rid2]
        _wait(lambda: len(h2.tokens) >= 2, what="decode progress")
        assert eng.preempt(h2)
        eng._decode_once = orig_decode
        _wait(lambda: rid2 in eng._suspended, what="suspension")
        c2.close()
        _wait(lambda: h2.finish is not None, what="disconnect reclaim")
        assert h2.finish == "cancel"
        _wait(lambda: not eng._suspended, what="swap record drop")
        assert eng.kv_blocks_in_use == 0
        assert srv.disconnect_cancels == 1


# ---------------------------------------------------------------------------
# router: tenant-aware dispatch + scale_down over suspended requests
# ---------------------------------------------------------------------------

def test_router_tenant_spill_dispatch(fitted):
    """Batch-tier submissions spill off an affine replica with
    interactive backlog (``tenant_spills``); interactive submissions keep
    their affinity — they are what the backlog drains into."""
    e1, e2 = _mk(fitted), _mk(fitted)
    r = ServingRouter(replicas=[e1, e2], affinity="prefix",
                      affinity_blocks=1, block_size=4,
                      tenants=[_live(), _bulk()])
    prompt = np.arange(1, 10, dtype=np.int32)
    key = np.asarray(prompt[:4], np.int32).tobytes()
    reps = list(r._replicas)
    affine = max(reps, key=lambda rep: zlib.crc32(
        key + rep.uid.to_bytes(4, "little")))
    other = next(rep for rep in reps if rep is not affine)
    # affine replica: NOT saturated (no affinity spill) but with an
    # interactive request queued; the other replica is least-loaded
    affine.load = lambda: {"queue_depth": 1, "active": 1, "slots_free": 1,
                           "slots_total": 2, "queued_interactive": 1}
    other.load = lambda: {"queue_depth": 0, "active": 0, "slots_free": 2,
                          "slots_total": 2, "queued_interactive": 0}
    assert r._dispatch_order(prompt, tenant="bulk")[0][0] is other
    assert r.counters["tenant_spills"] == 1
    assert r._dispatch_order(prompt, tenant="live")[0][0] is affine
    assert r.counters["affinity_routed"] == 1
    # untenanted traffic is batch-tier on a tenanted fleet: it spills too
    assert r._dispatch_order(prompt, tenant=None)[0][0] is other
    assert r.counters["tenant_spills"] == 2
    # fleet QoS reached every in-process replica as an unshared clone
    for e in (e1, e2):
        assert set(e._tenants) == {"live", "bulk"}
        assert e._tenants["live"] is not r._tenants["live"]


def test_router_scale_down_resubmits_suspended(fitted, ref_rows):
    """scale_down on a replica holding a SUSPENDED request: the drain
    timeout fails it typed, the relay resubmits to the surviving replica,
    and the client-visible stream is still bit-identical — zero loss."""
    e1 = _mk(fitted, num_slots=1)
    e2 = _mk(fitted, num_slots=1)
    r = ServingRouter(replicas=[e1, e2], affinity="prefix", block_size=4,
                      tenants=[_live(), _bulk()])
    r.start()
    try:
        h = r.submit(tenant="bulk", **REQS["bulk_sampled"])
        rec = r._live[h.id]
        _wait(lambda: rec.upstream is not None
              and rec.upstream.slot is not None, what="decode start")
        eng, uid = rec.replica.engine, rec.replica.uid
        survivor = e2 if eng is e1 else e1
        # queue interactive work on the owning replica, then preempt the
        # upstream: the freed (only) slot goes to the interactive
        # request, so the suspended upstream cannot resume
        it = eng.submit(tenant="live", prompt=P6, num_steps=24)
        assert eng.preempt(rec.upstream)
        _wait(lambda: rec.upstream.id in eng._suspended,
              what="suspension")
        assert r.scale_down(uid=uid, timeout=0.0) == uid
        row = h.result(timeout=60.0)
        np.testing.assert_array_equal(row, ref_rows["bulk_sampled"])
        assert r.counters["requests_failed"] == 0
        assert r.counters["resubmissions"] >= 1
        with pytest.raises(EngineDead):  # the direct submit died typed
            it.result(timeout=5.0)
        _wait(lambda: survivor.kv_blocks_in_use == 0, what="survivor idle")
    finally:
        r.stop()


# ---------------------------------------------------------------------------
# overload: loadgen QoS leg (fast deterministic tier-1 + slow soak)
# ---------------------------------------------------------------------------

def _overload(num_requests, qps, seed, queue_capacity=16):
    from examples import loadgen

    _, eng = loadgen.build_engine(num_slots=2, max_len=32, paged=True,
                                  block_size=8,
                                  queue_capacity=queue_capacity)
    for p in loadgen.qos_policies(3):
        eng.register_tenant(p)
    trace = loadgen.make_trace(num_requests, num_steps=8, seed=seed,
                               tenants=3, tier_mix=0.3)
    assert any(t["tenant"] == "interactive" for t in trace)
    assert any(t["tenant"] != "interactive" for t in trace)
    try:
        return eng, loadgen.run_overload(eng, trace, qps=qps,
                                         timeout_s=120.0)
    finally:
        eng.stop()


def test_overload_fast_leg():
    eng, point = _overload(num_requests=10, qps=500.0, seed=3)
    for k in ("interactive_p99_ms", "batch_completion_rate",
              "preempt_resume_ms", "quota_refused", "tenants"):
        assert k in point
    assert 0.0 <= point["batch_completion_rate"] <= 1.0
    assert point["interactive_completion_rate"] > 0.0
    assert point["interactive_p99_ms"] is not None
    assert eng.kv_blocks_in_use == 0
    s = eng.stats
    assert (s["requests_submitted"] == s["requests_completed"]
            + s["requests_failed"] + s["requests_rejected"])


@pytest.mark.slow
def test_overload_soak_interactive_holds():
    """An overload burst (arrivals far faster than service, queue deep
    enough that nothing sheds): the interactive tier holds its latency
    band — weighted-fair admission pops it strictly first, so the batch
    tier absorbs ALL the queueing delay — and everything still
    completes."""
    eng, point = _overload(num_requests=40, qps=400.0, seed=5,
                           queue_capacity=64)
    assert point["shed_interactive"] == point["shed_batch"] == 0
    assert point["interactive_completion_rate"] == 1.0
    assert point["batch_completion_rate"] == 1.0
    assert point["interactive_p99_ms"] is not None
    assert point["batch_p99_ms"] is not None
    assert point["interactive_p99_ms"] <= point["batch_p99_ms"]
    assert eng.kv_blocks_in_use == 0
    assert point["resumes"] == point["preemptions"]
