"""Test environment: force an 8-device virtual CPU platform *before* JAX
initializes, so distributed-trainer tests exercise real mesh sharding +
collectives without TPU hardware (SURVEY.md §4's multi-device simulation —
the idiomatic analogue of the reference's Spark ``local[*]`` fake cluster).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the sandbox presets a TPU tunnel
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# jax may already be imported at interpreter startup (sitecustomize) with the
# sandbox's JAX_PLATFORMS=axon snapshot — re-apply the env through the config
# API (shared workaround lives in distkeras_tpu.utils).
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])
from distkeras_tpu.utils import honor_platform_env

honor_platform_env()

import numpy as np
import pytest


@pytest.fixture(scope="session")
def eight_devices():
    import jax
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {devs}"
    return devs


@pytest.fixture()
def rng():
    return np.random.default_rng(0)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "tpu: exercises the real TPU chip in a subprocess (auto-skips when "
        "no accelerator is reachable)")
    config.addinivalue_line(
        "markers",
        "slow: multi-process end-to-end tests (worker subprocesses each "
        "import jax and compile)")
    config.addinivalue_line(
        "markers",
        "stream: streaming-ingestion / online-learning contract tests "
        "(tier-1 ones are generator-backed — no live sockets or sleeps on "
        "the fast path; socket-feed coverage uses socketpair only)")
    config.addinivalue_line(
        "markers",
        "paged: paged-KV-pool / radix-prefix-sharing serving tests "
        "(tier-1 ones run small seeded traces inline — no sleeps; the "
        "arena-pressure soaks and timing comparisons are additionally "
        "marked slow, mirroring the stream marker's tiering)")
    config.addinivalue_line(
        "markers",
        "analysis: dklint static-analysis contract tests (pure-ast over "
        "fixture strings plus the tier-1 zero-unbaselined gate over the "
        "package — no JAX imports of checked code, no sleeps)")
    config.addinivalue_line(
        "markers",
        "online: train-while-serve deployment tests (tier-1 ones are "
        "generator-backed and seeded with inline-pumped engines — no "
        "sleeps on the fast path; the chaos soak with live engine kills "
        "and supervised restarts is additionally marked slow)")
    config.addinivalue_line(
        "markers",
        "disagg: disaggregated prefill/decode serving tests (tier-1 legs "
        "are in-process or socketpair/loopback-only, seeded, and "
        "sleep-free; unified-vs-disagg timing comparisons are "
        "additionally marked slow)")


@pytest.fixture()
def lock_order_audit():
    """Opt-in runtime lock-order auditing: locks created inside the test
    body (engine/supervisor construction included) are instrumented, and
    teardown asserts the acquisition-order graph stayed acyclic.  See
    distkeras_tpu/analysis/runtime.py."""
    from distkeras_tpu.analysis.runtime import audit_locks
    with audit_locks() as auditor:
        yield auditor
    assert auditor.violations == [], \
        "runtime lock-order violations:\n" + "\n".join(auditor.violations)
