"""Test environment: force an 8-device virtual CPU platform *before* JAX
initializes, so distributed-trainer tests exercise real mesh sharding +
collectives without TPU hardware (SURVEY.md §4's multi-device simulation —
the idiomatic analogue of the reference's Spark ``local[*]`` fake cluster).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the sandbox presets a TPU tunnel
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# jax may already be imported at interpreter startup (sitecustomize) with the
# sandbox's JAX_PLATFORMS=axon snapshot — re-apply the env through the config
# API (shared workaround lives in distkeras_tpu.utils).
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])
from distkeras_tpu.utils import honor_platform_env

honor_platform_env()

import numpy as np
import pytest

# Cheap-first file ordering.  The tier-1 gate (ROADMAP.md) runs the whole
# suite under one wall-clock budget; in alphabetical order a handful of
# compile-heavy files (serving fastpath/resilience soaks, attention
# kernels) sit mid-alphabet and a budget overrun truncates hundreds of
# sub-second tests queued behind them.  Order files by measured mean
# seconds/test instead — fast feedback first, the soaks last, in-file
# order untouched (the sort is stable and keys are per-file, so files stay
# contiguous and module-scoped fixtures still build once).
_FILE_COST = {  # mean s/test on the CPU gate machine; unlisted -> 3.0
    "test_applykernel.py": 0.01, "test_wirecodec.py": 0.01,
    "test_evaluators.py": 0.01, "test_update_rules.py": 0.02,
    "test_data.py": 0.02, "test_analysis.py": 0.11,
    "test_losses_keras1.py": 0.22, "test_ps_sharding.py": 0.30,
    "test_dcn_chaos.py": 0.37,
    "test_event_ps.py": 0.30, "test_job_deployment.py": 0.34,
    "test_host_ps_overlap.py": 0.34, "test_host_ps.py": 0.41,
    "test_core.py": 0.42, "test_fault_tolerance.py": 0.56,
    "test_streaming.py": 0.63, "test_elastic_workers.py": 0.63,
    "test_schedules.py": 0.66, "test_topk_wire.py": 0.75,
    "test_keras_adapter.py": 0.76, "test_determinism_faults.py": 0.78,
    "test_quant.py": 1.07, "test_checkpoint_metrics.py": 1.10,
    "test_online_deployment.py": 1.40, "test_fused_ce.py": 1.51,
    "test_flash_attention.py": 1.52, "test_rope.py": 1.56,
    "test_resilience.py": 1.58, "test_trainers.py": 1.66,
    "test_batchnorm.py": 1.82, "test_beam_search.py": 2.37,
    "test_serving.py": 2.51, "test_pipeline.py": 2.60,
    "test_decode.py": 2.76, "test_router.py": 3.55,
    "test_serving_disagg.py": 3.82, "test_serving_bench.py": 3.85,
    "test_serving_qos.py": 4.0,
    "test_speculative.py": 4.44, "test_ulysses.py": 4.50,
    "test_parallelism.py": 4.69, "test_attention.py": 4.91,
    "test_packing.py": 5.10, "test_parallel_transformer.py": 5.47,
    "test_serving_event.py": 5.1,
    "test_serving_resilience.py": 5.49, "test_zero.py": 5.55,
    "test_serving_fastpath.py": 6.12, "test_tpu_smoke.py": 6.43,
    "test_fsdp.py": 7.41,
}


def pytest_collection_modifyitems(config, items):
    items.sort(key=lambda it: (
        _FILE_COST.get(os.path.basename(str(it.fspath)), 3.0),
        str(it.fspath)))


@pytest.fixture(scope="session")
def eight_devices():
    import jax
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {devs}"
    return devs


@pytest.fixture()
def rng():
    return np.random.default_rng(0)


@pytest.fixture(params=["threaded", "event"])
def server_core(request, monkeypatch):
    """Parametrize ``ServingServer``'s transport core (PR 19): a
    wire-touching test that pulls this fixture runs once per core —
    thread-per-connection and one-selector event loop — with no edits at
    its construction sites; the fixture rebinds the constructor's
    DEFAULT, so explicit ``server_core=`` arguments still win."""
    from distkeras_tpu import serving
    core = request.param
    orig = serving.ServingServer.__init__

    def _init(self, *args, **kw):
        kw.setdefault("server_core", core)
        orig(self, *args, **kw)

    monkeypatch.setattr(serving.ServingServer, "__init__", _init)
    return core


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "tpu: exercises the real TPU chip in a subprocess (auto-skips when "
        "no accelerator is reachable)")
    config.addinivalue_line(
        "markers",
        "slow: multi-process end-to-end tests (worker subprocesses each "
        "import jax and compile)")
    config.addinivalue_line(
        "markers",
        "stream: streaming-ingestion / online-learning contract tests "
        "(tier-1 ones are generator-backed — no live sockets or sleeps on "
        "the fast path; socket-feed coverage uses socketpair only)")
    config.addinivalue_line(
        "markers",
        "paged: paged-KV-pool / radix-prefix-sharing serving tests "
        "(tier-1 ones run small seeded traces inline — no sleeps; the "
        "arena-pressure soaks and timing comparisons are additionally "
        "marked slow, mirroring the stream marker's tiering)")
    config.addinivalue_line(
        "markers",
        "analysis: dklint static-analysis contract tests (pure-ast over "
        "fixture strings plus the tier-1 zero-unbaselined gate over the "
        "package — no JAX imports of checked code, no sleeps)")
    config.addinivalue_line(
        "markers",
        "online: train-while-serve deployment tests (tier-1 ones are "
        "generator-backed and seeded with inline-pumped engines — no "
        "sleeps on the fast path; the chaos soak with live engine kills "
        "and supervised restarts is additionally marked slow)")
    config.addinivalue_line(
        "markers",
        "disagg: disaggregated prefill/decode serving tests (tier-1 legs "
        "are in-process or socketpair/loopback-only, seeded, and "
        "sleep-free; unified-vs-disagg timing comparisons are "
        "additionally marked slow)")
    config.addinivalue_line(
        "markers",
        "router: replicated-fleet routing tests (tier-1 legs are "
        "in-process or loopback-only, seeded, and bounded-wait — "
        "condition-variable waits with deadlines, no fixed sleeps on "
        "the fast path; fleet-scaling timing comparisons are "
        "additionally marked slow)")
    config.addinivalue_line(
        "markers",
        "dcn: cross-process/WAN-grade chaos and partition-tolerance tests "
        "(tier-1 legs are sleep-free and at most two-process-local — "
        "ChaosProxy/ProcessChaos schedules are seeded-deterministic; the "
        "multi-process DCN soaks with SIGSTOP legs and journal respawns "
        "are additionally marked slow)")
    config.addinivalue_line(
        "markers",
        "qos: multi-tenant QoS tests — quotas, weighted-fair admission, "
        "SLO tiers, and paged-KV preemption with bit-identical resume "
        "(tier-1 legs run seeded traces on inline-stepped engines — no "
        "sleeps on the fast path; the overload soak is additionally "
        "marked slow)")


@pytest.fixture()
def lock_order_audit():
    """Opt-in runtime lock-order auditing: locks created inside the test
    body (engine/supervisor construction included) are instrumented, and
    teardown asserts the acquisition-order graph stayed acyclic.  See
    distkeras_tpu/analysis/runtime.py."""
    from distkeras_tpu.analysis.runtime import audit_locks
    with audit_locks() as auditor:
        yield auditor
    assert auditor.violations == [], \
        "runtime lock-order violations:\n" + "\n".join(auditor.violations)
