"""Tests for elastic workers (``resilience.LeaseLedger`` /
``WorkerSupervisor`` + ``elastic=True`` on the async host-PS trainers).

Key invariants asserted here:
 - The **exactly-once lease contract**: every lease is completed exactly
   once per epoch by someone — killing k of N workers mid-epoch loses zero
   training examples.  Revoked (stolen) leases reject their former
   holder's late renew/complete, so a straggler can never double-record.
 - **Death → respawn**: a worker that raises or exits has its leases
   revoked and a replacement respawned under a fresh id from a live center
   pull; ``max_respawns`` bounds the budget and total loss still fails
   loudly.
 - **Wedge → steal**: a hung worker (injected 'hang' fault, or a
   ``ChaosProxy`` 'stall') misses its lease deadline — computed from its
   own window-rate EWMA × slack — and survivors steal the lease; the
   epoch still completes.
 - ``fault_injection`` accepts ``(kind, budget)`` with kinds
   'raise'/'exit'/'hang' (legacy int = 'raise'); 'exit' dies MID-FRAME
   (torn commit + RST), and the PS handler must drop that connection
   cleanly — no codec error, no leaked connection-bookkeeping entry.
 - ``elastic=False`` (default) keeps the static-shard engine bit for bit.
"""

import socket
import threading
import time

import numpy as np
import pytest

from distkeras_tpu import ADAG, DOWNPOUR, DynSGD, networking
from distkeras_tpu.networking import ChaosFault, ChaosProxy
from distkeras_tpu.parameter_servers import (DeltaParameterServer,
                                             SocketParameterServer)
from distkeras_tpu.resilience import Lease, LeaseLedger, WorkerSupervisor
from distkeras_tpu.workers import DOWNPOURWorker, parse_fault_injection

from test_host_ps import make_dataset, make_model, _tiny_blob
from test_trainers import eval_accuracy


# ---------------------------------------------------------------------------
# the lease ledger
# ---------------------------------------------------------------------------

def test_ledger_partitions_window_aligned():
    led = LeaseLedger(num_rows=1000, rows_per_window=128, lease_windows=2)
    leases = led.begin_epoch(0)
    # 1000 rows / (128*2) per lease -> 3 full leases + a 232-row tail
    assert [(l.start, l.stop) for l in leases] == [
        (0, 256), (256, 512), (512, 768), (768, 1000)]
    assert [l.windows for l in leases] == [2, 2, 2, 2]  # tail: ceil(232/128)
    assert sum(l.stop - l.start for l in leases) == 1000  # zero rows dropped


def test_ledger_exactly_once_under_steal():
    """A revoked lease's former holder cannot renew or complete it; the
    stealer's completion is the one recorded — exactly once."""
    t = [0.0]
    led = LeaseLedger(400, rows_per_window=100, lease_windows=2,
                      min_deadline=1.0, slack=4.0, clock=lambda: t[0])
    led.begin_epoch(0)
    a = led.acquire(0)
    assert a.lease_id == 0 and led.renew(a.lease_id, 0)
    # worker 0 wedges: no renewal past the deadline
    t[0] += 10.0
    revoked = led.revoke_expired()
    assert [(l.lease_id, h) for l, h in revoked] == [(0, 0)]
    assert led.reassigned == 1
    # the straggler's late heartbeat and completion are rejected
    assert not led.renew(a.lease_id, 0)
    assert not led.complete(a.lease_id, 0)
    # a survivor steals and completes
    b = led.acquire(1)
    assert b.lease_id == 0
    for _ in range(b.windows):
        t[0] += 0.1
        assert led.renew(b.lease_id, 1)
    assert led.complete(b.lease_id, 1)
    assert not led.complete(b.lease_id, 1)  # at most once, even for the owner
    c = led.acquire(1)
    for _ in range(c.windows):
        t[0] += 0.1
        led.renew(c.lease_id, 1)
    led.complete(c.lease_id, 1)
    assert led.epoch_done()
    rep = led.assert_epoch_complete(0)
    assert rep["by_worker"] == {0: 1, 1: 1}  # every lease exactly once
    assert rep["rows_completed"] == 400


def test_ledger_incomplete_epoch_fails_loudly():
    led = LeaseLedger(200, rows_per_window=100, lease_windows=1)
    led.begin_epoch(0)
    l = led.acquire(0)
    led.renew(l.lease_id, 0)
    led.complete(l.lease_id, 0)
    with pytest.raises(RuntimeError, match="incomplete"):
        led.assert_epoch_complete(0)


def test_ledger_deadline_follows_worker_rate_ewma():
    """Deadlines adapt to each worker's measured pace: after renewals at a
    known cadence the next deadline is slack x expected time for the
    remaining windows, floored by min_deadline."""
    t = [0.0]
    led = LeaseLedger(1000, rows_per_window=100, lease_windows=5,
                      min_deadline=0.1, slack=3.0, clock=lambda: t[0])
    led.begin_epoch(0)
    l = led.acquire(7)
    # no history yet: the floor is all we have
    assert led._state[l.lease_id]["deadline"] == pytest.approx(0.1)
    for _ in range(2):  # two windows at exactly 1.0s each
        t[0] += 1.0
        assert led.renew(l.lease_id, 7)
    assert led.rates[7] == pytest.approx(1.0)
    # 3 windows left at ~1s each, slack 3 -> deadline now + ~9s
    assert led._state[l.lease_id]["deadline"] == pytest.approx(t[0] + 9.0)
    # a dead worker's holdings return to the pool
    assert led.revoke_worker(7) == 1
    assert led.acquire(8).lease_id == l.lease_id


# ---------------------------------------------------------------------------
# fault-kind parsing (satellite 1)
# ---------------------------------------------------------------------------

def test_fault_injection_parsing():
    # legacy int form, string keys (JSON round-trip), tuple and list forms
    assert parse_fault_injection({1: 2, "3": 4}) == {1: ("raise", 2),
                                                     3: ("raise", 4)}
    assert parse_fault_injection({0: ("exit", 1), "2": ["hang", 5]}) == {
        0: ("exit", 1), 2: ("hang", 5)}
    assert parse_fault_injection(None) == {}
    with pytest.raises(ValueError, match="kind"):
        parse_fault_injection({0: ("explode", 1)})
    with pytest.raises(ValueError, match="budget"):
        parse_fault_injection({0: ("exit", 1, 2)})
    # worker constructor resolves kinds eagerly too
    with pytest.raises(ValueError, match="kind"):
        DOWNPOURWorker(_tiny_blob(), "sgd", "mse", "127.0.0.1", 1,
                       fault_injection={0: ("nope", 1)})


# ---------------------------------------------------------------------------
# half-frame worker death at the PS handler (satellite 2)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("close", ["fin", "rst", "torn_header"])
def test_half_frame_disconnect_closes_cleanly(close):
    """A worker dying mid-frame (clean FIN, RST, or a truncated header)
    must not leave the PS handler raising a codec error or leaking its
    connection-bookkeeping entry: the handler exits silently, the
    live-connection count returns to zero, the center is untouched, and
    the server keeps serving others."""
    ps = DeltaParameterServer(_tiny_blob())
    server = SocketParameterServer(ps)
    server.start()
    try:
        sock = networking.connect("127.0.0.1", server.port)
        frame = networking.encode_message(
            {"delta": [np.ones(3, np.float32)], "worker_id": 0, "clock": 0})
        networking.send_opcode(sock, b"u")
        if close == "torn_header":
            sock.sendall(b"DKT1" + (500).to_bytes(4, "little") + b"{")
        else:
            sock.sendall(frame[: len(frame) // 2])
        if close == "rst":
            networking._hard_close(sock)
        else:
            sock.close()
        deadline = time.time() + 5.0
        while server.live_connections and time.time() < deadline:
            time.sleep(0.01)
        assert server.live_connections == 0  # bookkeeping decremented
        assert len(server._conn_threads) == 0  # handler thread unwound
        assert ps.num_updates == 0  # the torn commit never applied
        # the server still serves a healthy worker
        ok = networking.connect("127.0.0.1", server.port)
        networking.send_opcode(ok, b"u")
        networking.send_data(ok, {"delta": [np.ones(3, np.float32)],
                                  "worker_id": 1, "clock": 0})
        msg = networking.recv_data(ok)
        assert msg["clock"] == 1
        networking.send_opcode(ok, b"q")
        ok.close()
    finally:
        server.stop()


def test_exit_fault_dies_mid_frame_through_the_real_worker():
    """The 'exit' fault kind leaves the PS exactly that half-frame corpse:
    the worker's injected death sends a torn commit + RST through its real
    connection, and the server sheds it without a trace."""
    blob = _tiny_blob()
    ps = DeltaParameterServer(blob)
    server = SocketParameterServer(ps)
    server.start()
    wk = DOWNPOURWorker(blob, "sgd", "mse", "127.0.0.1", server.port,
                        fault_injection={0: ("exit", 1)})
    try:
        wk.connect()
        wk.pull()
        wk.commit([np.ones(3, np.float32)], 0)  # commit 1: applies
        with pytest.raises(SystemExit, match="exits at commit 2"):
            wk.commit([np.ones(3, np.float32)], 0)
        deadline = time.time() + 5.0
        while server.live_connections and time.time() < deadline:
            time.sleep(0.01)
        assert server.live_connections == 0
        assert ps.num_updates == 1  # the torn second commit never applied
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# ChaosProxy 'stall' — wedged workers without real timeouts (satellite 1)
# ---------------------------------------------------------------------------

def test_chaos_proxy_stall_wedges_the_connection():
    """'stall' holds the connection open and relays nothing: the worker
    wedges inside its recv (no reply, no reset) until the proxy stops —
    the deterministic stand-in for a hung worker host."""
    ps = DeltaParameterServer(_tiny_blob())
    server = SocketParameterServer(ps)
    server.start()
    try:
        with ChaosProxy("127.0.0.1", server.port,
                        faults=[ChaosFault(0, 1, "stall")]) as proxy:
            sock = networking.connect(proxy.host, proxy.port)
            networking.send_opcode(sock, b"p")
            networking.recv_data(sock)  # op 0 relays normally
            networking.send_opcode(sock, b"p")  # op 1: stalled
            sock.settimeout(0.3)
            with pytest.raises(socket.timeout):
                networking.recv_data(sock)
            assert proxy.injected == [(0, 1, "stall")]
            assert ps.num_updates == 0
            sock.close()
        # stop() released the stalled relay thread (no hang on teardown)
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# the supervisor — death detection, respawn, bounded budget
# ---------------------------------------------------------------------------

def _fake_run_fn(ledger, die_once_for=(), window_s=0.02):
    """A run_fn that drains the ledger without jax: each acquired lease's
    windows 'train' for ``window_s`` seconds (so the supervisor's poll
    loop observably interleaves); workers in ``die_once_for`` raise on
    their first lease (once per worker id)."""
    died = set()

    def run(wid, worker):
        while True:
            lease = ledger.acquire(wid)
            if lease is None:
                return {"history": [], "state": None}
            if wid in die_once_for and wid not in died:
                died.add(wid)
                raise RuntimeError(f"synthetic death of worker {wid}")
            for _ in range(lease.windows):
                time.sleep(window_s)
                assert ledger.renew(lease.lease_id, wid)
            assert ledger.complete(lease.lease_id, wid)

    return run


def test_supervisor_respawns_dead_worker_and_epoch_completes():
    led = LeaseLedger(800, rows_per_window=100, lease_windows=2,
                      min_deadline=5.0)
    sup = WorkerSupervisor(led, lambda wid: object(),
                           _fake_run_fn(led, die_once_for={0}),
                           num_workers=2, poll_interval=0.005)
    sup.run_epoch(0)
    rep = led.assert_epoch_complete(0)
    assert rep["completed"] == 4
    assert sup.respawns == 1
    assert sup.respawn_records[0]["died"] == 0
    assert sup.respawn_records[0]["replacement"] == 2
    assert sup.respawn_records[0]["recovery_ms"] is not None
    assert 0 in sup.failures and "synthetic death" in sup.failures[0]


def test_supervisor_raises_once_respawn_budget_is_spent():
    led = LeaseLedger(200, rows_per_window=100, lease_windows=1,
                      min_deadline=5.0)

    def always_dies(wid, worker):
        lease = led.acquire(wid)
        if lease is None:
            return {"history": [], "state": None}
        raise RuntimeError(f"worker {wid} always dies")

    sup = WorkerSupervisor(led, lambda wid: object(), always_dies,
                           num_workers=1, poll_interval=0.01, max_respawns=2)
    with pytest.raises(RuntimeError, match="all elastic workers failed"):
        sup.run_epoch(0)
    assert sup.respawns == 2  # the budget really was spent first


# ---------------------------------------------------------------------------
# trainer knob validation
# ---------------------------------------------------------------------------

def test_elastic_knob_validation():
    m = make_model()
    kw = dict(num_workers=2, label_col="label_encoded")
    t = ADAG(m, execution="host_ps", elastic=True, **kw)
    assert t.elastic is True and t.lease_windows is None
    assert ADAG(m, execution="host_ps", **kw).elastic is False  # default off
    with pytest.raises(ValueError, match="elastic"):
        ADAG(m, elastic=True, **kw)  # SPMD: no elastic membership
    # process_ps elastic is the supervised cross-process engine
    assert ADAG(m, execution="process_ps", elastic=True, **kw).elastic
    with pytest.raises(ValueError, match="lease_windows"):
        ADAG(m, execution="host_ps", elastic=True, lease_windows=0, **kw)
    with pytest.raises(ValueError, match="lease_timeout"):
        ADAG(m, execution="host_ps", elastic=True, lease_timeout=0.0, **kw)


def test_elastic_rejects_checkpoint_and_bare_hang_faults(tmp_path):
    ds = make_dataset(n=256)
    t = ADAG(make_model(), num_workers=2, batch_size=32, num_epoch=1,
             label_col="label_encoded", execution="host_ps", elastic=True,
             checkpoint_dir=str(tmp_path))
    with pytest.raises(ValueError, match="elastic"):
        t.train(ds)
    # 'hang' without elastic would deadlock the epoch join: rejected
    t2 = ADAG(make_model(), num_workers=2, batch_size=32, num_epoch=1,
              label_col="label_encoded", execution="host_ps",
              fault_injection={0: ("hang", 1)})
    with pytest.raises(ValueError, match="hang"):
        t2.train(ds)


# ---------------------------------------------------------------------------
# end to end: death/respawn matrix (satellite 4)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("cls,shards,kw", [
    (DOWNPOUR, 1, {"learning_rate": 0.05}),
    (DOWNPOUR, 3, {"learning_rate": 0.05, "wire_dtype": "topk",
                   "wire_topk": 0.1}),
    (ADAG, 1, {"learning_rate": 0.1, "wire_dtype": "topk",
               "wire_topk": 0.1}),
    (ADAG, 3, {"learning_rate": 0.1}),
    (DynSGD, 1, {"learning_rate": 0.05}),
    (DynSGD, 3, {"learning_rate": 0.05}),
])
def test_elastic_death_respawn_matrix(cls, shards, kw):
    """{DOWNPOUR, ADAG, DynSGD} x ps_shards {1,3} x wire {dense, topk}:
    one worker exits mid-epoch; the supervisor respawns a replacement, the
    ledger completes every lease exactly once per epoch (zero examples
    lost), worker-visible PS clocks stay monotone, and the run learns."""
    ds = make_dataset(n=1024)
    t = cls(make_model(), num_workers=2, batch_size=32, num_epoch=2,
            communication_window=4, label_col="label_encoded",
            execution="host_ps", elastic=True, ps_shards=shards,
            fault_injection={0: ("exit", 2)}, **kw)
    fitted = t.train(ds)
    stats = t.elastic_stats
    # zero-loss contract: every epoch's leases completed exactly once
    for epoch in range(t.num_epoch):
        rep = stats["lease_completions"][epoch]
        assert rep["completed"] == rep["leases"]
        assert rep["rows_completed"] == 1024
    assert stats["respawns"] >= 1
    assert t.failed_workers == [0]
    assert "exits at commit" in t.worker_failures[0]
    # monotone PS clocks: no worker ever saw its clock view regress
    for w in t._ps_workers:
        client = getattr(w, "_shard_client", None)
        regressions = (client.clock_regressions if client is not None
                       else w.clock_regressions)
        assert regressions == 0
    assert eval_accuracy(fitted, ds) > 0.6


def test_elastic_straggler_leases_stolen_epoch_finishes():
    """Straggler mitigation: one of two workers wedges ('hang') mid-epoch;
    its leases are revoked on the EWMA deadline and stolen by the
    survivor, the epoch still completes with zero examples lost, and the
    wedge is diagnosable from the resilience event log."""
    ds = make_dataset(n=1024)
    t = ADAG(make_model(), num_workers=2, batch_size=32, num_epoch=2,
             communication_window=4, learning_rate=0.1,
             label_col="label_encoded", execution="host_ps", elastic=True,
             lease_timeout=0.5, fault_injection={0: ("hang", 2)})
    t0 = time.perf_counter()
    fitted = t.train(ds)
    elapsed = time.perf_counter() - t0
    stats = t.elastic_stats
    for epoch in range(t.num_epoch):
        rep = stats["lease_completions"][epoch]
        assert rep["completed"] == rep["leases"]
        assert rep["rows_completed"] == 1024
    # the hung worker stopped at its commit budget: everything past its 2
    # windows was trained by survivors/replacements
    assert stats["windows_per_worker"].get(0, 0) <= 2
    assert stats["leases_reassigned"] >= 1
    kinds = {e["kind"] for e in stats["events"]}
    assert "lease_revoked" in kinds and "death" in kinds
    assert 0 in t.worker_failures and "wedged" in t.worker_failures[0]
    # the epoch finished promptly: bounded by lease deadlines, not by the
    # hung worker (which stays wedged until teardown)
    assert elapsed < 120.0
    assert eval_accuracy(fitted, ds) > 0.6


# ---------------------------------------------------------------------------
# ACCEPTANCE: kill 2 of 4 mid-epoch (one exit, one hang), zero loss
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("cls,lr", [(DOWNPOUR, 0.05), (ADAG, 0.1)])
def test_elastic_kill_two_of_four_zero_examples_lost(cls, lr):
    """ACCEPTANCE: with elastic=True, killing 2 of 4 workers mid-epoch
    (one 'exit', one 'hang') loses zero examples — the lease ledger
    asserts exactly-once completion per epoch — and the MLP reaches the
    non-faulted run's quality band."""
    ds = make_dataset(n=1024)

    def run(faults):
        t = cls(make_model(), num_workers=4, batch_size=32, num_epoch=2,
                communication_window=4, learning_rate=lr,
                label_col="label_encoded", execution="host_ps",
                elastic=True, lease_timeout=0.5, fault_injection=faults)
        return t, t.train(ds)

    clean_t, clean = run(None)
    chaos_t, chaos = run({1: ("exit", 1), 2: ("hang", 2)})
    stats = chaos_t.elastic_stats
    for epoch in range(chaos_t.num_epoch):
        rep = stats["lease_completions"][epoch]
        assert rep["completed"] == rep["leases"], rep
        assert rep["rows_completed"] == 1024
    assert {1, 2} <= set(chaos_t.failed_workers)
    assert stats["respawns"] >= 1
    clean_acc = eval_accuracy(clean, ds)
    chaos_acc = eval_accuracy(chaos, ds)
    assert clean_acc > 0.6
    # the faulted run lands in the non-faulted band
    assert chaos_acc > max(0.6, clean_acc - 0.1), (clean_acc, chaos_acc)
    # the clean elastic run had nothing to recover from
    assert clean_t.elastic_stats["respawns"] == 0
    assert clean_t.failed_workers == []


def test_elastic_composes_with_recovery_worker_and_shard_both_die():
    """Worker-side elastic + server-side recovery in one run: a worker
    exits mid-epoch AND a PS shard is crash-killed.  The WorkerSupervisor
    respawns the worker, the ShardSupervisor respawns the shard from its
    snapshot, every lease still completes exactly once, and the run
    learns."""
    ds = make_dataset(n=1024)
    t = ADAG(make_model(), num_workers=2, batch_size=32, num_epoch=2,
             communication_window=4, learning_rate=0.1,
             label_col="label_encoded", execution="host_ps", elastic=True,
             ps_shards=2, recovery=True, fault_injection={0: ("exit", 2)})
    stop = threading.Event()

    def killer():
        while getattr(t, "_ps_supervisor", None) is None \
                and not stop.is_set():
            time.sleep(0.005)
        sup = t._ps_supervisor
        while sup.group.servers[0].ps.num_updates < 2 and not stop.is_set():
            time.sleep(0.005)
        sup.kill_shard(0)

    th = threading.Thread(target=killer)
    th.start()
    try:
        fitted = t.train(ds)
    finally:
        stop.set()
        th.join()
    stats = t.elastic_stats
    for epoch in range(t.num_epoch):
        rep = stats["lease_completions"][epoch]
        assert rep["completed"] == rep["leases"]
        assert rep["rows_completed"] == 1024
    assert stats["respawns"] >= 1  # the worker side recovered
    assert len(t._ps_supervisor.recoveries) >= 1  # the server side too
    assert eval_accuracy(fitted, ds) > 0.6


def test_elastic_false_default_is_bit_identical():
    """elastic defaults to False and the default path is byte-for-byte the
    static engine: a deterministic single-worker host_ps run yields
    identical weights across invocations and never builds a ledger or
    worker supervisor."""
    ds = make_dataset(n=256)

    def run():
        t = DOWNPOUR(make_model(), num_workers=1, batch_size=32, num_epoch=1,
                     communication_window=4, learning_rate=0.05,
                     label_col="label_encoded", execution="host_ps")
        fitted = t.train(ds)
        return t, fitted.get_weights()

    t1, w1 = run()
    t2, w2 = run()
    assert t1.elastic is False
    assert not hasattr(t1, "_worker_supervisor")  # elastic code never ran
    assert t1.elastic_stats == {}
    for a, b in zip(w1, w2):
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# chaos soak (satellite 6, slow path)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_elastic_chaos_soak_one_worker_kill_per_epoch():
    """Soak: one worker is killed per epoch for 5 epochs (the respawned
    replacement of each casualty is itself fault-injected, so the killing
    continues across the membership churn).  Every epoch must complete
    its ledger exactly once and training must still converge."""
    ds = make_dataset(n=1024)
    # staggered budgets: each original worker commits ~2 windows per epoch
    # (8 lease-windows over 4 workers), so the deaths land roughly one per
    # epoch as the budgets run out — sustained membership churn
    faults = {0: ("exit", 1), 1: ("exit", 3), 2: ("exit", 5),
              3: ("exit", 7)}
    t = ADAG(make_model(), num_workers=4, batch_size=32, num_epoch=5,
             communication_window=4, learning_rate=0.1,
             label_col="label_encoded", execution="host_ps", elastic=True,
             fault_injection=faults)
    fitted = t.train(ds)
    stats = t.elastic_stats
    assert stats["respawns"] >= 3  # it really did keep dying
    for epoch in range(t.num_epoch):
        rep = stats["lease_completions"][epoch]
        assert rep["completed"] == rep["leases"]
        assert rep["rows_completed"] == 1024
    assert eval_accuracy(fitted, ds) > 0.6
