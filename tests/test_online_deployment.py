"""Online deployment (PR 15): train-while-serve under one lifecycle.

The contract pinned here (docs/DEPLOY.md, "Online deployment"):

 - **Freshness** is exact accounting: rows are stamped at stream entry
   (``feed()`` time for served-traffic feedback, read arrival for base
   chunks), horizons stamp their commit, and a successful ``attach_ps``
   pull closes committed horizons into row-weighted ``freshness_p50/p99``
   samples — unit-tested against hand-computed instants.
 - **attach_ps hardening**: the reload socket dials under a
   ``RetryPolicy``, a failed pull counts ``reload_failures`` and keeps
   the current weights bit for bit, a successful pull counts ``reloads``
   and stamps ``center_generation`` from the PS clock — and a PS killed
   between a center commit and the next pull leaves the engine on the
   OLD generation with untorn weights.
 - **bind/advertise**: the socket PS binds ``ps_bind_host`` and workers/
   engines dial ``ps_advertise_host``; a wildcard bind advertises
   loopback; defaults keep the historical loopback pair.
 - **OnlineDeployment**: the process graph runs end to end — serving
   during training horizons (reload-during-horizon keeps serving), served
   accuracy improves on the SERVED path, blue/green swaps are atomic
   (contiguous generation tags, every response attributed to exactly one
   generation), engine death loses zero requests, and constructing no
   deployment changes nothing.

Tier-1 legs are generator-backed, seeded, and inline-pumped (no live
decode threads); the chaos soak (worker exit + PS shard kill + engine
kill + blue/green in one run) is additionally marked slow.
"""

import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

import jax

from distkeras_tpu import DOWNPOUR
from distkeras_tpu.core.model import FittedModel, serialize_model
from distkeras_tpu.deployment_online import (FreshnessTracker,
                                             OnlineDeployment,
                                             _weighted_percentile)
from distkeras_tpu.models import transformer_lm
from distkeras_tpu.parameter_servers import (DeltaParameterServer,
                                             make_socket_server,
                                             resolve_ps_hosts)
from distkeras_tpu.resilience import RetryPolicy
from distkeras_tpu.serving import EngineDead, ServingEngine
from distkeras_tpu.streaming import StreamSource

from test_streaming import (click_chunks, make_embedding_model,
                            make_mapping)

pytestmark = pytest.mark.online

V, L = 16, 4  # vocab / context of the tiny next-item LM


def make_lm(seed=0):
    model = transformer_lm(vocab_size=V, seq_len=L + 2, d_model=16,
                           num_heads=2, num_layers=1, mlp_dim=32,
                           compute_dtype="float32")
    params = model.init(jax.random.PRNGKey(seed), (L + 2,))
    return FittedModel(model, params)


def make_engine(seed=1, **kw):
    f = make_lm(seed)
    kw.setdefault("num_slots", 4)
    kw.setdefault("max_len", 3)
    return ServingEngine((f.model, f.params), **kw)


def make_stream_trainer(**kw):
    kw.setdefault("num_workers", 2)
    kw.setdefault("batch_size", 8)
    kw.setdefault("num_epoch", 1)
    kw.setdefault("communication_window", 2)
    kw.setdefault("execution", "host_ps")
    kw.setdefault("loss", "sparse_categorical_crossentropy_from_logits")
    kw.setdefault("worker_optimizer", "adam")
    kw.setdefault("learning_rate", 3e-3)
    kw.setdefault("stream", True)
    kw.setdefault("horizon_windows", 4)
    kw.setdefault("seed", 0)
    return DOWNPOUR(make_lm().model, **kw)


def mapping_chunks(mapping, num_chunks, rows=128, seed=0):
    """Token-mapping LM stream: y = mapping[x] per position — prompt
    ``[item]`` + one greedy step recommends ``mapping[item]``."""
    rng = np.random.default_rng(seed)
    for _ in range(num_chunks):
        x = rng.integers(0, V, (rows, L)).astype(np.int32)
        yield x, mapping[x]


PROBE = np.arange(V, dtype=np.int32).reshape(-1, 1)


def served_accuracy(dep, mapping):
    rows, gens = dep.serve(list(PROBE), num_steps=1)
    pred = np.array([r[1] for r in rows])
    return float(np.mean(pred == mapping[PROBE[:, 0]])), gens


# ---------------------------------------------------------------------------
# the freshness tracker (pure, hand-computed instants)
# ---------------------------------------------------------------------------

def test_weighted_percentile_exact():
    assert _weighted_percentile([], 50) is None
    assert _weighted_percentile([(3.0, 7)], 50) == 3.0
    s = [(2.0, 10), (3.0, 10)]
    assert _weighted_percentile(s, 50) == 2.0   # 10 rows reach the median
    assert _weighted_percentile(s, 99) == 3.0
    assert _weighted_percentile([(5.0, 1), (1.0, 99)], 50) == 1.0


def test_freshness_tracker_exact_samples():
    tr = FreshnessTracker()
    h = tr.note_horizon([(10, 0.0), (10, 1.0)])  # two stamped chunks
    tr.note_commit(h, t=2.0)
    tr.note_commit(h, t=9.0)  # idempotent: first commit instant wins
    tr.note_pull(3.0, generation=5)
    s = tr.stats()
    # samples: (3-0, 10 rows) and (3-1, 10 rows), row-weighted
    assert s["freshness_p50_s"] == 2.0
    assert s["freshness_p99_s"] == 3.0
    assert s["freshness_rows"] == 20
    assert s["freshness_horizons_served"] == 1
    assert s["freshness_horizons_committed"] == 1
    assert s["reload_pulls"] == 1
    assert s["center_generation"] == 5


def test_freshness_pull_serves_only_prior_commits():
    tr = FreshnessTracker()
    a = tr.note_horizon([(4, 0.0)])
    b = tr.note_horizon([(4, 0.5)])
    tr.note_pull(1.0, generation=1)       # nothing committed yet
    assert tr.stats()["freshness_rows"] == 0
    tr.note_commit(a, t=2.0)
    tr.note_commit(b, t=5.0)
    tr.note_pull(3.0, generation=2)       # serves a, NOT b (commit 5 > 3)
    s = tr.stats()
    assert s["freshness_horizons_served"] == 1
    assert s["freshness_rows"] == 4
    tr.note_pull(6.0, generation=3)       # now b, sample stays per-chunk
    s = tr.stats()
    assert s["freshness_horizons_served"] == 2
    assert s["freshness_rows"] == 8
    assert s["center_generation"] == 3
    # a's sample closed at ITS pull (3.0), not re-stamped by later pulls
    assert s["freshness_p50_s"] == 3.0


def test_freshness_empty_stats():
    s = FreshnessTracker().stats()
    assert s["freshness_p50_s"] is None and s["freshness_p99_s"] is None
    assert s["freshness_rows"] == 0 and s["reload_pulls"] == 0
    assert s["center_generation"] is None


# ---------------------------------------------------------------------------
# bind/advertise resolution (satellite 2)
# ---------------------------------------------------------------------------

def test_resolve_ps_hosts_matrix():
    def t(bind, adv):
        return SimpleNamespace(ps_bind_host=bind, ps_advertise_host=adv)

    # defaults: the historical loopback pair, bit for bit
    assert resolve_ps_hosts(t(None, None)) == ("127.0.0.1", "127.0.0.1")
    assert resolve_ps_hosts(object()) == ("127.0.0.1", "127.0.0.1")
    # a wildcard bind is listenable but not dialable -> advertise loopback
    assert resolve_ps_hosts(t("0.0.0.0", None)) == ("0.0.0.0", "127.0.0.1")
    assert resolve_ps_hosts(t("::", None)) == ("::", "127.0.0.1")
    # a concrete bind advertises itself
    assert resolve_ps_hosts(t("10.0.0.5", None)) == ("10.0.0.5", "10.0.0.5")
    # an explicit advertise always wins
    assert resolve_ps_hosts(t("0.0.0.0", "10.0.0.5")) == \
        ("0.0.0.0", "10.0.0.5")


def test_ps_host_knobs_validated_eagerly():
    with pytest.raises(ValueError, match="empty string"):
        make_stream_trainer(ps_bind_host="")
    with pytest.raises(ValueError, match="empty string"):
        make_stream_trainer(ps_advertise_host="")
    with pytest.raises(ValueError, match="host_ps"):
        DOWNPOUR(make_embedding_model(), num_workers=2, batch_size=8,
                 num_epoch=1, communication_window=2,
                 ps_bind_host="0.0.0.0")  # SPMD engine: no socket server


def test_stream_trains_on_wildcard_bind_loopback_advertise():
    """The PS binds 0.0.0.0 while workers dial the advertised loopback —
    the multi-host address split, exercised end to end on one host.  Also
    pins no-deployment-no-change: a plain stream run grows no freshness
    keys."""
    mapping = make_mapping()
    tr = DOWNPOUR(make_embedding_model(), num_workers=2, batch_size=16,
                  num_epoch=1, communication_window=2, learning_rate=0.5,
                  execution="host_ps", stream=True, horizon_windows=8,
                  seed=0, ps_bind_host="0.0.0.0",
                  ps_advertise_host="127.0.0.1")
    fitted = tr.train(StreamSource(
        generator=click_chunks(mapping, num_chunks=6, rows=64, seed=1)))
    assert fitted is not None
    assert tr.stream_stats["rows"] == 6 * 64   # every row trained
    assert "freshness_p50_s" not in tr.stream_stats


# ---------------------------------------------------------------------------
# attach_ps hardening (satellite 1)
# ---------------------------------------------------------------------------

def test_attach_ps_failed_pull_counts_and_keeps_weights():
    """No PS behind the address: the retry-policy dial fails, the pull
    counts a reload_failure, and serving continues bit-identically on the
    current weights."""
    import socket as _socket
    probe = _socket.socket()
    probe.bind(("127.0.0.1", 0))
    dead_port = probe.getsockname()[1]
    probe.close()  # nothing listens there now

    f = make_lm(seed=3)
    eng = ServingEngine((f.model, f.params), num_slots=1, max_len=3)
    eng.attach_ps("127.0.0.1", dead_port, every=1,
                  retry_policy=RetryPolicy(attempts=1, backoff=0.0,
                                           jitter=0.0, deadline=0.05))
    want = np.asarray(f.generate(PROBE[3][None], 1, max_len=3))[0]
    h = eng.submit(PROBE[3], 1)
    eng.run_until_idle()
    assert eng.stats["reload_failures"] >= 1
    assert eng.stats["reloads"] == 0
    assert eng.stats["center_generation"] is None
    np.testing.assert_array_equal(h.result(), want)


def test_attach_ps_center_generation_tracks_ps_clock():
    """A successful pull stamps center_generation from the PS clock and
    fires the reload listener; the clock advances with commits."""
    center = make_lm(seed=9)
    ps = DeltaParameterServer(
        serialize_model(center.model, center.params))
    server = make_socket_server(ps)
    server.start()
    seen = []
    try:
        eng = make_engine(seed=1)
        eng.attach_ps("127.0.0.1", server.port, every=1)
        eng._reload_listener = lambda t, g: seen.append(g)
        h = eng.submit(PROBE[0], 1)
        eng.run_until_idle()
        assert h.done
        assert eng.stats["reloads"] >= 1
        assert eng.stats["center_generation"] == ps.num_updates == 0
        # the engine now serves the pulled center's numerics
        want = np.asarray(center.generate(PROBE[5][None], 1, max_len=3))[0]
        h2 = eng.submit(PROBE[5], 1)
        eng.run_until_idle()
        np.testing.assert_array_equal(h2.result(), want)
        # a commit advances the clock; the next pull observes it
        delta = [np.zeros_like(w) for w in ps.center]
        ps.handle_commit({"delta": delta, "worker_id": 0, "clock": 0})
        h3 = eng.submit(PROBE[6], 1)
        eng.run_until_idle()
        assert h3.done
        assert eng.stats["center_generation"] == ps.num_updates == 1
        assert seen[-1] == 1 and seen[0] == 0  # listener saw each pull
    finally:
        server.stop()


def test_ps_kill_between_commit_and_pull_keeps_old_generation():
    """The PS dies after a center commit but before the engine's next
    pull: the pull fails, the engine stays on the OLD generation with
    untorn weights — never a half-applied center."""
    center = make_lm(seed=9)
    ps = DeltaParameterServer(
        serialize_model(center.model, center.params))
    server = make_socket_server(ps)
    server.start()
    eng = make_engine(seed=1)
    eng.attach_ps("127.0.0.1", server.port, every=1,
                  retry_policy=RetryPolicy(attempts=1, backoff=0.0,
                                           jitter=0.0, deadline=0.05))
    h = eng.submit(PROBE[0], 1)
    eng.run_until_idle()
    assert h.done and eng.stats["reloads"] == 1
    assert eng.stats["center_generation"] == 0
    frozen = [np.asarray(w).copy() for w in
              eng.model.get_weights(eng.params)]
    # the center commits generation 1... and the PS dies before the pull
    ps.handle_commit({"delta": [np.ones_like(w) for w in ps.center],
                      "worker_id": 0, "clock": 0})
    server.stop()
    h2 = eng.submit(PROBE[7], 1)
    eng.run_until_idle()
    assert h2.done
    assert eng.stats["reload_failures"] >= 1
    assert eng.stats["center_generation"] == 0  # still the old generation
    for a, b in zip(eng.model.get_weights(eng.params), frozen):
        np.testing.assert_array_equal(np.asarray(a), b)  # untorn


def test_attach_ps_sharded_pull_gathers_full_center():
    """Sharded attach_ps (ps_shards>1): a pull gathers the center across
    the whole ShardedServerGroup — never one shard's torn slice — the
    clock sums the per-shard applies, and losing ANY shard keeps the
    current weights wholesale."""
    from distkeras_tpu.ps_sharding import ShardedServerGroup
    f = make_lm(seed=3)
    blob = serialize_model(f.model, f.params)
    group = ShardedServerGroup("downpour", blob, 1, 2)
    group.start()
    try:
        eng = make_engine(seed=4)  # different seed: weights differ
        pol = RetryPolicy(attempts=1, backoff=0.01, jitter=0.0,
                          deadline=0.25)
        eng.attach_ps("127.0.0.1", group.ports[0], retry_policy=pol,
                      shard_plan=group.plan,
                      shard_addrs=[("127.0.0.1", p) for p in group.ports])
        assert eng._ps_shard_addrs is not None
        eng._pull_weights()
        assert eng.stats["reloads"] == 1
        assert eng.stats["center_generation"] == 0
        center, _ = group.snapshot()
        pulled = eng.model.get_weights(eng.params)
        for a, b in zip(pulled, center):
            np.testing.assert_allclose(np.asarray(a), b, rtol=1e-6)
        # a restored center with per-shard clocks gathers back exactly,
        # and the engine's generation is the summed shard clocks
        bumped = [w + 1.0 for w in center]
        group.restore_state(bumped, [5, 7])
        eng._pull_weights()
        assert eng.stats["reloads"] == 2
        assert eng.stats["center_generation"] == 12
        for a, b in zip(eng.model.get_weights(eng.params), bumped):
            np.testing.assert_allclose(np.asarray(a), b, rtol=1e-6)
        # one shard down → the WHOLE pull fails, weights keep wholesale
        frozen = [np.array(w, copy=True)
                  for w in eng.model.get_weights(eng.params)]
        group.servers[1].stop()
        eng._pull_weights()
        assert eng.stats["reload_failures"] >= 1
        assert eng._reload_client is None  # torn client torn down
        for a, b in zip(eng.model.get_weights(eng.params), frozen):
            np.testing.assert_array_equal(np.asarray(a), b)
    finally:
        group.stop()


def test_attach_ps_shard_kwargs_validated():
    from distkeras_tpu.ps_sharding import make_shard_plan
    eng = make_engine()
    with pytest.raises(ValueError, match="pair"):
        eng.attach_ps("127.0.0.1", 1, shard_addrs=[("127.0.0.1", 1)])
    # the N=1 plan degenerates to the plain single-socket attachment
    plan = make_shard_plan([(2, 2)], [np.float32], 1)
    eng.attach_ps("127.0.0.1", 1, shard_plan=plan,
                  shard_addrs=[("127.0.0.1", 1)])
    assert eng._ps_shard_addrs is None and eng._ps_shard_plan is None


def test_respawn_clone_carries_reload_policy_and_listener():
    pol = RetryPolicy(attempts=2, backoff=0.01, jitter=0.0, deadline=0.2)
    seen = []
    eng = make_engine(seed=1)
    eng.attach_ps("127.0.0.1", 1, every=3, retry_policy=pol)
    eng._reload_listener = seen.append
    clone = eng.respawn_clone()
    assert clone._ps_addr == eng._ps_addr
    assert clone._reload_every == 3
    assert clone._reload_policy is pol
    assert clone._reload_listener is eng._reload_listener
    # a SHARDED attachment carries over too (blue/green over ps_shards>1)
    from distkeras_tpu.ps_sharding import make_shard_plan
    plan = make_shard_plan([(4, 4)], [np.float32], 2)
    eng2 = make_engine(seed=1)
    eng2.attach_ps("127.0.0.1", 1, shard_plan=plan,
                   shard_addrs=[("127.0.0.1", 1), ("127.0.0.1", 2)])
    clone2 = eng2.respawn_clone()
    assert clone2._ps_shard_plan is plan
    assert clone2._ps_shard_addrs == eng2._ps_shard_addrs


# ---------------------------------------------------------------------------
# OnlineDeployment: construction contract
# ---------------------------------------------------------------------------

def test_online_deployment_validation():
    eng = make_engine()
    src = StreamSource(generator=iter(()))
    with pytest.raises(ValueError, match="stream=True"):
        OnlineDeployment(
            DOWNPOUR(make_lm().model, num_workers=2, batch_size=8,
                     num_epoch=1, execution="host_ps"), src, eng)
    # ps_shards>1 is now a supported deployment shape (sharded attach_ps
    # gathers the center across the group — test_online_sharded_ps)
    dep = OnlineDeployment(make_stream_trainer(ps_shards=2), src,
                           make_engine())
    assert dep.trainer.ps_shards == 2
    with pytest.raises(ValueError, match="StreamSource"):
        OnlineDeployment(make_stream_trainer(), [1, 2], eng)
    with pytest.raises(ValueError, match="ServingEngine"):
        OnlineDeployment(make_stream_trainer(), src, object())
    with pytest.raises(ValueError, match="reload_every"):
        OnlineDeployment(make_stream_trainer(), src, eng, reload_every=0)
    attached = make_engine()
    attached.attach_ps("127.0.0.1", 1)
    with pytest.raises(ValueError, match="already attach_ps-ed"):
        OnlineDeployment(make_stream_trainer(), src, attached)


def test_no_deployment_no_behavior_change():
    """Constructing no OnlineDeployment leaves every seam at its default:
    the hooks are None, the engine counters zero, and CONSTRUCTING one
    mutates neither the base source nor the engine until start()."""
    tr = make_stream_trainer()
    assert getattr(tr, "_on_ps_ready", None) is None
    assert tr.on_horizon is None
    assert tr.ps_bind_host is None and tr.ps_advertise_host is None
    eng = make_engine()
    assert eng._reload_listener is None and eng._reload_policy is None
    assert eng.stats["reloads"] == 0
    assert eng.stats["reload_failures"] == 0
    assert eng.stats["center_generation"] is None
    base = StreamSource(generator=iter(()))
    dep = OnlineDeployment(make_stream_trainer(), base, eng)
    assert dep.source._base is base       # wrapped, not mutated
    assert eng._ps_addr is None           # attachment waits for start()
    assert eng._reload_listener is None
    assert dep.generation == 0 and dep.swaps == []


# ---------------------------------------------------------------------------
# the process graph end to end (tier-1: inline engine, natural drain)
# ---------------------------------------------------------------------------

def test_online_deployment_serves_during_horizons_and_tracks_freshness():
    """The tentpole loop: training horizons commit to the live PS, the
    inline engine hot-reloads BETWEEN decode steps while serving probe
    traffic from on_horizon (reload-during-horizon keeps serving), served
    traffic feeds back, and the run drains naturally once the base stream
    and feedback end.  Freshness is populated and mirrored."""
    rng = np.random.default_rng(0)
    mapping = rng.permutation(V).astype(np.int32)
    trainer = make_stream_trainer()
    dep = OnlineDeployment(
        trainer, StreamSource(generator=mapping_chunks(mapping, 3)),
        make_engine(), reload_every=1)
    curve, gen_tags = [], []

    def on_horizon(h, fitted):
        acc, gens = served_accuracy(dep, mapping)
        curve.append(acc)
        gen_tags.extend(gens)
        if h < 3:  # feedback rides along while the base stream lives
            fx = np.repeat(PROBE, L, axis=1)
            dep.feed(fx, mapping[fx])

    trainer.on_horizon = on_horizon
    dep.start()
    assert dep.wait_ps_ready(timeout=60.0)
    fitted = dep.join(timeout=300.0)
    dep.stop()
    assert fitted is not None and dep.done
    s = dep.stats()
    # zero lost examples: base + feedback rows all trained
    assert s["stream_stats"]["rows"] == 3 * 128 + s["rows_fed_back"]
    assert s["rows_fed_back"] > 0
    # the engine kept serving through every reload
    assert len(curve) == s["stream_stats"]["horizons"]
    assert s["engine_requests_failed"] == 0
    assert s["engine_requests_completed"] == len(gen_tags)
    assert all(g == 0 for g in gen_tags)  # no swaps: one generation
    # reload + freshness observables, populated and mirrored
    assert s["engine_reloads"] > 0
    assert s["engine_center_generation"] is not None
    assert s["freshness_p50_s"] is not None
    assert s["freshness_p99_s"] >= s["freshness_p50_s"]
    assert s["freshness_rows"] > 0
    assert trainer.stream_stats["freshness_p50_s"] == s["freshness_p50_s"]
    eng = dep.engine
    assert eng.stats["freshness_p50_s"] == s["freshness_p50_s"]
    # the served model LEARNED the mapping on the served path
    assert curve[-1] >= curve[0]
    assert curve[-1] >= 0.5


@pytest.mark.slow
def test_online_sharded_ps_kill_mid_horizon_untorn():
    """ISSUE 20 acceptance: the train-while-serve lifecycle over a SHARDED
    PS (ps_shards=2, recovery=True) — the engine's hot reload gathers the
    full center across the group, a PS shard killed mid-horizon respawns
    same-address through the ShardSupervisor, and serving never observes a
    torn center: every reload is all-shards-or-nothing, requests keep
    completing, and the served model still learns the mapping."""
    rng = np.random.default_rng(7)
    mapping = rng.permutation(V).astype(np.int32)
    trainer = make_stream_trainer(ps_shards=2, recovery=True)
    dep = OnlineDeployment(
        trainer, StreamSource(generator=mapping_chunks(mapping, 3,
                                                       seed=7)),
        make_engine(), reload_every=1)
    curve = []

    def on_horizon(h, fitted):
        if h == 1:
            dep.kill_ps_shard(0)  # mid-horizon chaos: shard 0 dies
        acc, gens = served_accuracy(dep, mapping)
        curve.append(acc)

    trainer.on_horizon = on_horizon
    dep.start()
    assert dep.wait_ps_ready(timeout=60.0)
    # the engine attached SHARDED: plan + one address per shard
    assert dep.engine._ps_shard_addrs is not None
    assert len(dep.engine._ps_shard_addrs) == 2
    fitted = dep.join(timeout=300.0)
    dep.stop()
    assert fitted is not None
    s = dep.stats()
    # the shard kill recovered same-address (journal respawn)
    recs = trainer._ps_supervisor.recoveries
    assert any(r["shard"] == 0 for r in recs)
    # zero lost base examples, serving never failed a request, and the
    # gathered reloads kept the served model learning
    assert s["stream_stats"]["rows"] == 3 * 128
    assert s["engine_requests_failed"] == 0
    assert s["engine_reloads"] > 0
    assert s["engine_center_generation"] is not None
    assert curve[-1] >= curve[0]
    assert curve[-1] >= 0.5


def test_blue_green_swaps_atomic_attribution():
    """Three blue/green swaps mid-run: generation tags stay contiguous,
    every response is attributed to exactly one generation, the old
    engine drains clean, and g+1 pulled the freshest center."""
    rng = np.random.default_rng(1)
    mapping = rng.permutation(V).astype(np.int32)
    trainer = make_stream_trainer(seed=1)
    dep = OnlineDeployment(
        trainer, StreamSource(generator=mapping_chunks(mapping, 3,
                                                       seed=1)),
        make_engine(), reload_every=1)
    records, by_gen, by_gen_horizons = [], {}, []

    def on_horizon(h, fitted):
        if h in (0, 1, 2):
            records.append(dep.blue_green_swap())
        acc, gens = served_accuracy(dep, mapping)
        assert len(set(gens)) == 1  # one serve batch, one generation
        by_gen.setdefault(gens[0], 0)
        by_gen[gens[0]] += len(gens)
        by_gen_horizons.append(h)

    trainer.on_horizon = on_horizon
    dep.start()
    dep.join(timeout=300.0)
    dep.stop()
    s = dep.stats()
    assert len(records) == 3
    assert all(r["blue_green"] for r in records)
    assert all(r["old_drained_clean"] for r in records)
    assert all(r["pulled"] for r in records)  # warmed on the live center
    # atomic: swap generations are exactly 1, 2, 3 — no gaps, no tears
    assert [r["generation"] for r in records] == [1, 2, 3]
    assert s["generation"] == 3
    # every probe is attributed to exactly one generation, and the stats
    # snapshot counts the CURRENT engine's share of them (earlier
    # generations retired their requests before draining)
    assert sum(by_gen.values()) == len(PROBE) * len(by_gen_horizons)
    assert s["engine_requests_completed"] == by_gen[s["generation"]]
    assert s["engine_requests_failed"] == 0


def test_serve_resubmits_lost_requests_after_engine_kill():
    """Requests in flight at an engine kill fail with EngineDead; serve()
    resubmits them to the swapped-in replacement — zero lost requests."""
    trainer = make_stream_trainer()
    eng = make_engine()
    dep = OnlineDeployment(
        trainer, StreamSource(generator=iter(())), eng, reload_every=1)
    # in-flight handles die loudly...
    h, g = dep.submit(PROBE[2], 1)
    assert g == 0
    dep.kill_engine()
    with pytest.raises(EngineDead):
        h.result(timeout=1.0)
    # ...and serve() rides the atomic swap to the replacement
    clone = eng.respawn_clone()
    threading.Timer(0.05, lambda: setattr(dep, "engine", clone)).start()
    rows, gens = dep.serve(list(PROBE[:4]), num_steps=1, retry_wait_s=5.0)
    assert all(r is not None for r in rows)
    assert gens == [1, 1, 1, 1]  # all on the replacement's generation
    assert dep.swaps[-1]["old_dead"] is True


def test_serve_raises_when_no_replacement_arrives():
    dep = OnlineDeployment(make_stream_trainer(),
                           StreamSource(generator=iter(())),
                           make_engine(), reload_every=1)
    dep.kill_engine()
    with pytest.raises(EngineDead, match="lost|replacement"):
        dep.serve(list(PROBE[:2]), num_steps=1, retries=1,
                  retry_wait_s=0.05)


def test_kill_ps_shard_requires_recovery():
    dep = OnlineDeployment(make_stream_trainer(),
                           StreamSource(generator=iter(())),
                           make_engine())
    with pytest.raises(RuntimeError, match="recovery=True"):
        dep.kill_ps_shard()


def test_source_stop_ends_self_sustaining_feedback_loop():
    """stop() must terminate a SELF-SUSTAINING stream: feedback pending
    at close is abandoned and the read returns None — otherwise a run
    whose on_horizon feeds every horizon would never end."""
    dep = OnlineDeployment(make_stream_trainer(),
                           StreamSource(generator=iter(())),
                           make_engine())
    dep.source.feed(np.zeros((4, L), np.int32), np.zeros((4, L), np.int32))
    assert dep.source.rows_fed_back == 4
    dep.source.stop()
    assert dep.source.read(64) is None  # pending feedback abandoned


def test_start_is_one_shot():
    rng = np.random.default_rng(3)
    mapping = rng.permutation(V).astype(np.int32)
    dep = OnlineDeployment(
        make_stream_trainer(),
        StreamSource(generator=mapping_chunks(mapping, 1, seed=3)),
        make_engine())
    dep.start()
    assert dep.join(timeout=120.0) is not None
    with pytest.raises(RuntimeError, match="one-shot"):
        dep.start()
    dep.stop()


# ---------------------------------------------------------------------------
# the chaos soak: every seam killed in one run (slow)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_online_chaos_soak_every_seam():
    """One run, every seam: a worker exits mid-horizon (lease re-lease), a
    PS shard is crash-stopped and respawned same-address (journal), the
    engine is declared dead and supervised back (atomic swap), and a
    blue/green swap lands — zero lost examples, zero lost requests, and
    the served model still learns."""
    rng = np.random.default_rng(2)
    mapping = rng.permutation(V).astype(np.int32)
    trainer = make_stream_trainer(
        seed=2, recovery=True,
        fault_injection={1: ("exit", 2)})
    dep = OnlineDeployment(
        trainer, StreamSource(generator=mapping_chunks(mapping, 4,
                                                       seed=2)),
        make_engine(), reload_every=1, supervise=True,
        supervisor_kw={"heartbeat_interval": 0.05,
                       "liveness_deadline": 15.0})
    curve = []

    def on_horizon(h, fitted):
        if h == 1:
            dep.kill_engine()          # EngineSupervisor swaps a clone in
        if h == 2:
            dep.kill_ps_shard(0)       # ShardSupervisor same-addr respawn
        if h == 3:
            dep.blue_green_swap()
        acc, gens = served_accuracy(dep, mapping)
        assert all(g is not None for g in gens)
        curve.append(acc)
        if h < 4:
            fx = np.repeat(PROBE, L, axis=1)
            dep.feed(fx, mapping[fx])

    trainer.on_horizon = on_horizon
    dep.start()
    dep.join(timeout=300.0)
    dep.stop()
    s = dep.stats()
    assert s["stream_stats"]["rows"] == 4 * 128 + s["rows_fed_back"]
    assert s["elastic_stats"]["respawns"] >= 1        # the worker seam
    assert any(r["restarted"]
               for r in s["engine_recoveries"])       # the engine seam
    assert trainer._ps_supervisor.restarts  # the PS seam
    assert [r["generation"] for r in s["swaps"]] == \
        list(range(1, len(s["swaps"]) + 1))           # atomic swaps
    assert any(r.get("blue_green") for r in s["swaps"])
    assert s["engine_reloads"] > 0
    assert s["freshness_p50_s"] is not None
    assert curve[-1] >= curve[0]
