"""Pallas flash-attention kernel vs the XLA reference (interpret mode on CPU;
the same kernel compiles for TPU — SURVEY.md §2.2 TPU-native kernel note)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.ops.attention import dot_product_attention
from distkeras_tpu.ops.flash_attention import flash_attention


def rand_qkv(seed, b=2, s=64, h=2, d=16):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (b, s, h, d)) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    q, k, v = rand_qkv(0)
    out = flash_attention(q, k, v, causal, None, 16, 16, True)
    want = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=1e-5)


def test_flash_single_block():
    q, k, v = rand_qkv(1, s=16)
    out = flash_attention(q, k, v, True, None, 128, 128, True)
    want = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=1e-5)


def test_flash_gradients():
    q, k, v = rand_qkv(2, b=1, s=32, h=1, d=8)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, True, None, 16, 16, True).sum()

    def loss_ref(q, k, v):
        return dot_product_attention(q, k, v, causal=True).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


@pytest.mark.parametrize("window", [1, 5, 16, 40])
def test_flash_sliding_window_matches_reference(window):
    """Windowed flash (multi-block: out-of-window k blocks skipped via
    _live_kq) == windowed XLA reference, forward and all three grads."""
    q, k, v = rand_qkv(7, b=1, s=64, h=2, d=8)
    out = flash_attention(q, k, v, True, None, 16, 16, True, window)
    want = dot_product_attention(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=1e-5)

    def f_flash(q, k, v):
        return flash_attention(q, k, v, True, None, 16, 16, True,
                               window).sum()

    def f_ref(q, k, v):
        return dot_product_attention(q, k, v, causal=True,
                                     window=window).sum()

    gf = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_flash_window_requires_causal():
    q, k, v = rand_qkv(8, s=16)
    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, k, v, False, None, 16, 16, True, 4)


def test_indivisible_seq_raises():
    q, k, v = rand_qkv(3, s=48)
    with pytest.raises(ValueError, match="not divisible"):
        flash_attention(q, k, v, False, None, 32, 32, True)


@pytest.mark.parametrize("causal", [False, True])
def test_fused_backward_gradient_parity(causal):
    """The fused Pallas dq/dk/dv kernels match the dense-attention VJP on a
    multi-block problem (several q AND k blocks, both mask modes) with a
    non-uniform cotangent."""
    q, k, v = rand_qkv(4, b=2, s=64, h=2, d=16)
    ct = jax.random.normal(jax.random.PRNGKey(9), q.shape)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal, None, 16, 16, True)
                * ct).sum()

    def loss_ref(q, k, v):
        return (dot_product_attention(q, k, v, causal=causal) * ct).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4,
                                   err_msg=f"d{name}")


def _walk_avals(jaxpr):
    for eqn in jaxpr.eqns:
        for var in eqn.outvars:
            yield var.aval
        for sub in jax.core.jaxprs_in_params(eqn.params) \
                if hasattr(jax.core, "jaxprs_in_params") else []:
            yield from _walk_avals(sub)
        for p in eqn.params.values():
            if hasattr(p, "jaxpr"):
                yield from _walk_avals(p.jaxpr)
            if isinstance(p, (list, tuple)):
                for item in p:
                    if hasattr(item, "jaxpr"):
                        yield from _walk_avals(item.jaxpr)


def test_backward_materializes_no_sxs():
    """Evidence for the flash memory claim: the whole value-and-grad
    computation contains no (S, S)-shaped intermediate — only block-sized
    tiles (the dense reference VJP does materialize S x S)."""
    s, blk = 256, 64
    q, k, v = rand_qkv(5, b=1, s=s, h=1, d=16)

    def loss(q, k, v):
        return flash_attention(q, k, v, True, None, blk, blk, True).sum()

    jaxpr = jax.make_jaxpr(jax.value_and_grad(loss, argnums=(0, 1, 2)))(
        q, k, v)

    def has_sxs(closed):
        return any(
            len(a.shape) >= 2 and a.shape[-1] == s and a.shape[-2] == s
            for a in _walk_avals(closed.jaxpr))

    assert not has_sxs(jaxpr), "flash backward materialized an S x S array"

    # sanity: the same detector fires on the dense reference
    def loss_ref(q, k, v):
        return dot_product_attention(q, k, v, causal=True).sum()

    ref = jax.make_jaxpr(jax.value_and_grad(loss_ref, argnums=(0, 1, 2)))(
        q, k, v)
    assert has_sxs(ref), "detector lost its teeth"


def test_flash_bf16_gradients_close():
    """bf16 inputs (the TPU training dtype): fused backward stays within
    bf16 tolerance of the f32 dense reference."""
    q, k, v = (t.astype(jnp.bfloat16) for t in rand_qkv(6, b=1, s=64, h=2,
                                                        d=16))

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, True, None, 32, 32, True)\
            .astype(jnp.float32).sum()

    def loss_ref(q, k, v):
        return dot_product_attention(
            q.astype(jnp.float32), k.astype(jnp.float32),
            v.astype(jnp.float32), causal=True).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(
        *(t.astype(jnp.float32) for t in (q, k, v)))
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a, dtype=np.float32),
                                   np.asarray(b), atol=0.06)
