"""Pallas flash-attention kernel vs the XLA reference (interpret mode on CPU;
the same kernel compiles for TPU — SURVEY.md §2.2 TPU-native kernel note)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.ops.attention import dot_product_attention
from distkeras_tpu.ops.flash_attention import flash_attention


def rand_qkv(seed, b=2, s=64, h=2, d=16):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (b, s, h, d)) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    q, k, v = rand_qkv(0)
    out = flash_attention(q, k, v, causal, None, 16, 16, True)
    want = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=1e-5)


def test_flash_single_block():
    q, k, v = rand_qkv(1, s=16)
    out = flash_attention(q, k, v, True, None, 128, 128, True)
    want = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=1e-5)


def test_flash_gradients():
    q, k, v = rand_qkv(2, b=1, s=32, h=1, d=8)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, True, None, 16, 16, True).sum()

    def loss_ref(q, k, v):
        return dot_product_attention(q, k, v, causal=True).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_indivisible_seq_raises():
    q, k, v = rand_qkv(3, s=48)
    with pytest.raises(ValueError, match="not divisible"):
        flash_attention(q, k, v, False, None, 32, 32, True)
