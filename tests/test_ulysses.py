"""Ulysses (all-to-all) sequence parallelism vs full attention and vs the
ring schedule, on the 8-device virtual mesh.  No reference counterpart
(SURVEY.md §2.3: sequence parallelism absent upstream) — with ring.py this
completes the two SP schedules SURVEY §5 names ("ring attention or
all-to-all sequence/context parallelism").
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from distkeras_tpu.ops.attention import dot_product_attention
from distkeras_tpu.parallel import get_mesh
from distkeras_tpu.parallel.transformer import ParallelTransformerLM
from distkeras_tpu.parallel.ulysses import ulysses_self_attention


def rand_qkv(rng, b=2, s=64, h=8, hkv=None, d=16):
    ks = jax.random.split(rng, 3)
    q = jax.random.normal(ks[0], (b, s, h, d))
    k = jax.random.normal(ks[1], (b, s, hkv or h, d))
    v = jax.random.normal(ks[2], (b, s, hkv or h, d))
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_full(eight_devices, causal):
    """Sequence sharded over 8 devices; two all_to_alls + local full-S
    attend == full attention."""
    mesh = get_mesh(8, axis_name="seq")
    q, k, v = rand_qkv(jax.random.PRNGKey(0))
    out = ulysses_self_attention(q, k, v, mesh, axis_name="seq",
                                 causal=causal)
    want = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=1e-5)


@pytest.mark.parametrize("kv_heads", [1, 2])
def test_ulysses_gqa_repeat_path_matches_full(eight_devices, kv_heads):
    """Hkv % sp != 0: k/v repeat up to H before the reshard; forward and
    k-gradients equal full-array GQA attention."""
    mesh = get_mesh(8, axis_name="seq")
    q, k, v = rand_qkv(jax.random.PRNGKey(1), hkv=kv_heads)
    out = ulysses_self_attention(q, k, v, mesh, axis_name="seq", causal=True)
    want = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=1e-5)

    g_u = jax.grad(lambda k_: ulysses_self_attention(
        q, k_, v, mesh, axis_name="seq", causal=True).sum())(k)
    g_f = jax.grad(lambda k_: dot_product_attention(
        q, k_, v, causal=True).sum())(k)
    np.testing.assert_allclose(np.asarray(g_u), np.asarray(g_f), atol=1e-4)


def test_ulysses_gqa_divisible_split_matches_full(eight_devices):
    """Hkv % sp == 0: kv heads split directly (no repeat) and the per-device
    head-group alignment preserves the global GQA grouping."""
    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    q, k, v = rand_qkv(jax.random.PRNGKey(2), hkv=4)
    out = ulysses_self_attention(q, k, v, mesh, axis_name="seq", causal=True)
    want = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=1e-5)


def test_ulysses_window_matches_full(eight_devices):
    """Sliding window on the gathered full sequence == windowed full
    attention (global positions line up with block-ordered all_to_all)."""
    mesh = get_mesh(8, axis_name="seq")
    q, k, v = rand_qkv(jax.random.PRNGKey(3))
    out = ulysses_self_attention(q, k, v, mesh, axis_name="seq",
                                 causal=True, window=12)
    want = dot_product_attention(q, k, v, causal=True, window=12)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=1e-5)


def test_ulysses_rejects_indivisible_heads(eight_devices):
    mesh = get_mesh(8, axis_name="seq")
    q, k, v = rand_qkv(jax.random.PRNGKey(4), h=4)
    with pytest.raises(ValueError, match="num_heads"):
        ulysses_self_attention(q, k, v, mesh, axis_name="seq", causal=True)


# -- integrated LM ------------------------------------------------------------

def mesh_of(shape):
    n = int(np.prod(shape))
    devs = np.array(jax.devices()[:n]).reshape(shape)
    return Mesh(devs, ("data", "seq", "model"))


def run_steps(lm, steps=3, lr=1e-2):
    import optax
    params = lm.init(jax.random.PRNGKey(7))
    opt_state, step = lm.compile_train_step(optax.adam(lr), params)
    rng = np.random.default_rng(0)
    toks = rng.integers(0, lm.vocab_size, (4, lm.seq_len)).astype(np.int32)
    labels = (toks + 1) % lm.vocab_size
    sh = lm.batch_sharding()
    toks, labels = jax.device_put(toks, sh), jax.device_put(labels, sh)
    losses = []
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, toks, labels)
        losses.append(float(loss))
    return losses


def make_lm(mesh, **kw):
    cfg = dict(vocab_size=32, seq_len=16, d_model=16, num_heads=8,
               num_layers=2, mlp_dim=32, mesh=mesh,
               compute_dtype=jnp.float32)
    cfg.update(kw)
    return ParallelTransformerLM(**cfg)


def test_ulysses_lm_matches_ring_and_single(eight_devices):
    """The dp×sp×tp LM under sp_impl='ulysses' == the same model under
    sp_impl='ring' == the 1×1×1 mesh: the SP schedule is an execution
    detail, not a numerics change."""
    l_u = run_steps(make_lm(mesh_of((1, 4, 2)), sp_impl="ulysses"))
    l_r = run_steps(make_lm(mesh_of((1, 4, 2)), sp_impl="ring"))
    l_1 = run_steps(make_lm(mesh_of((1, 1, 1))))
    np.testing.assert_allclose(l_u, l_r, rtol=2e-4)
    np.testing.assert_allclose(l_u, l_1, rtol=2e-4)


def test_ulysses_lm_rope_gqa_window(eight_devices):
    """Composed long-context stack (RoPE + GQA + sliding window) under
    ulysses == single device."""
    kw = dict(num_heads=8, num_kv_heads=2, attention_window=8,
              positional="rope", d_model=32)
    l_u = run_steps(make_lm(mesh_of((1, 4, 2)), sp_impl="ulysses", **kw))
    l_1 = run_steps(make_lm(mesh_of((1, 1, 1)), **kw))
    np.testing.assert_allclose(l_u, l_1, rtol=2e-4)


def test_ulysses_lm_rejects_bad_head_split(eight_devices):
    with pytest.raises(ValueError, match="ulysses"):
        make_lm(mesh_of((1, 4, 2)), sp_impl="ulysses", num_heads=4)
    with pytest.raises(ValueError, match="sp_impl"):
        make_lm(mesh_of((1, 4, 2)), sp_impl="nope")
