"""Keras → native conversion (core/keras_adapter.py): a converted model must
compute the same function as the original Keras model, starting from the
identical weights (reference parity: trainers accept a real ``keras.Model``
— ``distkeras/trainers.py :: Trainer.__init__(keras_model=...)``).
"""

import numpy as np
import pytest

keras = pytest.importorskip("keras")

import jax

from distkeras_tpu.core.keras_adapter import convert_keras_model, keras_weights
from distkeras_tpu.utils import serialize_keras_model, deserialize_keras_model
from distkeras_tpu import SingleTrainer, Dataset, OneHotTransformer


def make_keras_mlp():
    m = keras.Sequential([
        keras.layers.Input((16,)),
        keras.layers.Dense(32, activation="relu"),
        keras.layers.Dense(4, activation="softmax"),
    ])
    return m


def convert_with_weights(km):
    native = convert_keras_model(km)
    params = native.init(jax.random.PRNGKey(0), native.input_shape)
    return native, native.set_weights(params, keras_weights(km))


def test_mlp_forward_matches_keras():
    km = make_keras_mlp()
    x = np.random.default_rng(0).standard_normal((8, 16)).astype(np.float32)
    want = np.asarray(km(x))
    native, params = convert_with_weights(km)
    native.compute_dtype = "float32"
    got = np.asarray(native.apply(params, x))
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_convnet_forward_matches_keras():
    km = keras.Sequential([
        keras.layers.Input((8, 8, 3)),
        keras.layers.Conv2D(4, 3, padding="same", activation="relu"),
        keras.layers.MaxPooling2D(2),
        keras.layers.Flatten(),
        keras.layers.Dense(5, activation="softmax"),
    ])
    x = np.random.default_rng(1).standard_normal((4, 8, 8, 3)).astype(
        np.float32)
    want = np.asarray(km(x))
    native, params = convert_with_weights(km)
    native.compute_dtype = "float32"
    got = np.asarray(native.apply(params, x))
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_trainer_accepts_keras_model():
    """The reference entry point: hand a keras.Model straight to a Trainer."""
    km = make_keras_mlp()
    rng = np.random.default_rng(2)
    protos = rng.uniform(-1, 1, (4, 16))
    labels = rng.integers(0, 4, 512)
    x = (protos[labels] + 0.2 * rng.standard_normal((512, 16))).astype(
        np.float32)
    ds = OneHotTransformer(4).transform(
        Dataset({"features": x, "label": labels.astype(np.int64)}))
    t = SingleTrainer(km, batch_size=32, num_epoch=3,
                      label_col="label_encoded", worker_optimizer="adam",
                      learning_rate=5e-3)
    fitted = t.train(ds)
    preds = fitted.predict(x[:128])
    acc = float(np.mean(np.argmax(preds, -1) == labels[:128]))
    assert acc > 0.8, acc


def test_serialize_keras_model_parity():
    """utils.serialize_keras_model accepts a live keras model (reference:
    utils.py same-named function pickles json+weights)."""
    km = make_keras_mlp()
    blob = serialize_keras_model(km)
    fm = deserialize_keras_model(blob)
    x = np.random.default_rng(3).standard_normal((4, 16)).astype(np.float32)
    fm.model.compute_dtype = "float32"
    np.testing.assert_allclose(fm.predict(x), np.asarray(km(x)), atol=1e-5)


def test_unsupported_layer_raises():
    km = keras.Sequential([
        keras.layers.Input((4, 16)),
        keras.layers.LSTM(8),
    ])
    with pytest.raises(ValueError, match="Unsupported Keras layer"):
        convert_keras_model(km)
