"""Keras → native conversion (core/keras_adapter.py): a converted model must
compute the same function as the original Keras model, starting from the
identical weights (reference parity: trainers accept a real ``keras.Model``
— ``distkeras/trainers.py :: Trainer.__init__(keras_model=...)``).
"""

import numpy as np
import pytest

keras = pytest.importorskip("keras")

import jax

from distkeras_tpu.core.keras_adapter import convert_keras_model, keras_weights
from distkeras_tpu.utils import serialize_keras_model, deserialize_keras_model
from distkeras_tpu import SingleTrainer, Dataset, OneHotTransformer


def make_keras_mlp():
    m = keras.Sequential([
        keras.layers.Input((16,)),
        keras.layers.Dense(32, activation="relu"),
        keras.layers.Dense(4, activation="softmax"),
    ])
    return m


def convert_with_weights(km):
    native = convert_keras_model(km)
    params = native.init(jax.random.PRNGKey(0), native.input_shape)
    return native, native.set_weights(params, keras_weights(km))


def test_mlp_forward_matches_keras():
    km = make_keras_mlp()
    x = np.random.default_rng(0).standard_normal((8, 16)).astype(np.float32)
    want = np.asarray(km(x))
    native, params = convert_with_weights(km)
    native.compute_dtype = "float32"
    got = np.asarray(native.apply(params, x))
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_convnet_forward_matches_keras():
    km = keras.Sequential([
        keras.layers.Input((8, 8, 3)),
        keras.layers.Conv2D(4, 3, padding="same", activation="relu"),
        keras.layers.MaxPooling2D(2),
        keras.layers.Flatten(),
        keras.layers.Dense(5, activation="softmax"),
    ])
    x = np.random.default_rng(1).standard_normal((4, 8, 8, 3)).astype(
        np.float32)
    want = np.asarray(km(x))
    native, params = convert_with_weights(km)
    native.compute_dtype = "float32"
    got = np.asarray(native.apply(params, x))
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_trainer_accepts_keras_model():
    """The reference entry point: hand a keras.Model straight to a Trainer."""
    km = make_keras_mlp()
    rng = np.random.default_rng(2)
    protos = rng.uniform(-1, 1, (4, 16))
    labels = rng.integers(0, 4, 512)
    x = (protos[labels] + 0.2 * rng.standard_normal((512, 16))).astype(
        np.float32)
    ds = OneHotTransformer(4).transform(
        Dataset({"features": x, "label": labels.astype(np.int64)}))
    t = SingleTrainer(km, batch_size=32, num_epoch=3,
                      label_col="label_encoded", worker_optimizer="adam",
                      learning_rate=5e-3)
    fitted = t.train(ds)
    preds = fitted.predict(x[:128])
    acc = float(np.mean(np.argmax(preds, -1) == labels[:128]))
    assert acc > 0.8, acc


def test_serialize_keras_model_parity():
    """utils.serialize_keras_model accepts a live keras model (reference:
    utils.py same-named function pickles json+weights)."""
    km = make_keras_mlp()
    blob = serialize_keras_model(km)
    fm = deserialize_keras_model(blob)
    x = np.random.default_rng(3).standard_normal((4, 16)).astype(np.float32)
    fm.model.compute_dtype = "float32"
    np.testing.assert_allclose(fm.predict(x), np.asarray(km(x)), atol=1e-5)


def test_unsupported_layer_raises():
    km = keras.Sequential([
        keras.layers.Input((4, 16)),
        keras.layers.LSTM(8),
    ])
    with pytest.raises(ValueError, match="Unsupported Keras layer"):
        convert_keras_model(km)


def make_functional_convnet():
    """The reference's own MNIST-ConvNet idiom was a FUNCTIONAL model
    (SURVEY.md §2.1 rows 1/12) — a linear chain built with the functional
    API, not keras.Sequential."""
    inp = keras.layers.Input((8, 8, 1))
    h = keras.layers.Conv2D(4, 3, padding="same", activation="relu")(inp)
    h = keras.layers.MaxPooling2D(2)(h)
    h = keras.layers.Conv2D(8, 3, padding="valid", activation="relu")(h)
    h = keras.layers.Flatten()(h)
    h = keras.layers.Dense(16, activation="relu")(h)
    h = keras.layers.Dropout(0.1)(h)
    out = keras.layers.Dense(4, activation="softmax")(h)
    return keras.Model(inp, out)


def test_functional_convnet_forward_matches_keras():
    km = make_functional_convnet()
    x = np.random.default_rng(4).standard_normal((4, 8, 8, 1)).astype(
        np.float32)
    want = np.asarray(km(x, training=False))
    native, params = convert_with_weights(km)
    native.compute_dtype = "float32"
    got = np.asarray(native.apply(params, x))
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_functional_layernorm_matches_keras():
    inp = keras.layers.Input((16,))
    h = keras.layers.Dense(8)(inp)
    h = keras.layers.LayerNormalization(epsilon=1e-5)(h)
    out = keras.layers.Dense(4)(h)
    km = keras.Model(inp, out)
    km.layers[2].set_weights([  # non-trivial gamma/beta
        np.linspace(0.5, 1.5, 8).astype(np.float32),
        np.linspace(-0.2, 0.2, 8).astype(np.float32)])
    x = np.random.default_rng(5).standard_normal((6, 16)).astype(np.float32)
    want = np.asarray(km(x, training=False))
    native, params = convert_with_weights(km)
    native.compute_dtype = "float32"
    np.testing.assert_allclose(np.asarray(native.apply(params, x)), want,
                               atol=1e-5)


def test_functional_trains_and_matches_sequential_twin():
    """A functional model and its layer-identical Sequential twin convert
    to the same native spec; transplant the SAME keras weights into both
    and a short deterministic training run stays identical."""
    from distkeras_tpu.core.keras_adapter import keras_weights

    km_f = make_functional_convnet()
    km_s = keras.Sequential([
        keras.layers.Input((8, 8, 1)),
        keras.layers.Conv2D(4, 3, padding="same", activation="relu"),
        keras.layers.MaxPooling2D(2),
        keras.layers.Conv2D(8, 3, padding="valid", activation="relu"),
        keras.layers.Flatten(),
        keras.layers.Dense(16, activation="relu"),
        keras.layers.Dropout(0.1),
        keras.layers.Dense(4, activation="softmax"),
    ])
    km_s.set_weights(km_f.get_weights())  # same starting point

    rng = np.random.default_rng(6)
    x = rng.standard_normal((128, 8, 8, 1)).astype(np.float32)
    labels = rng.integers(0, 4, 128)
    y = np.eye(4, dtype=np.float32)[labels]

    def fit(km):
        t = SingleTrainer(km, batch_size=32, num_epoch=3,
                          worker_optimizer="sgd", learning_rate=0.1, seed=0)
        f = t.train(Dataset({"features": x, "label": y}))
        return t, f

    tf_, ff = fit(km_f)
    ts_, fs = fit(km_s)
    np.testing.assert_allclose(tf_.history, ts_.history, rtol=1e-6)
    np.testing.assert_allclose(ff.predict(x[:16]), fs.predict(x[:16]),
                               rtol=1e-5, atol=1e-6)


def test_nonlinear_graphs_rejected():
    # skip connection (merge)
    inp = keras.layers.Input((16,))
    h = keras.layers.Dense(16, activation="relu")(inp)
    out = keras.layers.Add()([inp, h])
    with pytest.raises(ValueError, match="merge"):
        convert_keras_model(keras.Model(inp, out))
    # shared layer (called twice)
    inp2 = keras.layers.Input((16,))
    shared = keras.layers.Dense(16)
    out2 = shared(shared(inp2))
    with pytest.raises(ValueError, match="called 2 times"):
        convert_keras_model(keras.Model(inp2, out2))
    # multi-output
    inp3 = keras.layers.Input((16,))
    a = keras.layers.Dense(4)(inp3)
    b = keras.layers.Dense(2)(inp3)
    with pytest.raises(ValueError, match="outputs"):
        convert_keras_model(keras.Model(inp3, [a, b]))
