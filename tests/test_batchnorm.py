"""BatchNormalization running-stats semantics across all three engines.

Round-2 VERDICT weak #2: stats were declared "updated outside apply by the
train step" but nothing ever wrote them — eval-mode BN normalized with
(mean=0, var=1) forever.  These tests pin the contract: training updates the
running stats toward the true input moments in every engine (single, SPMD,
host_ps), eval-mode inference uses them, and the Keras adapter round-trips
them.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu import (ADAG, BatchNormalization, Dense, Sequential,
                           SingleTrainer)
from distkeras_tpu.core import train as train_lib

from test_trainers import NUM_CLASSES, eval_accuracy, make_dataset

# input features with decidedly non-(0,1) moments so the default init stats
# are visibly wrong and convergence to the true moments is measurable
MEAN, STD = 5.0, 2.0


def make_bn_dataset(n=2048, d=16, seed=0):
    ds = make_dataset(n=n, d=d, seed=seed)
    x = np.asarray(ds["features"]) * STD + MEAN
    return ds.with_column("features", x.astype(np.float32))


def make_bn_model(d=16):
    return Sequential([BatchNormalization(momentum=0.9),
                       Dense(32, activation="relu"),
                       Dense(NUM_CLASSES, activation="softmax")],
                      input_shape=(d,), compute_dtype="float32")


def bn_stats(params):
    return params[0]["stats"]


def test_train_step_updates_running_stats():
    """Direct engine check: the core train step EMAs stats toward the batch
    moments (stats are aux, merged after the optimizer update)."""
    model = make_bn_model()
    state, tx = train_lib.init_state(
        model, jax.random.PRNGKey(0), (16,), "sgd", 0.05)
    step = jax.jit(train_lib.make_train_step(model, "categorical_crossentropy",
                                             tx))
    rng = np.random.default_rng(0)
    x = (MEAN + STD * rng.standard_normal((64, 16))).astype(np.float32)
    y = np.eye(NUM_CLASSES, dtype=np.float32)[rng.integers(0, NUM_CLASSES, 64)]
    for i in range(200):
        state, _ = step(state, (x, y), jax.random.PRNGKey(i))
    stats = bn_stats(state.params)
    np.testing.assert_allclose(stats["mean"], x.mean(axis=0), atol=0.15)
    np.testing.assert_allclose(stats["var"], x.var(axis=0), rtol=0.15)


def test_single_trainer_bn_eval_matches_train(eight_devices):
    """SingleTrainer path: after training, eval-mode (running-stats) accuracy
    must match train-mode (batch-stats) accuracy — the round-2 bug made
    eval-mode silently mis-predict."""
    ds = make_bn_dataset()
    t = SingleTrainer(make_bn_model(), batch_size=32, num_epoch=3,
                      label_col="label_encoded", worker_optimizer="adam",
                      learning_rate=1e-3)
    fitted = t.train(ds)
    stats = bn_stats(fitted.params)
    x = np.asarray(ds["features"])
    np.testing.assert_allclose(stats["mean"], x.mean(axis=0), atol=0.3)
    np.testing.assert_allclose(stats["var"], x.var(axis=0), rtol=0.3)
    # eval-mode inference (ModelPredictor uses train=False) works
    assert eval_accuracy(fitted, ds) > 0.9


def test_adag_spmd_bn_stats_synced_and_deterministic(eight_devices):
    """SPMD path: center stats converge to the data moments, are identical
    across two runs (bit-determinism holds with the stats psum in the round),
    and eval-mode accuracy is healthy."""

    def run():
        t = ADAG(make_bn_model(), num_workers=8, batch_size=16, num_epoch=4,
                 communication_window=4, label_col="label_encoded",
                 worker_optimizer="adam", learning_rate=1e-3, seed=7)
        return t.train(make_bn_dataset(seed=3), shuffle=True)

    f1, f2 = run(), run()
    stats = bn_stats(f1.params)
    x = np.asarray(make_bn_dataset(seed=3)["features"])
    np.testing.assert_allclose(stats["mean"], x.mean(axis=0), atol=0.3)
    np.testing.assert_allclose(stats["var"], x.var(axis=0), rtol=0.3)
    for a, b in zip(f1.get_weights(), f2.get_weights()):
        np.testing.assert_array_equal(a, b)
    assert eval_accuracy(f1, make_bn_dataset(seed=3)) > 0.9


def test_host_ps_bn_stats_update(eight_devices):
    """host_ps (async socket) path: worker-side EMA'd stats flow through the
    delta commits into the center; eval-mode inference works."""
    ds = make_bn_dataset(n=1024)
    t = ADAG(make_bn_model(), num_workers=2, batch_size=32, num_epoch=6,
             communication_window=4, label_col="label_encoded",
             worker_optimizer="adam", learning_rate=3e-3,
             execution="host_ps")
    fitted = t.train(ds)
    stats = bn_stats(fitted.params)
    x = np.asarray(ds["features"])
    # async hogwild stats: looser tolerance, but nowhere near the (0, 1) init
    np.testing.assert_allclose(stats["mean"], x.mean(axis=0), atol=1.0)
    np.testing.assert_allclose(stats["var"], x.var(axis=0), rtol=0.5)
    assert eval_accuracy(fitted, ds) > 0.9


def test_keras_adapter_bn_roundtrip_eval_parity():
    """A converted Keras BN model must predict identically (eval mode) —
    running stats included in the weight transfer."""
    keras = pytest.importorskip("keras")
    from distkeras_tpu.core.keras_adapter import (convert_keras_model,
                                                  keras_weights)

    km = keras.Sequential([
        keras.layers.Input((8,)),
        keras.layers.BatchNormalization(momentum=0.9),
        keras.layers.Dense(4, activation="softmax"),
    ])
    rng = np.random.default_rng(1)
    x = (3.0 + 2.0 * rng.standard_normal((256, 8))).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 256)]
    km.compile(optimizer="adam", loss="categorical_crossentropy")
    km.fit(x, y, epochs=2, batch_size=32, verbose=0)

    model = convert_keras_model(km)
    params = model.init(jax.random.PRNGKey(0), model.input_shape)
    params = model.set_weights(params, keras_weights(km))
    ours = model.apply(params, jnp.asarray(x), train=False)
    theirs = km.predict(x, verbose=0)
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=5e-3)
