"""Weight-only int8 quantization (core/quant.py).

The transform must (a) round-trip weights to ~1/127 per-channel relative
error, (b) flow through the UNMODIFIED forward/decode code via the pytree
leaf's ``astype``, (c) preserve task behavior (argmax predictions, greedy
decode) on trained models, and (d) actually shrink the weight bytes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.core.layers import Dense
from distkeras_tpu.core.model import FittedModel, Sequential
from distkeras_tpu.core.quant import (QuantizedTensor, dequantize_params,
                                      quantize_params, quantize_tensor,
                                      quantized_bytes)


def test_quantize_tensor_roundtrip_error():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(64, 32)) * np.exp(
        rng.normal(size=(1, 32))))  # per-channel magnitude spread
    qt = quantize_tensor(w)
    assert qt.q.dtype == jnp.int8 and qt.scale.shape == (1, 32)
    back = qt.astype(jnp.float32)
    # symmetric per-channel int8: error bounded by scale/2 per element
    err = np.abs(np.asarray(back - w))
    bound = np.asarray(qt.scale) / 2 + 1e-8
    assert (err <= bound).all()


def test_zero_channel_is_stable():
    w = jnp.zeros((8, 4))
    back = quantize_tensor(w).astype(jnp.float32)
    assert np.asarray(back).sum() == 0.0 and np.isfinite(
        np.asarray(back)).all()


def test_quantize_params_selects_kernels_only():
    model = Sequential([Dense(16, activation="relu"), Dense(4)],
                       input_shape=(8,), compute_dtype="float32")
    params = model.init(jax.random.PRNGKey(0), (8,))
    qp = quantize_params(params)
    assert isinstance(qp[0]["kernel"], QuantizedTensor)
    assert isinstance(qp[1]["kernel"], QuantizedTensor)
    # biases untouched
    assert not isinstance(qp[0]["bias"], QuantizedTensor)
    dq = dequantize_params(qp)
    assert not any(isinstance(l, QuantizedTensor)
                   for l in jax.tree_util.tree_leaves(
                       dq, is_leaf=lambda x: isinstance(x, QuantizedTensor)))


def test_mlp_predictions_survive_quantization():
    """A trained-ish MLP keeps its argmax predictions and close logits
    through the unmodified jitted forward."""
    rng = np.random.default_rng(1)
    model = Sequential([Dense(32, activation="relu"), Dense(10)],
                       input_shape=(16,), compute_dtype="float32")
    params = model.init(jax.random.PRNGKey(1), (16,))
    x = rng.normal(size=(64, 16)).astype(np.float32)
    full = model.predict(params, x)
    quant = model.predict(quantize_params(params), x)
    np.testing.assert_allclose(quant, full, rtol=0.1, atol=0.05)
    agree = (full.argmax(-1) == quant.argmax(-1)).mean()
    assert agree >= 0.95, agree


def test_transformer_generate_matches_unquantized():
    """Greedy decode through the KV-cache path on a trained x+1 LM is
    IDENTICAL after quantization (the margin on a trained task dwarfs the
    int8 rounding)."""
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.models.zoo import transformer_lm
    from distkeras_tpu.trainers import SingleTrainer

    model = transformer_lm(vocab_size=16, seq_len=12, d_model=32,
                           num_heads=4, num_layers=2, mlp_dim=64,
                           compute_dtype="float32")
    rng = np.random.default_rng(2)
    toks = rng.integers(0, 16, (256, 12)).astype(np.int32)
    labels = (toks + 1) % 16
    t = SingleTrainer(model, batch_size=32, num_epoch=25,
                      loss="sparse_categorical_crossentropy_from_logits",
                      worker_optimizer="adam", learning_rate=3e-3)
    fitted = t.train(Dataset({"features": toks, "label": labels}))

    q_fitted = fitted.quantize()
    prompt = np.array([[3, 4, 5, 6]], dtype=np.int32)
    full = np.asarray(fitted.generate(prompt, 8))
    quant = np.asarray(q_fitted.generate(prompt, 8))
    # the trained rule survives int8 and both decodes agree exactly
    want = (prompt[:, -1:] + 1 + np.arange(8)) % 16
    np.testing.assert_array_equal(quant[:, 4:], want)
    np.testing.assert_array_equal(full, quant)


def test_quantized_bytes_shrink():
    model = Sequential([Dense(256), Dense(256), Dense(10)],
                       input_shape=(128,), compute_dtype="float32")
    params = model.init(jax.random.PRNGKey(4), (128,))
    full = quantized_bytes(params)
    quant = quantized_bytes(quantize_params(params))
    # f32 kernels dominate: int8 + per-channel scales must be < 30% of full
    assert quant < 0.3 * full, (quant, full)


def test_serialize_quantized_refuses():
    model = Sequential([Dense(4)], input_shape=(8,),
                       compute_dtype="float32")
    params = model.init(jax.random.PRNGKey(5), (8,))
    fm = FittedModel(model, quantize_params(params))
    with pytest.raises(ValueError, match="quantize"):
        fm.serialize()


def test_quantize_idempotent_and_count_params():
    model = Sequential([Dense(16), Dense(4)], input_shape=(8,),
                       compute_dtype="float32")
    params = model.init(jax.random.PRNGKey(6), (8,))
    qp = quantize_params(params)
    qq = quantize_params(qp)  # no-op, not a crash
    assert isinstance(qq[0]["kernel"], QuantizedTensor)
    # logical param count unchanged by quantization
    assert model.count_params(qp) == model.count_params(params)


# ---------------------------------------------------------------------------
# KV-cache quantization (the serving engine's int8 slot pool, PR 11)
# ---------------------------------------------------------------------------

def test_quantize_kv_roundtrip_and_zero_preservation():
    from distkeras_tpu.core.quant import dequantize_kv, quantize_kv

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((2, 5, 3, 8)), jnp.float32)
    q, scale = quantize_kv(x)
    assert q.dtype == jnp.int8 and scale.shape == (2, 5, 3)
    back = np.asarray(dequantize_kv(q, scale, jnp.float32))
    # per-entry symmetric int8: relative error bounded by scale/2 per dim
    err = np.abs(back - np.asarray(x))
    amax = np.abs(np.asarray(x)).max(axis=-1, keepdims=True)
    assert (err <= amax / 127.0 * 0.51 + 1e-7).all()
    # never-written (all-zero) entries dequantize to EXACT zeros with
    # scale 0 — the empty-slot invariant the serving pool leans on
    z = jnp.zeros((1, 4, 2, 8), jnp.float32)
    qz, sz = quantize_kv(z)
    assert (np.asarray(sz) == 0).all()
    assert (np.asarray(dequantize_kv(qz, sz, jnp.float32)) == 0).all()


def test_init_cache_kv_dtype_and_bytes():
    from distkeras_tpu.core.decode import init_cache
    from distkeras_tpu.core.quant import kv_cache_bytes
    from distkeras_tpu.models import transformer_lm

    model = transformer_lm(vocab_size=16, seq_len=32, d_model=16,
                           num_heads=2, num_layers=2, mlp_dim=32,
                           compute_dtype="float32")
    model.init(jax.random.PRNGKey(0), (32,))
    fp = init_cache(model, 4, 32)
    q8 = init_cache(model, 4, 32, kv_dtype="int8")
    assert set(q8[2]) == {"k", "v", "ks", "vs"}
    assert q8[2]["k"].dtype == jnp.int8
    # >= 1.5x slots at fixed bytes — here f32 pools give ~2.7x
    assert kv_cache_bytes(fp) >= 1.5 * kv_cache_bytes(q8)
    with pytest.raises(ValueError, match="kv_dtype"):
        init_cache(model, 1, 8, kv_dtype="int4")


def test_init_cache_ring_slack_widens_ring():
    from distkeras_tpu.core.decode import init_cache
    from distkeras_tpu.models import transformer_lm

    model = transformer_lm(vocab_size=16, seq_len=32, d_model=16,
                           num_heads=2, num_layers=2, mlp_dim=32,
                           compute_dtype="float32", attention_window=6)
    model.init(jax.random.PRNGKey(0), (32,))
    ring = init_cache(model, 2, 24, rolling=True)
    slack = init_cache(model, 2, 24, rolling=True, ring_slack=4)
    assert ring[2]["k"].shape[1] == 6
    assert slack[2]["k"].shape[1] == 10
