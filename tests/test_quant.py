"""Weight-only int8 quantization (core/quant.py).

The transform must (a) round-trip weights to ~1/127 per-channel relative
error, (b) flow through the UNMODIFIED forward/decode code via the pytree
leaf's ``astype``, (c) preserve task behavior (argmax predictions, greedy
decode) on trained models, and (d) actually shrink the weight bytes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.core.layers import Dense
from distkeras_tpu.core.model import FittedModel, Sequential
from distkeras_tpu.core.quant import (QuantizedTensor, dequantize_params,
                                      quantize_params, quantize_tensor,
                                      quantized_bytes)


def test_quantize_tensor_roundtrip_error():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(64, 32)) * np.exp(
        rng.normal(size=(1, 32))))  # per-channel magnitude spread
    qt = quantize_tensor(w)
    assert qt.q.dtype == jnp.int8 and qt.scale.shape == (1, 32)
    back = qt.astype(jnp.float32)
    # symmetric per-channel int8: error bounded by scale/2 per element
    err = np.abs(np.asarray(back - w))
    bound = np.asarray(qt.scale) / 2 + 1e-8
    assert (err <= bound).all()


def test_zero_channel_is_stable():
    w = jnp.zeros((8, 4))
    back = quantize_tensor(w).astype(jnp.float32)
    assert np.asarray(back).sum() == 0.0 and np.isfinite(
        np.asarray(back)).all()


def test_quantize_params_selects_kernels_only():
    model = Sequential([Dense(16, activation="relu"), Dense(4)],
                       input_shape=(8,), compute_dtype="float32")
    params = model.init(jax.random.PRNGKey(0), (8,))
    qp = quantize_params(params)
    assert isinstance(qp[0]["kernel"], QuantizedTensor)
    assert isinstance(qp[1]["kernel"], QuantizedTensor)
    # biases untouched
    assert not isinstance(qp[0]["bias"], QuantizedTensor)
    dq = dequantize_params(qp)
    assert not any(isinstance(l, QuantizedTensor)
                   for l in jax.tree_util.tree_leaves(
                       dq, is_leaf=lambda x: isinstance(x, QuantizedTensor)))


def test_mlp_predictions_survive_quantization():
    """A trained-ish MLP keeps its argmax predictions and close logits
    through the unmodified jitted forward."""
    rng = np.random.default_rng(1)
    model = Sequential([Dense(32, activation="relu"), Dense(10)],
                       input_shape=(16,), compute_dtype="float32")
    params = model.init(jax.random.PRNGKey(1), (16,))
    x = rng.normal(size=(64, 16)).astype(np.float32)
    full = model.predict(params, x)
    quant = model.predict(quantize_params(params), x)
    np.testing.assert_allclose(quant, full, rtol=0.1, atol=0.05)
    agree = (full.argmax(-1) == quant.argmax(-1)).mean()
    assert agree >= 0.95, agree


def test_transformer_generate_matches_unquantized():
    """Greedy decode through the KV-cache path on a trained x+1 LM is
    IDENTICAL after quantization (the margin on a trained task dwarfs the
    int8 rounding)."""
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.models.zoo import transformer_lm
    from distkeras_tpu.trainers import SingleTrainer

    model = transformer_lm(vocab_size=16, seq_len=12, d_model=32,
                           num_heads=4, num_layers=2, mlp_dim=64,
                           compute_dtype="float32")
    rng = np.random.default_rng(2)
    toks = rng.integers(0, 16, (256, 12)).astype(np.int32)
    labels = (toks + 1) % 16
    t = SingleTrainer(model, batch_size=32, num_epoch=25,
                      loss="sparse_categorical_crossentropy_from_logits",
                      worker_optimizer="adam", learning_rate=3e-3)
    fitted = t.train(Dataset({"features": toks, "label": labels}))

    q_fitted = fitted.quantize()
    prompt = np.array([[3, 4, 5, 6]], dtype=np.int32)
    full = np.asarray(fitted.generate(prompt, 8))
    quant = np.asarray(q_fitted.generate(prompt, 8))
    # the trained rule survives int8 and both decodes agree exactly
    want = (prompt[:, -1:] + 1 + np.arange(8)) % 16
    np.testing.assert_array_equal(quant[:, 4:], want)
    np.testing.assert_array_equal(full, quant)


def test_quantized_bytes_shrink():
    model = Sequential([Dense(256), Dense(256), Dense(10)],
                       input_shape=(128,), compute_dtype="float32")
    params = model.init(jax.random.PRNGKey(4), (128,))
    full = quantized_bytes(params)
    quant = quantized_bytes(quantize_params(params))
    # f32 kernels dominate: int8 + per-channel scales must be < 30% of full
    assert quant < 0.3 * full, (quant, full)


def test_serialize_quantized_refuses():
    model = Sequential([Dense(4)], input_shape=(8,),
                       compute_dtype="float32")
    params = model.init(jax.random.PRNGKey(5), (8,))
    fm = FittedModel(model, quantize_params(params))
    with pytest.raises(ValueError, match="quantize"):
        fm.serialize()


def test_quantize_idempotent_and_count_params():
    model = Sequential([Dense(16), Dense(4)], input_shape=(8,),
                       compute_dtype="float32")
    params = model.init(jax.random.PRNGKey(6), (8,))
    qp = quantize_params(params)
    qq = quantize_params(qp)  # no-op, not a crash
    assert isinstance(qq[0]["kernel"], QuantizedTensor)
    # logical param count unchanged by quantization
    assert model.count_params(qp) == model.count_params(params)
