"""dklint contract tests: every rule family must (a) catch its planted
defect and (b) stay silent on the clean twin, plus the baseline
round-trip and the tier-1 gate that runs the analyzer over the real
package.  Fixtures are source strings analyzed from tmp_path — the
analyzer never imports checked code, so neither do these tests."""

import textwrap
from pathlib import Path

import pytest

from distkeras_tpu.analysis import (LockOrderAuditor, LockOrderViolation,
                                    OrderedLock, audit_locks,
                                    default_baseline_path, load_baseline,
                                    render_baseline, run_analysis)

pytestmark = pytest.mark.analysis


def analyze(tmp_path, files):
    for name, src in files.items():
        (tmp_path / name).write_text(textwrap.dedent(src))
    return run_analysis([str(tmp_path)], baseline=None)


def idents(report, rule=None):
    return [f.ident for f in report.unbaselined
            if rule is None or f.rule == rule]


# ---------------------------------------------------------------------------
# rule family 1: lock discipline
# ---------------------------------------------------------------------------

RACY = """
    import threading

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self._count = 0
            self._thread = None

        def start(self):
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

        def _loop(self):
            with self._lock:
                self._count += 1

        def snapshot(self):
            return self._count
"""

CLEAN_LOCKED = """
    import threading

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self._count = 0
            self._thread = None

        def start(self):
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

        def _loop(self):
            with self._lock:
                self._count += 1

        def snapshot(self):
            with self._lock:
                return self._count
"""


def test_unlocked_attr_in_threaded_class_is_flagged(tmp_path):
    report = analyze(tmp_path, {"mod.py": RACY})
    assert "lock-discipline:mod.py:Worker._count" in idents(report)


def test_consistently_locked_attr_is_clean(tmp_path):
    report = analyze(tmp_path, {"mod.py": CLEAN_LOCKED})
    assert idents(report, "lock-discipline") == []


def test_guards_annotation_catches_unlocked_access(tmp_path):
    src = """
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()  # guards: _count
                self._count = 0

            def bump(self):
                self._count += 1
    """
    report = analyze(tmp_path, {"mod.py": src})
    assert any(i.startswith("lock-guards:mod.py:Worker._count")
               for i in idents(report, "lock-guards")), report.unbaselined


def test_guards_annotation_flags_stale_attr(tmp_path):
    src = """
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()  # guards: _ghost
                self._count = 0
    """
    report = analyze(tmp_path, {"mod.py": src})
    assert any("_ghost" in i for i in idents(report, "lock-guards"))


def test_guards_annotation_clean_when_honored(tmp_path):
    src = """
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()  # guards: _count
                self._count = 0

            def bump(self):
                with self._lock:
                    self._count += 1
    """
    report = analyze(tmp_path, {"mod.py": src})
    assert idents(report, "lock-guards") == []


# ---------------------------------------------------------------------------
# rule family 2: lock-order cycles
# ---------------------------------------------------------------------------

TWO_LOCK_CYCLE = """
    import threading

    class AB:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def fwd(self):
            with self._a:
                with self._b:
                    pass

        def rev(self):
            with self._b:
                with self._a:
                    pass
"""

TWO_LOCK_CLEAN = """
    import threading

    class AB:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def fwd(self):
            with self._a:
                with self._b:
                    pass

        def also_fwd(self):
            with self._a:
                with self._b:
                    pass
"""


def test_two_lock_cycle_is_flagged(tmp_path):
    report = analyze(tmp_path, {"mod.py": TWO_LOCK_CYCLE})
    assert idents(report, "lock-order"), report.unbaselined


def test_consistent_lock_order_is_clean(tmp_path):
    report = analyze(tmp_path, {"mod.py": TWO_LOCK_CLEAN})
    assert idents(report, "lock-order") == []


def test_interprocedural_cycle_is_flagged(tmp_path):
    src = """
        import threading

        class AB:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def _take_a(self):
                with self._a:
                    pass

            def fwd(self):
                with self._a:
                    with self._b:
                        pass

            def rev(self):
                with self._b:
                    self._take_a()
    """
    report = analyze(tmp_path, {"mod.py": src})
    assert idents(report, "lock-order"), report.unbaselined


# ---------------------------------------------------------------------------
# rule family 3: JAX tracing / transfer discipline
# ---------------------------------------------------------------------------

def test_item_inside_jitted_fn_is_flagged(tmp_path):
    src = """
        import jax

        @jax.jit
        def f(x):
            return x.item()
    """
    report = analyze(tmp_path, {"mod.py": src})
    assert idents(report, "jax-host-sync"), report.unbaselined


def test_host_sync_reachable_through_helper_is_flagged(tmp_path):
    src = """
        import jax

        def helper(x):
            return float(x)

        @jax.jit
        def f(x):
            return helper(x)
    """
    report = analyze(tmp_path, {"mod.py": src})
    assert idents(report, "jax-host-sync"), report.unbaselined


def test_python_branch_on_tracer_is_flagged(tmp_path):
    src = """
        import jax

        @jax.jit
        def g(x):
            if x > 0:
                return x
            return -x
    """
    report = analyze(tmp_path, {"mod.py": src})
    assert idents(report, "jax-traced-branch"), report.unbaselined


def test_shape_branch_and_unjitted_item_are_clean(tmp_path):
    src = """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def g(x):
            if x.shape[0] > 1:
                return jnp.sum(x)
            return x

        def host_side(x):
            return x.item()
    """
    report = analyze(tmp_path, {"mod.py": src})
    assert idents(report, "jax-host-sync") == []
    assert idents(report, "jax-traced-branch") == []


def test_cache_threading_jit_without_donation_is_flagged(tmp_path):
    src = """
        import jax

        def make_step(model):
            def step(params, cache, tok):
                return tok, cache
            return jax.jit(step)
    """
    report = analyze(tmp_path, {"mod.py": src})
    assert idents(report, "jax-donate"), report.unbaselined


def test_cache_threading_jit_with_donation_is_clean(tmp_path):
    src = """
        import jax

        def make_step(model):
            def step(params, cache, tok):
                return tok, cache
            return jax.jit(step, donate_argnums=(1,))
    """
    report = analyze(tmp_path, {"mod.py": src})
    assert idents(report, "jax-donate") == []


# ---------------------------------------------------------------------------
# rule family 4: wire-protocol exhaustiveness
# ---------------------------------------------------------------------------

def test_same_namespace_opcode_collision_is_flagged(tmp_path):
    src = """
        PS_OP_PULL = b"p"
        PS_OP_PUSH = b"p"
        PS_OP_QUIT = b"q"
    """
    report = analyze(tmp_path, {"mod.py": src})
    assert "wire-opcode:PS_OP_PULL<->PS_OP_PUSH" in idents(report)


def test_distinct_opcodes_are_clean(tmp_path):
    src = """
        PS_OP_PULL = b"p"
        PS_OP_QUIT = b"q"
    """
    report = analyze(tmp_path, {"mod.py": src})
    assert idents(report, "wire-opcode") == []


def test_codec_tag_missing_from_decoder_is_flagged(tmp_path):
    src = """
        def encode(node):
            return {"__sp__": 1, "__nd__": 2, "__tuple__": 3}

        def decode(msg):
            if "__sp__" in msg:
                return msg["__sp__"]
            return msg["__nd__"]
    """
    report = analyze(tmp_path, {"mod.py": src})
    assert any(i.endswith(":__tuple__")
               for i in idents(report, "wire-codec")), report.unbaselined


def test_exhaustive_codec_is_clean(tmp_path):
    src = """
        def encode(node):
            return {"__sp__": 1, "__nd__": 2}

        def decode(msg):
            if "__sp__" in msg:
                return msg["__sp__"]
            return msg["__nd__"]
    """
    report = analyze(tmp_path, {"mod.py": src})
    assert idents(report, "wire-codec") == []


# ---------------------------------------------------------------------------
# baseline round-trip
# ---------------------------------------------------------------------------

def test_baseline_suppresses_and_reports_stale(tmp_path):
    (tmp_path / "mod.py").write_text(textwrap.dedent(RACY))
    first = run_analysis([str(tmp_path)], baseline=None)
    assert first.unbaselined
    entries = {f.ident: "known benign: fixture" for f in first.unbaselined}
    entries["lock-discipline:mod.py:Worker._gone"] = "stale on purpose"
    bl = tmp_path / "baseline.toml"
    bl.write_text(render_baseline(entries))
    second = run_analysis([str(tmp_path)], baseline=str(bl))
    assert second.unbaselined == []
    assert len(second.suppressed) == len(first.unbaselined)
    assert second.stale_baseline == ["lock-discipline:mod.py:Worker._gone"]


def test_baseline_rejects_empty_justification(tmp_path):
    bl = tmp_path / "baseline.toml"
    bl.write_text('[[finding]]\nid = "x:y:z"\njustification = ""\n')
    with pytest.raises(ValueError):
        load_baseline(str(bl))


# ---------------------------------------------------------------------------
# the tier-1 gate: the package itself stays clean
# ---------------------------------------------------------------------------

def test_package_has_zero_unbaselined_findings():
    import distkeras_tpu
    pkg = Path(distkeras_tpu.__file__).parent
    report = run_analysis([str(pkg)], baseline=default_baseline_path())
    assert not report.unbaselined, "unbaselined dklint findings:\n" + \
        "\n".join(f.render() for f in report.unbaselined)
    assert not report.stale_baseline, \
        f"stale baseline entries (delete them): {report.stale_baseline}"
    for f in report.suppressed:
        assert f.ident in load_baseline(default_baseline_path())


# ---------------------------------------------------------------------------
# runtime complement: OrderedLock / audit_locks
# ---------------------------------------------------------------------------

def test_ordered_lock_consistent_order_is_clean():
    aud = LockOrderAuditor()
    a = OrderedLock(name="a", auditor=aud)
    b = OrderedLock(name="b", auditor=aud)
    for _ in range(3):
        with a:
            with b:
                pass
    assert aud.violations == []
    assert "b" in aud.edges().get("a", {})


def test_ordered_lock_inversion_is_reported_not_deadlocked():
    aud = LockOrderAuditor()
    a = OrderedLock(name="a", auditor=aud)
    b = OrderedLock(name="b", auditor=aud)
    with a:
        with b:
            pass
    with b:
        with a:  # inversion: must report, must NOT block
            pass
    assert len(aud.violations) == 1
    assert "inversion" in aud.violations[0]


def test_ordered_lock_raise_on_violation():
    aud = LockOrderAuditor(raise_on_violation=True)
    a = OrderedLock(name="a", auditor=aud)
    b = OrderedLock(name="b", auditor=aud)
    with a:
        with b:
            pass
    with pytest.raises(LockOrderViolation):
        with b:
            with a:
                pass


def test_reentry_of_same_lock_is_not_an_edge():
    aud = LockOrderAuditor()
    a = OrderedLock(name="a", auditor=aud, reentrant=True)
    with a:
        with a:
            pass
    assert aud.violations == []
    assert aud.edges() == {}


def test_audit_locks_patches_and_restores_threading():
    import threading
    real = (threading.Lock, threading.RLock, threading.Condition)
    with audit_locks() as aud:
        lk = threading.Lock()
        assert isinstance(lk, OrderedLock)
        cv = threading.Condition(threading.Lock())
        with cv:
            cv.notify_all()
        with lk:
            pass
    assert (threading.Lock, threading.RLock, threading.Condition) == real
    assert aud.violations == []


def test_audit_locks_catches_cross_object_inversion():
    # NOTE: locks are classed by creation site (lockdep-style), so the two
    # locks must come from distinct lines to be distinct graph nodes
    with audit_locks() as aud:
        import threading
        a = threading.Lock()
        b = threading.Lock()
        with a:
            with b:
                pass
        with b:
            with a:
                pass
    assert len(aud.violations) == 1
