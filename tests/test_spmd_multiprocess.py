"""SPMD engine across OS-process boundaries (round-4 VERDICT missing #3).

The flagship SPMD/ICI path had only ever run single-process on virtual
devices; these tests launch ``scripts/spmd_multiprocess.py`` as 2 real OS
processes × 4 virtual CPU devices via ``job_deployment.Job`` +
``initialize_from_env`` (the deployed-script contract from docs/DEPLOY.md),
train ADAG on the GLOBAL 8-device mesh — the psum crossing the process
boundary — and hold the result against the single-process 8-device run.
The orbax leg saves process-sharded state from 2 processes and resumes it
in 2 fresh processes.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "spmd_multiprocess.py")


def _freeport() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _clean_env() -> dict:
    env = dict(os.environ)
    for k in list(env):
        if k.startswith("DISTKERAS_TPU_"):
            del env[k]  # a stale coordinator would hijack the solo run
    return env


def _launch_pair(args) -> None:
    """2 coordinated OS processes via the deployment layer itself."""
    from distkeras_tpu.job_deployment import Job, LocalJobRunner
    job = Job("spmd-mp", SCRIPT, args=[str(a) for a in args],
              hosts=["127.0.0.1", "127.0.0.1"],
              coordinator_port=_freeport())
    assert job.run(runner=LocalJobRunner()) == 0, job.returncodes


@pytest.mark.slow
def test_spmd_across_two_processes_matches_single_process(tmp_path):
    single, multi = tmp_path / "single.json", tmp_path / "multi.json"
    r = subprocess.run(
        [sys.executable, SCRIPT, "--out", str(single), "--epochs", "2"],
        env=_clean_env(), capture_output=True, text=True, timeout=420)
    assert r.returncode == 0, r.stderr[-2000:]
    _launch_pair(["--out", multi, "--epochs", "2"])

    a, b = json.load(open(single)), json.load(open(multi))
    assert b["num_processes"] == 2
    assert b["local_devices"] == 4 and b["global_devices"] == 8
    assert a["local_devices"] == 8
    # same global program: the loss trace and final center agree across
    # the execution topologies (reduction order differs -> float-eps slack)
    np.testing.assert_allclose(a["history"], b["history"],
                               rtol=0, atol=1e-5)
    assert abs(a["center_l1"] - b["center_l1"]) < 1e-3


@pytest.mark.slow
def test_spmd_multiprocess_orbax_save_and_resume(tmp_path):
    ck = tmp_path / "ckpt"
    straight = tmp_path / "straight.json"
    resumed = tmp_path / "resumed.json"
    _launch_pair(["--out", straight, "--epochs", "4"])
    _launch_pair(["--out", tmp_path / "a.json", "--epochs", "2",
                  "--checkpoint-dir", ck])
    _launch_pair(["--out", resumed, "--epochs", "4",
                  "--checkpoint-dir", ck, "--resume"])

    s, b = json.load(open(straight)), json.load(open(resumed))
    assert b["resumed"]
    # the resumed run trained exactly epochs 2..4: its trace equals the
    # straight run's tail and the centers land together — the orbax
    # process-sharded round trip is lossless
    assert len(s["history"]) == 2 * len(b["history"])
    np.testing.assert_allclose(b["history"],
                               s["history"][len(b["history"]):],
                               rtol=0, atol=1e-5)
    assert abs(b["center_l1"] - s["center_l1"]) < 1e-3
