"""Speculative decoding (core/decode.py :: speculative_generate).

The contract is EXACTNESS: whatever the draft proposes, the output equals
plain greedy ``generate`` on the target model, bit for bit.  A good draft
only changes how many target forwards that takes (asserted via stats).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.core.decode import generate, speculative_generate
from distkeras_tpu.models.zoo import transformer_lm


def make_lm(layers=2, seed=0, vocab=16, seq_len=32):
    model = transformer_lm(vocab_size=vocab, seq_len=seq_len, d_model=32,
                           num_heads=4, num_layers=layers, mlp_dim=64,
                           compute_dtype="float32")
    return model, model.init(jax.random.PRNGKey(seed))


PROMPT = np.array([[3, 4, 5], [9, 2, 7]], np.int32)


def test_exact_with_random_draft():
    """An UNTRAINED draft (near-zero accept rate) still yields exactly the
    greedy output."""
    model, params = make_lm(seed=0)
    draft, dparams = make_lm(layers=1, seed=99)
    want = np.asarray(generate(model, params, PROMPT, 10))
    got, stats = speculative_generate(model, params, draft, dparams,
                                      PROMPT, 10, draft_len=3,
                                      return_stats=True)
    np.testing.assert_array_equal(np.asarray(got), want)
    assert stats["drafted"] > 0


def test_exact_with_self_draft_and_fewer_calls():
    """Draft == target: output identical and most proposals accepted, so
    target forwards collapse well below one-per-token.  (Acceptance is
    high, not total: the draft steps single-token while the verify runs
    batched, and on an UNTRAINED model near-tie logits can argmax apart
    under the two fusion orders — exactness never depends on acceptance.)
    """
    model, params = make_lm(seed=1)
    want = np.asarray(generate(model, params, PROMPT, 12))
    got, stats = speculative_generate(model, params, model, params,
                                      PROMPT, 12, draft_len=3,
                                      return_stats=True)
    np.testing.assert_array_equal(np.asarray(got), want)
    assert stats["accepted"] >= stats["drafted"] // 2
    assert stats["target_calls"] < 12


@pytest.mark.parametrize("steps,k", [(1, 4), (5, 1), (7, 16)])
def test_exact_across_step_and_draft_lengths(steps, k):
    model, params = make_lm(seed=2)
    draft, dparams = make_lm(layers=1, seed=3)
    want = np.asarray(generate(model, params, PROMPT, steps))
    got = np.asarray(speculative_generate(model, params, draft, dparams,
                                          PROMPT, steps, draft_len=k))
    np.testing.assert_array_equal(got, want)


def test_trained_draft_accepts_most():
    """A draft trained on the same x+1 task accepts nearly everything."""
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.trainers import SingleTrainer

    rng = np.random.default_rng(0)
    x = rng.integers(0, 16, (256, 12)).astype(np.int32)
    y = (x + 1) % 16

    def train(layers):
        model = transformer_lm(vocab_size=16, seq_len=24, d_model=32,
                               num_heads=4, num_layers=layers, mlp_dim=64,
                               compute_dtype="float32")
        t = SingleTrainer(model, batch_size=32, num_epoch=25,
                          loss="sparse_categorical_crossentropy_from_logits",
                          worker_optimizer="adam", learning_rate=3e-3)
        f = t.train(Dataset({"features": x, "label": y}))
        return f.model, f.params

    model, params = train(2)
    draft, dparams = train(1)
    prompt = np.array([[3, 4, 5, 6]], np.int32)
    want = np.asarray(generate(model, params, prompt, 16))
    got, stats = speculative_generate(model, params, draft, dparams,
                                      prompt, 16, draft_len=4,
                                      return_stats=True)
    np.testing.assert_array_equal(np.asarray(got), want)
    # both learned x+1, so the draft's proposals almost all land
    assert stats["accepted"] / stats["drafted"] > 0.8
    assert stats["target_calls"] < 16


def test_validation():
    model, params = make_lm()
    draft, dparams = make_lm(layers=1, vocab=8)
    with pytest.raises(ValueError, match="vocabularies differ"):
        speculative_generate(model, params, draft, dparams, PROMPT, 4)
    draft, dparams = make_lm(layers=1)
    with pytest.raises(ValueError, match="num_steps"):
        speculative_generate(model, params, draft, dparams, PROMPT, 0)
    with pytest.raises(ValueError, match="draft_len"):
        speculative_generate(model, params, draft, dparams, PROMPT, 4,
                             draft_len=0)


def test_long_self_draft_acceptance_does_not_decay():
    """Regression for the draft-cache hole: fully-accepted rounds used to
    leave one unwritten (zero) draft slot each, quietly diluting every
    later draft forward.  With the back-fill, a trained self-draft keeps
    accepting across a LONG generation."""
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.trainers import SingleTrainer

    rng = np.random.default_rng(1)
    x = rng.integers(0, 16, (256, 12)).astype(np.int32)
    model = transformer_lm(vocab_size=16, seq_len=64, d_model=32,
                           num_heads=4, num_layers=2, mlp_dim=64,
                           compute_dtype="float32")
    t = SingleTrainer(model, batch_size=32, num_epoch=25,
                      loss="sparse_categorical_crossentropy_from_logits",
                      worker_optimizer="adam", learning_rate=3e-3)
    f = t.train(Dataset({"features": x, "label": (x + 1) % 16}))

    prompt = np.array([[3, 4, 5, 6]], np.int32)
    want = np.asarray(generate(f.model, f.params, prompt, 48))
    got, stats = speculative_generate(f.model, f.params, f.model, f.params,
                                      prompt, 48, draft_len=4,
                                      return_stats=True)
    np.testing.assert_array_equal(np.asarray(got), want)
    assert stats["accepted"] / stats["drafted"] > 0.9
    # sustained acceptance => far fewer target calls than tokens
    assert stats["target_calls"] <= 48 // 4 + 2


def test_sampled_speculative_matches_target_distribution():
    """Exactness property of speculative SAMPLING: with temperature +
    top-k warping, the committed-token marginals equal the warped target
    distribution (computed in closed form), whatever the draft proposes.
    1024 iid rows give ~0.04 expected TV noise at vocab 12; the 0.08
    gate catches any systematic bias (e.g. committing raw draft samples
    or skipping the residual redraw) which shifts TV by O(p-q) ~ 0.3+."""
    V, B, temp, topk = 12, 1024, 1.3, 6
    model, params = make_lm(seed=4, vocab=V)
    draft, dparams = make_lm(layers=1, seed=5, vocab=V)
    prompt = np.tile(np.array([[3, 4, 5]], np.int32), (B, 1))
    out = speculative_generate(model, params, draft, dparams, prompt,
                               num_steps=2, draft_len=3,
                               temperature=temp, top_k=topk,
                               rng=jax.random.PRNGKey(0))
    toks = np.asarray(out)[:, 3:]                              # (B, 2)

    from distkeras_tpu.core.decode import _filter_logits

    def warped(tok_rows):
        lg = model.apply(params, jnp.asarray(tok_rows, jnp.int32))
        wl = _filter_logits(lg[:, -1] / temp, topk, None)
        return np.asarray(jax.nn.softmax(wl, axis=-1))

    p1 = warped(prompt[:1])[0]                                 # (V,)
    emp1 = np.bincount(toks[:, 0], minlength=V) / B
    assert 0.5 * np.abs(emp1 - p1).sum() < 0.08

    # second-token marginal: sum_x p1(x) * p(y | prompt + x), enumerated
    exts = np.concatenate([np.tile(prompt[:1], (V, 1)),
                           np.arange(V, dtype=np.int32)[:, None]], axis=1)
    p2 = (p1[:, None] * warped(exts)).sum(axis=0)
    emp2 = np.bincount(toks[:, 1], minlength=V) / B
    assert 0.5 * np.abs(emp2 - p2).sum() < 0.08


def test_sampled_speculative_deterministic_and_validated():
    model, params = make_lm(seed=6)
    draft, dparams = make_lm(layers=1, seed=7)
    key = jax.random.PRNGKey(3)
    a = np.asarray(speculative_generate(model, params, draft, dparams,
                                        PROMPT, 6, temperature=0.8,
                                        top_p=0.9, rng=key))
    b = np.asarray(speculative_generate(model, params, draft, dparams,
                                        PROMPT, 6, temperature=0.8,
                                        top_p=0.9, rng=key))
    np.testing.assert_array_equal(a, b)  # same key -> same tokens
    with pytest.raises(ValueError, match="rng"):
        speculative_generate(model, params, draft, dparams, PROMPT, 4,
                             temperature=0.5)
    with pytest.raises(ValueError, match="top_k/top_p"):
        speculative_generate(model, params, draft, dparams, PROMPT, 4,
                             top_k=5)
    with pytest.raises(ValueError, match="top_p"):
        speculative_generate(model, params, draft, dparams, PROMPT, 4,
                             temperature=0.5, top_p=1.5,
                             rng=jax.random.PRNGKey(0))


def test_eos_stopping_matches_generate_and_saves_calls():
    """eos_id/pad_id on speculative_generate: bit-identical to generate's
    stopping semantics, and a fully-finished batch stops issuing verify
    calls (the early-exit path)."""
    model, params = make_lm(seed=0)
    draft, dparams = make_lm(layers=1, seed=99)
    prompt = PROMPT[:1]  # single row: batch finishes when it does
    base = np.asarray(generate(model, params, prompt, 12))
    eos = int(base[0, 3 + 2])  # a token the greedy path actually emits
    want = np.asarray(generate(model, params, prompt, 12,
                               eos_id=eos, pad_id=1))
    got, stats = speculative_generate(model, params, draft, dparams,
                                      prompt, 12, draft_len=3,
                                      eos_id=eos, pad_id=1,
                                      return_stats=True)
    np.testing.assert_array_equal(np.asarray(got), want)
    assert (np.asarray(got)[0] == 1).any()  # padding actually happened
    _, stats_free = speculative_generate(model, params, draft, dparams,
                                         prompt, 12, draft_len=3,
                                         return_stats=True)
    assert stats["target_calls"] <= stats_free["target_calls"]

    # batched: per-row stopping with static output shape
    want2 = np.asarray(generate(model, params, PROMPT, 12, eos_id=eos))
    got2 = np.asarray(speculative_generate(model, params, draft, dparams,
                                           PROMPT, 12, draft_len=3,
                                           eos_id=eos))
    np.testing.assert_array_equal(got2, want2)

    with pytest.raises(ValueError, match="pad_id"):
        speculative_generate(model, params, draft, dparams, PROMPT, 4,
                             pad_id=1)
    with pytest.raises(ValueError, match="eos_id"):
        speculative_generate(model, params, draft, dparams, PROMPT, 4,
                             eos_id=99)


def test_eos_composes_with_sampling():
    """eos stopping + rejection sampling: deterministic per key, static
    shape, pad after the first eos in every row."""
    model, params = make_lm(seed=8)
    draft, dparams = make_lm(layers=1, seed=9)
    key = jax.random.PRNGKey(5)
    a = np.asarray(speculative_generate(model, params, draft, dparams,
                                        PROMPT, 10, temperature=1.0,
                                        top_k=8, rng=key, eos_id=3,
                                        pad_id=0))
    b = np.asarray(speculative_generate(model, params, draft, dparams,
                                        PROMPT, 10, temperature=1.0,
                                        top_k=8, rng=key, eos_id=3,
                                        pad_id=0))
    np.testing.assert_array_equal(a, b)
    assert a.shape == (2, 13)
    for row in a:
        gen = row[3:]
        hits = np.where(gen == 3)[0]
        if len(hits):  # everything after the first eos is pad
            assert (gen[hits[0] + 1:] == 0).all()
