"""Closed-form unit tests for the per-algorithm update rules (SURVEY.md §4:
"unit-test each algorithm's update rule as a pure function").

Each rule is checked against hand-computed numbers matching the reference PS
semantics (DeltaParameterServer, ADAGParameterServer, DynSGDParameterServer,
AEASGDWorker's elastic term).
"""

import jax.numpy as jnp
import numpy as np

from distkeras_tpu.parallel import rules


def tree(v):
    return [{"kernel": jnp.asarray(v, jnp.float32)}]


def leaf(t):
    return np.asarray(t[0]["kernel"])


def test_delta_commit():
    center = tree([1.0, 2.0])
    delta = tree([0.5, -1.0])
    np.testing.assert_allclose(leaf(rules.delta_commit(center, delta)),
                               [1.5, 1.0])


def test_adag_commit_normalizes():
    center = tree([0.0, 0.0])
    summed = tree([4.0, 8.0])  # sum over 4 workers' deltas
    out = rules.adag_commit(center, summed, 4)
    np.testing.assert_allclose(leaf(out), [1.0, 2.0])


def test_elastic_difference_and_updates():
    local = tree([2.0])
    center = tree([1.0])
    alpha = 0.5
    e = rules.elastic_difference(local, center, alpha)
    np.testing.assert_allclose(leaf(e), [0.5])  # α(x − x̃)
    new_local = rules.easgd_worker_update(local, e)
    np.testing.assert_allclose(leaf(new_local), [1.5])  # x − e
    new_center = rules.easgd_center_update(center, e)
    np.testing.assert_allclose(leaf(new_center), [1.5])  # x̃ + e


def test_elastic_fixed_point():
    # at local == center the elastic force vanishes
    local = center = tree([3.0])
    e = rules.elastic_difference(local, center, 0.9)
    np.testing.assert_allclose(leaf(e), [0.0])


def test_dynsgd_staleness_scaling():
    center = tree([0.0])
    delta = tree([6.0])
    np.testing.assert_allclose(
        leaf(rules.dynsgd_commit(center, delta, 0.0)), [6.0])  # fresh
    np.testing.assert_allclose(
        leaf(rules.dynsgd_commit(center, delta, 2.0)), [2.0])  # stale by 2


def test_average_trees():
    out = rules.average_trees([tree([1.0, 3.0]), tree([3.0, 5.0])])
    np.testing.assert_allclose(leaf(out), [2.0, 4.0])
