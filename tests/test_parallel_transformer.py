"""ParallelTransformerLM: the integrated dp × sp × tp (+ ep) train step.

Checks: (a) the 8-device 2×2×2 mesh program computes the same loss as the
same model on a degenerate 1×1×1 mesh (sharding changes nothing
numerically), (b) training converges on a deterministic next-token task,
(c) sharded params actually carry their specs on device.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh

from distkeras_tpu.parallel.transformer import ParallelTransformerLM


def make_lm(mesh, **kw):
    cfg = dict(vocab_size=32, seq_len=16, d_model=16, num_heads=2,
               num_layers=2, mlp_dim=32, mesh=mesh,
               compute_dtype=jnp.float32)
    cfg.update(kw)
    return ParallelTransformerLM(**cfg)


def make_batch(lm, n=4, seed=0):
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, lm.vocab_size, (n, lm.seq_len)).astype(np.int32)
    labels = (toks + 1) % lm.vocab_size
    sh = lm.batch_sharding()
    return jax.device_put(toks, sh), jax.device_put(labels, sh)


def mesh_of(shape):
    n = int(np.prod(shape))
    devs = np.array(jax.devices()[:n]).reshape(shape)
    return Mesh(devs, ("data", "seq", "model"))


def run_steps(lm, steps, seed=0, lr=1e-2):
    params = lm.init(jax.random.PRNGKey(7))
    opt_state, step = lm.compile_train_step(optax.adam(lr), params)
    toks, labels = make_batch(lm, seed=seed)
    losses = []
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, toks, labels)
        losses.append(float(loss))
    return losses, params


@pytest.mark.parametrize("moe", [False, True])
def test_sharded_matches_single_device(eight_devices, moe):
    kw = {}
    if moe:
        # capacity_factor high enough that no token drops on either mesh
        # (per-shard capacities differ between meshes otherwise)
        kw = dict(moe_layers=(1,), num_experts=2, capacity_factor=8.0)
    l8, _ = run_steps(make_lm(mesh_of((2, 2, 2)), **kw), 3)
    l1, _ = run_steps(make_lm(mesh_of((1, 1, 1)), **kw), 3)
    np.testing.assert_allclose(l8, l1, rtol=2e-4)


def test_gqa_window_sharded_matches_single_device(eight_devices):
    """GQA (2 kv heads over 4 q heads) + sliding window on the 2×2×2 mesh
    == the same model on a 1×1×1 mesh; kv shards are kv-head sized."""
    kw = dict(num_heads=4, num_kv_heads=2, attention_window=8, d_model=16)
    l8, p8 = run_steps(make_lm(mesh_of((2, 2, 2)), **kw), 3)
    l1, _ = run_steps(make_lm(mesh_of((1, 1, 1)), **kw), 3)
    np.testing.assert_allclose(l8, l1, rtol=2e-4)
    wk = p8["layers"][0]["wk"]
    # (d, Hkv·Dh) = (16, 2*4) split over tp=2 -> local (16, 4)
    assert wk.addressable_shards[0].data.shape == (16, 4)


def test_rope_sharded_matches_single_device(eight_devices):
    """positional='rope' (global-position q/k rotation, no pos table) on
    the 2×2×2 mesh == the same model on a 1×1×1 mesh, and it trains."""
    kw = dict(num_heads=2, positional="rope")
    l8, p8 = run_steps(make_lm(mesh_of((2, 2, 2)), **kw), 3)
    l1, _ = run_steps(make_lm(mesh_of((1, 1, 1)), **kw), 3)
    np.testing.assert_allclose(l8, l1, rtol=2e-4)
    assert "pos" not in p8  # no additive positional table under rope

    losses, _ = run_steps(make_lm(mesh_of((2, 2, 2)), **kw), 30)
    assert losses[-1] < 0.3 * losses[0], losses
    with pytest.raises(ValueError, match="positional"):
        make_lm(mesh_of((2, 2, 2)), positional="alibi")


def test_gqa_tp_divisibility_validated(eight_devices):
    with pytest.raises(ValueError, match="num_kv_heads"):
        make_lm(mesh_of((2, 2, 2)), num_heads=4, num_kv_heads=3)
    with pytest.raises(ValueError, match="kv heads"):
        make_lm(mesh_of((2, 2, 2)), num_heads=4, num_kv_heads=1)
    with pytest.raises(ValueError, match="window must be"):
        make_lm(mesh_of((2, 2, 2)), attention_window=0)


def test_training_converges(eight_devices):
    losses, _ = run_steps(
        make_lm(mesh_of((2, 2, 2)), moe_layers=(1,), num_experts=2), 30)
    assert losses[-1] < 0.3 * losses[0], losses


def test_params_are_sharded(eight_devices):
    lm = make_lm(mesh_of((2, 2, 2)))
    params = lm.init(jax.random.PRNGKey(0))
    wq = params["layers"][0]["wq"]          # P(None, 'model'): split in 2
    assert wq.sharding.spec == jax.sharding.PartitionSpec(None, "model")
    local = wq.addressable_shards[0].data.shape
    assert local == (16, 8)                  # (d, H·Dh/tp) = (16, 16/2)
    assert params["embed"].addressable_shards[0].data.shape == (32, 16)


def test_remat_matches_no_remat(eight_devices):
    """jax.checkpoint per block changes memory, not math: identical losses."""
    plain, _ = run_steps(make_lm(mesh_of((2, 2, 2))), 3)
    remat, _ = run_steps(make_lm(mesh_of((2, 2, 2)), remat=True), 3)
    np.testing.assert_allclose(plain, remat, rtol=1e-6)


def test_validation_errors():
    mesh = mesh_of((2, 2, 2))
    with pytest.raises(ValueError, match="num_heads"):
        make_lm(mesh, num_heads=3)
    with pytest.raises(ValueError, match="seq_len"):
        make_lm(mesh, seq_len=15)
