"""Serving fast path (distkeras_tpu/serving.py, ``prefill_mode="bucketed"``).

PR 9 rebuilt the engine's compute path in three layers: compiled bucketed
batch prefill, chunked long-prompt prefill interleaved with decode, and
device-resident decode state with one-step lookahead.  The contract
pinned here:

 - bucketed AND chunked prefill emit tokens BIT-IDENTICAL to the eager
   reference (``prefill_mode="eager"``) and to offline ``generate``,
   across greedy + sampled × rolling + full-cache × mixed prompt lengths
   sharing one bucketed batch — the fast path is an execution strategy,
   never a numerics change;
 - the bucketed hot path never calls the eager ``_forward`` (compiled by
   construction, the acceptance criterion);
 - a decode-only iteration performs ZERO host→device uploads and exactly
   ONE device→host readback (the sampled token row) — asserted with a
   transfer-counting double wrapped around the jitted step;
 - a long-prompt admission stalls the running batch by at most one
   ``prefill_chunk`` chunk per iteration (deterministic counter
   assertion — the Sarathi-style stall-free property);
 - ``warmup()`` precompiles every bucket/chunk/decode program, so live
   traffic after a supervisor respawn re-traces NOTHING;
 - hot weight reload fires only when ``decode_steps`` actually advances
   (a reap-only iteration parked on a reload multiple must not re-pull).
"""

import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import distkeras_tpu.serving as serving
from distkeras_tpu.core import decode
from distkeras_tpu.core.model import FittedModel
from distkeras_tpu.models import transformer_lm
from distkeras_tpu.serving import ServingEngine, _pow2_buckets

VOCAB = 17
PROMPT = np.array([3, 4, 5, 6], np.int32)


def _fitted(seed=0, **kw):
    model = transformer_lm(vocab_size=VOCAB, seq_len=32, d_model=16,
                           num_heads=2, num_layers=2, mlp_dim=32,
                           compute_dtype="float32", **kw)
    params = model.init(jax.random.PRNGKey(seed), (32,))
    return FittedModel(model, params)


@pytest.fixture(scope="module")
def fitted():
    return _fitted()


@pytest.fixture(scope="module")
def windowed():
    return _fitted(seed=1, attention_window=6)


def _want(fitted, h, **kw):
    return np.asarray(fitted.generate(
        h.prompt[None], h.num_steps, max_len=kw.pop("max_len"),
        temperature=h.temperature,
        rng=h.key if h.temperature > 0 else None,
        top_k=h.top_k, top_p=h.top_p, **kw))[0]


# ---------------------------------------------------------------------------
# bit-identity: bucketed / chunked / rolling vs eager reference + generate
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kw", [
    {},                                                       # greedy
    {"temperature": 0.7, "seed": 11},                         # plain sample
    {"temperature": 0.7, "top_k": 5, "top_p": 0.9, "seed": 11},
])
def test_bucketed_lone_request_matches_eager_and_generate(fitted, kw):
    rows = {}
    for mode in ("bucketed", "eager"):
        eng = ServingEngine(fitted, num_slots=3, max_len=24,
                            prefill_mode=mode)
        h = eng.submit(PROMPT, 8, **kw)
        eng.run_until_idle()
        rows[mode] = h.result()
    want = _want(fitted, h, max_len=24)
    np.testing.assert_array_equal(rows["bucketed"], want)
    np.testing.assert_array_equal(rows["eager"], want)


def test_mixed_prompt_lengths_share_one_bucketed_batch(fitted):
    """Four requests of four different lengths admitted in the same
    iteration land in ONE batched bucket prefill (their lengths all round
    up to the same bucket), and every output still matches generate."""
    eng = ServingEngine(fitted, num_slots=4, max_len=24,
                        prefills_per_step=4)
    hs = [eng.submit(np.arange(1, 1 + p, dtype=np.int32) % VOCAB, 6,
                     temperature=0.5, seed=40 + p)
          for p in (2, 3, 5, 7)]
    eng.run_until_idle()
    assert eng.stats["prefill_batches"] == 1
    assert eng.stats["prefill_batch_size_mean"] == 4.0
    for h in hs:
        np.testing.assert_array_equal(h.result(),
                                      _want(fitted, h, max_len=24))


def test_chunked_prefill_bit_identical_and_counted(fitted):
    """A prompt past ``prefill_chunk`` splits into ceil(P/chunk) chunks
    (the final one bucket-rounded) and still reproduces generate exactly,
    greedy and sampled, while a short concurrent request rides along."""
    long_p = (np.arange(1, 14, dtype=np.int32) * 3) % VOCAB  # 13 tokens
    for kw in ({}, {"temperature": 0.6, "seed": 5}):
        eng = ServingEngine(fitted, num_slots=2, max_len=32,
                            prefill_chunk=4)
        h = eng.submit(long_p, 8, **kw)
        h2 = eng.submit(PROMPT, 4)
        eng.run_until_idle()
        assert eng.stats["prefill_chunks"] == 4  # 4+4+4 + final 1
        np.testing.assert_array_equal(h.result(),
                                      _want(fitted, h, max_len=32))
        np.testing.assert_array_equal(h2.result(),
                                      _want(fitted, h2, max_len=32))


def test_rolling_bucketed_and_chunked_bit_identical(windowed):
    """Rolling engines: the bucket program ring-converts per-row traced
    lengths; the chunked path stages a full cache and collapses it on the
    final chunk — both must match offline rolling generate."""
    eng = ServingEngine(windowed, num_slots=2, max_len=24, rolling=True)
    h1 = eng.submit(np.arange(1, 8, dtype=np.int32) % VOCAB, 10,
                    temperature=0.6, seed=9)
    h2 = eng.submit(np.array([1, 2], np.int32), 6)
    eng.run_until_idle()
    for h in (h1, h2):
        np.testing.assert_array_equal(
            h.result(), _want(windowed, h, max_len=24, rolling=True))
    assert eng.caches[2]["k"].shape[1] == 6  # the pool really is a ring

    eng = ServingEngine(windowed, num_slots=2, max_len=28, rolling=True,
                        prefill_chunk=4)
    lp = (np.arange(1, 14, dtype=np.int32) * 5) % VOCAB
    h = eng.submit(lp, 8, temperature=0.8, seed=3)
    eng.run_until_idle()
    assert eng.stats["prefill_chunks"] == 4
    np.testing.assert_array_equal(
        h.result(), _want(windowed, h, max_len=28, rolling=True))


def test_ring_from_prefill_matches_to_ring():
    """The traced per-row ring conversion is a relayout: bit-equal to the
    host-side _to_ring for every p_len/window relation, including a
    mixed-length batch in one call."""
    rng = np.random.default_rng(0)
    c = jnp.asarray(rng.standard_normal((3, 12, 2, 3)), jnp.float32)
    for lens, w in (([9, 3, 4], 4), ([1, 12, 6], 6)):
        got = np.asarray(decode.ring_from_prefill(c, jnp.array(lens), w))
        for r, p in enumerate(lens):
            want = np.asarray(decode._to_ring(c[r:r + 1, :p], p, w))
            np.testing.assert_array_equal(got[r:r + 1], want)


def test_eos_retirement_on_fast_path(fitted):
    greedy = np.asarray(fitted.generate(PROMPT[None], 8, max_len=24))[0]
    eos = int(greedy[len(PROMPT) + 2])
    eng = ServingEngine(fitted, num_slots=2, max_len=24)
    h = eng.submit(PROMPT, 8, eos_id=eos, pad_id=1)
    eng.run_until_idle()
    want = np.asarray(fitted.generate(PROMPT[None], 8, eos_id=eos,
                                      pad_id=1, max_len=24))[0]
    np.testing.assert_array_equal(h.result(), want)
    assert h.finish == "eos"


# ---------------------------------------------------------------------------
# hot-path discipline: no eager forward, one transfer each way
# ---------------------------------------------------------------------------

def test_no_eager_forward_in_bucketed_hot_path(fitted, monkeypatch):
    """Acceptance criterion: with prefill_mode="bucketed" (the default)
    the engine never calls the module-level eager ``_forward`` — only the
    eager reference mode does."""
    def bomb(*a, **k):
        raise AssertionError("eager _forward reached the bucketed hot "
                             "path")

    monkeypatch.setattr(serving, "_forward", bomb)
    eng = ServingEngine(fitted, num_slots=2, max_len=24, prefill_chunk=4)
    h = eng.submit(PROMPT, 4)
    hl = eng.submit((np.arange(1, 12, dtype=np.int32) * 7) % VOCAB, 4)
    eng.run_until_idle()  # both the batch and the chunked path: no bomb
    assert h.done and hl.done
    eager = ServingEngine(fitted, num_slots=1, max_len=24,
                          prefill_mode="eager")
    eager.submit(PROMPT, 2)
    with pytest.raises(AssertionError, match="hot path"):
        eager.run_until_idle()


def test_decode_iteration_transfer_discipline(fitted):
    """Steady-state decode: zero host→device uploads, exactly one
    device→host readback per iteration, and every jitted-step argument is
    already a device array (the test double wraps the step)."""
    eng = ServingEngine(fitted, num_slots=2, max_len=24).warmup()
    h = eng.submit(PROMPT, 14)
    eng.step()  # admission iteration (uploads happen here, counted apart)
    orig = eng._decode_fn

    def checked(*args):
        leaves = jax.tree_util.tree_leaves(args)
        assert all(isinstance(a, jax.Array) for a in leaves), \
            "decode step received a host array (implicit h2d transfer)"
        return orig(*args)

    eng._decode_fn = checked
    h0, d0 = eng.stats["h2d_transfers"], eng.stats["d2h_transfers"]
    for _ in range(6):
        eng.step()
    assert eng.stats["h2d_transfers"] - h0 == 0
    assert eng.stats["d2h_transfers"] - d0 == 6
    eng.run_until_idle()
    np.testing.assert_array_equal(h.result(),
                                  _want(fitted, h, max_len=24))


def test_lookahead_flushes_at_idle(fitted):
    """One-step lookahead leaves the pipeline drained when work runs out:
    every token is delivered, nothing pends, and the engine reports idle."""
    eng = ServingEngine(fitted, num_slots=2, max_len=24)
    h = eng.submit(PROMPT, 5)
    eng.run_until_idle()
    assert h.done and len(h.tokens) == 5
    assert not eng._pending and not eng._prefilling
    assert not eng.step()  # truly idle


# ---------------------------------------------------------------------------
# stall-free chunked admission (deterministic counters, tier-1)
# ---------------------------------------------------------------------------

def test_long_prompt_admission_does_not_stall_decode(fitted):
    """While a 12-token prompt chunk-prefills at prefill_chunk=4, the
    running request keeps decoding EVERY iteration: the admission costs
    the running batch at most one chunk of prefill per step, never the
    whole prompt (the counter twin of the wall-clock TTFT bench)."""
    eng = ServingEngine(fitted, num_slots=2, max_len=32, prefill_chunk=4)
    a = eng.submit(PROMPT, 20)
    while not a.tokens:
        eng.step()
    steps0 = eng.stats["decode_steps"]
    a0 = len(a.tokens)
    b = eng.submit((np.arange(1, 13, dtype=np.int32) * 3) % VOCAB, 4)
    iters = 0
    while not b.tokens and iters < 20:
        eng.step()
        iters += 1
    assert eng.stats["prefill_chunks"] == 3        # 4 + 4 + final 4
    # every chunk iteration also ran a decode step for the running batch
    decoded = eng.stats["decode_steps"] - steps0
    assert decoded >= 3 and decoded == iters
    assert len(a.tokens) - a0 >= 3
    # and B's first token arrived within chunks + pipeline slack
    assert iters <= 5
    eng.run_until_idle()
    np.testing.assert_array_equal(a.result(), _want(fitted, a, max_len=32))
    np.testing.assert_array_equal(b.result(), _want(fitted, b, max_len=32))


# ---------------------------------------------------------------------------
# warmup precompilation + reload gate
# ---------------------------------------------------------------------------

def test_warmup_precompiles_every_program(fitted, monkeypatch):
    """After warmup(), traffic through every bucket AND the chunked path
    triggers zero new jit traces (counted via decode._forward, which every
    program traces through) — a supervisor respawn must not pay per-bucket
    compiles under live traffic."""
    calls = []
    orig = decode._forward

    def counting(*a, **k):
        calls.append(1)
        return orig(*a, **k)

    monkeypatch.setattr(decode, "_forward", counting)
    eng = ServingEngine(fitted, num_slots=2, max_len=24, prefill_chunk=4,
                        prefills_per_step=2).warmup()
    traced = len(calls)
    assert traced > 0
    h1 = eng.submit(np.array([2, 3, 4], np.int32), 3)       # bucket batch
    h2 = eng.submit((np.arange(1, 12, dtype=np.int32)) % VOCAB, 3)  # chunks
    eng.run_until_idle()
    assert h1.done and h2.done
    assert len(calls) == traced, "live traffic re-traced a program"


def test_warmup_refuses_mid_prefill_engine(fitted):
    eng = ServingEngine(fitted, num_slots=1, max_len=32, prefill_chunk=4)
    eng.submit((np.arange(1, 13, dtype=np.int32)) % VOCAB, 4)
    eng.step()
    assert eng._prefilling
    with pytest.raises(RuntimeError, match="active"):
        eng.warmup()


def test_pow2_bucket_ladder():
    assert _pow2_buckets(32) == [8, 16, 32]
    assert _pow2_buckets(100) == [8, 16, 32, 64, 100]
    assert _pow2_buckets(8) == [8]
    assert _pow2_buckets(5) == [5]


def test_prefill_knob_validation(fitted):
    with pytest.raises(ValueError, match="prefill_mode"):
        ServingEngine(fitted, num_slots=1, max_len=24,
                      prefill_mode="turbo")
    with pytest.raises(ValueError, match="prefill_chunk"):
        ServingEngine(fitted, num_slots=1, max_len=24, prefill_chunk=0)


def test_respawn_clone_carries_prefill_knobs(fitted):
    eng = ServingEngine(fitted, num_slots=2, max_len=24,
                        prefill_mode="eager", prefill_chunk=16)
    clone = eng.respawn_clone()
    assert clone.prefill_mode == "eager"
    assert clone.prefill_chunk == 16


def test_reload_gate_requires_decode_progress(fitted):
    """The hot-reload satellite: _pull_weights fires only when
    decode_steps ADVANCES onto a reload multiple — a reap-only iteration
    parked on a multiple must not re-pull every pass."""
    eng = ServingEngine(fitted, num_slots=1, max_len=24)
    pulls = []
    eng._pull_weights = lambda: pulls.append(1)
    eng._reload_every = 1
    eng.submit(PROMPT, 3)
    eng.run_until_idle()
    base = len(pulls)
    assert base >= 1  # decode progress pulled as expected
    # park the counter on a multiple, then run a reap-only iteration
    h2 = eng.submit(PROMPT, 3)
    eng.cancel(h2)
    assert eng.step()  # reap does work, decode_steps does not advance
    assert len(pulls) == base


# ---------------------------------------------------------------------------
# speculative decoding on the fast path (PR 11): greedy token-identity,
# heterogeneous per-row accept lengths, stats vocabulary, warmup coverage
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def draft():
    return _fitted(seed=99)  # independent random draft: near-floor accepts


@pytest.mark.parametrize("draft_kind", ["self", "random"])
@pytest.mark.parametrize("spec_len", [1, 3])
def test_spec_greedy_token_identity_vs_eager(fitted, draft, draft_kind,
                                             spec_len):
    """The tentpole contract: greedy speculation is TOKEN-IDENTICAL to
    the non-speculative engine whatever the draft proposes — a self-draft
    (high accept: rows ride the fast lane) and an independent random
    draft (near-floor accept: every round falls back to the correction
    token) both reproduce the eager reference bit for bit, with MIXED
    prompt lengths (so mixed accept lengths) sharing one batch."""
    d = fitted if draft_kind == "self" else draft
    subs = [(np.arange(1, 1 + p, dtype=np.int32) % VOCAB, 5 + p % 3)
            for p in (2, 4, 7)]
    eager = ServingEngine(fitted, num_slots=3, max_len=24,
                          prefill_mode="eager", prefills_per_step=3)
    want = [eager.submit(pr, n) for pr, n in subs]
    eager.run_until_idle()
    eng = ServingEngine(fitted, num_slots=3, max_len=24, spec_draft=d,
                        spec_len=spec_len, prefills_per_step=3)
    got = [eng.submit(pr, n) for pr, n in subs]
    eng.run_until_idle()
    for w, g in zip(want, got):
        np.testing.assert_array_equal(g.result(), w.result())
    assert eng.stats["verify_calls"] >= 1
    assert eng.stats["drafted"] >= spec_len
    assert 0 <= eng.stats["accepted"] <= eng.stats["drafted"]


def test_spec_rolling_token_identity(windowed):
    """Rolling pools under speculation: the ring carries spec_len slack
    slots so the L-token verify never overwrites the oldest query's
    window — greedy output still matches the eager rolling reference."""
    subs = [(np.arange(1, 8, dtype=np.int32) % VOCAB, 10),
            (np.array([1, 2], np.int32), 6)]
    eager = ServingEngine(windowed, num_slots=2, max_len=24, rolling=True,
                          prefill_mode="eager", prefills_per_step=2)
    want = [eager.submit(pr, n) for pr, n in subs]
    eager.run_until_idle()
    eng = ServingEngine(windowed, num_slots=2, max_len=24, rolling=True,
                        spec_draft=windowed, spec_len=3,
                        prefills_per_step=2)
    # the pool ring really is window + spec_len slots
    assert eng.caches[2]["k"].shape[1] == 6 + 3
    got = [eng.submit(pr, n) for pr, n in subs]
    eng.run_until_idle()
    for w, g in zip(want, got):
        np.testing.assert_array_equal(g.result(), w.result())


def test_spec_sampled_deterministic_and_greedy_rows_exact(fitted):
    """A mixed greedy + sampled batch under speculation: sampled rows are
    deterministic per seed (run twice, identical) and the GREEDY rows in
    the same batch stay bit-identical to the eager reference — per-row
    independence of the accept/commit machinery."""
    subs = [((PROMPT, 8), {}),
            ((np.array([1, 2], np.int32), 6),
             {"temperature": 0.7, "top_k": 5, "seed": 3}),
            ((np.arange(1, 8, dtype=np.int32), 5), {})]

    def run():
        eng = ServingEngine(fitted, num_slots=3, max_len=24,
                            spec_draft=fitted, spec_len=4,
                            prefills_per_step=3)
        hs = [eng.submit(*a, **k) for a, k in subs]
        eng.run_until_idle()
        return [h.result() for h in hs]

    rows1, rows2 = run(), run()
    for a, b in zip(rows1, rows2):
        np.testing.assert_array_equal(a, b)
    eager = ServingEngine(fitted, num_slots=2, max_len=24,
                          prefill_mode="eager", prefills_per_step=2)
    w0 = eager.submit(*subs[0][0])
    w2 = eager.submit(*subs[2][0])
    eager.run_until_idle()
    np.testing.assert_array_equal(rows1[0], w0.result())
    np.testing.assert_array_equal(rows1[2], w2.result())


def test_spec_chunked_prefill_and_eos(fitted):
    """Long prompts chunk-prefill into BOTH pools (target + draft
    staging), and eos retirement mid-round matches generate's stopping
    semantics token for token."""
    lp = (np.arange(1, 14, dtype=np.int32) * 3) % VOCAB
    eng = ServingEngine(fitted, num_slots=2, max_len=32, spec_draft=fitted,
                        spec_len=3, prefill_chunk=4)
    h = eng.submit(lp, 8)
    eng.run_until_idle()
    assert eng.stats["prefill_chunks"] == 4
    np.testing.assert_array_equal(h.result(), _want(fitted, h, max_len=32))

    greedy = np.asarray(fitted.generate(PROMPT[None], 8, max_len=24))[0]
    eos = int(greedy[len(PROMPT) + 2])
    eng = ServingEngine(fitted, num_slots=2, max_len=24, spec_draft=fitted,
                        spec_len=4)
    h = eng.submit(PROMPT, 8, eos_id=eos, pad_id=1)
    eng.run_until_idle()
    want = np.asarray(fitted.generate(PROMPT[None], 8, eos_id=eos,
                                      pad_id=1, max_len=24))[0]
    np.testing.assert_array_equal(h.result(), want)
    assert h.finish == "eos"


def test_spec_stats_mirror_offline_vocabulary(fitted):
    """The engine reports speculation through speculative_generate's own
    stats keys: drafted/accepted (+ verify_calls, mirrored verbatim by
    target_calls) — one vocabulary across offline and serving."""
    eng = ServingEngine(fitted, num_slots=2, max_len=24, spec_draft=fitted,
                        spec_len=3)
    h = eng.submit(PROMPT, 10)
    eng.run_until_idle()
    s = eng.stats
    assert h.done and s["verify_calls"] >= 1
    assert s["target_calls"] == s["verify_calls"]
    assert s["drafted"] == 3 * s["verify_calls"]
    assert 0 <= s["accepted"] <= s["drafted"]
    # offline stats carry the same keys (the satellite's shared contract)
    _, off = fitted.speculative_generate(fitted, PROMPT[None], 6,
                                         draft_len=3, return_stats=True)
    assert set(off) == {"target_calls", "drafted", "accepted"}
    assert set(off) < set(s)


def test_spec_warmup_precompiles_draft_and_verify(fitted, monkeypatch):
    """warmup() on a speculative engine compiles the spec round (draft
    steps + verify + back-fill), every bucket's dual-pool prefill, and
    the chunk programs — live traffic re-traces NOTHING (the respawn-
    under-traffic guarantee, extended to the new programs)."""
    calls = []
    orig = decode._forward

    def counting(*a, **k):
        calls.append(1)
        return orig(*a, **k)

    monkeypatch.setattr(decode, "_forward", counting)
    eng = ServingEngine(fitted, num_slots=2, max_len=24, spec_draft=fitted,
                        spec_len=2, prefill_chunk=4,
                        prefills_per_step=2).warmup()
    traced = len(calls)
    assert traced > 0
    h1 = eng.submit(np.array([2, 3, 4], np.int32), 6)        # bucket batch
    h2 = eng.submit((np.arange(1, 12, dtype=np.int32)) % VOCAB, 6)  # chunks
    eng.run_until_idle()
    assert h1.done and h2.done
    assert len(calls) == traced, "live speculative traffic re-traced"


def test_spec_and_quant_validation(fitted, draft):
    with pytest.raises(ValueError, match="spec_len"):
        ServingEngine(fitted, num_slots=1, max_len=24, spec_draft=fitted,
                      spec_len=0)
    with pytest.raises(ValueError, match="bit-exactness reference"):
        ServingEngine(fitted, num_slots=1, max_len=24,
                      prefill_mode="eager", spec_draft=fitted)
    with pytest.raises(ValueError, match="bit-exactness reference"):
        ServingEngine(fitted, num_slots=1, max_len=24,
                      prefill_mode="eager", kv_dtype="int8")
    with pytest.raises(ValueError, match="quantize"):
        ServingEngine(fitted, num_slots=1, max_len=24, quantize="fp4")
    with pytest.raises(ValueError, match="kv_dtype"):
        ServingEngine(fitted, num_slots=1, max_len=24, kv_dtype="int4")
    small = _fitted(seed=5)
    small.model.layers[0].input_dim = VOCAB + 1  # forge a vocab mismatch
    with pytest.raises(ValueError, match="vocabularies differ"):
        ServingEngine(fitted, num_slots=1, max_len=24, spec_draft=small)


# ---------------------------------------------------------------------------
# quantization on the fast path: int8/bf16 weights, int8 KV pool
# ---------------------------------------------------------------------------

def test_weight_quant_int8_matches_offline_quantized_generate(fitted):
    """quantize="int8" routes construction through quantize_params: the
    engine's output equals offline generate on the SAME quantized params
    (lossy vs fp32, exact vs the quantized reference)."""
    q = fitted.quantize()
    want = np.asarray(q.generate(PROMPT[None], 8, max_len=24))[0]
    eng = ServingEngine(fitted, num_slots=2, max_len=24, quantize="int8")
    h = eng.submit(PROMPT, 8)
    eng.run_until_idle()
    np.testing.assert_array_equal(h.result(), want)


def test_kv_int8_pool_halves_slot_bytes(fitted):
    """The capacity math: an int8 KV pool sustains >= 1.5x the slots of
    the full-precision pool at fixed bytes (byte-accounted, not assumed),
    and requests still complete sanely through the quantized read/write
    path — including under speculation (both pools quantized)."""
    fp = ServingEngine(fitted, num_slots=4, max_len=24)
    q8 = ServingEngine(fitted, num_slots=4, max_len=24, kv_dtype="int8")
    per_slot_q8 = q8.kv_pool_bytes // q8.num_slots
    assert fp.kv_pool_bytes // per_slot_q8 >= int(1.5 * fp.num_slots)
    h = q8.submit(PROMPT, 8)
    q8.run_until_idle()
    row = h.result()
    assert row.shape == (len(PROMPT) + 8,)
    assert (0 <= row).all() and (row < VOCAB).all()
    spec = ServingEngine(fitted, num_slots=2, max_len=24, kv_dtype="int8",
                         spec_draft=fitted, spec_len=3, quantize="int8")
    h2 = spec.submit(PROMPT, 8)
    spec.run_until_idle()
    assert h2.result().shape == (len(PROMPT) + 8,)
    assert spec.stats["verify_calls"] >= 1


def test_respawn_clone_carries_spec_and_quant_state(fitted, draft):
    """The supervisor contract: a respawned clone carries the draft model,
    spec_len, and both quantization knobs — and still warms up and
    serves (greedy spec identity preserved across the respawn)."""
    eng = ServingEngine(fitted, num_slots=2, max_len=24, spec_draft=draft,
                        spec_len=2, quantize="bf16", kv_dtype="int8")
    clone = eng.respawn_clone().warmup()
    assert clone.spec_len == 2 and clone.quantize == "bf16"
    assert clone.kv_dtype == "int8"
    assert clone._draft_model is draft.model
    h = clone.submit(PROMPT, 4)
    clone.run_until_idle()
    assert h.result().shape == (len(PROMPT) + 4,)

    # without quantization, the clone's greedy spec output is bit-equal
    eng2 = ServingEngine(fitted, num_slots=2, max_len=24, spec_draft=fitted)
    clone2 = eng2.respawn_clone()
    h2 = clone2.submit(PROMPT, 8)
    clone2.run_until_idle()
    np.testing.assert_array_equal(h2.result(),
                                  _want(fitted, h2, max_len=24))


def test_defaults_unchanged_no_spec_counters_move(fitted):
    """spec_draft=None / quantize=None / kv_dtype=None: the PR 9 engine,
    bit for bit — pools keep their dtypes and the speculation counters
    never move."""
    eng = ServingEngine(fitted, num_slots=2, max_len=24)
    assert "ks" not in eng.caches[2] and eng.d_caches is None
    h = eng.submit(PROMPT, 8)
    eng.run_until_idle()
    np.testing.assert_array_equal(h.result(), _want(fitted, h, max_len=24))
    assert eng.stats["drafted"] == 0 and eng.stats["verify_calls"] == 0


# ---------------------------------------------------------------------------
# perf smoke (slow): compiled batched prefill beats sequential eager
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_batched_prefill_beats_sequential_eager_prefill(fitted):
    """≥ 4 queued prompts: one warmed bucketed engine (batched compiled
    prefill) finishes the admission burst faster than the eager engine's
    per-request uncompiled prefills — the wall-clock half of the fast-path
    acceptance (the counter half is tier-1 above)."""
    prompts = [((np.arange(8) * (i + 2)) % VOCAB).astype(np.int32)
               for i in range(8)]

    def run(mode):
        eng = ServingEngine(fitted, num_slots=8, max_len=24,
                            prefills_per_step=8, prefill_mode=mode)
        if mode == "bucketed":
            eng.warmup()
        # throwaway round so BOTH modes have their decode/prefill
        # programs compiled before the timed burst
        eng.submit(prompts[0], 1)
        eng.run_until_idle()
        t0 = time.perf_counter()
        hs = [eng.submit(p, 1) for p in prompts]
        eng.run_until_idle()
        dt = time.perf_counter() - t0
        assert all(h.done for h in hs)
        return dt

    eager = run("eager")
    fast = run("bucketed")
    assert fast < eager, (fast, eager)


# ---------------------------------------------------------------------------
# paged KV pool + radix prefix sharing (PR 12)
# ---------------------------------------------------------------------------

def _assert_no_block_leaks(eng):
    """Every retirement path must return the pool to baseline: no block
    held by a live request, and free + cached + private == arena."""
    assert eng.kv_blocks_in_use == 0
    assert eng._pool.check_conservation()


@pytest.mark.paged
@pytest.mark.parametrize("kw", [
    {},                                                       # greedy
    {"temperature": 0.7, "seed": 11},                         # plain sample
    {"temperature": 0.7, "top_k": 5, "top_p": 0.9, "seed": 11},
])
def test_paged_lone_request_matches_dense_and_generate(fitted, kw):
    """The paged pool is a storage relayout, not a numerics change: a lone
    request through block-table decode/prefill emits tokens identical to
    the dense engine and to offline generate."""
    eng = ServingEngine(fitted, num_slots=3, max_len=24, paged=True,
                        block_size=4)
    h = eng.submit(PROMPT, 8, **kw)
    eng.run_until_idle()
    np.testing.assert_array_equal(h.result(), _want(fitted, h, max_len=24))
    _assert_no_block_leaks(eng)


@pytest.mark.paged
def test_paged_rolling_lone_request_matches_generate(windowed):
    """Rolling paged pools: the ring lives in blocks behind the table
    (fixed per-slot allocation, no sharing) — tokens identical to rolling
    generate, bucketed AND chunked admission."""
    eng = ServingEngine(windowed, num_slots=2, max_len=24, rolling=True,
                        paged=True, block_size=4)
    h = eng.submit(PROMPT, 10)
    eng.run_until_idle()
    want = np.asarray(windowed.generate(h.prompt[None], 10, max_len=24,
                                        rolling=True))[0]
    np.testing.assert_array_equal(h.result(), want)
    _assert_no_block_leaks(eng)
    eng = ServingEngine(windowed, num_slots=2, max_len=28, rolling=True,
                        paged=True, block_size=4, prefill_chunk=4)
    long_p = (np.arange(1, 14, dtype=np.int32) * 5) % VOCAB
    h = eng.submit(long_p, 6, temperature=0.5, seed=7)
    eng.run_until_idle()
    want = np.asarray(windowed.generate(
        h.prompt[None], 6, max_len=28, rolling=True,
        temperature=0.5, rng=h.key))[0]
    np.testing.assert_array_equal(h.result(), want)
    _assert_no_block_leaks(eng)


@pytest.mark.paged
def test_paged_spec_greedy_identity_and_sampled_determinism(fitted):
    """Speculation on the paged pool: greedy committed chains stay the
    target argmax chain (== generate), and sampled rows reproduce the
    dense speculative engine's draws exactly (same key-fold schedule —
    the block tables change storage, not randomness)."""
    eng = ServingEngine(fitted, num_slots=3, max_len=24, paged=True,
                        block_size=4, spec_draft=fitted, spec_len=3)
    g = eng.submit(PROMPT, 8)
    s = eng.submit(np.array([5, 6, 7], np.int32), 8, temperature=0.7,
                   seed=5)
    eng.run_until_idle()
    np.testing.assert_array_equal(g.result(), _want(fitted, g, max_len=24))
    dense = ServingEngine(fitted, num_slots=3, max_len=24,
                          spec_draft=fitted, spec_len=3)
    s2 = dense.submit(np.array([5, 6, 7], np.int32), 8, temperature=0.7,
                      seed=5)
    dense.run_until_idle()
    np.testing.assert_array_equal(s.result(), s2.result())
    assert eng.stats["drafted"] > 0
    _assert_no_block_leaks(eng)


@pytest.mark.paged
def test_paged_prefix_sharing_reuses_blocks_exactly(fitted):
    """The tentpole contract: a second admission sharing a full-block
    prefix walks the trie, SHARES the matched blocks (allocation shrinks
    by exactly the reuse — byte-accounted, not just faster), prefills
    only its suffix, and still emits generate-identical tokens."""
    eng = ServingEngine(fitted, num_slots=2, max_len=28, paged=True,
                        block_size=4)
    prefix = (np.arange(12) % VOCAB).astype(np.int32)      # 3 full blocks
    h1 = eng.submit(np.concatenate([prefix, [1, 2]]).astype(np.int32), 6)
    eng.run_until_idle()
    alloc1 = eng.stats["blocks_allocated"]
    pf1 = eng.stats["prefill_tokens"]
    h2 = eng.submit(np.concatenate([prefix, [5, 6]]).astype(np.int32), 6,
                    temperature=0.5, seed=3)
    eng.run_until_idle()
    np.testing.assert_array_equal(h1.result(), _want(fitted, h1,
                                                     max_len=28))
    np.testing.assert_array_equal(h2.result(), _want(fitted, h2,
                                                     max_len=28))
    assert eng.stats["prefix_hits"] == 1
    assert eng.stats["prefix_hit_tokens"] == 12
    assert eng.stats["blocks_reused"] == 3
    # h2 allocated 3 fewer fresh blocks than a cold admission would
    assert (eng.stats["blocks_allocated"] - alloc1
            == alloc1 - eng.stats["blocks_reused"])
    # and prefilled only its 2-token suffix
    assert eng.stats["prefill_tokens"] - pf1 == 2
    _assert_no_block_leaks(eng)


@pytest.mark.paged
def test_paged_cow_copies_partial_boundary_block(fitted):
    """A prompt matching a cached chain PARTIALLY into a block gets a
    copy-on-write duplicate: the original stays shared/cached, the new
    request writes its divergent suffix into its own copy — outputs
    exact on both sides."""
    eng = ServingEngine(fitted, num_slots=2, max_len=28, paged=True,
                        block_size=4)
    p1 = (np.arange(10) % VOCAB).astype(np.int32)  # 2 full + 2 boundary
    h1 = eng.submit(np.concatenate([p1, [1, 2]]).astype(np.int32), 4)
    eng.run_until_idle()
    h2 = eng.submit(np.concatenate([p1, [9, 9]]).astype(np.int32), 4)
    eng.run_until_idle()
    np.testing.assert_array_equal(h2.result(), _want(fitted, h2,
                                                     max_len=28))
    assert eng.stats["cow_copies"] == 1
    assert eng.stats["prefix_hit_tokens"] == 10   # 8 shared + 2 copied
    _assert_no_block_leaks(eng)


@pytest.mark.paged
def test_paged_chunked_prefill_and_prefix_hit_skips_chunks(fitted):
    """Paged chunked prefill writes straight into the request's blocks
    (no staging — they are private until the final chunk installs the
    table), stays generate-identical, and a later admission hitting the
    long prompt's prefix skips the chunked path entirely (suffix fits a
    bucket)."""
    eng = ServingEngine(fitted, num_slots=2, max_len=32, paged=True,
                        block_size=4, prefill_chunk=4)
    long_p = (np.arange(1, 14, dtype=np.int32) * 3) % VOCAB  # 13 tokens
    h = eng.submit(long_p, 8)
    h2 = eng.submit(PROMPT, 4)
    eng.run_until_idle()
    assert eng.stats["prefill_chunks"] == 4
    np.testing.assert_array_equal(h.result(), _want(fitted, h, max_len=32))
    np.testing.assert_array_equal(h2.result(), _want(fitted, h2,
                                                     max_len=32))
    chunks0 = eng.stats["prefill_chunks"]
    h3 = eng.submit(np.concatenate([long_p[:12], [9, 9]]).astype(np.int32),
                    6)
    eng.run_until_idle()
    np.testing.assert_array_equal(h3.result(), _want(fitted, h3,
                                                     max_len=32))
    assert eng.stats["prefill_chunks"] == chunks0  # hit → bucket path
    assert eng.stats["prefix_hits"] >= 1
    _assert_no_block_leaks(eng)


@pytest.mark.paged
def test_paged_capacity_pressure_evicts_and_backpressures(fitted):
    """A deliberately tiny arena: admissions queue when live requests
    hold every block, cached refcount-0 chains are LRU-evicted to make
    room, every request still completes exactly, and the pool returns to
    baseline."""
    eng = ServingEngine(fitted, num_slots=4, max_len=24, paged=True,
                        block_size=4, kv_blocks=8).warmup()
    hs = [eng.submit((np.arange(i + 1, i + 5) % VOCAB).astype(np.int32),
                     6, seed=i) for i in range(6)]
    eng.run_until_idle()
    for h in hs:
        np.testing.assert_array_equal(h.result(), _want(fitted, h,
                                                        max_len=24))
    assert eng.stats["blocks_evicted"] > 0
    _assert_no_block_leaks(eng)


@pytest.mark.paged
def test_paged_transfer_discipline_zero_h2d_one_d2h(fitted):
    """PR 9's decode transfer contract survives paging: block tables are
    device-resident (installed by the prefill program, nulled by the
    retire program), so a decode-only iteration still uploads nothing
    and reads back exactly the sampled token row."""
    eng = ServingEngine(fitted, num_slots=2, max_len=24, paged=True,
                        block_size=4).warmup()
    h = eng.submit(PROMPT, 14)
    eng.step()
    orig = eng._decode_fn

    def checked(*args):
        leaves = jax.tree_util.tree_leaves(args)
        assert all(isinstance(a, jax.Array) for a in leaves), \
            "paged decode step received a host array (implicit h2d)"
        return orig(*args)

    eng._decode_fn = checked
    h0, d0 = eng.stats["h2d_transfers"], eng.stats["d2h_transfers"]
    for _ in range(6):
        eng.step()
    assert eng.stats["h2d_transfers"] - h0 == 0
    assert eng.stats["d2h_transfers"] - d0 == 6
    eng.run_until_idle()
    np.testing.assert_array_equal(h.result(), _want(fitted, h, max_len=24))


@pytest.mark.paged
def test_paged_warmup_precompiles_every_program(fitted, monkeypatch):
    """warmup() on a paged engine compiles the block-table decode, every
    bucket's paged prefill, the in-arena chunk programs, and the COW
    copy — live traffic (prefix hits and COW included) re-traces
    nothing."""
    calls = []
    orig = decode._forward

    def counting(*a, **k):
        calls.append(1)
        return orig(*a, **k)

    monkeypatch.setattr(decode, "_forward", counting)
    eng = ServingEngine(fitted, num_slots=2, max_len=24, paged=True,
                        block_size=4, prefill_chunk=4,
                        prefills_per_step=2).warmup()
    traced = len(calls)
    assert traced > 0
    h1 = eng.submit(np.array([2, 3, 4], np.int32), 3)       # bucket batch
    h2 = eng.submit((np.arange(1, 12, dtype=np.int32)) % VOCAB, 3)  # chunks
    eng.run_until_idle()
    h3 = eng.submit((np.arange(1, 11, dtype=np.int32)) % VOCAB, 3)  # COW hit
    eng.run_until_idle()
    assert h1.done and h2.done and h3.done
    assert eng.stats["prefix_hits"] >= 1
    assert len(calls) == traced, "paged live traffic re-traced a program"


@pytest.mark.paged
def test_paged_respawn_clone_fresh_trie_same_arena(fitted):
    """respawn_clone() carries the paged knobs and arena SHAPE but builds
    a FRESH trie + allocator: cached chains index the dead pool's arena
    contents, which the clone does not share."""
    eng = ServingEngine(fitted, num_slots=2, max_len=24, paged=True,
                        block_size=4, kv_blocks=10)
    h = eng.submit(PROMPT, 4)
    eng.run_until_idle()
    assert h.done and eng._pool.cached_blocks() > 0
    clone = eng.respawn_clone()
    assert clone.paged and clone.block_size == 4 and clone.kv_blocks == 10
    assert clone._pool is not eng._pool
    assert clone._pool.cached_blocks() == 0
    assert clone.stats["prefix_hits"] == 0
    assert len(clone._pool.free) == 10
    h2 = clone.submit(PROMPT, 4)
    clone.run_until_idle()
    np.testing.assert_array_equal(h2.result(), h.result())


@pytest.mark.paged
def test_paged_knob_validation(fitted):
    with pytest.raises(ValueError, match="paged"):
        ServingEngine(fitted, num_slots=1, max_len=24, paged=True,
                      prefill_mode="eager")
    with pytest.raises(ValueError, match="block_size"):
        ServingEngine(fitted, num_slots=1, max_len=24, paged=True,
                      block_size=0)
    with pytest.raises(ValueError, match="kv_blocks"):
        ServingEngine(fitted, num_slots=1, max_len=24, paged=True,
                      block_size=4, kv_blocks=2)   # can't hold one request


@pytest.mark.paged
def test_paged_default_off_is_dense(fitted):
    """paged=False (the default) builds the exact dense engine: no pool,
    no trie, per-slot cache rows, and zeroed paged stats."""
    eng = ServingEngine(fitted, num_slots=2, max_len=24)
    assert not eng.paged and eng._pool is None and eng.kv_blocks is None
    assert eng.kv_blocks_in_use is None
    assert eng.caches[2]["k"].shape[0] == 2     # (num_slots, max_len, ...)
    h = eng.submit(PROMPT, 6)
    eng.run_until_idle()
    np.testing.assert_array_equal(h.result(), _want(fitted, h, max_len=24))
    assert eng.stats["blocks_allocated"] == 0
    assert eng.stats["prefix_hits"] == 0


@pytest.mark.paged
def test_paged_pool_byte_accounting(fitted):
    """kv_pool_bytes counts the arena (blocks + the null block), shrinks
    with kv_blocks, and the int8 arena pages codes + scales identically
    (fewer bytes than the f32 arena at the same block count)."""
    from distkeras_tpu.core import quant as quant_mod
    big = ServingEngine(fitted, num_slots=2, max_len=24, paged=True,
                        block_size=4)
    small = ServingEngine(fitted, num_slots=2, max_len=24, paged=True,
                          block_size=4, kv_blocks=6)
    assert small.kv_pool_bytes < big.kv_pool_bytes
    assert small.stats["kv_pool_bytes"] == small.kv_pool_bytes
    q8 = ServingEngine(fitted, num_slots=2, max_len=24, paged=True,
                       block_size=4, kv_dtype="int8")
    assert q8.kv_pool_bytes < big.kv_pool_bytes
    blk = quant_mod.kv_block_bytes(big.caches, big.block_size)
    assert blk * (big.kv_blocks + 1) == big.kv_pool_bytes
    # and the int8 paged engine still decodes exactly like the dense
    # int8 engine (lossy vs f32, but layout-exact between pools)
    h = q8.submit(PROMPT, 6)
    q8.run_until_idle()
    dense8 = ServingEngine(fitted, num_slots=2, max_len=24,
                           kv_dtype="int8")
    h2 = dense8.submit(PROMPT, 6)
    dense8.run_until_idle()
    np.testing.assert_array_equal(h.result(), h2.result())
    _assert_no_block_leaks(q8)


@pytest.mark.paged
def test_paged_same_iteration_batch_admissions_exact(fitted):
    """prefills_per_step > 1: same-pass admissions sharing a prefix do
    NOT cross-match (the epoch guard — a same-pass matcher could land in
    a bucket group dispatched before the writer's), but every output is
    still exact and later admissions DO hit the published chains."""
    eng = ServingEngine(fitted, num_slots=4, max_len=28, paged=True,
                        block_size=4, prefills_per_step=4)
    prefix = (np.arange(8) % VOCAB).astype(np.int32)
    hs = [eng.submit(np.concatenate([prefix, [i]]).astype(np.int32), 5,
                     seed=i) for i in range(4)]
    eng.run_until_idle()
    assert eng.stats["prefix_hits"] == 0          # same pass: no matches
    for h in hs:
        np.testing.assert_array_equal(h.result(), _want(fitted, h,
                                                        max_len=28))
    h5 = eng.submit(np.concatenate([prefix, [9]]).astype(np.int32), 5)
    eng.run_until_idle()
    np.testing.assert_array_equal(h5.result(), _want(fitted, h5,
                                                     max_len=28))
    assert eng.stats["prefix_hits"] == 1          # later pass: hit
    _assert_no_block_leaks(eng)
