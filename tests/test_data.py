"""Dataset / transformer tests (reference pipeline semantics)."""

import numpy as np
import pytest

from distkeras_tpu.data import (Dataset, MinMaxTransformer, DenseTransformer,
                                ReshapeTransformer, OneHotTransformer,
                                LabelIndexTransformer)
from distkeras_tpu.data.datasets import load_mnist, load_atlas_higgs


def make_ds(n=20):
    rng = np.random.default_rng(0)
    return Dataset({"features": rng.uniform(0, 255, (n, 12)).astype(np.float32),
                    "label": rng.integers(0, 3, n)})


def test_dataset_basic_ops():
    ds = make_ds(20)
    assert len(ds) == 20
    assert set(ds.columns) == {"features", "label"}
    ds2 = ds.with_column("extra", np.zeros(20))
    assert "extra" in ds2 and "extra" not in ds
    left, right = ds.split(0.75, seed=0)
    assert len(left) == 15 and len(right) == 5


def test_dataset_shuffle_preserves_pairs():
    ds = make_ds(50)
    shuffled = ds.shuffle(seed=1)
    # pairs stay aligned: sort both by first feature and compare labels
    orig = sorted(zip(ds["features"][:, 0].tolist(), ds["label"].tolist()))
    shuf = sorted(zip(shuffled["features"][:, 0].tolist(),
                      shuffled["label"].tolist()))
    assert orig == shuf


def test_shard_and_batches():
    ds = make_ds(21)
    # non-divisible rows refuse by default: neither silent drop nor silent
    # duplication (round-3 VERDICT weak #7)
    with pytest.raises(ValueError, match="drop_remainder=True"):
        ds.repartition(4).shard()
    with pytest.raises(ValueError, match="mutually exclusive"):
        ds.shard(4, drop_remainder=True, pad=True)
    # explicit wrap-pad: 21 rows over 4 shards → 6/shard, no row lost
    shards = ds.repartition(4).shard(pad=True)
    assert shards["features"].shape == (4, 6, 12)
    flat = shards["features"].reshape(-1, 12)
    np.testing.assert_array_equal(flat[:21], ds["features"])
    np.testing.assert_array_equal(flat[21:], ds["features"][:3])  # wrapped
    # explicit opt-in truncation matches the old behavior
    dropped = ds.repartition(4).shard(drop_remainder=True)
    assert dropped["features"].shape == (4, 5, 12)
    # evenly divisible: identical either way, no copy path
    even = make_ds(20).shard(4)
    assert even["features"].shape == (4, 5, 12)
    with pytest.raises(ValueError):
        make_ds(3).shard(4)
    batches = ds.batches(4, ["features", "label"])
    assert batches["features"].shape == (5, 4, 12)
    with pytest.raises(ValueError):
        ds.batches(100, ["features"])


def test_minmax_transformer():
    ds = make_ds()
    out = MinMaxTransformer(0.0, 1.0, 0.0, 255.0).transform(ds)
    f = out["features"]
    assert f.min() >= 0.0 and f.max() <= 1.0


def test_reshape_onehot_labelindex():
    ds = make_ds()
    r = ReshapeTransformer(shape=(3, 4, 1)).transform(ds)
    assert r["features"].shape == (20, 3, 4, 1)
    oh = OneHotTransformer(3, input_col="label",
                           output_col="label_encoded").transform(ds)
    enc = oh["label_encoded"]
    assert enc.shape == (20, 3)
    np.testing.assert_array_equal(np.argmax(enc, -1), ds["label"])
    probs = np.eye(3, dtype=np.float32)[ds["label"]]
    withp = ds.with_column("prediction", probs)
    li = LabelIndexTransformer().transform(withp)
    np.testing.assert_array_equal(li["prediction_index"], ds["label"])


def test_dense_transformer_dtype():
    ds = make_ds()
    out = DenseTransformer().transform(ds)
    assert out["features"].dtype == np.float32


def test_synthetic_datasets_learnable_structure():
    train, test = load_mnist(n_train=512, n_test=128)
    assert train["features"].shape == (512, 784)
    assert train["label"].max() <= 9
    # deterministic across calls
    t2, _ = load_mnist(n_train=512, n_test=128)
    np.testing.assert_array_equal(train["features"], t2["features"])
    htrain, _ = load_atlas_higgs(n_train=256, n_test=64)
    assert htrain["features"].shape == (256, 28)
    assert set(np.unique(htrain["label"])) <= {0, 1}


def test_load_digits_real_data():
    from distkeras_tpu.data.datasets import load_digits
    train, test = load_digits(n_train=1500)
    assert train["features"].shape == (1500, 64)
    assert test["features"].shape == (297, 64)  # 1797 total, real sklearn set
    assert 0.0 <= train["features"].min() and train["features"].max() <= 255.0
    assert set(np.unique(train["label"])) <= set(range(10))
    # deterministic split, disjoint-by-construction halves
    t2, _ = load_digits(n_train=1500)
    np.testing.assert_array_equal(train["features"], t2["features"])
    # n_test caps the test split
    _, small = load_digits(n_train=1500, n_test=100)
    assert small["features"].shape == (100, 64)


def test_read_csv(tmp_path):
    p = tmp_path / "higgs.csv"
    p.write_text("f1,f2,label,f3\n"
                 "1.0,2.0,0,3.5\n"
                 "4.0,5.0,1,6.5\n"
                 "7.0,8.0,0,9.5\n")
    from distkeras_tpu.data.datasets import read_csv
    ds = read_csv(str(p), label_column="label")
    assert ds["features"].shape == (3, 3)
    np.testing.assert_allclose(ds["features"][1], [4.0, 5.0, 6.5])
    np.testing.assert_array_equal(ds["label"], [0, 1, 0])

    sub = read_csv(str(p), label_column="label", feature_columns=["f3", "f1"])
    np.testing.assert_allclose(sub["features"][0], [3.5, 1.0])

    import pytest
    with pytest.raises(ValueError, match="label column"):
        read_csv(str(p), label_column="nope")


def test_read_csv_edge_cases(tmp_path):
    import pytest
    from distkeras_tpu.data.datasets import read_csv
    single = tmp_path / "one.csv"
    single.write_text("a,b,label\n1.0,2.0,1\n")
    ds = read_csv(str(single), label_column="label")
    assert ds["features"].shape == (1, 2)
    with pytest.raises(ValueError, match="empty"):
        read_csv(str(single), label_column="label", feature_columns=[])


def test_read_csv_native_matches_genfromtxt(tmp_path, monkeypatch):
    """Differential test: the C++ csvloader path must be observably identical
    to the np.genfromtxt fallback (csrc/csvloader.cpp's contract)."""
    from distkeras_tpu.data import datasets

    if datasets._native_csv is None:
        pytest.skip("native csvloader not built")

    # CRLF line endings, blank lines, missing field (-> NaN feature),
    # scientific notation, negative values, whitespace padding
    p = tmp_path / "mixed.csv"
    p.write_bytes(b"x1,x2,label\r\n"
                  b"1.5, -2e-3 ,0\r\n"
                  b"\r\n"
                  b",4.25,1\r\n"
                  b"3.75,0.5,1\r\n")

    def load(native: bool):
        if not native:
            monkeypatch.setattr(datasets, "_native_csv", None)
        ds = datasets.read_csv(str(p), label_column="label")
        monkeypatch.undo()
        return ds

    nat, ref = load(True), load(False)
    np.testing.assert_array_equal(np.isnan(nat["features"]),
                                  np.isnan(ref["features"]))
    np.testing.assert_allclose(np.nan_to_num(nat["features"]),
                               np.nan_to_num(ref["features"]))
    np.testing.assert_array_equal(nat["label"], ref["label"])
    assert np.isnan(nat["features"][1, 0])  # the missing field

    # quoted fields must fall back (native path would misparse) — behavior
    # identical because the gate routes them to genfromtxt
    q = tmp_path / "quoted.csv"
    q.write_text('a,label\n"1.0",0\n"2.0",1\n')
    def gate(raw, names, delim=","):
        return datasets._native_parse(raw, names, delim,
                                      raw.find(b"\n") + 1)

    assert gate(q.read_bytes(), ["a", "label"]) is None

    # header-level gates (checked before the body is even read):
    # non-identifier names, duplicates (genfromtxt renames to 'a','a_1'),
    # numpy's excludelist ('print' -> 'print_'), whitespace delimiters
    assert not datasets._header_eligible(["my col", "label"], ",")
    assert not datasets._header_eligible(["a", "a", "label"], ",")
    assert not datasets._header_eligible(["print", "label"], ",")
    assert not datasets._header_eligible(["a", "label"], " ")
    assert datasets._header_eligible(["a", "label"], ",")

    # body-level gates: hex floats, underscore literals (strtod-vs-float()
    # divergences), non-ASCII bytes (fallback raises UnicodeDecodeError;
    # native must not mask that), tabs (genfromtxt line-strip rules), and
    # bare CR (universal newlines treat it as a row separator)
    assert gate(b"a,label\n0x10,0\n", ["a", "label"]) is None
    assert gate(b"a,label\n1_5,0\n", ["a", "label"]) is None
    assert gate(b"a,label\n1,0\n\xff,1\n", ["a", "label"]) is None
    assert gate(b"a,label\n1,0\n\t\n2,1\n", ["a", "label"]) is None
    assert gate(b"a,label\n1,0\r2,1\n", ["a", "label"]) is None
    # duplicate-name read_csv behaves identically either way (header gate)
    d2 = tmp_path / "dup2.csv"
    d2.write_text("print,label\n1,0\n")
    pr = datasets.read_csv(str(d2), label_column="label",
                           feature_columns=["print_"])
    np.testing.assert_array_equal(pr["features"], [[1.0]])
    ws = tmp_path / "ws.csv"
    ws.write_bytes(b"a,label\n1,0\n   \n2,1\n")
    wnat = datasets.read_csv(str(ws), label_column="label")
    monkeypatch.setattr(datasets, "_native_csv", None)
    wref = datasets.read_csv(str(ws), label_column="label")
    monkeypatch.undo()
    np.testing.assert_array_equal(wnat["features"], wref["features"])
    assert len(wnat) == 2
    d = tmp_path / "dup.csv"
    d.write_text("a,a,label\n1,2,0\n")
    dup = datasets.read_csv(str(d), label_column="label")
    np.testing.assert_array_equal(dup["features"], [[1.0, 2.0]])

    # >63-char numeric field takes the heap-buffer path, still exact
    v = "0" * 70 + "1.5"
    lf = tmp_path / "long.csv"
    lf.write_text(f"a,label\n{v},1\n")
    got = datasets.read_csv(str(lf), label_column="label")
    assert got["features"][0, 0] == np.float32(float(v))


def test_read_csv_native_big_multithreaded(tmp_path):
    """> 64 KiB body exercises the multi-chunk threaded parse; values must
    round-trip exactly and a ragged row must raise."""
    from distkeras_tpu.data import datasets
    import pytest

    if datasets._native_csv is None:
        pytest.skip("native csvloader not built")

    rng = np.random.default_rng(3)
    vals = rng.standard_normal((4000, 6))
    labels = rng.integers(0, 2, 4000)
    lines = ["c0,c1,c2,c3,c4,c5,label"]
    lines += [",".join(repr(float(v)) for v in row) + f",{y}"
              for row, y in zip(vals, labels)]
    p = tmp_path / "big.csv"
    p.write_text("\n".join(lines) + "\n")
    assert p.stat().st_size > (1 << 16)

    ds = datasets.read_csv(str(p), label_column="label")
    np.testing.assert_array_equal(ds["features"],
                                  vals.astype(np.float32))
    np.testing.assert_array_equal(ds["label"], labels)

    bad = tmp_path / "ragged.csv"
    bad.write_text("a,b,label\n1,2,0\n1,2\n")
    with pytest.raises(ValueError, match="fields"):
        datasets.read_csv(str(bad), label_column="label")


def test_ingest_mnist_idx_roundtrip(tmp_path, monkeypatch):
    """scripts/ingest_mnist_idx.py: fake IDX files -> mnist.npz ->
    load_mnist serves the REAL pixels (data upgrade with zero code
    changes, gzip and raw variants both parsed)."""
    import gzip
    import os
    import struct
    import subprocess
    import sys

    import numpy as np

    rng = np.random.default_rng(0)
    x_tr = rng.integers(0, 256, (32, 28, 28)).astype(np.uint8)
    y_tr = rng.integers(0, 10, 32).astype(np.uint8)
    x_te = rng.integers(0, 256, (8, 28, 28)).astype(np.uint8)
    y_te = rng.integers(0, 10, 8).astype(np.uint8)

    src = tmp_path / "idx"
    src.mkdir()

    def write_images(name, arr, gz):
        blob = struct.pack(">IIII", 2051, len(arr), 28, 28) + arr.tobytes()
        p = src / (name + (".gz" if gz else ""))
        p.write_bytes(gzip.compress(blob) if gz else blob)

    def write_labels(name, arr, gz):
        blob = struct.pack(">II", 2049, len(arr)) + arr.tobytes()
        p = src / (name + (".gz" if gz else ""))
        p.write_bytes(gzip.compress(blob) if gz else blob)

    write_images("train-images-idx3-ubyte", x_tr, gz=True)   # .gz variant
    write_labels("train-labels-idx1-ubyte", y_tr, gz=False)  # raw variant
    write_images("t10k-images-idx3-ubyte", x_te, gz=False)
    write_labels("t10k-labels-idx1-ubyte", y_te, gz=True)

    out = tmp_path / "data"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "ingest_mnist_idx.py"),
         str(src), "--out", str(out)],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    assert (out / "mnist.npz").exists()

    from distkeras_tpu.data import datasets as dsmod
    monkeypatch.setattr(dsmod, "_DATA_DIRS", [str(out)])
    assert dsmod.has_real_data("mnist")
    train, test = dsmod.load_mnist(n_train=32, n_test=8)
    np.testing.assert_array_equal(
        train["features"], x_tr.reshape(-1, 784).astype(np.float32))
    np.testing.assert_array_equal(train["label"], y_tr.astype(np.int64))
    np.testing.assert_array_equal(test["label"], y_te.astype(np.int64))
