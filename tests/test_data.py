"""Dataset / transformer tests (reference pipeline semantics)."""

import numpy as np
import pytest

from distkeras_tpu.data import (Dataset, MinMaxTransformer, DenseTransformer,
                                ReshapeTransformer, OneHotTransformer,
                                LabelIndexTransformer)
from distkeras_tpu.data.datasets import load_mnist, load_atlas_higgs


def make_ds(n=20):
    rng = np.random.default_rng(0)
    return Dataset({"features": rng.uniform(0, 255, (n, 12)).astype(np.float32),
                    "label": rng.integers(0, 3, n)})


def test_dataset_basic_ops():
    ds = make_ds(20)
    assert len(ds) == 20
    assert set(ds.columns) == {"features", "label"}
    ds2 = ds.with_column("extra", np.zeros(20))
    assert "extra" in ds2 and "extra" not in ds
    left, right = ds.split(0.75, seed=0)
    assert len(left) == 15 and len(right) == 5


def test_dataset_shuffle_preserves_pairs():
    ds = make_ds(50)
    shuffled = ds.shuffle(seed=1)
    # pairs stay aligned: sort both by first feature and compare labels
    orig = sorted(zip(ds["features"][:, 0].tolist(), ds["label"].tolist()))
    shuf = sorted(zip(shuffled["features"][:, 0].tolist(),
                      shuffled["label"].tolist()))
    assert orig == shuf


def test_shard_and_batches():
    ds = make_ds(21)
    shards = ds.repartition(4).shard()
    assert shards["features"].shape == (4, 5, 12)
    batches = ds.batches(4, ["features", "label"])
    assert batches["features"].shape == (5, 4, 12)
    with pytest.raises(ValueError):
        ds.batches(100, ["features"])


def test_minmax_transformer():
    ds = make_ds()
    out = MinMaxTransformer(0.0, 1.0, 0.0, 255.0).transform(ds)
    f = out["features"]
    assert f.min() >= 0.0 and f.max() <= 1.0


def test_reshape_onehot_labelindex():
    ds = make_ds()
    r = ReshapeTransformer(shape=(3, 4, 1)).transform(ds)
    assert r["features"].shape == (20, 3, 4, 1)
    oh = OneHotTransformer(3, input_col="label",
                           output_col="label_encoded").transform(ds)
    enc = oh["label_encoded"]
    assert enc.shape == (20, 3)
    np.testing.assert_array_equal(np.argmax(enc, -1), ds["label"])
    probs = np.eye(3, dtype=np.float32)[ds["label"]]
    withp = ds.with_column("prediction", probs)
    li = LabelIndexTransformer().transform(withp)
    np.testing.assert_array_equal(li["prediction_index"], ds["label"])


def test_dense_transformer_dtype():
    ds = make_ds()
    out = DenseTransformer().transform(ds)
    assert out["features"].dtype == np.float32


def test_synthetic_datasets_learnable_structure():
    train, test = load_mnist(n_train=512, n_test=128)
    assert train["features"].shape == (512, 784)
    assert train["label"].max() <= 9
    # deterministic across calls
    t2, _ = load_mnist(n_train=512, n_test=128)
    np.testing.assert_array_equal(train["features"], t2["features"])
    htrain, _ = load_atlas_higgs(n_train=256, n_test=64)
    assert htrain["features"].shape == (256, 28)
    assert set(np.unique(htrain["label"])) <= {0, 1}


def test_load_digits_real_data():
    from distkeras_tpu.data.datasets import load_digits
    train, test = load_digits(n_train=1500)
    assert train["features"].shape == (1500, 64)
    assert test["features"].shape == (297, 64)  # 1797 total, real sklearn set
    assert 0.0 <= train["features"].min() and train["features"].max() <= 255.0
    assert set(np.unique(train["label"])) <= set(range(10))
    # deterministic split, disjoint-by-construction halves
    t2, _ = load_digits(n_train=1500)
    np.testing.assert_array_equal(train["features"], t2["features"])
    # n_test caps the test split
    _, small = load_digits(n_train=1500, n_test=100)
    assert small["features"].shape == (100, 64)


def test_read_csv(tmp_path):
    p = tmp_path / "higgs.csv"
    p.write_text("f1,f2,label,f3\n"
                 "1.0,2.0,0,3.5\n"
                 "4.0,5.0,1,6.5\n"
                 "7.0,8.0,0,9.5\n")
    from distkeras_tpu.data.datasets import read_csv
    ds = read_csv(str(p), label_column="label")
    assert ds["features"].shape == (3, 3)
    np.testing.assert_allclose(ds["features"][1], [4.0, 5.0, 6.5])
    np.testing.assert_array_equal(ds["label"], [0, 1, 0])

    sub = read_csv(str(p), label_column="label", feature_columns=["f3", "f1"])
    np.testing.assert_allclose(sub["features"][0], [3.5, 1.0])

    import pytest
    with pytest.raises(ValueError, match="label column"):
        read_csv(str(p), label_column="nope")


def test_read_csv_edge_cases(tmp_path):
    import pytest
    from distkeras_tpu.data.datasets import read_csv
    single = tmp_path / "one.csv"
    single.write_text("a,b,label\n1.0,2.0,1\n")
    ds = read_csv(str(single), label_column="label")
    assert ds["features"].shape == (1, 2)
    with pytest.raises(ValueError, match="empty"):
        read_csv(str(single), label_column="label", feature_columns=[])
