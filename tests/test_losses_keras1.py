"""The widened Keras-1 loss-name family (core/losses.py).

The reference accepted any Keras loss string through ``loss=`` (SURVEY.md
§2.1 rows 1-11); these pin the added names against hand computations /
closed forms on small arrays.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.core.losses import get_loss


Y = jnp.asarray(np.array([[1.0, 0.0], [0.5, 0.5]]))
P = jnp.asarray(np.array([[0.8, 0.2], [0.25, 0.75]]))


def test_kld_matches_hand_sum():
    got = float(get_loss("kld")(Y, P))
    rows = [1.0 * np.log(1.0 / 0.8),
            0.5 * np.log(0.5 / 0.25) + 0.5 * np.log(0.5 / 0.75)]
    # row 0's zero entry contributes eps-level noise only
    np.testing.assert_allclose(got, np.mean(rows), rtol=1e-4, atol=1e-4)


def test_hinge_conventions():
    yt = jnp.asarray([[1.0, -1.0]])
    yp = jnp.asarray([[0.3, 0.4]])
    np.testing.assert_allclose(float(get_loss("hinge")(yt, yp)),
                               ((1 - 0.3) + (1 + 0.4)) / 2, rtol=1e-6)
    # 0/1 labels convert to -1/1
    yt01 = jnp.asarray([[1.0, 0.0]])
    np.testing.assert_allclose(float(get_loss("hinge")(yt01, yp)),
                               ((1 - 0.3) + (1 + 0.4)) / 2, rtol=1e-6)
    np.testing.assert_allclose(
        float(get_loss("squared_hinge")(yt, yp)),
        ((1 - 0.3) ** 2 + (1 + 0.4) ** 2) / 2, rtol=1e-6)


def test_poisson_and_msle_and_mape():
    yt = jnp.asarray([[2.0, 0.5]])
    yp = jnp.asarray([[1.5, 1.0]])
    np.testing.assert_allclose(
        float(get_loss("poisson")(yt, yp)),
        np.mean([1.5 - 2.0 * np.log(1.5), 1.0 - 0.5 * np.log(1.0)]),
        rtol=1e-6)
    np.testing.assert_allclose(
        float(get_loss("msle")(yt, yp)),
        np.mean((np.log1p([1.5, 1.0]) - np.log1p([2.0, 0.5])) ** 2),
        rtol=1e-6)
    np.testing.assert_allclose(
        float(get_loss("mape")(yt, yp)),
        100 * np.mean([0.5 / 2.0, 0.5 / 0.5]), rtol=1e-6)


def test_cosine_proximity_extremes():
    # Keras-1 reduction: mean over ALL elements, so a perfectly aligned
    # dim-2 pair scores -1/2, not -1 (ADVICE r4: gradient-scale parity for
    # migrated configs)
    a = jnp.asarray([[1.0, 0.0]])
    assert float(get_loss("cosine")(a, a)) == pytest.approx(-0.5)
    assert float(get_loss("cosine")(a, jnp.asarray([[0.0, 1.0]]))) == \
        pytest.approx(0.0, abs=1e-6)
    assert float(get_loss("cosine")(a, -a)) == pytest.approx(0.5)
    # row-count invariance of the global mean: duplicating rows is a no-op
    two = jnp.concatenate([a, a])
    assert float(get_loss("cosine")(two, two)) == pytest.approx(-0.5)


def test_all_new_names_resolve_and_reduce_to_scalar():
    for name in ("mape", "msle", "kld", "hinge", "squared_hinge",
                 "poisson", "cosine_proximity"):
        v = get_loss(name)(Y, P)
        assert v.shape == (), name
        assert np.isfinite(float(v)), name
