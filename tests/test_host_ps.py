"""Tests for the host-parameter-server path: wire protocol, PS apply rules
over sockets, and end-to-end ``execution='host_ps'`` training — the
semantically-exact async engine (true hogwild interleaving on loopback, the
analogue of the reference's Spark ``local[*]`` simulation; SURVEY.md §4)."""

import socket
import threading

import numpy as np
import pytest

from distkeras_tpu import (Sequential, Dense, ADAG, DOWNPOUR, AEASGD, EAMSGD,
                           DynSGD, Dataset, OneHotTransformer)
from distkeras_tpu import networking
from distkeras_tpu.parameter_servers import (
    DeltaParameterServer, ADAGParameterServer, DynSGDParameterServer,
    SocketParameterServer)

NUM_CLASSES = 4


def make_dataset(n=2048, d=16, seed=0):
    rng = np.random.default_rng(seed)
    protos = rng.uniform(-1, 1, (NUM_CLASSES, d))
    labels = rng.integers(0, NUM_CLASSES, n)
    x = (protos[labels] + 0.3 * rng.standard_normal((n, d))).astype(np.float32)
    ds = Dataset({"features": x, "label": labels.astype(np.int64)})
    return OneHotTransformer(NUM_CLASSES, input_col="label",
                             output_col="label_encoded").transform(ds)


def make_model():
    return Sequential([Dense(32, activation="relu"),
                       Dense(NUM_CLASSES, activation="softmax")],
                      input_shape=(16,), compute_dtype="float32")


# ---------------------------------------------------------------------------
# wire protocol
# ---------------------------------------------------------------------------

def test_wire_roundtrip_structures():
    msg = {
        "weights": [np.arange(6, dtype=np.float32).reshape(2, 3),
                    np.ones((4,), np.float64)],
        "clock": 7,
        "name": "worker-0",
        "nested": {"t": (1, 2.5, None), "flag": True},
    }
    out = networking.decode_message(networking.encode_message(msg))
    assert out["clock"] == 7 and out["name"] == "worker-0"
    assert out["nested"]["t"] == (1, 2.5, None)
    assert out["nested"]["flag"] is True
    np.testing.assert_array_equal(out["weights"][0], msg["weights"][0])
    assert out["weights"][1].dtype == np.float64


def test_wire_rejects_garbage():
    with pytest.raises(ValueError):
        networking.decode_message(b"XXXX" + b"\x00" * 16)
    with pytest.raises(TypeError):
        networking.encode_message({"bad": object()})


def test_wire_rejects_mismatched_buffer_length():
    # a frame whose u64 buffer length disagrees with the header's dtype*shape
    # must be rejected before allocation (OOM guard on the PS host)
    good = networking.encode_message({"w": np.zeros((4,), np.float32)})
    tampered = bytearray(good)
    off = len(good) - 16 - 8  # u64 length prefix of the single 16-byte buffer
    tampered[off:off + 8] = (1 << 60).to_bytes(8, "little")
    with pytest.raises(ValueError, match="expects|Truncated"):
        networking.decode_message(bytes(tampered))


def test_wire_rejects_mismatched_buffer_length_python_path(monkeypatch):
    """Same OOM-guard, forced through the pure-Python decode path (the
    native codec, when built, otherwise intercepts with 'Truncated')."""
    monkeypatch.setattr(networking, "_native", None)
    good = networking.encode_message({"w": np.zeros((4,), np.float32)})
    tampered = bytearray(good)
    off = len(good) - 16 - 8
    tampered[off:off + 8] = (64).to_bytes(8, "little")  # wrong but in-range
    tampered += b"\x00" * 48  # pad so the lie is physically satisfiable
    with pytest.raises(ValueError, match="expects"):
        networking.decode_message(bytes(tampered))


def test_send_recv_over_socketpair():
    a, b = socket.socketpair()
    payload = {"delta": [np.random.default_rng(0).standard_normal((128, 64))]}
    t = threading.Thread(target=networking.send_data, args=(a, payload))
    t.start()
    out = networking.recv_data(b)
    t.join()
    np.testing.assert_array_equal(out["delta"][0], payload["delta"][0])
    a.close(); b.close()


# ---------------------------------------------------------------------------
# PS apply rules over real sockets
# ---------------------------------------------------------------------------

def _tiny_blob():
    return {"model": make_model().to_json(),
            "weights": [np.zeros((3,), np.float32)] * 1}


def test_socket_ps_pull_commit_delta():
    ps = DeltaParameterServer(_tiny_blob())
    server = SocketParameterServer(ps)
    server.start()
    try:
        sock = networking.connect("127.0.0.1", server.port)
        networking.send_opcode(sock, b"p")
        msg = networking.recv_data(sock)
        assert msg["clock"] == 0
        np.testing.assert_array_equal(msg["weights"][0], np.zeros(3))

        networking.send_opcode(sock, b"c")
        networking.send_data(sock, {"delta": [np.ones(3, np.float32)],
                                    "worker_id": 0, "clock": 0})
        networking.send_opcode(sock, b"p")
        msg = networking.recv_data(sock)
        assert msg["clock"] == 1
        np.testing.assert_array_equal(msg["weights"][0], np.ones(3))
        sock.close()
    finally:
        server.stop()


def test_adag_ps_normalizes_by_workers():
    ps = ADAGParameterServer(_tiny_blob(), num_workers=4)
    ps.handle_commit({"delta": [np.full(3, 8.0, np.float32)], "clock": 0})
    np.testing.assert_allclose(ps.center[0], np.full(3, 2.0))


def test_ps_applies_match_shared_rules():
    """The PS numpy commit loops must agree with parallel/rules.py — the
    single source of algorithm semantics both engines claim to implement."""
    from distkeras_tpu.parallel import rules
    rng = np.random.default_rng(3)
    w0 = [rng.standard_normal((5,)).astype(np.float32),
          rng.standard_normal((2, 3)).astype(np.float32)]
    delta = [rng.standard_normal(a.shape).astype(np.float32) for a in w0]

    def blob():
        return {"model": make_model().to_json(),
                "weights": [a.copy() for a in w0]}

    ps = DeltaParameterServer(blob())
    ps.handle_commit({"delta": delta, "clock": 0})
    expect = rules.delta_commit(w0, delta)
    for got, want in zip(ps.center, expect):
        np.testing.assert_allclose(got, np.asarray(want), rtol=1e-6)

    ps = ADAGParameterServer(blob(), num_workers=4)
    ps.handle_commit({"delta": delta, "clock": 0})
    expect = rules.adag_commit(w0, delta, 4)
    for got, want in zip(ps.center, expect):
        np.testing.assert_allclose(got, np.asarray(want), rtol=1e-6)

    ps = DynSGDParameterServer(blob())
    ps.num_updates = 3  # worker pulled at clock 1 → staleness 2
    ps.handle_commit({"delta": delta, "clock": 1})
    expect = rules.dynsgd_commit(w0, delta, 2.0)
    for got, want in zip(ps.center, expect):
        np.testing.assert_allclose(got, np.asarray(want), rtol=1e-6)


def test_dynsgd_ps_staleness_scaling():
    ps = DynSGDParameterServer(_tiny_blob())
    # first commit: staleness 0 → full apply
    ps.handle_commit({"delta": [np.ones(3, np.float32)], "clock": 0})
    np.testing.assert_allclose(ps.center[0], np.ones(3))
    # second commit still claims clock 0 → staleness 1 → halved
    ps.handle_commit({"delta": [np.ones(3, np.float32)], "clock": 0})
    np.testing.assert_allclose(ps.center[0], np.full(3, 1.5))


# ---------------------------------------------------------------------------
# end-to-end host_ps training
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("cls,kw", [
    (ADAG, {"communication_window": 4, "learning_rate": 0.1}),
    (DOWNPOUR, {"communication_window": 4, "learning_rate": 0.02}),
    (DynSGD, {"communication_window": 4, "learning_rate": 0.05}),
    (AEASGD, {"communication_window": 8, "rho": 1.0, "learning_rate": 0.05}),
    (EAMSGD, {"communication_window": 8, "rho": 1.0, "learning_rate": 0.05,
              "momentum": 0.9}),
])
def test_host_ps_training_learns(cls, kw):
    ds = make_dataset()
    t = cls(make_model(), num_workers=2, batch_size=32, num_epoch=2,
            label_col="label_encoded", execution="host_ps", **kw)
    fitted = t.train(ds)
    assert t.get_training_time() > 0
    assert len(t.get_history()) > 0
    # async scheduling is nondeterministic; assert learning, not exact curves
    hist = t.get_history()
    assert np.mean(hist[-5:]) < np.mean(hist[:5])
    preds = fitted.predict(ds["features"][:256])
    acc = float(np.mean(np.argmax(preds, axis=1) == ds["label"][:256]))
    assert acc > 0.6


def test_host_ps_rejects_non_ps_trainer():
    from distkeras_tpu import AveragingTrainer
    ds = make_dataset(n=256)
    t = AveragingTrainer(make_model(), num_workers=2, batch_size=32,
                         label_col="label_encoded", execution="host_ps")
    with pytest.raises(ValueError, match="host_ps"):
        t.train(ds)


def test_wire_dtype_bfloat16_roundtrip():
    """bf16 ndarrays survive the codec (ml_dtypes name-based dtype wire)."""
    import ml_dtypes
    a = np.arange(6, dtype=np.float32).reshape(2, 3).astype(ml_dtypes.bfloat16)
    out = networking.decode_message(networking.encode_message({"d": a}))
    assert out["d"].dtype == np.dtype(ml_dtypes.bfloat16)
    np.testing.assert_array_equal(out["d"].astype(np.float32),
                                  a.astype(np.float32))


def test_host_ps_bf16_wire_compression_learns():
    """ADAG over host_ps with bf16-compressed commits still trains, and the
    PS center stays f32."""
    ds = make_dataset()
    t = ADAG(make_model(), num_workers=2, batch_size=32, num_epoch=2,
             communication_window=4, label_col="label_encoded",
             learning_rate=0.1, execution="host_ps", wire_dtype="bfloat16")
    fitted = t.train(ds)
    preds = fitted.predict(ds["features"][:256])
    acc = float(np.mean(np.argmax(preds, axis=1) == ds["label"][:256]))
    assert acc > 0.6, acc
    assert all(w.dtype == np.float32 for w in fitted.get_weights())


def test_int8_commit_quantizes_with_error_feedback():
    """commit(wire_dtype='int8') ships int8 codes + f32 scales, returns the
    as-applied delta, and carries the quantization error into the next
    window (EF-SGD): eff = delta + prev_residual == applied + new_residual
    exactly, and |residual| <= scale/2 elementwise."""
    from distkeras_tpu import networking as net
    from distkeras_tpu.core.layers import Dense
    from distkeras_tpu.core.model import Sequential, serialize_model
    from distkeras_tpu.workers import DOWNPOURWorker
    import jax

    m = Sequential([Dense(2)], input_shape=(3,), compute_dtype="float32")
    blob = serialize_model(m, m.init(jax.random.PRNGKey(0)))
    wk = DOWNPOURWorker(blob, "sgd", "mse", "127.0.0.1", 1,
                        wire_dtype="int8")
    sent = []
    wk._sock = object()  # never touched by the stubs below
    orig_op, orig_send = net.send_opcode, net.send_data
    net.send_opcode = lambda s, op: None
    net.send_data = lambda s, msg: sent.append(msg)
    try:
        rng = np.random.default_rng(3)
        d1 = [rng.standard_normal((3, 2)).astype(np.float32) * 0.01,
              rng.standard_normal((2,)).astype(np.float32) * 0.01]
        a1 = wk.commit(d1, 0)
        assert all(c.dtype == np.int8 for c in sent[0]["delta"])
        for d, a, r, s in zip(d1, a1, wk._residual, sent[0]["scales"]):
            np.testing.assert_allclose(d, a + r, atol=1e-7)
            assert np.all(np.abs(r) <= s / 2 + 1e-7)
        r1 = [r.copy() for r in wk._residual]
        d2 = [rng.standard_normal((3, 2)).astype(np.float32) * 0.01,
              rng.standard_normal((2,)).astype(np.float32) * 0.01]
        a2 = wk.commit(d2, 0)
        for d, p, a, r in zip(d2, r1, a2, wk._residual):
            np.testing.assert_allclose(d + p, a + r, atol=1e-7)
    finally:
        net.send_opcode, net.send_data = orig_op, orig_send


def test_host_ps_int8_wire_compression_learns():
    """ADAG over host_ps with int8-quantized commits (4x fewer delta bytes)
    still trains to high accuracy — error feedback keeps the center honest."""
    ds = make_dataset()
    t = ADAG(make_model(), num_workers=2, batch_size=32, num_epoch=2,
             communication_window=4, label_col="label_encoded",
             learning_rate=0.1, execution="host_ps", wire_dtype="int8")
    fitted = t.train(ds)
    preds = fitted.predict(ds["features"][:256])
    acc = float(np.mean(np.argmax(preds, axis=1) == ds["label"][:256]))
    assert acc > 0.6, acc
    assert all(w.dtype == np.float32 for w in fitted.get_weights())


def test_host_ps_trains_transformer_lm():
    """The async socket-PS engine handles the sequence-model family too:
    a RoPE/GQA causal LM's loss drops through true hogwild training (the
    wire carries the full transformer param pytree)."""
    from distkeras_tpu.models.zoo import transformer_lm

    model = transformer_lm(vocab_size=16, seq_len=12, d_model=32,
                           num_heads=4, num_layers=1, mlp_dim=64,
                           compute_dtype="float32", num_kv_heads=2,
                           positional="rope")
    rng = np.random.default_rng(0)
    x = rng.integers(0, 16, (128, 12)).astype(np.int32)
    y = (x + 1) % 16
    tr = ADAG(model, num_workers=2, batch_size=16, num_epoch=8,
              communication_window=2, execution="host_ps",
              loss="sparse_categorical_crossentropy_from_logits",
              worker_optimizer="adam", learning_rate=3e-3)
    tr.train(Dataset({"features": x, "label": y}), shuffle=True)
    hist = tr.get_history()
    assert len(hist) > 0
    first = np.mean(hist[:4])
    last = np.mean(hist[-4:])
    assert last < 0.5 * first, (first, last)


def test_wire_dtype_resolves_eagerly():
    """float16 (numpy-native) and bad names resolve/fail at construction."""
    from distkeras_tpu.workers import DOWNPOURWorker
    import pytest
    blob = {"model": "{}", "weights": []}
    w = DOWNPOURWorker.__new__(DOWNPOURWorker)  # bypass model deserialization
    # constructor path: use the real init with a stub blob via PSWorker args
    from distkeras_tpu.core.model import Sequential, serialize_model
    from distkeras_tpu.core.layers import Dense
    import jax
    m = Sequential([Dense(2)], input_shape=(3,), compute_dtype="float32")
    blob = serialize_model(m, m.init(jax.random.PRNGKey(0)))
    wk = DOWNPOURWorker(blob, "sgd", "mse", "127.0.0.1", 1,
                        wire_dtype="float16")
    assert wk.wire_dtype == np.dtype(np.float16)
    with pytest.raises((TypeError, AttributeError)):
        DOWNPOURWorker(blob, "sgd", "mse", "127.0.0.1", 1,
                       wire_dtype="not_a_dtype")
