"""Disaggregated prefill/decode serving (PR 16, ``serving.DisaggPair``).

The contract pinned here, mirroring docs/serving.md's failure matrix:

 - a prefill→decode pair emits tokens BIT-IDENTICAL to a unified paged
   engine, greedy AND sampled, float32 AND int8 KV (the shipped block
   set plus RNG key reconstruct the exact device state the unified
   token loop would have had);
 - zero block leak on BOTH engines across completion, cancel, and
   kill/mid-transfer interleavings (``kv_blocks_in_use == 0`` after the
   traffic drains — the pool refcount contract extended over the wire);
 - a prefill engine killed with requests in flight re-routes them to
   the next live prefill engine with the ORIGINAL rng key (idempotent
   retry, one client-visible request, ``prefill_reroutes`` booked);
 - a dead decode engine is TERMINAL (typed ``EngineDead``, no silent
   re-route — it owned all live KV state), the seam
   ``resilience.PairSupervisor`` restarts through ``replace_engine``;
 - the wire path (``SERVING_OP_KVBLOCKS`` through ``ServingServer``)
   behaves identically, and hostile/torn 'k' frames shed with the
   decode pool untouched.

Tier-1 legs are in-process or loopback-only, seeded, and sleep-free.
"""

import time

import numpy as np
import pytest

import jax

from distkeras_tpu import networking
from distkeras_tpu.core.model import FittedModel
from distkeras_tpu.models import transformer_lm
from distkeras_tpu.networking import ChaosFault, ChaosProxy
from distkeras_tpu.resilience import PairSupervisor
from distkeras_tpu.serving import (DisaggPair, EngineDead, ServingClient,
                                   ServingEngine, ServingServer)

pytestmark = pytest.mark.disagg

VOCAB = 17
PROMPT = np.array([3, 4, 5, 6], np.int32)


@pytest.fixture(scope="module")
def fitted():
    model = transformer_lm(vocab_size=VOCAB, seq_len=32, d_model=16,
                           num_heads=2, num_layers=2, mlp_dim=32,
                           compute_dtype="float32")
    params = model.init(jax.random.PRNGKey(0), (32,))
    return FittedModel(model, params)


def _mk(fitted, role, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_len", 24)
    kw.setdefault("block_size", 4)
    kw.setdefault("kv_blocks", 30)
    return ServingEngine(fitted, paged=True, role=role, **kw)


def _unified_rows(fitted, reqs, **ekw):
    """Reference rows from a unified paged engine (inline scheduler)."""
    eng = _mk(fitted, "unified", **ekw)
    hs = [eng.submit(**r) for r in reqs]
    eng.run_until_idle()
    assert eng.kv_blocks_in_use == 0
    return [h.result() for h in hs]


def _assert_zero_leak(pair):
    assert pair.kv_blocks_in_use == 0
    for e in pair.engines:
        assert e.kv_blocks_in_use == 0, f"leak on role={e.role} engine"


# ---------------------------------------------------------------------------
# token identity: pair vs unified, greedy + sampled × float32 + int8 KV
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kv_dtype", [None, "int8"],
                         ids=["kv-f32", "kv-int8"])
def test_pair_token_identical_to_unified(fitted, kv_dtype):
    """The disaggregated hand-off is an execution strategy, never a
    numerics change: greedy AND sampled streams match the unified engine
    bit for bit (int8 KV ships quantized codes + per-block scales)."""
    reqs = [
        {"prompt": PROMPT, "num_steps": 8},                       # greedy
        {"prompt": np.arange(1, 8, dtype=np.int32), "num_steps": 6,
         "temperature": 0.7, "seed": 11},
        {"prompt": np.array([2, 9], np.int32), "num_steps": 5,
         "temperature": 0.7, "top_k": 5, "top_p": 0.9, "seed": 23},
    ]
    ekw = {} if kv_dtype is None else {"kv_dtype": kv_dtype}
    want = _unified_rows(fitted, reqs, **ekw)
    pair = DisaggPair([_mk(fitted, "prefill", **ekw)],
                      decode=_mk(fitted, "decode", **ekw), poll_s=0.005)
    with pair:
        hs = [pair.submit(**r) for r in reqs]
        rows = [h.result(timeout=60.0) for h in hs]
    for got, ref in zip(rows, want):
        np.testing.assert_array_equal(got, ref)
    _assert_zero_leak(pair)
    s = pair.stats
    assert s["requests_completed"] == len(reqs)
    assert s["kv_blocks_shipped"] > 0
    assert s["kv_block_bytes_shipped"] > 0
    # one ship-side sample (gather+host) + one ingest-side sample per req
    assert len(s["transfer_ms"]) == 2 * len(reqs)


def test_pair_zero_steps_completes_on_prefill_side(fitted):
    """num_steps=0 never crosses the wire: the prefill engine completes
    it in place and the pair books it without a router thread."""
    pair = DisaggPair([_mk(fitted, "prefill")],
                      decode=_mk(fitted, "decode"), poll_s=0.005)
    with pair:
        h = pair.submit(PROMPT, 0)
        assert h.wait(timeout=30.0)
    assert h.finish == "empty"
    assert pair.counters["requests_completed"] == 1
    _assert_zero_leak(pair)


# ---------------------------------------------------------------------------
# zero block leak across cancel interleavings
# ---------------------------------------------------------------------------

def test_cancel_mid_decode_reclaims_blocks_both_sides(fitted):
    """Cancel lands on whichever engine owns the request; after the
    traffic drains neither arena holds a block."""
    pair = DisaggPair([_mk(fitted, "prefill")],
                      decode=_mk(fitted, "decode"), poll_s=0.005)
    with pair:
        doomed = pair.submit(PROMPT, 18)
        keeper = pair.submit(np.array([5, 6, 7], np.int32), 4)
        # wait for the doomed stream to actually start decoding, then cancel
        chunk, done = doomed.next_chunk(timeout=30.0)
        assert chunk, "no first token within timeout"
        assert pair.cancel(doomed) or doomed.done
        assert doomed.wait(timeout=30.0)
        assert keeper.wait(timeout=30.0)
        assert pair.drain(timeout=30.0)
    assert doomed.finish in ("cancel", "length", "eos")  # cancel can race
    assert keeper.finish in ("length", "eos")
    _assert_zero_leak(pair)
    c = pair.counters
    assert c["requests_submitted"] == 2
    assert (c["requests_completed"] + c["requests_cancelled"]) == 2


def test_cancel_queued_before_prefill(fitted):
    """A cancel that lands while the request is still queued on the
    prefill engine never touches the decode side."""
    pre = _mk(fitted, "prefill")
    dec = _mk(fitted, "decode")
    pair = DisaggPair([pre], decode=dec, poll_s=0.005)
    try:
        # engines NOT started: the request parks in pre's queue, so the
        # cancel deterministically lands before prefill; driving pre's
        # scheduler inline sheds it without ever taking a KV slot
        h = pair.submit(PROMPT, 8)
        assert pair.cancel(h)
        pre.run_until_idle()
        assert h.wait(timeout=30.0)
        assert h.finish == "cancel"
    finally:
        pair.stop()
    _assert_zero_leak(pair)
    assert dec.stats["requests_submitted"] == 0
    assert pair.counters["requests_cancelled"] == 1


# ---------------------------------------------------------------------------
# prefill death: deterministic mid-flight re-route
# ---------------------------------------------------------------------------

def test_prefill_death_reroutes_with_original_key(fitted):
    """pre1 is never started, so the request deterministically parks in
    its queue; declaring it dead fails the upstream handle and the router
    resubmits to pre2 with the ORIGINAL rng key — one client request,
    token-identical to unified, zero leak on every engine."""
    req = {"prompt": PROMPT, "num_steps": 6, "temperature": 0.6,
           "seed": 7}
    (want,) = _unified_rows(fitted, [req])
    pre1 = _mk(fitted, "prefill")
    pre2 = _mk(fitted, "prefill")
    dec = _mk(fitted, "decode")
    pair = DisaggPair([pre1, pre2], decode=dec, poll_s=0.005)
    try:
        pre2.start()
        dec.start()
        h = pair.submit(**req)  # round-robin lands on (unstarted) pre1
        assert pre1.stats["requests_submitted"] == 1
        pre1.declare_dead("chaos: prefill killed mid-flight")
        row = h.result(timeout=60.0)
    finally:
        pair.stop()
    np.testing.assert_array_equal(row, want)
    assert pair.counters["prefill_reroutes"] == 1
    assert pair.counters["requests_completed"] == 1
    assert pair.counters["requests_failed"] == 0
    assert pre2.stats["requests_submitted"] == 1
    _assert_zero_leak(pair)


def test_every_prefill_dead_fails_typed(fitted):
    """When no live prefill engine remains, the re-route budget exhausts
    and the proxy fails with the typed EngineDead."""
    pre = _mk(fitted, "prefill")
    dec = _mk(fitted, "decode")
    pair = DisaggPair([pre], decode=dec, poll_s=0.005)
    try:
        dec.start()
        h = pair.submit(PROMPT, 6)
        pre.declare_dead("chaos: the only prefill engine died")
        assert h.wait(timeout=30.0)
    finally:
        pair.stop()
    assert isinstance(h.error, EngineDead)
    with pytest.raises(EngineDead):
        h.result()
    assert pair.counters["requests_failed"] == 1
    _assert_zero_leak(pair)


# ---------------------------------------------------------------------------
# decode death: terminal, typed, restartable through the supervisor seam
# ---------------------------------------------------------------------------

def test_decode_death_is_terminal_no_reroute(fitted):
    """The decode engine owns all live KV state, so its death fails the
    proxy with EngineDead instead of silently re-routing."""
    pre = _mk(fitted, "prefill")
    dec = _mk(fitted, "decode")
    pair = DisaggPair([pre], decode=dec, poll_s=0.005)
    try:
        pre.start()  # decode NOT started: the hand-off parks in its queue
        h = pair.submit(PROMPT, 8)
        # wait for the prefill half + transfer to land on the decode queue
        assert h.next_chunk(timeout=30.0)[0], "prefill token not relayed"
        dec.declare_dead("chaos: decode engine killed")
        assert h.wait(timeout=30.0)
    finally:
        pair.stop()
    assert isinstance(h.error, EngineDead)
    assert pair.counters["prefill_reroutes"] == 0
    assert pair.counters["requests_failed"] == 1
    assert pair.dead is not None
    _assert_zero_leak(pair)


def test_pair_supervisor_restart_seam(fitted):
    """resilience.PairSupervisor: a dead engine is respawned through
    respawn_clone and swapped into the pair via replace_engine; traffic
    after recovery completes token-identically."""
    req = {"prompt": PROMPT, "num_steps": 6}
    (want,) = _unified_rows(fitted, [req])
    pre = _mk(fitted, "prefill")
    dec = _mk(fitted, "decode")
    pair = DisaggPair([pre], decode=dec, poll_s=0.005)
    with pair:
        assert pair.submit(**req).wait(timeout=60.0)
        sup = PairSupervisor(pair, liveness_deadline=30.0)
        assert sup.check_all() == [None, None]
        pre.declare_dead("chaos: kill the prefill half")
        recs = sup.recover_all()
        assert len(recs) == 1 and recs[0]["restarted"]
        assert sup.restarts == 1
        new_pre = pair.engines[0]
        assert new_pre is not pre and new_pre.role == "prefill"
        row = pair.submit(**req).result(timeout=60.0)
    np.testing.assert_array_equal(row, want)
    assert pair.counters["requests_completed"] == 2
    _assert_zero_leak(pair)


# ---------------------------------------------------------------------------
# the wire path: SERVING_OP_KVBLOCKS through ServingServer
# ---------------------------------------------------------------------------

def test_pair_over_wire_token_identical(fitted, server_core):
    """decode_addr mode: blocks ship over loopback through the serving
    protocol's 'k' opcode; the client-visible stream is unchanged."""
    reqs = [
        {"prompt": PROMPT, "num_steps": 6},
        {"prompt": np.array([2, 9, 4], np.int32), "num_steps": 5,
         "temperature": 0.7, "seed": 5},
    ]
    want = _unified_rows(fitted, reqs)
    with ServingServer(_mk(fitted, "decode"), poll_s=0.005) as srv:
        pair = DisaggPair([_mk(fitted, "prefill")], decode_addr=srv.addr,
                          poll_s=0.005)
        with pair:
            rows = [pair.submit(**r).result(timeout=60.0) for r in reqs]
        for got, ref in zip(rows, want):
            np.testing.assert_array_equal(got, ref)
        assert srv.engine.kv_blocks_in_use == 0
        assert srv.engine.stats["kv_blocks_ingested"] > 0
    _assert_zero_leak(pair)
    assert pair.counters["requests_completed"] == len(reqs)


def _prefilled(fitted, num_steps=6):
    """Run a real prefill half inline and return its shipped artifacts."""
    pre = _mk(fitted, "prefill")
    h = pre.submit(PROMPT, num_steps)
    pre.run_until_idle()
    assert h.finish == "prefilled"
    assert pre.kv_blocks_in_use == 0
    return h.kvblocks, int(h.tokens[0])


def test_hostile_kvblocks_frame_sheds_pool_untouched(fitted, server_core):
    """A 'k' frame whose payload lies about its own geometry dies in
    validate() (typed ProtocolError → the server's shed path) BEFORE any
    engine call: protocol_errors increments, the decode pool never
    allocates, and the server keeps serving."""
    kvb, first = _prefilled(fitted)
    # self-inconsistent: row counts no longer match num_blocks*block_size
    torn = kvb.decoded()
    for c in torn.layers:
        if c is not None:
            for k in list(c):
                c[k] = c[k][:-1]
    with ServingServer(_mk(fitted, "decode"), poll_s=0.005) as srv:
        with ServingClient(*srv.addr) as c:
            with pytest.raises((ConnectionError, OSError)):
                c.submit_prefilled(torn, PROMPT, first, 6)
                c.sock.recv(1)  # the shed path drops the connection
        assert srv.protocol_errors == 1
        assert srv.engine.kv_blocks_in_use == 0
        assert srv.engine.stats["kv_blocks_ingested"] == 0
        # the server survived: the intact block set decodes fine
        with ServingClient(*srv.addr) as c:
            rid = c.submit_prefilled(kvb, PROMPT, first, 6)
            toks = []  # the stream starts at the prefill token
            for chunk, done in c.stream(rid):
                toks.extend(int(t) for t in chunk)
                if done is not None:
                    assert done["finish"] in ("length", "eos")
                    break
        (want,) = _unified_rows(fitted, [{"prompt": PROMPT,
                                          "num_steps": 6}])
        np.testing.assert_array_equal(np.asarray(toks, np.int32),
                                      want[len(PROMPT):])
        assert srv.engine.kv_blocks_in_use == 0


def test_geometry_mismatch_rejected_typed(fitted, server_core):
    """A self-consistent block set that doesn't match the DECODE engine's
    arena geometry is a typed bad_request (engine-level ValueError), not
    a dropped connection."""
    kvb, first = _prefilled(fitted)
    with ServingServer(_mk(fitted, "decode", block_size=8, kv_blocks=16),
                       poll_s=0.005) as srv:
        with ServingClient(*srv.addr) as c:
            with pytest.raises(ValueError):
                c.submit_prefilled(kvb, PROMPT, first, 6)
        assert srv.protocol_errors == 0
        assert srv.engine.kv_blocks_in_use == 0


def test_torn_kvblocks_transfer_decode_pool_untouched(fitted, server_core):
    """ChaosProxy tears the 'k' frame mid-transfer (half the payload,
    then RST): the decode server sheds the torn frame with its pool
    untouched and keeps serving the next, intact transfer."""
    kvb, first = _prefilled(fitted)
    with ServingServer(_mk(fitted, "decode"), poll_s=0.005) as srv:
        with ChaosProxy(*srv.addr, protocol="serving",
                        faults=[ChaosFault(0, 0, "tear")]) as px:
            with ServingClient(*px.addr) as c:
                with pytest.raises((ConnectionError, OSError)):
                    c.submit_prefilled(kvb, PROMPT, first, 6)
                    c.sock.recv(1)
            # the proxy RSTs the client before the server's handler has
            # necessarily observed the tear — wait for its accounting
            deadline = time.monotonic() + 10.0
            while (srv.protocol_errors + srv.disconnects == 0
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            assert srv.protocol_errors + srv.disconnects >= 1
            assert srv.engine.kv_blocks_in_use == 0
            assert srv.engine.stats["kv_blocks_ingested"] == 0
        # intact retry straight at the server completes
        with ServingClient(*srv.addr) as c:
            rid = c.submit_prefilled(kvb, PROMPT, first, 6)
            for chunk, done in c.stream(rid):
                if done is not None:
                    assert done["finish"] in ("length", "eos")
                    break
        assert srv.engine.kv_blocks_in_use == 0


# ---------------------------------------------------------------------------
# role-mode admission contracts + loadgen surface
# ---------------------------------------------------------------------------

def test_role_admission_contracts(fitted):
    with pytest.raises(ValueError):
        _mk(fitted, "decode").submit(PROMPT, 4)  # decode rejects submit
    with pytest.raises(ValueError):
        ServingEngine(fitted, num_slots=2, max_len=24, role="prefill")
    with pytest.raises(ValueError):
        DisaggPair([_mk(fitted, "unified")], decode=_mk(fitted, "decode"))
    with pytest.raises(ValueError):
        DisaggPair([_mk(fitted, "prefill")])  # neither decode nor addr


def test_loadgen_bimodal_trace_and_disagg_builder():
    from examples import loadgen
    trace = loadgen.make_trace(40, num_steps=12, seed=0,
                               prompt_lengths=(4, 24), pattern="bimodal",
                               long_fraction=0.4)
    lens = {len(r["prompt"]) for r in trace}
    assert lens == {4, 24}
    for r in trace:
        assert r["num_steps"] == (3 if len(r["prompt"]) == 24 else 12)
    _, pair = loadgen.build_engine(num_slots=2, max_len=32,
                                   disaggregate=True, prefill_engines=2)
    assert isinstance(pair, DisaggPair)
    roles = [e.role for e in pair.engines]
    assert roles == ["prefill", "prefill", "decode"]
    assert networking.SERVING_OP_KVBLOCKS == b"k"
