"""Rotary position embeddings (ops/rope.py + rope= on the attention stack).

RoPE's defining property — attention scores depend only on RELATIVE
distance — is asserted directly, plus training/decode integration on the
rope-positional transformer_lm.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu import Dataset, SingleTrainer
from distkeras_tpu.core.layers import MultiHeadAttention, TransformerBlock
from distkeras_tpu.models.zoo import transformer_lm
from distkeras_tpu.ops.rope import apply_rope


def test_rope_matches_complex_rotation_oracle():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 6, 3, 8)).astype(np.float32)
    pos = jnp.arange(6)
    got = np.asarray(apply_rope(jnp.asarray(x), pos))

    theta = 10000.0
    d = 8
    freqs = theta ** (-np.arange(0, d, 2) / d)            # (d/2,)
    ang = np.arange(6)[:, None] * freqs[None, :]          # (S, d/2)
    z = x[..., 0::2] + 1j * x[..., 1::2]                  # complex pairs
    zr = z * np.exp(1j * ang)[None, :, None, :]
    want = np.stack([zr.real, zr.imag], axis=-1).reshape(x.shape)
    np.testing.assert_allclose(got, want.astype(np.float32), atol=1e-5)


def test_rope_scores_are_relative():
    """q·k after RoPE depends only on the position DIFFERENCE: shifting
    every position by a constant leaves all pairwise scores unchanged."""
    rng = jax.random.PRNGKey(1)
    kq, kk = jax.random.split(rng)
    q = jax.random.normal(kq, (1, 8, 2, 16))
    k = jax.random.normal(kk, (1, 8, 2, 16))

    def scores(offset):
        pos = jnp.arange(8) + offset
        qr, kr = apply_rope(q, pos), apply_rope(k, pos)
        return jnp.einsum("bqhd,bkhd->bhqk", qr, kr)

    np.testing.assert_allclose(np.asarray(scores(0)),
                               np.asarray(scores(37)), atol=1e-4)


def test_rope_validation():
    with pytest.raises(ValueError, match="even"):
        MultiHeadAttention(num_heads=2, key_dim=7, causal=True, rope=True)
    with pytest.raises(ValueError, match="even"):
        TransformerBlock(2, 7, 16, causal=True, rope=True)
    with pytest.raises(ValueError, match="even head dim"):
        apply_rope(jnp.zeros((1, 2, 1, 5)), jnp.arange(2))
    with pytest.raises(ValueError, match="positional"):
        transformer_lm(positional="alibi")
    # legacy configs without the rope field deserialize as rope=False
    from distkeras_tpu.core.layers import Layer
    cfg = MultiHeadAttention(num_heads=2, key_dim=8).get_config()
    cfg.pop("rope", None)
    assert Layer.from_config(cfg).rope is False


def test_rope_lm_trains_and_decodes():
    """positional='rope' LM (no PositionalEmbedding layer) learns
    next-token; KV-cache decode matches the full forward stepwise and
    generate() continues the rule."""
    from distkeras_tpu.core.decode import decode_step, init_cache

    model = transformer_lm(vocab_size=16, seq_len=12, d_model=32,
                           num_heads=4, num_layers=1, mlp_dim=64,
                           compute_dtype="float32", positional="rope")
    assert all(layer.kind != "PositionalEmbedding" for layer in model.layers)

    rng = np.random.default_rng(0)
    x = rng.integers(0, 16, (256, 12)).astype(np.int32)
    y = (x + 1) % 16
    tr = SingleTrainer(model, batch_size=32, num_epoch=30,
                       loss="sparse_categorical_crossentropy_from_logits",
                       worker_optimizer="adam", learning_rate=3e-3)
    fitted = tr.train(Dataset({"features": x, "label": y}))
    logits = fitted.predict(x[:64])
    acc = (np.argmax(logits, -1) == y[:64]).mean()
    assert acc > 0.9, acc

    # stepwise decode parity against the full forward
    toks = x[:2]
    full = np.asarray(fitted.model.apply(fitted.params, toks), np.float32)
    caches = init_cache(fitted.model, batch=2, max_len=12)
    step = jax.jit(lambda c, t, p: decode_step(fitted.model, fitted.params,
                                               c, t, p))
    for p in range(12):
        logits_p, caches = step(caches, toks[:, p], p)
        np.testing.assert_allclose(np.asarray(logits_p), full[:, p],
                                   rtol=2e-5, atol=2e-5)

    out = np.asarray(fitted.generate(np.array([[4, 5, 6]], np.int32), 5))
    np.testing.assert_array_equal(out[0, 3:], (7 + np.arange(5)) % 16)


def test_linear_scaling_is_position_interpolation():
    """apply_rope(x, pos, scale=s) == apply_rope at positions pos/s —
    the Chen et al. linear-interpolation contract."""
    import numpy as np
    from distkeras_tpu.ops.rope import apply_rope
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((2, 8, 2, 16)), jnp.float32)
    pos = jnp.arange(8) * 4
    scaled = apply_rope(x, pos, scale=4.0)
    plain = apply_rope(x, jnp.arange(8))  # pos/4
    np.testing.assert_allclose(np.asarray(scaled), np.asarray(plain),
                               rtol=1e-6, atol=1e-6)


def test_ntk_theta_formula_and_validation():
    import pytest
    from distkeras_tpu.ops.rope import ntk_theta
    d = 64
    got = ntk_theta(4.0, d)
    assert abs(got - 10000.0 * 4.0 ** (d / (d - 2))) < 1e-6
    assert ntk_theta(1.0, d) == 10000.0
    with pytest.raises(ValueError, match="factor"):
        ntk_theta(0.5, d)
    with pytest.raises(ValueError, match="even"):
        ntk_theta(2.0, 7)


def test_scaled_model_decode_matches_forward():
    """rope_theta/rope_scale thread identically through the training
    forward and the KV-cache decode walker."""
    import numpy as np
    from distkeras_tpu.core.decode import init_cache, decode_step
    from distkeras_tpu.models.zoo import transformer_lm
    from distkeras_tpu.ops.rope import ntk_theta

    model = transformer_lm(vocab_size=16, seq_len=12, d_model=32,
                           num_heads=4, num_layers=2, mlp_dim=64,
                           compute_dtype="float32", positional="rope",
                           rope_theta=ntk_theta(2.0, 8), rope_scale=2.0)
    params = model.init(jax.random.PRNGKey(0))
    toks = jnp.asarray(np.random.default_rng(1).integers(0, 16, (2, 12)),
                       jnp.int32)
    full = np.asarray(model.apply(params, toks))
    caches = init_cache(model, batch=2, max_len=12)
    for p in range(12):
        logits, caches = decode_step(model, params, caches, toks[:, p], p)
        np.testing.assert_allclose(np.asarray(logits), full[:, p],
                                   rtol=2e-4, atol=2e-4)
    # config round-trips the scaling knobs
    from distkeras_tpu.core.model import Sequential
    clone = Sequential.from_json(model.to_json())
    blk = [l for l in clone.layers if getattr(l, "rope", False)][0]
    assert blk.rope_scale == 2.0 and blk.rope_theta != 10000.0


def test_parallel_lm_threads_rope_scaling(eight_devices):
    """The tp path honors rope_theta/rope_scale: a scaled LM computes a
    DIFFERENT (but finite) loss than the default — the knob is wired, not
    dropped (round-4 review: the tp path used to hardcode the defaults)."""
    import numpy as np
    import optax
    from jax.sharding import Mesh
    from distkeras_tpu.parallel.transformer import ParallelTransformerLM

    devs = np.array(jax.devices()[:4]).reshape(2, 1, 2)
    mesh = Mesh(devs, ("data", "seq", "model"))

    def loss_of(**kw):
        lm = ParallelTransformerLM(
            vocab_size=32, seq_len=16, d_model=16, num_heads=2,
            num_layers=1, mlp_dim=32, mesh=mesh,
            compute_dtype=jnp.float32, positional="rope", **kw)
        params = lm.init(jax.random.PRNGKey(5))
        opt_state, step = lm.compile_train_step(optax.adam(1e-2), params)
        rng = np.random.default_rng(0)
        toks = rng.integers(0, 32, (8, 16)).astype(np.int32)
        sh = lm.batch_sharding()
        _, _, loss = step(params, opt_state, jax.device_put(toks, sh),
                          jax.device_put((toks + 1) % 32, sh))
        return float(loss)

    base = loss_of()
    scaled = loss_of(rope_scale=4.0)
    assert np.isfinite(base) and np.isfinite(scaled)
    assert abs(base - scaled) > 1e-6
    with pytest.raises(ValueError, match="rope_scale"):
        loss_of(rope_scale=0.5)


def test_rope_theta_and_knob_guards():
    """ADVICE r4: theta <= 0 must raise eagerly (not NaN at first forward),
    and rope knobs without rope=True must raise instead of silently no-op."""
    from distkeras_tpu.core.layers import MultiHeadAttention, TransformerBlock
    from distkeras_tpu.ops.rope import validate_rope_scaling
    with pytest.raises(ValueError, match="rope_theta"):
        validate_rope_scaling(0.0, 1.0)
    with pytest.raises(ValueError, match="rope_theta"):
        validate_rope_scaling(-10000.0, 2.0)
    with pytest.raises(ValueError, match="rope=False"):
        MultiHeadAttention(2, 4, rope_theta=50000.0)
    with pytest.raises(ValueError, match="rope=False"):
        TransformerBlock(2, 4, 8, rope_scale=2.0)
    # the valid combinations still construct
    MultiHeadAttention(2, 4, rope=True, rope_theta=50000.0, rope_scale=2.0)
    TransformerBlock(2, 4, 8, rope=True, rope_theta=50000.0)
