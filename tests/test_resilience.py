"""Tests for the PS resilience layer (``distkeras_tpu/resilience.py`` +
``networking.ChaosProxy``): survivable parameter servers.

Key invariants asserted here:
 - ``RetryPolicy`` unifies every connect/reconnect path: jittered
   exponential backoff (thundering-herd avoidance), attempt and wall-clock
   deadline bounds, deterministic under a seed.
 - The **bounded-loss contract**: a shard respawned from its last snapshot
   drops exactly the windows committed after that snapshot — nothing more —
   and commits resume cleanly on the restored center.
 - The **generation handshake**: a restarted shard rejects in-flight
   commits stamped with the old generation; workers re-sync from the reply
   and their per-shard clocks stay monotonic across the restart.
 - ``ShardSupervisor`` detects both a *crashed* shard (dead accept loop)
   and a *wedged* one (heartbeat through the apply lock times out), and
   respawns on the same address.
 - ``ChaosProxy`` drives the REAL socket stack: scripted resets, torn
   frames, delays, and duplicated replies at exact (connection, opcode)
   injection points — no transport monkeypatching.
 - End to end: ``recovery=True`` survives a mid-run shard kill under each
   async algorithm at ``ps_shards`` 1 and 3, while ``recovery=False`` +
   ``ps_shards=1`` (the defaults) keep the PR 2 behavior (asserted by the
   untouched test_host_ps*/test_ps_sharding suites).
"""

import logging
import socket
import threading
import time

import numpy as np
import pytest

from distkeras_tpu import ADAG, DOWNPOUR, DynSGD, networking
from distkeras_tpu.networking import ChaosFault, ChaosProxy
from distkeras_tpu.parameter_servers import (DeltaParameterServer,
                                             SocketParameterServer)
from distkeras_tpu.ps_sharding import (PSShardDown, ShardedPSClient,
                                       ShardedServerGroup)
from distkeras_tpu.resilience import (RetryPolicy, ShardJournal,
                                      ShardSupervisor)
from distkeras_tpu.workers import DOWNPOURWorker

from test_host_ps import make_dataset, make_model
from test_host_ps_overlap import _tiny_blob
from test_trainers import eval_accuracy

#: fast-converging policy for loopback tests (kills + respawns land in ms)
FAST = RetryPolicy(attempts=None, backoff=0.02, max_backoff=0.2,
                   deadline=20.0, seed=0)


def _blob(n=8, m=3):
    return {"model": make_model().to_json(),
            "weights": [np.zeros((n,), np.float32),
                        np.zeros((m,), np.float32)]}


def _group(algorithm="downpour", num_shards=2, blob=None):
    g = ShardedServerGroup(algorithm, blob or _blob(), num_workers=1,
                           num_shards=num_shards)
    g.start()
    return g


def _supervisor(group, **kw):
    kw.setdefault("heartbeat_interval", 0.05)
    kw.setdefault("liveness_deadline", 0.3)
    kw.setdefault("snapshot_interval", 0.05)
    return ShardSupervisor(group, "downpour", 1, **kw)


# ---------------------------------------------------------------------------
# RetryPolicy — the unified, jittered backoff contract (satellite 1)
# ---------------------------------------------------------------------------

def test_retry_policy_delays_jitter_and_caps():
    p = RetryPolicy(attempts=6, backoff=0.1, max_backoff=0.5, jitter=0.5,
                    seed=7)
    delays = list(p.delays())
    assert len(delays) == 6
    for i, d in enumerate(delays):
        base = min(0.1 * 2 ** i, 0.5)
        assert base <= d <= base * 1.5  # jitter stretches, never shrinks
    assert delays == list(p.delays())  # seeded: deterministic
    # unseeded: two policies draw different jitter streams (herd avoidance)
    a = list(RetryPolicy(attempts=6, backoff=0.1).delays())
    b = list(RetryPolicy(attempts=6, backoff=0.1).delays())
    assert a != b


def test_retry_policy_needs_a_bound():
    with pytest.raises(ValueError, match="bound"):
        RetryPolicy(attempts=None, deadline=None)
    with pytest.raises(ValueError, match="attempts"):
        RetryPolicy(attempts=0)


def test_retry_policy_deadline_bounds_wall_clock():
    p = RetryPolicy(attempts=None, backoff=0.01, max_backoff=0.02,
                    deadline=0.1, seed=0)
    calls = []

    def always_fails():
        calls.append(1)
        raise ConnectionRefusedError

    t0 = time.perf_counter()
    with pytest.raises(ConnectionRefusedError):
        p.call(always_fails, (ConnectionRefusedError,))
    assert time.perf_counter() - t0 < 2.0
    assert len(calls) >= 2  # it did retry before the deadline cut it off


def test_retry_policy_call_succeeds_after_transient_faults():
    faults = [ConnectionResetError(), socket.timeout()]

    def flaky():
        if faults:
            raise faults.pop(0)
        return "up"

    p = RetryPolicy(attempts=5, backoff=0.001, seed=0)
    assert p.call(flaky, (ConnectionResetError, socket.timeout)) == "up"


def test_worker_connect_backoff_is_jittered(monkeypatch):
    """Satellite: N workers re-dialing a restarted shard must not sleep in
    lockstep — the per-instance jitter streams differ."""
    from distkeras_tpu import resilience

    def refuse(host, port, **kw):
        raise ConnectionRefusedError

    monkeypatch.setattr(networking, "connect", refuse)
    sleeps: dict = {}

    def record(key):
        def sleep(d):
            sleeps.setdefault(key, []).append(d)
        return sleep

    for key in ("a", "b"):
        monkeypatch.setattr(resilience.time, "sleep", record(key))
        wk = DOWNPOURWorker(_tiny_blob(), "sgd", "mse", "127.0.0.1", 1)
        with pytest.raises(ConnectionError, match="refused"):
            wk.connect(attempts=6, backoff=0.05)
    assert len(sleeps["a"]) == 6 and len(sleeps["b"]) == 6
    assert sleeps["a"] != sleeps["b"]  # jitter desynchronizes the herd


# ---------------------------------------------------------------------------
# heartbeat + generation handshake at the protocol level
# ---------------------------------------------------------------------------

def test_heartbeat_opcode_returns_clock_and_generation():
    ps = DeltaParameterServer(_tiny_blob())
    server = SocketParameterServer(ps, generation=3)
    server.start()
    try:
        sock = networking.connect("127.0.0.1", server.port)
        networking.send_opcode(sock, b"h")
        msg = networking.recv_data(sock)
        assert msg["clock"] == 0 and msg["gen"] == 3
        assert "weights" not in msg  # cheap probe, no center payload
        networking.send_opcode(sock, b"q")
        sock.close()
    finally:
        server.stop()


def test_stale_generation_commit_is_rejected():
    """The epoch/generation handshake: a commit stamped with an older
    generation (computed against a center a restart rolled back) is
    DROPPED; the 'u' reply still re-syncs the worker with the current
    state + generation in the same round trip."""
    ps = DeltaParameterServer(_tiny_blob())
    server = SocketParameterServer(ps, generation=1)
    server.start()
    try:
        sock = networking.connect("127.0.0.1", server.port)
        delta = {"delta": [np.ones(3, np.float32)], "worker_id": 0,
                 "clock": 0}
        networking.send_opcode(sock, b"u")
        networking.send_data(sock, {**delta, "gen": 0})  # stale
        msg = networking.recv_data(sock)
        assert msg["stale"] is True and msg["gen"] == 1
        assert msg["clock"] == 0  # nothing applied
        np.testing.assert_array_equal(msg["weights"][0], np.zeros(3))

        networking.send_opcode(sock, b"c")
        networking.send_data(sock, {**delta, "gen": 0})  # stale 'c': dropped
        networking.send_opcode(sock, b"u")
        networking.send_data(sock, {**delta, "gen": 1})  # current: applied
        msg = networking.recv_data(sock)
        assert "stale" not in msg and msg["clock"] == 1
        np.testing.assert_array_equal(msg["weights"][0], np.ones(3))
        sock.close()
    finally:
        server.stop()


def test_unstamped_commits_keep_working():
    """Back-compat: commits without a 'gen' field (PR 2 workers, raw
    protocol tests) apply regardless of the server generation."""
    ps = DeltaParameterServer(_tiny_blob())
    server = SocketParameterServer(ps, generation=5)
    server.start()
    try:
        sock = networking.connect("127.0.0.1", server.port)
        networking.send_opcode(sock, b"u")
        networking.send_data(sock, {"delta": [np.ones(3, np.float32)],
                                    "worker_id": 0, "clock": 0})
        msg = networking.recv_data(sock)
        assert msg["clock"] == 1 and msg["gen"] == 5
        sock.close()
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# snapshot journal + the bounded-loss contract
# ---------------------------------------------------------------------------

def test_shard_journal_roundtrip_and_retention(tmp_path):
    j = ShardJournal(str(tmp_path), max_to_keep=2)
    assert j.latest(0) is None
    for snap in range(1, 4):
        j.save(0, snap, [np.full((4,), float(snap), np.float32)],
               clock=snap * 10, generation=snap)
    out = j.latest(0)
    assert out["clock"] == 30 and out["generation"] == 3
    assert out["snap_id"] == 3
    np.testing.assert_array_equal(out["center"][0], np.full(4, 3.0))
    # retention: only the last max_to_keep snapshots remain on disk
    assert j._ckpt(0).all_steps() == [2, 3]
    # shards journal independently
    j.save(1, 1, [np.zeros((2, 2), np.float32)], clock=7, generation=0)
    assert j.latest(1)["clock"] == 7
    assert j.latest(0)["clock"] == 30


def test_bounded_loss_contract_across_respawn(lock_order_audit):
    """ACCEPTANCE: commit d1 → snapshot → commit d2 → crash → respawn.
    The restored center is exactly w0+d1 (d2, committed after the last
    snapshot, is dropped — the same loss class as worker staleness), the
    restored clock matches, and a post-restart commit d3 lands on the
    restored center.  The client's view of the shard clock never runs
    backwards."""
    group = _group(num_shards=2)
    sup = _supervisor(group)  # loop NOT started: deterministic sequencing
    client = ShardedPSClient(group.plan, group.addrs, recovery=True,
                             policy=FAST)
    try:
        for j in range(2):
            sup.snapshot_shard(j)  # the initial-state snapshot
        client.connect()
        shapes = [w.shape for w in _blob()["weights"]]
        d1 = [np.full(s, 1.0, np.float32) for s in shapes]
        d2 = [np.full(s, 10.0, np.float32) for s in shapes]
        d3 = [np.full(s, 100.0, np.float32) for s in shapes]
        client.update({"delta": d1, "worker_id": 0, "clock": 0})
        sup.snapshot_shard(0)  # d1 is durable on shard 0
        client.update({"delta": d2, "worker_id": 0, "clock": 1})
        assert client._clocks == [2, 2]

        sup.kill_shard(0)
        rec = sup.respawn_shard(0)
        assert rec["restored_clock"] == 1  # the post-d1 snapshot
        assert rec["dropped_updates"] == 1  # exactly d2
        assert rec["generation"] == 1

        center = client.pull()  # reconnect-resumes shard 0
        assert client.resumes >= 1
        s0 = group.plan.scatter(center)[0]
        np.testing.assert_array_equal(s0[0], np.full(s0[0].shape, 1.0))
        # shard 1 never died: it kept d1+d2
        s1 = group.plan.scatter(center)[1]
        np.testing.assert_array_equal(s1[0], np.full(s1[0].shape, 11.0))
        # monotonic view: restored shard-0 clock (1) did not roll the
        # client's baseline (2) backwards
        assert client._clocks[0] == 2 and client.clock_regressions >= 1
        assert client._gens[0] == 1

        client.update({"delta": d3, "worker_id": 0, "clock": 2})
        after = client.pull()
        a0 = group.plan.scatter(after)[0]
        np.testing.assert_array_equal(a0[0], np.full(a0[0].shape, 101.0))
    finally:
        client.abort()
        group.stop()


# ---------------------------------------------------------------------------
# the supervisor — crash and wedge detection, same-address respawn
# ---------------------------------------------------------------------------

def test_supervisor_detects_crash_and_respawns_same_port(lock_order_audit):
    group = _group(num_shards=2)
    sup = _supervisor(group)
    sup.start()
    try:
        port0 = group.servers[0].port
        sup.kill_shard(0)
        deadline = time.time() + 10.0
        while not sup.recoveries and time.time() < deadline:
            time.sleep(0.02)
        assert sup.recoveries and sup.recoveries[0]["shard"] == 0
        assert group.servers[0].port == port0  # same address
        assert group.servers[0].generation == 1
        assert sup.heartbeat(0, timeout=1.0)  # serving again
        assert sup.heartbeat(1, timeout=1.0)  # shard 1 untouched
        assert group.servers[1].generation == 0
    finally:
        sup.stop()
        group.stop()


def test_supervisor_detects_wedged_shard(caplog):
    """A shard whose apply lock is stuck (wedged apply, not a dead process)
    fails the heartbeat deadline — the probe goes THROUGH the apply lock —
    and is respawned.  Neither the supervisor's snapshot tick nor its
    detection loop may deadlock on the wedged lock, and the wedged handler
    leak is logged by the respawn's stop()."""
    group = _group(num_shards=2)
    sup = _supervisor(group)
    sup.start()  # initial snapshots while healthy
    wedged = group.servers[0]
    assert wedged.ps._lock.acquire(timeout=5.0)  # the wedge: applies block
    try:
        with caplog.at_level(logging.WARNING):
            deadline = time.time() + 10.0
            while not sup.recoveries and time.time() < deadline:
                time.sleep(0.02)
        assert sup.recoveries and sup.recoveries[0]["shard"] == 0
        assert group.servers[0] is not wedged
        assert sup.heartbeat(0, timeout=1.0)  # fresh PS, fresh lock
        assert sup.heartbeat(1, timeout=1.0)  # the healthy shard never left
        # the wedged handler (blocked past stop's join budget) was reported
        assert "still alive" in caplog.text
        # and the snapshot tick skipped the wedged shard instead of
        # deadlocking (we reached this line at all proves the loop lived)
    finally:
        wedged.ps._lock.release()
        sup.stop()
        group.stop()


# ---------------------------------------------------------------------------
# single-socket PSWorker reconnect-resume
# ---------------------------------------------------------------------------

def test_single_socket_worker_reconnect_resume():
    """The non-sharded transport recovers too: the PS crashes and a
    replacement (generation 1, restored state) binds the same port; the
    worker re-dials mid-run, re-syncs, and its stale-generation in-flight
    commit is rejected rather than applied to the restored center."""
    blob = _tiny_blob()
    ps = DeltaParameterServer(blob)
    server = SocketParameterServer(ps)
    server.start()
    port = server.port
    wk = DOWNPOURWorker(blob, "sgd", "mse", "127.0.0.1", port,
                        recovery=True, retry_policy=FAST)
    replacement = None
    try:
        wk.connect()
        wk.pull()
        assert wk._gen == 0
        applied, center = wk.update([np.ones(3, np.float32)], 0)
        assert wk._last_clock == 1
        # pool-decoded views are only valid until the next receive: copy
        # before the background restart thread reads them
        center = [np.array(w) for w in center]

        server.crash()

        def restart():
            time.sleep(0.3)  # the worker must actually wait through this
            ps2 = DeltaParameterServer(
                {"model": blob["model"], "weights": center})
            ps2.num_updates = 1
            srv = SocketParameterServer(ps2, port=port, generation=1)
            srv.start()
            return srv

        th = [None]

        def run():
            th[0] = restart()

        rt = threading.Thread(target=run)
        rt.start()
        # mid-run op against the dead PS: reconnect-resume, not a raise
        w = wk.pull()
        rt.join()
        replacement = th[0]
        assert wk.resumes >= 1 and wk._gen == 1
        np.testing.assert_array_equal(np.asarray(w[0]), np.ones(3))
        applied, center = wk.update([np.ones(3, np.float32)], 0)
        assert wk._last_clock == 2
        np.testing.assert_array_equal(np.asarray(center[0]), np.full(3, 2.0))
        wk.disconnect()
    finally:
        server.stop()
        if replacement is not None:
            replacement.stop()


def test_worker_without_recovery_still_fails_fast():
    """recovery=False (default): a mid-run transport fault raises
    immediately — the PR 2 contract, bit for bit."""
    ps = DeltaParameterServer(_tiny_blob())
    server = SocketParameterServer(ps)
    server.start()
    wk = DOWNPOURWorker(_tiny_blob(), "sgd", "mse", "127.0.0.1", server.port)
    try:
        wk.connect()
        wk.pull()
        server.crash()
        with pytest.raises((ConnectionError, OSError)):
            for _ in range(3):  # first op may still drain a buffered reply
                wk.pull()
        assert wk.resumes == 0
    finally:
        server.stop()


def test_recovery_knob_validation():
    m = make_model()
    kw = dict(num_workers=2, label_col="label_encoded")
    t = ADAG(m, execution="host_ps", recovery=True, **kw)
    assert t.recovery is True and t.recovery_policy is None
    assert ADAG(m, execution="host_ps", **kw).recovery is False
    with pytest.raises(ValueError, match="recovery"):
        ADAG(m, recovery=True, **kw)  # SPMD: resume is the recovery story
    # process_ps recovery rides the supervised (elastic) engine only
    with pytest.raises(ValueError, match="recovery"):
        ADAG(m, execution="process_ps", recovery=True, **kw)
    t2 = ADAG(m, execution="process_ps", recovery=True, elastic=True, **kw)
    assert t2.recovery and t2.elastic


# ---------------------------------------------------------------------------
# topk residual re-sync across shard restarts (wire_dtype="topk")
# ---------------------------------------------------------------------------

def test_topk_stale_commit_recredits_residual():
    """A gen-rejected sparse commit re-credits its as-applied mass into the
    error-feedback residual — the dropped window ships again on the next
    commit instead of being lost (at density 1.0 the arithmetic is exact:
    after the re-send the center equals the once-dropped delta)."""
    blob = {"model": make_model().to_json(),
            "weights": [np.zeros((8,), np.float32)]}
    ps = DeltaParameterServer(blob)
    server = SocketParameterServer(ps, generation=1)
    server.start()
    try:
        wk = DOWNPOURWorker(blob, "sgd", "mse", "127.0.0.1", server.port,
                            wire_dtype="topk", wire_topk=1.0)
        wk.connect()
        wk._gen = 0  # pretend our view predates a respawn (old generation)
        delta = [np.arange(1, 9, dtype=np.float32)]
        applied, center = wk.update(delta, 0)
        # the commit was DROPPED (stale gen): center untouched, clock still 0
        np.testing.assert_array_equal(np.asarray(center[0]), np.zeros(8))
        assert wk._last_clock == 0 and wk.recredits == 1
        # ...and its whole as-applied mass is back in the residual
        np.testing.assert_allclose(wk._residual_flat, delta[0], atol=1e-7)
        # the stale reply re-synced the generation; a zero follow-up commit
        # ships exactly the re-credited mass
        assert wk._gen == 1
        applied, center = wk.update([np.zeros(8, np.float32)], 0)
        np.testing.assert_allclose(np.asarray(center[0]), delta[0],
                                   atol=1e-6)
        np.testing.assert_allclose(wk._residual_flat, 0.0, atol=1e-7)
        wk.disconnect()
    finally:
        server.stop()


def test_topk_sharded_recredit_only_the_stale_shard():
    """With the commit scattered over shards, only the gen-rejecting
    shard's split is re-credited: the surviving shard's slice applied and
    must NOT be double-counted."""
    blob = _blob(8, 3)
    group = _group(num_shards=2, blob=blob)
    try:
        wk = DOWNPOURWorker(blob, "sgd", "mse", "127.0.0.1",
                            group.ports[0], shard_plan=group.plan,
                            shard_addrs=group.addrs,
                            wire_dtype="topk", wire_topk=1.0)
        wk.connect()
        wk.pull()  # learn every shard's generation (0)
        group.servers[0].generation = 1  # shard 0 "respawned"
        total = group.plan.flat_elements()
        delta_flat = np.arange(1, total + 1, dtype=np.float32)
        delta = []
        off = 0
        for w in blob["weights"]:
            delta.append(delta_flat[off:off + w.size].reshape(w.shape))
            off += w.size
        wk.update(delta, 0)
        assert wk._shard_client.last_stale == [True, False]
        assert wk.recredits == 1
        owner = group.plan.shard_of_flat(np.arange(total))
        res = wk._residual_flat
        # shard-0-owned coordinates are back in the residual...
        np.testing.assert_allclose(res[owner == 0], delta_flat[owner == 0],
                                   atol=1e-7)
        # ...shard-1-owned ones applied and stay out of it
        np.testing.assert_allclose(res[owner == 1], 0.0, atol=1e-7)
        gathered, clocks = group.snapshot()
        flat_c = np.concatenate([g.reshape(-1) for g in gathered])
        np.testing.assert_allclose(flat_c[owner == 1],
                                   delta_flat[owner == 1], atol=1e-6)
        np.testing.assert_allclose(flat_c[owner == 0], 0.0, atol=1e-7)
        wk.disconnect()
    finally:
        group.stop()


# ---------------------------------------------------------------------------
# ChaosProxy — deterministic faults through the real socket stack
# ---------------------------------------------------------------------------

def test_chaos_proxy_is_transparent_without_faults():
    ps = DeltaParameterServer(_tiny_blob())
    server = SocketParameterServer(ps)
    server.start()
    try:
        with ChaosProxy("127.0.0.1", server.port) as proxy:
            sock = networking.connect(proxy.host, proxy.port)
            networking.send_opcode(sock, b"u")
            networking.send_data(sock, {"delta": [np.ones(3, np.float32)],
                                        "worker_id": 0, "clock": 0})
            msg = networking.recv_data(sock)
            assert msg["clock"] == 1
            np.testing.assert_array_equal(msg["weights"][0], np.ones(3))
            networking.send_opcode(sock, b"q")
            sock.close()
            assert proxy.injected == []
    finally:
        server.stop()


def test_chaos_proxy_scripted_reset_triggers_resume():
    """A scripted connection reset at an exact opcode index: the worker
    reconnect-resumes through the proxy and the dropped request is the
    only loss."""
    ps = DeltaParameterServer(_tiny_blob())
    server = SocketParameterServer(ps)
    server.start()
    try:
        with ChaosProxy("127.0.0.1", server.port, seed=1,
                        faults=[ChaosFault(0, 2, "reset")]) as proxy:
            wk = DOWNPOURWorker(_tiny_blob(), "sgd", "mse", proxy.host,
                                proxy.port, recovery=True, retry_policy=FAST)
            wk.connect()
            wk.pull()                                    # op 0
            wk.update([np.ones(3, np.float32)], 0)       # op 1
            # op 2 is reset on the floor: the 'u' never reaches the PS;
            # the worker re-syncs with a pull on a fresh proxy connection
            wk.update([np.ones(3, np.float32)], 0)
            assert wk.resumes >= 1
            assert proxy.injected == [(0, 2, "reset")]
            # exactly one of the two commits applied (the reset one dropped)
            assert ps.num_updates == 1
            wk.disconnect()
    finally:
        server.stop()


def test_chaos_proxy_torn_frame_drops_connection_center_untouched():
    """A torn 'u' frame (half the payload, then RST): the server drops
    that connection without applying — the real torn-frame policy, driven
    through real sockets — and the worker recovers."""
    ps = DeltaParameterServer(_tiny_blob())
    server = SocketParameterServer(ps)
    server.start()
    try:
        with ChaosProxy("127.0.0.1", server.port, seed=1,
                        faults=[ChaosFault(0, 1, "tear")]) as proxy:
            wk = DOWNPOURWorker(_tiny_blob(), "sgd", "mse", proxy.host,
                                proxy.port, recovery=True, retry_policy=FAST)
            wk.connect()
            wk.pull()                               # op 0
            wk.update([np.ones(3, np.float32)], 0)  # op 1: torn mid-frame
            assert wk.resumes >= 1
            assert ps.num_updates == 0  # the torn commit never applied
            applied, center = wk.update([np.ones(3, np.float32)], 0)
            assert ps.num_updates == 1
            np.testing.assert_array_equal(np.asarray(center[0]), np.ones(3))
            wk.disconnect()
    finally:
        server.stop()


def test_chaos_proxy_duplicated_reply_is_discarded():
    """A duplicated 'u' reply (replayed by the network) must not desync
    the pipeline: the worker discards the stale duplicate — a genuine
    combined reply always advances the clock — and the next window reads
    the right reply."""
    ps = DeltaParameterServer(_tiny_blob())
    server = SocketParameterServer(ps)
    server.start()
    try:
        with ChaosProxy("127.0.0.1", server.port, seed=1,
                        faults=[ChaosFault(0, 1, "dup_reply")]) as proxy:
            wk = DOWNPOURWorker(_tiny_blob(), "sgd", "mse", proxy.host,
                                proxy.port, recovery=True, retry_policy=FAST)
            wk.connect()
            wk.pull()
            wk.update([np.ones(3, np.float32)], 0)  # reply duplicated
            applied, center = wk.update([np.ones(3, np.float32)], 0)
            assert wk.stale_replies == 1  # the duplicate was discarded
            assert wk._last_clock == 2
            np.testing.assert_array_equal(np.asarray(center[0]),
                                          np.full(3, 2.0))
            wk.disconnect()
    finally:
        server.stop()


def test_chaos_proxy_delay_stalls_the_round_trip():
    ps = DeltaParameterServer(_tiny_blob())
    server = SocketParameterServer(ps)
    server.start()
    try:
        with ChaosProxy("127.0.0.1", server.port,
                        faults=[ChaosFault(0, 0, "delay", 0.25)]) as proxy:
            sock = networking.connect(proxy.host, proxy.port)
            t0 = time.perf_counter()
            networking.send_opcode(sock, b"p")
            networking.recv_data(sock)
            assert time.perf_counter() - t0 >= 0.25
            sock.close()
    finally:
        server.stop()


def test_chaos_proxy_seeded_auto_faults_are_reproducible():
    """auto mode draws per-opcode faults from a stream seeded by
    (seed, connection index): a connection's fault sequence is a pure
    function of the seed and its opcode count.  Asserted on the decision
    stream itself — the *realized* end-to-end fault list additionally
    depends on how many connections a recovering worker dials, which is
    wall-clock-timing dependent (this used to make the test flaky) — plus
    a live-traffic run showing faults land and the worker survives them."""
    import random

    def stream(seed, conn, n=20):
        proxy = ChaosProxy.__new__(ChaosProxy)  # decision logic only
        proxy.faults = []
        proxy.auto = {"reset": 0.3}
        rng = random.Random((seed << 20) ^ conn)
        return [(f.action if f is not None else None)
                for f in (proxy._fault_for(conn, i, rng) for i in range(n))]

    for conn in range(4):
        assert stream(42, conn) == stream(42, conn)  # seeded: deterministic
    assert stream(42, 0) != stream(42, 1)  # per-connection streams differ
    assert stream(42, 0) != stream(7, 0)   # and follow the seed
    assert any(a == "reset" for a in stream(42, 0))  # p=0.3 over 20 draws

    ps = DeltaParameterServer(_tiny_blob())
    server = SocketParameterServer(ps)
    server.start()
    try:
        with ChaosProxy("127.0.0.1", server.port, seed=42,
                        auto={"reset": 0.3}) as proxy:
            wk = DOWNPOURWorker(_tiny_blob(), "sgd", "mse", proxy.host,
                                proxy.port, recovery=True,
                                retry_policy=FAST.replace(seed=42))
            wk.connect()
            wk.pull()
            for _ in range(6):
                wk.update([np.ones(3, np.float32)], 0)
            wk.disconnect()
            assert len(proxy.injected) >= 1  # faults really landed
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# end to end: mid-run reconnect-resume through the trainer (satellite 3)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("cls,shards,kw", [
    (DOWNPOUR, 1, {"learning_rate": 0.05}),
    (DOWNPOUR, 3, {"learning_rate": 0.05}),
    (ADAG, 1, {"learning_rate": 0.1}),
    (ADAG, 3, {"learning_rate": 0.1}),
    (DynSGD, 1, {"learning_rate": 0.05}),
    (DynSGD, 3, {"learning_rate": 0.05}),
    # wire_dtype="topk" column: sparse commits + device-side selection
    # survive the respawn too, with the EF residual staying correct
    (DOWNPOUR, 3, {"learning_rate": 0.05, "wire_dtype": "topk",
                   "wire_topk": 0.1}),
    (ADAG, 1, {"learning_rate": 0.1, "wire_dtype": "topk",
               "wire_topk": 0.1}),
])
def test_mid_run_reconnect_resume(cls, shards, kw):
    """Delta/ADAG/DynSGD x ps_shards in {1, 3} (plus a sparse-topk column):
    a shard crash mid-run is survived — the supervisor respawns it with the
    generation bumped, the workers reconnect without restarting the run,
    every sampled per-shard clock is monotone non-decreasing across the
    restart, and the run still learns.  Under wire_dtype="topk" the
    error-feedback residual must additionally stay correct (finite, and
    bounded by the staleness the run already tolerates) across the
    respawn."""
    ds = make_dataset(n=1024)
    t = cls(make_model(), num_workers=2, batch_size=32, num_epoch=2,
            communication_window=4, label_col="label_encoded",
            execution="host_ps", ps_shards=shards, recovery=True, **kw)
    samples = []
    stop = threading.Event()

    def watcher():
        while getattr(t, "_ps_supervisor", None) is None and not stop.is_set():
            time.sleep(0.005)
        sup = t._ps_supervisor
        while sup.group.servers[0].ps.num_updates < 2 and not stop.is_set():
            time.sleep(0.005)
        sup.kill_shard(0)
        while not stop.is_set():  # sample worker-visible clocks until done
            for w in getattr(t, "_ps_workers", []):
                c = getattr(w, "_shard_client", None)
                if c is not None:
                    samples.append((id(w), list(c._clocks)))
            time.sleep(0.005)

    th = threading.Thread(target=watcher)
    th.start()
    try:
        fitted = t.train(ds)
    finally:
        stop.set()
        th.join()
    sup = t._ps_supervisor
    assert len(sup.recoveries) >= 1
    assert sup.recoveries[0]["shard"] == 0
    assert sup.recoveries[0]["generation"] >= 1
    # the workers learned the restarted shard's new generation
    gens = [w._shard_client._gens[0] for w in t._ps_workers]
    assert all(g is not None and g >= 1 for g in gens)
    assert any(w._shard_client.resumes >= 1 for w in t._ps_workers)
    # per-shard clocks stayed monotone across the restart, per worker
    last: dict = {}
    for wid, clocks in samples:
        if wid in last:
            assert all(a >= b for a, b in zip(clocks, last[wid])), \
                (clocks, last[wid])
        last[wid] = clocks
    if kw.get("wire_dtype") == "topk":
        # residual correctness across the respawn: every worker's EF
        # residual exists (commits ran sparse) and is finite — a corrupted
        # re-credit would show up as NaN/inf or runaway magnitude here
        for w in t._ps_workers:
            res = (w._residual_dev if w._residual_dev is not None
                   else w._residual_flat)
            assert res is not None
            res = np.asarray(res)
            assert np.all(np.isfinite(res))
    assert eval_accuracy(fitted, ds) > 0.6


def test_recovery_survives_chaos_proxy_shard_kill_mid_epoch():
    """ACCEPTANCE: workers ride ChaosProxies to every shard; the shard-0
    proxy's deterministic script kills the shard mid-epoch.  The supervisor
    restores it from the last snapshot on the same port; the workers
    reconnect through the proxy and training completes and learns."""
    ds = make_dataset(n=512)
    t = ADAG(make_model(), num_workers=2, batch_size=32, num_epoch=3,
             communication_window=4, learning_rate=0.1,
             label_col="label_encoded", execution="host_ps", ps_shards=2,
             recovery=True)
    proxies = []

    def hook(addrs):
        for j, (h, p) in enumerate(addrs):
            faults = []
            if j == 0:  # the 4th opcode on the first connection: shard dies
                faults = [ChaosFault(0, 3, "call",
                                     lambda: t._ps_supervisor.kill_shard(0))]
            proxies.append(ChaosProxy(h, p, seed=j, faults=faults))
        return [p.addr for p in proxies]

    t._shard_addr_hook = hook
    try:
        fitted = t.train(ds)
    finally:
        for p in proxies:
            p.stop()
    sup = t._ps_supervisor
    assert any(act == "call" for _, _, act in proxies[0].injected)
    assert len(sup.recoveries) >= 1 and sup.recoveries[0]["shard"] == 0
    assert any(w._shard_client.resumes >= 1 for w in t._ps_workers)
    assert eval_accuracy(fitted, ds) > 0.6


@pytest.mark.slow
def test_chaos_soak_one_shard_kill_per_epoch():
    """Soak (satellite 5): a seeded ChaosProxy fronts every shard; the
    shard-0 proxy kills its shard once per epoch-sized stretch of traffic
    for a 5-epoch run, with seeded random delays sprinkled on top.
    Training must still converge within tolerance."""
    ds = make_dataset(n=1024)
    t = ADAG(make_model(), num_workers=2, batch_size=32, num_epoch=5,
             communication_window=4, learning_rate=0.1,
             label_col="label_encoded", execution="host_ps", ps_shards=2,
             recovery=True)
    proxies = []
    # 1024 rows / 2 workers = 512 each; window*batch = 128 -> 4 windows per
    # epoch per worker: every connection's 4th opcode (initial pull + 3
    # windows in) kills shard 0 — once per epoch-equivalent per connection
    windows_per_epoch = 4

    def hook(addrs):
        for j, (h, p) in enumerate(addrs):
            faults = []
            if j == 0:
                faults = [ChaosFault(-1, windows_per_epoch, "call",
                                     lambda: t._ps_supervisor.kill_shard(0))]
            proxies.append(ChaosProxy(h, p, seed=j,
                                      auto={"delay": (0.02, 0.01)},
                                      faults=faults))
        return [p.addr for p in proxies]

    t._shard_addr_hook = hook
    try:
        fitted = t.train(ds)
    finally:
        for p in proxies:
            p.stop()
    sup = t._ps_supervisor
    assert len(sup.recoveries) >= 2  # it really did keep dying
    assert eval_accuracy(fitted, ds) > 0.6
