"""LR schedules + gradient accumulation on the trainer surface.

No reference counterpart (the 2016 upstream is fixed-LR throughout —
SURVEY.md §5 config row); this is the round-3 VERDICT #9 modernization:
``lr_schedule`` (warmup_cosine / cosine / callable) and
``gradient_accumulation`` exposed through the existing kwargs surface on
all three engines (single, SPMD, host_ps).
"""

import numpy as np
import pytest

from distkeras_tpu import ADAG, SingleTrainer
from distkeras_tpu.core.optimizers import build, build_tx, get_schedule

from test_trainers import eval_accuracy, make_dataset, make_model


def test_get_schedule_closed_forms():
    # warmup_cosine: 0 at step 0, peak at warmup end, ~0 at horizon
    s = get_schedule("warmup_cosine", base_lr=0.1, total_steps=100)
    assert float(s(0)) == 0.0
    np.testing.assert_allclose(float(s(10)), 0.1, rtol=1e-6)
    assert float(s(100)) < 1e-8
    # overrides via dict
    s2 = get_schedule({"name": "warmup_cosine", "warmup_steps": 4,
                       "decay_steps": 50}, base_lr=1.0)
    np.testing.assert_allclose(float(s2(4)), 1.0, rtol=1e-6)
    # cosine: starts at base, ends at alpha*base
    c = get_schedule({"name": "cosine", "alpha": 0.1}, base_lr=0.2,
                     total_steps=10)
    np.testing.assert_allclose(float(c(0)), 0.2, rtol=1e-6)
    np.testing.assert_allclose(float(c(10)), 0.02, rtol=1e-6)
    # constant / None / callable passthrough
    assert get_schedule("constant", 0.3, 10) == 0.3
    assert get_schedule(None, 0.3) == 0.3
    f = lambda step: 0.5
    assert get_schedule(f, 0.3) is f
    # validation
    with pytest.raises(ValueError, match="decay_steps"):
        get_schedule("warmup_cosine", 0.1)  # no horizon anywhere
    with pytest.raises(ValueError, match="unknown lr_schedule"):
        get_schedule("polynomial", 0.1, 10)
    with pytest.raises(ValueError, match="unknown lr_schedule keys"):
        get_schedule({"name": "cosine", "warmup_steps": 3}, 0.1, 10)
    with pytest.raises(ValueError, match="unknown lr_schedule keys"):
        get_schedule({"name": "constant", "warmup_steps": 3}, 0.1, 10)
    with pytest.raises(TypeError, match="lr_schedule"):
        get_schedule(42, 0.1, 10)


def test_build_rejects_bad_accumulation():
    import jax
    params = make_model().init(jax.random.PRNGKey(0), (16,))
    with pytest.raises(ValueError, match="gradient_accumulation"):
        build_tx("sgd", params, 0.1, gradient_accumulation=0)
    # k=1 is the plain transformation (no MultiSteps wrapper state)
    tx, state = build("sgd", params, 0.1, gradient_accumulation=1)
    assert not hasattr(state, "mini_step")


def test_gradient_clip_norm():
    import jax
    import jax.numpy as jnp
    import optax
    params = make_model().init(jax.random.PRNGKey(0), (16,))
    tx, state = build("sgd", params, 1.0, gradient_clip_norm=1e-3)
    # giant synthetic grads: the applied update's global norm is exactly
    # lr * clip (sgd lr=1.0)
    grads = jax.tree_util.tree_map(lambda p: jnp.ones_like(p) * 100.0,
                                   params)
    updates, _ = tx.update(grads, state, params)
    np.testing.assert_allclose(float(optax.global_norm(updates)), 1e-3,
                               rtol=1e-5)
    # under the norm: untouched (plain sgd)
    small = jax.tree_util.tree_map(lambda p: jnp.full_like(p, 1e-6), params)
    upd2, _ = tx.update(small, state, params)
    np.testing.assert_allclose(np.asarray(jax.tree_util.tree_leaves(upd2)[0]),
                               -1e-6, rtol=1e-5)
    with pytest.raises(ValueError, match="gradient_clip_norm"):
        build_tx("sgd", params, 1.0, gradient_clip_norm=0.0)
    # trainers validate eagerly at construction, like accumulation
    with pytest.raises(ValueError, match="gradient_clip_norm"):
        SingleTrainer(make_model(), gradient_clip_norm=0.0)


def test_zero_schedule_freezes_params():
    """A callable schedule is really driving the optimizer: lr ≡ 0 must
    leave the initial weights untouched through a full train()."""
    ds = make_dataset(n=256)
    model = make_model()
    t = SingleTrainer(model, batch_size=32, num_epoch=2,
                      label_col="label_encoded", worker_optimizer="sgd",
                      learning_rate=0.1, lr_schedule=lambda step: 0.0)
    fitted = t.train(ds)
    import jax
    init = model.get_weights(model.init(jax.random.PRNGKey(t.seed), (16,)))
    for a, b in zip(fitted.get_weights(), init):
        np.testing.assert_array_equal(a, b)


def test_accumulation_matches_large_batch():
    """SGD + gradient_accumulation=K on batch B equals plain SGD on batch
    K*B (MultiSteps averages the K mini-step gradients; with full masks the
    average of two 16-row means is the 32-row mean)."""
    ds = make_dataset(n=512)  # divisible by 32: every mask is all-ones
    kw = dict(label_col="label_encoded", worker_optimizer="sgd",
              learning_rate=0.1, num_epoch=2, seed=3)
    small = SingleTrainer(make_model(), batch_size=16,
                          gradient_accumulation=2, **kw)
    big = SingleTrainer(make_model(), batch_size=32, **kw)
    w_small = small.train(ds).get_weights()
    w_big = big.train(ds).get_weights()
    for a, b in zip(w_small, w_big):
        np.testing.assert_allclose(a, b, atol=1e-5)


def test_spmd_schedule_and_accumulation_converge(eight_devices):
    """The flagship path: ADAG over the 8-device mesh with warmup+cosine
    and gradient accumulation still reaches the accuracy bar."""
    ds = make_dataset()
    t = ADAG(make_model(), num_workers=8, batch_size=16, num_epoch=4,
             communication_window=4, label_col="label_encoded",
             worker_optimizer="sgd", learning_rate=0.3,
             lr_schedule="warmup_cosine", gradient_accumulation=2,
             gradient_clip_norm=5.0)
    fitted = t.train(ds)
    assert eval_accuracy(fitted, ds) > 0.9
    # the schedule horizon the trainer derived: rounds*window*epochs / K
    assert t._schedule_steps == t.num_epoch * 4 * 4 // 2


def test_validation_history_and_metrics(eight_devices, tmp_path):
    """validation_data records a per-epoch val loss (JSONL 'val' events on
    the distributed path) and it decreases on learnable data."""
    import json
    ds = make_dataset(n=1024, seed=0)
    val = make_dataset(n=256, seed=9)
    path = str(tmp_path / "m.jsonl")
    t = ADAG(make_model(), num_workers=8, batch_size=8, num_epoch=4,
             communication_window=4, label_col="label_encoded",
             worker_optimizer="adam", learning_rate=1e-3,
             metrics_path=path)
    t.train(ds, validation_data=val)
    assert len(t.validation_history) == 4
    assert t.validation_history[-1] < t.validation_history[0]
    assert t.stopped_epoch is None
    events = [json.loads(l) for l in open(path)]
    assert sum(e.get("kind") == "val" for e in events) == 4

    s = SingleTrainer(make_model(), batch_size=32, num_epoch=3,
                      label_col="label_encoded", worker_optimizer="adam",
                      learning_rate=1e-3)
    s.train(ds, validation_data=val)
    assert len(s.validation_history) == 3
    assert s.validation_history[-1] < s.validation_history[0]


def test_early_stopping_halts_on_plateau():
    """Unlearnable labels: validation loss plateaus immediately, so
    patience=2 must cut a 20-epoch run short."""
    rng = np.random.default_rng(0)
    import numpy as _np
    from distkeras_tpu import Dataset, OneHotTransformer
    noise = Dataset({"features": rng.standard_normal((256, 16)).astype(
        _np.float32), "label": rng.integers(0, 4, 256)})
    noise = OneHotTransformer(4, input_col="label",
                              output_col="label_encoded").transform(noise)
    val = make_dataset(n=128, seed=5)
    t = SingleTrainer(make_model(), batch_size=32, num_epoch=20,
                      label_col="label_encoded", worker_optimizer="sgd",
                      learning_rate=0.05, early_stopping_patience=2)
    t.train(noise, validation_data=val)
    assert t.stopped_epoch is not None
    assert len(t.validation_history) < 20
    # epochs actually trained == epochs validated
    assert len(t.get_history()) == len(t.validation_history) * (256 // 32)


def test_validation_kwarg_validation(eight_devices):
    from distkeras_tpu import AveragingTrainer
    ds = make_dataset(n=256)
    with pytest.raises(ValueError, match="early_stopping_patience"):
        SingleTrainer(make_model(), label_col="label_encoded",
                      early_stopping_patience=3).train(ds)
    with pytest.raises(ValueError, match="between-epoch hook|spmd"):
        ADAG(make_model(), num_workers=2, label_col="label_encoded",
             execution="host_ps").train(ds, validation_data=ds)
    # patience on an async engine is dead config even without val data
    with pytest.raises(ValueError, match="between-epoch hook|spmd"):
        ADAG(make_model(), num_workers=2, label_col="label_encoded",
             execution="host_ps", early_stopping_patience=2).train(ds)
    # local-family trainers never move the center: refused at construction
    with pytest.raises(ValueError, match="center"):
        AveragingTrainer(make_model(), num_workers=2,
                         early_stopping_patience=2)
    with pytest.raises(ValueError, match="early_stopping_patience"):
        SingleTrainer(make_model(), early_stopping_patience=0)


def test_host_ps_schedule_and_accumulation_converge(eight_devices):
    ds = make_dataset(n=1024)
    t = ADAG(make_model(), num_workers=2, batch_size=16, num_epoch=4,
             communication_window=2, label_col="label_encoded",
             worker_optimizer="sgd", learning_rate=0.3,
             lr_schedule="warmup_cosine", gradient_accumulation=2,
             execution="host_ps")
    fitted = t.train(ds)
    assert eval_accuracy(fitted, ds) > 0.9


def test_lion_optimizer_resolves_and_steps():
    import jax.numpy as jnp
    from distkeras_tpu.core.optimizers import get_optimizer
    tx = get_optimizer("lion").to_optax()
    params = {"w": jnp.ones((4,))}
    state = tx.init(params)
    updates, state = tx.update({"w": jnp.full((4,), 0.5)}, state, params)
    # lion: sign-based updates scaled by lr (1e-4 default)
    np.testing.assert_allclose(np.asarray(updates["w"]),
                               -1e-4 * np.ones(4), rtol=1e-5)
