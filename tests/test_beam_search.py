"""Beam-search decoding (core/decode.py :: beam_search).

Semantics pinned against greedy decode and hand-checkable invariants: k=1
reduces to generate(), beams come back sorted, scores are true summed token
log-probs (re-scored by a teacher-forced forward), eos freezes a beam into
padding, and the trained x+1 LM's best beam follows the learned rule.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.core.decode import beam_search, generate
from distkeras_tpu.models.zoo import transformer_lm


def tiny_lm(seed=0):
    model = transformer_lm(vocab_size=16, seq_len=24, d_model=32,
                           num_heads=4, num_layers=2, mlp_dim=64,
                           compute_dtype="float32")
    return model, model.init(jax.random.PRNGKey(seed))


PROMPT = np.array([[3, 4, 5], [7, 8, 9]], np.int32)


def test_shapes_and_sorted():
    model, params = tiny_lm()
    toks, scores = beam_search(model, params, PROMPT, 6, num_beams=3)
    assert toks.shape == (2, 3, 9) and scores.shape == (2, 3)
    s = np.asarray(scores)
    assert (s[:, :-1] >= s[:, 1:] - 1e-6).all(), "beams not sorted"
    np.testing.assert_array_equal(np.asarray(toks)[:, :, :3],
                                  np.broadcast_to(PROMPT[:, None], (2, 3, 3)))


def test_k1_equals_greedy():
    model, params = tiny_lm()
    b1, _ = beam_search(model, params, PROMPT, 7, num_beams=1)
    np.testing.assert_array_equal(np.asarray(b1)[:, 0],
                                  np.asarray(generate(model, params,
                                                      PROMPT, 7)))


def test_scores_are_true_logprobs():
    """Re-score every returned beam with a teacher-forced full forward:
    the summed log-probs must match the search's reported score."""
    model, params = tiny_lm(seed=1)
    toks, scores = beam_search(model, params, PROMPT, 5, num_beams=3)
    toks, scores = np.asarray(toks), np.asarray(scores)
    p = PROMPT.shape[1]
    for bi in range(toks.shape[0]):
        for ki in range(toks.shape[1]):
            seq = toks[bi, ki]
            logits = model.apply(params, jnp.asarray(seq[None]))
            logp = jax.nn.log_softmax(
                jnp.asarray(logits, jnp.float32), axis=-1)
            want = sum(float(logp[0, t - 1, seq[t]])
                       for t in range(p, len(seq)))
            np.testing.assert_allclose(scores[bi, ki], want, rtol=1e-4,
                                       atol=1e-4)


def test_beam_beats_or_matches_greedy_score():
    """The best beam's log-prob is >= greedy's by construction."""
    model, params = tiny_lm(seed=2)
    _, scores = beam_search(model, params, PROMPT, 6, num_beams=4)
    b1, s1 = beam_search(model, params, PROMPT, 6, num_beams=1)
    assert (np.asarray(scores)[:, 0] >= np.asarray(s1)[:, 0] - 1e-5).all()


def test_eos_freezes_and_pads():
    model, params = tiny_lm()
    toks, _ = beam_search(model, params, PROMPT, 6, num_beams=3, eos_id=5,
                          pad_id=0)
    toks = np.asarray(toks)
    for row in toks.reshape(-1, toks.shape[-1]):
        gen = row[PROMPT.shape[1]:]
        if (gen == 5).any():
            after = gen[np.argmax(gen == 5) + 1:]
            assert (after == 0).all(), row


def test_length_penalty_reranks():
    """alpha > 0 divides by length^alpha — ranking must still be sorted
    under the normalized scores it returns."""
    model, params = tiny_lm(seed=3)
    _, ranked = beam_search(model, params, PROMPT, 6, num_beams=4,
                            eos_id=2, length_penalty=1.0)
    r = np.asarray(ranked)
    assert (r[:, :-1] >= r[:, 1:] - 1e-6).all()


def test_trained_lm_best_beam_follows_rule():
    """On the trained x+1 LM the best beam is the rule continuation (same
    as greedy, which tests/test_decode.py pins)."""
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.trainers import SingleTrainer

    model = transformer_lm(vocab_size=16, seq_len=12, d_model=32,
                           num_heads=4, num_layers=2, mlp_dim=64,
                           compute_dtype="float32")
    rng = np.random.default_rng(0)
    x = rng.integers(0, 16, (256, 12)).astype(np.int32)
    t = SingleTrainer(model, batch_size=32, num_epoch=25,
                      loss="sparse_categorical_crossentropy_from_logits",
                      worker_optimizer="adam", learning_rate=3e-3)
    fitted = t.train(Dataset({"features": x, "label": (x + 1) % 16}))

    prompt = np.array([[3, 4, 5, 6]], np.int32)
    toks, scores = fitted.beam_search(prompt, 6, num_beams=3)
    want = (prompt[:, -1:] + 1 + np.arange(6)) % 16
    np.testing.assert_array_equal(np.asarray(toks)[:, 0, 4:], want)


def test_validation():
    model, params = tiny_lm()
    with pytest.raises(ValueError, match="num_beams"):
        beam_search(model, params, PROMPT, 4, num_beams=0)
    with pytest.raises(ValueError, match="num_steps"):
        beam_search(model, params, PROMPT, 0)
    with pytest.raises(ValueError, match="length_penalty"):
        beam_search(model, params, PROMPT, 4, length_penalty=-1)
    with pytest.raises(ValueError, match="eos_id"):
        beam_search(model, params, PROMPT, 4, eos_id=99)
    with pytest.raises(ValueError, match="pad_id"):
        beam_search(model, params, PROMPT, 4, pad_id=0)
    with pytest.raises(ValueError, match="pad_id"):
        # out of vocabulary range (ADVICE r4: mirror the eos_id check)
        beam_search(model, params, PROMPT, 4, eos_id=1, pad_id=99)
    with pytest.raises(ValueError, match="positional"):
        beam_search(model, params, PROMPT, 30)  # past the context limit
