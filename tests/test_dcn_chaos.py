"""Simulated-DCN chaos rail (ISSUE 20): WAN-grade ``ChaosProxy`` actions
(partition/asymmetric-delay/bandwidth), the ``ProcessChaos`` signal
controller, half-open-connection reaping on both PS cores, and worker
partition tolerance — capped by the two-process chaos acceptance run.

Tier-1 legs here are loopback-local and bounded-wait (condition polls
with deadlines; the only fixed intervals are the sub-second chaos
windows themselves).  The multi-process acceptance soak — worker SIGKILL
+ PS kill/journal-respawn + a freeze-and-heal partition across real OS
processes — is additionally marked ``slow``.
"""

import random
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from distkeras_tpu import networking
from distkeras_tpu.networking import (ChaosFault, ChaosProxy, ProcessChaos,
                                      ProcessFault)
from distkeras_tpu.parameter_servers import (DeltaParameterServer,
                                             _enable_keepalive,
                                             make_socket_server)
from distkeras_tpu.resilience import Partitioned
from distkeras_tpu.workers import DOWNPOURWorker

from test_host_ps import make_model

pytestmark = pytest.mark.dcn

SHAPES = [(2048,), (3,)]


def _blob():
    """Protocol-only blob (no keras model): one 8 KiB tensor so bandwidth
    shaping has something to pace, one tiny one."""
    return {"model": "{}",
            "weights": [np.zeros(s, np.float32) for s in SHAPES]}


def _model_blob(n=3):
    return {"model": make_model().to_json(),
            "weights": [np.zeros((n,), np.float32)]}


def _wait(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while not pred() and time.monotonic() < deadline:
        time.sleep(0.01)
    assert pred()


def _heartbeat(host, port, timeout=1.0):
    """One 'h' round trip on a fresh dial; raises on a dead/partitioned
    path."""
    sock = networking.connect(host, port)
    try:
        sock.settimeout(timeout)
        networking.send_opcode(sock, b"h")
        return networking.recv_data(sock)
    finally:
        try:
            sock.close()
        except OSError:
            pass


@pytest.fixture(params=["threaded", "event"])
def core(request):
    return request.param


@pytest.fixture(params=["python", "native"])
def codec(request):
    """Force one wire-codec implementation (test_wirecodec's idiom): the
    'python' leg nulls the native module so the pure-Python fallback
    carries the chaos traffic end to end; 'native' runs only where the
    extension is already built (test_wirecodec builds it; standalone runs
    without it skip the leg rather than paying a build here)."""
    old = networking._native
    if request.param == "python":
        networking._native = None
    elif networking._native is None:
        pytest.skip("native wire codec not built")
    yield request.param
    networking._native = old


# ---------------------------------------------------------------------------
# half-open-connection reaping (both PS cores)
# ---------------------------------------------------------------------------

def test_enable_keepalive_tightens_probe_schedule():
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    cli = socket.create_connection(srv.getsockname())
    conn, _ = srv.accept()
    try:
        _enable_keepalive(conn, 6.0)
        assert conn.getsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE) == 1
        if hasattr(socket, "TCP_KEEPIDLE"):
            assert conn.getsockopt(socket.IPPROTO_TCP,
                                   socket.TCP_KEEPIDLE) == 3
            assert conn.getsockopt(socket.IPPROTO_TCP,
                                   socket.TCP_KEEPINTVL) == 1
            assert conn.getsockopt(socket.IPPROTO_TCP,
                                   socket.TCP_KEEPCNT) == 3
        # without a deadline only the keepalive bit is set (OS schedule)
        _enable_keepalive(cli)
        assert cli.getsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE) == 1
    finally:
        conn.close()
        cli.close()
        srv.close()


def test_idle_deadline_validation(core):
    ps = DeltaParameterServer(_blob())
    for bad in (0, -1.0):
        with pytest.raises(ValueError, match="idle_deadline"):
            make_socket_server(ps, ps_core=core, idle_deadline=bad)


def test_half_open_peer_is_reaped(core):
    """A peer that vanishes without RST (SIGKILLed process, partitioned
    host) used to pin ``live_connections`` forever; with ``idle_deadline``
    the silent connection is reaped while an active one keeps serving."""
    ps = DeltaParameterServer(_blob())
    server = make_socket_server(ps, ps_core=core, idle_deadline=0.3)
    server.start()
    ghost = live = None
    try:
        ghost = networking.connect("127.0.0.1", server.port)  # never speaks
        _wait(lambda: server.live_connections == 1)
        live = networking.connect("127.0.0.1", server.port)
        # keep the live connection ACTIVE while the ghost idles out —
        # only silence past the deadline is reaped, not slow clients
        deadline = time.monotonic() + 5.0
        while server.reaped == 0 and time.monotonic() < deadline:
            networking.send_opcode(live, b"h")
            networking.recv_data(live)
            time.sleep(0.02)
        assert server.reaped == 1
        _wait(lambda: server.live_connections == 1)
        networking.send_opcode(live, b"p")
        msg = networking.recv_data(live)
        assert msg["clock"] == 0 and len(msg["weights"]) == len(SHAPES)
    finally:
        for s in (ghost, live):
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
        server.stop()


def test_idle_deadline_off_keeps_silent_connections(core):
    """Default (idle_deadline=None): the seed-era contract — an idle
    connection is NOT reaped, however long it stays silent."""
    ps = DeltaParameterServer(_blob())
    server = make_socket_server(ps, ps_core=core)
    server.start()
    ghost = None
    try:
        ghost = networking.connect("127.0.0.1", server.port)
        _wait(lambda: server.live_connections == 1)
        time.sleep(0.45)  # > the other test's deadline, silent throughout
        assert server.reaped == 0
        assert server.live_connections == 1
    finally:
        if ghost is not None:
            ghost.close()
        server.stop()


# ---------------------------------------------------------------------------
# ChaosProxy WAN-grade actions (both codecs x both PS cores)
# ---------------------------------------------------------------------------

def test_chaos_partition_refuses_dials_then_heals(codec, core):
    ps = DeltaParameterServer(_blob())
    server = make_socket_server(ps, ps_core=core)
    server.start()
    proxy = ChaosProxy("127.0.0.1", server.port,
                       faults=[ChaosFault(0, 1, "partition", 0.4)])
    sock = None
    try:
        sock = networking.connect(proxy.host, proxy.port)
        sock.settimeout(5.0)
        networking.send_opcode(sock, b"p")          # op 0: relays fine
        assert networking.recv_data(sock)["clock"] == 0
        t0 = time.monotonic()
        networking.send_opcode(sock, b"h")          # op 1: partition fires
        with pytest.raises((ConnectionError, OSError, ValueError,
                            socket.timeout)):
            networking.recv_data(sock)              # this pair was RST
        # dials INTO the partition are refused (retryable from a worker's
        # reconnect loop, not a wedge)
        with pytest.raises((ConnectionError, OSError, ValueError,
                            socket.timeout)):
            _heartbeat(proxy.host, proxy.port, timeout=1.0)
        # ... then the partition HEALS on the wall clock and relaying
        # resumes for brand-new connections
        healed = None
        deadline = time.monotonic() + 5.0
        while healed is None and time.monotonic() < deadline:
            try:
                healed = _heartbeat(proxy.host, proxy.port, timeout=1.0)
            except (ConnectionError, OSError, ValueError, socket.timeout):
                time.sleep(0.05)
        assert healed is not None and healed["clock"] == 0
        assert time.monotonic() - t0 >= 0.3  # the heal waited out the arg
        assert proxy.injected == [(0, 1, "partition")]
    finally:
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        proxy.stop()
        server.stop()


def test_chaos_asymmetric_delay_directions(codec, core):
    """``delay_up`` holds the REQUEST at the proxy (the server-side apply
    is deferred); ``delay_down`` holds only the REPLY (the server has
    long answered when the client finally hears it)."""
    ps = DeltaParameterServer(_blob())
    server = make_socket_server(ps, ps_core=core)
    server.start()
    proxy = ChaosProxy("127.0.0.1", server.port,
                       faults=[ChaosFault(0, 0, "delay_up", 0.35),
                               ChaosFault(1, 0, "delay_down", 0.35)])
    up = down = None
    try:
        up = networking.connect(proxy.host, proxy.port)
        networking.send_opcode(up, b"c")
        networking.send_data(up, {"delta": [np.ones(s, np.float32)
                                            for s in SHAPES],
                                  "worker_id": 0, "clock": 0})
        # the commit is in flight but held upstream of the server
        assert ps.num_updates == 0
        _wait(lambda: ps.num_updates == 1)

        down = networking.connect(proxy.host, proxy.port)
        down.settimeout(5.0)
        t0 = time.monotonic()
        networking.send_opcode(down, b"p")
        msg = networking.recv_data(down)
        assert time.monotonic() - t0 >= 0.3
        np.testing.assert_array_equal(np.asarray(msg["weights"][1]),
                                      np.ones(3, np.float32))
        assert proxy.injected == [(0, 0, "delay_up"),
                                  (1, 0, "delay_down")]
    finally:
        for s in (up, down):
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
        proxy.stop()
        server.stop()


def test_chaos_jittered_delay_is_a_pure_function_of_the_seed():
    """(base, jitter) args draw from the connection's seeded rng stream —
    jittered yet reproducible, with no wall clock involved."""
    a = ChaosProxy._jittered((0.2, 0.1), random.Random((7 << 20) ^ 3))
    b = ChaosProxy._jittered((0.2, 0.1), random.Random((7 << 20) ^ 3))
    assert a == b and 0.2 <= a <= 0.3
    rng = random.Random(0)
    assert ChaosProxy._jittered(None, rng) == 0.05     # scalar defaults
    assert ChaosProxy._jittered(0.7, rng) == 0.7       # are rng-free
    assert ChaosProxy._jittered(None, rng, default=1 << 20) == 1 << 20


def test_chaos_bandwidth_shapes_both_directions_bit_exact(codec, core):
    """One 'u' round trip through a 32 KiB/s link: the ~8 KiB request and
    its ~8 KiB combined reply are both paced (>= ~0.5 s wall) and arrive
    BIT-EXACT — shaping changes timing, never bytes."""
    ps = DeltaParameterServer(_blob())
    server = make_socket_server(ps, ps_core=core)
    server.start()
    proxy = ChaosProxy("127.0.0.1", server.port,
                       faults=[ChaosFault(0, 0, "bandwidth", 32768)])
    sock = None
    try:
        sock = networking.connect(proxy.host, proxy.port)
        sock.settimeout(10.0)
        t0 = time.monotonic()
        networking.send_opcode(sock, b"u")
        networking.send_data(sock, {"delta": [np.ones(s, np.float32)
                                              for s in SHAPES],
                                    "worker_id": 0, "clock": 0})
        msg = networking.recv_data(sock)
        assert time.monotonic() - t0 >= 0.35
        assert msg["clock"] == 1
        np.testing.assert_array_equal(np.asarray(msg["weights"][0]),
                                      np.ones(2048, np.float32))
        assert proxy.injected == [(0, 0, "bandwidth")]
    finally:
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        proxy.stop()
        server.stop()


# ---------------------------------------------------------------------------
# ProcessChaos: seeded signal schedules over real OS processes
# ---------------------------------------------------------------------------

def test_process_chaos_schedule_is_deterministic():
    targets = {"w0": 111, "w1": 222}
    kw = dict(auto={"kill": 0.1, "stop": (0.2, 0.5)},
              tick_s=0.25, horizon_s=5.0)
    a = ProcessChaos(targets, seed=3, **kw)
    b = ProcessChaos(targets, seed=3, **kw)
    assert a.schedule == b.schedule  # pure function of the ctor args
    assert any(f.action == "kill" for f in a.schedule)
    stops = [f for f in a.schedule if f.action == "stop"]
    conts = [f for f in a.schedule if f.action == "cont"]
    assert stops, "p=0.2 over 20 ticks x 2 targets must draw a stop"
    # every auto 'stop' schedules its own thaw freeze_s later — no test
    # can leave a stopped process behind by construction
    for f in stops:
        assert any(c.target == f.target
                   and abs(c.at_s - (f.at_s + 0.5)) < 1e-9 for c in conts)
    assert ProcessChaos(targets, seed=4, **kw).schedule != a.schedule


def test_process_chaos_validates_targets_and_actions():
    with pytest.raises(ValueError, match="unknown target"):
        ProcessChaos({"a": 1}, faults=[ProcessFault("b", 0.1, "kill")])
    with pytest.raises(ValueError, match="action"):
        ProcessChaos({"a": 1}, faults=[ProcessFault("a", 0.1, "nuke")])
    with pytest.raises(ValueError, match="auto action"):
        ProcessChaos({"a": 1}, auto={"explode": 0.5})


@pytest.mark.slow  # fires real SIGSTOP/SIGCONT/SIGKILL at a subprocess
def test_process_chaos_fires_signals_and_records_dead_slots():
    """The scripted stop/cont/kill lifecycle against a real (cheap,
    jax-free) process: signals land in order, fire-time pid resolution
    records a signal to an already-reaped slot as ``pid=None``."""
    proc = subprocess.Popen([sys.executable, "-c",
                             "import time; time.sleep(30)"])
    chaos = ProcessChaos({"w": lambda: proc},
                         faults=[ProcessFault("w", 0.05, "stop"),
                                 ProcessFault("w", 0.15, "cont"),
                                 ProcessFault("w", 0.25, "kill"),
                                 ProcessFault("w", 0.6, "kill")])
    try:
        chaos.start()
        assert proc.wait(timeout=10.0) == -signal.SIGKILL
        _wait(lambda: len(chaos.injected) == 4, timeout=5.0)
        assert [(t, a) for t, _, a, _ in chaos.injected] == [
            ("w", "stop"), ("w", "cont"), ("w", "kill"), ("w", "kill")]
        pids = [p for _, _, _, p in chaos.injected]
        assert pids[:3] == [proc.pid] * 3
        assert pids[3] is None  # dead slot: recorded, skipped
    finally:
        chaos.stop()
        if proc.poll() is None:
            proc.kill()


# ---------------------------------------------------------------------------
# worker partition tolerance (partition_windows > 0)
# ---------------------------------------------------------------------------

def test_partition_budget_exhaustion_raises_typed_partitioned():
    """No heal in sight: the worker buffers ``partition_windows`` windows
    of committed mass, then surfaces ``Partitioned`` — typed apart from
    ``PSShardDown`` (the PATH died, not the endpoint; a supervisor must
    not respawn a healthy PS for it) yet still a ``ConnectionError``."""
    blob = _model_blob()
    ps = DeltaParameterServer(blob)
    server = make_socket_server(ps, ps_core="event")
    server.start()
    wk = DOWNPOURWorker(blob, "sgd", "mse", "127.0.0.1", server.port,
                        partition_windows=2)
    try:
        wk.connect()
        wk.pull()
        server.crash()
        d = [np.ones(3, np.float32)]
        with pytest.raises(Partitioned) as ei:
            for _ in range(10):  # first sends may still reach dead buffers
                wk.commit(d, 0)
        assert ei.value.pending_windows == 3  # budget 2 + the overflow
        assert ei.value.addr == ("127.0.0.1", server.port)
        assert isinstance(ei.value, ConnectionError)
        assert wk.partitions == 1 and wk.reconciliations == 0
        # the partition cache still serves the last good center
        assert np.asarray(wk.pull()[0]).shape == (3,)
    finally:
        server.stop()


def test_partition_heal_reconciles_buffered_mass():
    """Through a real scripted partition: the worker keeps computing into
    its pending buffer while dark, the per-window heal probe adopts a
    fresh path once the proxy heals, and the buffered mass lands as ONE
    reconciliation commit — bounded loss is exactly the windows in flight
    at partition onset."""
    blob = _model_blob()
    ps = DeltaParameterServer(blob)
    server = make_socket_server(ps, ps_core="event")
    server.start()
    proxy = ChaosProxy("127.0.0.1", server.port,
                       faults=[ChaosFault(0, 2, "partition", 0.35)])
    wk = DOWNPOURWorker(blob, "sgd", "mse", proxy.host, proxy.port,
                        partition_windows=64)
    try:
        wk.connect()
        wk.pull()                        # op 0
        d = [np.ones(3, np.float32)]
        wk.commit(d, 0)                  # op 1: applied
        wk.commit(d, 0)                  # op 2: dropped at partition onset
        committed = 2
        deadline = time.monotonic() + 8.0
        while wk.reconciliations == 0 and time.monotonic() < deadline:
            wk.commit(d, 0)
            committed += 1
            time.sleep(0.05)
        assert wk.partitions == 1 and wk.reconciliations == 1
        center = np.asarray(wk.pull()[0])
        # every window landed except those in flight when the partition
        # hit (op 2 always; at most one more racing the RST)
        assert committed - 2 <= center[0] <= committed - 1
        np.testing.assert_array_equal(center, np.full(3, center[0]))
    finally:
        proxy.stop()
        server.stop()


def test_partition_windows_trainer_validation():
    from distkeras_tpu import DOWNPOUR
    m = make_model()
    with pytest.raises(ValueError, match="ps_shards"):
        DOWNPOUR(m, num_workers=2, execution="host_ps", ps_shards=2,
                 partition_windows=4)
    with pytest.raises(ValueError, match="process_ps"):
        DOWNPOUR(m, num_workers=2, execution="host_ps", recovery=True,
                 partition_windows=4)


# ---------------------------------------------------------------------------
# the acceptance run: two-process simulated DCN under chaos
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_two_process_dcn_chaos_zero_loss_and_journal_respawn():
    """ROADMAP item 1's acceptance: worker processes training through a
    sharded, recoverable, elastic, process-placed PS over compressed wire
    survive a worker SIGKILL, a PS-shard SIGKILL (same-address journal
    respawn, generation bumped), and a freeze-and-heal partition —
    completing every epoch with ZERO lost examples and a final model in
    the single-host accuracy band."""
    from distkeras_tpu import DOWNPOUR

    from test_trainers import eval_accuracy, make_dataset
    from test_trainers import make_model as make_dense_model

    ds = make_dataset(n=1024)
    t = DOWNPOUR(make_dense_model(), num_workers=2, batch_size=16,
                 num_epoch=3, communication_window=4,
                 label_col="label_encoded", worker_optimizer="sgd",
                 learning_rate=0.05, execution="process_ps", elastic=True,
                 recovery=True, ps_shards=2, ps_placement="process",
                 wire_dtype="bfloat16", freeze_deadline=3.0)
    t.snapshot_interval = 0.2  # journal often: tight bounded-loss window

    box = {}

    def run():
        try:
            box["fitted"] = t.train(ds)
        except BaseException as e:  # surfaced below, not swallowed
            box["error"] = e

    th = threading.Thread(target=run, name="dcn-train")
    th.start()
    chaos = None
    try:
        _wait(lambda: getattr(t, "_process_supervisor", None) is not None
              and len(t._process_supervisor.procs) == 2
              or "error" in box, timeout=180.0)
        assert "error" not in box, box.get("error")
        sup = t._process_supervisor
        chaos = ProcessChaos(
            {"worker1": lambda: sup.procs.get(1),
             "shard0": lambda: sup.ps_procs[0]},
            faults=[
                ProcessFault("worker1", 2.0, "kill"),   # abrupt worker death
                ProcessFault("shard0", 6.0, "kill"),    # PS death -> journal
                                                        # respawn same-address
                ProcessFault("shard0", 12.0, "stop"),   # partition: frozen
                                                        # host, no FIN/RST...
                ProcessFault("shard0", 12.6, "cont"),   # ...heals under the
                                                        # supervisor deadline
            ])
        chaos.start()
        th.join(timeout=600.0)
        assert not th.is_alive(), "DCN chaos run wedged"
        assert "error" not in box, box.get("error")
    finally:
        if chaos is not None:
            chaos.stop()
        th.join(timeout=10.0)

    # zero lost examples: every epoch's lease ledger closed over the full
    # dataset (assert_epoch_complete raised otherwise; re-assert the rows)
    reports = t.elastic_stats["lease_completions"]
    assert sorted(reports) == [0, 1, 2]
    for rep in reports.values():
        assert rep["rows_completed"] == 1024
        assert rep["completed"] == rep["leases"]

    # the worker SIGKILL was seen and a replacement spawned
    delivered = {(tgt, act) for tgt, _, act, pid in chaos.injected
                 if pid is not None}
    assert ("worker1", "kill") in delivered
    assert 1 in t.worker_failures and t.elastic_stats["respawns"] >= 1

    # the PS shard death journal-respawned SAME-ADDRESS with its clock
    # carried forward (monotone across the respawn) and generation bumped
    assert ("shard0", "kill") in delivered
    assert t.elastic_stats["ps_restarts"][0] >= 1
    recs = [r for r in t.elastic_stats["ps_recoveries"]
            if r.get("shard") == 0]
    assert recs

    # final loss inside the single-host band (test_process_ps's
    # chaos-free DOWNPOUR run asserts > 0.8 at 2 epochs)
    assert eval_accuracy(box["fitted"], ds) > 0.8
