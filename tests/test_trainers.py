"""End-to-end trainer tests on the 8-device virtual CPU mesh.

Covers the full reference config matrix at miniature scale (SURVEY.md §6):
SingleTrainer, ADAG, DOWNPOUR, AEASGD, EAMSGD, DynSGD, Averaging/Ensemble —
each must train (loss decreases / accuracy above chance) and return a usable
FittedModel through the predictor+evaluator pipeline.
"""

import jax
import numpy as np
import pytest

from distkeras_tpu import (Sequential, Dense, SingleTrainer, ADAG, DOWNPOUR,
                           AEASGD, EAMSGD, DynSGD, AveragingTrainer,
                           EnsembleTrainer, Dataset, OneHotTransformer,
                           ModelPredictor, LabelIndexTransformer,
                           AccuracyEvaluator)
from distkeras_tpu.parallel import get_mesh


NUM_CLASSES = 4


def make_dataset(n=2048, d=16, seed=0):
    rng = np.random.default_rng(seed)
    protos = rng.uniform(-1, 1, (NUM_CLASSES, d))
    labels = rng.integers(0, NUM_CLASSES, n)
    x = (protos[labels] + 0.3 * rng.standard_normal((n, d))).astype(np.float32)
    ds = Dataset({"features": x, "label": labels.astype(np.int64)})
    return OneHotTransformer(NUM_CLASSES, input_col="label",
                             output_col="label_encoded").transform(ds)


def make_model():
    return Sequential([Dense(32, activation="relu"),
                       Dense(NUM_CLASSES, activation="softmax")],
                      input_shape=(16,), compute_dtype="float32")


def eval_accuracy(fitted, ds):
    pred = ModelPredictor(fitted).predict(ds)
    idx = LabelIndexTransformer().transform(pred)
    return AccuracyEvaluator().evaluate(idx)


def test_single_trainer_learns():
    ds = make_dataset()
    t = SingleTrainer(make_model(), batch_size=32, num_epoch=3,
                      label_col="label_encoded", worker_optimizer="sgd",
                      learning_rate=0.1)
    fitted = t.train(ds)
    assert t.get_training_time() > 0
    assert len(t.get_history()) == 3 * (2048 // 32)
    assert t.get_history()[-1] < t.get_history()[0]
    assert eval_accuracy(fitted, ds) > 0.9


@pytest.mark.parametrize("cls,kw", [
    (ADAG, {"communication_window": 4}),
    (DOWNPOUR, {"communication_window": 4, "learning_rate": 0.02}),
    (DynSGD, {"communication_window": 4}),
    (AEASGD, {"rho": 1.0, "learning_rate": 0.1, "communication_window": 4}),
    (EAMSGD, {"rho": 1.0, "learning_rate": 0.05, "momentum": 0.9,
              "communication_window": 4}),
])
def test_distributed_trainers_learn(eight_devices, cls, kw):
    ds = make_dataset()
    kw.setdefault("learning_rate", 0.1)
    t = cls(make_model(), num_workers=8, batch_size=16, num_epoch=3,
            label_col="label_encoded", worker_optimizer="sgd", **kw)
    fitted = t.train(ds)
    assert t.num_workers == 8
    hist = t.get_history()
    assert len(hist) > 0
    acc = eval_accuracy(fitted, ds)
    assert acc > 0.8, f"{cls.__name__} reached only {acc}"


def test_adag_matches_reference_update_semantics(eight_devices):
    """One ADAG round with window=1 equals the all-reduce-mean SGD step."""
    ds = make_dataset(n=128)
    model = make_model()
    t = ADAG(model, num_workers=8, batch_size=16, num_epoch=1,
             communication_window=1, label_col="label_encoded",
             worker_optimizer="sgd", learning_rate=0.1, seed=7)
    fitted = t.train(ds)
    # manual: same init, one step per worker on its batch, average deltas
    import jax.numpy as jnp
    from distkeras_tpu.core.train import init_state, make_train_step
    params0 = model.init(jax.random.PRNGKey(7))
    state, tx = init_state(model, jax.random.PRNGKey(7), (16,), "sgd", 0.1)
    state = state._replace(params=params0)
    step = make_train_step(model, "categorical_crossentropy", tx)
    x, y = ds["features"], ds["label_encoded"]
    deltas = []
    # worker-major sharding matches shape_epoch_data's layout
    for w in range(8):
        xs = jnp.asarray(x[w * 16:(w + 1) * 16])
        ys = jnp.asarray(y[w * 16:(w + 1) * 16])
        st, _ = step(state, (xs, ys), jax.random.PRNGKey(0))
        deltas.append(jax.tree_util.tree_map(
            lambda a, b: np.asarray(a) - np.asarray(b), st.params, params0))
    mean_delta = jax.tree_util.tree_map(
        lambda *ds_: np.mean(np.stack(ds_), axis=0), *deltas)
    want = jax.tree_util.tree_map(lambda p, d: np.asarray(p) + d, params0,
                                  mean_delta)
    got = fitted.params
    flat_w = jax.tree_util.tree_leaves(want)
    flat_g = jax.tree_util.tree_leaves(got)
    for a, b in zip(flat_w, flat_g):
        np.testing.assert_allclose(a, b, atol=2e-5)


def test_averaging_and_ensemble(eight_devices):
    ds = make_dataset()
    t = AveragingTrainer(make_model(), num_workers=8, batch_size=16,
                         num_epoch=2, label_col="label_encoded",
                         worker_optimizer="sgd", learning_rate=0.1)
    fitted = t.train(ds)
    assert eval_accuracy(fitted, ds) > 0.8

    e = EnsembleTrainer(make_model(), num_models=8, batch_size=16,
                        num_epoch=2, label_col="label_encoded",
                        worker_optimizer="sgd", learning_rate=0.1)
    models = e.train(ds)
    assert len(models) == 8
    accs = [eval_accuracy(m, ds) for m in models[:2]]
    assert all(a > 0.7 for a in accs)
    # ensemble members differ (trained on different shards)
    w0 = models[0].get_weights()[0]
    w1 = models[1].get_weights()[0]
    assert not np.allclose(w0, w1)


def test_predictor_sharded_matches_single(eight_devices):
    ds = make_dataset(n=100)
    t = SingleTrainer(make_model(), batch_size=32, num_epoch=1,
                      label_col="label_encoded", learning_rate=0.1)
    fitted = t.train(ds)
    mesh = get_mesh(8)
    p_single = ModelPredictor(fitted, mesh=None, batch_size=16).predict(ds)
    p_shard = ModelPredictor(fitted, mesh=mesh, batch_size=4).predict(ds)
    np.testing.assert_allclose(p_single["prediction"], p_shard["prediction"],
                               atol=1e-5)


def test_trainer_serialize_and_reuse(eight_devices):
    ds = make_dataset(n=512)
    t = ADAG(make_model(), num_workers=8, batch_size=8, num_epoch=1,
             communication_window=4, label_col="label_encoded",
             learning_rate=0.1)
    fitted = t.train(ds)
    blob = t.serialize()
    from distkeras_tpu.utils import deserialize_keras_model
    fm = deserialize_keras_model(blob)
    x = ds["features"][:10]
    np.testing.assert_allclose(fm.predict(x), fitted.predict(x), rtol=1e-6)
    # warm-start another trainer from the fitted model
    t2 = SingleTrainer(fm, batch_size=32, num_epoch=1,
                       label_col="label_encoded", learning_rate=0.05)
    t2.train(ds)


def test_adag_accuracy_parity_with_single(eight_devices):
    """SURVEY §6 north-star: ADAG's final validation accuracy matches the
    single-worker baseline within epsilon on identical data/model/seed.
    The committed PARITY.json artifact (scripts/accuracy_parity.py) is the
    full-size version of this assertion."""
    train, test = make_dataset(n=2560, seed=11).split(0.8, seed=3)

    s = SingleTrainer(make_model(), batch_size=16, num_epoch=6,
                      label_col="label_encoded", worker_optimizer="adam",
                      learning_rate=1e-3, seed=0)
    single_acc = eval_accuracy(s.train(train, shuffle=True), test)

    a = ADAG(make_model(), num_workers=8, batch_size=16, num_epoch=6,
             communication_window=4, label_col="label_encoded",
             worker_optimizer="adam", learning_rate=1e-3, seed=0)
    adag_acc = eval_accuracy(a.train(train, shuffle=True), test)

    assert single_acc > 0.9 and adag_acc > 0.9
    assert abs(single_acc - adag_acc) < 0.05, (single_acc, adag_acc)


def test_parallelism_factor(eight_devices):
    """Reference parity (SURVEY §2.1 row 6): async trainers accept
    parallelism_factor; host_ps runs factor x num_workers true-async
    workers, SPMD rejects a factor > 1 instead of silently ignoring it."""
    ds = make_dataset(n=512)
    t = ADAG(make_model(), num_workers=2, parallelism_factor=2, batch_size=8,
             num_epoch=4, communication_window=2, label_col="label_encoded",
             worker_optimizer="adam", learning_rate=5e-3,
             execution="host_ps")
    fitted = t.train(ds)
    assert t.parallelism_factor == 2
    assert eval_accuracy(fitted, ds) > 0.5
    with pytest.raises(ValueError):
        ADAG(make_model(), num_workers=2, parallelism_factor=2)
    with pytest.raises(ValueError):
        ADAG(make_model(), num_workers=2, parallelism_factor=0)


def test_ensemble_serialize_returns_all_members(eight_devices):
    from distkeras_tpu.core.model import FittedModel

    ds = make_dataset(n=512)
    e = EnsembleTrainer(make_model(), num_models=4, batch_size=8, num_epoch=1,
                        label_col="label_encoded", worker_optimizer="sgd",
                        learning_rate=0.1)
    with pytest.raises(ValueError):
        e.serialize()
    models = e.train(ds)
    blobs = e.serialize()["ensemble"]
    assert len(blobs) == 4
    x = ds["features"][:8]
    for blob, m in zip(blobs, models):
        np.testing.assert_allclose(FittedModel.deserialize(blob).predict(x),
                                   m.predict(x), rtol=1e-6)
