"""Event-driven serving transport (PR 19): the regression surface the
thread-per-connection core never needed.

 - **Thread accounting** — ``server_core="event"`` holds O(1) server-side
   threads while 64 concurrent wire streams are live (the threaded core
   holds one per connection), and ``stop(join_timeout)`` drains the
   selector, closes every registered connection, and leaks zero fds.
 - **Backpressure cap** — a connection whose outbound token backlog
   exceeds ``max_conn_buffer`` stops being read/pumped until the client
   drains it; the streams still complete, in order, losing nothing.
 - **ClientPool eviction race** — concurrent checkout/release against a
   small ``max_idle_per_addr`` neither double-vends a client nor leaks
   sockets past ``close()`` (the ``_closed`` latch regression).

Wire-parity coverage (same tests on both cores) lives in the serving
matrix via the ``server_core`` fixture; this file pins what is SPECIFIC
to the event core.
"""

import os
import socket
import threading
import time

import numpy as np
import pytest

import jax

from distkeras_tpu import networking
from distkeras_tpu.core.model import FittedModel
from distkeras_tpu.models import transformer_lm
from distkeras_tpu.serving import ServingClient, ServingEngine, ServingServer

VOCAB = 17
PROMPT = np.array([3, 4, 5, 6], np.int32)


def _fitted(seed=0, **kw):
    model = transformer_lm(vocab_size=VOCAB, seq_len=32, d_model=16,
                           num_heads=2, num_layers=2, mlp_dim=32,
                           compute_dtype="float32", **kw)
    params = model.init(jax.random.PRNGKey(seed), (32,))
    return FittedModel(model, params)


@pytest.fixture(scope="module")
def fitted():
    return _fitted()


def _conn_threads():
    """Per-connection server threads alive right now (the O(N) the event
    core exists to eliminate)."""
    return [t for t in threading.enumerate()
            if t.name.startswith("dkt-serving-conn")]


def _open_fds():
    return len(os.listdir("/proc/self/fd"))


# ---------------------------------------------------------------------------
# thread accounting + fd hygiene
# ---------------------------------------------------------------------------

def test_event_core_o1_threads_at_64_streams(fitted):
    eng = ServingEngine(fitted, num_slots=4, max_len=28,
                        queue_capacity=128)
    srv = ServingServer(eng, server_core="event", poll_s=0.01).start()
    fds_after_close = None
    clients = []
    try:
        # 64 live wire connections, each with an in-flight request
        rids = {}
        for i in range(64):
            c = ServingClient(*srv.addr)
            clients.append(c)
            rids[i] = c.submit(PROMPT, 6, temperature=0.5, seed=7)
        assert _conn_threads() == []  # zero per-connection threads
        assert srv._loop is not None and srv._loop.alive
        assert srv._loop.registered() >= 65  # 64 conns + the listener
        done = {}

        def _drain(i, c, rid):
            for _tok, d in c.stream(rid):
                if d is not None:
                    done[i] = d["row"]

        pumps = [threading.Thread(target=_drain, args=(i, c, rids[i]),
                                  daemon=True)
                 for i, c in enumerate(clients)]
        for t in pumps:
            t.start()
        # mid-flight: the server side still holds ONE I/O thread
        assert _conn_threads() == []
        for t in pumps:
            t.join(timeout=120.0)
        assert len(done) == 64
        # every stream completed bit-identically (same seed, same params)
        want = np.asarray(fitted.generate(
            PROMPT[None], 6, max_len=28, temperature=0.5,
            rng=jax.random.PRNGKey(7)))[0]
        for i in range(64):
            np.testing.assert_array_equal(done[i], want)
        for c in clients:
            c.close()
        clients = []
        fds_after_close = _open_fds()
    finally:
        for c in clients:
            c.close()
        srv.stop(join_timeout=10.0)
    # stop() drained the selector: loop thread gone, nothing registered,
    # and the server-side conns + listener returned their fds
    assert not srv._loop.alive
    assert srv._loop.registered() == 0
    assert _conn_threads() == []
    if fds_after_close is not None:
        assert _open_fds() < fds_after_close


def test_event_stop_closes_registered_connections(fitted):
    eng = ServingEngine(fitted, num_slots=2, max_len=24)
    srv = ServingServer(eng, server_core="event").start()
    socks = [networking.connect(*srv.addr) for _ in range(8)]
    deadline = time.monotonic() + 5.0
    while srv._loop.registered() < 9 and time.monotonic() < deadline:
        time.sleep(0.01)  # accepts run on the loop thread
    assert srv._loop.registered() >= 9
    srv.stop(join_timeout=5.0)
    assert srv._loop.registered() == 0
    # every accepted socket sees EOF: the server closed its side
    for s in socks:
        s.settimeout(2.0)
        assert s.recv(1) == b""
        s.close()


# ---------------------------------------------------------------------------
# backpressure: a never-reading client cannot grow the backlog unbounded
# ---------------------------------------------------------------------------

def test_event_write_backlog_is_capped(fitted):
    """64 pipelined streams on ONE socket whose client refuses to read:
    the outbound backlog must stop at ``max_conn_buffer`` (+ the frame
    that crossed it), not absorb all 64 reply streams; once the client
    drains, every stream completes in order with its full token count."""
    cap = 1 << 12
    eng = ServingEngine(fitted, num_slots=4, max_len=28,
                        queue_capacity=128)
    srv = ServingServer(eng, server_core="event", poll_s=0.01,
                        max_conn_buffer=cap).start()
    try:
        # a raw client socket with a TINY receive buffer (set before
        # connect so the advertised window is small) — loopback kernel
        # buffers otherwise absorb the whole backlog and the cap never
        # engages
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
        s.connect(srv.addr)
        n, steps = 64, 16
        rids = []
        for _ in range(n):
            networking.send_opcode(s, networking.SERVING_OP_ENQUEUE)
            networking.send_data(s, {"prompt": PROMPT,
                                     "num_steps": steps})
            ack = networking.recv_data(s)
            assert ack.get("ok"), ack
            rids.append(int(ack["id"]))
        # pin the server side's send buffer small too
        deadline = time.monotonic() + 5.0
        while not srv._econns and time.monotonic() < deadline:
            time.sleep(0.005)
        for cn in list(srv._econns.values()):
            cn.sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF,
                               4096)
        # request all 64 streams back-to-back without reading a byte:
        # stream #1 relays while #2..#64 sit deferred behind it
        for rid in rids:
            networking.send_opcode(s, networking.SERVING_OP_STREAM)
            networking.send_data(s, {"id": rid})
        peak = 0
        deadline = time.monotonic() + 20.0
        paused = False
        while time.monotonic() < deadline and not paused:
            conns = list(srv._econns.values())
            if conns:
                peak = max([peak] + [cn.out_bytes for cn in conns])
                paused = any(cn.paused for cn in conns)
            time.sleep(0.005)
        assert paused, "backlog never hit the cap — backpressure untested"
        # bounded: the cap plus at most one frame that crossed it
        assert peak < cap + (1 << 14)
        # a second client on the same server is unaffected by the stall
        fast = ServingClient(*srv.addr)
        row = fast.generate(PROMPT, 4)
        assert row.shape[0] >= PROMPT.size + 4
        fast.close()
        # drain: all 64 streams arrive whole and in submission order
        for rid in rids:
            toks, finish = [], None
            while finish is None:
                reply = networking.recv_data(s)
                assert not reply.get("error"), reply
                toks.extend(int(t) for t in reply["tokens"])
                if reply["done"]:
                    finish = reply["finish"]
                    assert int(reply["id"]) == rid
            assert finish == "length"
            assert len(toks) == steps
        s.close()
    finally:
        srv.stop(join_timeout=10.0)


# ---------------------------------------------------------------------------
# ClientPool eviction under concurrent checkout (satellite fix)
# ---------------------------------------------------------------------------

def test_client_pool_concurrent_checkout_with_eviction():
    """Two threads hammering acquire/release on one address while
    ``max_idle_per_addr=1`` evicts: no client is ever vended to two
    owners at once, and ``close()`` reaps everything — including a
    client released AFTER close (the ``_closed``-latch regression)."""
    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(128)
    addr = lsock.getsockname()
    accepted = []

    def _accept():
        while True:
            try:
                s, _ = lsock.accept()
            except OSError:
                return
            accepted.append(s)

    threading.Thread(target=_accept, daemon=True).start()

    class _Conn:
        def __init__(self, a):
            self.sock = socket.create_connection(tuple(a))
            self.closed = False

        def close(self):
            self.closed = True
            self.sock.close()

    pool = networking.ClientPool(_Conn, max_idle_per_addr=1)
    vended, errs = [], []
    in_use = set()
    use_lock = threading.Lock()

    def _worker():
        try:
            for _ in range(50):
                cl = pool.acquire(addr)
                with use_lock:
                    assert id(cl) not in in_use, "double-vended client"
                    in_use.add(id(cl))
                    vended.append(cl)
                with use_lock:
                    in_use.discard(id(cl))
                pool.release(addr, cl)
        except BaseException as e:  # surfaced below
            errs.append(e)

    workers = [threading.Thread(target=_worker) for _ in range(2)]
    for t in workers:
        t.start()
    for t in workers:
        t.join(timeout=30.0)
    assert errs == []
    # late release after close: the latch closes it instead of re-parking
    straggler = pool.acquire(addr)
    pool.close()
    pool.release(addr, straggler)
    assert straggler.closed
    assert all(cl.closed for cl in vended)
    lsock.close()
    for s in accepted:
        s.close()


# ---------------------------------------------------------------------------
# event-core mid-stream semantics spot check (single connection)
# ---------------------------------------------------------------------------

def test_event_midstream_cancel_then_deferred_enqueue(fitted):
    eng = ServingEngine(fitted, num_slots=2, max_len=28,
                        queue_capacity=8)
    srv = ServingServer(eng, server_core="event", poll_s=0.01).start()
    try:
        c = ServingClient(*srv.addr)
        rid = c.submit(PROMPT, 16)
        networking.send_opcode(c.sock, networking.SERVING_OP_STREAM)
        networking.send_data(c.sock, {"id": rid})
        # pipelined mid-stream ops on the SAME socket: a cancel for this
        # id (honored immediately) and a deferred follow-up enqueue
        networking.send_opcode(c.sock, networking.SERVING_OP_CANCEL)
        networking.send_data(c.sock, {"id": rid})
        networking.send_opcode(c.sock, networking.SERVING_OP_ENQUEUE)
        networking.send_data(c.sock, {"prompt": PROMPT, "num_steps": 2})
        finish = None
        while finish is None:
            reply = networking.recv_data(c.sock, pool=c._pool)
            assert not reply.get("error"), reply
            if reply["done"]:
                finish = reply["finish"]
        assert finish == "cancel"
        # the deferred enqueue is answered after the final stream frame
        ack = networking.recv_data(c.sock, pool=c._pool)
        assert ack.get("ok") and "id" in ack
        row = None
        for _tok, done in c.stream(int(ack["id"])):
            if done is not None:
                row = done["row"]
        assert row is not None and row.shape[0] >= PROMPT.size + 2
        c.close()
    finally:
        srv.stop(join_timeout=5.0)
