"""Streaming input pipeline: round_stream layout parity with
shape_epoch_data, prefetch_to_device semantics, and the streamed epoch
matching the all-at-once epoch bit-for-bit.
"""

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from distkeras_tpu.data.pipeline import round_stream, prefetch_to_device
from distkeras_tpu.parallel import get_mesh
from distkeras_tpu.parallel.spmd import SPMDEngine, shape_epoch_data

from test_trainers import make_dataset, make_model


def test_round_stream_matches_shape_epoch_data():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((1000, 5)).astype(np.float32)
    y = rng.standard_normal((1000, 3)).astype(np.float32)
    n, w, b = 4, 3, 8
    xb, yb, rounds = shape_epoch_data(x, y, n, w, b)
    streamed = list(round_stream(x, y, n, w, b))
    assert len(streamed) == rounds
    for r, (xr, yr) in enumerate(streamed):
        np.testing.assert_array_equal(xr, xb[r])
        np.testing.assert_array_equal(yr, yb[r])


def test_prefetch_preserves_order_and_count(eight_devices):
    mesh = get_mesh(8)
    sh = NamedSharding(mesh, P())
    items = [(np.full((4,), i, np.float32),) for i in range(7)]
    out = list(prefetch_to_device(iter(items), (sh,), buffer_size=3))
    assert len(out) == 7
    for i, (a,) in enumerate(out):
        assert float(a[0]) == i
        assert a.sharding.is_equivalent_to(sh, a.ndim)


def test_streamed_epoch_matches_all_at_once(eight_devices):
    """run_epoch_streaming == run_epoch on the same data, bit for bit."""
    ds = make_dataset(n=1024)
    model = make_model()
    x = np.asarray(ds["features"])
    y = np.asarray(ds["label_encoded"])
    n, w, b = 8, 4, 8

    def fresh():
        eng = SPMDEngine(model, "categorical_crossentropy", "sgd",
                         get_mesh(8), "adag", communication_window=w,
                         learning_rate=0.1)
        st = eng.init_state(jax.random.PRNGKey(0), (16,))
        return eng, st, eng.worker_rngs(3)

    eng1, st1, rngs1 = fresh()
    xb, yb, _ = shape_epoch_data(x, y, n, w, b)
    st1, losses1 = eng1.run_epoch(st1, xb, yb, rngs1)

    eng2, st2, rngs2 = fresh()
    st2, losses2 = eng2.run_epoch_streaming(
        st2, round_stream(x, y, n, w, b), rngs2)

    np.testing.assert_array_equal(np.asarray(losses1), losses2)
    for a, b_ in zip(jax.tree_util.tree_leaves(jax.device_get(st1.center)),
                     jax.tree_util.tree_leaves(jax.device_get(st2.center))):
        np.testing.assert_array_equal(a, b_)


def test_streamed_epoch_with_shuffle_differs_but_learns(eight_devices):
    ds = make_dataset(n=1024)
    model = make_model()
    x = np.asarray(ds["features"])
    y = np.asarray(ds["label_encoded"])
    eng = SPMDEngine(model, "categorical_crossentropy", "sgd", get_mesh(8),
                     "adag", communication_window=4, learning_rate=0.1)
    st = eng.init_state(jax.random.PRNGKey(0), (16,))
    rngs = eng.worker_rngs(0)
    all_losses = []
    for epoch in range(3):
        st, losses = eng.run_epoch_streaming(
            st, round_stream(x, y, 8, 4, 8, shuffle_seed=epoch), rngs)
        all_losses.extend(losses.tolist())
    assert all_losses[-1] < all_losses[0]


def test_round_consumes_every_window_batch(eight_devices):
    """Regression for the round-fn axis bug (round 3): the per-worker window
    scan must run ``window`` optimizer steps per round — squeezing the wrong
    axis of the (window, workers, batch) block trained on only the first
    batch of every window and silently discarded the rest."""
    mesh = get_mesh(8)
    eng = SPMDEngine(make_model(), "categorical_crossentropy", "adam", mesh,
                     "adag", communication_window=4, learning_rate=1e-3)
    state = eng.init_state(jax.random.PRNGKey(0), (16,))
    ds = make_dataset(n=2048)
    xb, yb, rounds = shape_epoch_data(
        np.asarray(ds["features"]), np.asarray(ds["label_encoded"]), 8, 4, 16)
    state, _ = eng.run_epoch(state, xb, yb, eng.worker_rngs(0))
    counts = [np.asarray(l) for l in jax.tree_util.tree_leaves(state.opt_state)
              if np.asarray(l).dtype == np.int32 and np.asarray(l).ndim == 1]
    assert counts, "adam opt state should carry per-worker step counts"
    for c in counts:
        np.testing.assert_array_equal(c, rounds * 4)
