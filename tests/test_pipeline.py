"""Streaming input pipeline: round_stream layout parity with
shape_epoch_data, prefetch_to_device semantics, and the streamed epoch
matching the all-at-once epoch bit-for-bit.
"""

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from distkeras_tpu.data.pipeline import round_stream, prefetch_to_device
from distkeras_tpu.parallel import get_mesh
from distkeras_tpu.parallel.spmd import SPMDEngine, shape_epoch_data

from test_trainers import make_dataset, make_model


def test_round_stream_matches_shape_epoch_data():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((1000, 5)).astype(np.float32)
    y = rng.standard_normal((1000, 3)).astype(np.float32)
    n, w, b = 4, 3, 8
    xb, yb, mb, rounds = shape_epoch_data(x, y, n, w, b)
    streamed = list(round_stream(x, y, n, w, b))
    assert len(streamed) == rounds
    for r, (xr, yr, mr) in enumerate(streamed):
        np.testing.assert_array_equal(xr, xb[r])
        np.testing.assert_array_equal(yr, yb[r])
        np.testing.assert_array_equal(mr, mb[r])


def test_prefetch_preserves_order_and_count(eight_devices):
    mesh = get_mesh(8)
    sh = NamedSharding(mesh, P())
    items = [(np.full((4,), i, np.float32),) for i in range(7)]
    out = list(prefetch_to_device(iter(items), (sh,), buffer_size=3))
    assert len(out) == 7
    for i, (a,) in enumerate(out):
        assert float(a[0]) == i
        assert a.sharding.is_equivalent_to(sh, a.ndim)


def test_streamed_epoch_matches_all_at_once(eight_devices):
    """run_epoch_streaming == run_epoch on the same data, bit for bit."""
    ds = make_dataset(n=1024)
    model = make_model()
    x = np.asarray(ds["features"])
    y = np.asarray(ds["label_encoded"])
    n, w, b = 8, 4, 8

    def fresh():
        eng = SPMDEngine(model, "categorical_crossentropy", "sgd",
                         get_mesh(8), "adag", communication_window=w,
                         learning_rate=0.1)
        st = eng.init_state(jax.random.PRNGKey(0), (16,))
        return eng, st, eng.worker_rngs(3)

    eng1, st1, rngs1 = fresh()
    xb, yb, mb, _ = shape_epoch_data(x, y, n, w, b)
    st1, losses1 = eng1.run_epoch(st1, xb, yb, mb, rngs1)

    eng2, st2, rngs2 = fresh()
    st2, losses2 = eng2.run_epoch_streaming(
        st2, round_stream(x, y, n, w, b), rngs2)

    np.testing.assert_array_equal(np.asarray(losses1), losses2)
    for a, b_ in zip(jax.tree_util.tree_leaves(jax.device_get(st1.center)),
                     jax.tree_util.tree_leaves(jax.device_get(st2.center))):
        np.testing.assert_array_equal(a, b_)


def test_streamed_epoch_with_shuffle_differs_but_learns(eight_devices):
    ds = make_dataset(n=1024)
    model = make_model()
    x = np.asarray(ds["features"])
    y = np.asarray(ds["label_encoded"])
    eng = SPMDEngine(model, "categorical_crossentropy", "sgd", get_mesh(8),
                     "adag", communication_window=4, learning_rate=0.1)
    st = eng.init_state(jax.random.PRNGKey(0), (16,))
    rngs = eng.worker_rngs(0)
    all_losses = []
    for epoch in range(3):
        st, losses = eng.run_epoch_streaming(
            st, round_stream(x, y, 8, 4, 8, shuffle_seed=epoch), rngs)
        all_losses.extend(losses.tolist())
    assert all_losses[-1] < all_losses[0]


def test_round_consumes_every_window_batch(eight_devices):
    """Regression for the round-fn axis bug (round 3): the per-worker window
    scan must run ``window`` optimizer steps per round — squeezing the wrong
    axis of the (window, workers, batch) block trained on only the first
    batch of every window and silently discarded the rest."""
    mesh = get_mesh(8)
    eng = SPMDEngine(make_model(), "categorical_crossentropy", "adam", mesh,
                     "adag", communication_window=4, learning_rate=1e-3)
    state = eng.init_state(jax.random.PRNGKey(0), (16,))
    ds = make_dataset(n=2048)
    xb, yb, mb, rounds = shape_epoch_data(
        np.asarray(ds["features"]), np.asarray(ds["label_encoded"]), 8, 4, 16)
    state, _ = eng.run_epoch(state, xb, yb, mb, eng.worker_rngs(0))
    counts = [np.asarray(l) for l in jax.tree_util.tree_leaves(state.opt_state)
              if np.asarray(l).dtype == np.int32 and np.asarray(l).ndim == 1]
    assert counts, "adam opt state should carry per-worker step counts"
    for c in counts:
        np.testing.assert_array_equal(c, rounds * 4)


def test_shape_epoch_data_pads_instead_of_dropping():
    """Round-2 VERDICT weak #4: the flagship 8x12x128 config used to drop
    ~18% of MNIST per epoch.  Now the tail is wrap-padded and masked: zero
    real rows lost, every real row appears exactly once with mask 1."""
    n_rows = 60000
    x = np.arange(n_rows, dtype=np.float32)[:, None]
    y = np.zeros((n_rows, 1), np.float32)
    xb, yb, mb, rounds = shape_epoch_data(x, y, 8, 12, 128)
    per_round = 8 * 12 * 128
    assert rounds == -(-n_rows // per_round) == 5
    assert mb.shape == xb.shape[:4]
    assert int(mb.sum()) == n_rows  # 0 dropped (was 10848)
    real = xb[..., 0][mb.astype(bool)]
    assert sorted(real.astype(int).tolist()) == list(range(n_rows))


def test_small_dataset_pads_up_to_one_round():
    """Datasets smaller than one round now train (wrap-padded) instead of
    raising."""
    x = np.arange(10, dtype=np.float32)[:, None]
    y = np.zeros((10, 1), np.float32)
    xb, yb, mb, rounds = shape_epoch_data(x, y, 4, 2, 4)
    assert rounds == 1 and int(mb.sum()) == 10


def test_masked_gradient_matches_unpadded(eight_devices):
    """Exactness: one SGD step on a wrap-padded+masked batch must equal the
    step on the raw unpadded rows (padding contributes zero to loss/grad)."""
    import jax.numpy as jnp
    from distkeras_tpu.core.train import make_masked_loss_fn, make_loss_fn

    model = make_model()
    params = model.init(jax.random.PRNGKey(0), (16,))
    rng = np.random.default_rng(0)
    x = rng.standard_normal((10, 16)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 10)]

    # padded batch of 16: rows 10.. wrap to rows 0.. with mask 0
    idx = np.arange(16) % 10
    w = (np.arange(16) < 10).astype(np.float32)
    masked = make_masked_loss_fn(model, "categorical_crossentropy")
    plain = make_loss_fn(model, "categorical_crossentropy")
    (lm, _), gm = jax.value_and_grad(masked, has_aux=True)(
        params, jnp.asarray(x[idx]), jnp.asarray(y[idx]), jnp.asarray(w),
        None)
    (lp, _), gp = jax.value_and_grad(plain, has_aux=True)(
        params, jnp.asarray(x), jnp.asarray(y), None)
    np.testing.assert_allclose(float(lm), float(lp), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(gm),
                    jax.tree_util.tree_leaves(gp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_trainer_examples_metric_counts_real_rows(eight_devices):
    """The throughput metric counts real dataset rows, not padded batches."""
    from distkeras_tpu import ADAG

    ds = make_dataset(n=1500)  # not divisible by 8*4*16=512 -> padding
    t = ADAG(make_model(), num_workers=8, batch_size=16, num_epoch=1,
             communication_window=4, label_col="label_encoded",
             worker_optimizer="adam", learning_rate=1e-3)
    t.train(ds)
    epochs = [e for e in t.metrics if e.get("kind") == "epoch"]
    assert epochs and epochs[0]["examples"] == 1500


def test_round_layout_spreads_padding_across_workers():
    """Code-review finding (round 3): padding must never concentrate on one
    worker — a pad-only worker would blend untrained init params into
    Averaging/Ensemble/EASGD results.  The round-robin deal gives every
    worker its fair share of real rows."""
    from distkeras_tpu.data.pipeline import num_rounds, round_block

    assert num_rounds(10, 4, 2, 4) == 1
    sel, mask = round_block(10, 4, 2, 4, 0)  # 32 slots, 22 padding
    assert sel.shape == mask.shape == (2, 4, 4)  # (window, workers, batch)
    per_worker = mask.sum(axis=(0, 2))
    assert per_worker.min() >= 2 and per_worker.max() <= 3
    real = sel[mask.astype(bool)]
    assert sorted(real.tolist()) == list(range(10))
    # fewer rows than workers is refused, not silently degraded
    import pytest
    with pytest.raises(ValueError):
        num_rounds(3, 4, 2, 4)


def test_fully_padded_batch_is_true_noop():
    """Code-review finding (round 3): a wsum==0 batch must not move params
    or optimizer state (Adam moves on a zero gradient otherwise)."""
    import jax.numpy as jnp
    from distkeras_tpu.core.train import make_masked_step, init_state

    model = make_model()
    state, tx = init_state(model, jax.random.PRNGKey(0), (16,), "adam", 1e-3)
    step = jax.jit(make_masked_step(model, "categorical_crossentropy", tx))
    x = jnp.zeros((8, 16), jnp.float32)
    y = jnp.zeros((8, 4), jnp.float32)

    # one real step so adam momentum is non-trivial
    p1, s1, _, _ = step(state.params, state.opt_state,
                        jnp.ones((8, 16)), jnp.eye(4)[jnp.zeros(8, int)],
                        jnp.ones(8), jax.random.PRNGKey(1))
    # fully padded step: everything must come back bit-identical
    p2, s2, loss, wsum = step(p1, s1, x, y, jnp.zeros(8),
                              jax.random.PRNGKey(2))
    assert float(wsum) == 0.0 and float(loss) == 0.0
    for a, b in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree_util.tree_leaves(s1),
                    jax.tree_util.tree_leaves(s2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_streamed_packed_epoch_matches_all_at_once(eight_devices):
    """Packed streaming: run_epoch_streaming on (x, y, seg, mask)
    quadruples == packed run_epoch, bit for bit; arity misuse refused."""
    import pytest
    from distkeras_tpu.data.packing import pack_documents, packed_lm_labels
    from distkeras_tpu.models.zoo import transformer_lm

    rng = np.random.default_rng(11)
    docs = [[int(v) for v in rng.integers(1, 32, int(rng.integers(4, 10)))]
            for _ in range(128)]
    tok, seg = pack_documents(docs, 16)
    lab = packed_lm_labels(tok, seg)
    model = transformer_lm(vocab_size=32, seq_len=16, d_model=32,
                           num_heads=4, num_layers=2, mlp_dim=64,
                           compute_dtype="float32", positional="rope")
    n, w, b = 8, 2, 2

    def fresh():
        eng = SPMDEngine(
            model, "sparse_categorical_crossentropy_masked_from_logits",
            "adam", get_mesh(8), "adag", communication_window=w,
            learning_rate=1e-3, packed=True)
        st = eng.init_state(jax.random.PRNGKey(0), (16,))
        return eng, st, eng.worker_rngs(3)

    eng1, st1, rngs1 = fresh()
    xb, yb, sb, mb, _ = shape_epoch_data(tok, lab, n, w, b,
                                         columns_seg=seg)
    st1, losses1 = eng1.run_epoch(st1, xb, yb, mb, rngs1, sb=sb)

    eng2, st2, rngs2 = fresh()
    st2, losses2 = eng2.run_epoch_streaming(
        st2, round_stream(tok, lab, n, w, b, seg=seg), rngs2)

    np.testing.assert_array_equal(np.asarray(losses1), losses2)
    for a, b_ in zip(jax.tree_util.tree_leaves(jax.device_get(st1.center)),
                     jax.tree_util.tree_leaves(jax.device_get(st2.center))):
        np.testing.assert_array_equal(a, b_)

    # triples into a packed engine refuse loudly
    eng3, st3, rngs3 = fresh()
    with pytest.raises(ValueError, match="expects 4"):
        eng3.run_epoch_streaming(st3, round_stream(tok, lab, n, w, b),
                                 rngs3)
    # ...and quadruples into an UNPACKED engine too (regression: zip in
    # prefetch_to_device used to silently truncate, dropping the mask and
    # training with seg in its place)
    eng4 = SPMDEngine(
        model, "sparse_categorical_crossentropy_masked_from_logits",
        "adam", get_mesh(8), "adag", communication_window=w,
        learning_rate=1e-3)
    st4 = eng4.init_state(jax.random.PRNGKey(0), (16,))
    with pytest.raises(ValueError, match="expects 3"):
        eng4.run_epoch_streaming(
            st4, round_stream(tok, lab, n, w, b, seg=seg),
            eng4.worker_rngs(3))
