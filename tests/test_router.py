"""ServingRouter contract tests — the replicated-fleet routing layer.

Tier-1 legs are in-process or loopback-only, seeded, and bounded-wait:

 - a single-replica router is BIT-IDENTICAL to a bare engine (greedy and
   sampled, in-process and over the wire) — the defaults-unchanged
   contract;
 - the lock-free ``ServingEngine.load()`` snapshot tracks queue depth /
   slots / trie blocks / draining / death, in-process and through the
   wire ``'s'`` probe;
 - prefix-affinity routing lands shared-prefix tenants on one warm-trie
   replica (fleet ``prefix_hit_rate`` holds) where random routing
   scatters them (hit rate collapses), with the saturation spill as the
   escape hatch;
 - the replica-kill failover matrix (queued / mid-stream × in-process /
   wire) loses ZERO accepted requests: typed ``EngineDead`` requests
   resubmit elsewhere with their original seed and the replayed stream
   is token-identical, already-delivered prefix included;
 - rolling blue/green swaps every replica's generation under traffic
   with every response attributed to exactly one ``(replica,
   generation)``;
 - elastic scale-down drains without leaking requests or KV blocks.
"""

import threading
import time

import numpy as np
import pytest

import jax

from distkeras_tpu import networking
from distkeras_tpu.core.model import FittedModel
from distkeras_tpu.models import transformer_lm
from distkeras_tpu.resilience import FleetSupervisor, RetryPolicy
from distkeras_tpu.router import ServingRouter
from distkeras_tpu.serving import (Draining, EngineDead, QueueFull,
                                   ServingClient, ServingEngine,
                                   ServingServer)

pytestmark = pytest.mark.router

VOCAB = 17
PROMPT = np.array([3, 4, 5, 6], np.int32)


def _fitted(seed=0):
    model = transformer_lm(vocab_size=VOCAB, seq_len=32, d_model=16,
                           num_heads=2, num_layers=2, mlp_dim=32,
                           compute_dtype="float32")
    params = model.init(jax.random.PRNGKey(seed), (32,))
    return FittedModel(model, params)


@pytest.fixture(scope="module")
def fitted():
    return _fitted()


def _want(fitted, prompt, steps, **kw):
    seed = kw.pop("seed", None)
    if seed is not None:
        kw["rng"] = jax.random.PRNGKey(seed)
    return np.asarray(fitted.generate(prompt[None], steps, max_len=24,
                                      **kw))[0]


def _engine(fitted, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_len", 24)
    return ServingEngine(fitted, **kw)


def _paged_engine(fitted, **kw):
    kw.setdefault("prefill_mode", "bucketed")
    kw.setdefault("paged", True)
    kw.setdefault("block_size", 4)
    kw.setdefault("kv_blocks", 64)
    return _engine(fitted, **kw)


def _wait_for(pred, timeout=20.0, interval=0.005):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(interval)
    return pred()


# ---------------------------------------------------------------------------
# single-replica bit-identity (the defaults-unchanged contract)
# ---------------------------------------------------------------------------

def test_single_replica_router_bit_identical_in_process(fitted):
    with ServingRouter([_engine(fitted)]) as r:
        greedy = r.submit(PROMPT, 8).result(timeout=30)
        sampled = r.submit(PROMPT, 8, temperature=0.9, seed=5,
                           top_k=8).result(timeout=30)
    np.testing.assert_array_equal(greedy, _want(fitted, PROMPT, 8))
    np.testing.assert_array_equal(
        sampled, _want(fitted, PROMPT, 8, temperature=0.9, seed=5,
                       top_k=8))


def test_single_replica_router_bit_identical_over_wire(fitted, server_core):
    with ServingServer(_engine(fitted)) as srv:
        with ServingRouter(addrs=[srv.addr]) as r:
            greedy = r.submit(PROMPT, 8).result(timeout=30)
            sampled = r.submit(PROMPT, 8, temperature=0.9,
                               seed=5).result(timeout=30)
    np.testing.assert_array_equal(greedy, _want(fitted, PROMPT, 8))
    np.testing.assert_array_equal(
        sampled, _want(fitted, PROMPT, 8, temperature=0.9, seed=5))


def test_router_streams_chunks_like_an_engine(fitted):
    with ServingRouter([_engine(fitted)]) as r:
        h = r.submit(PROMPT, 6)
        got = []
        while True:
            chunk, done = h.next_chunk(timeout=5.0)
            got.extend(int(t) for t in chunk)
            if done:
                break
        assert got == list(h.tokens)
        np.testing.assert_array_equal(h.result(), _want(fitted, PROMPT, 6))


def test_router_rejects_non_unified_replicas(fitted):
    pre = _paged_engine(fitted, role="prefill")
    with pytest.raises(ValueError, match="unified"):
        ServingRouter([pre])
    with pytest.raises(ValueError, match="at least one replica"):
        ServingRouter()


# ---------------------------------------------------------------------------
# the lock-free load snapshot (satellite: ServingEngine.load())
# ---------------------------------------------------------------------------

def test_engine_load_snapshot_tracks_queue_and_completion(fitted):
    eng = _engine(fitted)  # inline: stepped by hand, fully deterministic
    assert eng.load()["queue_depth"] == 0
    assert eng.load()["slots_free"] == eng.num_slots
    h1 = eng.submit(PROMPT, 4)
    h2 = eng.submit(PROMPT, 4, seed=1, temperature=0.5)
    assert eng.load()["queue_depth"] == 2
    while not (h1.done and h2.done):
        eng.step()
    snap = eng.load()
    assert snap["queue_depth"] == 0
    assert snap["requests_completed"] == 2
    assert snap["tokens_generated"] > 0
    assert snap["dead"] is False and snap["draining"] is False


def test_engine_load_snapshot_reports_death_and_drain(fitted):
    eng = _engine(fitted)
    eng.submit(PROMPT, 4)
    eng.declare_dead("chaos")
    snap = eng.load()
    assert snap["dead"] is True and snap["queue_depth"] == 0

    eng2 = _engine(fitted)
    assert eng2.drain(timeout=10.0)
    assert eng2.load()["draining"] is True


def test_engine_load_snapshot_counts_trie_blocks_incrementally(fitted):
    eng = _paged_engine(fitted)
    shared = np.array([1, 2, 3, 4, 5, 6, 7, 8, 9], np.int32)
    for seed in range(3):
        h = eng.submit(shared, 4, seed=seed)
        while not h.done:
            eng.step()
    snap = eng.load()
    # the incremental counter must mirror the trie walk exactly, and the
    # shared prompt must actually have populated the trie
    assert snap["trie_blocks"] == eng._pool.cached_blocks() > 0
    assert eng._pool.trie_nodes == eng._pool.cached_blocks()
    assert eng.stats["prefix_hit_tokens"] > 0


def test_trie_node_counter_survives_eviction(fitted):
    # a pool small enough that later admissions evict cached chains
    eng = _paged_engine(fitted, kv_blocks=8, num_slots=1)
    for seed in range(5):
        p = np.array([seed + 1] * 9, np.int32)  # distinct chains
        h = eng.submit(p, 4, seed=seed)
        while not h.done:
            eng.step()
    assert eng.stats["blocks_evicted"] > 0
    assert eng._pool.trie_nodes == eng._pool.cached_blocks()


def test_wire_stats_probe_matches_engine_load(fitted, server_core):
    with ServingServer(_engine(fitted)) as srv:
        c = ServingClient(*srv.addr)
        try:
            snap = c.load()
            want = srv.engine.load()
            assert set(snap) == set(want)
            assert snap["slots_total"] == want["slots_total"]
            assert snap["dead"] is False
        finally:
            c.close()


# ---------------------------------------------------------------------------
# routing policy units
# ---------------------------------------------------------------------------

def test_route_key_follows_trie_block_boundary_rule(fitted):
    r = ServingRouter([_engine(fitted)], block_size=4, affinity_blocks=2)
    # cap is p_len - 1: a 4-token prompt cannot share its only block
    assert r._route_key(np.arange(4, dtype=np.int32)) is None
    k1 = r._route_key(np.arange(5, dtype=np.int32))
    assert k1 == np.arange(4, dtype=np.int32).tobytes()
    # affinity_blocks caps the hashed prefix at 2 blocks = 8 tokens
    k2 = r._route_key(np.arange(16, dtype=np.int32))
    assert k2 == np.arange(8, dtype=np.int32).tobytes()
    r.stop()


def test_should_spill_rule():
    idle = {"queue_depth": 0, "slots_free": 2, "slots_total": 2}
    busy = {"queue_depth": 2, "slots_free": 0, "slots_total": 2}
    flood = {"queue_depth": 9, "slots_free": 0, "slots_total": 2}
    # free slots: never spill, whatever the queue says
    assert not ServingRouter._should_spill(idle, idle)
    # saturated but within one slot-pool of the least-loaded: stay affine
    assert not ServingRouter._should_spill(busy, idle)
    # saturated AND far deeper than least-loaded: spill
    assert ServingRouter._should_spill(flood, idle)


def test_prefix_dispatch_is_stable_and_spills_under_saturation(fitted):
    r = ServingRouter([_engine(fitted), _engine(fitted)], block_size=4,
                      affinity_blocks=2)
    prompt = np.array([9] * 9, np.int32)
    first = [rep.uid for rep, _ in r._dispatch_order(prompt)][0]
    for _ in range(5):  # rendezvous: same key, same replica, every time
        assert r._dispatch_order(prompt)[0][0].uid == first
    affine = r._replicas[first]
    other = r._replicas[1 - first]
    # saturate the affine replica far past the spill threshold
    affine.load = lambda: {"queue_depth": 9, "slots_free": 0,
                           "slots_total": 2, "active": 2}
    other.load = lambda: {"queue_depth": 0, "slots_free": 2,
                          "slots_total": 2, "active": 0}
    spills0 = r.counters["affinity_spills"]
    assert r._dispatch_order(prompt)[0][0].uid == other.uid
    assert r.counters["affinity_spills"] == spills0 + 1
    r.stop()


def test_dispatch_excludes_dead_and_draining_replicas(fitted):
    e0, e1 = _engine(fitted), _engine(fitted)
    r = ServingRouter([e0, e1], affinity="least-loaded")
    e0.declare_dead("chaos")
    order = r._dispatch_order(PROMPT)
    assert [rep.uid for rep, _ in order] == [1]
    e1.declare_dead("chaos")
    with pytest.raises(EngineDead, match="no live serving replica"):
        r._dispatch_order(PROMPT)
    r.stop()


# ---------------------------------------------------------------------------
# prefix-affinity vs random: the cache-aware-routing win
# ---------------------------------------------------------------------------

def _fleet_trace(groups=4, per_group=5, prefix_len=8, steps=3):
    """Multi-tenant shared-prefix trace: ``groups`` tenants, each with a
    distinct ``prefix_len``-token system prefix and per-request suffix."""
    out = []
    for g in range(groups):
        for i in range(per_group):
            prompt = np.array([g + 2] * prefix_len + [10 + i], np.int32)
            out.append((prompt, steps, g))
    return out


def _run_fleet(fitted, affinity, seed=0):
    engines = [_paged_engine(fitted), _paged_engine(fitted)]
    with ServingRouter(engines, affinity=affinity, block_size=4,
                       affinity_blocks=2, seed=seed) as r:
        by_group = {}
        for prompt, steps, g in _fleet_trace():
            h = r.submit(prompt, steps, seed=g)
            h.result(timeout=30)  # sequential: deterministic trie state
            by_group.setdefault(g, set()).add(r.generation_of(h)[0])
        stats = r.stats
        hit = stats["prefix_hit_tokens"]
        rate = hit / max(hit + stats["prefill_tokens"], 1)
        r.drain(timeout=10.0)
    return rate, by_group, stats


def test_affinity_routing_holds_prefix_hit_rate_where_random_collapses(
        fitted):
    aff_rate, aff_groups, aff_stats = _run_fleet(fitted, "prefix")
    rnd_rate, rnd_groups, _ = _run_fleet(fitted, "random", seed=3)
    # affinity: every tenant's requests landed on ONE warm-trie replica
    assert all(len(uids) == 1 for uids in aff_groups.values())
    # random provably scattered at least one tenant across replicas
    assert any(len(uids) > 1 for uids in rnd_groups.values())
    # and the hit rate shows it: warm tries serve the shared prefix
    assert aff_rate > rnd_rate
    assert aff_rate > 0.4  # 2 shared blocks of a 9-token prompt, 4/5 hits
    assert aff_stats["affinity_routed"] > 0
    assert aff_stats["resubmissions"] == 0


# ---------------------------------------------------------------------------
# replica-kill failover matrix: zero accepted requests lost
# ---------------------------------------------------------------------------

def test_kill_while_queued_resubmits_in_process(fitted):
    # replica 0 never schedules (not started) -> the request parks on it;
    # killing it must move the request to the live replica, bit-identically
    e0, e1 = _engine(fitted), _engine(fitted)
    r = ServingRouter([e0, e1], affinity="least-loaded")
    e1.start()
    try:
        h = r.submit(PROMPT, 8, seed=7, temperature=0.9)
        assert r.generation_of(h) == (0, 0)
        assert len(h.tokens) == 0
        e0.declare_dead("chaos: killed with the request queued")
        got = h.result(timeout=30)
        np.testing.assert_array_equal(
            got, _want(fitted, PROMPT, 8, seed=7, temperature=0.9))
        assert r.generation_of(h) == (1, 0)
        assert r.counters["resubmissions"] == 1
        assert r.counters["requests_failed"] == 0
    finally:
        r.stop()


def test_kill_mid_stream_replays_exactly_once_in_process(fitted):
    # replica 0 is stepped BY HAND: emit a few tokens, then die mid-stream.
    # The resubmitted stream must replay the prefix silently — the client
    # sees each token exactly once, and the row is bit-identical.
    e0, e1 = _engine(fitted), _engine(fitted)
    r = ServingRouter([e0, e1], affinity="least-loaded")
    e1.start()
    try:
        h = r.submit(PROMPT, 10, seed=11, temperature=0.8)
        assert r.generation_of(h) == (0, 0)
        up = r._live[h.id].upstream  # the replica-side handle
        while len(up.tokens) < 3:  # hand-step: 3 of 10 tokens, no more
            e0.step()
        assert _wait_for(lambda: len(h.tokens) >= 3)
        assert not h.done
        prefix = list(h.tokens)[:3]
        e0.declare_dead("chaos: killed mid-stream")
        got = h.result(timeout=30)
        want = _want(fitted, PROMPT, 10, seed=11, temperature=0.8)
        np.testing.assert_array_equal(got, want)
        # the already-delivered prefix was never duplicated or rewritten
        assert list(got[len(PROMPT):len(PROMPT) + 3]) == prefix
        assert r.generation_of(h) == (1, 0)
        assert r.counters["resubmissions"] == 1
        assert r.counters["requests_failed"] == 0
    finally:
        r.stop()


def test_kill_under_load_loses_zero_requests_in_process(fitted):
    e0, e1 = _engine(fitted, num_slots=4), _engine(fitted, num_slots=4)
    r = ServingRouter([e0, e1], affinity="least-loaded")
    e1.start()
    try:
        handles = [(r.submit(PROMPT, 6, seed=s, temperature=0.7), s)
                   for s in range(8)]
        parked = [h for h, _ in handles if r.generation_of(h)[0] == 0]
        assert parked  # least-loaded spread some share onto replica 0
        e0.declare_dead("chaos: killed under load")
        for h, s in handles:
            np.testing.assert_array_equal(
                h.result(timeout=30),
                _want(fitted, PROMPT, 6, seed=s, temperature=0.7))
        assert r.counters["requests_failed"] == 0
        assert r.counters["requests_completed"] == len(handles)
        assert r.counters["resubmissions"] >= len(parked)
    finally:
        r.stop()


def test_kill_resubmits_over_wire_typed_death(fitted, server_core):
    # typed EngineDead through the wire: the dead server answers probes
    # (dead=True) and streams error frames; requests fail over to the
    # live server
    with ServingServer(_engine(fitted)) as s0, \
            ServingServer(_engine(fitted)) as s1:
        with ServingRouter(addrs=[s0.addr, s1.addr],
                           affinity="least-loaded", load_ttl=0.0) as r:
            want = _want(fitted, PROMPT, 8, seed=7, temperature=0.9)
            handles = [r.submit(PROMPT, 8, seed=7, temperature=0.9)
                       for _ in range(4)]
            s0.engine.declare_dead("chaos: wire replica killed")
            for h in handles:
                np.testing.assert_array_equal(h.result(timeout=30), want)
            assert r.counters["requests_failed"] == 0
            assert r.counters["requests_completed"] == 4


def test_kill_resubmits_over_wire_transport_fault(fitted, server_core):
    # the server process "dies" (socket torn, probes unreachable): relays
    # must fail over on the raw ConnectionError, not just typed frames
    s0 = ServingServer(_engine(fitted)).start()
    s1 = ServingServer(_engine(fitted)).start()
    try:
        with ServingRouter(addrs=[s0.addr, s1.addr],
                           affinity="least-loaded", load_ttl=0.0) as r:
            want = _want(fitted, PROMPT, 8, seed=7, temperature=0.9)
            handles = [r.submit(PROMPT, 8, seed=7, temperature=0.9)
                       for _ in range(4)]
            s0.stop()
            for h in handles:
                np.testing.assert_array_equal(h.result(timeout=30), want)
            assert r.counters["requests_failed"] == 0
    finally:
        s0.stop()
        s1.stop()


def test_whole_fleet_dead_fails_typed(fitted):
    e0 = _engine(fitted)
    r = ServingRouter([e0], retry_policy=RetryPolicy(attempts=2,
                                                     backoff=0.01))
    e1_started = e0  # single replica: kill it with a request in flight
    h = r.submit(PROMPT, 8)
    e1_started.declare_dead("chaos: the whole fleet")
    with pytest.raises(EngineDead):
        h.result(timeout=30)
    assert r.counters["requests_failed"] == 1
    with pytest.raises(EngineDead):
        r.submit(PROMPT, 4)
    r.stop()


def test_cancel_mid_failover_mirrors_cancel(fitted):
    e0, e1 = _engine(fitted), _engine(fitted)
    r = ServingRouter([e0, e1], affinity="least-loaded")
    e1.start()
    try:
        h = r.submit(PROMPT, 8)
        assert r.cancel(h) is True
        e0.step()  # one scheduler iteration sheds the cancelled request
        assert _wait_for(lambda: h.done)
        assert h.finish == "cancel"
        assert r.cancel(h) is False
        assert r.counters["requests_cancelled"] == 1
    finally:
        r.stop()


# ---------------------------------------------------------------------------
# rolling blue/green: every response attributed to exactly one generation
# ---------------------------------------------------------------------------

def test_rolling_swap_under_traffic_attributes_every_response(fitted):
    e0, e1 = _engine(fitted), _engine(fitted)
    with ServingRouter([e0, e1], affinity="least-loaded") as r:
        want = _want(fitted, PROMPT, 6, seed=2, temperature=0.6)
        before = [r.submit(PROMPT, 6, seed=2, temperature=0.6)
                  for _ in range(4)]
        assert r.rolling_swap(drain_timeout=15.0) == 2
        after = [r.submit(PROMPT, 6, seed=2, temperature=0.6)
                 for _ in range(4)]
        for h in before + after:
            np.testing.assert_array_equal(h.result(timeout=30), want)
        gens = [r.generation_of(h) for h in before + after]
        # exactly one (replica, generation) per response, all valid
        assert all(g is not None and g[1] in (0, 1) for g in gens)
        # post-swap traffic runs on the NEW generation only
        assert all(g[1] == 1 for g in [r.generation_of(h) for h in after])
        assert r.counters["generation_swaps"] == 2
        assert r.counters["requests_failed"] == 0
        # the swapped-out engines are fully retired, replacements live
        assert e0 not in r.engines and e1 not in r.engines
        assert len(r.engines) == 2


# ---------------------------------------------------------------------------
# elasticity: scale up on queue pressure, drain down without leaks
# ---------------------------------------------------------------------------

def test_scale_down_drains_without_losing_requests_or_blocks(fitted):
    e0, e1 = _paged_engine(fitted), _paged_engine(fitted)
    with ServingRouter([e0, e1], affinity="least-loaded") as r:
        handles = [r.submit(PROMPT, 4, seed=s) for s in range(6)]
        for h in handles:
            h.result(timeout=30)
        victim_uid = r.scale_down(timeout=15.0)
        assert victim_uid is not None
        assert r.num_replicas == 1
        victim = e0 if victim_uid == 0 else e1
        assert victim not in r.engines
        # the drained replica leaked nothing: every request terminal,
        # every KV block back in its pool
        assert victim.kv_blocks_in_use == 0
        s = victim.stats
        assert (s["requests_submitted"]
                == s["requests_completed"] + s["requests_failed"]
                + s["requests_rejected"])
        assert r.counters["requests_failed"] == 0
        # min_replicas floor: the last replica is not drainable
        assert r.scale_down(timeout=5.0) is None
        # the survivor still serves
        np.testing.assert_array_equal(
            r.submit(PROMPT, 4, seed=0).result(timeout=30),
            _want(fitted, PROMPT, 4, seed=0))
        assert _wait_for(lambda: r.kv_blocks_in_use == 0, timeout=10.0)


def test_autoscale_tick_grows_on_queue_pressure(fitted):
    e0 = _engine(fitted, num_slots=1, queue_capacity=16)
    r = ServingRouter([e0], engine_factory=lambda: _engine(fitted),
                      scale_up_queue=2, max_replicas=2)
    try:
        parked = [r.submit(PROMPT, 4, seed=s)
                  for s in range(6)]  # replica 0 not started: queue grows
        assert r.autoscale_tick() == "up"
        assert r.num_replicas == 2
        assert r.counters["scale_ups"] == 1
        # the new replica is live: a fresh request routes somewhere live
        # (replica 0 is saturated per the spill rule) and completes
        r.start()
        np.testing.assert_array_equal(
            r.submit(PROMPT, 4, seed=0).result(timeout=30),
            _want(fitted, PROMPT, 4, seed=0))
        for s, h in enumerate(parked):  # zero-loss through the scale-up
            np.testing.assert_array_equal(
                h.result(timeout=30), _want(fitted, PROMPT, 4, seed=s))
    finally:
        r.stop()


def test_fleet_supervisor_restarts_dead_replica(fitted):
    e0, e1 = _engine(fitted), _engine(fitted)
    with ServingRouter([e0, e1], affinity="least-loaded") as r:
        sup = FleetSupervisor(r, liveness_deadline=5.0)
        assert sup.check_all() == [None, None]
        e0.declare_dead("chaos")
        assert sup.check_all()[0] == "crashed"
        recs = sup.recover_all()
        assert len(recs) == 1 and recs[0]["restarted"]
        assert sup.restarts == 1
        # the replacement went in through replace_engine: generation
        # bumped, fresh engine serving
        assert e0 not in r.engines and len(r.engines) == 2
        snap = r.fleet_snapshot()
        assert snap[0]["generation"] == 1
        np.testing.assert_array_equal(
            r.submit(PROMPT, 4).result(timeout=30),
            _want(fitted, PROMPT, 4))
        # elastic membership: refresh() tracks a scale-up
        r.engine_factory = lambda: _engine(fitted)
        r.scale_up()
        sup.refresh()
        assert len(sup.supervisors) == 3


# ---------------------------------------------------------------------------
# admission semantics at the router boundary
# ---------------------------------------------------------------------------

def test_router_backpressure_is_typed_and_blocking_waits(fitted):
    e0 = _engine(fitted, num_slots=1, queue_capacity=1)
    r = ServingRouter([e0])  # not started: nothing drains the queue
    try:
        h = r.submit(PROMPT, 4, block=False)
        with pytest.raises(QueueFull):
            r.submit(PROMPT, 4, block=False)
        with pytest.raises(QueueFull):
            r.submit(PROMPT, 4, block=True, timeout=0.05)
        r.cancel(h)
        e0.step()  # shed the parked request so teardown has no stragglers
        assert _wait_for(lambda: h.done)
    finally:
        r.stop()


def test_router_drain_stops_admission_typed(fitted):
    with ServingRouter([_engine(fitted)]) as r:
        h = r.submit(PROMPT, 4)
        assert r.drain(timeout=15.0)
        np.testing.assert_array_equal(h.result(timeout=5),
                                      _want(fitted, PROMPT, 4))
        with pytest.raises(Draining):
            r.submit(PROMPT, 4)
        assert r.counters["requests_rejected"] == 1


# ---------------------------------------------------------------------------
# networking.ClientPool + RetryPolicy.call_reconnecting units
# ---------------------------------------------------------------------------

class _FakeClient:
    def __init__(self, addr):
        self.addr = addr
        self.closed = False

    def close(self):
        self.closed = True


def test_client_pool_reuses_and_bounds_idle():
    pool = networking.ClientPool(_FakeClient, max_idle_per_addr=2)
    a = ("h", 1)
    c1 = pool.acquire(a)
    assert pool.dials == 1
    pool.release(a, c1)
    assert pool.acquire(a) is c1 and pool.reuses == 1
    extra = [pool.acquire(a) for _ in range(3)]
    assert pool.dials == 4
    for c in [c1] + extra:
        pool.release(a, c)
    # only max_idle_per_addr stay pooled; the overflow is closed
    assert sum(1 for c in [c1] + extra if c.closed) == 2
    broken = pool.acquire(a)
    pool.discard(broken)
    assert broken.closed and pool.discards == 1
    pool.close()
    assert all(c.closed for c in [c1] + extra)


def test_retry_policy_call_reconnecting_repairs_transport():
    calls, redials = [], []

    def fn():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionError("torn")
        return "ok"

    def reconnect():
        redials.append(1)
        if len(redials) == 1:
            raise OSError("still down")  # swallowed: policy backs off

    pol = RetryPolicy(attempts=5, backoff=0.001, jitter=0.0)
    assert pol.call_reconnecting(
        fn, reconnect, retry_on=(ConnectionError,)) == "ok"
    assert len(calls) == 3 and len(redials) == 2

    # typed (non-transport) failures retry WITHOUT touching the transport
    calls.clear(), redials.clear()

    def typed():
        calls.append(1)
        if len(calls) < 2:
            raise EngineDead("restarting")
        return "ok"

    assert pol.call_reconnecting(
        typed, reconnect, retry_on=(EngineDead,)) == "ok"
    assert redials == []
