"""Event-driven PS core: drain coalescing correctness and ordering rules.

The contract under test (docs/host_ps.md, "Event loop + coalescing"):

 - DOWNPOUR (and every commute-by-sum rule): a coalesced drain is
   BIT-equal to the same commits applied sequentially — dense commits keep
   per-commit arithmetic, and runs of sparse commits merge into one
   scatter-add whose STABLE index sort preserves every coordinate's
   arrival-order accumulation.
 - ADAG: same bit-equality (its 1/num_workers scale is clock-independent).
 - DynSGD: staleness is stamped at ENQUEUE (the ``_arrival`` field the
   event server sets at parse time), so commits coalesced into one drain
   do not count each other as staleness; without a stamp the sequential
   seed-era semantics hold bit for bit (the regression pin).
 - Mixed dense + top-k commits in one drain apply in arrival order.

Protocol-level tests drive the real event server with scripted interleaves
(an apply gate to wedge the loop mid-drain, ChaosProxy ``delay`` to push a
commit into a later drain) so the drain groupings are deterministic.
"""

import threading
import time

import numpy as np
import pytest

from distkeras_tpu import networking
from distkeras_tpu.networking import ChaosFault, ChaosProxy, SparseDelta
from distkeras_tpu.parameter_servers import (ADAGParameterServer,
                                             DeltaParameterServer,
                                             DynSGDParameterServer,
                                             SocketParameterServer,
                                             ThreadedSocketParameterServer,
                                             make_socket_server)

SHAPES = [(48,), (4, 8), (), (16,)]
TOTAL = sum(int(np.prod(s, dtype=np.int64)) for s in SHAPES)


def _blob():
    return {"model": "{}",
            "weights": [np.zeros(s, np.float32) for s in SHAPES]}


def _dense_msg(rng, clock=0):
    return {"delta": [rng.standard_normal(s).astype(np.float32)
                      for s in SHAPES],
            "worker_id": 0, "clock": clock}


def _sparse_msg(rng, k=12, clock=0, sort=True):
    idx = rng.choice(TOTAL, size=k, replace=False).astype(np.int32)
    if sort:
        idx = np.sort(idx)
    vals = rng.standard_normal(k).astype(np.float32)
    return {"delta": SparseDelta(idx, vals, TOTAL),
            "worker_id": 0, "clock": clock}


def _sequential_twin(make_ps, msgs):
    """The reference result: the same messages applied one at a time."""
    ps = make_ps()
    for m in msgs:
        ps.handle_commit(dict(m))
    return ps


# ---------------------------------------------------------------------------
# apply_drain unit level: bit-equality + ordering rules
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mix", ["dense", "sparse", "mixed"])
def test_downpour_coalesced_drain_bit_equal_sequential(mix):
    """DOWNPOUR: one coalesced drain == the same commits applied
    sequentially, bit for bit — dense, sparse (merged into ONE
    scatter-add), and interleaved."""
    rng = np.random.default_rng(0)
    if mix == "dense":
        msgs = [_dense_msg(rng) for _ in range(5)]
    elif mix == "sparse":
        msgs = [_sparse_msg(rng, k) for k in (3, 17, 9, 1)]
    else:
        msgs = [_dense_msg(rng), _sparse_msg(rng, 11), _sparse_msg(rng, 5),
                _dense_msg(rng), _sparse_msg(rng, 7)]
    a = DeltaParameterServer(_blob())
    clock = a.apply_drain([dict(m) for m in msgs])
    b = _sequential_twin(lambda: DeltaParameterServer(_blob()), msgs)
    assert clock == b.num_updates == len(msgs)
    for wa, wb in zip(a.center, b.center):
        np.testing.assert_array_equal(wa, wb)


def test_sparse_run_overlapping_indices_accumulate_in_arrival_order():
    """The stable-merge property: sparse commits hitting the SAME
    coordinates (some sent unsorted) coalesce into one scatter-add whose
    per-coordinate accumulation order is arrival order — bit-equal to the
    sequential applies even where float addition order matters."""
    rng = np.random.default_rng(1)
    # adversarial values: exercise the non-associativity of float addition
    # so any order change would show up as a bit difference
    msgs = []
    for i in range(6):
        idx = np.array([0, 1, 2, 5, TOTAL - 1], np.int32)
        vals = (rng.standard_normal(5) * 10.0 ** rng.integers(-6, 6, 5)
                ).astype(np.float32)
        if i % 2:
            order = rng.permutation(5)
            idx, vals = idx[order], vals[order]  # unsorted sender
        msgs.append({"delta": SparseDelta(idx, vals, TOTAL),
                     "worker_id": 0, "clock": 0})
    a = DeltaParameterServer(_blob())
    a.apply_drain([dict(m) for m in msgs])
    b = _sequential_twin(lambda: DeltaParameterServer(_blob()), msgs)
    for wa, wb in zip(a.center, b.center):
        np.testing.assert_array_equal(wa, wb)


def test_adag_coalesced_drain_bit_equal_sequential():
    rng = np.random.default_rng(2)
    msgs = [_dense_msg(rng), _sparse_msg(rng, 13), _sparse_msg(rng, 4)]
    a = ADAGParameterServer(_blob(), num_workers=4)
    a.apply_drain([dict(m) for m in msgs])
    b = _sequential_twin(lambda: ADAGParameterServer(_blob(), 4), msgs)
    for wa, wb in zip(a.center, b.center):
        np.testing.assert_array_equal(wa, wb)


def test_dynsgd_arrival_stamp_prices_staleness_at_enqueue():
    """The documented DynSGD ordering rule: each commit's staleness comes
    from its ``_arrival`` stamp, so drain-mates don't inflate each other's
    staleness.  Hand-computed: scale_i = 1/(max(arrival_i - clock_i,0)+1),
    applied in arrival order."""
    ps = DynSGDParameterServer(_blob())
    d = [np.full(s, 8.0, np.float32) for s in SHAPES]
    msgs = [
        {"delta": [x.copy() for x in d], "clock": 0, "_arrival": 0},  # 1/1
        {"delta": [x.copy() for x in d], "clock": 0, "_arrival": 1},  # 1/2
        {"delta": [x.copy() for x in d], "clock": 0, "_arrival": 1},  # 1/2
        {"delta": [x.copy() for x in d], "clock": 3, "_arrival": 3},  # 1/1
    ]
    ps.apply_drain(msgs)
    assert ps.num_updates == 4
    for w, s in zip(ps.center, SHAPES):
        np.testing.assert_array_equal(w, np.full(s, 8.0 + 4.0 + 4.0 + 8.0))


def test_dynsgd_without_stamp_keeps_sequential_semantics():
    """Regression pin: direct sequential applies (no ``_arrival``) price
    staleness from the live clock — the seed-era behavior, bit for bit."""
    ps = DynSGDParameterServer(_blob())
    d = [np.full(s, 8.0, np.float32) for s in SHAPES]
    ps.handle_commit({"delta": [x.copy() for x in d], "clock": 0})  # 1/1
    ps.handle_commit({"delta": [x.copy() for x in d], "clock": 0})  # 1/2
    ps.handle_commit({"delta": [x.copy() for x in d], "clock": 0})  # 1/3
    for w, s in zip(ps.center, SHAPES):
        np.testing.assert_allclose(
            w, np.full(s, 8.0 + 4.0 + 8.0 / 3.0), rtol=1e-6)


def test_mixed_dense_and_topk_commits_in_one_drain():
    """Satellite: a drain holding dense AND top-k commits applies them in
    arrival order — dense commits split the sparse runs, and the result is
    bit-equal to sequential applies."""
    rng = np.random.default_rng(3)
    msgs = [_sparse_msg(rng, 9), _dense_msg(rng), _sparse_msg(rng, 9),
            _sparse_msg(rng, 9, sort=False), _dense_msg(rng)]
    a = DeltaParameterServer(_blob())
    a.apply_drain([dict(m) for m in msgs])
    b = _sequential_twin(lambda: DeltaParameterServer(_blob()), msgs)
    for wa, wb in zip(a.center, b.center):
        np.testing.assert_array_equal(wa, wb)


# ---------------------------------------------------------------------------
# the live event server: scripted drain groupings
# ---------------------------------------------------------------------------

class _GatedPS(DeltaParameterServer):
    """First apply blocks on a gate — wedges the I/O loop mid-drain so the
    test controls exactly which commits pile up for the next drain."""

    def __init__(self, blob, gate):
        super().__init__(blob)
        self._gate = gate
        self._applied = 0

    def _apply(self, msg):
        if self._applied == 0:
            self._gate.wait(10.0)
        self._applied += 1
        super()._apply(msg)


class _GatedDynSGDPS(DynSGDParameterServer):
    def __init__(self, blob, gate):
        super().__init__(blob)
        self._gate = gate
        self._applied = 0

    def _apply(self, msg):
        if self._applied == 0:
            self._gate.wait(10.0)
        self._applied += 1
        super()._apply(msg)


def _send_commit(port, delta, clock=0):
    sock = networking.connect("127.0.0.1", port)
    networking.send_opcode(sock, b"c")
    networking.send_data(sock, {"delta": delta, "worker_id": 0,
                                "clock": clock})
    return sock


def _wait(pred, timeout=5.0):
    deadline = time.time() + timeout
    while not pred() and time.time() < deadline:
        time.sleep(0.01)
    assert pred()


def test_event_server_coalesces_commits_that_arrive_mid_apply():
    """Commits landing while an apply is in flight are merged into ONE
    drain: wedge the first apply, send three more commits, release — the
    three apply as one batch (``coalesce_stats`` proves it) and the center
    equals the sum of all four."""
    gate = threading.Event()
    ps = _GatedPS(_blob(), gate)
    server = SocketParameterServer(ps)
    server.start()
    socks = []
    try:
        d = [np.ones(s, np.float32) for s in SHAPES]
        socks.append(_send_commit(server.port, d))
        _wait(lambda: ps._lock.locked())  # the loop is wedged in apply 1
        for _ in range(3):
            socks.append(_send_commit(server.port, d))
        time.sleep(0.3)  # let the three commits reach the kernel buffers
        gate.set()
        _wait(lambda: ps.num_updates == 4)
        for w, s in zip(ps.center, SHAPES):
            np.testing.assert_array_equal(w, np.full(s, 4.0))
        stats = server.coalesce_stats
        assert stats["commits_applied"] == 4
        assert stats["max_drain"] >= 2       # the merge really happened
        assert stats["coalesced_drains"] >= 1
    finally:
        gate.set()
        for s in socks:
            s.close()
        server.stop()


def test_dynsgd_drain_groupings_under_chaos_delay():
    """The satellite's scripted interleave: commit A wedges the apply;
    B1/B2 arrive mid-apply and coalesce into drain 2 (both stamped at
    arrival clock 1 → staleness 1 → scale 1/2 — drain-mates do NOT count
    each other); commit C rides a ChaosProxy ``delay`` long enough to land
    in its own later drain (arrival clock 3 → staleness 3 → scale 1/4).
    Final center = A + (B1+B2)/2 + C/4, exact in powers of two."""
    gate = threading.Event()
    ps = _GatedDynSGDPS(_blob(), gate)
    server = SocketParameterServer(ps)
    server.start()
    proxy = ChaosProxy("127.0.0.1", server.port,
                       faults=[ChaosFault(0, 0, "delay", 1.2)])
    socks = []
    try:
        d = [np.full(s, 8.0, np.float32) for s in SHAPES]
        socks.append(_send_commit(server.port, d))         # A: scale 1
        _wait(lambda: ps._lock.locked())
        # C through the proxy now: its 1.2 s delay outlasts the gate
        sock_c = networking.connect(proxy.host, proxy.port)
        networking.send_opcode(sock_c, b"c")
        networking.send_data(sock_c, {"delta": d, "worker_id": 0,
                                      "clock": 0})
        socks.append(sock_c)
        socks.append(_send_commit(server.port, d))         # B1
        socks.append(_send_commit(server.port, d))         # B2
        time.sleep(0.3)
        gate.set()                                         # drain 2: B1+B2
        _wait(lambda: ps.num_updates == 4, timeout=10.0)   # drain 3: C
        expected = 8.0 + 4.0 + 4.0 + 2.0
        for w, s in zip(ps.center, SHAPES):
            np.testing.assert_array_equal(w, np.full(s, expected))
        assert proxy.injected == [(0, 0, "delay")]
        assert server.coalesce_stats["max_drain"] >= 2
    finally:
        gate.set()
        for s in socks:
            s.close()
        proxy.stop()
        server.stop()


def test_coalesce_false_applies_one_commit_per_batch():
    """``coalesce=False`` keeps the event loop but degrades every drain to
    per-commit batches — the sequential semantics knob."""
    gate = threading.Event()
    ps = _GatedPS(_blob(), gate)
    server = SocketParameterServer(ps, coalesce=False)
    server.start()
    socks = []
    try:
        d = [np.ones(s, np.float32) for s in SHAPES]
        socks.append(_send_commit(server.port, d))
        _wait(lambda: ps._lock.locked())
        for _ in range(3):
            socks.append(_send_commit(server.port, d))
        time.sleep(0.3)
        gate.set()
        _wait(lambda: ps.num_updates == 4)
        stats = server.coalesce_stats
        assert stats["commits_applied"] == 4
        assert stats["max_drain"] == 1
        assert stats["coalesced_drains"] == 0
    finally:
        gate.set()
        for s in socks:
            s.close()
        server.stop()


def test_shared_drain_snapshot_keeps_u_reply_clock_advancing():
    """Two workers' 'u' commits coalesced into one drain share one
    snapshot; each connection's reply clock still strictly advances across
    its own round trips (the duplicate-reply discard baseline)."""
    ps = DeltaParameterServer(_blob())
    server = SocketParameterServer(ps)
    server.start()
    try:
        socks = [networking.connect("127.0.0.1", server.port)
                 for _ in range(2)]
        d = [np.ones(s, np.float32) for s in SHAPES]
        last = [0, 0]
        for round_ in range(3):
            for s in socks:
                networking.send_opcode(s, b"u")
                networking.send_data(s, {"delta": d, "worker_id": 0,
                                         "clock": 0})
            for i, s in enumerate(socks):
                msg = networking.recv_data(s)
                assert msg["clock"] > last[i]
                last[i] = msg["clock"]
        assert ps.num_updates == 6
        for s in socks:
            networking.send_opcode(s, b"q")
            s.close()
    finally:
        server.stop()


def test_apply_error_drops_connection_but_loop_survives():
    """A hostile commit (mis-declared sparse length) costs its own
    connection, not the server: the loop logs and keeps serving."""
    ps = DeltaParameterServer(_blob())
    server = SocketParameterServer(ps)
    server.start()
    try:
        bad = networking.connect("127.0.0.1", server.port)
        networking.send_opcode(bad, b"c")
        networking.send_data(bad, {
            "delta": SparseDelta(np.array([0], np.int32),
                                 np.array([1.0], np.float32), TOTAL + 7),
            "worker_id": 0, "clock": 0})
        bad.settimeout(5.0)
        try:
            got = bad.recv(1)
        except (ConnectionError, OSError):
            got = b""
        assert got == b""  # the server hung up on the offender
        bad.close()
        ok = networking.connect("127.0.0.1", server.port)
        networking.send_opcode(ok, b"u")
        networking.send_data(ok, {"delta": [np.ones(s, np.float32)
                                            for s in SHAPES],
                                  "worker_id": 1, "clock": 0})
        msg = networking.recv_data(ok)
        assert msg["clock"] == 1  # nothing of the hostile commit applied
        ok.close()
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# knob plumbing: ps_core / coalesce / apply_kernel through the trainers
# ---------------------------------------------------------------------------

def _tiny_training(**kw):
    from distkeras_tpu import ADAG, Dataset
    from distkeras_tpu.core.layers import Dense
    from distkeras_tpu.core.model import Sequential

    rng = np.random.default_rng(0)
    x = rng.standard_normal((96, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 96)]
    model = Sequential([Dense(8, activation="relu"),
                        Dense(3, activation="softmax")],
                       input_shape=(6,), compute_dtype="float32")
    t = ADAG(model, num_workers=1, parallelism_factor=2, batch_size=8,
             num_epoch=1, communication_window=2, learning_rate=0.05,
             execution="host_ps", **kw)
    t.train(Dataset({"features": x, "label": y}))
    return t


@pytest.mark.parametrize("core", ["event", "threaded"])
def test_trainer_ps_core_knob_end_to_end(core):
    t = _tiny_training(ps_core=core)
    assert len(t.history) > 0
    stats = t.ps_coalesce_stats
    if core == "event":
        assert stats is not None and stats["commits_applied"] > 0
    else:
        assert stats is None  # the threaded core has no drains


def test_trainer_apply_kernel_auto_end_to_end():
    t = _tiny_training(apply_kernel="auto")
    assert len(t.history) > 0


def test_trainer_knob_validation():
    from distkeras_tpu import ADAG
    from test_trainers import make_model
    kw = dict(num_workers=2, label_col="label_encoded")
    with pytest.raises(ValueError, match="ps_core"):
        ADAG(make_model(), execution="host_ps", ps_core="nope", **kw)
    with pytest.raises(ValueError, match="apply_kernel"):
        ADAG(make_model(), execution="host_ps", apply_kernel="nope", **kw)
    with pytest.raises(ValueError, match="ps_core/coalesce/apply_kernel"):
        ADAG(make_model(), ps_core="threaded", **kw)  # SPMD: no server
    t = ADAG(make_model(), execution="host_ps", **kw)
    assert t.ps_core == "event" and t.coalesce and t.apply_kernel is None


def test_make_socket_server_selects_core():
    ps = DeltaParameterServer(_blob())
    assert isinstance(make_socket_server(ps), SocketParameterServer)
    assert isinstance(make_socket_server(ps, ps_core="threaded"),
                      ThreadedSocketParameterServer)
    with pytest.raises(ValueError, match="ps_core"):
        make_socket_server(ps, ps_core="green")


# ---------------------------------------------------------------------------
# FrameParser: the event loop's incremental receive path
# ---------------------------------------------------------------------------

def _frame_stream(msgs, ops=None):
    """A wire byte stream of framed commits interleaved with frameless ops."""
    out = bytearray()
    ops = ops or ["u"] * len(msgs)
    for op, m in zip(ops, msgs):
        out += op.encode()
        out += networking.encode_message(m)
    return bytes(out)


def _drain_parser(p):
    return list(p.messages())


def _copy_msg(m):
    if m is None:
        return None
    out = dict(m)
    d = out.get("delta")
    if isinstance(d, SparseDelta):
        out["delta"] = SparseDelta(np.array(d.indices), np.array(d.values),
                                   d.length, getattr(d, "scale", None))
    elif d is not None:
        out["delta"] = [np.array(a) for a in d]
    return out


def _assert_msgs_equal(got, want_ops, want_msgs):
    assert [op for op, _ in got] == [o.encode() for o in want_ops]
    framed = [m for _, m in got if m is not None]
    assert len(framed) == len(want_msgs)
    for g, w in zip(framed, want_msgs):
        gd, wd = g["delta"], w["delta"]
        if isinstance(wd, SparseDelta):
            assert isinstance(gd, SparseDelta)
            np.testing.assert_array_equal(np.asarray(gd.indices),
                                          np.asarray(wd.indices))
            np.testing.assert_array_equal(np.asarray(gd.values),
                                          np.asarray(wd.values))
            assert gd.length == wd.length
        else:
            for a, b in zip(gd, wd):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_frameparser_whole_stream_one_feed():
    rng = np.random.default_rng(0)
    msgs = [_dense_msg(rng) for _ in range(3)]
    stream = _frame_stream(msgs)
    p = networking.FrameParser()
    p.feed(stream)
    _assert_msgs_equal(_drain_parser(p), ["u"] * 3, msgs)


@pytest.mark.parametrize("chunk", [1, 7, 64, 1000])
def test_frameparser_chunked_fuzz_equals_one_feed(chunk):
    """Any chunking of the byte stream — down to one byte at a time —
    yields exactly the messages of a single whole-stream feed (the parser
    may be drained between any two feeds)."""
    rng = np.random.default_rng(1)
    msgs = [_dense_msg(rng), _sparse_msg(rng), _dense_msg(rng)]
    stream = _frame_stream(msgs, ops=["u", "c", "u"])
    p = networking.FrameParser()
    got = []
    for off in range(0, len(stream), chunk):
        p.feed(stream[off:off + chunk])
        # Snapshot at drain time: decoded arrays are views into the frame
        # buffer, which the parser recycles once the caller consumed them.
        got.extend((op, _copy_msg(m)) for op, m in _drain_parser(p))
    _assert_msgs_equal(got, ["u", "c", "u"], msgs)


def test_frameparser_frameless_ops_interleaved():
    rng = np.random.default_rng(2)
    m = _dense_msg(rng)
    stream = b"p" + b"h" + b"u" + networking.encode_message(m) + b"q"
    p = networking.FrameParser()
    p.feed(stream)
    got = _drain_parser(p)
    assert [op for op, _ in got] == [b"p", b"h", b"u", b"q"]
    assert got[0][1] is None and got[3][1] is None


def test_frameparser_direct_fill_writable_advance():
    """The big-frame path: once the torn frame's header has arrived the
    parser exposes the preallocated tail for recv_into-style direct
    filling, and the filled frame decodes identically."""
    rng = np.random.default_rng(3)
    m = {"delta": [rng.standard_normal(40_000).astype(np.float32)],
         "worker_id": 0, "clock": 0}
    stream = b"u" + networking.encode_message(m)
    p = networking.FrameParser()
    assert p.writable() is None
    p.feed(stream[:4096])  # header lands, payload torn
    assert _drain_parser(p) == []
    w = p.writable()
    assert w is not None and len(w) == len(stream) - 4096
    w[:] = stream[4096:]
    p.advance(len(w))
    _assert_msgs_equal(_drain_parser(p), ["u"], [m])
    assert p.writable() is None


def test_frameparser_recycles_retired_frame_buffer():
    """Steady-state same-size torn frames reassemble into the SAME buffer
    (no per-frame allocate-and-zero) — the recycle contract assumes the
    caller consumed the previous frame's views before feeding more."""
    rng = np.random.default_rng(4)
    p = networking.FrameParser()
    buf_ids = []
    for _ in range(3):
        m = {"delta": [rng.standard_normal(10_000).astype(np.float32)],
             "worker_id": 0, "clock": 0}
        stream = b"u" + networking.encode_message(m)
        p.feed(stream[:1024])
        assert _drain_parser(p) == []
        w = p.writable()
        w[:] = stream[1024:]
        p.advance(len(w))
        got = _drain_parser(p)
        _assert_msgs_equal(got, ["u"], [m])
        buf_ids.append(id(w.obj))
    assert buf_ids[1] == buf_ids[2]  # second torn frame reuses the first's


def test_frameparser_bad_magic_raises():
    p = networking.FrameParser()
    p.feed(b"u" + b"XXXX" + b"\0" * 16)
    with pytest.raises(ValueError, match="magic"):
        _drain_parser(p)


def test_frameparser_oversized_header_raises():
    import struct
    p = networking.FrameParser()
    bad = b"u" + networking.MAGIC + struct.pack("<I", 1 << 30)
    p.feed(bad)
    with pytest.raises(ValueError, match="[Hh]eader"):
        _drain_parser(p)


def test_frameparser_buffer_length_lie_raises():
    """A frame whose u64 buffer prefix disagrees with the header's
    dtype×shape is rejected (the desync guard recv_data applies)."""
    rng = np.random.default_rng(5)
    m = _dense_msg(rng)
    frame = bytearray(networking.encode_message(m))
    # corrupt the first payload-buffer length prefix
    (hlen,) = networking._U32.unpack_from(frame, 4)
    off = 8 + hlen
    networking._U64.pack_into(frame, off, 7)
    p = networking.FrameParser()
    p.feed(b"u" + bytes(frame))
    with pytest.raises(ValueError):
        _drain_parser(p)
