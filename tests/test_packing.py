"""Sequence packing (data/packing.py + segment-isolated attention).

The load-bearing property: with RoPE (relative positions), a document
packed mid-row behind other documents produces EXACTLY the hidden states
and logits it would produce unpacked — the segment mask removes every
cross-document score and RoPE makes within-segment attention
position-shift-invariant.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.core.losses import get_loss
from distkeras_tpu.data.packing import (pack_documents, packed_lm_labels,
                                        packing_efficiency)
from distkeras_tpu.models.zoo import transformer_lm


def test_pack_documents_first_fit():
    docs = [[1, 2, 3], [4, 5], [6, 7, 8, 9], [10]]
    tokens, segs = pack_documents(docs, seq_len=6)
    # row 0: doc0 (3) + doc1 (2) + doc3 (1); row 1: doc2 (4)
    np.testing.assert_array_equal(tokens[0], [1, 2, 3, 4, 5, 10])
    np.testing.assert_array_equal(segs[0], [1, 1, 1, 2, 2, 3])
    np.testing.assert_array_equal(tokens[1], [6, 7, 8, 9, 0, 0])
    np.testing.assert_array_equal(segs[1], [1, 1, 1, 1, 0, 0])
    assert packing_efficiency(segs) == 10 / 12
    with pytest.raises(ValueError, match="never truncates"):
        pack_documents([[1] * 7], seq_len=6)
    # empty docs are skipped, not packed as ghost segments
    t2, s2 = pack_documents([[], [1]], seq_len=4)
    assert s2[0, 0] == 1 and (s2[0, 1:] == 0).all()


def test_packed_lm_labels_mask_boundaries():
    tokens = np.array([[1, 2, 3, 4, 5, 0]])
    segs = np.array([[1, 1, 2, 2, 2, 0]])
    labels = packed_lm_labels(tokens, segs)
    # within-segment next tokens; -1 at the 1->2 boundary, into padding,
    # and at the last position
    np.testing.assert_array_equal(labels[0], [2, -1, 4, 5, -1, -1])


def test_masked_loss_skips_ignored():
    loss = get_loss("sparse_categorical_crossentropy_masked_from_logits")
    logits = jnp.asarray(np.random.default_rng(0).normal(size=(1, 4, 8)),
                         jnp.float32)
    labels = jnp.array([[3, -1, 5, -1]])
    got = float(loss(labels, logits))
    logp = jax.nn.log_softmax(logits, axis=-1)
    want = -(float(logp[0, 0, 3]) + float(logp[0, 2, 5])) / 2
    np.testing.assert_allclose(got, want, rtol=1e-6)


def lm(seq_len):
    return transformer_lm(vocab_size=32, seq_len=seq_len, d_model=32,
                          num_heads=4, num_layers=2, mlp_dim=64,
                          compute_dtype="float32", positional="rope")


def counting_docs(seed, count):
    """The shared x+1-rule corpus (token ids 1..31, wrap): variable-length
    counting runs, used by every packed-trainer test so the learned rule
    stays comparable across them."""
    rng = np.random.default_rng(seed)
    docs = []
    for _ in range(count):
        n = int(rng.integers(4, 10))
        start = int(rng.integers(1, 31))
        docs.append([(start + i) % 31 + 1 for i in range(n)])
    return docs


def test_packed_forward_equals_unpacked_per_document():
    """The killer property: each packed document's logits equal its
    unpacked forward (RoPE + segment mask)."""
    model = lm(seq_len=12)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(1)
    docs = [list(rng.integers(1, 32, n)) for n in (5, 4, 3, 7)]
    tokens, segs = pack_documents(docs, seq_len=12)
    packed = np.asarray(model.apply(params, jnp.asarray(tokens),
                                    segment_ids=jnp.asarray(segs)))

    # map every doc back to its packed (row, start) slot and compare
    for doc in docs:
        n = len(doc)
        solo = np.asarray(model.apply(
            params, jnp.asarray(np.array(doc)[None], jnp.int32)))[0]
        found = False
        for r in range(tokens.shape[0]):
            for s in range(12 - n + 1):
                if (tokens[r, s:s + n] == doc).all() \
                        and len(set(segs[r, s:s + n])) == 1 \
                        and segs[r, s] != 0 \
                        and (s == 0 or segs[r, s - 1] != segs[r, s]) \
                        and (s + n == 12 or segs[r, s + n] != segs[r, s]):
                    np.testing.assert_allclose(packed[r, s:s + n], solo,
                                               rtol=2e-4, atol=2e-4)
                    found = True
        assert found, f"doc of len {n} not located in packed rows"


def test_without_segment_ids_documents_leak():
    """Control: dropping the segment mask changes the second document's
    logits (it sees the first) — proves the mask is doing the work."""
    model = lm(seq_len=8)
    params = model.init(jax.random.PRNGKey(2))
    tokens = np.array([[1, 2, 3, 4, 5, 6, 7, 8]], np.int32)
    segs = np.array([[1, 1, 1, 1, 2, 2, 2, 2]], np.int32)
    masked = np.asarray(model.apply(params, jnp.asarray(tokens),
                                    segment_ids=jnp.asarray(segs)))
    unmasked = np.asarray(model.apply(params, jnp.asarray(tokens)))
    # doc 1 (positions 0-3) sees nothing new -> identical either way
    np.testing.assert_allclose(masked[0, :4], unmasked[0, :4],
                               rtol=2e-4, atol=2e-4)
    assert np.abs(masked[0, 4:] - unmasked[0, 4:]).max() > 1e-3


def test_packed_training_learns_the_rule():
    """Train on PACKED x+1 documents via the masked loss and verify the
    learned rule generates correctly — packing end to end."""
    import optax
    from distkeras_tpu.core.decode import generate

    model = lm(seq_len=16)
    params = model.init(jax.random.PRNGKey(3))
    docs = counting_docs(4, 192)
    tokens, segs = pack_documents(docs, seq_len=16)
    labels = packed_lm_labels(tokens, segs)
    loss_fn = get_loss("sparse_categorical_crossentropy_masked_from_logits")

    tx = optax.adam(3e-3)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt, toks, segs, labels):
        def loss(p):
            logits = model.apply(p, toks, segment_ids=segs)
            return loss_fn(labels, logits)
        l, g = jax.value_and_grad(loss)(params)
        updates, opt = tx.update(g, opt, params)
        return optax.apply_updates(params, updates), opt, l

    toks_j = jnp.asarray(tokens)
    segs_j = jnp.asarray(segs)
    labels_j = jnp.asarray(labels)
    first = last = None
    for e in range(60):
        params, opt, l = step(params, opt, toks_j, segs_j, labels_j)
        if e == 0:
            first = float(l)
        last = float(l)
    assert last < first * 0.25, (first, last)

    prompt = np.array([[5, 6, 7]], np.int32)
    out = np.asarray(generate(model, params, prompt, 5))
    want = (prompt[:, -1:] + np.arange(1, 6) - 1) % 31 + 1
    np.testing.assert_array_equal(out[:, 3:], want)


def test_learned_positional_refused():
    model = transformer_lm(vocab_size=16, seq_len=8, d_model=16,
                           num_heads=2, num_layers=1, mlp_dim=32,
                           compute_dtype="float32", positional="learned")
    params = model.init(jax.random.PRNGKey(0))
    toks = jnp.zeros((1, 8), jnp.int32)
    segs = jnp.ones((1, 8), jnp.int32)
    with pytest.raises(ValueError, match="rope"):
        model.apply(params, toks, segment_ids=segs)


def test_bad_impl_still_rejected_with_segments():
    from distkeras_tpu.ops.attention import attention
    q = jnp.zeros((1, 8, 2, 4))
    segs = jnp.ones((1, 8), jnp.int32)
    with pytest.raises(ValueError, match="unknown attention impl"):
        attention(q, q, q, causal=True, impl="palas", segment_ids=segs)
    with pytest.raises(ValueError, match="pallas flash kernel"):
        attention(q, q, q, causal=True, impl="pallas", segment_ids=segs)


def test_row_retirement_keeps_first_fit_semantics():
    # a large corpus packs identically to naive first-fit and quickly
    rng = np.random.default_rng(7)
    docs = [list(rng.integers(1, 9, int(rng.integers(3, 12))))
            for _ in range(3000)]
    tokens, segs = pack_documents(docs, seq_len=32)
    # every token accounted for, no truncation
    assert int((segs != 0).sum()) == sum(len(d) for d in docs)
    assert packing_efficiency(segs) > 0.9


def test_single_trainer_packed_path():
    """SingleTrainer(segment_col=...) trains on a packed corpus through
    the flagship API and the learned rule generates correctly."""
    from distkeras_tpu.core.decode import generate
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.trainers import SingleTrainer

    docs = counting_docs(5, 192)
    tokens, segs = pack_documents(docs, seq_len=16)
    labels = packed_lm_labels(tokens, segs)

    model = lm(seq_len=16)
    t = SingleTrainer(
        model, batch_size=32, num_epoch=20,
        loss="sparse_categorical_crossentropy_masked_from_logits",
        worker_optimizer="adam", learning_rate=3e-3,
        segment_col="segment_ids")
    fitted = t.train(Dataset({"features": tokens, "label": labels,
                              "segment_ids": segs}), shuffle=True)
    assert t.history[-1] < t.history[0] * 0.25

    prompt = np.array([[5, 6, 7]], np.int32)
    out = np.asarray(generate(fitted.model, fitted.params, prompt, 5))
    want = (prompt[:, -1:] + np.arange(1, 6) - 1) % 31 + 1
    np.testing.assert_array_equal(out[:, 3:], want)


def test_packed_validation_matches_unpacked():
    """Packed validation (round-4 VERDICT weak #4): ``validation_data``
    with ``segment_col`` runs through the masked loss with segment
    isolation, and the packed val loss equals the SAME documents evaluated
    unpacked one-row-per-document (RoPE + segment mask make the two
    forwards identical; the masked mean runs over the same label set)."""
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.trainers import SingleTrainer

    rng = np.random.default_rng(7)
    seq_len = 16
    docs = [list(rng.integers(1, 32, int(rng.integers(3, 8))))
            for _ in range(24)]
    train_docs, val_docs = docs[:16], docs[16:]
    tok_tr, seg_tr = pack_documents(train_docs, seq_len)
    lab_tr = packed_lm_labels(tok_tr, seg_tr)
    tok_v, seg_v = pack_documents(val_docs, seq_len)
    lab_v = packed_lm_labels(tok_v, seg_v)

    model = lm(seq_len=seq_len)
    t = SingleTrainer(
        model, batch_size=8, num_epoch=1,
        loss="sparse_categorical_crossentropy_masked_from_logits",
        worker_optimizer="adam", learning_rate=1e-3,
        segment_col="segment_ids")
    fitted = t.train(
        Dataset({"features": tok_tr, "label": lab_tr,
                 "segment_ids": seg_tr}),
        validation_data=Dataset({"features": tok_v, "label": lab_v,
                                 "segment_ids": seg_v}))
    assert len(t.validation_history) == 1

    # unpacked equivalent: one row per validation document
    n = len(val_docs)
    tok_u = np.zeros((n, seq_len), np.int32)
    seg_u = np.zeros((n, seq_len), np.int32)
    for i, d in enumerate(val_docs):
        tok_u[i, :len(d)] = d
        seg_u[i, :len(d)] = 1
    lab_u = packed_lm_labels(tok_u, seg_u)
    loss = get_loss("sparse_categorical_crossentropy_masked_from_logits")
    pred = fitted.model.apply(fitted.params, jnp.asarray(tok_u),
                              segment_ids=jnp.asarray(seg_u))
    want = float(loss(jnp.asarray(lab_u), pred))
    np.testing.assert_allclose(t.validation_history[0], want,
                               rtol=2e-4, atol=2e-4)

    # validation data missing the segment column is still refused
    with pytest.raises(ValueError, match="segment"):
        t2 = SingleTrainer(model, segment_col="segment_ids",
                           loss="sparse_categorical_crossentropy_masked")
        t2.train(Dataset({"features": tok_tr, "label": lab_tr,
                          "segment_ids": seg_tr}),
                 validation_data=Dataset({"features": tok_v,
                                          "label": lab_v}))


def test_segment_col_requires_masked_loss():
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.trainers import SingleTrainer
    model = lm(seq_len=8)
    t = SingleTrainer(model, segment_col="segment_ids",
                      loss="sparse_categorical_crossentropy_from_logits")
    ds = Dataset({"features": np.zeros((4, 8), np.int32),
                  "label": np.zeros((4, 8), np.int32),
                  "segment_ids": np.ones((4, 8), np.int32)})
    with pytest.raises(ValueError, match="masked"):
        t.train(ds)


def test_distributed_packed_path():
    """Packing on the DISTRIBUTED engine (SPMD twin of the SingleTrainer
    path): ADAG(segment_col=...) trains a packed corpus over the 8-device
    mesh — segment ids ride the round scan into the masked step — learns
    the x+1 rule, threads packed validation, and refuses misuse."""
    from distkeras_tpu.core.decode import generate
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.trainers import ADAG

    docs = counting_docs(9, 384)
    tokens, segs = pack_documents(docs, seq_len=16)
    labels = packed_lm_labels(tokens, segs)
    ds = Dataset({"features": tokens, "label": labels,
                  "segment_ids": segs})

    model = lm(seq_len=16)
    t = ADAG(model, num_workers=8, batch_size=4, num_epoch=30,
             communication_window=2,
             loss="sparse_categorical_crossentropy_masked_from_logits",
             worker_optimizer="adam", learning_rate=3e-3,
             segment_col="segment_ids")
    fitted = t.train(ds, shuffle=True, validation_data=ds)
    assert t.history[-1] < t.history[0] * 0.3
    assert len(t.validation_history) == 30

    prompt = np.array([[5, 6, 7]], np.int32)
    out = np.asarray(generate(fitted.model, fitted.params, prompt, 5))
    want = (prompt[:, -1:] + np.arange(1, 6) - 1) % 31 + 1
    np.testing.assert_array_equal(out[:, 3:], want)

    with pytest.raises(ValueError, match="masked"):
        ADAG(model, num_workers=8, segment_col="segment_ids",
             loss="sparse_categorical_crossentropy_from_logits").train(ds)
    with pytest.raises(ValueError, match="spmd"):
        ADAG(model, num_workers=8, segment_col="segment_ids",
             loss="sparse_categorical_crossentropy_masked",
             execution="host_ps").train(ds)


def test_local_family_trainers_accept_packing():
    """AveragingTrainer/EnsembleTrainer inherit the packed path through
    DistributedTrainer ('local' algorithm, no exchange): packed corpora
    train per-worker with segment isolation; members genuinely differ."""
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.trainers import AveragingTrainer, EnsembleTrainer

    docs = counting_docs(12, 256)
    tokens, segs = pack_documents(docs, seq_len=16)
    labels = packed_lm_labels(tokens, segs)
    ds = Dataset({"features": tokens, "label": labels,
                  "segment_ids": segs})

    t = AveragingTrainer(
        lm(seq_len=16), num_workers=8, batch_size=4, num_epoch=4,
        loss="sparse_categorical_crossentropy_masked_from_logits",
        worker_optimizer="adam", learning_rate=3e-3,
        segment_col="segment_ids")
    t.train(ds, shuffle=True)
    assert t.history[-1] < t.history[0]

    e = EnsembleTrainer(
        lm(seq_len=16), num_models=8, batch_size=4, num_epoch=2,
        loss="sparse_categorical_crossentropy_masked_from_logits",
        worker_optimizer="adam", learning_rate=3e-3,
        segment_col="segment_ids")
    members = e.train(ds, shuffle=True)
    assert len(members) == 8
    w0 = jax.tree_util.tree_leaves(members[0].params)[0]
    w1 = jax.tree_util.tree_leaves(members[1].params)[0]
    assert np.abs(np.asarray(w0) - np.asarray(w1)).max() > 1e-6
