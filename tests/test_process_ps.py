"""Cross-process DCN training: workers as separate OS processes.

Round-3 VERDICT missing #3: the reference actually ran workers in other
*processes* (Spark executors on other machines); the host_ps engine only
proved the protocol across threads in one interpreter.  Here
``execution='process_ps'`` launches each worker as its own Python process
via ``job_deployment.LocalJobRunner`` (the ``ps_worker_main`` entry point,
``DISTKERAS_TPU_*`` env contract) dialing the driver's
SocketParameterServer over loopback TCP — nothing is shared but the wire.
"""

import numpy as np
import pytest

from distkeras_tpu import ADAG, DOWNPOUR

from test_trainers import eval_accuracy, make_dataset, make_model


@pytest.mark.slow
def test_process_ps_trains_across_os_processes():
    ds = make_dataset(n=1024)
    t = ADAG(make_model(), num_workers=2, batch_size=16, num_epoch=3,
             communication_window=4, label_col="label_encoded",
             worker_optimizer="adam", learning_rate=2e-3,
             execution="process_ps")
    fitted = t.train(ds)
    # final-model retrieval + convergence through the socket wire only
    assert eval_accuracy(fitted, ds) > 0.9
    assert t.get_training_time() > 0
    # per-worker histories were collected from the worker processes:
    # 2 workers x 3 epochs x ceil(512/(4*16)) = 8 windows
    assert len(t.get_history()) == 2 * 3 * 8
    # loss decreased within each worker's stream
    h = t.get_history()
    assert h[23] < h[0] and h[47] < h[24]


@pytest.mark.slow
def test_process_ps_elastic_family():
    """AEASGD across OS processes: the elastic rho rides the JSON worker
    config and the persistent local models converge against the center."""
    from distkeras_tpu import AEASGD
    ds = make_dataset(n=512)
    t = AEASGD(make_model(), num_workers=2, batch_size=16, num_epoch=3,
               communication_window=4, rho=1.0, learning_rate=0.1,
               label_col="label_encoded", worker_optimizer="sgd",
               execution="process_ps")
    fitted = t.train(ds)
    assert eval_accuracy(fitted, ds) > 0.85


@pytest.mark.slow
def test_process_ps_downpour_and_validation():
    ds = make_dataset(n=512)
    t = DOWNPOUR(make_model(), num_workers=2, batch_size=16, num_epoch=2,
                 communication_window=4, label_col="label_encoded",
                 worker_optimizer="sgd", learning_rate=0.05,
                 execution="process_ps")
    fitted = t.train(ds)
    assert eval_accuracy(fitted, ds) > 0.8

    with pytest.raises(ValueError, match="resume"):
        ADAG(make_model(), num_workers=2, execution="process_ps",
             label_col="label_encoded").train(ds, resume=True)
    with pytest.raises(ValueError, match="checkpoint"):
        ADAG(make_model(), num_workers=2, execution="process_ps",
             checkpoint_dir="/tmp/nope",
             label_col="label_encoded").train(ds)
