"""Load-generator + serving-bench surface (examples/loadgen.py, bench.py).

The fast variants here are tier-1: a small fixed trace through the closed
loop must complete losslessly with sane metrics, and the trace itself must
be a pure function of its seed.  The full-size comparison — continuous
batching beating sequential per-request ``generate`` at ≥ 4 concurrent
requests — and the offered-QPS sweep are ``slow`` (they time real decode
work).
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "examples"))
import loadgen  # noqa: E402


def test_trace_is_deterministic():
    a = loadgen.make_trace(12, seed=3, temperature=0.7)
    b = loadgen.make_trace(12, seed=3, temperature=0.7)
    assert len(a) == len(b) == 12
    for ra, rb in zip(a, b):
        assert ra.keys() == rb.keys()
        np.testing.assert_array_equal(ra["prompt"], rb["prompt"])
        assert ra["seed"] == rb["seed"]
        assert ra.get("temperature") == rb.get("temperature")
    c = loadgen.make_trace(12, seed=4, temperature=0.7)
    assert any((len(ra["prompt"]) != len(rc["prompt"]))
               or (ra["prompt"] != rc["prompt"][:len(ra["prompt"])]).any()
               for ra, rc in zip(a, c))


def test_closed_loop_fast_trace_lossless():
    """Tier-1 deterministic variant: every traced request completes, zero
    shed, tokens accounted exactly, occupancy recorded."""
    _, engine = loadgen.build_engine(num_slots=2, queue_capacity=16)
    trace = loadgen.make_trace(6, num_steps=6, temperature=0.5)
    try:
        m = loadgen.run_closed_loop(engine, trace, concurrency=4,
                                    timeout_s=120.0)
    finally:
        engine.stop()
    assert m["completed"] == 6 and m["shed"] == 0
    assert m["tokens"] == 6 * 6
    assert m["tokens_per_sec"] > 0
    assert m["p50_ms"] is not None and m["p99_ms"] >= m["p50_ms"]
    assert 0.0 < m["slot_occupancy"] <= 1.0
    assert all(n >= 1 for n in engine.stats["slot_requests"])
    # the TTFT observables ride the same run: first token precedes the
    # end of its request, and prompt tokens flowed through the compiled
    # prefill path
    assert m["ttft_p50_ms"] is not None
    assert m["ttft_p50_ms"] <= m["p50_ms"]
    assert m["prefill_tokens_per_sec"] > 0
    assert engine.stats["prefill_batches"] >= 1
    assert engine.stats["prefill_batch_size_mean"] >= 1.0


def test_closed_loop_outputs_match_offline_generate():
    """The loadgen path changes scheduling only: each traced request's
    tokens equal offline generate's for the same seed."""
    import jax

    fitted, engine = loadgen.build_engine(num_slots=2, queue_capacity=16)
    trace = loadgen.make_trace(5, num_steps=5, temperature=0.6)
    handles = [engine.submit(**req) for req in trace]
    try:
        engine.start()
        for h in handles:
            assert h.wait(timeout=120.0)
    finally:
        engine.stop()
    for h, req in zip(handles, trace):
        temp = req.get("temperature", 0.0)
        want = np.asarray(fitted.generate(
            req["prompt"][None], req["num_steps"], temperature=temp,
            rng=jax.random.PRNGKey(req["seed"]) if temp else None,
            max_len=engine.max_len))[0]
        np.testing.assert_array_equal(h.result(), want)


def test_bench_serving_fields_shape():
    """bench.serving_bench returns exactly the serving_* field set (None
    allowed — the artifact contract) without touching the north star."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench
    out = bench.serving_bench(budget_s=0.0)  # force the overrun path
    assert set(out) == {"serving_tokens_per_sec", "serving_p50_ms",
                        "serving_p99_ms", "serving_slot_occupancy",
                        "serving_sequential_tokens_per_sec",
                        "serving_shed_rate", "serving_slot_reclaim_ms",
                        "serving_deadline_miss_rate",
                        "serving_ttft_p50_ms", "serving_ttft_p99_ms",
                        "serving_prefill_tokens_per_sec",
                        "serving_longprompt_ttft_p99_ms",
                        "serving_longprompt_ttft_eager_p99_ms",
                        "serving_spec_tokens_per_sec",
                        "serving_spec_accept_rate",
                        "serving_quant_capacity_slots"}


def test_closed_loop_chaos_kill_schedule_no_leaks():
    """The --chaos client-kill schedule: seeded kills cancel mid-run, the
    engine reclaims every slot (zero leaks), survivors complete, and the
    new failure-semantics metrics are recorded."""
    # 24-step requests: the fast-path engine streams short requests so
    # quickly that a killer waiting for its seeded token count could lose
    # the race and cancel an already-finished request (a no-op) — the
    # longer run keeps every seeded kill landing mid-run
    _, engine = loadgen.build_engine(num_slots=2, queue_capacity=16)
    trace = loadgen.make_trace(8, num_steps=24, temperature=0.5)
    try:
        m = loadgen.run_closed_loop(engine, trace, concurrency=4,
                                    timeout_s=120.0, chaos_kill=0.4,
                                    chaos_seed=3)
    finally:
        engine.stop()
    assert m["killed"] > 0  # the seeded schedule really killed someone
    # every request reached a terminal state: zero leaks
    s = engine.stats
    assert s["requests_submitted"] == 8
    assert m["completed"] == 8  # completed counts every retirement
    assert s["requests_cancelled"] + s["requests_expired"] >= 1
    assert not engine._active.any()
    assert sorted(engine._free) == list(range(engine.num_slots))
    # metric fields recorded (killed requests excluded from latencies)
    assert m["slot_reclaim_ms"] is None or m["slot_reclaim_ms"] >= 0
    assert 0.0 <= m["deadline_miss_rate"] <= 1.0
    assert 0.0 <= m["shed_rate"] <= 1.0
    # determinism: the kill schedule is a pure function of the seed
    _, engine2 = loadgen.build_engine(num_slots=2, queue_capacity=16)
    try:
        m2 = loadgen.run_closed_loop(engine2, trace, concurrency=4,
                                     timeout_s=120.0, chaos_kill=0.4,
                                     chaos_seed=3)
    finally:
        engine2.stop()
    assert m2["killed"] == m["killed"]


@pytest.mark.slow
def test_continuous_batching_beats_sequential_at_4_concurrent():
    """The acceptance comparison: the engine's closed-loop tokens/sec beats
    sequential per-request generate on the same trace at ≥ 4 concurrent
    requests (4 slots, 8 users)."""
    fitted, engine = loadgen.build_engine(num_slots=4)
    trace = loadgen.make_trace(24, num_steps=16, temperature=0.7)
    try:
        closed = loadgen.run_closed_loop(engine, trace, concurrency=8,
                                         timeout_s=300.0)
    finally:
        engine.stop()
    seq = loadgen.sequential_baseline(fitted, trace, max_len=engine.max_len)
    assert closed["completed"] == 24
    assert closed["tokens_per_sec"] > seq["tokens_per_sec"], (closed, seq)


@pytest.mark.slow
def test_open_loop_qps_sweep_sheds_under_overload():
    """Offered-QPS sweep: a modest rate completes everything; an absurd
    rate against a tiny queue sheds (bounded buffering, not collapse)."""
    _, engine = loadgen.build_engine(num_slots=2, queue_capacity=4)
    trace = loadgen.make_trace(16, num_steps=8)
    try:
        calm = loadgen.run_open_loop(engine, trace, qps=2.0,
                                     timeout_s=300.0)
    finally:
        engine.stop()
    assert calm["shed"] == 0 and calm["completed"] == 16
    _, engine = loadgen.build_engine(num_slots=2, queue_capacity=4)
    # saturate admission before the engine thread can drain: floods the
    # bounded queue at effectively infinite rate
    trace = loadgen.make_trace(64, num_steps=8)
    try:
        flood = loadgen.run_open_loop(engine, trace, qps=1e6,
                                      timeout_s=300.0)
    finally:
        engine.stop()
    assert flood["shed"] > 0
    assert flood["completed"] == 64 - flood["shed"]  # shed, never lost
