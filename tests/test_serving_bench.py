"""Load-generator + serving-bench surface (examples/loadgen.py, bench.py).

The fast variants here are tier-1: a small fixed trace through the closed
loop must complete losslessly with sane metrics, and the trace itself must
be a pure function of its seed.  The full-size comparison — continuous
batching beating sequential per-request ``generate`` at ≥ 4 concurrent
requests — and the offered-QPS sweep are ``slow`` (they time real decode
work).
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "examples"))
import loadgen  # noqa: E402


def test_trace_is_deterministic():
    a = loadgen.make_trace(12, seed=3, temperature=0.7)
    b = loadgen.make_trace(12, seed=3, temperature=0.7)
    assert len(a) == len(b) == 12
    for ra, rb in zip(a, b):
        assert ra.keys() == rb.keys()
        np.testing.assert_array_equal(ra["prompt"], rb["prompt"])
        assert ra["seed"] == rb["seed"]
        assert ra.get("temperature") == rb.get("temperature")
    c = loadgen.make_trace(12, seed=4, temperature=0.7)
    assert any((len(ra["prompt"]) != len(rc["prompt"]))
               or (ra["prompt"] != rc["prompt"][:len(ra["prompt"])]).any()
               for ra, rc in zip(a, c))


def test_closed_loop_fast_trace_lossless():
    """Tier-1 deterministic variant: every traced request completes, zero
    shed, tokens accounted exactly, occupancy recorded."""
    _, engine = loadgen.build_engine(num_slots=2, queue_capacity=16)
    trace = loadgen.make_trace(6, num_steps=6, temperature=0.5)
    try:
        m = loadgen.run_closed_loop(engine, trace, concurrency=4,
                                    timeout_s=120.0)
    finally:
        engine.stop()
    assert m["completed"] == 6 and m["shed"] == 0
    assert m["tokens"] == 6 * 6
    assert m["tokens_per_sec"] > 0
    assert m["p50_ms"] is not None and m["p99_ms"] >= m["p50_ms"]
    assert 0.0 < m["slot_occupancy"] <= 1.0
    assert all(n >= 1 for n in engine.stats["slot_requests"])
    # the TTFT observables ride the same run: first token precedes the
    # end of its request, and prompt tokens flowed through the compiled
    # prefill path
    assert m["ttft_p50_ms"] is not None
    assert m["ttft_p50_ms"] <= m["p50_ms"]
    assert m["prefill_tokens_per_sec"] > 0
    assert engine.stats["prefill_batches"] >= 1
    assert engine.stats["prefill_batch_size_mean"] >= 1.0


def test_closed_loop_outputs_match_offline_generate():
    """The loadgen path changes scheduling only: each traced request's
    tokens equal offline generate's for the same seed."""
    import jax

    fitted, engine = loadgen.build_engine(num_slots=2, queue_capacity=16)
    trace = loadgen.make_trace(5, num_steps=5, temperature=0.6)
    handles = [engine.submit(**req) for req in trace]
    try:
        engine.start()
        for h in handles:
            assert h.wait(timeout=120.0)
    finally:
        engine.stop()
    for h, req in zip(handles, trace):
        temp = req.get("temperature", 0.0)
        want = np.asarray(fitted.generate(
            req["prompt"][None], req["num_steps"], temperature=temp,
            rng=jax.random.PRNGKey(req["seed"]) if temp else None,
            max_len=engine.max_len))[0]
        np.testing.assert_array_equal(h.result(), want)


def test_bench_serving_fields_shape():
    """bench.serving_bench returns exactly the serving_* field set (None
    allowed — the artifact contract) without touching the north star."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench
    out = bench.serving_bench(budget_s=0.0)  # force the overrun path
    assert set(out) == {"serving_tokens_per_sec", "serving_p50_ms",
                        "serving_p99_ms", "serving_slot_occupancy",
                        "serving_sequential_tokens_per_sec",
                        "serving_shed_rate", "serving_slot_reclaim_ms",
                        "serving_deadline_miss_rate",
                        "serving_ttft_p50_ms", "serving_ttft_p99_ms",
                        "serving_prefill_tokens_per_sec",
                        "serving_longprompt_ttft_p99_ms",
                        "serving_longprompt_ttft_eager_p99_ms",
                        "serving_spec_tokens_per_sec",
                        "serving_spec_accept_rate",
                        "serving_quant_capacity_slots",
                        "serving_prefix_ttft_p99_ms",
                        "serving_prefix_ttft_dense_p99_ms",
                        "serving_prefix_hit_rate",
                        "serving_prefix_prefill_tokens_per_sec",
                        "serving_prefix_prefill_dense_tokens_per_sec",
                        "serving_paged_capacity_slots",
                        "serving_unified_decode_p99_ms",
                        "serving_disagg_decode_p99_ms",
                        "serving_kv_transfer_bytes",
                        "serving_interactive_p99_ms_under_overload",
                        "serving_batch_completion_rate",
                        "serving_preempt_resume_ms"}


def test_closed_loop_chaos_kill_schedule_no_leaks():
    """The --chaos client-kill schedule: seeded kills cancel mid-run, the
    engine reclaims every slot (zero leaks), survivors complete, and the
    new failure-semantics metrics are recorded."""
    # 24-step requests: the fast-path engine streams short requests so
    # quickly that a killer waiting for its seeded token count could lose
    # the race and cancel an already-finished request (a no-op) — the
    # longer run keeps every seeded kill landing mid-run
    _, engine = loadgen.build_engine(num_slots=2, queue_capacity=16)
    trace = loadgen.make_trace(8, num_steps=24, temperature=0.5)
    try:
        m = loadgen.run_closed_loop(engine, trace, concurrency=4,
                                    timeout_s=120.0, chaos_kill=0.4,
                                    chaos_seed=3)
    finally:
        engine.stop()
    assert m["killed"] > 0  # the seeded schedule really killed someone
    # every request reached a terminal state: zero leaks
    s = engine.stats
    assert s["requests_submitted"] == 8
    assert m["completed"] == 8  # completed counts every retirement
    assert s["requests_cancelled"] + s["requests_expired"] >= 1
    assert not engine._active.any()
    assert sorted(engine._free) == list(range(engine.num_slots))
    # metric fields recorded (killed requests excluded from latencies)
    assert m["slot_reclaim_ms"] is None or m["slot_reclaim_ms"] >= 0
    assert 0.0 <= m["deadline_miss_rate"] <= 1.0
    assert 0.0 <= m["shed_rate"] <= 1.0
    # determinism: the kill schedule is a pure function of the seed
    _, engine2 = loadgen.build_engine(num_slots=2, queue_capacity=16)
    try:
        m2 = loadgen.run_closed_loop(engine2, trace, concurrency=4,
                                     timeout_s=120.0, chaos_kill=0.4,
                                     chaos_seed=3)
    finally:
        engine2.stop()
    assert m2["killed"] == m["killed"]


@pytest.mark.slow
def test_continuous_batching_beats_sequential_at_4_concurrent():
    """The acceptance comparison: the engine's closed-loop tokens/sec beats
    sequential per-request generate on the same trace at ≥ 4 concurrent
    requests (4 slots, 8 users)."""
    fitted, engine = loadgen.build_engine(num_slots=4)
    trace = loadgen.make_trace(24, num_steps=16, temperature=0.7)
    try:
        closed = loadgen.run_closed_loop(engine, trace, concurrency=8,
                                         timeout_s=300.0)
    finally:
        engine.stop()
    seq = loadgen.sequential_baseline(fitted, trace, max_len=engine.max_len)
    assert closed["completed"] == 24
    assert closed["tokens_per_sec"] > seq["tokens_per_sec"], (closed, seq)


@pytest.mark.slow
def test_open_loop_qps_sweep_sheds_under_overload():
    """Offered-QPS sweep: a modest rate completes everything; an absurd
    rate against a tiny queue sheds (bounded buffering, not collapse)."""
    _, engine = loadgen.build_engine(num_slots=2, queue_capacity=4)
    trace = loadgen.make_trace(16, num_steps=8)
    try:
        calm = loadgen.run_open_loop(engine, trace, qps=2.0,
                                     timeout_s=300.0)
    finally:
        engine.stop()
    assert calm["shed"] == 0 and calm["completed"] == 16
    _, engine = loadgen.build_engine(num_slots=2, queue_capacity=4)
    # saturate admission before the engine thread can drain: floods the
    # bounded queue at effectively infinite rate
    trace = loadgen.make_trace(64, num_steps=8)
    try:
        flood = loadgen.run_open_loop(engine, trace, qps=1e6,
                                      timeout_s=300.0)
    finally:
        engine.stop()
    assert flood["shed"] > 0
    assert flood["completed"] == 64 - flood["shed"]  # shed, never lost


# ---------------------------------------------------------------------------
# paged loadgen (PR 12): the fast leg is tier-1 (seeded trace, no sleeps);
# the timing comparison is slow
# ---------------------------------------------------------------------------

@pytest.mark.paged
def test_paged_loadgen_shared_prefix_fast_leg():
    """Tier-1 deterministic paged leg: a shared-prefix trace through a
    paged engine completes losslessly, records prefix hits with
    byte-accounted block reuse, and the trace generator is a pure
    function of its seed."""
    a = loadgen.make_trace(8, seed=3, prefix_groups=2, prefix_len=8)
    b = loadgen.make_trace(8, seed=3, prefix_groups=2, prefix_len=8)
    for ra, rb in zip(a, b):
        np.testing.assert_array_equal(ra["prompt"], rb["prompt"])
    # round-robin groups: requests 0 and 2 share a prefix, 0 and 1 don't
    np.testing.assert_array_equal(a[0]["prompt"][:8], a[2]["prompt"][:8])
    assert (a[0]["prompt"][:8] != a[1]["prompt"][:8]).any()
    _, engine = loadgen.build_engine(num_slots=2, max_len=32, paged=True,
                                     block_size=4, queue_capacity=16)
    trace = loadgen.make_trace(6, num_steps=6, temperature=0.5,
                               prefix_groups=1, prefix_len=8)
    try:
        m = loadgen.run_closed_loop(engine, trace, concurrency=4,
                                    timeout_s=120.0)
    finally:
        engine.stop()
    assert m["completed"] == 6 and m["shed"] == 0
    assert m["prefix_hits"] >= 1
    assert m["prefix_hit_tokens"] >= 8
    assert m["prefix_hit_rate"] > 0
    assert m["blocks_reused"] >= 1
    assert m["kv_pool_bytes"] == engine.kv_pool_bytes
    assert engine.kv_blocks_in_use == 0


# ---------------------------------------------------------------------------
# fleet routing (PR 17): the fast legs are tier-1 (seeded trace, bounded
# waits); the scaling timing comparison is slow
# ---------------------------------------------------------------------------

@pytest.mark.router
def test_fleet_bench_fields_shape():
    """bench.serving_fleet_bench returns exactly the serving_fleet_*
    field set (None allowed — the artifact contract)."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench
    out = bench.serving_fleet_bench(budget_s=0.0)  # force the overrun path
    assert set(out) == {"serving_fleet_tokens_per_sec",
                        "serving_fleet_prefix_hit_rate",
                        "serving_fleet_failover_lost_requests"}
    assert all(v is None for v in out.values())


# ---------------------------------------------------------------------------
# wire transport scaling (PR 19): the fast legs are tier-1 (small trace over
# loopback, bounded waits); the 64-client scaling comparison is slow
# ---------------------------------------------------------------------------

def test_wire_bench_fields_shape():
    """bench.serving_wire_bench returns exactly the transport-scaling
    field set (None allowed — the artifact contract)."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench
    out = bench.serving_wire_bench(budget_s=0.0)  # force the overrun path
    assert set(out) == {"serving_event_tokens_per_sec",
                        "serving_connection_scaling"}
    assert all(v is None for v in out.values())


def test_wire_closed_loop_lossless_both_cores():
    """Tier-1 deterministic wire leg: a small trace through a
    ServingServer over loopback completes losslessly on BOTH transport
    cores, and the event core's mid-flight per-connection server thread
    count is ZERO while the threaded core's is positive."""
    from distkeras_tpu.serving import ServingServer

    trace = loadgen.make_trace(6, num_steps=6, temperature=0.5)
    conn_threads = {}
    for core in ("threaded", "event"):
        _, engine = loadgen.build_engine(num_slots=2, queue_capacity=16)
        srv = ServingServer(engine, server_core=core, poll_s=0.01).start()
        try:
            m = loadgen.run_wire_closed_loop(srv.addr, trace,
                                             concurrency=4,
                                             timeout_s=120.0)
        finally:
            srv.stop()
            engine.stop()
        assert m["completed"] == 6, (core, m)
        assert m["tokens"] == 6 * 6
        assert m["tokens_per_sec"] > 0
        assert m["p50_ms"] is not None and m["p99_ms"] >= m["p50_ms"]
        conn_threads[core] = m["server_conn_threads_peak"]
    assert conn_threads["event"] == 0, conn_threads
    assert conn_threads["threaded"] >= 1, conn_threads


@pytest.mark.slow
def test_wire_event_core_holds_throughput_at_64_clients():
    """The PR 19 acceptance comparison: at 64 concurrent wire clients the
    event core's ONE selector thread sustains at least the threaded
    core's tokens/sec (64 relay threads), with zero per-connection
    server threads."""
    from distkeras_tpu.serving import ServingServer

    trace = loadgen.make_trace(96, num_steps=8)
    tps = {}
    for core in ("threaded", "event"):
        _, engine = loadgen.build_engine(num_slots=4, queue_capacity=128)
        srv = ServingServer(engine, server_core=core, poll_s=0.01).start()
        try:
            m = loadgen.run_wire_closed_loop(srv.addr, trace,
                                             concurrency=64,
                                             timeout_s=300.0)
        finally:
            srv.stop()
            engine.stop()
        assert m["completed"] == 96, (core, m)
        tps[core] = m["tokens_per_sec"]
        if core == "event":
            assert m["server_conn_threads_peak"] == 0, m
        else:
            assert m["server_conn_threads_peak"] >= 32, m
    # one loop thread replaces 64 relay threads without losing
    # throughput (10% guard band: both cores are engine-bound here,
    # the margin absorbs scheduler noise on a loaded CI host)
    assert tps["event"] >= tps["threaded"] * 0.9, tps


@pytest.mark.router
def test_closed_loop_router_fleet_lossless():
    """Tier-1 deterministic fleet leg: the closed loop drives a 2-replica
    router exactly like a bare engine (duck-typed submit/cancel/stats),
    every request completes, and the per-replica skew report accounts
    for the whole trace."""
    _, router = loadgen.build_fleet(replicas=2, affinity="least-loaded",
                                    num_slots=2)
    trace = loadgen.make_trace(6, num_steps=6, temperature=0.5)
    try:
        m = loadgen.run_closed_loop(router, trace, concurrency=4,
                                    timeout_s=120.0)
        report = loadgen.fleet_report(router, m)
    finally:
        router.stop()
    assert m["completed"] == 6 and m["shed"] == 0
    assert m["tokens"] == 6 * 6
    assert m["tokens_per_sec"] > 0
    assert report["replicas"] == 2
    assert sum(p["routed"] for p in report["per_replica"]) == 6
    assert report["requests_failed"] == 0
    assert report["routed_skew"] is not None and report["routed_skew"] >= 1


@pytest.mark.router
@pytest.mark.slow
def test_fleet_bench_scaling_and_failover():
    """The full bench leg: the scaling curve records every fleet size,
    affinity routing beats the random control arm on the tenanted trace,
    and the failover count is ZERO — the acceptance bar."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench
    out = bench.serving_fleet_bench(budget_s=300.0)
    scaling = out["serving_fleet_tokens_per_sec"]
    assert scaling and scaling["1"] > 0
    hit = out["serving_fleet_prefix_hit_rate"]
    assert hit["prefix"] is not None and hit["random"] is not None
    assert hit["prefix"] > hit["random"], hit
    assert out["serving_fleet_failover_lost_requests"] == 0


@pytest.mark.paged
@pytest.mark.slow
def test_paged_shared_prefix_ttft_beats_dense_5x():
    """The PR 12 acceptance bar: ≥8 users sharing a ≥128-token prefix see
    ≥5× better TTFT p99 AND effective prefill-tokens/sec through the
    paged pool than through the PR 9 bucketed path (prefix warmed once on
    both sides — steady state), with prefix_hit_tokens byte-accounting
    proving the win is block reuse."""
    # prefill-heavy trace (one continuation token): the measured quantity
    # IS the prefill path — TTFT is the time to that token, and wall time
    # is prefill-dominated so tokens/sec measures cache fill, not decode
    trace = loadgen.make_trace(24, num_steps=1, prompt_lengths=(4, 6, 8),
                               prefix_groups=1, prefix_len=240)
    results = {}
    for paged in (True, False):
        _, eng = loadgen.build_engine(num_slots=8, max_len=256,
                                      paged=paged, block_size=16,
                                      prefill_chunk=16,
                                      prefills_per_step=4)
        try:
            eng.warmup()
            eng.submit(trace[0]["prompt"], 1)
            eng.run_until_idle()          # warm the shared prefix once
            m = loadgen.run_closed_loop(eng, trace, concurrency=8,
                                        timeout_s=300.0)
            eff = (m["prefill_tokens_per_sec"] or 0.0)
            if m["wall_s"]:
                eff += m["prefix_hit_tokens"] / m["wall_s"]
            results[paged] = (m["ttft_p99_ms"], eff, m)
        finally:
            eng.stop()
    ttft_paged, eff_paged, m_paged = results[True]
    ttft_dense, eff_dense, _ = results[False]
    assert m_paged["prefix_hit_tokens"] >= 224 * 23  # every later request
    # hit rate over the ENGINE lifetime includes the one warm prefill
    assert m_paged["prefix_hit_rate"] > 0.85
    assert ttft_dense >= 5 * ttft_paged, (ttft_dense, ttft_paged)
    assert eff_paged >= 5 * eff_dense, (eff_paged, eff_dense)
