"""Native apply kernel (csrc/applykernel.cpp) vs the pure-NumPy reference.

The kernel's contract is BIT-equality: ``axpy_f32`` reproduces numpy's
``dst += scale * src`` (two roundings — the extension compiles with
``-ffp-contract=off`` so no FMA collapses them) and ``scatter_add_f32``
reproduces ``np.add.at``'s sequential array-order accumulation.  Fuzzed
over dense/bf16/int8/SparseDelta apply paths and over BOTH buffer
alignments (numpy-aligned arrays and byte-offset unaligned views).

Mirrors the wirecodec test guard: builds the extension in place when a
toolchain exists, skips gracefully otherwise.  The fallback smoke test is
tier-1 safe — it monkeypatches the native module away and proves the
numpy path serves every apply.
"""

import subprocess
import sys

import numpy as np
import pytest

from distkeras_tpu import applykernel, networking
from distkeras_tpu.networking import SparseDelta
from distkeras_tpu.parameter_servers import (ADAGParameterServer,
                                             DeltaParameterServer,
                                             DynSGDParameterServer,
                                             _scatter_add)


def _ensure_native():
    if applykernel._native is not None:
        return applykernel._native
    r = subprocess.run(
        [sys.executable, "setup.py", "build_ext", "--inplace"],
        cwd=applykernel.__file__.rsplit("/", 2)[0], capture_output=True)
    if r.returncode != 0:
        pytest.skip(f"no native toolchain: {r.stderr[-200:]}")
    import distkeras_tpu._applykernel as native
    applykernel._native = native
    return native


@pytest.fixture()
def native():
    old = applykernel._native
    yield _ensure_native()
    applykernel._native = old


def _unaligned_f32(n, rng=None):
    """A writable float32 array at a 1-byte offset — deliberately
    unaligned (flags.aligned is False), the pooled-view worst case."""
    raw = bytearray(4 * n + 1)
    arr = np.frombuffer(raw, dtype=np.float32, count=n, offset=1)
    if rng is not None:
        arr[:] = rng.standard_normal(n).astype(np.float32)
    return arr


# ---------------------------------------------------------------------------
# primitive bit-equality, fuzzed, both alignments
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("alignment", ["aligned", "unaligned"])
@pytest.mark.parametrize("scale", [1.0, 0.25, 1.0 / 3.0, -2.7183, 0.0])
def test_axpy_bit_equal_fuzz(native, alignment, scale):
    rng = np.random.default_rng(hash((alignment, scale)) % (2 ** 31))
    for n in (0, 1, 7, 128, 1023):
        if alignment == "aligned":
            dst_n = rng.standard_normal(n).astype(np.float32)
            src = rng.standard_normal(n).astype(np.float32)
        else:
            dst_n = _unaligned_f32(n, rng)
            src = _unaligned_f32(n, rng)
        dst_k = dst_n.copy()
        # numpy reference — exactly what ParameterServer._apply_scaled does
        if scale == 1.0:
            dst_n += src
        else:
            dst_n += scale * src
        native.axpy_f32(dst_k, np.ascontiguousarray(src), scale)
        np.testing.assert_array_equal(dst_k, dst_n)


@pytest.mark.parametrize("alignment", ["aligned", "unaligned"])
def test_scatter_add_bit_equal_fuzz(native, alignment):
    rng = np.random.default_rng(5 if alignment == "aligned" else 6)
    for n, k in ((1, 1), (64, 7), (512, 200), (300, 900)):
        if alignment == "aligned":
            dst_n = rng.standard_normal(n).astype(np.float32)
        else:
            dst_n = _unaligned_f32(n, rng)
        dst_k = dst_n.copy()
        # duplicates on purpose: per-coordinate accumulation ORDER is part
        # of the bit-equality contract
        idx = rng.integers(0, n, size=k).astype(np.int64)
        vals = (rng.standard_normal(k)
                * 10.0 ** rng.integers(-6, 6, k)).astype(np.float32)
        np.add.at(dst_n, idx, vals)
        native.scatter_add_f32(dst_k, idx, vals)
        np.testing.assert_array_equal(dst_k, dst_n)


def test_scatter_add_out_of_range_raises(native):
    dst = np.zeros(4, np.float32)
    with pytest.raises(IndexError):
        native.scatter_add_f32(dst, np.array([4], np.int64),
                               np.array([1.0], np.float32))
    with pytest.raises(IndexError):
        native.scatter_add_f32(dst, np.array([-1], np.int64),
                               np.array([1.0], np.float32))


def test_axpy_shape_mismatch_raises(native):
    with pytest.raises(ValueError):
        native.axpy_f32(np.zeros(4, np.float32),
                        np.zeros(5, np.float32), 1.0)


# ---------------------------------------------------------------------------
# the full apply path: dense / bf16 / int8 / SparseDelta, kernel vs numpy
# ---------------------------------------------------------------------------

SHAPES = [(33,), (8, 5), (), (64,)]
TOTAL = sum(int(np.prod(s, dtype=np.int64)) for s in SHAPES)


def _blob():
    return {"model": "{}",
            "weights": [np.zeros(s, np.float32) for s in SHAPES]}


def _wire_msgs(rng):
    """One commit per wire form, decoded exactly as the transport boundary
    decodes them before the apply rule sees the message."""
    import ml_dtypes
    dense = [rng.standard_normal(s).astype(np.float32) * 0.1
             for s in SHAPES]
    bf16 = [d.astype(ml_dtypes.bfloat16) for d in dense]
    scales = [float(np.max(np.abs(d)) / 127.0) or 1.0 for d in dense]
    int8_decoded = [np.asarray(np.clip(np.rint(d / s), -127, 127)
                               .astype(np.int8), np.float32) * s
                    for d, s in zip(dense, scales)]
    k = 17
    idx = np.sort(rng.choice(TOTAL, k, replace=False)).astype(np.int32)
    vals = rng.standard_normal(k).astype(np.float32)
    sp_scale = float(np.max(np.abs(vals)) / 127.0) or 1.0
    sp_int8 = SparseDelta(idx, np.clip(np.rint(vals / sp_scale), -127, 127)
                          .astype(np.int8), TOTAL, sp_scale)
    return [
        {"delta": dense, "clock": 0},
        {"delta": bf16, "clock": 0},
        {"delta": int8_decoded, "clock": 0},
        {"delta": SparseDelta(idx, vals, TOTAL), "clock": 0},
        {"delta": sp_int8.decoded(), "clock": 0},
    ]


@pytest.mark.parametrize("make_ps", [
    lambda kern: DeltaParameterServer(_blob(), apply_kernel=kern),
    lambda kern: ADAGParameterServer(_blob(), 3, apply_kernel=kern),
    lambda kern: DynSGDParameterServer(_blob(), apply_kernel=kern),
], ids=["delta", "adag", "dynsgd"])
def test_apply_path_bit_equal_native_vs_numpy(native, make_ps):
    rng = np.random.default_rng(9)
    msgs = _wire_msgs(rng)
    ps_numpy, ps_native = make_ps(None), make_ps("native")
    for m in msgs:
        ps_numpy.handle_commit(dict(m))
        ps_native.handle_commit(dict(m))
    # sequential applies agree bit for bit...
    for a, b in zip(ps_numpy.center, ps_native.center):
        np.testing.assert_array_equal(a, b)
    # ...and a coalesced drain of the same mixed forms does too
    ps_numpy2, ps_native2 = make_ps(None), make_ps("native")
    ps_numpy2.apply_drain([dict(m) for m in msgs])
    ps_native2.apply_drain([dict(m) for m in msgs])
    for a, b in zip(ps_numpy2.center, ps_native2.center):
        np.testing.assert_array_equal(a, b)


def test_scatter_add_helper_native_matches_numpy(native):
    rng = np.random.default_rng(11)
    center_a = [rng.standard_normal(s).astype(np.float32) for s in SHAPES]
    center_b = [c.copy() for c in center_a]
    idx = np.sort(rng.choice(TOTAL, 29, replace=False)).astype(np.int32)
    vals = rng.standard_normal(29).astype(np.float32)
    sp = SparseDelta(idx, vals, TOTAL)
    _scatter_add(center_a, sp, 0.5, kernel=None)
    _scatter_add(center_b, sp, 0.5, kernel=native)
    for a, b in zip(center_a, center_b):
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# fallback + resolution (tier-1 safe: no native module required)
# ---------------------------------------------------------------------------

def test_python_fallback_serves_applies_when_native_absent(monkeypatch):
    """The satellite's smoke test: with the native module monkeypatched
    away, 'auto' resolves to the numpy path and the apply still works —
    the fallback can't rot unexercised on machines where the extension is
    always importable."""
    monkeypatch.setattr(applykernel, "_native", None)
    assert applykernel.resolve("auto") is None
    assert applykernel.resolve(None) is None
    assert applykernel.resolve("numpy") is None
    with pytest.raises(RuntimeError, match="not.*built|build_ext"):
        applykernel.resolve("native")
    ps = DeltaParameterServer(_blob(), apply_kernel="auto")
    assert ps._kernel is None  # the numpy path is live
    d = [np.full(s, 2.0, np.float32) for s in SHAPES]
    ps.handle_commit({"delta": d, "clock": 0})
    idx = np.array([0, 1], np.int32)
    ps.handle_commit({"delta": SparseDelta(idx, np.ones(2, np.float32),
                                           TOTAL), "clock": 0})
    assert ps.num_updates == 2
    np.testing.assert_array_equal(ps.center[0][:2], np.full(2, 3.0))
    np.testing.assert_array_equal(ps.center[0][2:], np.full(31, 2.0))


def test_resolve_rejects_unknown_names():
    with pytest.raises(ValueError, match="apply_kernel"):
        applykernel.resolve("cuda")
