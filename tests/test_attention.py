"""Attention stack: dot-product op, MHA/Transformer layers, causal LM
training, and ring attention (sequence parallelism) vs full attention on the
8-device virtual mesh.  No reference counterpart (SURVEY.md §2.3) — this
covers the framework's long-context layer.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu import Sequential, Dataset, SingleTrainer
from distkeras_tpu.core.layers import (MultiHeadAttention, TransformerBlock,
                                       LayerNormalization,
                                       PositionalEmbedding)
from distkeras_tpu.models.zoo import transformer_lm
from distkeras_tpu.ops.attention import dot_product_attention
from distkeras_tpu.parallel.ring import ring_self_attention
from distkeras_tpu.parallel import get_mesh


def rand_qkv(rng, b=2, s=32, h=4, d=8, dtype=jnp.float32):
    ks = jax.random.split(rng, 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


def naive_attention(q, k, v, causal=False):
    d = q.shape[-1]
    scores = np.einsum("bqhd,bkhd->bhqk", np.asarray(q, np.float64),
                       np.asarray(k, np.float64)) / np.sqrt(d)
    if causal:
        s = scores.shape[-1]
        scores = np.where(np.triu(np.ones((s, s), bool), 1)[None, None],
                          -np.inf, scores)
    scores -= scores.max(-1, keepdims=True)
    p = np.exp(scores)
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", p, np.asarray(v, np.float64))


@pytest.mark.parametrize("causal", [False, True])
def test_dot_product_attention_matches_naive(causal):
    q, k, v = rand_qkv(jax.random.PRNGKey(0))
    out = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), naive_attention(q, k, v,
                                                                causal),
                               atol=1e-5)


def test_causal_masks_future():
    """Changing future tokens must not change past outputs."""
    q, k, v = rand_qkv(jax.random.PRNGKey(1), s=16)
    out1 = dot_product_attention(q, k, v, causal=True)
    k2 = k.at[:, 10:].set(99.0)
    v2 = v.at[:, 10:].set(-99.0)
    out2 = dot_product_attention(q, k2, v2, causal=True)
    np.testing.assert_allclose(np.asarray(out1[:, :10]),
                               np.asarray(out2[:, :10]), atol=1e-5)
    assert not np.allclose(out1[:, 10:], out2[:, 10:])


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(eight_devices, causal):
    """Sequence sharded over 8 devices; ring result == full attention."""
    mesh = get_mesh(8, axis_name="seq")
    q, k, v = rand_qkv(jax.random.PRNGKey(2), b=2, s=64, h=2, d=16)
    out = ring_self_attention(q, k, v, mesh, axis_name="seq", causal=causal)
    want = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=1e-5)


@pytest.mark.parametrize("kv_heads", [1, 2])
def test_ring_attention_gqa_matches_full(eight_devices, kv_heads):
    """GQA through the ring (k/v rotate at Hkv heads) == full-array GQA
    attention, forward and q/k-gradients."""
    mesh = get_mesh(8, axis_name="seq")
    ks = jax.random.split(jax.random.PRNGKey(11), 3)
    q = jax.random.normal(ks[0], (2, 64, 4, 16))
    k = jax.random.normal(ks[1], (2, 64, kv_heads, 16))
    v = jax.random.normal(ks[2], (2, 64, kv_heads, 16))
    out = ring_self_attention(q, k, v, mesh, axis_name="seq", causal=True)
    want = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=1e-5)

    g_ring = jax.grad(lambda k_: ring_self_attention(
        q, k_, v, mesh, axis_name="seq", causal=True).sum())(k)
    g_full = jax.grad(lambda k_: dot_product_attention(
        q, k_, v, causal=True).sum())(k)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_full),
                               atol=1e-4)


def test_ring_attention_window_matches_full(eight_devices):
    """Sliding window through the ring (global-position masking across
    rotating blocks) == windowed full attention, incl. blockwise."""
    mesh = get_mesh(8, axis_name="seq")
    q, k, v = rand_qkv(jax.random.PRNGKey(13), b=2, s=64, h=2, d=16)
    for block_k in (None, 4):
        out = ring_self_attention(q, k, v, mesh, axis_name="seq",
                                  causal=True, block_k=block_k, window=12)
        want = dot_product_attention(q, k, v, causal=True, window=12)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   atol=1e-5)
    with pytest.raises(ValueError, match="causal"):
        ring_self_attention(q, k, v, mesh, axis_name="seq", causal=False,
                            window=12)


def test_ring_attention_grads_match(eight_devices):
    """d(sum(out))/dq through the ring collective == through full attention."""
    mesh = get_mesh(8, axis_name="seq")
    q, k, v = rand_qkv(jax.random.PRNGKey(3), b=1, s=32, h=2, d=8)

    g_ring = jax.grad(lambda q_: ring_self_attention(
        q_, k, v, mesh, axis_name="seq", causal=True).sum())(q)
    g_full = jax.grad(lambda q_: dot_product_attention(
        q_, k, v, causal=True).sum())(q)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_full),
                               atol=1e-4)


def test_mha_layer_shapes_and_serialization():
    layer = MultiHeadAttention(num_heads=4, key_dim=8, causal=True)
    params, out_shape = layer.init(jax.random.PRNGKey(0), (16, 32))
    assert out_shape == (16, 32)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 32))
    y = layer.apply(params, x, compute_dtype=jnp.float32)
    assert y.shape == (2, 16, 32)

    model = Sequential([TransformerBlock(2, 8, 32), LayerNormalization()],
                       input_shape=(16, 32), compute_dtype="float32")
    p = model.init(jax.random.PRNGKey(0))
    clone = Sequential.from_json(model.to_json())
    p2 = clone.init(jax.random.PRNGKey(0))
    np.testing.assert_allclose(
        np.asarray(model.apply(p, x)), np.asarray(clone.apply(p2, x)),
        atol=1e-6)


@pytest.mark.parametrize("kv_heads", [1, 2])
def test_gqa_matches_repeated_kv_mha(kv_heads):
    """GQA == classic MHA with the kv heads explicitly repeated per group
    (exact: same f32 arithmetic, just grouped einsums)."""
    rng = jax.random.PRNGKey(7)
    ks = jax.random.split(rng, 3)
    b, s, h, d = 2, 16, 4, 8
    q = jax.random.normal(ks[0], (b, s, h, d))
    k = jax.random.normal(ks[1], (b, s, kv_heads, d))
    v = jax.random.normal(ks[2], (b, s, kv_heads, d))
    for causal in (False, True):
        got = dot_product_attention(q, k, v, causal=causal)
        want = dot_product_attention(q, jnp.repeat(k, h // kv_heads, axis=2),
                                     jnp.repeat(v, h // kv_heads, axis=2),
                                     causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-6)
    # gradients flow to the shared kv heads
    g = jax.grad(lambda k_: dot_product_attention(
        q, k_, v, causal=True).sum())(k)
    assert g.shape == k.shape and float(jnp.abs(g).sum()) > 0


def test_sliding_window_attention():
    """window semantics: query p sees (p-window, p]; window >= S == full
    causal; window=1 == attend only self (output = v row)."""
    q, k, v = rand_qkv(jax.random.PRNGKey(9), s=16)
    full = dot_product_attention(q, k, v, causal=True)
    same = dot_product_attention(q, k, v, causal=True, window=16)
    np.testing.assert_allclose(np.asarray(same), np.asarray(full), atol=0)

    only_self = dot_product_attention(q, k, v, causal=True, window=1)
    np.testing.assert_allclose(np.asarray(only_self), np.asarray(v),
                               atol=1e-5)

    # window=4: output at p must ignore keys at positions <= p-4
    w4 = dot_product_attention(q, k, v, causal=True, window=4)
    k2 = k.at[:, :8].set(77.0)
    v2 = v.at[:, :8].set(-77.0)
    w4b = dot_product_attention(q, k2, v2, causal=True, window=4)
    np.testing.assert_allclose(np.asarray(w4[:, 11:]),
                               np.asarray(w4b[:, 11:]), atol=1e-5)
    assert not np.allclose(w4[:, :8], w4b[:, :8])

    # naive masked-softmax oracle
    d = q.shape[-1]
    scores = np.einsum("bqhd,bkhd->bhqk", np.asarray(q, np.float64),
                       np.asarray(k, np.float64)) / np.sqrt(d)
    pos = np.arange(16)
    hide = (pos[None, :] > pos[:, None]) | (pos[None, :] <= pos[:, None] - 4)
    scores = np.where(hide[None, None], -np.inf, scores)
    p = np.exp(scores - scores.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    want = np.einsum("bhqk,bkhd->bqhd", p, np.asarray(v, np.float64))
    np.testing.assert_allclose(np.asarray(w4), want, atol=1e-5)

    with pytest.raises(ValueError, match="causal"):
        dot_product_attention(q, k, v, window=4)
    with pytest.raises(ValueError, match="window"):
        dot_product_attention(q, k, v, causal=True, window=0)
    with pytest.raises(ValueError, match="causal"):
        MultiHeadAttention(num_heads=4, key_dim=8, attention_window=4)
    with pytest.raises(ValueError, match="causal"):
        TransformerBlock(4, 8, 64, attention_window=4)  # eager, not at init
    # window covering every key is normalized away (keeps flash eligible)
    from distkeras_tpu.ops.attention import attention
    w_all = attention(q, k, v, causal=True, window=999, impl="xla")
    np.testing.assert_allclose(np.asarray(w_all), np.asarray(full), atol=0)


def test_sliding_window_decode_matches_forward():
    """KV-cache decode of a windowed LM (window=4) matches its full
    forward stepwise (training coverage: the windowed-grad parity cases in
    tests/test_flash_attention.py and the e2e windowed-LM ADAG run in the
    verify workflow)."""
    from distkeras_tpu.core.decode import decode_step, init_cache
    model = transformer_lm(vocab_size=16, seq_len=12, d_model=32,
                           num_heads=4, num_layers=1, mlp_dim=64,
                           compute_dtype="float32", attention_window=4)
    params = model.init(jax.random.PRNGKey(0))
    toks = np.random.default_rng(1).integers(0, 16, (2, 12)).astype(np.int32)
    full = np.asarray(model.apply(params, toks), np.float32)
    caches = init_cache(model, batch=2, max_len=12)
    step = jax.jit(lambda c, t, p: decode_step(model, params, c, t, p))
    for pos in range(12):
        logits, caches = step(caches, toks[:, pos], pos)
        np.testing.assert_allclose(np.asarray(logits), full[:, pos],
                                   rtol=2e-5, atol=2e-5)


def test_gqa_head_mismatch_rejected():
    q, k, v = rand_qkv(jax.random.PRNGKey(8), h=4)
    with pytest.raises(ValueError, match="divisible"):
        dot_product_attention(q, k[:, :, :3], v[:, :, :3])
    with pytest.raises(ValueError, match="divisible"):
        MultiHeadAttention(num_heads=4, key_dim=8, num_kv_heads=3)


def test_gqa_layer_params_and_serialization():
    """num_kv_heads shrinks wk/wv; spec round-trips; pre-GQA configs (no
    num_kv_heads key) deserialize as classic MHA."""
    layer = MultiHeadAttention(num_heads=4, key_dim=8, num_kv_heads=2)
    params, _ = layer.init(jax.random.PRNGKey(0), (16, 32))
    assert params["wq"].shape == (32, 32)
    assert params["wk"].shape == (32, 16)  # 2 kv heads * key_dim 8
    assert params["bv"].shape == (16,)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 32))
    assert layer.apply(params, x, compute_dtype=jnp.float32).shape == \
        (2, 16, 32)

    model = Sequential(
        [TransformerBlock(4, 8, 32, num_kv_heads=2), LayerNormalization()],
        input_shape=(16, 32), compute_dtype="float32")
    p = model.init(jax.random.PRNGKey(0))
    clone = Sequential.from_json(model.to_json())
    p2 = clone.init(jax.random.PRNGKey(0))
    np.testing.assert_allclose(
        np.asarray(model.apply(p, x)), np.asarray(clone.apply(p2, x)),
        atol=1e-6)

    # legacy config without the field -> classic MHA
    from distkeras_tpu.core.layers import Layer
    cfg = MultiHeadAttention(num_heads=4, key_dim=8).get_config()
    cfg.pop("num_kv_heads", None)
    legacy = Layer.from_config(cfg)
    lp, _ = legacy.init(jax.random.PRNGKey(0), (16, 32))
    assert lp["wk"].shape == (32, 32)


def test_gqa_transformer_lm_trains():
    """A GQA (2 kv heads / 4 q heads) tiny LM learns next-token like the
    full-MHA one (same harness as test_transformer_lm_trains)."""
    model = transformer_lm(vocab_size=16, seq_len=12, d_model=32,
                           num_heads=4, num_layers=1, mlp_dim=64,
                           compute_dtype="float32", num_kv_heads=2)
    rng = np.random.default_rng(0)
    x = rng.integers(0, 16, (256, 12)).astype(np.int32)
    y = (x + 1) % 16
    ds = Dataset({"features": x, "label": y})
    tr = SingleTrainer(model, batch_size=32, num_epoch=30,
                       loss="sparse_categorical_crossentropy_from_logits",
                       worker_optimizer="adam", learning_rate=3e-3)
    fitted = tr.train(ds)
    logits = fitted.predict(x[:64])
    acc = (np.argmax(logits, -1) == y[:64]).mean()
    assert acc > 0.9, acc


def test_transformer_lm_trains():
    """Tiny causal LM learns a deterministic next-token rule (y = x+1 mod V)
    via SingleTrainer — the long-context model family rides the standard
    trainer API unchanged."""
    vocab, seq = 16, 12
    rng = np.random.default_rng(0)
    x = rng.integers(0, vocab, (512, seq)).astype(np.int32)
    y = (x + 1) % vocab
    ds = Dataset({"features": x, "label": y.astype(np.int64)})
    model = transformer_lm(vocab_size=vocab, seq_len=seq, d_model=32,
                           num_heads=2, num_layers=1, mlp_dim=64,
                           compute_dtype="float32")
    t = SingleTrainer(model, batch_size=32, num_epoch=10,
                      loss="sparse_categorical_crossentropy_from_logits",
                      worker_optimizer="adam", learning_rate=3e-3)
    fitted = t.train(ds)
    assert t.get_history()[-1] < 0.3 * t.get_history()[0]
    logits = fitted.predict(x[:32])
    acc = float(np.mean(np.argmax(logits, -1) == y[:32]))
    assert acc > 0.9, acc


def test_positional_embedding_bounds():
    layer = PositionalEmbedding(max_len=8)
    with pytest.raises(ValueError, match="exceeds max_len"):
        layer.init(jax.random.PRNGKey(0), (16, 4))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_blockwise_matches(eight_devices, causal):
    """block_k chunking (long-context memory knob): identical result and
    gradients to the unchunked ring, which itself matches full attention."""
    mesh = get_mesh(8, axis_name="seq")
    q, k, v = rand_qkv(jax.random.PRNGKey(5), b=2, s=64, h=2, d=16)
    # S_local = 8, chunk at 4 -> 2 chunks per rotation
    out = ring_self_attention(q, k, v, mesh, axis_name="seq", causal=causal,
                              block_k=4)
    want = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=1e-5)

    # gradients w.r.t. q, k AND v: the chunked path differentiates through
    # the scan's dynamic_slice transpose, which the unchunked path never
    # exercises
    g_blk = jax.grad(lambda qkv: ring_self_attention(
        *qkv, mesh, axis_name="seq", causal=causal, block_k=4).sum())(
        (q, k, v))
    g_full = jax.grad(lambda qkv: dot_product_attention(
        *qkv, causal=causal).sum())((q, k, v))
    for name, a, b in zip("qkv", g_blk, g_full):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4,
                                   err_msg=f"d{name}")
