"""Serving-path fault tolerance (PR 8): deadlines, cancellation/disconnect
reclamation, graceful drain, the supervised engine, and the serving chaos
matrix.

The contract pinned here is the serving twin of the host-PS robustness
stack (PRs 3/5):

 - a request can always be *retired early* — deadline expiry, explicit
   cancel (wire ``'x'`` or in-process), or client disconnect — and its KV
   slot returns to the pool within one scheduler iteration, with the
   retire reason (``finish``) carried to the client on the final stream
   frame;
 - no handle ever blocks forever: a crashed or wedged decode loop fails
   every in-flight handle with a typed ``EngineDead`` (inline and
   background modes, ``stop(join_timeout)`` leaks included), and the wire
   server bounds its stream waits by the request deadline /
   ``stream_timeout_s`` with a typed ``"stall"`` frame;
 - ``drain`` stops admission (``Draining``), finishes in-flight work,
   then stops;
 - ``EngineSupervisor`` detects crash AND wedge (decode-loop heartbeat),
   restarts from the model weights with a fresh slot pool, and
   ``ServingClient.generate(retry_policy=...)`` resubmits idempotently —
   surviving requests stay bit-identical to offline ``generate``;
 - every fault in the chaos matrix {client reset mid-stream, client
   stall, explicit cancel, deadline expiry, engine crash} reclaims the
   affected slot while unaffected concurrent requests produce output
   bit-identical to offline ``generate``.
"""

import socket
import struct
import threading
import time

import numpy as np
import pytest

import jax

from distkeras_tpu import networking
from distkeras_tpu.core.model import FittedModel
from distkeras_tpu.networking import ChaosFault, ChaosProxy
from distkeras_tpu.models import transformer_lm
from distkeras_tpu.resilience import EngineSupervisor, RetryPolicy
from distkeras_tpu.serving import (Draining, EngineDead, QueueFull,
                                   ServingClient, ServingEngine,
                                   ServingServer)

VOCAB = 17
PROMPT = np.array([3, 4, 5, 6], np.int32)
OTHER = np.array([7, 8, 9], np.int32)


def _fitted(seed=0, **kw):
    model = transformer_lm(vocab_size=VOCAB, seq_len=32, d_model=16,
                           num_heads=2, num_layers=2, mlp_dim=32,
                           compute_dtype="float32", **kw)
    params = model.init(jax.random.PRNGKey(seed), (32,))
    return FittedModel(model, params)


@pytest.fixture(scope="module")
def fitted():
    return _fitted()


def _want(fitted, prompt, steps, **kw):
    seed = kw.pop("seed", None)
    if seed is not None:
        kw["rng"] = jax.random.PRNGKey(seed)
    return np.asarray(fitted.generate(prompt[None], steps, max_len=24,
                                      **kw))[0]


def _hard_close(sock):
    """RST (SO_LINGER=0) — the signature of a killed client process."""
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                    struct.pack("ii", 1, 0))
    sock.close()


def _wedge(engine):
    """Monkey-wedge an engine's decode step on an Event (released by the
    returned callable — always call it in teardown)."""
    ev = threading.Event()
    engine._decode_once = lambda: ev.wait(120.0)
    return ev.set


def _wait_for(pred, timeout=10.0, interval=0.005):
    t0 = time.monotonic()
    while not pred():
        if time.monotonic() - t0 > timeout:
            return False
        time.sleep(interval)
    return True


def _assert_slots_reclaimed(engine):
    assert not engine._active.any()
    assert sorted(engine._free) == list(range(engine.num_slots))
    assert all(h is None for h in engine._handles)


# ---------------------------------------------------------------------------
# per-request deadlines
# ---------------------------------------------------------------------------

def test_deadline_queued_shed_before_prefill(fitted):
    """A queued request whose deadline expires is retired WITHOUT ever
    taking a slot; the running request is untouched."""
    eng = ServingEngine(fitted, num_slots=1, max_len=24)
    running = eng.submit(PROMPT, 12)
    queued = eng.submit(OTHER, 5, deadline_s=0.01)
    eng.step()          # prefills `running` only
    time.sleep(0.03)    # the queued deadline passes
    eng.run_until_idle()
    assert queued.finish == "deadline"
    assert queued.slot is None and queued.started_at is None
    assert running.finish == "length"
    np.testing.assert_array_equal(running.result(),
                                  _want(fitted, PROMPT, 12))
    # the shed request still returns a generate-shaped (all-pad) row
    row = queued.result()
    assert row.shape == (len(OTHER) + 5,)
    np.testing.assert_array_equal(row[:len(OTHER)], OTHER)
    assert eng.stats["requests_expired"] == 1
    _assert_slots_reclaimed(eng)


def test_deadline_expires_mid_run_frees_slot(fitted):
    """A running request past its deadline is retired mid-run — partial
    tokens kept, slot freed immediately — while a concurrent request
    stays bit-identical to offline generate."""
    eng = ServingEngine(fitted, num_slots=2, max_len=24)
    doomed = eng.submit(PROMPT, 16, deadline_s=0.05)
    healthy = eng.submit(OTHER, 10, temperature=0.6, seed=5)
    eng.step()
    eng.step()  # both prefilled + decoding
    time.sleep(0.06)
    eng.run_until_idle()
    assert doomed.finish == "deadline"
    assert 1 <= len(doomed.tokens) < 16  # partial, padded by result()
    assert healthy.finish == "length"
    np.testing.assert_array_equal(
        healthy.result(), _want(fitted, OTHER, 10, temperature=0.6, seed=5))
    assert eng.stats["requests_expired"] == 1
    assert len(eng.stats["slot_reclaim_ms"]) == 1
    _assert_slots_reclaimed(eng)


LONG_PROMPT = (np.arange(1, 13, dtype=np.int32) * 3) % VOCAB  # 12 tokens


def test_cancel_mid_chunked_prefill_frees_slot(fitted):
    """PR 9's chunked prefill adds a new retirement window: a slot that is
    claimed but still PREFILLING chunk-by-chunk.  Cancel must free it
    before its first token, and the next occupant is unpolluted."""
    eng = ServingEngine(fitted, num_slots=1, max_len=24, prefill_chunk=4)
    h = eng.submit(LONG_PROMPT, 4)
    eng.step()  # admission + first chunk: claimed, not yet decoding
    assert eng._prefilling and not eng._active.any() and not h.done
    eng.cancel(h)
    eng.step()
    assert h.finish == "cancel" and not h.tokens
    assert eng.stats["requests_cancelled"] == 1
    assert len(eng.stats["slot_reclaim_ms"]) == 1  # it held a KV slot
    _assert_slots_reclaimed(eng)
    h2 = eng.submit(PROMPT, 3)
    eng.run_until_idle()
    np.testing.assert_array_equal(h2.result(), _want(fitted, PROMPT, 3))


def test_deadline_mid_chunked_prefill_frees_slot(fitted):
    eng = ServingEngine(fitted, num_slots=1, max_len=24, prefill_chunk=4)
    h = eng.submit(LONG_PROMPT, 4, deadline_s=0.05)
    eng.step()
    assert eng._prefilling
    time.sleep(0.06)
    eng.run_until_idle()
    assert h.finish == "deadline" and not h.tokens
    assert eng.stats["requests_expired"] == 1
    _assert_slots_reclaimed(eng)


def test_disconnect_mid_chunked_prefill_reclaims(fitted, server_core):
    """A client that dies while its request is mid-chunked-prefill: the
    server's disconnect reclamation cancels it, and the scheduler aborts
    the prefill and frees the slot — no handle or slot leaks."""
    eng = ServingEngine(fitted, num_slots=1, max_len=24, prefill_chunk=4)
    started, release = threading.Event(), threading.Event()
    orig = eng._advance_chunk

    def gated(slot):
        started.set()
        release.wait(10.0)  # hold the prefill mid-flight
        orig(slot)

    eng._advance_chunk = gated
    try:
        with ServingServer(eng) as srv:
            c = ServingClient(*srv.addr)
            c.submit(LONG_PROMPT, 4)
            assert started.wait(10.0)
            _hard_close(c.sock)  # RST while the prefill is gated
            assert _wait_for(lambda: srv.disconnect_cancels >= 1)
            release.set()
            assert _wait_for(lambda: eng.stats["requests_cancelled"] >= 1)
            assert _wait_for(lambda: not eng._prefilling
                             and sorted(eng._free) == [0])
    finally:
        release.set()
    assert all(h is None for h in eng._handles)
    assert not srv._handles and not srv._owner  # no handle-table leaks


def test_engine_wide_default_deadline(fitted):
    eng = ServingEngine(fitted, num_slots=1, max_len=24,
                        default_deadline_s=0.02)
    h = eng.submit(PROMPT, 16)
    assert h.deadline is not None
    time.sleep(0.04)
    eng.run_until_idle()
    assert h.finish == "deadline"
    # an explicit per-request deadline overrides the default
    h2 = eng.submit(PROMPT, 4, deadline_s=30.0)
    eng.run_until_idle()
    assert h2.finish == "length"


def test_deadline_validation(fitted):
    eng = ServingEngine(fitted, num_slots=1, max_len=24)
    with pytest.raises(ValueError, match="deadline_s"):
        eng.submit(PROMPT, 4, deadline_s=0.0)
    with pytest.raises(ValueError, match="default_deadline_s"):
        ServingEngine(fitted, num_slots=1, max_len=24,
                      default_deadline_s=-1.0)


# ---------------------------------------------------------------------------
# cancellation: in-process, wire opcode, disconnect reclamation
# ---------------------------------------------------------------------------

def test_cancel_queued_and_running(fitted):
    eng = ServingEngine(fitted, num_slots=1, max_len=24)
    running = eng.submit(PROMPT, 16)
    queued = eng.submit(OTHER, 8)
    eng.step()  # prefill `running`
    assert eng.cancel(queued)
    eng.step()
    assert queued.finish == "cancel" and queued.slot is None
    assert eng.cancel(running)
    eng.step()  # the reap retires it before any further decode
    assert running.finish == "cancel"
    assert not eng.cancel(running)  # already finished
    assert eng.stats["requests_cancelled"] == 2
    # only the RUNNING cancel samples slot_reclaim_ms — the queued shed
    # never held a slot, so it must not dilute the reclamation metric
    assert len(eng.stats["slot_reclaim_ms"]) == 1
    _assert_slots_reclaimed(eng)


def test_cancel_wire_opcode_and_finish_reason(fitted, server_core):
    with ServingServer(ServingEngine(fitted, num_slots=1, max_len=24),
                       poll_s=0.01) as srv:
        with ServingClient(*srv.addr) as c:
            rid = c.submit(PROMPT, 16)
            assert c.cancel(rid) is True
            chunks, final = [], None
            for tokens, done in c.stream(rid):
                chunks.append(tokens)
                if done is not None:
                    final = done
            assert final["finish"] == "cancel"
            # the padded row is still generate-shaped
            assert final["row"].shape == (len(PROMPT) + 16,)
            assert c.cancel(999) is False  # unknown id: not cancelled
    assert srv.engine.stats["requests_cancelled"] == 1


def test_midstream_cancel_same_socket(fitted, server_core):
    """A cancel sent on the SAME socket mid-stream is consumed between
    chunk frames (unacked); the stream's final frame carries
    finish="cancel"."""
    eng = ServingEngine(fitted, num_slots=1, max_len=24)
    with ServingServer(eng, poll_s=0.01) as srv:
        with ServingClient(*srv.addr) as c:
            rid = c.submit(PROMPT, 16)
            gen = c.stream(rid)
            next(gen)  # stream established, first chunk read
            c.cancel(rid, await_ack=False)  # fire-and-forget mid-stream
            final = None
            for tokens, done in gen:
                if done is not None:
                    final = done
            assert final["finish"] in ("cancel", "length")
    _wait_for(lambda: not eng._active.any())
    _assert_slots_reclaimed(eng)


def test_client_disconnect_mid_stream_reclaims_slot(fitted, server_core):
    """A client that RSTs mid-stream has its request cancelled within one
    poll slice — the slot is back in the pool long before the request
    would have decoded to completion."""
    eng = ServingEngine(fitted, num_slots=2, max_len=24)
    with ServingServer(eng, poll_s=0.01) as srv:
        c = ServingClient(*srv.addr)
        rid = c.submit(PROMPT, 16)
        gen = c.stream(rid)
        next(gen)           # one chunk, then the client dies
        _hard_close(c.sock)
        assert _wait_for(lambda: eng.stats["requests_cancelled"] >= 1)
        assert _wait_for(lambda: not eng._active.any())
        assert srv.disconnect_cancels >= 1
        assert _wait_for(lambda: srv.live_connections == 0)
        # the engine keeps serving: a fresh client is bit-identical
        with ServingClient(*srv.addr) as c2:
            np.testing.assert_array_equal(c2.generate(OTHER, 10),
                                          _want(fitted, OTHER, 10))
        _assert_slots_reclaimed(eng)
        with srv._hlock:  # no handle-table leak for the abandoned id
            assert rid not in srv._handles and rid not in srv._owner


def test_submit_then_die_reclaims_ownership(fitted, server_core):
    """A connection that submitted (but never streamed) and died has its
    owned request cancelled — a dead client pins neither slot nor handle
    entry."""
    eng = ServingEngine(fitted, num_slots=2, max_len=24)
    with ServingServer(eng, poll_s=0.01) as srv:
        doomed = ServingClient(*srv.addr)
        doomed.submit(PROMPT, 16)
        with ServingClient(*srv.addr) as healthy:
            rid = healthy.submit(OTHER, 10, temperature=0.6, seed=5)
            _hard_close(doomed.sock)
            row = None
            for tokens, done in healthy.stream(rid):
                if done is not None:
                    row = done["row"]
            np.testing.assert_array_equal(
                row, _want(fitted, OTHER, 10, temperature=0.6, seed=5))
        assert _wait_for(lambda: eng.stats["requests_cancelled"] >= 1)
        assert _wait_for(lambda: not eng._active.any())
        _assert_slots_reclaimed(eng)
        with srv._hlock:
            assert not srv._handles and not srv._owner


@pytest.mark.parametrize("codec", ["python", "native"])
def test_half_frame_disconnect_sheds_connection(fitted, codec, monkeypatch,
                                                server_core):
    """Half a serving request frame then RST (both codecs): the handler
    sheds the connection silently — live bookkeeping decrements, pooled
    buffers go with the handler — and the engine keeps serving."""
    if codec == "python":
        monkeypatch.setattr(networking, "_native", None)
    elif networking._native is None:
        pytest.skip("native codec not built")
    eng = ServingEngine(fitted, num_slots=1, max_len=24)
    with ServingServer(eng) as srv:
        raw = networking.connect(*srv.addr)
        frame = networking.encode_message(
            {"prompt": PROMPT, "num_steps": 8})
        networking.send_opcode(raw, networking.SERVING_OP_ENQUEUE)
        raw.sendall(bytes(frame)[:len(frame) // 2])  # torn mid-frame
        _hard_close(raw)
        assert _wait_for(
            lambda: srv.disconnects + srv.protocol_errors >= 1)
        assert _wait_for(lambda: srv.live_connections == 0)
        # nothing reached the engine; it still serves new clients
        assert eng.stats["requests_submitted"] == 0
        with ServingClient(*srv.addr) as c:
            np.testing.assert_array_equal(c.generate(PROMPT, 6),
                                          _want(fitted, PROMPT, 6))


def test_stalled_engine_sends_typed_error_frame(fitted, server_core):
    """Satellite: the handler's stream wait is bounded (stream_timeout_s /
    request deadline), not a hardcoded minute — a wedged engine yields a
    typed "stall" error frame, and the connection stays usable."""
    eng = ServingEngine(fitted, num_slots=1, max_len=24)
    release = _wedge(eng)
    try:
        with ServingServer(eng, poll_s=0.02, stream_timeout_s=0.3) as srv:
            with ServingClient(*srv.addr) as c:
                rid = c.submit(PROMPT, 8)
                t0 = time.monotonic()
                with pytest.raises(EngineDead, match="stall|progress"):
                    for _ in c.stream(rid):
                        pass
                assert time.monotonic() - t0 < 5.0  # not 60 s
                # same connection still answers (cancel ack round-trip)
                assert c.cancel(rid) in (True, False)
            release()  # unwedge BEFORE the server stops the engine
    finally:
        release()


# ---------------------------------------------------------------------------
# graceful drain
# ---------------------------------------------------------------------------

def test_drain_finishes_inflight_then_stops(fitted, lock_order_audit):
    eng = ServingEngine(fitted, num_slots=1, max_len=24).start()
    h1 = eng.submit(PROMPT, 8)
    h2 = eng.submit(OTHER, 5)  # queued behind h1 on the lone slot
    assert eng.drain(timeout=60.0) is True
    assert h1.finish == "length" and h2.finish == "length"
    np.testing.assert_array_equal(h1.result(), _want(fitted, PROMPT, 8))
    np.testing.assert_array_equal(h2.result(), _want(fitted, OTHER, 5))
    with pytest.raises(Draining):
        eng.submit(PROMPT, 4)
    assert eng._thread is None  # stopped
    _assert_slots_reclaimed(eng)


def test_drain_inline_engine(fitted):
    """An engine never start()ed is driven to idle by drain itself."""
    eng = ServingEngine(fitted, num_slots=1, max_len=24)
    h = eng.submit(PROMPT, 6)
    assert eng.drain(timeout=60.0) is True
    assert h.finish == "length"


def test_drain_over_the_wire_is_typed(fitted, server_core):
    eng = ServingEngine(fitted, num_slots=1, max_len=24)
    with ServingServer(eng) as srv:
        with ServingClient(*srv.addr) as c:
            np.testing.assert_array_equal(c.generate(PROMPT, 4),
                                          _want(fitted, PROMPT, 4))
            assert eng.drain(timeout=60.0) is True
            with pytest.raises(Draining):
                c.submit(PROMPT, 4)


def test_drain_timeout_fails_leftovers_typed(fitted):
    eng = ServingEngine(fitted, num_slots=1, max_len=24)
    h = eng.submit(PROMPT, 8)
    release = _wedge(eng)
    try:
        eng.start()
        _wait_for(lambda: eng._active.any())
        t0 = time.monotonic()
        assert eng.drain(timeout=0.2) is False
        assert time.monotonic() - t0 < 8.0
        assert h.finish == "drain"
        with pytest.raises(EngineDead, match="drain timed out"):
            h.result()
    finally:
        release()


def test_drain_after_backpressure_shed_returns_clean(fitted):
    """Regression: a QueueFull shed must not unbalance drain()'s terminal
    accounting — a rejected request is terminal (requests_rejected), so
    drain after a rejection still finishes the real work and returns True
    instead of timing out and falsely declaring the idle engine dead."""
    eng = ServingEngine(fitted, num_slots=1, max_len=24, queue_capacity=1)
    h1 = eng.submit(PROMPT, 4)
    with pytest.raises(QueueFull):
        eng.submit(OTHER, 4, block=False)
    assert eng.drain(timeout=30.0) is True
    assert h1.finish == "length"
    assert eng.dead is None
    s = eng.stats
    assert (s["requests_submitted"]
            == s["requests_completed"] + s["requests_failed"]
            + s["requests_rejected"])


def test_blocked_submit_raises_typed_on_death(fitted):
    """Regression: a submitter blocked on a full queue is woken by
    _declare_dead (which clears the queue) — it must raise the typed
    EngineDead, not enqueue into an engine no scheduler will ever run
    (a silent result() hang)."""
    eng = ServingEngine(fitted, num_slots=1, max_len=24, queue_capacity=1)
    eng.submit(PROMPT, 8)
    errs = []

    def blocked():
        try:
            eng.submit(OTHER, 4, block=True, timeout=30.0)
        except BaseException as e:  # noqa: BLE001 — recorded for assert
            errs.append(e)

    t = threading.Thread(target=blocked, daemon=True)
    t.start()
    time.sleep(0.1)  # inside the capacity wait
    eng._declare_dead(RuntimeError("chaos: killed while submitter waits"))
    t.join(timeout=5.0)
    assert not t.is_alive()
    assert errs and isinstance(errs[0], EngineDead)
    s = eng.stats
    assert (s["requests_submitted"]
            == s["requests_completed"] + s["requests_failed"]
            + s["requests_rejected"])


def test_blocked_submit_raises_draining_on_drain(fitted):
    """Same contract for drain: admission stopping must reach a submitter
    already blocked on the capacity wait."""
    eng = ServingEngine(fitted, num_slots=1, max_len=24, queue_capacity=1)
    h1 = eng.submit(PROMPT, 4)
    errs = []

    def blocked():
        try:
            eng.submit(OTHER, 4, block=True, timeout=30.0)
        except BaseException as e:  # noqa: BLE001 — recorded for assert
            errs.append(e)

    t = threading.Thread(target=blocked, daemon=True)
    t.start()
    time.sleep(0.1)
    assert eng.drain(timeout=30.0) is True
    t.join(timeout=5.0)
    assert not t.is_alive()
    assert errs and isinstance(errs[0], Draining)
    assert h1.finish == "length"


def test_pipelined_enqueue_mid_stream_keeps_connection(fitted, server_core):
    """Regression: a client that pipelines its next 'q' on the same socket
    while a stream is still relaying is NOT a dead client — the server
    stashes the opcode, finishes the stream, then processes the enqueue,
    instead of tearing down the connection and cancelling its work."""
    eng = ServingEngine(fitted, num_slots=1, max_len=24)
    with ServingServer(eng, poll_s=0.01) as srv:
        with ServingClient(*srv.addr) as c:
            rid = c.submit(PROMPT, 8)
            networking.send_opcode(c.sock, networking.SERVING_OP_STREAM)
            networking.send_data(c.sock, {"id": rid})
            # pipeline the next request before reading any stream frame
            networking.send_opcode(c.sock, networking.SERVING_OP_ENQUEUE)
            networking.send_data(c.sock, {"prompt": OTHER, "num_steps": 4})
            final = None
            while final is None:
                reply = networking.recv_data(c.sock)
                assert not reply.get("error"), reply
                if reply["done"]:
                    final = reply
            assert final["finish"] == "length"
            np.testing.assert_array_equal(
                np.array(final["row"], np.int32), _want(fitted, PROMPT, 8))
            # the stashed enqueue is answered after the final frame
            ack = networking.recv_data(c.sock)
            assert ack.get("ok") and "id" in ack
            chunks = []
            for tokens, done in c.stream(int(ack["id"])):
                chunks.append(tokens)
                if done is not None:
                    np.testing.assert_array_equal(done["row"],
                                                  _want(fitted, OTHER, 4))
        assert eng.stats["requests_cancelled"] == 0


# ---------------------------------------------------------------------------
# crashed / wedged engine: typed failure, no silent hangs
# ---------------------------------------------------------------------------

def test_inline_crash_fails_handles_and_raises(fitted):
    eng = ServingEngine(fitted, num_slots=2, max_len=24)
    h1 = eng.submit(PROMPT, 8)
    h2 = eng.submit(OTHER, 8)

    def boom():
        raise RuntimeError("chaos: decode crashed")

    eng._decode_once = boom
    with pytest.raises(RuntimeError, match="chaos"):
        eng.run_until_idle()
    for h in (h1, h2):
        assert h.finish == "error"
        with pytest.raises(EngineDead):
            h.result()
    with pytest.raises(EngineDead):
        eng.submit(PROMPT, 2)
    assert eng.dead is not None
    assert eng.stats["requests_failed"] == 2


def test_background_crash_fails_handles_within_deadline(fitted):
    eng = ServingEngine(fitted, num_slots=1, max_len=24)
    h = eng.submit(PROMPT, 8)

    def boom():
        raise RuntimeError("chaos: decode crashed")

    eng._decode_once = boom
    eng.start()
    assert h.wait(timeout=10.0), "handle must fail, not hang"
    with pytest.raises(EngineDead, match="chaos"):
        h.result()
    eng.stop()


def test_stop_join_timeout_surfaces_wedged_thread(fitted):
    """Satellite: stop() on a wedged decode thread logs, fails in-flight
    handles typed, and returns — instead of pretending a clean stop."""
    eng = ServingEngine(fitted, num_slots=1, max_len=24)
    h = eng.submit(PROMPT, 8)
    release = _wedge(eng)
    try:
        eng.start()
        _wait_for(lambda: eng._active.any())
        t0 = time.monotonic()
        eng.stop(join_timeout=0.2)
        assert time.monotonic() - t0 < 8.0
        assert eng.dead is not None
        with pytest.raises(EngineDead, match="wedged"):
            h.result(timeout=5.0)
    finally:
        release()


# ---------------------------------------------------------------------------
# EngineSupervisor: detect crash + wedge, restart, client retry
# ---------------------------------------------------------------------------

def test_supervisor_restarts_crashed_engine_and_client_retries(
        fitted, lock_order_audit, server_core):
    eng = ServingEngine(fitted, num_slots=2, max_len=24).warmup()
    want = _want(fitted, PROMPT, 6)
    with ServingServer(eng, poll_s=0.01) as srv:
        with EngineSupervisor(srv, heartbeat_interval=0.05,
                              liveness_deadline=2.0) as sup:
            with ServingClient(*srv.addr) as c:
                np.testing.assert_array_equal(c.generate(PROMPT, 6), want)

                def boom():
                    raise RuntimeError("chaos: decode crashed")

                eng._decode_once = boom
                row = c.generate(
                    PROMPT, 6,
                    retry_policy=RetryPolicy(attempts=40, backoff=0.05))
                np.testing.assert_array_equal(row, want)  # bit-identical
            assert srv.engine is not eng
            assert srv.engine.dead is None
            assert len(sup.recoveries) == 1
            rec = sup.recoveries[0]
            assert rec["reason"] == "crashed" and rec["restarted"]
            assert rec["recovery_ms"] is not None
            _assert_slots_reclaimed(srv.engine)


def test_supervisor_detects_wedged_engine_via_heartbeat(
        fitted, lock_order_audit, server_core):
    eng = ServingEngine(fitted, num_slots=2, max_len=24).warmup()
    want = _want(fitted, PROMPT, 6)
    release = _wedge(eng)
    try:
        with ServingServer(eng, poll_s=0.01) as srv:
            with EngineSupervisor(srv, heartbeat_interval=0.05,
                                  liveness_deadline=0.5) as sup:
                with ServingClient(*srv.addr) as c:
                    row = c.generate(
                        PROMPT, 6,
                        retry_policy=RetryPolicy(attempts=60, backoff=0.05))
                    np.testing.assert_array_equal(row, want)
                assert len(sup.recoveries) == 1, sup.recoveries
                assert sup.recoveries[0]["reason"] == "wedged"
    finally:
        release()


def test_supervisor_without_restart_fails_typed(fitted):
    eng = ServingEngine(fitted, num_slots=1, max_len=24).warmup()
    h = eng.submit(PROMPT, 8)

    def boom():
        raise RuntimeError("chaos: decode crashed")

    eng._decode_once = boom
    eng.start()
    with EngineSupervisor(eng, heartbeat_interval=0.05,
                          liveness_deadline=1.0, restart=False) as sup:
        assert h.wait(timeout=10.0)
        with pytest.raises(EngineDead):
            h.result()
        assert _wait_for(lambda: len(sup.recoveries) == 1)
        assert not sup.recoveries[0]["restarted"]
        assert sup.engine is eng  # no replacement
    with pytest.raises(EngineDead):
        eng.submit(PROMPT, 2)
    eng.stop()


def test_respawn_clone_preserves_knobs_and_numerics(fitted):
    eng = ServingEngine(fitted, num_slots=3, max_len=24, queue_capacity=7,
                        prefills_per_step=2, default_deadline_s=9.0)
    clone = eng.respawn_clone().warmup()
    assert clone.num_slots == 3 and clone.queue_capacity == 7
    assert clone.prefills_per_step == 2
    assert clone.default_deadline_s == 9.0
    h = clone.submit(PROMPT, 8, temperature=0.7, top_k=5, seed=11)
    clone.run_until_idle()
    np.testing.assert_array_equal(
        h.result(),
        _want(fitted, PROMPT, 8, temperature=0.7, top_k=5, seed=11))


def test_warmup_refuses_active_engine_and_keeps_bit_identity(fitted):
    eng = ServingEngine(fitted, num_slots=2, max_len=24).warmup()
    h = eng.submit(PROMPT, 8, temperature=0.7, seed=11)
    eng.step()
    with pytest.raises(RuntimeError, match="active"):
        eng.warmup()
    eng.run_until_idle()
    np.testing.assert_array_equal(
        h.result(), _want(fitted, PROMPT, 8, temperature=0.7, seed=11))


# ---------------------------------------------------------------------------
# the serving chaos matrix (ChaosProxy serving protocol)
# ---------------------------------------------------------------------------

def test_chaos_proxy_serving_clean_relay(fitted, server_core):
    eng = ServingEngine(fitted, num_slots=2, max_len=24)
    with ServingServer(eng, poll_s=0.01) as srv:
        with ChaosProxy(*srv.addr, protocol="serving") as px:
            with ServingClient(*px.addr) as c:
                np.testing.assert_array_equal(
                    c.generate(PROMPT, 8, temperature=0.6, seed=3),
                    _want(fitted, PROMPT, 8, temperature=0.6, seed=3))


@pytest.mark.parametrize("fault", [
    ChaosFault(0, 0, "reset"),        # request dropped + RST at 'q'
    ChaosFault(0, 0, "tear"),         # half the enqueue frame, then RST
    ChaosFault(0, 1, "cut_stream", 2),  # RST mid-stream after 2 chunks
    ChaosFault(0, 0, "delay", 0.05),  # delayed but successful
])
def test_chaos_matrix_slot_reclaimed_others_bit_identical(fitted, fault,
                                                          server_core):
    """For each scripted fault at an exact (conn, opcode) point: the
    affected slot is reclaimed, no handle blocks forever, and an
    unaffected concurrent request (direct connection) stays bit-identical
    to offline generate."""
    eng = ServingEngine(fitted, num_slots=2, max_len=24)
    want_other = _want(fitted, OTHER, 10, temperature=0.6, seed=5)
    with ServingServer(eng, poll_s=0.01) as srv:
        with ChaosProxy(*srv.addr, protocol="serving",
                        faults=[fault]) as px:
            faulted = ServingClient(*px.addr)
            healthy = ServingClient(*srv.addr)  # bypasses the proxy
            rid_h = healthy.submit(OTHER, 10, temperature=0.6, seed=5)
            outcome = None
            try:
                row = faulted.generate(PROMPT, 16)
                outcome = "completed"
            except (ConnectionError, OSError, ValueError, QueueFull):
                outcome = "faulted"
            if fault.action == "delay":
                assert outcome == "completed"
                np.testing.assert_array_equal(row,
                                              _want(fitted, PROMPT, 16))
            else:
                assert outcome == "faulted"
            assert px.injected == [(0, fault.op_index, fault.action)]
            # the unaffected request is bit-identical
            final = None
            for tokens, done in healthy.stream(rid_h):
                if done is not None:
                    final = done
            np.testing.assert_array_equal(final["row"], want_other)
            faulted.close()
            healthy.close()
        # every slot reclaimed, nothing active, nothing leaked
        assert _wait_for(lambda: not eng._active.any())
        assert _wait_for(lambda: srv.live_connections == 0)
        _assert_slots_reclaimed(eng)
        with srv._hlock:
            assert not srv._handles and not srv._owner


def test_chaos_client_stall_reclaims_via_deadline(fitted, server_core):
    """The "client stall" row of the matrix: a client that submits and
    never streams (connection held open, nothing read) cannot pin a slot
    past the request deadline."""
    eng = ServingEngine(fitted, num_slots=1, max_len=24,
                        default_deadline_s=0.3)
    with ServingServer(eng, poll_s=0.01) as srv:
        stalled = ServingClient(*srv.addr)
        rid = stalled.submit(PROMPT, 16)  # never streams, just sits there
        # a second client's request gets the slot after the deadline
        with ServingClient(*srv.addr) as c:
            np.testing.assert_array_equal(
                c.generate(OTHER, 6, deadline_s=30.0),
                _want(fitted, OTHER, 6))
        assert eng.stats["requests_expired"] >= 1
        _assert_slots_reclaimed(eng)
        # the stalled client wakes up late: the final frame tells it WHY
        # its request ended (retire reason "deadline" on the wire)
        final = None
        for tokens, done in stalled.stream(rid):
            if done is not None:
                final = done
        assert final["finish"] == "deadline"
        assert final["row"].shape == (len(PROMPT) + 16,)
        stalled.close()


# ---------------------------------------------------------------------------
# speculation under chaos (PR 11): retiring a slot MID-draft-round must
# free both target and draft KV rows with zero leaks
# ---------------------------------------------------------------------------

def _spec_engine(fitted, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_len", 24)
    return ServingEngine(fitted, spec_draft=fitted, spec_len=3, **kw)


@pytest.mark.parametrize("spec_draft", [False, True])
def test_cancel_mid_round_frees_slot_next_occupant_unpolluted(fitted,
                                                              spec_draft):
    """Cancel lands while a (speculative) round is in flight: the slot —
    target AND draft KV rows — returns to the pool within one iteration,
    and the next occupant's output is bit-identical to offline generate
    (no stale draft/verify state bleeds across occupancies)."""
    eng = (_spec_engine(fitted, num_slots=1)
           if spec_draft else ServingEngine(fitted, num_slots=1,
                                            max_len=24))
    h = eng.submit(PROMPT, 16)
    eng.step()   # prefill
    eng.step()   # a decode/spec round dispatched (lookahead in flight)
    eng.cancel(h)
    eng.step()
    assert h.finish == "cancel"
    assert eng.stats["requests_cancelled"] == 1
    _assert_slots_reclaimed(eng)
    # greedy next occupant: under speculation greedy is the
    # token-identity contract (sampled rows are distribution-exact with
    # a different key schedule — see docs/serving.md)
    h2 = eng.submit(OTHER, 10)
    eng.run_until_idle()
    np.testing.assert_array_equal(h2.result(),
                                  _want(fitted, OTHER, 10))


@pytest.mark.parametrize("spec_draft", [False, True])
def test_deadline_mid_round_and_mid_chunked_prefill(fitted, spec_draft):
    """Deadline expiry retires a speculating slot mid-run AND a chunked
    prefill mid-flight (both pools' staging dropped) — zero leaks."""
    build = (_spec_engine if spec_draft
             else lambda f, **kw: ServingEngine(f, max_len=24, **kw))
    eng = build(fitted, num_slots=1, prefill_chunk=4)
    h = eng.submit(LONG_PROMPT, 4, deadline_s=0.05)
    eng.step()
    assert eng._prefilling
    time.sleep(0.06)
    eng.run_until_idle()
    assert h.finish == "deadline" and not h.tokens
    _assert_slots_reclaimed(eng)

    eng = build(fitted, num_slots=2)
    doomed = eng.submit(PROMPT, 16, deadline_s=0.05)
    healthy = eng.submit(OTHER, 10)
    eng.step()
    eng.step()
    time.sleep(0.06)
    eng.run_until_idle()
    assert doomed.finish == "deadline"
    assert healthy.finish == "length"
    np.testing.assert_array_equal(healthy.result(),
                                  _want(fitted, OTHER, 10))
    _assert_slots_reclaimed(eng)


def test_disconnect_mid_round_reclaims_speculating_slot(fitted, server_core):
    """A client RST while its request is mid-speculative-round: the wire
    server's disconnect reclamation cancels it and both KV pools' rows
    free — the engine keeps serving, bit-identical."""
    eng = _spec_engine(fitted)
    with ServingServer(eng, poll_s=0.01) as srv:
        c = ServingClient(*srv.addr)
        rid = c.submit(PROMPT, 16)
        gen = c.stream(rid)
        next(gen)
        _hard_close(c.sock)
        assert _wait_for(lambda: eng.stats["requests_cancelled"] >= 1)
        assert _wait_for(lambda: not eng._active.any())
        assert srv.disconnect_cancels >= 1
        with ServingClient(*srv.addr) as c2:
            np.testing.assert_array_equal(c2.generate(OTHER, 10),
                                          _want(fitted, OTHER, 10))
        _assert_slots_reclaimed(eng)
        with srv._hlock:
            assert rid not in srv._handles and rid not in srv._owner


@pytest.mark.parametrize("fault", [
    ChaosFault(0, 0, "reset"),
    ChaosFault(0, 1, "cut_stream", 2),
])
def test_chaos_matrix_under_speculation(fitted, fault, server_core):
    """The PR 8 chaos matrix rows re-run against a SPECULATIVE engine:
    the faulted slot reclaims (draft pool included), the unaffected
    concurrent request stays bit-identical to offline generate."""
    eng = _spec_engine(fitted)
    # greedy concurrent request: the spec-mode bit-identity contract
    want_other = _want(fitted, OTHER, 10)
    with ServingServer(eng, poll_s=0.01) as srv:
        with ChaosProxy(*srv.addr, protocol="serving",
                        faults=[fault]) as px:
            faulted = ServingClient(*px.addr)
            healthy = ServingClient(*srv.addr)
            rid_h = healthy.submit(OTHER, 10)
            with pytest.raises((ConnectionError, OSError, ValueError,
                                QueueFull)):
                faulted.generate(PROMPT, 16)
            final = None
            for tokens, done in healthy.stream(rid_h):
                if done is not None:
                    final = done
            np.testing.assert_array_equal(final["row"], want_other)
            faulted.close()
            healthy.close()
        assert _wait_for(lambda: not eng._active.any())
        assert _wait_for(lambda: srv.live_connections == 0)
        _assert_slots_reclaimed(eng)
        with srv._hlock:
            assert not srv._handles and not srv._owner


def test_supervisor_restart_preserves_spec_and_quant(fitted, server_core):
    """An engine crash under supervision: the respawned clone carries the
    draft + quantization state (satellite contract) and the retried
    request completes — greedy speculation still token-identical."""
    eng = _spec_engine(fitted, kv_dtype="int8").warmup()
    with ServingServer(eng, poll_s=0.01) as srv:
        with EngineSupervisor(srv, heartbeat_interval=0.05,
                              liveness_deadline=2.0) as sup:
            with ServingClient(*srv.addr) as c:
                def boom():
                    raise RuntimeError("chaos: decode crashed")

                eng._decode_once = boom
                row = c.generate(
                    PROMPT, 6,
                    retry_policy=RetryPolicy(attempts=40, backoff=0.05))
                np.testing.assert_array_equal(row.shape,
                                              (len(PROMPT) + 6,))
            new = srv.engine
            assert new is not eng and new.dead is None
            assert new._draft_model is eng._draft_model
            assert new.spec_len == eng.spec_len
            assert new.kv_dtype == "int8"
            assert len(sup.recoveries) == 1
            _assert_slots_reclaimed(new)


def test_attach_ps_pull_requantizes_center(fitted):
    """Satellite: a quantized engine's hot reload re-quantizes the pulled
    center through quantize_params instead of swapping raw fp32 weights
    in — post-pull params still carry QuantizedTensor kernel leaves and
    serve the quantized numerics of the NEW weights."""
    from distkeras_tpu.core.quant import QuantizedTensor

    new_fitted = _fitted(seed=42)  # the center the fake PS serves
    ready = threading.Event()
    addr = {}

    def one_pull_ps():
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", 0))
        srv.listen(4)
        addr["port"] = srv.getsockname()[1]
        ready.set()
        try:
            conn, _ = srv.accept()
            while conn.recv(1) == b"p":
                networking.send_data(
                    conn, {"weights": new_fitted.get_weights()})
        except OSError:
            pass
        finally:
            srv.close()

    t = threading.Thread(target=one_pull_ps, daemon=True)
    t.start()
    assert ready.wait(timeout=5.0)
    eng = ServingEngine(fitted, num_slots=1, max_len=24, quantize="int8")
    eng.attach_ps("127.0.0.1", addr["port"], every=1)
    h = eng.submit(PROMPT, 6)
    eng.run_until_idle()
    assert h.done and eng.stats["weight_reloads"] >= 1
    leaves = jax.tree_util.tree_leaves(
        eng.params, is_leaf=lambda x: isinstance(x, QuantizedTensor))
    assert any(isinstance(l, QuantizedTensor) for l in leaves), \
        "pull swapped raw weights into a quantized engine"
    # the engine now serves the NEW center's quantized numerics
    want = np.asarray(new_fitted.quantize().generate(
        OTHER[None], 5, max_len=24))[0]
    h2 = eng.submit(OTHER, 5)
    eng.run_until_idle()
    np.testing.assert_array_equal(h2.result(), want)
    eng.stop()
    t.join(timeout=5.0)


# ---------------------------------------------------------------------------
# hot reload under PS death (claimed in PR 6's docstring, now pinned)
# ---------------------------------------------------------------------------

def test_attach_ps_keeps_serving_when_ps_dies_mid_pull(fitted):
    """The PS answers one pull with HALF a frame then RSTs (and is gone
    for good) — the engine logs, keeps the current weights, and output
    stays bit-identical to offline generate with those weights."""
    ready = threading.Event()
    addr = {}

    def half_frame_ps():
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", 0))
        srv.listen(4)
        addr["port"] = srv.getsockname()[1]
        ready.set()
        try:
            conn, _ = srv.accept()
            conn.recv(1)  # the 'p' pull opcode
            frame = networking.encode_message(
                {"weights": [np.zeros((4, 4), np.float32)]})
            conn.sendall(bytes(frame)[:len(frame) // 2])
            _hard_close(conn)
        finally:
            srv.close()

    t = threading.Thread(target=half_frame_ps, daemon=True)
    t.start()
    assert ready.wait(timeout=5.0)
    eng = ServingEngine(fitted, num_slots=1, max_len=24)
    eng.attach_ps("127.0.0.1", addr["port"], every=1)
    h = eng.submit(PROMPT, 8)
    eng.run_until_idle()
    t.join(timeout=5.0)
    assert eng.stats["weight_reloads"] == 0  # pull failed, weights kept
    np.testing.assert_array_equal(h.result(), _want(fitted, PROMPT, 8))
    # the dead PS stays dead; serving continues regardless
    h2 = eng.submit(OTHER, 5)
    eng.run_until_idle()
    np.testing.assert_array_equal(h2.result(), _want(fitted, OTHER, 5))


# ---------------------------------------------------------------------------
# slow soak: seeded client kills + one supervised engine crash
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_soak_killed_clients_and_engine_crash_zero_leaks(fitted, server_core):
    """~10% of clients RST mid-stream, and the engine is crashed once
    mid-run under supervision: zero slot leaks, zero lost surviving
    requests, every surviving row bit-identical to offline generate."""
    rng = np.random.default_rng(0)
    reqs = []
    for i in range(30):
        p_len = int(rng.integers(2, 6))
        reqs.append({
            "prompt": rng.integers(0, VOCAB, p_len).astype(np.int32),
            "num_steps": int(rng.integers(6, 14)),
            "temperature": 0.7, "seed": 1000 + i,
            "kill": bool(rng.random() < 0.1),
        })
    # expected rows computed OFFLINE for the survivors
    wants = {i: _want(fitted, r["prompt"], r["num_steps"],
                      temperature=0.7, seed=r["seed"])
             for i, r in enumerate(reqs) if not r["kill"]}
    eng = ServingEngine(fitted, num_slots=3, max_len=24,
                        queue_capacity=64).warmup()
    srv = ServingServer(eng, poll_s=0.01).start()
    sup = EngineSupervisor(srv, heartbeat_interval=0.05,
                           liveness_deadline=3.0, max_restarts=2).start()
    crash_at = threading.Event()
    results = {}
    errors = []
    lock = threading.Lock()

    def run_request(i, req):
        policy = RetryPolicy(attempts=80, backoff=0.05, max_backoff=0.5)
        try:
            with ServingClient(*srv.addr) as c:
                if req["kill"]:
                    # the SUBMIT is inside the tolerant block too: a kill
                    # client racing the supervised restart window gets the
                    # typed EngineDead/Draining rejection at submit time —
                    # it was about to RST anyway, so a rejected submission
                    # is still just a kill, not a soak failure (this race
                    # was the historical flake in this test)
                    try:
                        rid = c.submit(req["prompt"], req["num_steps"],
                                       temperature=req["temperature"],
                                       seed=req["seed"])
                        next(c.stream(rid))
                    except (ConnectionError, OSError, ValueError,
                            EngineDead, Draining, QueueFull):
                        pass  # engine death beat us to it — still a kill
                    _hard_close(c.sock)
                    return
                row = c.generate(req["prompt"], req["num_steps"],
                                 temperature=req["temperature"],
                                 seed=req["seed"], retry_policy=policy)
                with lock:
                    results[i] = row
        except BaseException as e:  # noqa: BLE001 - asserted below
            with lock:
                errors.append((i, e))

    def crasher():
        crash_at.wait(timeout=60.0)

        def boom():
            raise RuntimeError("chaos: soak crash")

        srv.engine._decode_once = boom

    threads = [threading.Thread(target=run_request, args=(i, r))
               for i, r in enumerate(reqs)]
    ct = threading.Thread(target=crasher)
    ct.start()
    for i, t in enumerate(threads):
        t.start()
        if i == len(threads) // 2:
            crash_at.set()  # crash the engine mid-flight
    for t in threads:
        t.join(timeout=120.0)
    ct.join(timeout=5.0)
    try:
        assert not errors, errors[:3]
        # zero lost surviving requests, all bit-identical
        assert set(results) == set(wants)
        for i, row in results.items():
            np.testing.assert_array_equal(row, wants[i], err_msg=f"req {i}")
        # exactly one supervised restart happened
        assert len(sup.recoveries) == 1 and sup.recoveries[0]["restarted"]
        # zero slot leaks on the live engine; the dead one failed loudly
        final = srv.engine
        assert _wait_for(lambda: not final._active.any())
        _assert_slots_reclaimed(final)
        assert eng.dead is not None
        # handle reclamation for hard-closed clients is asynchronous: the
        # server's stream poll has to notice the RST before _release_owned
        # runs, so wait for it rather than asserting the instantaneous state
        assert _wait_for(lambda: not srv._handles and not srv._owner), (
            srv._handles, srv._owner)
    finally:
        sup.stop()
        srv.stop()


# ---------------------------------------------------------------------------
# paged pool under chaos (PR 12): every retirement path must return the
# block allocator to baseline — zero leaked blocks, refcounts at zero
# ---------------------------------------------------------------------------

def _paged_engine(fitted, spec=False, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_len", 24)
    kw.setdefault("block_size", 4)
    if spec:
        kw.setdefault("spec_draft", fitted)
        kw.setdefault("spec_len", 3)
    return ServingEngine(fitted, paged=True, **kw)


def _assert_no_block_leaks(eng):
    assert eng.kv_blocks_in_use == 0, (
        f"leaked {eng.kv_blocks_in_use} blocks")
    assert eng._pool.check_conservation()
    assert not eng._plans


@pytest.mark.paged
@pytest.mark.parametrize("spec", [False, True])
@pytest.mark.parametrize("reason", ["cancel_running", "deadline_running",
                                    "cancel_mid_chunk",
                                    "deadline_mid_chunk", "cancel_queued"])
def test_paged_retirement_matrix_zero_block_leaks(fitted, reason, spec):
    """The full early-retirement matrix on the paged pool, speculation on
    and off: cancel/deadline against queued, running (mid-round), and
    mid-chunked-prefill requests — each path releases the request's
    block plan (shared refs dropped, private blocks freed) and the next
    occupant reuses them with generate-identical output."""
    chunked = reason.endswith("mid_chunk")
    eng = _paged_engine(fitted, spec=spec, num_slots=1, prefill_chunk=4)
    if reason == "cancel_queued":
        running = eng.submit(PROMPT, 12)
        target = eng.submit(OTHER, 5)       # queued behind the lone slot
        eng.step()
        eng.cancel(target)
        eng.run_until_idle()
        assert running.finish == "length"
    else:
        prompt = LONG_PROMPT if chunked else PROMPT
        kw = {"deadline_s": 0.05} if reason.startswith("deadline") else {}
        target = eng.submit(prompt, 8, **kw)
        eng.step()
        if chunked:
            assert eng._prefilling
        else:
            eng.step()                       # a round in flight
        if reason.startswith("cancel"):
            eng.cancel(target)
        else:
            time.sleep(0.06)
        eng.run_until_idle()
    assert target.finish == ("cancel" if reason.startswith("cancel")
                             else "deadline")
    _assert_slots_reclaimed(eng)
    _assert_no_block_leaks(eng)
    h2 = eng.submit(OTHER, 6)
    eng.run_until_idle()
    np.testing.assert_array_equal(h2.result(), _want(fitted, OTHER, 6))
    _assert_no_block_leaks(eng)


@pytest.mark.paged
def test_paged_disconnect_and_drain_zero_block_leaks(fitted, server_core):
    """Wire disconnect reclamation and graceful drain on the paged pool:
    a client RST mid-stream cancels its request and frees its blocks; a
    drain finishes in-flight work and leaves the allocator at baseline
    (cached chains are reusable capacity, not leaks)."""
    eng = _paged_engine(fitted)
    with ServingServer(eng, poll_s=0.01) as srv:
        c = ServingClient(*srv.addr)
        rid = c.submit(PROMPT, 16)
        gen = c.stream(rid)
        next(gen)
        _hard_close(c.sock)
        assert _wait_for(lambda: eng.stats["requests_cancelled"] >= 1)
        assert _wait_for(lambda: not eng._active.any())
        with ServingClient(*srv.addr) as c2:
            np.testing.assert_array_equal(c2.generate(OTHER, 10),
                                          _want(fitted, OTHER, 10))
        _assert_slots_reclaimed(eng)
        _assert_no_block_leaks(eng)
    eng = _paged_engine(fitted)
    h = eng.submit(PROMPT, 6)
    assert eng.drain(timeout=30.0)
    assert h.finish == "length"
    _assert_no_block_leaks(eng)


@pytest.mark.paged
@pytest.mark.parametrize("fault", [
    ChaosFault(0, 0, "reset"),
    ChaosFault(0, 1, "cut_stream", 2),
])
def test_paged_chaos_matrix_survivors_bit_identical(fitted, fault,
                                                    server_core):
    """The PR 8 chaos-matrix rows against the paged pool: the faulted
    request's blocks free, the unaffected concurrent request stays
    bit-identical, and the allocator returns to baseline."""
    eng = _paged_engine(fitted)
    want_other = _want(fitted, OTHER, 10, temperature=0.6, seed=5)
    with ServingServer(eng, poll_s=0.01) as srv:
        with ChaosProxy(*srv.addr, protocol="serving",
                        faults=[fault]) as px:
            faulted = ServingClient(*px.addr)
            healthy = ServingClient(*srv.addr)
            rid_h = healthy.submit(OTHER, 10, temperature=0.6, seed=5)
            with pytest.raises((ConnectionError, OSError, ValueError,
                                QueueFull)):
                faulted.generate(PROMPT, 16)
            final = None
            for tokens, done in healthy.stream(rid_h):
                if done is not None:
                    final = done
            np.testing.assert_array_equal(final["row"], want_other)
            faulted.close()
            healthy.close()
        assert _wait_for(lambda: not eng._active.any())
        assert _wait_for(lambda: srv.live_connections == 0)
        _assert_slots_reclaimed(eng)
        _assert_no_block_leaks(eng)


@pytest.mark.paged
def test_paged_supervisor_restart_carries_knobs(fitted, server_core):
    """Engine crash under supervision: the respawned clone keeps
    paged/block_size/kv_blocks (same arena shape) with a FRESH trie, and
    the retried request completes generate-identically."""
    eng = _paged_engine(fitted, kv_blocks=12).warmup()
    with ServingServer(eng, poll_s=0.01) as srv:
        with EngineSupervisor(srv, heartbeat_interval=0.05,
                              liveness_deadline=2.0) as sup:
            with ServingClient(*srv.addr) as c:
                def boom():
                    raise RuntimeError("chaos: decode crashed")

                eng._decode_once = boom
                row = c.generate(
                    PROMPT, 6,
                    retry_policy=RetryPolicy(attempts=40, backoff=0.05))
                np.testing.assert_array_equal(row,
                                              _want(fitted, PROMPT, 6))
            assert len(sup.recoveries) >= 1
            fresh = srv.engine
            assert fresh is not eng
            assert fresh.paged and fresh.block_size == 4
            assert fresh.kv_blocks == 12
            _assert_no_block_leaks(fresh)


@pytest.mark.paged
@pytest.mark.slow
def test_paged_arena_pressure_soak_zero_leaks(fitted):
    """Slow arena-pressure soak: a tight arena, shared-prefix traffic,
    ~20% seeded client kills + deadline expiries over many rounds —
    every surviving request exact, the allocator at baseline after the
    storm (the `paged` marker keeps this out of tier-1 via `slow`)."""
    rng = np.random.default_rng(0)
    eng = _paged_engine(fitted, num_slots=3, max_len=24,
                        kv_blocks=12).warmup().start()
    prefix = (np.arange(8) % VOCAB).astype(np.int32)
    try:
        for i in range(30):
            prompt = np.concatenate(
                [prefix, rng.integers(0, VOCAB, 2)]).astype(np.int32)
            kill = rng.random() < 0.2
            h = eng.submit(prompt, 6, seed=i,
                           deadline_s=(0.02 if rng.random() < 0.1
                                       else None))
            if kill:
                eng.cancel(h)
            else:
                h.wait(timeout=30.0)
                if h.finish == "length":
                    np.testing.assert_array_equal(
                        h.result(), _want(fitted, prompt, 6))
        assert _wait_for(
            lambda: eng.stats["requests_submitted"]
            == eng.stats["requests_completed"]
            + eng.stats["requests_failed"]
            + eng.stats["requests_rejected"])
    finally:
        eng.stop()
    _assert_slots_reclaimed(eng)
    _assert_no_block_leaks(eng)
    assert eng.stats["prefix_hits"] > 0
