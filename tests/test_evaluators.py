"""Evaluator metrics (reference ships accuracy only — SURVEY.md §2.1 row
20; F1/top-k are extras).  F1/precision/recall are cross-checked against
scikit-learn's implementations on random predictions.
"""

import numpy as np
import pytest

from distkeras_tpu import (AccuracyEvaluator, Dataset, F1Evaluator,
                           TopKAccuracyEvaluator)


def make_ds(pred, label, **extra):
    cols = {"prediction_index": np.asarray(pred),
            "label": np.asarray(label)}
    cols.update({k: np.asarray(v) for k, v in extra.items()})
    return Dataset(cols)


def test_f1_matches_sklearn():
    sk = pytest.importorskip("sklearn.metrics")
    rng = np.random.default_rng(0)
    label = rng.integers(0, 4, 500)
    pred = np.where(rng.random(500) < 0.7, label, rng.integers(0, 4, 500))
    ds = make_ds(pred, label)
    for average in ("macro", "micro"):
        for metric, sk_fn in (("f1", sk.f1_score),
                              ("precision", sk.precision_score),
                              ("recall", sk.recall_score)):
            got = F1Evaluator(average=average, metric=metric).evaluate(ds)
            want = sk_fn(label, pred, average=average, zero_division=0)
            np.testing.assert_allclose(got, want, atol=1e-9), (average,
                                                               metric)
    # binary on class 1
    blabel = (label > 1).astype(int)
    bpred = (pred > 1).astype(int)
    bds = make_ds(bpred, blabel)
    got = F1Evaluator(average="binary").evaluate(bds)
    want = sk.f1_score(blabel, bpred, zero_division=0)
    np.testing.assert_allclose(got, want, atol=1e-9)


def test_f1_edge_cases():
    # no positive predictions or labels -> 0, not NaN
    ds = make_ds([0, 0, 0], [0, 0, 0])
    assert F1Evaluator(average="binary").evaluate(ds) == 0.0
    # micro == accuracy for single-label classification
    ds2 = make_ds([0, 1, 2, 2], [0, 1, 1, 2], )
    micro = F1Evaluator(average="micro").evaluate(ds2)
    acc = AccuracyEvaluator().evaluate(ds2)
    assert micro == acc == 0.75
    # one-hot labels accepted
    oh = np.eye(3)[[0, 1, 1, 2]]
    ds3 = Dataset({"prediction_index": np.array([0, 1, 2, 2]), "label": oh})
    assert F1Evaluator(average="micro").evaluate(ds3) == 0.75
    with pytest.raises(ValueError, match="average"):
        F1Evaluator(average="weighted")
    with pytest.raises(ValueError, match="metric"):
        F1Evaluator(metric="auc")


def test_accuracy_float_predictions():
    """Float-stored class indices round (not truncate); NaN fails loudly."""
    ds = make_ds(np.array([0.9, 1.1, 2.0]), np.array([1, 1, 2]))
    # truncation would read 0.9 as class 0 and score 2/3
    assert AccuracyEvaluator().evaluate(ds) == 1.0
    for bad in (np.nan, np.inf, -np.inf):
        bad_ds = make_ds(np.array([0.0, bad]), np.array([0, 1]))
        with pytest.raises(ValueError, match="NaN/inf"):
            AccuracyEvaluator().evaluate(bad_ds)


def test_topk_rejects_non_2d_predictions():
    ds1 = Dataset({"prediction": np.array([0.5, 0.5]),
                   "label": np.array([0, 1])})
    with pytest.raises(ValueError, match="num_classes"):
        TopKAccuracyEvaluator(k=1).evaluate(ds1)
    ds3 = Dataset({"prediction": np.zeros((2, 3, 4)),
                   "label": np.array([0, 1])})
    with pytest.raises(ValueError, match="num_classes"):
        TopKAccuracyEvaluator(k=1).evaluate(ds3)


def test_topk_accuracy():
    probs = np.array([[0.5, 0.3, 0.2],    # top2 = {0, 1}
                      [0.1, 0.2, 0.7],    # top2 = {2, 1}
                      [0.4, 0.35, 0.25]])  # top2 = {0, 1}
    label = np.array([1, 0, 2])
    ds = Dataset({"prediction": probs, "label": label})
    assert TopKAccuracyEvaluator(k=1).evaluate(ds) == 0.0
    np.testing.assert_allclose(
        TopKAccuracyEvaluator(k=2).evaluate(ds), 1 / 3)
    assert TopKAccuracyEvaluator(k=3).evaluate(ds) == 1.0
    # k larger than the class count clamps
    assert TopKAccuracyEvaluator(k=10).evaluate(ds) == 1.0
    with pytest.raises(ValueError, match="k must be"):
        TopKAccuracyEvaluator(k=0)


def test_auc_matches_sklearn():
    sk = pytest.importorskip("sklearn.metrics")
    from distkeras_tpu import AUCEvaluator
    rng = np.random.default_rng(3)
    label = rng.integers(0, 2, 400)
    score = np.clip(label * 0.4 + rng.normal(0.3, 0.3, 400), 0, 1)
    ds = Dataset({"prediction": score, "label": label})
    got = AUCEvaluator().evaluate(ds)
    want = sk.roc_auc_score(label, score)
    np.testing.assert_allclose(got, want, atol=1e-12)
    # ties: quantized scores exercise the midrank path
    q = np.round(score * 4) / 4
    np.testing.assert_allclose(
        AUCEvaluator().evaluate(Dataset({"prediction": q, "label": label})),
        sk.roc_auc_score(label, q), atol=1e-12)


def test_auc_shapes_and_validation():
    from distkeras_tpu import AUCEvaluator
    label = np.array([0, 1, 0, 1])
    # (N, 2) class probabilities: column 1 is the positive score
    two_col = np.array([[0.9, 0.1], [0.2, 0.8], [0.6, 0.4], [0.3, 0.7]])
    perfect = AUCEvaluator().evaluate(
        Dataset({"prediction": two_col, "label": label}))
    assert perfect == 1.0
    # one-hot labels collapse through _labels_1d
    onehot = np.eye(2)[label]
    assert AUCEvaluator().evaluate(
        Dataset({"prediction": two_col, "label": onehot})) == 1.0
    with pytest.raises(ValueError, match="binary"):
        AUCEvaluator().evaluate(
            Dataset({"prediction": np.ones(3), "label": np.array([0, 1, 2])}))
    with pytest.raises(ValueError, match="both classes"):
        AUCEvaluator().evaluate(
            Dataset({"prediction": np.ones(3), "label": np.ones(3)}))
