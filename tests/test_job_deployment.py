"""Job deployment + punchcard queue (reference:
``distkeras/job_deployment.py`` — SURVEY.md §2.1 row 22).  SSH itself is not
exercised (no cluster in CI); the LocalJobRunner doubles for it, and the SSH
command rendering is checked textually.
"""

import os
import sys

from distkeras_tpu.job_deployment import (Job, LocalJobRunner, SSHJobRunner,
                                          Punchcard)


def _touch_script(tmp_path, body: str) -> str:
    p = tmp_path / "job_script.py"
    p.write_text(body)
    return str(p)


def test_local_job_runs_and_reports_exit(tmp_path):
    out = tmp_path / "out.txt"
    script = _touch_script(tmp_path, f"""
import os
with open({str(out)!r}, "a") as f:
    f.write(os.environ["DISTKERAS_TPU_PROCESS_ID"] + "\\n")
""")
    job = Job("write-pid", script, hosts=["h0", "h1", "h2"])
    rc = job.run(runner=LocalJobRunner())
    assert rc == 0
    assert job.returncodes == [0, 0, 0]
    pids = sorted(out.read_text().split())
    assert pids == ["0", "1", "2"]


def test_job_failure_propagates(tmp_path):
    script = _touch_script(tmp_path, "import sys; sys.exit(3)")
    job = Job("fail", script)
    assert job.run(runner=LocalJobRunner()) == 3


def test_host_env_renders_coordinator():
    job = Job("j", "train.py", hosts=["tpu-a", "tpu-b"], coordinator_port=9999)
    env0 = job.host_env(0)
    env1 = job.host_env(1)
    assert env0["DISTKERAS_TPU_COORDINATOR"] == "tpu-a:9999"
    assert env1["DISTKERAS_TPU_COORDINATOR"] == "tpu-a:9999"
    assert env0["DISTKERAS_TPU_PROCESS_ID"] == "0"
    assert env1["DISTKERAS_TPU_PROCESS_ID"] == "1"
    assert env1["DISTKERAS_TPU_NUM_PROCESSES"] == "2"


def test_host_env_uncoordinated_blanks_inherited_coordinator():
    """coordinated=False must actively BLANK the coordinator vars — child
    launchers overlay host_env on os.environ, and a driver itself running
    under a coordinated Job must not drag its uncoordinated children into
    the parent's jax.distributed group."""
    job = Job("j", "worker.py", hosts=["h"] * 3, coordinated=False)
    env = job.host_env(2)
    assert env["DISTKERAS_TPU_PROCESS_ID"] == "2"
    assert env["DISTKERAS_TPU_COORDINATOR"] == ""
    assert env["DISTKERAS_TPU_NUM_PROCESSES"] == "1"
    # initialize_from_env treats the blank coordinator as absent (no-op)
    import os
    from distkeras_tpu.job_deployment import initialize_from_env
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        initialize_from_env()  # must not try to join a group
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    # round-trips through the punchcard record
    assert not Job.from_record(job.to_record()).coordinated


def test_ssh_command_rendering(monkeypatch):
    captured = []

    class FakePopen:
        def __init__(self, cmd, **kw):
            captured.append(cmd)

        def wait(self):
            return 0

    import distkeras_tpu.job_deployment as jd
    monkeypatch.setattr(jd.subprocess, "Popen", FakePopen)
    job = Job("j", "train.py", args=["--epochs", "2"], hosts=["a", "b"])
    assert job.run(runner=SSHJobRunner()) == 0
    assert len(captured) == 2
    assert captured[0][0] == "ssh"
    assert captured[0][-2] == "a"
    assert "DISTKERAS_TPU_PROCESS_ID=0" in captured[0][-1]
    assert "--epochs 2" in captured[0][-1].replace("'", "")


def test_punchcard_fifo(tmp_path):
    q = Punchcard(str(tmp_path / "queue.jsonl"))
    assert q.pop() is None
    script = _touch_script(tmp_path, "pass")
    q.submit(Job("first", script))
    q.submit(Job("second", script, args=["x"], hosts=["h"]))
    assert [j.name for j in q.pending()] == ["first", "second"]
    head = q.pop()
    assert head.name == "first"
    assert [j.name for j in q.pending()] == ["second"]
    restored = q.pending()[0]
    assert restored.args == ["x"] and restored.hosts == ["h"]


def test_punchcard_serve_drains(tmp_path):
    marker = tmp_path / "ran.txt"
    script = _touch_script(
        tmp_path, f"open({str(marker)!r}, 'a').write('x')")
    q = Punchcard(str(tmp_path / "queue.jsonl"))
    q.submit(Job("a", script))
    q.submit(Job("b", script))
    n = q.serve(runner=LocalJobRunner())
    assert n == 2
    assert marker.read_text() == "xx"
    assert q.pending() == []
