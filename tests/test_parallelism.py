"""TP / EP / PP primitives on the 8-device virtual mesh, each verified
against a single-device reference computation (forward and, where it
matters, gradients).  No reference counterpart (SURVEY.md §2.3: TP/PP/EP all
absent upstream) — this is the framework's model-parallel layer.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distkeras_tpu.parallel import get_mesh
from distkeras_tpu.parallel.tp import (column_parallel_dense,
                                       row_parallel_dense, tp_mlp,
                                       tp_self_attention)
from distkeras_tpu.parallel.moe import moe_mlp, top1_routing
from distkeras_tpu.parallel.pipeline import pipeline_apply
from distkeras_tpu.ops.attention import dot_product_attention


# ---------------------------------------------------------------------------
# tensor parallelism
# ---------------------------------------------------------------------------

def test_tp_mlp_matches_dense(eight_devices):
    mesh = get_mesh(8, axis_name="model")
    d, f, b = 16, 64, 4
    ks = jax.random.split(jax.random.PRNGKey(0), 5)
    x = jax.random.normal(ks[0], (b, d))
    w1 = jax.random.normal(ks[1], (d, f)) * 0.1
    b1 = jax.random.normal(ks[2], (f,)) * 0.1
    w2 = jax.random.normal(ks[3], (f, d)) * 0.1
    b2 = jax.random.normal(ks[4], (d,)) * 0.1

    want = jax.nn.gelu(x @ w1 + b1) @ w2 + b2

    fn = jax.shard_map(
        lambda x_, w1_, b1_, w2_, b2_: tp_mlp(
            x_, w1_, b1_, w2_, b2_, axis_name="model",
            compute_dtype=jnp.float32),
        mesh=mesh,
        in_specs=(P(), P(None, "model"), P("model"), P("model", None), P()),
        out_specs=P())
    got = fn(x, w1, b1, w2, b2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_tp_attention_matches_full(eight_devices):
    """Heads split over 'model' (8 shards × 1 head) == unsharded MHA."""
    mesh = get_mesh(8, axis_name="model")
    b, s, heads, dh = 2, 8, 8, 4
    d = heads * dh
    ks = jax.random.split(jax.random.PRNGKey(1), 5)
    x = jax.random.normal(ks[0], (b, s, d))
    wq, wk, wv = (jax.random.normal(k, (d, d)) * 0.1 for k in ks[1:4])
    wo = jax.random.normal(ks[4], (d, d)) * 0.1

    def full(x):
        q, k, v = (
            (x @ w).reshape(b, s, heads, dh) for w in (wq, wk, wv))
        out = dot_product_attention(q, k, v, causal=True)
        return out.reshape(b, s, d) @ wo

    fn = jax.shard_map(
        lambda x_, q_, k_, v_, o_: tp_self_attention(
            x_, q_, k_, v_, o_, num_local_heads=1, head_dim=dh,
            axis_name="model", causal=True, compute_dtype=jnp.float32),
        mesh=mesh,
        in_specs=(P(), P(None, "model"), P(None, "model"), P(None, "model"),
                  P("model", None)),
        out_specs=P())
    got = fn(x, wq, wk, wv, wo)
    np.testing.assert_allclose(np.asarray(got), np.asarray(full(x)),
                               atol=1e-4)


# ---------------------------------------------------------------------------
# expert parallelism
# ---------------------------------------------------------------------------

def test_top1_routing_capacity():
    logits = jnp.array([[9.0, 0.0], [8.0, 0.0], [7.0, 0.0], [0.0, 5.0]])
    dispatch, combine = top1_routing(logits, capacity=2)
    # tokens 0,1 land in expert 0 slots 0,1; token 2 dropped; token 3 → e1
    assert dispatch[0, 0, 0] == 1 and dispatch[1, 0, 1] == 1
    assert dispatch[2].sum() == 0
    assert dispatch[3, 1, 0] == 1
    gates = jax.nn.softmax(logits, -1)
    np.testing.assert_allclose(combine[3, 1, 0], gates[3, 1], atol=1e-6)


def _moe_reference(x, router_kernel, w1, b1, w2, b2, capacity,
                   shard_size):
    """Per-token top-1 expert MLP; tokens are routed in per-shard slices of
    ``shard_size`` with per-slice expert capacities (matching moe_mlp's
    token sharding over the expert axis)."""
    t, d = x.shape
    gates = jax.nn.softmax(x @ router_kernel, -1)
    expert = np.asarray(jnp.argmax(gates, -1))
    gate = np.asarray(jnp.max(gates, -1))
    out = np.zeros((t, d), np.float32)
    for start in range(0, t, shard_size):
        counts = {}
        for i in range(start, start + shard_size):
            e = int(expert[i])
            counts[e] = counts.get(e, 0) + 1
            if counts[e] > capacity:
                continue
            h = np.asarray(jax.nn.gelu(x[i] @ w1[e] + b1[e]))
            out[i] = (h @ w2[e] + b2[e]) * gate[i]
    return out


def test_moe_matches_reference(eight_devices):
    """8 experts over 8 devices, replicated input: all_to_all round-trip
    equals the per-token reference."""
    mesh = get_mesh(8, axis_name="model")
    b, s, d, f, e = 1, 16, 8, 16, 8
    ks = jax.random.split(jax.random.PRNGKey(2), 6)
    x = jax.random.normal(ks[0], (b, s, d))
    router = jax.random.normal(ks[1], (d, e))
    w1 = jax.random.normal(ks[2], (e, d, f)) * 0.2
    b1 = jax.random.normal(ks[3], (e, f)) * 0.1
    w2 = jax.random.normal(ks[4], (e, f, d)) * 0.2
    b2 = jax.random.normal(ks[5], (e, d)) * 0.1

    # each of the 8 shards routes 16/8 = 2 tokens;
    # capacity = ceil(2.0 * 2 / 8) = 1
    capacity = 1
    fn = jax.shard_map(
        # the MoE output is identical on every device but shard_map
        # cannot infer that statically; psum/n makes replication provable
        lambda x_, r_, w1_, b1_, w2_, b2_: jax.lax.psum(moe_mlp(
            x_, r_, w1_, b1_, w2_, b2_, axis_name="model",
            capacity_factor=2.0, compute_dtype=jnp.float32), "model") / 8,
        mesh=mesh,
        in_specs=(P(), P(), P("model"), P("model"), P("model"), P("model")),
        out_specs=P())
    got = np.asarray(fn(x, router, w1, b1, w2, b2)).reshape(b * s, d)
    want = _moe_reference(np.asarray(x).reshape(-1, d), np.asarray(router),
                          np.asarray(w1), np.asarray(b1), np.asarray(w2),
                          np.asarray(b2), capacity, shard_size=2)
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_moe_gradients_flow(eight_devices):
    mesh = get_mesh(8, axis_name="model")
    ks = jax.random.split(jax.random.PRNGKey(3), 6)
    x = jax.random.normal(ks[0], (1, 8, 8))
    router = jax.random.normal(ks[1], (8, 8))
    w1 = jax.random.normal(ks[2], (8, 8, 16)) * 0.2
    b1 = jnp.zeros((8, 16))
    w2 = jax.random.normal(ks[4], (8, 16, 8)) * 0.2
    b2 = jnp.zeros((8, 8))

    def loss(w1_):
        fn = jax.shard_map(
            lambda x_, r_, a, b_, c, d_: jax.lax.psum(moe_mlp(
                x_, r_, a, b_, c, d_, axis_name="model",
                capacity_factor=2.0, compute_dtype=jnp.float32),
                "model") / 8,
            mesh=mesh,
            in_specs=(P(), P(), P("model"), P("model"), P("model"),
                      P("model")),
            out_specs=P())
        return jnp.sum(fn(x, router, w1_, b1, w2, b2) ** 2)

    g = jax.grad(loss)(w1)
    assert np.isfinite(np.asarray(g)).all()
    assert float(jnp.abs(g).sum()) > 0


# ---------------------------------------------------------------------------
# pipeline parallelism
# ---------------------------------------------------------------------------

def test_pipeline_matches_sequential(eight_devices):
    """4-stage MLP pipeline over microbatches == sequential composition."""
    devs = jax.devices()[:4]
    mesh = Mesh(np.array(devs), ("stage",))
    d, micro_b, m = 8, 4, 6
    ks = jax.random.split(jax.random.PRNGKey(4), 5)
    ws = jax.random.normal(ks[0], (4, d, d)) * 0.3
    x = jax.random.normal(ks[1], (m, micro_b, d))

    def stage_fn(w, h):
        return jnp.tanh(h @ w)

    def sequential(x):
        h = x
        for i in range(4):
            h = stage_fn(ws[i], h)
        return h

    fn = jax.shard_map(
        # outputs are zeros on all but the last stage, so a psum over the
        # stage axis replicates the result for out_specs=P()
        lambda w, xm: jax.lax.psum(
            pipeline_apply(stage_fn, w[0], xm, axis_name="stage"), "stage"),
        mesh=mesh, in_specs=(P("stage"), P()), out_specs=P())
    got = fn(ws, x)
    want = jax.vmap(sequential)(x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_pipeline_gradients(eight_devices):
    devs = jax.devices()[:4]
    mesh = Mesh(np.array(devs), ("stage",))
    d, micro_b, m = 4, 2, 4
    ws = jax.random.normal(jax.random.PRNGKey(5), (4, d, d)) * 0.3
    x = jax.random.normal(jax.random.PRNGKey(6), (m, micro_b, d))

    def stage_fn(w, h):
        return jnp.tanh(h @ w)

    def loss_pipe(ws_):
        fn = jax.shard_map(
            lambda w, xm: jax.lax.psum(
                pipeline_apply(stage_fn, w[0], xm, axis_name="stage"),
                "stage"),
            mesh=mesh, in_specs=(P("stage"), P()), out_specs=P())
        return jnp.sum(fn(ws_, x) ** 2)

    def loss_seq(ws_):
        h = x
        for i in range(4):
            h = jax.vmap(lambda hh: stage_fn(ws_[i], hh))(h)
        return jnp.sum(h.astype(jnp.float32) ** 2)

    gp = jax.grad(loss_pipe)(ws)
    gs = jax.grad(loss_seq)(ws)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gs), atol=1e-4)


def test_pipeline_transformer_matches_sequential(eight_devices):
    """The integrated dp x pp transformer (round-3): the pipelined loss and
    gradients equal the sequential single-device reference on the same
    params, and one optimizer step runs end to end."""
    import optax
    from jax.sharding import Mesh, PartitionSpec as P
    from distkeras_tpu.parallel.pp_transformer import PipelineTransformerLM

    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("data", "stage"))
    lm = PipelineTransformerLM(
        vocab_size=32, seq_len=16, d_model=16, num_heads=2, num_layers=4,
        mlp_dim=32, mesh=mesh, num_microbatches=2,
        compute_dtype=jnp.float32)
    params = lm.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, 32, (8, 16)), jnp.int32)
    labels = (tokens + 1) % 32

    # pipelined loss+grads via shard_map
    pipelined = jax.jit(jax.shard_map(
        jax.value_and_grad(lm._local_loss), mesh=mesh,
        in_specs=(lm.param_specs(), P("data"), P("data")),
        out_specs=(P(), lm.param_specs())))
    loss_p, grads_p = pipelined(params, tokens, labels)

    loss_r, grads_r = jax.value_and_grad(lm.reference_forward_loss)(
        jax.device_get(params), tokens, labels)

    np.testing.assert_allclose(float(loss_p), float(loss_r), rtol=1e-5)
    for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_flatten_with_path(jax.device_get(grads_p))[0],
            jax.tree_util.tree_flatten_with_path(grads_r)[0]):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5,
            err_msg=str(pa))

    # and a full optimizer step executes
    opt_state, step = lm.compile_train_step(optax.adam(1e-3), params)
    params2, opt_state, loss = step(params, opt_state, tokens, labels)
    assert np.isfinite(float(loss))
    # stage-sharded layer params actually moved
    w_before = np.asarray(jax.device_get(
        lm.init(jax.random.PRNGKey(0))["layers"]["wq"]))
    w_after = np.asarray(jax.device_get(params2["layers"]["wq"]))
    assert not np.allclose(w_before, w_after)
