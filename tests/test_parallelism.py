"""TP / EP / PP primitives on the 8-device virtual mesh, each verified
against a single-device reference computation (forward and, where it
matters, gradients).  No reference counterpart (SURVEY.md §2.3: TP/PP/EP all
absent upstream) — this is the framework's model-parallel layer.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distkeras_tpu.parallel import get_mesh
from distkeras_tpu.parallel import _compat
from distkeras_tpu.parallel.tp import (column_parallel_dense,
                                       row_parallel_dense, tp_mlp,
                                       tp_self_attention)
from distkeras_tpu.parallel.moe import moe_mlp, top1_routing
from distkeras_tpu.parallel.pipeline import pipeline_apply
from distkeras_tpu.ops.attention import dot_product_attention


# ---------------------------------------------------------------------------
# tensor parallelism
# ---------------------------------------------------------------------------

def test_tp_mlp_matches_dense(eight_devices):
    mesh = get_mesh(8, axis_name="model")
    d, f, b = 16, 64, 4
    ks = jax.random.split(jax.random.PRNGKey(0), 5)
    x = jax.random.normal(ks[0], (b, d))
    w1 = jax.random.normal(ks[1], (d, f)) * 0.1
    b1 = jax.random.normal(ks[2], (f,)) * 0.1
    w2 = jax.random.normal(ks[3], (f, d)) * 0.1
    b2 = jax.random.normal(ks[4], (d,)) * 0.1

    want = jax.nn.gelu(x @ w1 + b1) @ w2 + b2

    fn = _compat.shard_map(
        lambda x_, w1_, b1_, w2_, b2_: tp_mlp(
            x_, w1_, b1_, w2_, b2_, axis_name="model",
            compute_dtype=jnp.float32),
        mesh=mesh,
        in_specs=(P(), P(None, "model"), P("model"), P("model", None), P()),
        out_specs=P())
    got = fn(x, w1, b1, w2, b2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_tp_attention_matches_full(eight_devices):
    """Heads split over 'model' (8 shards × 1 head) == unsharded MHA."""
    mesh = get_mesh(8, axis_name="model")
    b, s, heads, dh = 2, 8, 8, 4
    d = heads * dh
    ks = jax.random.split(jax.random.PRNGKey(1), 5)
    x = jax.random.normal(ks[0], (b, s, d))
    wq, wk, wv = (jax.random.normal(k, (d, d)) * 0.1 for k in ks[1:4])
    wo = jax.random.normal(ks[4], (d, d)) * 0.1

    def full(x):
        q, k, v = (
            (x @ w).reshape(b, s, heads, dh) for w in (wq, wk, wv))
        out = dot_product_attention(q, k, v, causal=True)
        return out.reshape(b, s, d) @ wo

    fn = _compat.shard_map(
        lambda x_, q_, k_, v_, o_: tp_self_attention(
            x_, q_, k_, v_, o_, num_local_heads=1, head_dim=dh,
            axis_name="model", causal=True, compute_dtype=jnp.float32),
        mesh=mesh,
        in_specs=(P(), P(None, "model"), P(None, "model"), P(None, "model"),
                  P("model", None)),
        out_specs=P())
    got = fn(x, wq, wk, wv, wo)
    np.testing.assert_allclose(np.asarray(got), np.asarray(full(x)),
                               atol=1e-4)


# ---------------------------------------------------------------------------
# expert parallelism
# ---------------------------------------------------------------------------

def test_top1_routing_capacity():
    logits = jnp.array([[9.0, 0.0], [8.0, 0.0], [7.0, 0.0], [0.0, 5.0]])
    dispatch, combine = top1_routing(logits, capacity=2)
    # tokens 0,1 land in expert 0 slots 0,1; token 2 dropped; token 3 → e1
    assert dispatch[0, 0, 0] == 1 and dispatch[1, 0, 1] == 1
    assert dispatch[2].sum() == 0
    assert dispatch[3, 1, 0] == 1
    gates = jax.nn.softmax(logits, -1)
    np.testing.assert_allclose(combine[3, 1, 0], gates[3, 1], atol=1e-6)


def _moe_reference(x, router_kernel, w1, b1, w2, b2, capacity,
                   shard_size):
    """Per-token top-1 expert MLP; tokens are routed in per-shard slices of
    ``shard_size`` with per-slice expert capacities (matching moe_mlp's
    token sharding over the expert axis)."""
    t, d = x.shape
    gates = jax.nn.softmax(x @ router_kernel, -1)
    expert = np.asarray(jnp.argmax(gates, -1))
    gate = np.asarray(jnp.max(gates, -1))
    out = np.zeros((t, d), np.float32)
    for start in range(0, t, shard_size):
        counts = {}
        for i in range(start, start + shard_size):
            e = int(expert[i])
            counts[e] = counts.get(e, 0) + 1
            if counts[e] > capacity:
                continue
            h = np.asarray(jax.nn.gelu(x[i] @ w1[e] + b1[e]))
            out[i] = (h @ w2[e] + b2[e]) * gate[i]
    return out


def test_moe_matches_reference(eight_devices):
    """8 experts over 8 devices, replicated input: all_to_all round-trip
    equals the per-token reference."""
    mesh = get_mesh(8, axis_name="model")
    b, s, d, f, e = 1, 16, 8, 16, 8
    ks = jax.random.split(jax.random.PRNGKey(2), 6)
    x = jax.random.normal(ks[0], (b, s, d))
    router = jax.random.normal(ks[1], (d, e))
    w1 = jax.random.normal(ks[2], (e, d, f)) * 0.2
    b1 = jax.random.normal(ks[3], (e, f)) * 0.1
    w2 = jax.random.normal(ks[4], (e, f, d)) * 0.2
    b2 = jax.random.normal(ks[5], (e, d)) * 0.1

    # each of the 8 shards routes 16/8 = 2 tokens;
    # capacity = ceil(2.0 * 2 / 8) = 1
    capacity = 1
    fn = _compat.shard_map(
        # the MoE output is identical on every device but shard_map
        # cannot infer that statically; psum/n makes replication provable
        lambda x_, r_, w1_, b1_, w2_, b2_: jax.lax.psum(moe_mlp(
            x_, r_, w1_, b1_, w2_, b2_, axis_name="model",
            capacity_factor=2.0, compute_dtype=jnp.float32)[0], "model") / 8,
        mesh=mesh,
        in_specs=(P(), P(), P("model"), P("model"), P("model"), P("model")),
        out_specs=P())
    got = np.asarray(fn(x, router, w1, b1, w2, b2)).reshape(b * s, d)
    want = _moe_reference(np.asarray(x).reshape(-1, d), np.asarray(router),
                          np.asarray(w1), np.asarray(b1), np.asarray(w2),
                          np.asarray(b2), capacity, shard_size=2)
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_moe_gradients_flow(eight_devices):
    mesh = get_mesh(8, axis_name="model")
    ks = jax.random.split(jax.random.PRNGKey(3), 6)
    x = jax.random.normal(ks[0], (1, 8, 8))
    router = jax.random.normal(ks[1], (8, 8))
    w1 = jax.random.normal(ks[2], (8, 8, 16)) * 0.2
    b1 = jnp.zeros((8, 16))
    w2 = jax.random.normal(ks[4], (8, 16, 8)) * 0.2
    b2 = jnp.zeros((8, 8))

    def loss(w1_):
        fn = _compat.shard_map(
            lambda x_, r_, a, b_, c, d_: jax.lax.psum(moe_mlp(
                x_, r_, a, b_, c, d_, axis_name="model",
                capacity_factor=2.0, compute_dtype=jnp.float32)[0],
                "model") / 8,
            mesh=mesh,
            in_specs=(P(), P(), P("model"), P("model"), P("model"),
                      P("model")),
            out_specs=P())
        return jnp.sum(fn(x, router, w1_, b1, w2, b2) ** 2)

    g = jax.grad(loss)(w1)
    assert np.isfinite(np.asarray(g)).all()
    assert float(jnp.abs(g).sum()) > 0


def test_topk_routing_aux_and_top2():
    from distkeras_tpu.parallel.moe import load_balance_loss, topk_routing
    # aux closed forms: uniform routing scores 1, full collapse scores ~E
    t, e = 8, 4
    uniform = jnp.zeros((t, e))
    _, _, stats_u = topk_routing(uniform, capacity=t, k=1)
    np.testing.assert_allclose(float(load_balance_loss(stats_u)), 1.0,
                               atol=1e-6)
    collapsed = jnp.tile(jnp.array([[50.0, 0, 0, 0]]), (t, 1))
    _, _, stats_c = topk_routing(collapsed, capacity=t, k=1)
    np.testing.assert_allclose(float(load_balance_loss(stats_c)), e,
                               rtol=1e-3)

    # top-2: each token reaches its two largest-gate experts, weights
    # renormalized to sum to 1
    logits = jnp.array([[3.0, 2.0, -50.0], [0.0, 1.0, 2.0]])
    dispatch, combine, _ = topk_routing(logits, capacity=2, k=2)
    assert dispatch[0, 0].sum() == 1 and dispatch[0, 1].sum() == 1
    assert dispatch[0, 2].sum() == 0
    assert dispatch[1, 2].sum() == 1 and dispatch[1, 1].sum() == 1
    g = jax.nn.softmax(logits, -1)
    np.testing.assert_allclose(
        float(combine[0].sum()), 1.0, atol=1e-5)  # renormalized pair
    np.testing.assert_allclose(
        float(combine[0, 0].sum()),
        float(g[0, 0] / (g[0, 0] + g[0, 1])), atol=1e-5)

    # capacity counts first-choice traffic before second choices: with
    # capacity=1, a second choice cannot evict a first choice
    crowd = jnp.array([[4.0, 0.0], [3.0, 0.0], [0.0, 3.0]])
    d2, _, _ = topk_routing(crowd, capacity=1, k=2)
    assert d2[0, 0].sum() == 1    # token 0 first-choice e0 kept
    assert d2[1, 0].sum() == 0    # token 1 first-choice e0 over capacity
    assert d2[2, 1].sum() == 1    # token 2 first-choice e1 kept
    # second choices (e1 for 0/1, e0 for 2) all hit full experts → dropped
    assert float(d2.sum()) == 2.0

    with pytest.raises(ValueError, match="router k"):
        topk_routing(logits, capacity=2, k=5)


def test_moe_aux_loss_prevents_expert_starvation(eight_devices):
    """Train a 2-expert MoE regression whose router starts collapsed onto
    expert 0; the Switch aux loss must revive expert 1 (utilization bounds)
    while the task loss still falls."""
    from distkeras_tpu.parallel.moe import moe_mlp
    mesh = get_mesh(2, axis_name="model")
    rng = np.random.default_rng(0)
    t, d, f = 64, 8, 16
    # two input clusters needing different linear maps; both have positive
    # mean so the biased router below prefers expert 0 for EVERY token
    # (the router is bias-free: logit_0 = 2·Σx > 0 > logit_1 for both)
    half = t // 2
    x = np.concatenate([rng.normal(1.0, 0.3, (half, d)),
                        rng.normal(0.3, 0.3, (t - half, d))]).astype(
                            np.float32)[None]                  # (1, T, D)
    w_a = rng.normal(0, 1, (d, d)).astype(np.float32)
    w_b = rng.normal(0, 1, (d, d)).astype(np.float32)
    y = np.concatenate([x[0, :half] @ w_a, x[0, half:] @ w_b])[None]

    params = {
        # collapsed start: every token prefers expert 0
        "router": jnp.concatenate([jnp.full((d, 1), 0.5),
                                   jnp.full((d, 1), -0.5)], axis=1),
        "w1": jnp.asarray(rng.normal(0, 0.2, (2, d, f)), jnp.float32),
        "b1": jnp.zeros((2, f)),
        "w2": jnp.asarray(rng.normal(0, 0.2, (2, f, d)), jnp.float32),
        "b2": jnp.zeros((2, d)),
    }

    from distkeras_tpu.parallel.moe import load_balance_loss

    def loss_fn(p, aux_weight):
        def local(x_, y_, r_, w1_, b1_, w2_, b2_):
            out, stats = moe_mlp(x_, r_, w1_, b1_, w2_, b2_,
                                 axis_name="model", capacity_factor=2.0,
                                 compute_dtype=jnp.float32)
            mse = jnp.mean((out - y_) ** 2)
            stats = jax.tree_util.tree_map(
                lambda v: jax.lax.pmean(v, "model"), stats)
            return (jax.lax.pmean(mse, "model")
                    + aux_weight * load_balance_loss(stats))
        fn = _compat.shard_map(
            local, mesh=mesh,
            in_specs=(P(), P(), P(), P("model"), P("model"), P("model"),
                      P("model")),
            out_specs=P())
        return fn(jnp.asarray(x), jnp.asarray(y), p["router"], p["w1"],
                  p["b1"], p["w2"], p["b2"])

    def utilization(p):
        gates = np.asarray(
            jax.nn.softmax(jnp.asarray(x[0]) @ p["router"], axis=-1))
        frac = np.bincount(gates.argmax(-1), minlength=2) / t
        return frac

    assert utilization(params)[0] == 1.0  # collapsed before training

    import optax

    def train(aux_weight, steps=200):
        tx = optax.adam(3e-2)
        opt = tx.init(params)

        @jax.jit
        def step(p, o):
            l, g = jax.value_and_grad(
                lambda q: loss_fn(q, aux_weight))(p)
            updates, o = tx.update(g, o, p)
            return l, optax.apply_updates(p, updates), o

        p, first = params, None
        for _ in range(steps):
            l, p, opt = step(p, opt)
            first = float(l) if first is None else first
        return p, first, float(l)

    # aux weight sized to the toy mse scale (~1e1 vs aux ∈ [1, 2])
    p_aux, first, last = train(aux_weight=0.1)
    frac = utilization(p_aux)
    assert frac.min() >= 0.2, f"expert starved: utilization {frac}"
    assert last < first
    # contrast: without the aux term the same run stays collapsed — the
    # balance really comes from the loss, not from the task gradient
    p_no, _, _ = train(aux_weight=0.0)
    assert utilization(p_no).max() > 0.85


# ---------------------------------------------------------------------------
# pipeline parallelism
# ---------------------------------------------------------------------------

def test_pipeline_matches_sequential(eight_devices):
    """4-stage MLP pipeline over microbatches == sequential composition."""
    devs = jax.devices()[:4]
    mesh = Mesh(np.array(devs), ("stage",))
    d, micro_b, m = 8, 4, 6
    ks = jax.random.split(jax.random.PRNGKey(4), 5)
    ws = jax.random.normal(ks[0], (4, d, d)) * 0.3
    x = jax.random.normal(ks[1], (m, micro_b, d))

    def stage_fn(w, h):
        return jnp.tanh(h @ w)

    def sequential(x):
        h = x
        for i in range(4):
            h = stage_fn(ws[i], h)
        return h

    fn = _compat.shard_map(
        # outputs are zeros on all but the last stage, so a psum over the
        # stage axis replicates the result for out_specs=P()
        lambda w, xm: jax.lax.psum(
            pipeline_apply(stage_fn, w[0], xm, axis_name="stage"), "stage"),
        mesh=mesh, in_specs=(P("stage"), P()), out_specs=P())
    got = fn(ws, x)
    want = jax.vmap(sequential)(x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_pipeline_gradients(eight_devices):
    devs = jax.devices()[:4]
    mesh = Mesh(np.array(devs), ("stage",))
    d, micro_b, m = 4, 2, 4
    ws = jax.random.normal(jax.random.PRNGKey(5), (4, d, d)) * 0.3
    x = jax.random.normal(jax.random.PRNGKey(6), (m, micro_b, d))

    def stage_fn(w, h):
        return jnp.tanh(h @ w)

    def loss_pipe(ws_):
        fn = _compat.shard_map(
            lambda w, xm: jax.lax.psum(
                pipeline_apply(stage_fn, w[0], xm, axis_name="stage"),
                "stage"),
            mesh=mesh, in_specs=(P("stage"), P()), out_specs=P())
        return jnp.sum(fn(ws_, x) ** 2)

    def loss_seq(ws_):
        h = x
        for i in range(4):
            h = jax.vmap(lambda hh: stage_fn(ws_[i], hh))(h)
        return jnp.sum(h.astype(jnp.float32) ** 2)

    gp = jax.grad(loss_pipe)(ws)
    gs = jax.grad(loss_seq)(ws)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gs), atol=1e-4)


def test_pipeline_transformer_matches_sequential(eight_devices):
    """The integrated dp x pp transformer (round-3): the pipelined loss and
    gradients equal the sequential single-device reference on the same
    params, and one optimizer step runs end to end."""
    import optax
    from jax.sharding import Mesh, PartitionSpec as P
    from distkeras_tpu.parallel.pp_transformer import PipelineTransformerLM

    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("data", "stage"))
    lm = PipelineTransformerLM(
        vocab_size=32, seq_len=16, d_model=16, num_heads=2, num_layers=4,
        mlp_dim=32, mesh=mesh, num_microbatches=2,
        compute_dtype=jnp.float32)
    params = lm.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, 32, (8, 16)), jnp.int32)
    labels = (tokens + 1) % 32

    # pipelined loss+grads via shard_map
    pipelined = jax.jit(_compat.shard_map(
        jax.value_and_grad(lm._local_loss), mesh=mesh,
        in_specs=(lm.param_specs(), P("data"), P("data")),
        out_specs=(P(), lm.param_specs())))
    loss_p, grads_p = pipelined(params, tokens, labels)

    loss_r, grads_r = jax.value_and_grad(lm.reference_forward_loss)(
        jax.device_get(params), tokens, labels)

    np.testing.assert_allclose(float(loss_p), float(loss_r), rtol=1e-5)
    for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_flatten_with_path(jax.device_get(grads_p))[0],
            jax.tree_util.tree_flatten_with_path(grads_r)[0]):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5,
            err_msg=str(pa))

    # remat=True recomputes stage internals in backward — same grads
    # (before the optimizer step below donates the params buffers)
    lm_r = PipelineTransformerLM(
        vocab_size=32, seq_len=16, d_model=16, num_heads=2, num_layers=4,
        mlp_dim=32, mesh=mesh, num_microbatches=2,
        compute_dtype=jnp.float32, remat=True)
    loss_m, grads_m = jax.jit(_compat.shard_map(
        jax.value_and_grad(lm_r._local_loss), mesh=mesh,
        in_specs=(lm_r.param_specs(), P("data"), P("data")),
        out_specs=(P(), lm_r.param_specs())))(params, tokens, labels)
    np.testing.assert_allclose(float(loss_m), float(loss_r), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(grads_m)),
                    jax.tree_util.tree_leaves(jax.device_get(grads_p))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)

    # and a full optimizer step executes
    opt_state, step = lm.compile_train_step(optax.adam(1e-3), params)
    params2, opt_state, loss = step(params, opt_state, tokens, labels)
    assert np.isfinite(float(loss))
    # stage-sharded layer params actually moved
    w_before = np.asarray(jax.device_get(
        lm.init(jax.random.PRNGKey(0))["layers"]["wq"]))
    w_after = np.asarray(jax.device_get(params2["layers"]["wq"]))
    assert not np.allclose(w_before, w_after)

    # analytic bubble fraction: (n-1)/(M+n-1)
    assert lm.bubble_fraction() == pytest.approx(3 / 5)
    assert PipelineTransformerLM(
        vocab_size=32, seq_len=16, d_model=16, num_heads=2, num_layers=4,
        mlp_dim=32, mesh=mesh,
        num_microbatches=8).bubble_fraction() == pytest.approx(3 / 11)


def test_pipeline_1f1b_toy_grads_match_autodiff(eight_devices):
    """pipeline_1f1b's hand-built backward == jax.grad of the sequential
    program on a toy stage stack: loss, per-stage grads, head grads, and
    the input cotangent all match."""
    from distkeras_tpu.parallel.pipeline import pipeline_1f1b

    n, m, micro_b, d = 4, 6, 2, 4
    mesh = Mesh(np.array(jax.devices()[:n]), ("stage",))
    ws = jax.random.normal(jax.random.PRNGKey(5), (n, d, d)) * 0.3
    x = jax.random.normal(jax.random.PRNGKey(6), (m, micro_b, d))
    labels = jax.random.normal(jax.random.PRNGKey(7), (m, micro_b, d))
    head = {"scale": jnp.asarray(1.5)}

    def stage_fn(w, h):
        return jnp.tanh(h @ w)

    def head_loss(hp, y, lbl):
        return jnp.sum((hp["scale"] * y - lbl) ** 2)

    def local(w, h_, xm, lm_):
        loss, dstage, dhead, dx = pipeline_1f1b(
            stage_fn, w[0], xm, lm_, head_loss, h_, axis_name="stage")
        lead = lambda t: jax.tree_util.tree_map(lambda v: v[None], t)
        return loss[None], lead(dstage), lead(dhead), lead(dx)

    fn = jax.jit(_compat.shard_map(
        local, mesh=mesh, in_specs=(P("stage"), P(), P(), P()),
        out_specs=(P("stage"),) * 4))
    loss, dstage, dhead, dx = fn(ws, head, x, labels)

    def seq_loss(ws_, head_, x_):
        h = x_
        for i in range(n):
            h = jax.vmap(lambda hh: stage_fn(ws_[i], hh))(h)
        return sum(head_loss(head_, h[j], labels[j]) for j in range(m))

    loss_o, (dws_o, dhead_o, dx_o) = jax.value_and_grad(
        seq_loss, argnums=(0, 1, 2))(ws, head, x)
    np.testing.assert_allclose(float(loss[n - 1]), float(loss_o), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(dstage), np.asarray(dws_o),
                               atol=1e-4)
    np.testing.assert_allclose(float(dhead["scale"][n - 1]),
                               float(dhead_o["scale"]), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(dx[0]), np.asarray(dx_o),
                               atol=1e-4)

    # edge cases: fewer microbatches than stages (M=2 < n=4, the ring is
    # mostly bubble) and the degenerate single-stage "ring" (n=1)
    for n_e, m_e in ((4, 2), (1, 3)):
        mesh_e = Mesh(np.array(jax.devices()[:n_e]), ("stage",))
        ws_e = ws[:n_e]
        x_e, l_e = x[:m_e], labels[:m_e]

        def local_e(w, h_, xm, lm_):
            loss, dstage, dhead, dx = pipeline_1f1b(
                stage_fn, w[0], xm, lm_, head_loss, h_,
                axis_name="stage")
            lead = lambda t: jax.tree_util.tree_map(lambda v: v[None], t)
            return loss[None], lead(dstage), lead(dhead), lead(dx)

        fn_e = jax.jit(_compat.shard_map(
            local_e, mesh=mesh_e, in_specs=(P("stage"), P(), P(), P()),
            out_specs=(P("stage"),) * 4))
        loss_e, dstage_e, _, dx_e = fn_e(ws_e, head, x_e, l_e)

        def seq_e(ws_, head_, x_):
            h = x_
            for i in range(n_e):
                h = jax.vmap(lambda hh: stage_fn(ws_[i], hh))(h)
            return sum(head_loss(head_, h[j], l_e[j]) for j in range(m_e))

        lo, (dws_o2, _, dx_o2) = jax.value_and_grad(
            seq_e, argnums=(0, 1, 2))(ws_e, head, x_e)
        np.testing.assert_allclose(float(loss_e[n_e - 1]), float(lo),
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(dstage_e),
                                   np.asarray(dws_o2), atol=1e-4)
        np.testing.assert_allclose(np.asarray(dx_e[0]), np.asarray(dx_o2),
                                   atol=1e-4)


def test_pipeline_1f1b_lm_matches_gpipe(eight_devices):
    """The 1F1B dp×pp LM: loss and ALL gradients equal the GPipe autodiff
    path (itself oracle-checked against the sequential reference), with
    more microbatches than stages (M=8 > n=4 — the regime where 1F1B's
    O(n) activation buffer actually differs from O(M)), and training
    converges through compile_train_step."""
    import optax
    from distkeras_tpu.parallel.pp_transformer import PipelineTransformerLM

    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("data", "stage"))
    kw = dict(vocab_size=32, seq_len=16, d_model=16, num_heads=2,
              num_layers=4, mlp_dim=32, mesh=mesh, num_microbatches=8,
              compute_dtype=jnp.float32)
    lm_g = PipelineTransformerLM(**kw)
    lm_1 = PipelineTransformerLM(**kw, schedule="1f1b")
    params = lm_g.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, 32, (16, 16)), jnp.int32)
    labels = (tokens + 1) % 32

    loss_g, grads_g = jax.jit(_compat.shard_map(
        jax.value_and_grad(lm_g._local_loss), mesh=mesh,
        in_specs=(lm_g.param_specs(), P("data"), P("data")),
        out_specs=(P(), lm_g.param_specs())))(params, tokens, labels)
    loss_1, grads_1 = jax.jit(_compat.shard_map(
        lm_1._local_loss_and_grads_1f1b, mesh=mesh,
        in_specs=(lm_1.param_specs(), P("data"), P("data")),
        out_specs=(P(), lm_1.param_specs())))(params, tokens, labels)
    np.testing.assert_allclose(float(loss_1), float(loss_g), rtol=1e-5)
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(
                jax.device_get(grads_g))[0],
            jax.tree_util.tree_flatten_with_path(
                jax.device_get(grads_1))[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, err_msg=str(pa))

    # remat composes (same grads, tick inputs re-linearized)
    lm_r = PipelineTransformerLM(**kw, schedule="1f1b", remat=True)
    _, grads_r = jax.jit(_compat.shard_map(
        lm_r._local_loss_and_grads_1f1b, mesh=mesh,
        in_specs=(lm_r.param_specs(), P("data"), P("data")),
        out_specs=(P(), lm_r.param_specs())))(params, tokens, labels)
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(grads_r)),
                    jax.tree_util.tree_leaves(jax.device_get(grads_1))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)

    # the compiled 1F1B train step trains
    opt_state, step = lm_1.compile_train_step(optax.adam(1e-2), params)
    losses = []
    for _ in range(20):
        params, opt_state, loss = step(params, opt_state, tokens, labels)
        losses.append(float(loss))
    assert losses[-1] < 0.5 * losses[0], losses

    # schedule-aware analytic bubble: 2(n-1)/(M+2(n-1)) for 1F1B
    assert lm_1.bubble_fraction() == pytest.approx(6 / 14)
    assert lm_g.bubble_fraction() == pytest.approx(3 / 11)

    with pytest.raises(ValueError, match="schedule"):
        PipelineTransformerLM(**kw, schedule="interleaved")


def test_pipeline_3d_dp_pp_tp(eight_devices):
    """3-D parallelism: Megatron tensor parallelism inside each pipeline
    stage over a ('data', 'stage', 'model') mesh.  Loss/grads of the
    sharded GPipe program equal the dense single-device oracle, the 1F1B
    schedule equals GPipe, weights are really model-split, and the train
    step converges."""
    import optax
    from distkeras_tpu.parallel.pp_transformer import PipelineTransformerLM

    devs = np.array(jax.devices()[:8]).reshape(2, 2, 2)
    mesh = Mesh(devs, ("data", "stage", "model"))
    kw = dict(vocab_size=32, seq_len=16, d_model=16, num_heads=2,
              num_layers=2, mlp_dim=32, mesh=mesh, num_microbatches=2,
              compute_dtype=jnp.float32, model_axis="model")
    lm = PipelineTransformerLM(**kw)
    params = lm.init(jax.random.PRNGKey(0))
    # column split: wq (2 stages, 1 layer, 16, 16) → local (1, 1, 16, 8)
    assert params["layers"]["wq"].addressable_shards[0].data.shape \
        == (1, 1, 16, 8)
    assert params["layers"]["w2"].addressable_shards[0].data.shape \
        == (1, 1, 16, 16)  # row split on mlp_dim 32 → 16

    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, 32, (8, 16)), jnp.int32)
    labels = (tokens + 1) % 32

    loss_g, grads_g = jax.jit(_compat.shard_map(
        jax.value_and_grad(lm._local_loss), mesh=mesh,
        in_specs=(lm.param_specs(), P("data"), P("data")),
        out_specs=(P(), lm.param_specs())))(params, tokens, labels)
    # dense oracle on the gathered full-width params
    loss_r, grads_r = jax.value_and_grad(lm.reference_forward_loss)(
        jax.device_get(params), tokens, labels)
    np.testing.assert_allclose(float(loss_g), float(loss_r), rtol=1e-5)
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(
                jax.device_get(grads_g))[0],
            jax.tree_util.tree_flatten_with_path(grads_r)[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, err_msg=str(pa))

    # 1F1B under tp: same loss/grads as the GPipe autodiff path
    lm1 = PipelineTransformerLM(**kw, schedule="1f1b")
    loss_1, grads_1 = jax.jit(_compat.shard_map(
        lm1._local_loss_and_grads_1f1b, mesh=mesh,
        in_specs=(lm1.param_specs(), P("data"), P("data")),
        out_specs=(P(), lm1.param_specs())))(params, tokens, labels)
    np.testing.assert_allclose(float(loss_1), float(loss_g), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(grads_1)),
                    jax.tree_util.tree_leaves(jax.device_get(grads_g))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)

    # remat composes with the tp stage under the manual 1F1B backward
    lm_r = PipelineTransformerLM(**kw, schedule="1f1b", remat=True)
    loss_m, grads_m = jax.jit(_compat.shard_map(
        lm_r._local_loss_and_grads_1f1b, mesh=mesh,
        in_specs=(lm_r.param_specs(), P("data"), P("data")),
        out_specs=(P(), lm_r.param_specs())))(params, tokens, labels)
    np.testing.assert_allclose(float(loss_m), float(loss_g), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(grads_m)),
                    jax.tree_util.tree_leaves(jax.device_get(grads_1))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)

    # the compiled 3-D train step converges
    opt_state, step = lm1.compile_train_step(optax.adam(1e-2), params)
    toks_d = jax.device_put(tokens, lm1.batch_sharding())
    labels_d = jax.device_put(labels, lm1.batch_sharding())
    losses = []
    for _ in range(25):
        params, opt_state, loss = step(params, opt_state, toks_d, labels_d)
        losses.append(float(loss))
    assert losses[-1] < 0.4 * losses[0], losses

    with pytest.raises(ValueError, match="num_heads"):
        PipelineTransformerLM(**{**kw, "num_heads": 1})  # 1 % tp=2 != 0
