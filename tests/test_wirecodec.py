"""Native C++ wire codec (csrc/wirecodec.cpp) vs the pure-Python codec.

The two implementations must be byte-identical on the wire (either end of a
host-PS connection may run either one).  Builds the extension in place if it
isn't already built; skips gracefully where no toolchain exists.

The ``codec`` fixture parametrizes the shared contract tests over BOTH
implementations — forcing ``networking._native = None`` routes every encode,
decode, and pooled-payload split through the pure-Python fallback
(``_decode_payload_py`` included), so the fallback can't rot unexercised on
machines where the native extension is always importable.
"""

import socket
import subprocess
import sys
import threading

import numpy as np
import pytest

from distkeras_tpu import networking


def _ensure_native():
    if networking._native is not None:
        return networking._native
    r = subprocess.run(
        [sys.executable, "setup.py", "build_ext", "--inplace"],
        cwd=networking.__file__.rsplit("/", 2)[0], capture_output=True)
    if r.returncode != 0:
        pytest.skip(f"no native toolchain: {r.stderr[-200:]}")
    import importlib
    import distkeras_tpu._wirecodec as native
    networking._native = native
    return native


@pytest.fixture()
def native():
    old = networking._native
    yield _ensure_native()
    networking._native = old


@pytest.fixture(params=["python", "native"])
def codec(request):
    """Force one codec implementation for the duration of a test: 'python'
    nulls the native module (every path falls back to the pure-Python twin,
    ``_decode_payload_py`` included); 'native' requires/builds the
    extension."""
    old = networking._native
    networking._native = None if request.param == "python" \
        else _ensure_native()
    yield request.param
    networking._native = old


MESSAGE = {
    "weights": [np.arange(12, dtype=np.float32).reshape(3, 4),
                np.ones((5,), np.float64)],
    "clock": 7,
    "tag": "commit",
    "nested": {"t": (1, 2.5, None), "flag": True},
}


def test_native_and_python_bytes_identical(native):
    networking._native = native
    enc_native = networking.encode_message(MESSAGE)
    networking._native = None
    enc_python = networking.encode_message(MESSAGE)
    assert enc_native == enc_python


def test_cross_decoding(native):
    """Python-encoded → native-decoded and vice versa."""
    networking._native = None
    blob_py = networking.encode_message(MESSAGE)
    networking._native = native
    out = networking.decode_message(blob_py)
    np.testing.assert_array_equal(out["weights"][0], MESSAGE["weights"][0])
    assert out["nested"]["t"] == (1, 2.5, None)

    blob_nat = networking.encode_message(MESSAGE)
    networking._native = None
    out2 = networking.decode_message(blob_nat)
    np.testing.assert_array_equal(out2["weights"][1], MESSAGE["weights"][1])
    assert out2["clock"] == 7 and out2["tag"] == "commit"


def test_native_rejects_corrupt_frames(native):
    networking._native = native
    blob = bytearray(networking.encode_message(MESSAGE))
    with pytest.raises(ValueError, match="magic"):
        networking.decode_message(b"XXXX" + bytes(blob[4:]))
    with pytest.raises(ValueError):
        networking.decode_message(bytes(blob[:len(blob) - 3]))  # truncated


def test_native_decode_zero_copy(native):
    header, views = native.decode_frames(
        networking.encode_message(MESSAGE))
    assert all(isinstance(v, memoryview) for v in views)
    assert views[0].nbytes == 12 * 4


def test_roundtrip_large_delta(native):
    """Weight-delta-shaped message (the PS hot path) round-trips exactly."""
    networking._native = native
    rng = np.random.default_rng(0)
    delta = [rng.standard_normal((500, 500)).astype(np.float32),
             rng.standard_normal((500,)).astype(np.float32)]
    out = networking.decode_message(
        networking.encode_message({"delta": delta, "worker": 3}))
    for a, b in zip(out["delta"], delta):
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# contract tests, parametrized over BOTH codec implementations
# ---------------------------------------------------------------------------

def test_roundtrip_either_codec(codec):
    out = networking.decode_message(networking.encode_message(MESSAGE))
    np.testing.assert_array_equal(out["weights"][0], MESSAGE["weights"][0])
    np.testing.assert_array_equal(out["weights"][1], MESSAGE["weights"][1])
    assert out["clock"] == 7 and out["nested"]["t"] == (1, 2.5, None)


def test_payload_decode_either_codec(codec):
    """decode_payload (the pooled-receive frame splitter) splits and
    truncation-checks identically on both implementations."""
    payload = b"".join(len(x).to_bytes(8, "little") + x
                       for x in (b"abc", b"", b"0123456789"))
    assert [bytes(v) for v in networking.decode_payload(payload)] == \
        [b"abc", b"", b"0123456789"]
    with pytest.raises(ValueError, match="Truncated"):
        networking.decode_payload(payload[:-3])


def test_pooled_recv_either_codec(codec):
    """The zero-copy pooled receive path (recv_data(pool=...) →
    decode_payload) works — and reuses its buffer — on both codecs."""
    pool = networking.BufferPool()
    a, b = socket.socketpair()
    msg = {"weights": [np.arange(24, dtype=np.float32).reshape(4, 6)],
           "clock": 5}
    try:
        for _ in range(2):
            t = threading.Thread(target=networking.send_data, args=(a, msg))
            t.start()
            out = networking.recv_data(b, pool=pool)
            t.join()
            np.testing.assert_array_equal(out["weights"][0],
                                          msg["weights"][0])
            assert out["clock"] == 5
        assert pool.misses == 1 and pool.hits == 1
        assert not out["weights"][0].flags["OWNDATA"]  # view into the pool
    finally:
        a.close()
        b.close()


def test_rejects_corrupt_frames_either_codec(codec):
    blob = networking.encode_message(MESSAGE)
    with pytest.raises(ValueError, match="magic"):
        networking.decode_message(b"XXXX" + blob[4:])
    with pytest.raises(ValueError):
        networking.decode_message(blob[:len(blob) - 3])  # truncated


SPARSE_MESSAGE = {
    "delta": networking.SparseDelta(
        np.array([0, 3, 7, 12], np.int32),
        np.array([0.5, -1.25, 2.0, -3.5], np.float32), 20),
    "coded": networking.SparseDelta(
        np.array([1, 2], np.int32), np.array([10, -20], np.int8), 6,
        scale=0.25),
    "worker_id": 1,
    "clock": 4,
}


def test_sparse_node_roundtrip_either_codec(codec):
    """The sparse payload node (indices + values + dense length, optional
    value scale) survives both codec implementations bit for bit."""
    out = networking.decode_message(networking.encode_message(SPARSE_MESSAGE))
    sp = out["delta"]
    assert isinstance(sp, networking.SparseDelta)
    np.testing.assert_array_equal(sp.indices,
                                  SPARSE_MESSAGE["delta"].indices)
    np.testing.assert_array_equal(sp.values, SPARSE_MESSAGE["delta"].values)
    assert sp.length == 20 and sp.scale is None
    coded = out["coded"]
    assert coded.values.dtype == np.int8 and coded.scale == 0.25
    np.testing.assert_allclose(coded.f32_values(), [2.5, -5.0])


def test_sparse_node_pooled_recv_either_codec(codec):
    """A sparse commit received through the zero-copy pooled path decodes to
    views over the pool; .decoded() detaches them for use past the next
    receive."""
    pool = networking.BufferPool()
    a, b = socket.socketpair()
    try:
        for _ in range(2):
            t = threading.Thread(target=networking.send_data,
                                 args=(a, SPARSE_MESSAGE))
            t.start()
            out = networking.recv_data(b, pool=pool)
            t.join()
            sp = out["delta"]
            np.testing.assert_array_equal(
                sp.indices, SPARSE_MESSAGE["delta"].indices)
            assert not sp.values.flags["OWNDATA"]  # view into the pool
            detached = sp.decoded()
            assert detached.values.flags["OWNDATA"]
        assert pool.misses == 1 and pool.hits == 1
    finally:
        a.close()
        b.close()


def test_encode_pool_bytes_identical_either_codec(codec):
    """The encode-side scratch pool (send-path satellite) produces byte-
    identical frames to the plain encoder, and reuses its buffer."""
    pool = networking.BufferPool()
    for msg in (MESSAGE, SPARSE_MESSAGE):
        plain = networking.encode_message(msg)
        assert bytes(networking.encode_message_into(msg, pool)) == plain
        assert bytes(networking.encode_message_into(msg, pool)) == plain
    assert pool.hits == 2  # one reuse per message size


def test_sparse_dense_equivalence_fuzz(codec):
    """Randomized dense↔sparse equivalence (fixed seed): for random tensor
    lists, densities, and value codings, selecting with topk_select,
    shipping through the codec, and scatter-adding on the far side equals
    the dense apply of the densified delta — and the EF invariant
    eff == applied + residual holds to coding precision."""
    from distkeras_tpu.parameter_servers import _scatter_add
    from distkeras_tpu.workers import topk_select

    rng = np.random.default_rng(1234)
    for trial in range(10):
        nt = rng.integers(1, 5)
        shapes = [tuple(rng.integers(1, 9, rng.integers(0, 3)))
                  for _ in range(nt)]
        total = sum(int(np.prod(s)) for s in shapes)
        eff = (rng.standard_normal(total) * 10.0 ** rng.integers(-3, 2)
               ).astype(np.float32)
        k = int(rng.integers(1, total + 1))
        code = [None, "bfloat16", "int8"][trial % 3]
        idx, wire, applied, scale, res = topk_select(eff, k, code)
        dense = np.zeros(total, np.float32)
        dense[idx] = applied
        np.testing.assert_allclose(eff, dense + res, atol=1e-6)
        sp = networking.decode_message(networking.encode_message(
            {"d": networking.SparseDelta(idx, wire, total, scale)}))["d"]
        center = [rng.standard_normal(s).astype(np.float32) for s in shapes]
        expect = [c.copy() for c in center]
        scale_f = float(rng.uniform(0.25, 2.0))
        _scatter_add(center, sp, scale_f)
        off = 0
        for c in expect:
            c += scale_f * dense[off:off + c.size].reshape(c.shape)
            off += c.size
        for got, want in zip(center, expect):
            np.testing.assert_allclose(got, want, atol=1e-5)


ROW_SPARSE_MESSAGE = {
    "delta": [np.ones((3,), np.float32),
              networking.RowSparseDelta(
                  np.array([0, 4, 9], np.int32),
                  np.arange(12, dtype=np.float32).reshape(3, 4), 16)],
    "worker_id": 2,
    "clock": 5,
}


def test_row_sparse_node_roundtrip_either_codec(codec):
    """The row-sparse payload node (rows + (k, dim) value block + dense row
    count) survives both codec implementations bit for bit, embedded in a
    mixed dense+row-sparse delta list (the wire form of a row_sparse
    commit)."""
    out = networking.decode_message(
        networking.encode_message(ROW_SPARSE_MESSAGE))
    dense, rsp = out["delta"]
    np.testing.assert_array_equal(dense, ROW_SPARSE_MESSAGE["delta"][0])
    assert isinstance(rsp, networking.RowSparseDelta)
    want = ROW_SPARSE_MESSAGE["delta"][1]
    np.testing.assert_array_equal(rsp.rows, want.rows)
    np.testing.assert_array_equal(rsp.values, want.values)
    assert rsp.num_rows == 16 and rsp.row_shape == (4,)
    np.testing.assert_array_equal(rsp.to_dense()[want.rows], want.values)


def test_row_sparse_node_pooled_recv_either_codec(codec):
    """A row-sparse commit through the zero-copy pooled path decodes to
    views over the pool; .decoded() detaches them."""
    pool = networking.BufferPool()
    a, b = socket.socketpair()
    try:
        for _ in range(2):
            t = threading.Thread(target=networking.send_data,
                                 args=(a, ROW_SPARSE_MESSAGE))
            t.start()
            out = networking.recv_data(b, pool=pool)
            t.join()
            rsp = out["delta"][1]
            assert not rsp.values.flags["OWNDATA"]  # view into the pool
            detached = rsp.decoded()
            assert detached.values.flags["OWNDATA"]
            np.testing.assert_array_equal(
                detached.values, ROW_SPARSE_MESSAGE["delta"][1].values)
        assert pool.misses == 1 and pool.hits == 1
    finally:
        a.close()
        b.close()


def test_row_sparse_slice_rows():
    """Shard splitting by row range: local re-indexing, empty middles,
    boundary rows land exactly once."""
    rsp = networking.RowSparseDelta(
        np.array([0, 4, 9, 10], np.int32),
        np.arange(8, dtype=np.float32).reshape(4, 2), 12)
    lo = rsp.slice_rows(0, 5)
    np.testing.assert_array_equal(lo.rows, [0, 4])
    hi = rsp.slice_rows(5, 12)
    np.testing.assert_array_equal(hi.rows, [4, 5])
    assert lo.num_rows == 5 and hi.num_rows == 7
    full = np.zeros((12, 2), np.float32)
    full[:5] += lo.to_dense()
    full[5:] += hi.to_dense()
    np.testing.assert_array_equal(full, rsp.to_dense())
    empty = rsp.slice_rows(5, 9)
    assert empty.nnz == 0 and empty.num_rows == 4


# --- decode guards: duplicate/negative/out-of-range/unsorted indices must
# --- reject with the typed ProtocolError, never corrupt the center

def _sp(idx, length=16):
    return networking.SparseDelta(np.asarray(idx, np.int32),
                                  np.ones(len(idx), np.float32), length)


def _rsp(rows, num_rows=16):
    return networking.RowSparseDelta(
        np.asarray(rows, np.int32),
        np.ones((len(rows), 3), np.float32), num_rows)


@pytest.mark.parametrize("make,label", [
    (lambda: _sp([3, 3, 7]), "duplicate"),
    (lambda: _sp([-1, 2, 7]), "negative"),
    (lambda: _sp([2, 7, 16]), "out-of-range"),
    (lambda: _sp([7, 2, 3]), "unsorted"),
    (lambda: _rsp([3, 3, 7]), "row-duplicate"),
    (lambda: _rsp([-1, 2, 7]), "row-negative"),
    (lambda: _rsp([2, 7, 16]), "row-out-of-range"),
    (lambda: _rsp([7, 2, 3]), "row-unsorted"),
])
def test_sparse_guard_rejects_bad_indices_either_codec(make, label, codec):
    """Hostile/corrupt index vectors survive the codec (the codec frames
    buffers, it doesn't interpret them) but validate() rejects them with
    the typed ProtocolError — a ValueError subclass, so every server
    handler's torn-frame path drops the connection."""
    node = make()
    out = networking.decode_message(
        networking.encode_message({"delta": node}))["delta"]
    with pytest.raises(networking.ProtocolError):
        out.validate()
    assert isinstance(networking.ProtocolError("x"), ValueError)


def test_sparse_guard_fuzz_valid_commits_pass(codec):
    """Randomized valid commits (sorted unique in-range indices) always
    pass validation after a codec round trip — the guard rejects only
    contract violations."""
    rng = np.random.default_rng(7)
    for _ in range(20):
        length = int(rng.integers(4, 200))
        k = int(rng.integers(0, min(length, 32) + 1))
        idx = np.sort(rng.choice(length, size=k, replace=False)).astype(
            np.int32)
        sp = networking.SparseDelta(idx, rng.standard_normal(k).astype(
            np.float32), length)
        networking.decode_message(networking.encode_message(
            {"d": sp}))["d"].validate()
        rows = int(rng.integers(2, 50))
        kk = int(rng.integers(0, rows + 1))
        rr = np.sort(rng.choice(rows, size=kk, replace=False)).astype(
            np.int32)
        rsp = networking.RowSparseDelta(
            rr, rng.standard_normal((kk, 3)).astype(np.float32), rows)
        networking.decode_message(networking.encode_message(
            {"d": rsp}))["d"].validate()


def test_sparse_guard_fuzz_corrupted_commits_reject(codec):
    """Fuzz: valid commits corrupted at a random index position (dup /
    negate / overflow) must reject after the round trip."""
    rng = np.random.default_rng(13)
    for trial in range(30):
        length = int(rng.integers(8, 100))
        k = int(rng.integers(2, min(length, 16) + 1))
        idx = np.sort(rng.choice(length, size=k, replace=False)).astype(
            np.int64)
        pos = int(rng.integers(0, k))
        kind = trial % 3
        if kind == 0:
            idx[pos] = idx[(pos + 1) % k]  # duplicate
        elif kind == 1:
            idx[pos] = -1 - idx[pos]  # negative
        else:
            idx[pos] = length + int(rng.integers(0, 5))  # out of range
        row_form = trial % 2 == 0
        if row_form:
            node = networking.RowSparseDelta(
                idx, np.ones((k, 2), np.float32), length)
        else:
            node = networking.SparseDelta(
                idx, np.ones(k, np.float32), length)
        out = networking.decode_message(
            networking.encode_message({"d": node}))["d"]
        with pytest.raises(networking.ProtocolError):
            out.validate()


# serving-protocol messages ('q' enqueue / 'r' stream reply —
# networking.SERVING_OP_ENQUEUE / SERVING_OP_STREAM): the request, ack,
# backpressure, chunk, and final frames the serving server exchanges must
# round-trip BOTH codec implementations unchanged (either end of a serving
# connection may run either one).

SERVING_FRAMES = [
    {"prompt": np.array([3, 4, 5, 6], np.int32), "num_steps": 16,
     "temperature": 0.7, "top_k": 5, "top_p": 0.9, "eos_id": 2,
     "pad_id": 0, "seed": 11},
    {"prompt": np.array([1], np.int32), "num_steps": 1},  # minimal request
    {"ok": True, "id": 7},
    {"ok": False, "error": "queue full"},                 # backpressure
    {"id": 7, "tokens": np.array([9, 4, 1], np.int32), "done": False},
    {"id": 7, "tokens": np.array([], np.int32), "done": True,
     "finish": "eos", "row": np.array([3, 4, 5, 6, 9, 4, 1, 2], np.int32)},
]


def test_serving_frames_roundtrip_either_codec(codec):
    assert len(networking.SERVING_OP_ENQUEUE) == 1
    assert len(networking.SERVING_OP_STREAM) == 1
    for frame in SERVING_FRAMES:
        out = networking.decode_message(networking.encode_message(frame))
        assert out.keys() == frame.keys()
        for key, want in frame.items():
            if isinstance(want, np.ndarray):
                np.testing.assert_array_equal(out[key], want)
                assert out[key].dtype == want.dtype
            else:
                assert out[key] == want and type(out[key]) is type(want)


def test_serving_frames_pooled_socket_roundtrip_either_codec(codec):
    """The serving wire pattern end to end: every frame kind through a
    socket with pooled receive AND pooled send, twice (buffer reuse)."""
    recv_pool = networking.BufferPool()
    send_pool = networking.BufferPool()
    a, b = socket.socketpair()
    try:
        for _ in range(2):
            for frame in SERVING_FRAMES:
                t = threading.Thread(target=networking.send_data,
                                     args=(a, frame),
                                     kwargs={"pool": send_pool})
                t.start()
                out = networking.recv_data(b, pool=recv_pool)
                t.join()
                assert out.keys() == frame.keys()
    finally:
        a.close()
        b.close()
    assert recv_pool.hits > 0 and send_pool.hits > 0


def test_buffer_pool_concurrent_get_safe():
    """BufferPool.get is thread-safe (the serving server's per-connection
    reuse pattern has several threads alive against pools): concurrent
    distinct-size acquisitions under an eviction-prone max_idle must not
    corrupt the bookkeeping dicts or lose buffers."""
    pool = networking.BufferPool(max_idle=4)
    errors = []

    def worker(wid):
        try:
            for i in range(300):
                buf = pool.get(64 + (wid * 7 + i) % 16)
                buf[0:1] = b"x"  # touch the buffer we were handed
        except Exception as e:  # pragma: no cover - the failure under test
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(w,)) for w in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert pool.hits + pool.misses == 8 * 300


def test_native_rejects_u64_overflow_lengths(native):
    """Hostile u64 lengths that would wrap `off + blen` must terminate with
    'Truncated', not loop or return empty buffers."""
    good = networking.encode_message({"w": np.zeros((4,), np.float32)})
    for evil in ((1 << 64) - 8, (1 << 64) - 1, (1 << 63)):
        tampered = bytearray(good)
        off = len(good) - 16 - 8
        tampered[off:off + 8] = evil.to_bytes(8, "little")
        with pytest.raises(ValueError, match="Truncated"):
            native.decode_frames(bytes(tampered))


# KV-block transfer node ('k' SERVING_OP_KVBLOCKS / __kvb__ — PR 16):
# a prefill engine ships a request's filled paged-KV blocks (plus int8
# scales, positions, RNG key) to a decode engine.  Like the sparse nodes,
# the codecs frame the buffers and validate() is the transport-boundary
# guard: hostile geometry must raise the typed ProtocolError before the
# receiving pool allocates anything.

def _kvb(int8=False, bs=4, nb=2, hkv=2, dh=3, seed=0):
    """A 3-layer KVBlocks (layer 0 cache-less, like an embedding layer)."""
    rng = np.random.default_rng(seed)
    rows = nb * bs
    layers = [None]
    for _ in range(2):
        if int8:
            c = {"k": rng.integers(-127, 128, (rows, hkv, dh)).astype(
                     np.int8),
                 "v": rng.integers(-127, 128, (rows, hkv, dh)).astype(
                     np.int8),
                 "ks": rng.random((rows, hkv)).astype(np.float32),
                 "vs": rng.random((rows, hkv)).astype(np.float32)}
        else:
            c = {"k": rng.standard_normal((rows, hkv, dh)).astype(
                     np.float32),
                 "v": rng.standard_normal((rows, hkv, dh)).astype(
                     np.float32)}
        layers.append(c)
    return networking.KVBlocks(layers, bs, nb, positions=rows - 1,
                               key=np.array([0, 11], np.uint32))


def test_kvblocks_opcode_distinct():
    ops = (networking.SERVING_OP_ENQUEUE, networking.SERVING_OP_STREAM,
           networking.SERVING_OP_CANCEL, networking.SERVING_OP_KVBLOCKS)
    assert len(networking.SERVING_OP_KVBLOCKS) == 1
    assert len(set(ops)) == len(ops)


@pytest.mark.parametrize("int8", [False, True],
                         ids=["dense", "int8-scales"])
def test_kvblocks_roundtrip_either_codec(codec, int8):
    """__kvb__ survives both codecs bit for bit: block geometry, positions,
    RNG key, per-layer k/v payloads (and int8 codes + per-entry scales),
    None layers preserved positionally."""
    kvb = _kvb(int8=int8)
    frame = {"blocks": kvb, "prompt": np.array([1, 2, 3], np.int32),
             "first_token": 9, "num_steps": 4}
    out = networking.decode_message(networking.encode_message(frame))
    got = out["blocks"]
    assert isinstance(got, networking.KVBlocks)
    assert got.block_size == kvb.block_size
    assert got.num_blocks == kvb.num_blocks
    assert got.positions == kvb.positions
    np.testing.assert_array_equal(got.key, kvb.key)
    assert got.key.dtype == np.uint32
    assert len(got.layers) == len(kvb.layers)
    assert got.layers[0] is None
    for mine, want in zip(got.layers[1:], kvb.layers[1:]):
        assert sorted(mine) == sorted(want)
        for k in want:
            np.testing.assert_array_equal(mine[k], want[k])
            assert mine[k].dtype == want[k].dtype
    assert got.nbytes == kvb.nbytes
    got.validate()  # a clean round trip must stay admissible


def test_kvblocks_pooled_recv_decoded_either_codec(codec):
    """Through the zero-copy pooled path the payloads are views into the
    reusable recv buffer; decoded() detaches them (what ServingServer
    must do before queueing past the next recv)."""
    pool = networking.BufferPool()
    kvb = _kvb(int8=True)
    a, b = socket.socketpair()
    try:
        for _ in range(2):
            t = threading.Thread(target=networking.send_data,
                                 args=(a, {"blocks": kvb}))
            t.start()
            out = networking.recv_data(b, pool=pool)
            t.join()
            got = out["blocks"]
            assert not got.layers[1]["k"].flags["OWNDATA"]
            det = got.validate().decoded()
            assert det.layers[1]["k"].flags["OWNDATA"]
            np.testing.assert_array_equal(det.layers[1]["k"],
                                          kvb.layers[1]["k"])
            np.testing.assert_array_equal(det.layers[2]["vs"],
                                          kvb.layers[2]["vs"])
        assert pool.misses == 1 and pool.hits == 1
    finally:
        a.close()
        b.close()


def _corrupt(kvb, how):
    if how == "zero-blocks":
        kvb.num_blocks = 0
    elif how == "positions-zero":
        kvb.positions = 0
    elif how == "positions-overflow":
        kvb.positions = kvb.num_blocks * kvb.block_size + 1
    elif how == "missing-v":
        del kvb.layers[1]["v"]
    elif how == "unknown-payload":
        kvb.layers[1]["evil"] = kvb.layers[1]["k"]
    elif how == "row-count-lie":
        kvb.layers[1]["k"] = kvb.layers[1]["k"][:-1]
        kvb.layers[1]["v"] = kvb.layers[1]["v"][:-1]
    elif how == "kv-dtype-split":
        kvb.layers[1]["v"] = kvb.layers[1]["v"].astype(np.float64)
    elif how == "half-scales":
        del kvb.layers[1]["vs"]
    elif how == "scales-on-dense":
        kvb.layers[1]["ks"] = np.ones(kvb.layers[1]["k"].shape[:2],
                                      np.float32)
        kvb.layers[1]["vs"] = kvb.layers[1]["ks"]
    elif how == "scale-shape-lie":
        kvb.layers[1]["ks"] = kvb.layers[1]["ks"][:, :1]
    elif how == "no-layers":
        kvb.layers = [None, None, None]
    elif how == "signed-key":
        kvb.key = np.array([-1, 2], np.int64)
    return kvb


@pytest.mark.parametrize("how", [
    "zero-blocks", "positions-zero", "positions-overflow", "missing-v",
    "unknown-payload", "row-count-lie", "kv-dtype-split", "no-layers",
    "signed-key"])
def test_kvblocks_hostile_rejects_either_codec(codec, how):
    """Hostile/torn block frames survive the codec (it frames buffers,
    it doesn't interpret them) but validate() rejects with the typed
    ProtocolError — the serving server's ValueError shed path."""
    kvb = _corrupt(_kvb(), how)
    out = networking.decode_message(
        networking.encode_message({"blocks": kvb}))["blocks"]
    with pytest.raises(networking.ProtocolError):
        out.validate()


@pytest.mark.parametrize("how", ["half-scales", "scales-on-dense",
                                 "scale-shape-lie"])
def test_kvblocks_hostile_scale_rejects_either_codec(codec, how):
    kvb = _corrupt(_kvb(int8=(how != "scales-on-dense")), how)
    out = networking.decode_message(
        networking.encode_message({"blocks": kvb}))["blocks"]
    with pytest.raises(networking.ProtocolError):
        out.validate()
