"""ZeRO-1 optimizer-state sharding (train_step.build_train_step(zero_axis=)).

No reference counterpart (the reference's optimizer state lives whole inside
each Spark worker's Keras model) — this is the scaling-book recipe for
fitting optimizer moments on pods: annotate the optax state sharded over the
data axis and let GSPMD place the slice/all-gather collectives.  Numerics
must be IDENTICAL to the unsharded path; the moments must actually be
partitioned on device.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from distkeras_tpu.parallel.pp_transformer import PipelineTransformerLM
from distkeras_tpu.parallel.train_step import zero_shard_specs
from distkeras_tpu.parallel.transformer import ParallelTransformerLM


def mesh_of(shape, axes=("data", "seq", "model")):
    n = int(np.prod(shape))
    devs = np.array(jax.devices()[:n]).reshape(shape)
    return Mesh(devs, axes)


def make_lm(mesh, **kw):
    cfg = dict(vocab_size=32, seq_len=16, d_model=16, num_heads=2,
               num_layers=2, mlp_dim=32, mesh=mesh,
               compute_dtype=jnp.float32)
    cfg.update(kw)
    return ParallelTransformerLM(**cfg)


def run_steps(lm, steps=3, zero=False, lr=1e-2):
    params = lm.init(jax.random.PRNGKey(7))
    opt_state, step = lm.compile_train_step(optax.adam(lr), params,
                                            zero=zero)
    rng = np.random.default_rng(0)
    toks = rng.integers(0, lm.vocab_size, (8, lm.seq_len)).astype(np.int32)
    labels = (toks + 1) % lm.vocab_size
    sh = lm.batch_sharding()
    toks, labels = jax.device_put(toks, sh), jax.device_put(labels, sh)
    losses = []
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, toks, labels)
        losses.append(float(loss))
    return losses, params, opt_state


def moment_leaves(opt_state):
    """The adam mu-tree leaves (arrays) of an optax state."""
    for entry in opt_state:
        if hasattr(entry, "mu"):
            return jax.tree_util.tree_leaves(entry.mu)
    raise AssertionError("no ScaleByAdamState in opt state")


def test_zero_matches_unsharded_and_single(eight_devices):
    """dp=4 × tp=2 LM: zero=True losses == zero=False == 1×1×1 mesh."""
    l_z, _, _ = run_steps(make_lm(mesh_of((4, 1, 2))), zero=True)
    l_n, _, _ = run_steps(make_lm(mesh_of((4, 1, 2))), zero=False)
    l_1, _, _ = run_steps(make_lm(mesh_of((1, 1, 1))))
    np.testing.assert_allclose(l_z, l_n, rtol=1e-6)
    np.testing.assert_allclose(l_z, l_1, rtol=2e-4)


def test_zero_moments_actually_sharded(eight_devices):
    """Each data shard owns 1/dp of every ZeRO-eligible moment buffer."""
    lm = make_lm(mesh_of((4, 1, 2)))
    _, _, opt_z = run_steps(lm, steps=1, zero=True)
    _, _, opt_n = run_steps(lm, steps=1, zero=False)
    sharded = 0
    for lz, ln in zip(moment_leaves(opt_z), moment_leaves(opt_n)):
        nz = lz.addressable_shards[0].data.size
        nn = ln.addressable_shards[0].data.size
        assert nz <= nn
        sharded += nz < nn
    assert sharded > 0, "no moment leaf actually shrank under zero=True"
    # embed: (32, 16) replicated over data without zero -> (8, 16) with
    embed_mu = [l for l in moment_leaves(opt_z) if l.shape == (32, 16)]
    assert any(l.addressable_shards[0].data.shape[0] == 8 for l in embed_mu)


def test_zero_composes_with_pipeline_1f1b(eight_devices):
    """dp×pp 1F1B + zero: loss equals the non-zero 1F1B path."""
    mesh = mesh_of((2, 4), axes=("data", "stage"))

    def run(zero):
        lm = PipelineTransformerLM(
            vocab_size=32, seq_len=8, d_model=8, num_heads=2, num_layers=4,
            mlp_dim=16, mesh=mesh, num_microbatches=4, schedule="1f1b",
            compute_dtype=jnp.float32)
        params = lm.init(jax.random.PRNGKey(3))
        opt_state, step = lm.compile_train_step(optax.adam(1e-2), params,
                                                zero=zero)
        rng = np.random.default_rng(1)
        toks = rng.integers(0, 32, (8, 8)).astype(np.int32)
        labels = (toks + 1) % 32
        sh = lm.batch_sharding()
        toks, labels = jax.device_put(toks, sh), jax.device_put(labels, sh)
        losses = []
        for _ in range(2):
            params, opt_state, loss = step(params, opt_state, toks, labels)
            losses.append(float(loss))
        return losses

    np.testing.assert_allclose(run(True), run(False), rtol=1e-6)


def test_zero_shard_specs_fallback():
    """Leaves with no dp-divisible unsharded dim keep their inherited spec;
    scalars stay replicated."""
    mesh = mesh_of((4, 1, 2))
    shapes = {"a": jax.ShapeDtypeStruct((6, 5), jnp.float32),   # 6 % 4 != 0
              "b": jax.ShapeDtypeStruct((8, 6), jnp.float32),
              "c": jax.ShapeDtypeStruct((), jnp.float32),
              "d": jax.ShapeDtypeStruct((6, 8), jnp.float32)}   # dim1 works
    specs = {"a": P(), "b": P(None, "model"), "c": P(), "d": P()}
    out = zero_shard_specs(specs, shapes, mesh, "data")
    assert out["a"] == P()
    assert out["b"] == P("data", "model")
    assert out["c"] == P()
    assert out["d"] == P(None, "data")


def test_zero_rejects_unknown_axis(eight_devices):
    from distkeras_tpu.parallel.train_step import build_train_step
    lm = make_lm(mesh_of((4, 1, 2)))
    params = lm.init(jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="zero_axis"):
        build_train_step(lm.mesh, lm._loss, lm.param_specs(),
                         P("data", "seq"), optax.adam(1e-2), params,
                         zero_axis="nope")
