"""FSDP / ZeRO-3 param sharding (train_step.build_train_step(fsdp_axis=)).

No reference counterpart (the reference replicates the whole Keras model in
every Spark worker) — this is the scaling-book's fully-sharded data
parallelism expressed the GSPMD way: params and moments live partitioned
over the data axis at rest, sharding constraints at the step boundaries let
XLA place the per-step all-gather and the grad reduce-scatter.  Numerics
must match the replicated path; params/moments must actually be partitioned
on device after a step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from distkeras_tpu.parallel.pp_transformer import PipelineTransformerLM
from distkeras_tpu.parallel.train_step import shard_specs_over_axis
from distkeras_tpu.parallel.transformer import ParallelTransformerLM


def mesh_of(shape, axes=("data", "seq", "model")):
    n = int(np.prod(shape))
    devs = np.array(jax.devices()[:n]).reshape(shape)
    return Mesh(devs, axes)


def make_lm(mesh, **kw):
    cfg = dict(vocab_size=32, seq_len=16, d_model=16, num_heads=2,
               num_layers=2, mlp_dim=32, mesh=mesh,
               compute_dtype=jnp.float32)
    cfg.update(kw)
    return ParallelTransformerLM(**cfg)


def run_steps(lm, steps=3, fsdp=False, lr=1e-2):
    params = lm.init(jax.random.PRNGKey(7))
    opt_state, step = lm.compile_train_step(optax.adam(lr), params,
                                            fsdp=fsdp)
    rng = np.random.default_rng(0)
    toks = rng.integers(0, lm.vocab_size, (8, lm.seq_len)).astype(np.int32)
    labels = (toks + 1) % lm.vocab_size
    sh = lm.batch_sharding()
    toks, labels = jax.device_put(toks, sh), jax.device_put(labels, sh)
    losses = []
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, toks, labels)
        losses.append(float(loss))
    return losses, params, opt_state


def local_size(x):
    return x.addressable_shards[0].data.size


def test_fsdp_matches_replicated_and_single(eight_devices):
    """dp=4 × tp=2 LM: fsdp=True losses == fsdp=False == 1×1×1 mesh."""
    l_f, _, _ = run_steps(make_lm(mesh_of((4, 1, 2))), fsdp=True)
    l_n, _, _ = run_steps(make_lm(mesh_of((4, 1, 2))), fsdp=False)
    l_1, _, _ = run_steps(make_lm(mesh_of((1, 1, 1))))
    np.testing.assert_allclose(l_f, l_n, rtol=1e-5)
    np.testing.assert_allclose(l_f, l_1, rtol=2e-4)


def test_fsdp_params_and_moments_actually_sharded(eight_devices):
    """After a step, each data shard holds 1/dp of every eligible param AND
    moment leaf — the at-rest HBM win that distinguishes ZeRO-3 from
    ZeRO-1."""
    lm = make_lm(mesh_of((4, 1, 2)))
    _, p_f, opt_f = run_steps(lm, steps=1, fsdp=True)
    _, p_n, opt_n = run_steps(lm, steps=1, fsdp=False)
    shrank = sum(local_size(a) < local_size(b) for a, b in
                 zip(jax.tree_util.tree_leaves(p_f),
                     jax.tree_util.tree_leaves(p_n)))
    assert shrank > 0, "no param leaf shrank under fsdp=True"
    # embed (32, 16): replicated over data without fsdp -> (8, 16) with
    embed = p_f["embed"]
    assert embed.addressable_shards[0].data.shape == (8, 16)
    # the head's adam mu must be sharded too (ZeRO-3 covers the moments)
    mu_shrank = sum(
        local_size(a) < local_size(b) for a, b in
        zip(jax.tree_util.tree_leaves(opt_f),
            jax.tree_util.tree_leaves(opt_n))
        if hasattr(a, "addressable_shards"))
    assert mu_shrank > 0, "no optimizer leaf shrank under fsdp=True"


def test_fsdp_final_params_equal_replicated(eight_devices):
    """Three steps of fsdp and replicated training land on the same
    weights (gather the fsdp params back to host for comparison)."""
    lm = make_lm(mesh_of((4, 1, 2)))
    _, p_f, _ = run_steps(lm, steps=3, fsdp=True)
    _, p_n, _ = run_steps(lm, steps=3, fsdp=False)
    for a, b in zip(jax.tree_util.tree_leaves(p_f),
                    jax.tree_util.tree_leaves(p_n)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=1e-6)


def test_fsdp_composes_with_pipeline_1f1b(eight_devices):
    """dp×pp 1F1B + fsdp: loss equals the replicated 1F1B path."""
    mesh = mesh_of((2, 4), axes=("data", "stage"))

    def run(fsdp):
        lm = PipelineTransformerLM(
            vocab_size=32, seq_len=8, d_model=8, num_heads=2, num_layers=4,
            mlp_dim=16, mesh=mesh, num_microbatches=4, schedule="1f1b",
            compute_dtype=jnp.float32)
        params = lm.init(jax.random.PRNGKey(3))
        opt_state, step = lm.compile_train_step(optax.adam(1e-2), params,
                                                fsdp=fsdp)
        rng = np.random.default_rng(1)
        toks = rng.integers(0, 32, (8, 8)).astype(np.int32)
        labels = (toks + 1) % 32
        sh = lm.batch_sharding()
        toks, labels = jax.device_put(toks, sh), jax.device_put(labels, sh)
        losses = []
        for _ in range(2):
            params, opt_state, loss = step(params, opt_state, toks, labels)
            losses.append(float(loss))
        return losses

    np.testing.assert_allclose(run(True), run(False), rtol=1e-6)


def test_shard_specs_over_axis_on_params():
    """The param variant of the per-leaf rule: tp-sharded dims are kept,
    the first divisible unsharded dim takes the fsdp axis."""
    mesh = mesh_of((4, 1, 2))
    shapes = {"wq": jax.ShapeDtypeStruct((16, 16), jnp.float32),
              "ln": jax.ShapeDtypeStruct((6,), jnp.float32),
              "b": jax.ShapeDtypeStruct((8,), jnp.float32)}
    specs = {"wq": P(None, "model"), "ln": P(), "b": P()}
    out = shard_specs_over_axis(specs, shapes, mesh, "data")
    assert out["wq"] == P("data", "model")
    assert out["ln"] == P()          # 6 % 4 != 0 -> untouched
    assert out["b"] == P("data")


def test_fsdp_rejects_unknown_axis(eight_devices):
    from distkeras_tpu.parallel.train_step import build_train_step
    lm = make_lm(mesh_of((4, 1, 2)))
    params = lm.init(jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="fsdp_axis"):
        build_train_step(lm.mesh, lm._loss, lm.param_specs(),
                         P("data", "seq"), optax.adam(1e-2), params,
                         fsdp_axis="nope")


def test_fsdp_state_orbax_roundtrip(eight_devices, tmp_path):
    """Pod-resume integration: FSDP-sharded params + moments survive an
    orbax save/restore with their NamedShardings intact, and training
    continues bit-identically from the restored state."""
    pytest.importorskip("orbax.checkpoint")
    from distkeras_tpu.checkpoint import OrbaxCheckpointer

    lm = make_lm(mesh_of((4, 1, 2)))
    params = lm.init(jax.random.PRNGKey(7))
    opt_state, step = lm.compile_train_step(optax.adam(1e-2), params,
                                            fsdp=True)
    rng = np.random.default_rng(0)
    toks = rng.integers(0, lm.vocab_size, (8, lm.seq_len)).astype(np.int32)
    labels = (toks + 1) % lm.vocab_size
    sh = lm.batch_sharding()
    toks, labels = jax.device_put(toks, sh), jax.device_put(labels, sh)

    params, opt_state, _ = step(params, opt_state, toks, labels)

    ck = OrbaxCheckpointer(str(tmp_path / "fsdp_ck"), async_save=False)
    ck.save(1, {"params": params, "opt": opt_state})
    ck.wait()
    restored = ck.restore({"params": params, "opt": opt_state})

    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(restored["params"])):
        assert a.sharding == b.sharding  # FSDP layout survives
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # continuing from the restored state matches continuing in-memory
    # (step donates its state args: the restored copies are separate
    # buffers, and params/opt_state are not reused after this call)
    p1, o1, l1 = step(params, opt_state, toks, labels)
    p2, o2, l2 = step(restored["params"], restored["opt"], toks, labels)
    np.testing.assert_array_equal(float(l1), float(l2))
    for a, b in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
