"""Fused softmax-cross-entropy kernel vs the XLA oracle.

The oracle is plain ``log_softmax`` + gather (what
``core.losses.sparse_categorical_crossentropy`` computes); the kernel must
match it in value and logits-gradient, including ragged (non-block-multiple)
shapes, bf16 inputs, and use inside the parallel LM's loss.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.ops.fused_ce import fused_softmax_cross_entropy


def oracle(logits, labels):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.take_along_axis(
        logp, labels.astype(jnp.int32)[:, None], axis=-1)[:, 0]


def rand(t, v, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    logits = jnp.asarray(rng.normal(size=(t, v)) * 3.0, dtype)
    labels = jnp.asarray(rng.integers(0, v, size=(t,)), jnp.int32)
    return logits, labels


@pytest.mark.parametrize("t,v", [(8, 16), (256, 512), (300, 1000),
                                 (7, 130), (64, 50257 % 2048)])
def test_value_matches_oracle(t, v):
    logits, labels = rand(t, v, seed=t + v)
    got = fused_softmax_cross_entropy(logits, labels,
                                      block_t=64, block_v=128)
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(oracle(logits, labels)),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("t,v", [(32, 64), (100, 300)])
def test_grad_matches_oracle(t, v):
    logits, labels = rand(t, v, seed=3)
    w = jnp.asarray(np.random.default_rng(1).normal(size=(t,)), jnp.float32)

    # weighted sum exercises a non-uniform cotangent
    g_fused = jax.grad(lambda lg: jnp.sum(
        w * fused_softmax_cross_entropy(lg, labels, block_t=32,
                                        block_v=64)))(logits)
    g_ref = jax.grad(lambda lg: jnp.sum(w * oracle(lg, labels)))(logits)
    np.testing.assert_allclose(np.asarray(g_fused), np.asarray(g_ref),
                               rtol=1e-4, atol=1e-5)


def test_bf16_logits_grad_dtype_and_value():
    logits, labels = rand(64, 128, seed=5, dtype=jnp.bfloat16)
    loss = fused_softmax_cross_entropy(logits, labels)
    assert loss.dtype == jnp.float32
    g = jax.grad(lambda lg: jnp.sum(
        fused_softmax_cross_entropy(lg, labels)))(logits)
    assert g.dtype == jnp.bfloat16
    g_ref = jax.grad(lambda lg: jnp.sum(oracle(lg, labels)))(
        logits.astype(jnp.float32))
    np.testing.assert_allclose(np.asarray(g, np.float32),
                               np.asarray(g_ref), rtol=0.05, atol=0.02)


def test_extreme_logits_stable():
    """Online-softmax must survive ±1e4 logits without overflow."""
    logits = jnp.array([[1e4, 0.0, -1e4, 5.0] * 32] * 8, jnp.float32)
    labels = jnp.zeros((8,), jnp.int32)
    got = fused_softmax_cross_entropy(logits, labels, block_v=32)
    assert np.isfinite(np.asarray(got)).all()
    # blockwise vs whole-row summation order differs at ~1e-5 relative
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(oracle(logits, labels)),
                               rtol=1e-4, atol=1e-5)


def test_jit_and_vocab_one_block():
    logits, labels = rand(16, 32, seed=9)
    f = jax.jit(lambda lg, lb: fused_softmax_cross_entropy(lg, lb))
    np.testing.assert_allclose(np.asarray(f(logits, labels)),
                               np.asarray(oracle(logits, labels)),
                               rtol=1e-5, atol=1e-5)


def test_inside_parallel_lm_loss(eight_devices):
    """ParallelTransformerLM(fused_ce=True) trains to the same losses as
    the XLA loss path on a dp×tp mesh."""
    import optax
    from jax.sharding import Mesh
    from distkeras_tpu.parallel.transformer import ParallelTransformerLM

    devs = np.array(jax.devices()[:4]).reshape(2, 1, 2)
    mesh = Mesh(devs, ("data", "seq", "model"))

    def run(fused):
        lm = ParallelTransformerLM(
            vocab_size=48, seq_len=16, d_model=16, num_heads=2,
            num_layers=2, mlp_dim=32, mesh=mesh,
            compute_dtype=jnp.float32, fused_ce=fused)
        params = lm.init(jax.random.PRNGKey(11))
        opt_state, step = lm.compile_train_step(optax.adam(1e-2), params)
        rng = np.random.default_rng(2)
        toks = rng.integers(0, 48, (8, 16)).astype(np.int32)
        labels = (toks + 1) % 48
        sh = lm.batch_sharding()
        toks, labels = jax.device_put(toks, sh), jax.device_put(labels, sh)
        losses = []
        for _ in range(3):
            params, opt_state, loss = step(params, opt_state, toks, labels)
            losses.append(float(loss))
        return losses

    np.testing.assert_allclose(run(True), run(False), rtol=1e-5)
