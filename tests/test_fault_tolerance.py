"""Worker-death tolerance on the PS engines (SURVEY §5 fault table: the
reference had no failure handling of its own — a dead worker was a Spark
task retry).  Here ``fault_tolerance=True`` lets a PS run survive worker
death: the PS already treats a dropped socket as a normal disconnect, so
the driver's only job is to finish with the survivors and report the dead.
``fault_injection={worker_id: n}`` makes a worker raise at its n+1-th
commit — the fault-injection hook the reference never had.
"""

import numpy as np
import pytest

from distkeras_tpu import ADAG, DOWNPOUR

from test_trainers import eval_accuracy, make_dataset, make_model


def test_host_ps_survives_injected_worker_death():
    """4 workers, worker 1 dies at its 3rd commit: training completes on
    the survivors, the dead id is reported, and the model still learns."""
    ds = make_dataset(n=1024)
    t = ADAG(make_model(), num_workers=4, batch_size=16, num_epoch=3,
             communication_window=4, label_col="label_encoded",
             worker_optimizer="adam", learning_rate=2e-3,
             execution="host_ps", fault_tolerance=True,
             fault_injection={1: 2})
    fitted = t.train(ds)
    assert t.failed_workers == [1]
    # the tolerated death stays diagnosable
    assert "injected fault" in t.worker_failures[1]
    assert eval_accuracy(fitted, ds) > 0.85
    # survivors' full histories + the casualty's partial one came back
    assert len(t.get_history()) > 0


def test_host_ps_tolerates_exit_fault_kind():
    """PR 5 fault kinds on the legacy (non-elastic) engine: an ('exit', n)
    worker dies MID-FRAME via SystemExit — no traceback-bearing raise —
    and fault_tolerance still finishes on the survivors with the death
    diagnosable.  (The 'hang' kind needs elastic=True and is rejected
    here — tests/test_elastic_workers.py.)"""
    ds = make_dataset(n=1024)
    t = ADAG(make_model(), num_workers=4, batch_size=16, num_epoch=3,
             communication_window=4, label_col="label_encoded",
             worker_optimizer="adam", learning_rate=2e-3,
             execution="host_ps", fault_tolerance=True,
             fault_injection={1: ("exit", 2)})
    fitted = t.train(ds)
    assert t.failed_workers == [1]
    assert "SystemExit" in t.worker_failures[1]
    assert eval_accuracy(fitted, ds) > 0.85


def test_injected_fault_without_tolerance_raises():
    ds = make_dataset(n=512)
    t = DOWNPOUR(make_model(), num_workers=2, batch_size=16, num_epoch=1,
                 communication_window=2, label_col="label_encoded",
                 worker_optimizer="sgd", learning_rate=0.05,
                 execution="host_ps", fault_injection={0: 1})
    with pytest.raises(RuntimeError, match="injected fault"):
        t.train(ds)


def test_all_workers_dead_still_raises():
    """fault_tolerance survives SOME deaths, not total loss."""
    ds = make_dataset(n=512)
    t = ADAG(make_model(), num_workers=2, batch_size=16, num_epoch=1,
             communication_window=2, label_col="label_encoded",
             worker_optimizer="sgd", learning_rate=0.05,
             execution="host_ps", fault_tolerance=True,
             fault_injection={0: 1, 1: 1})
    with pytest.raises(RuntimeError, match="all 2 workers failed"):
        t.train(ds)


def test_spmd_rejects_fault_kwargs():
    ds = make_dataset(n=256)
    for kw in (dict(fault_tolerance=True), dict(fault_injection={0: 1})):
        t = ADAG(make_model(), num_workers=2, batch_size=16, num_epoch=1,
                 label_col="label_encoded", **kw)
        with pytest.raises(ValueError, match="fault_tolerance"):
            t.train(ds)


def test_failed_workers_reset_between_runs():
    ds = make_dataset(n=512)
    t = ADAG(make_model(), num_workers=2, batch_size=16, num_epoch=1,
             communication_window=4, label_col="label_encoded",
             worker_optimizer="adam", learning_rate=2e-3,
             execution="host_ps", fault_tolerance=True,
             fault_injection={0: 1})
    t.train(ds)
    assert t.failed_workers == [0]
    t.fault_injection = None
    t.train(ds)
    assert t.failed_workers == []


@pytest.mark.slow
def test_process_ps_survives_worker_process_death():
    """Cross-process flavor: one of two OS worker processes exits nonzero
    mid-training; the driver completes with the survivor and reports it."""
    ds = make_dataset(n=512)
    t = ADAG(make_model(), num_workers=2, batch_size=16, num_epoch=4,
             communication_window=4, label_col="label_encoded",
             worker_optimizer="adam", learning_rate=4e-3,
             execution="process_ps", fault_tolerance=True,
             fault_injection={1: 2})
    fitted = t.train(ds)
    assert t.failed_workers == [1]
    assert t.worker_failures[1].startswith("exit code")
    # half the shard died after 2 commits: the survivor's half still
    # carries the model well past chance (0.25 for 4 classes)
    assert eval_accuracy(fitted, ds) > 0.7
    # only the survivor's history came back (the casualty never wrote one)
    assert len(t.get_history()) > 0
