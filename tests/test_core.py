"""Core layer/model/loss/optimizer tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.core import (Sequential, Dense, Conv2D, MaxPooling2D,
                                Flatten, Reshape, Activation, Dropout,
                                BatchNormalization)
from distkeras_tpu.core.model import (serialize_model, deserialize_model,
                                      FittedModel)
from distkeras_tpu.core.losses import (categorical_crossentropy,
                                       binary_crossentropy,
                                       mean_squared_error, get_loss)
from distkeras_tpu.core import optimizers as opt_lib
from distkeras_tpu.core.train import init_state, make_train_step


def small_mlp(cdtype="float32"):
    return Sequential([Dense(16, activation="relu"),
                       Dense(4, activation="softmax")],
                      input_shape=(8,), compute_dtype=cdtype)


def test_dense_forward_shapes():
    m = small_mlp()
    params = m.init(jax.random.PRNGKey(0))
    x = jnp.ones((5, 8))
    y = m.apply(params, x)
    assert y.shape == (5, 4)
    np.testing.assert_allclose(np.sum(np.asarray(y), axis=-1),
                               np.ones(5), rtol=1e-5)


def test_dense_matches_manual_matmul():
    m = Sequential([Dense(3)], input_shape=(2,), compute_dtype="float32")
    params = m.init(jax.random.PRNGKey(1))
    x = np.array([[1.0, 2.0]], np.float32)
    want = x @ np.asarray(params[0]["kernel"]) + np.asarray(params[0]["bias"])
    got = np.asarray(m.apply(params, x))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_conv_pool_flatten_shapes():
    m = Sequential([
        Reshape((8, 8, 1)),
        Conv2D(4, 3, padding="SAME", activation="relu"),
        MaxPooling2D(2),
        Flatten(),
        Dense(10, activation="softmax"),
    ], input_shape=(64,), compute_dtype="float32")
    params = m.init(jax.random.PRNGKey(0))
    y = m.apply(params, jnp.ones((2, 64)))
    assert y.shape == (2, 10)
    assert m.output_shape == (10,)


def test_bf16_compute_close_to_f32():
    m32 = small_mlp("float32")
    mbf = small_mlp("bfloat16")
    params = m32.init(jax.random.PRNGKey(2))
    x = jnp.asarray(np.random.default_rng(0).standard_normal((4, 8)),
                    jnp.float32)
    y32 = np.asarray(m32.apply(params, x))
    ybf = np.asarray(mbf.apply(params, x))
    np.testing.assert_allclose(y32, ybf, atol=0.03)


def test_dropout_train_vs_eval():
    m = Sequential([Dropout(0.5)], input_shape=(10,),
                   compute_dtype="float32")
    params = m.init(jax.random.PRNGKey(0))
    x = jnp.ones((4, 10))
    y_eval = m.apply(params, x, train=False)
    np.testing.assert_array_equal(np.asarray(y_eval), np.ones((4, 10)))
    y_train = m.apply(params, x, train=True, rng=jax.random.PRNGKey(3))
    vals = np.unique(np.asarray(y_train))
    assert set(np.round(vals, 5)).issubset({0.0, 2.0})


def test_batchnorm_shapes():
    m = Sequential([Dense(6), BatchNormalization(), Activation("relu")],
                   input_shape=(3,), compute_dtype="float32")
    params = m.init(jax.random.PRNGKey(0))
    y = m.apply(params, jnp.ones((5, 3)), train=True)
    assert y.shape == (5, 6)
    y_eval = m.apply(params, jnp.ones((5, 3)), train=False)
    assert y_eval.shape == (5, 6)


def test_serialize_roundtrip():
    m = small_mlp()
    params = m.init(jax.random.PRNGKey(0))
    blob = serialize_model(m, params)
    m2, params2 = deserialize_model(blob)
    x = jnp.ones((3, 8))
    np.testing.assert_allclose(np.asarray(m.apply(params, x)),
                               np.asarray(m2.apply(params2, x)), rtol=1e-6)


def test_fitted_model_save_load(tmp_path):
    m = small_mlp()
    params = m.init(jax.random.PRNGKey(0))
    fm = FittedModel(m, params)
    path = str(tmp_path / "model.npz")
    fm.save(path)
    fm2 = FittedModel.load(path)
    x = np.ones((2, 8), np.float32)
    np.testing.assert_allclose(fm.predict(x), fm2.predict(x), rtol=1e-6)


def test_conv_model_json_roundtrip():
    # tuples in layer configs (pool_size/strides/target_shape) must survive
    # the JSON round-trip as tuples
    from distkeras_tpu.models import mnist_convnet
    m = mnist_convnet("float32")
    params = m.init(jax.random.PRNGKey(0))
    blob = serialize_model(m, params)
    m2, params2 = deserialize_model(blob)
    x = np.random.default_rng(0).uniform(0, 1, (2, 784)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(m.apply(params, x)),
                               np.asarray(m2.apply(params2, x)), rtol=1e-5)


def test_losses_closed_form():
    y = jnp.array([[0.0, 1.0]])
    p = jnp.array([[0.3, 0.7]])
    np.testing.assert_allclose(
        float(categorical_crossentropy(y, p)), -np.log(0.7), rtol=1e-3)
    np.testing.assert_allclose(
        float(mean_squared_error(jnp.array([1.0]), jnp.array([3.0]))), 4.0)
    np.testing.assert_allclose(
        float(binary_crossentropy(jnp.array([1.0]), jnp.array([0.5]))),
        -np.log(0.5), rtol=1e-3)
    with pytest.raises(ValueError):
        get_loss("nope")


def test_optimizer_resolution():
    for name in ["sgd", "adam", "adagrad", "adadelta", "rmsprop",
                 "nadam", "adamax", "adamw", "lamb"]:
        opt = opt_lib.get_optimizer(name)
        assert opt.to_optax() is not None
    opt = opt_lib.get_optimizer(opt_lib.SGD(learning_rate=0.5))
    assert opt.hyper["learning_rate"] == 0.5


def test_train_step_reduces_loss():
    m = small_mlp()
    rng = np.random.default_rng(0)
    x = rng.standard_normal((64, 8)).astype(np.float32)
    labels = (x[:, 0] > 0).astype(np.int64)
    y = np.eye(4, dtype=np.float32)[labels]
    state, tx = init_state(m, jax.random.PRNGKey(0), (8,), "sgd", 0.1)
    step = jax.jit(make_train_step(m, "categorical_crossentropy", tx))
    key = jax.random.PRNGKey(0)
    losses = []
    for i in range(30):
        key, sub = jax.random.split(key)
        state, l = step(state, (jnp.asarray(x), jnp.asarray(y)), sub)
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.9
    assert int(state.step) == 30
