"""KV-cache autoregressive decoding (core/decode.py) vs the full forward.

The decode walker must reproduce the training-time forward numerics one
token at a time: teacher-forced per-step logits match the full ``apply``,
greedy generation continues a learned rule, and the GQA cache is the
advertised ``num_kv_heads`` size.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu import Dataset, SingleTrainer
from distkeras_tpu.core.decode import decode_step, generate, init_cache
from distkeras_tpu.models.zoo import transformer_lm


def tiny_lm(num_kv_heads=None, seq_len=12):
    return transformer_lm(vocab_size=16, seq_len=seq_len, d_model=32,
                          num_heads=4, num_layers=2, mlp_dim=64,
                          compute_dtype="float32",
                          num_kv_heads=num_kv_heads)


@pytest.mark.parametrize("num_kv_heads", [None, 2])
def test_stepwise_logits_match_full_forward(num_kv_heads):
    """Teacher-forced decode_step logits at every position == the full
    (B, S, V) forward logits (f32 tolerance)."""
    model = tiny_lm(num_kv_heads)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(1)
    toks = rng.integers(0, 16, (2, 12)).astype(np.int32)

    full = np.asarray(model.apply(params, toks), np.float32)  # (2, 12, 16)

    caches = init_cache(model, batch=2, max_len=12)
    step = jax.jit(lambda c, t, p: decode_step(model, params, c, t, p))
    for pos in range(12):
        logits, caches = step(caches, toks[:, pos], pos)
        np.testing.assert_allclose(np.asarray(logits), full[:, pos],
                                   rtol=2e-5, atol=2e-5)


def test_gqa_cache_is_kv_head_sized():
    model = tiny_lm(num_kv_heads=1, seq_len=24)
    caches = init_cache(model, batch=3, max_len=20)
    blocks = [c for c in caches if c is not None]
    assert len(blocks) == 2
    for c in blocks:
        assert c["k"].shape == (3, 20, 1, 8)  # 1 kv head, key_dim 32/4
        assert c["v"].shape == (3, 20, 1, 8)
    full = init_cache(tiny_lm(seq_len=24), batch=3, max_len=20)
    assert [c["k"].shape for c in full if c is not None] == \
        [(3, 20, 4, 8), (3, 20, 4, 8)]
    # a cache beyond the trained positional range is refused (the decode
    # would silently clamp to the last embedding row otherwise)
    with pytest.raises(ValueError, match="positional"):
        init_cache(tiny_lm(seq_len=12), batch=1, max_len=20)
    with pytest.raises(ValueError, match="positional"):
        generate(tiny_lm(seq_len=12),
                 tiny_lm(seq_len=12).init(jax.random.PRNGKey(0)),
                 np.zeros((1, 8), np.int32), 10)


@pytest.fixture(scope="module")
def increment_lm():
    """One trained y = x+1 (mod 16) LM shared by the behavioral tests
    (training it costs ~35 s on the CPU mesh — pay once per module)."""
    model = tiny_lm(num_kv_heads=2, seq_len=24)
    rng = np.random.default_rng(0)
    x = rng.integers(0, 16, (256, 24)).astype(np.int32)
    y = (x + 1) % 16
    tr = SingleTrainer(model, batch_size=32, num_epoch=30,
                       loss="sparse_categorical_crossentropy_from_logits",
                       worker_optimizer="adam", learning_rate=3e-3)
    return tr.train(Dataset({"features": x, "label": y}))


def test_generate_continues_learned_rule(increment_lm):
    """The trained x+1 LM's continuation must keep incrementing."""
    prompt = np.array([[3, 4, 5, 6], [11, 12, 13, 14]], np.int32)
    out = np.asarray(increment_lm.generate(prompt, num_steps=6))
    assert out.shape == (2, 10)
    np.testing.assert_array_equal(out[:, :4], prompt)  # prompt preserved
    want = (prompt[:, -1:] + 1 + np.arange(6)) % 16
    np.testing.assert_array_equal(out[:, 4:], want)


@pytest.mark.parametrize("p_len,steps", [(3, 14), (9, 10)])
def test_rolling_cache_matches_full(p_len, steps):
    """rolling=True (O(window) ring cache) produces EXACTLY the tokens of
    the full cache, for prompts shorter and longer than the window."""
    from distkeras_tpu.core.decode import init_cache
    model = transformer_lm(vocab_size=16, seq_len=24, d_model=32,
                           num_heads=4, num_layers=2, mlp_dim=64,
                           compute_dtype="float32", num_kv_heads=2,
                           attention_window=6, positional="rope")
    params = model.init(jax.random.PRNGKey(0))
    prompt = np.random.default_rng(3).integers(
        0, 16, (2, p_len)).astype(np.int32)
    full = np.asarray(generate(model, params, prompt, steps))
    rolled = np.asarray(generate(model, params, prompt, steps,
                                 rolling=True))
    np.testing.assert_array_equal(full, rolled)

    # ring caches really are window-sized
    caches = init_cache(model, batch=2, max_len=24, rolling=True)
    assert all(c["k"].shape[1] == 6 for c in caches if c is not None)
    # rolling without a window is refused
    nowin = tiny_lm()
    with pytest.raises(ValueError, match="rolling"):
        init_cache(nowin, 1, 8, rolling=True)
    with pytest.raises(ValueError, match="rolling"):
        generate(nowin, nowin.init(jax.random.PRNGKey(0)),
                 prompt[:, :3], 2, rolling=True)


def test_filter_logits_topk_topp():
    """Closed-form checks of the sampling filter itself."""
    from distkeras_tpu.core.decode import _filter_logits
    logits = jnp.log(jnp.array([[0.5, 0.25, 0.15, 0.1]]))
    # top_k keeps the k largest, -inf elsewhere
    out = np.asarray(_filter_logits(logits, top_k=2, top_p=None))
    assert np.isfinite(out[0, :2]).all() and np.isinf(out[0, 2:]).all()
    # top_k past the vocab keeps everything
    assert np.isfinite(
        np.asarray(_filter_logits(logits, top_k=99, top_p=None))).all()
    # nucleus: preceding mass < p keeps {0.5, 0.25} at p=0.6 (0.5 alone
    # reaches only 0.5 < 0.6, so the second token joins)
    out = np.asarray(_filter_logits(logits, top_k=None, top_p=0.6))
    assert np.isfinite(out[0, :2]).all() and np.isinf(out[0, 2:]).all()
    # tiny p still keeps the top token
    out = np.asarray(_filter_logits(logits, top_k=None, top_p=1e-6))
    assert np.isfinite(out[0, 0]) and np.isinf(out[0, 1:]).all()
    # p = 1 keeps everything
    assert np.isfinite(
        np.asarray(_filter_logits(logits, top_k=None, top_p=1.0))).all()
    # composed: k first, then p over the survivors
    out = np.asarray(_filter_logits(logits, top_k=3, top_p=0.6))
    assert np.isfinite(out[0, :2]).all() and np.isinf(out[0, 2:]).all()


def test_generate_topk_topp_sampling():
    model = tiny_lm()
    params = model.init(jax.random.PRNGKey(0))
    prompt = np.zeros((2, 3), np.int32)
    rng = jax.random.PRNGKey(7)
    # top_k=1 == greedy regardless of temperature
    greedy = np.asarray(generate(model, params, prompt, 5))
    k1 = np.asarray(generate(model, params, prompt, 5, temperature=2.0,
                             rng=rng, top_k=1))
    np.testing.assert_array_equal(greedy, k1)
    # a tiny nucleus likewise collapses to greedy
    p_tiny = np.asarray(generate(model, params, prompt, 5, temperature=2.0,
                                 rng=rng, top_p=1e-9))
    np.testing.assert_array_equal(greedy, p_tiny)
    # valid sampling with both filters + the rolling-cache path
    win_model = transformer_lm(vocab_size=16, seq_len=24, d_model=32,
                               num_heads=4, num_layers=2, mlp_dim=64,
                               compute_dtype="float32", attention_window=6,
                               positional="rope")
    win_params = win_model.init(jax.random.PRNGKey(1))
    out = np.asarray(generate(win_model, win_params, prompt, 8,
                              temperature=1.0, rng=rng, top_k=5, top_p=0.9,
                              rolling=True))
    assert out.shape == (2, 11)
    assert ((0 <= out) & (out < 16)).all()
    # validation
    with pytest.raises(ValueError, match="temperature"):
        generate(model, params, prompt, 2, top_k=5)
    with pytest.raises(ValueError, match="top_k"):
        generate(model, params, prompt, 2, temperature=1.0, rng=rng,
                 top_k=0)
    with pytest.raises(ValueError, match="top_p"):
        generate(model, params, prompt, 2, temperature=1.0, rng=rng,
                 top_p=1.5)


def test_generate_eos_stopping(increment_lm):
    """After a row emits eos_id, its remaining slots are pad_id; other
    rows keep generating (static output shape)."""
    model, params = increment_lm.model, increment_lm.params

    # row 0 counts 3,4,5... and hits eos 7 mid-generation; row 1 starts at
    # 9 and never reaches it within the horizon
    prompt = np.array([[3, 4], [9, 10]], np.int32)
    out = np.asarray(generate(model, params, prompt, 8, eos_id=7,
                              pad_id=0))
    np.testing.assert_array_equal(out[0], [3, 4, 5, 6, 7, 0, 0, 0, 0, 0])
    np.testing.assert_array_equal(
        out[1], [9, 10, 11, 12, 13, 14, 15, 0, 1, 2])
    # pad defaults to the eos token itself
    out2 = np.asarray(generate(model, params, prompt, 8, eos_id=7))
    np.testing.assert_array_equal(out2[0], [3, 4, 5, 6, 7, 7, 7, 7, 7, 7])
    with pytest.raises(ValueError, match="pad_id"):
        generate(model, params, prompt, 2, pad_id=0)
    # out-of-vocab eos could never trigger: refused, not silently ignored
    with pytest.raises(ValueError, match="eos_id"):
        generate(model, params, prompt, 2, eos_id=16)
    # out-of-vocab pad would be silently clamped by scatter/gather: refuse
    with pytest.raises(ValueError, match="pad_id"):
        generate(model, params, prompt, 2, eos_id=7, pad_id=16)


def test_jit_decode_step_entry_point():
    """jit_decode_step drives a hand-rolled loop to the same tokens as
    generate(), without recompiling across positions."""
    from distkeras_tpu.core.decode import jit_decode_step
    model = tiny_lm(seq_len=16)
    params = model.init(jax.random.PRNGKey(0))
    prompt = np.array([[5, 3, 9]], np.int32)
    steps = 6
    want = np.asarray(generate(model, params, prompt, steps))

    caches = init_cache(model, batch=1, max_len=3 + steps)
    step = jit_decode_step(model)
    # teacher-forced prefill one token at a time (exercises pos tracing)
    for pos in range(3):
        logits, caches = step(params, caches, prompt[:, pos], pos)
    toks = [int(np.argmax(np.asarray(logits)[0]))]
    for pos in range(3, 3 + steps - 1):
        logits, caches = step(params, caches,
                              np.array([toks[-1]], np.int32), pos)
        toks.append(int(np.argmax(np.asarray(logits)[0])))
    np.testing.assert_array_equal(want[0, 3:], toks)
    # one compile for all positions: pos is traced, not baked in
    assert step._cache_size() == 1
    # unsupported models are rejected at build time
    from distkeras_tpu.core.layers import Conv2D
    from distkeras_tpu import Sequential
    with pytest.raises(ValueError, match="unsupported layer"):
        jit_decode_step(Sequential([Conv2D(4, 3)], input_shape=(8, 8, 1)))


def test_generate_sampling_and_validation():
    model = tiny_lm()
    params = model.init(jax.random.PRNGKey(0))
    prompt = np.zeros((1, 3), np.int32)
    # temperature sampling needs rng
    with pytest.raises(ValueError, match="rng"):
        generate(model, params, prompt, 2, temperature=1.0)
    out = np.asarray(generate(model, params, prompt, 2, temperature=1.0,
                              rng=jax.random.PRNGKey(3)))
    assert out.shape == (1, 5)
    assert ((0 <= out) & (out < 16)).all()
    with pytest.raises(ValueError, match="max_len"):
        generate(model, params, prompt, 4, max_len=5)
    with pytest.raises(ValueError, match="num_steps"):
        generate(model, params, prompt, -2)
    np.testing.assert_array_equal(
        np.asarray(generate(model, params, prompt, 0)), prompt)
    # num_steps == 0 does not bypass validation (ADVICE r3): invalid
    # combinations fail the same way regardless of step count
    with pytest.raises(ValueError, match="max_len"):
        generate(model, params, prompt, 0, max_len=2)
    with pytest.raises(ValueError, match="rolling"):
        generate(model, params, prompt, 0, rolling=True)
    # encoder-style (non-causal) blocks are rejected: the cached step would
    # silently diverge from the full bidirectional forward
    from distkeras_tpu.core.layers import TransformerBlock, Embedding
    from distkeras_tpu import Sequential
    enc = Sequential([Embedding(16, 32), TransformerBlock(4, 8, 64)],
                     input_shape=(8,), compute_dtype="float32")
    with pytest.raises(ValueError, match="causal"):
        init_cache(enc, 1, 8)
    # unsupported architectures are rejected up front
    from distkeras_tpu.core.layers import Conv2D
    from distkeras_tpu import Sequential
    bad = Sequential([Conv2D(4, 3)], input_shape=(8, 8, 1))
    with pytest.raises(ValueError, match="unsupported layer"):
        init_cache(bad, 1, 4)
