"""Tests for streaming ingestion + row-sparse embedding online learning
(``distkeras_tpu/streaming.py`` + ``row_sparse=`` across the PS stack).

Key invariants:
 - The **streaming lease contract**: a horizon re-leases through the
   unchanged ``LeaseLedger``/``WorkerSupervisor`` machinery, so killing k
   of N workers mid-horizon loses zero examples within the horizon
   (exactly-once completion asserted per horizon), clocks stay monotone,
   and a chaos soak passes under the streaming contract.
 - **Row-sparse embedding commits are EXACT**: a run with
   ``row_sparse=True`` is bit-identical to the dense run, sharded splits
   by row range are bit-identical, the PS row scatter-add equals the
   dense-gather reference, and commit bytes scale with touched rows, not
   table size (byte-counting double: ≤5% of dense at ~1% row touch).
 - **Ingest path discipline**: the socket feed receives every frame into
   reusable ``BufferPool`` scratch (transfer-counting double), and the
   bounded ``StreamBuffer`` applies producer backpressure.
 - ``stream=False`` defaults stay bit-identical (no streaming machinery
   constructed).

Tier-1 streaming trainings are generator-backed — no live sockets, no
sleeps; the socket-feed coverage uses ``socket.socketpair()`` only.
"""

import socket
import threading

import numpy as np
import pytest

from distkeras_tpu import ADAG, DOWNPOUR, AEASGD, Dataset, Sequential
from distkeras_tpu import networking
from distkeras_tpu.core.layers import Dense, Embedding, Flatten
from distkeras_tpu.parameter_servers import (DeltaParameterServer,
                                             SocketParameterServer,
                                             ThreadedSocketParameterServer,
                                             _row_scatter_add)
from distkeras_tpu.streaming import (StreamBuffer, StreamSource, feed_stream,
                                     embedding_weight_indices,
                                     resolve_row_sparse_tables)
from distkeras_tpu.workers import DOWNPOURWorker

V, D, C = 64, 8, 4


def make_mapping(seed=0):
    return np.random.default_rng(seed).integers(0, C, V)


def make_click_dataset(mapping, n=512, seed=0):
    rng = np.random.default_rng(seed)
    items = rng.integers(0, V, n).astype(np.int32).reshape(-1, 1)
    y = np.eye(C, dtype=np.float32)[mapping[items[:, 0]]]
    return Dataset({"features": items, "label": y})


def make_embedding_model(vocab=V, dim=D):
    return Sequential([Embedding(vocab, dim), Flatten(),
                       Dense(C, activation="softmax")],
                      input_shape=(1,), compute_dtype="float32")


def click_chunks(mapping, num_chunks, rows=64, seed=0, drift_to=None,
                 drift_at=None):
    """Generator of (x, y) chunks; from chunk ``drift_at`` on, labels come
    from ``drift_to`` instead of ``mapping`` — the drifting stream."""
    rng = np.random.default_rng(seed)
    for i in range(num_chunks):
        m = (drift_to if drift_at is not None and i >= drift_at
             else mapping)
        items = rng.integers(0, V, rows).astype(np.int32).reshape(-1, 1)
        yield items, np.eye(C, dtype=np.float32)[m[items[:, 0]]]


def eval_mapping_accuracy(fitted, mapping):
    items = np.arange(V, dtype=np.int32).reshape(-1, 1)
    return float((fitted.predict(items).argmax(-1) == mapping).mean())


# ---------------------------------------------------------------------------
# the bounded buffer
# ---------------------------------------------------------------------------

def test_stream_buffer_rows_fifo_and_copies():
    buf = StreamBuffer(capacity_rows=8)
    x = np.arange(6, dtype=np.int32).reshape(6, 1)
    y = np.arange(12, dtype=np.float32).reshape(6, 2)
    buf.push(x, y)
    ax, ay = buf.take(4)
    np.testing.assert_array_equal(ax[:, 0], [0, 1, 2, 3])
    assert ax.flags["OWNDATA"] and ay.flags["OWNDATA"]  # safe to keep
    buf.push(x[:4] + 100, y[:4])  # wraps around the ring
    bx, _ = buf.take(10)
    np.testing.assert_array_equal(bx[:, 0], [4, 5, 100, 101, 102, 103])
    buf.close()
    assert buf.take(1) is None
    with pytest.raises(RuntimeError, match="close"):
        buf.push(x, y)
    assert buf.rows_in == 10 and buf.rows_out == 10


def test_stream_buffer_backpressure_blocks_producer():
    """push() blocks while the ring is full and resumes when a consumer
    drains it — the OOM guard toward an over-fast feed."""
    buf = StreamBuffer(capacity_rows=4)
    x = np.arange(8, dtype=np.int32).reshape(8, 1)
    y = np.ones((8, 1), np.float32)
    done = threading.Event()

    def producer():
        buf.push(x, y)  # 8 rows through a 4-row ring: must block mid-way
        done.set()

    t = threading.Thread(target=producer)
    t.start()
    assert not done.wait(0.05)  # producer is blocked on the full ring
    ax, _ = buf.take(8)  # drains 4, unblocking the rest
    bx, _ = buf.take(8)
    assert done.wait(5.0)
    t.join()
    np.testing.assert_array_equal(np.concatenate([ax, bx])[:, 0],
                                  np.arange(8))
    with pytest.raises(TimeoutError):
        # 8 rows into the empty 4-row ring with no consumer: the push
        # fills the ring, blocks on the rest, and times out
        buf.push(x, y, timeout=0.01)


def test_stream_buffer_shape_mismatch_rejected():
    buf = StreamBuffer(capacity_rows=8)
    buf.push(np.zeros((2, 3), np.float32), np.zeros((2, 1), np.float32))
    with pytest.raises(ValueError, match="shaped"):
        buf.push(np.zeros((2, 4), np.float32), np.zeros((2, 1), np.float32))
    with pytest.raises(ValueError, match="rows"):
        buf.push(np.zeros((2, 3), np.float32), np.zeros((3, 1), np.float32))


# ---------------------------------------------------------------------------
# the stream source
# ---------------------------------------------------------------------------

@pytest.mark.stream
def test_stream_source_generator_reads_in_order_to_exhaustion():
    chunks = [(np.full((3, 1), i, np.int32), np.full((3, 2), i, np.float32))
              for i in range(5)]
    src = StreamSource(generator=iter(chunks), buffer_rows=4)
    x1, y1 = src.read(7)  # spans chunks; ring grows past its bound (sync)
    np.testing.assert_array_equal(x1[:, 0], [0, 0, 0, 1, 1, 1, 2])
    x2, _ = src.read(100)  # tail: whatever is left
    np.testing.assert_array_equal(x2[:, 0], [2, 2, 3, 3, 3, 4, 4, 4])
    assert src.read(1) is None  # exhausted and drained
    assert src.buffer.rows_in == 15 and src.buffer.rows_out == 15


def test_stream_source_socket_feed_reuses_pool_scratch():
    """SATELLITE: the socket feed's ingest loop receives every frame into
    reusable BufferPool scratch — a transfer-counting double asserts the
    per-batch receive is a pool HIT (one allocation per frame size, not
    per batch), and the delivered rows are owned copies."""

    class CountingPool(networking.BufferPool):
        def __init__(self):
            super().__init__()
            self.gets = []

        def get(self, size):
            self.gets.append(size)
            return super().get(size)

    a, b = socket.socketpair()
    rng = np.random.default_rng(0)
    chunks = [(rng.integers(0, V, 32).astype(np.int32).reshape(-1, 1),
               rng.standard_normal((32, C)).astype(np.float32))
              for _ in range(10)]
    feeder = threading.Thread(target=feed_stream, args=(a, chunks))
    feeder.start()
    pool = CountingPool()
    src = StreamSource(sock=b, pool=pool)
    try:
        out = src.read(320)
        feeder.join()
        x, y = out
        assert len(x) == 320
        np.testing.assert_array_equal(x[:32], chunks[0][0])
        np.testing.assert_array_equal(y[-32:], chunks[-1][1])
        assert x.flags["OWNDATA"]  # ring copies, not pool views
        assert src.read(1) is None  # {"end": True} closed the stream
        # transfer discipline: 11 same-shape frames (10 chunks + end),
        # each a pool acquisition; only the first of each frame SIZE may
        # miss — everything else reuses the same scratch
        assert pool.hits >= 8, (pool.hits, pool.misses)
        assert pool.misses <= 2, (pool.hits, pool.misses)
    finally:
        src.stop()
        a.close()


def test_stream_source_socket_eof_ends_stream():
    """A feed that dies mid-stream (EOF, no {"end"} frame) ends the stream
    where it broke instead of wedging the reader."""
    a, b = socket.socketpair()
    src = StreamSource(sock=b)
    networking.send_data(a, {"x": np.zeros((4, 1), np.int32),
                             "y": np.zeros((4, C), np.float32)})
    a.close()  # EOF mid-stream
    x, _ = src.read(100, timeout=10.0)
    assert len(x) == 4
    assert src.read(1, timeout=10.0) is None
    src.stop()


def test_stream_source_arg_validation():
    with pytest.raises(ValueError, match="exactly one"):
        StreamSource()
    with pytest.raises(ValueError, match="exactly one"):
        StreamSource(generator=iter([]), addr=("h", 1))


# ---------------------------------------------------------------------------
# row-sparse profile: table detection + exact apply
# ---------------------------------------------------------------------------

def test_embedding_table_detection_from_model_spec():
    import jax
    model = Sequential([Embedding(V, D), Flatten(),
                        Dense(16, activation="relu"),
                        Dense(C, activation="softmax")],
                       input_shape=(1,), compute_dtype="float32")
    params = model.init(jax.random.PRNGKey(0), (1,))
    assert embedding_weight_indices(model, params) == [0]
    assert resolve_row_sparse_tables(True, model, params) == [0]
    assert resolve_row_sparse_tables([0], model, params) == [0]
    with pytest.raises(ValueError, match="weights"):
        resolve_row_sparse_tables([99], model, params)
    with pytest.raises(ValueError, match="rows"):
        resolve_row_sparse_tables([1], model, params)  # a (dim,) bias/1-D
    dense_model = Sequential([Dense(4, activation="softmax")],
                             input_shape=(3,), compute_dtype="float32")
    dparams = dense_model.init(jax.random.PRNGKey(0), (3,))
    with pytest.raises(ValueError, match="no Embedding"):
        resolve_row_sparse_tables(True, dense_model, dparams)


def test_row_scatter_add_bit_identical_to_dense_reference():
    """ACCEPTANCE: the O(k·dim) row scatter-add equals the dense-gather
    reference (center += scale * densified_delta) BIT for bit, across
    scales and touch patterns."""
    rng = np.random.default_rng(3)
    for _ in range(10):
        rows_n = int(rng.integers(4, 40))
        dim = int(rng.integers(1, 9))
        k = int(rng.integers(0, rows_n + 1))
        rows = np.sort(rng.choice(rows_n, size=k, replace=False)).astype(
            np.int32)
        vals = rng.standard_normal((k, dim)).astype(np.float32)
        rsp = networking.RowSparseDelta(rows, vals, rows_n)
        scale = float(rng.uniform(0.25, 2.0))
        center = rng.standard_normal((rows_n, dim)).astype(np.float32)
        expect = center.copy()
        expect += scale * rsp.to_dense()  # the dense reference
        _row_scatter_add(center, rsp, scale)
        np.testing.assert_array_equal(center, expect)


def test_row_scatter_add_rejects_mis_split_commits():
    center = np.zeros((8, 4), np.float32)
    ok = networking.RowSparseDelta(np.array([1], np.int32),
                                   np.ones((1, 4), np.float32), 8)
    _row_scatter_add(center, ok)
    with pytest.raises(ValueError, match="declares"):
        _row_scatter_add(center, networking.RowSparseDelta(
            np.array([1], np.int32), np.ones((1, 4), np.float32), 9))
    with pytest.raises(ValueError, match="shaped"):
        _row_scatter_add(center, networking.RowSparseDelta(
            np.array([1], np.int32), np.ones((1, 3), np.float32), 8))
    with pytest.raises(ValueError, match="range"):
        _row_scatter_add(center, networking.RowSparseDelta(
            np.array([8], np.int32), np.ones((1, 4), np.float32), 8))


@pytest.mark.parametrize("server_cls", [SocketParameterServer,
                                        ThreadedSocketParameterServer])
def test_hostile_row_sparse_commit_dropped_without_corruption(server_cls):
    """A wire commit violating the row-sparse contract (duplicate rows —
    would double-apply; out-of-range — would corrupt a neighbour) is
    rejected at the transport boundary on BOTH cores: the connection
    drops like a torn frame, the center and clock are untouched, and the
    server keeps serving."""
    blob = {"model": make_embedding_model().to_json(),
            "weights": [np.zeros((8, 4), np.float32)]}
    ps = DeltaParameterServer(blob)
    server = server_cls(ps)
    server.start()
    try:
        for rows in ([2, 2], [9], [-1], [5, 3]):
            sock = networking.connect("127.0.0.1", server.port)
            networking.send_opcode(sock, b"u")
            networking.send_data(sock, {
                "delta": [networking.RowSparseDelta(
                    np.asarray(rows, np.int32),
                    np.ones((len(rows), 4), np.float32), 8)],
                "worker_id": 0, "clock": 0})
            # the server must drop the connection, not reply
            sock.settimeout(5.0)
            with pytest.raises((ConnectionError, socket.timeout, ValueError)):
                reply = networking.recv_data(sock)
                raise ValueError(f"server applied a hostile commit: {reply}")
            sock.close()
        assert ps.num_updates == 0
        np.testing.assert_array_equal(ps.center[0], 0.0)
        # still serves a healthy commit
        ok = networking.connect("127.0.0.1", server.port)
        networking.send_opcode(ok, b"u")
        networking.send_data(ok, {
            "delta": [networking.RowSparseDelta(
                np.array([1, 3], np.int32),
                np.ones((2, 4), np.float32), 8)],
            "worker_id": 0, "clock": 0})
        assert networking.recv_data(ok)["clock"] == 1
        networking.send_opcode(ok, b"q")
        ok.close()
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# row-sparse end to end: bit-identity + commit-byte scaling
# ---------------------------------------------------------------------------

def _run_clicks(row_sparse, shards=1, mapping=None, algorithm=DOWNPOUR):
    ds = make_click_dataset(mapping if mapping is not None
                            else make_mapping())
    t = algorithm(make_embedding_model(), num_workers=1, batch_size=16,
                  num_epoch=2, communication_window=2, learning_rate=0.5,
                  execution="host_ps", row_sparse=row_sparse,
                  ps_shards=shards)
    fitted = t.train(ds)
    return t, fitted.get_weights()


def test_row_sparse_run_bit_identical_to_dense():
    """ACCEPTANCE: a deterministic single-worker DOWNPOUR run with
    row_sparse=True produces BIT-identical weights to the dense run — the
    profile is exact (support detected from the delta itself), and a
    dense apply only ever adds exact zeros where row-sparse skips."""
    _, w_dense = _run_clicks(None)
    _, w_rs = _run_clicks(True)
    for a, b in zip(w_dense, w_rs):
        np.testing.assert_array_equal(a, b)


def test_row_sparse_sharded_split_bit_identical():
    """Row-range shard splitting is exact: single-worker N-shard
    row-sparse runs match the 1-shard run bit for bit (every touched row
    lands on exactly one shard, in local coordinates)."""
    _, w1 = _run_clicks(True, shards=1)
    _, w3 = _run_clicks(True, shards=3)
    for a, b in zip(w1, w3):
        np.testing.assert_array_equal(a, b)


def test_row_sparse_commit_bytes_scale_with_touched_rows():
    """ACCEPTANCE: embedding commit bytes scale with the rows a window
    touched, not the table size — a byte-counting double around the real
    worker transport shows row-sparse commits at ≤5% of the dense commit
    at ~1% row touch."""
    vocab = 8192  # large table; each window touches ≤ 32 rows (0.4%)
    mapping = np.random.default_rng(0).integers(0, C, vocab)
    rng = np.random.default_rng(1)
    items = rng.integers(0, vocab, 256).astype(np.int32).reshape(-1, 1)
    ds = Dataset({"features": items,
                  "label": np.eye(C, dtype=np.float32)[mapping[items[:, 0]]]})

    commit_bytes = {}

    def run(row_sparse):
        t = DOWNPOUR(make_embedding_model(vocab=vocab), num_workers=1,
                     batch_size=16, num_epoch=1, communication_window=2,
                     learning_rate=0.5, execution="host_ps",
                     row_sparse=row_sparse, comm_overlap=False)
        sizes = []
        orig = DOWNPOURWorker._send_request

        def counting(self, op, msg):
            sizes.append(len(networking.encode_message(msg)))
            return orig(self, op, msg)

        DOWNPOURWorker._send_request = counting
        try:
            t.train(ds)
        finally:
            DOWNPOURWorker._send_request = orig
        commit_bytes[bool(row_sparse)] = sizes

    run(None)
    run(True)
    dense = np.mean(commit_bytes[False])
    sparse = np.mean(commit_bytes[True])
    # dense commits carry the whole (8192, 8) table every window;
    # row-sparse carries ≤ window·batch touched rows
    assert sparse <= 0.05 * dense, (sparse, dense)
    # and the dense table really dominates the dense commit
    assert dense > vocab * D * 4


def test_row_sparse_knob_validation():
    m = make_embedding_model()
    kw = dict(num_workers=1, batch_size=16)
    t = DOWNPOUR(m, execution="host_ps", row_sparse=True, **kw)
    assert t.row_sparse is True and t.comm_overlap is False
    assert DOWNPOUR(m, execution="host_ps", **kw).row_sparse is None
    with pytest.raises(ValueError, match="host_ps"):
        DOWNPOUR(m, row_sparse=True, **kw)  # SPMD: no PS wire
    with pytest.raises(ValueError, match="delta family"):
        AEASGD(m, execution="host_ps", row_sparse=True, **kw)
    with pytest.raises(ValueError, match="compose"):
        DOWNPOUR(m, execution="host_ps", row_sparse=True,
                 wire_dtype="topk", **kw)
    # worker-level guards (direct construction)
    import jax
    params = m.init(jax.random.PRNGKey(0), (1,))
    blob = {"model": m.to_json(), "weights": m.get_weights(params)}
    with pytest.raises(ValueError, match="row"):
        DOWNPOURWorker(blob, "sgd", "categorical_crossentropy",
                       "127.0.0.1", 1, row_sparse_tables=[1])  # 1-D weight
    with pytest.raises(ValueError, match="comm_overlap"):
        DOWNPOURWorker(blob, "sgd", "categorical_crossentropy",
                       "127.0.0.1", 1, row_sparse_tables=[0],
                       comm_overlap=True)


# ---------------------------------------------------------------------------
# streaming end to end: the horizon contract
# ---------------------------------------------------------------------------

@pytest.mark.stream
def test_stream_training_accuracy_tracks_drift():
    """ACCEPTANCE: online learning on a drifting stream — labels remap for
    half the vocabulary mid-stream; per-horizon accuracy against the LIVE
    mapping must recover into the asserted band after the drift."""
    map_a = make_mapping(seed=0)
    map_b = map_a.copy()
    flip = np.random.default_rng(1).permutation(V)[: V // 2]
    map_b[flip] = (map_b[flip] + 1) % C

    gen = click_chunks(map_a, num_chunks=24, rows=64, seed=2,
                       drift_to=map_b, drift_at=12)
    accs = []

    def on_horizon(h, fitted):
        live = map_a if h < 2 else map_b  # horizons 0-1 pre-drift
        accs.append(eval_mapping_accuracy(fitted, live))

    t = DOWNPOUR(make_embedding_model(), num_workers=1, batch_size=16,
                 num_epoch=1, communication_window=2, learning_rate=0.5,
                 execution="host_ps", stream=True, horizon_windows=12,
                 row_sparse=True)
    t.on_horizon = on_horizon
    fitted = t.train(StreamSource(generator=gen))
    assert t.stream_stats["horizons"] == 4
    assert t.stream_stats["rows"] == 24 * 64
    # pre-drift the model is learning mapping A...
    assert accs[1] > 0.6, accs
    # ...and after the drift it tracks mapping B (the asserted band: the
    # post-drift horizons RECOVER past the pre-drift level, online)
    assert accs[-1] > 0.8, accs
    assert accs[-1] >= accs[1], accs
    assert eval_mapping_accuracy(fitted, map_b) > 0.8
    # every horizon completed its ledger exactly once
    for h in range(t.stream_stats["horizons"]):
        rep = t.elastic_stats["lease_completions"][h]
        assert rep["completed"] == rep["leases"]


@pytest.mark.stream
@pytest.mark.parametrize("cls,shards", [(DOWNPOUR, 1), (ADAG, 3)])
def test_stream_kill_workers_mid_horizon_zero_loss(cls, shards):
    """ACCEPTANCE: kill k of N workers mid-horizon (one 'exit', one
    'hang') under the streaming contract — zero examples lost within any
    horizon (exactly-once ledger per horizon), clocks monotone, the
    stream drains to the end, and the model still learns."""
    mapping = make_mapping()
    t = cls(make_embedding_model(), num_workers=4, batch_size=16,
            num_epoch=1, communication_window=2, learning_rate=0.5,
            execution="host_ps", stream=True, horizon_windows=16,
            row_sparse=True, ps_shards=shards, lease_timeout=0.5,
            fault_injection={1: ("exit", 2), 2: ("hang", 3)})
    fitted = t.train(StreamSource(
        generator=click_chunks(mapping, num_chunks=24, rows=64, seed=3)))
    stats = t.elastic_stats
    assert t.stream_stats["horizons"] >= 1
    assert t.stream_stats["rows"] == 24 * 64  # the whole stream trained
    for h in range(t.stream_stats["horizons"]):
        rep = stats["lease_completions"][h]
        assert rep["completed"] == rep["leases"], rep
    assert {1, 2} <= set(t.failed_workers)
    assert stats["respawns"] >= 1
    for w in t._ps_workers:
        client = getattr(w, "_shard_client", None)
        regressions = (client.clock_regressions if client is not None
                       else w.clock_regressions)
        assert regressions == 0
    assert eval_mapping_accuracy(fitted, mapping) > 0.7


@pytest.mark.stream
def test_stream_tail_horizon_takes_the_remainder():
    """A stream whose row count is not a horizon multiple trains the tail
    as a smaller final horizon — nothing dropped, nothing padded across
    horizons."""
    mapping = make_mapping()
    # 5 chunks of 64 rows = 320; horizon = 4 windows × 2 × 16 = 128 rows
    t = DOWNPOUR(make_embedding_model(), num_workers=1, batch_size=16,
                 num_epoch=1, communication_window=2, learning_rate=0.5,
                 execution="host_ps", stream=True, horizon_windows=4)
    t.train(StreamSource(
        generator=click_chunks(mapping, num_chunks=5, rows=64, seed=4)))
    assert t.stream_stats["horizons"] == 3  # 128 + 128 + 64
    assert t.stream_stats["rows"] == 320
    reps = t.elastic_stats["lease_completions"]
    assert reps[0]["rows_completed"] == 128
    assert reps[2]["rows_completed"] == 64


@pytest.mark.stream
def test_stream_max_horizons_bounds_an_unbounded_source():
    """max_horizons ends the run even though the source never does."""
    mapping = make_mapping()

    def forever():
        rng = np.random.default_rng(5)
        while True:
            items = rng.integers(0, V, 64).astype(np.int32).reshape(-1, 1)
            yield items, np.eye(C, dtype=np.float32)[mapping[items[:, 0]]]

    t = DOWNPOUR(make_embedding_model(), num_workers=1, batch_size=16,
                 num_epoch=1, communication_window=2, learning_rate=0.5,
                 execution="host_ps", stream=True, horizon_windows=4,
                 max_horizons=2)
    t.train(StreamSource(generator=forever()))
    assert t.stream_stats["horizons"] == 2
    assert t.stream_stats["rows"] == 2 * 128


def test_stream_knob_validation():
    m = make_embedding_model()
    kw = dict(num_workers=1, batch_size=16)
    t = DOWNPOUR(m, execution="host_ps", stream=True, **kw)
    assert t.stream is True and t.horizon_windows is None
    assert DOWNPOUR(m, execution="host_ps", **kw).stream is False
    with pytest.raises(ValueError, match="stream"):
        DOWNPOUR(m, stream=True, **kw)  # SPMD has no stream path
    with pytest.raises(ValueError, match="stream"):
        DOWNPOUR(m, execution="process_ps", stream=True, **kw)
    with pytest.raises(ValueError, match="horizon_windows"):
        DOWNPOUR(m, execution="host_ps", stream=True, horizon_windows=0,
                 **kw)
    with pytest.raises(ValueError, match="horizon_windows"):
        DOWNPOUR(m, execution="host_ps", horizon_windows=4, **kw)
    with pytest.raises(ValueError, match="max_horizons"):
        DOWNPOUR(m, execution="host_ps", max_horizons=1, **kw)
    # stream=True trains from a StreamSource, not a Dataset
    t2 = DOWNPOUR(m, execution="host_ps", stream=True, **kw)
    with pytest.raises(ValueError, match="StreamSource"):
        t2.train(make_click_dataset(make_mapping()))
    # no checkpointing across horizons
    t3 = DOWNPOUR(m, execution="host_ps", stream=True,
                  checkpoint_dir="/tmp/nope", **kw)
    with pytest.raises(ValueError, match="horizon"):
        t3.train(StreamSource(generator=iter([])))


def test_stream_false_default_is_bit_identical():
    """stream/row_sparse default off and the default path is byte-for-byte
    the PR 9 engine: a deterministic single-worker host_ps run yields
    identical weights across invocations and never constructs streaming
    machinery."""
    mapping = make_mapping()
    ds = make_click_dataset(mapping, n=256)

    def run():
        t = DOWNPOUR(make_embedding_model(), num_workers=1, batch_size=16,
                     num_epoch=1, communication_window=2, learning_rate=0.5,
                     execution="host_ps")
        fitted = t.train(ds)
        return t, fitted.get_weights()

    t1, w1 = run()
    t2, w2 = run()
    assert t1.stream is False and t1.row_sparse is None
    assert t1.stream_stats == {}
    assert not hasattr(t1, "_worker_supervisor")
    for a, b in zip(w1, w2):
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# chaos soak under the streaming contract (slow path)
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.stream
def test_stream_chaos_soak():
    """Soak: a long drifting stream under compound chaos — a ChaosProxy
    between workers and every PS shard injecting seeded resets/delays,
    shard recovery on, and worker faults ('exit' + 'hang') with staggered
    budgets so the killing continues across membership churn.  Every
    horizon must complete its ledger exactly once and the model must
    track the drifted mapping at the end."""
    map_a = make_mapping(seed=0)
    map_b = map_a.copy()
    flip = np.random.default_rng(2).permutation(V)[: V // 2]
    map_b[flip] = (map_b[flip] + 1) % C

    proxies = []

    def hook(addrs):
        out = []
        for h, p in addrs:
            proxy = networking.ChaosProxy(h, p, seed=7,
                                          auto={"delay": (0.02, 0.01)})
            proxies.append(proxy)
            out.append(proxy.addr)
        return out

    t = ADAG(make_embedding_model(), num_workers=4, batch_size=16,
             num_epoch=1, communication_window=2, learning_rate=0.5,
             execution="host_ps", stream=True, horizon_windows=16,
             row_sparse=True, ps_shards=2, recovery=True,
             lease_timeout=1.0,
             fault_injection={0: ("exit", 2), 1: ("exit", 6),
                              2: ("hang", 10)})
    t._shard_addr_hook = hook
    gen = click_chunks(map_a, num_chunks=72, rows=64, seed=9,
                       drift_to=map_b, drift_at=24)
    try:
        fitted = t.train(StreamSource(generator=gen))
    finally:
        for proxy in proxies:
            proxy.stop()
    assert t.stream_stats["rows"] == 72 * 64
    for h in range(t.stream_stats["horizons"]):
        rep = t.elastic_stats["lease_completions"][h]
        assert rep["completed"] == rep["leases"], rep
    assert t.elastic_stats["respawns"] >= 2
    assert eval_mapping_accuracy(fitted, map_b) > 0.75
