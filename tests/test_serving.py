"""Continuous-batching serving engine (distkeras_tpu/serving.py).

The invariants pinned here are the engine's whole contract:

 - a lone request through the engine emits tokens BIT-IDENTICAL to offline
   ``generate`` under the same seed/params (greedy, sampled top-k/top-p,
   eos stopping, rolling-window caches) — the slot pool is an execution
   strategy, never a numerics change;
 - the slot lifecycle: admission → prefill → decode → eos/length
   retirement → slot reuse, including a mixed-length batch where a short
   request retires and a queued one back-fills its slot MID-RUN (the
   continuous-batching property itself);
 - bounded-queue backpressure (``QueueFull``), in process and over the
   wire;
 - the per-row ``decode_step``/sampling substrate matches the scalar path
   row for row.
"""

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distkeras_tpu.core import decode
from distkeras_tpu.core.model import FittedModel, serialize_model
from distkeras_tpu.models import transformer_lm
from distkeras_tpu.serving import (QueueFull, ServingClient, ServingEngine,
                                   ServingServer)

VOCAB = 17


def _fitted(seed=0, **kw):
    model = transformer_lm(vocab_size=VOCAB, seq_len=32, d_model=16,
                           num_heads=2, num_layers=2, mlp_dim=32,
                           compute_dtype="float32", **kw)
    params = model.init(jax.random.PRNGKey(seed), (32,))
    return FittedModel(model, params)


@pytest.fixture(scope="module")
def fitted():
    return _fitted()


PROMPT = np.array([3, 4, 5, 6], np.int32)


# ---------------------------------------------------------------------------
# bit-identity with offline generate
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kw", [
    {},                                                       # greedy
    {"temperature": 0.7, "seed": 11},                         # plain sample
    {"temperature": 0.7, "top_k": 5, "top_p": 0.9, "seed": 11},
])
def test_lone_request_bit_identical_to_generate(fitted, kw):
    eng = ServingEngine(fitted, num_slots=3, max_len=24)
    h = eng.submit(PROMPT, 8, **kw)
    eng.run_until_idle()
    gkw = dict(kw)
    seed = gkw.pop("seed", None)
    if seed is not None:
        gkw["rng"] = jax.random.PRNGKey(seed)
    want = np.asarray(fitted.generate(PROMPT[None], 8, max_len=24, **gkw))[0]
    np.testing.assert_array_equal(h.result(), want)


def test_eos_stopping_matches_generate(fitted):
    greedy = np.asarray(fitted.generate(PROMPT[None], 8, max_len=24))[0]
    eos = int(greedy[len(PROMPT) + 2])  # a token greedy WILL emit
    eng = ServingEngine(fitted, num_slots=2, max_len=24)
    h = eng.submit(PROMPT, 8, eos_id=eos, pad_id=1)
    eng.run_until_idle()
    want = np.asarray(fitted.generate(PROMPT[None], 8, eos_id=eos, pad_id=1,
                                      max_len=24))[0]
    np.testing.assert_array_equal(h.result(), want)
    assert h.finish == "eos"
    assert len(h.tokens) < 8  # retired early; result() pads to num_steps


def test_rolling_slots_bit_identical(fitted):
    windowed = _fitted(seed=1, attention_window=6)
    eng = ServingEngine(windowed, num_slots=2, max_len=24, rolling=True)
    long_p = np.arange(1, 8, dtype=np.int32) % VOCAB
    h1 = eng.submit(long_p, 10, temperature=0.6, seed=9)
    h2 = eng.submit(np.array([1, 2], np.int32), 6)
    eng.run_until_idle()
    w1 = np.asarray(windowed.generate(long_p[None], 10, temperature=0.6,
                                      rng=jax.random.PRNGKey(9),
                                      rolling=True, max_len=24))[0]
    w2 = np.asarray(windowed.generate(np.array([[1, 2]], np.int32), 6,
                                      rolling=True, max_len=24))[0]
    np.testing.assert_array_equal(h1.result(), w1)
    np.testing.assert_array_equal(h2.result(), w2)
    # the pool really is a ring: W slots per block, not max_len
    assert eng.caches[2]["k"].shape[1] == 6


# ---------------------------------------------------------------------------
# slot lifecycle: admission → prefill → decode → retirement → reuse
# ---------------------------------------------------------------------------

def test_mixed_length_batch_backfills_mid_run(fitted):
    """2 slots, 3 requests: the short one retires first and the queued
    third back-fills its slot while the long one is still decoding."""
    eng = ServingEngine(fitted, num_slots=2, max_len=24)
    long_h = eng.submit(np.array([1, 2, 3], np.int32), 14)
    short_h = eng.submit(np.array([4, 5], np.int32), 3)
    queued_h = eng.submit(np.array([6, 7, 8, 9], np.int32), 5)
    assert eng.queue_depth == 3 and not eng._active.any()
    eng.run_until_idle()
    # zero requests lost; outputs still match offline generate
    for h in (long_h, short_h, queued_h):
        assert h.finish == "length"
        want = np.asarray(fitted.generate(h.prompt[None], h.num_steps,
                                          max_len=24))[0]
        np.testing.assert_array_equal(h.result(), want)
    # the third request reused the short one's slot, MID-run of the long one
    assert queued_h.slot == short_h.slot
    assert queued_h.started_at < long_h.finished_at
    # every slot served at least one request; the short slot served two
    assert all(n >= 1 for n in eng.stats["slot_requests"])
    assert eng.stats["slot_requests"][short_h.slot] == 2
    assert eng.stats["requests_completed"] == 3
    assert eng.slot_occupancy > 0.5


def test_many_requests_zero_lost_every_slot_reused(fitted):
    eng = ServingEngine(fitted, num_slots=2, max_len=24)
    rng = np.random.default_rng(0)
    handles = []
    for i in range(7):
        p_len = int(rng.integers(1, 6))
        steps = int(rng.integers(1, 8))
        prompt = rng.integers(0, VOCAB, p_len).astype(np.int32)
        handles.append(eng.submit(prompt, steps, temperature=0.5,
                                  seed=100 + i))
    eng.run_until_idle()
    assert eng.stats["requests_completed"] == 7  # zero lost
    assert all(n >= 2 for n in eng.stats["slot_requests"])  # all reused
    for h in handles:
        want = np.asarray(fitted.generate(h.prompt[None], h.num_steps,
                                          temperature=0.5, rng=h.key,
                                          max_len=24))[0]
        np.testing.assert_array_equal(h.result(), want)


def test_retired_slot_state_is_cleared(fitted):
    eng = ServingEngine(fitted, num_slots=1, max_len=24)
    h = eng.submit(PROMPT, 3, temperature=0.9, top_k=3, seed=5)
    eng.run_until_idle()
    assert h.done and eng._handles[0] is None
    assert not eng._active.any()
    assert eng._temp[0] == 0.0 and eng._topk[0] == 0 and eng._topp[0] == 0.0
    assert eng._free == [0]
    # a greedy follow-up through the same slot is unpolluted by the
    # previous occupant's sampling params
    h2 = eng.submit(PROMPT, 4)
    eng.run_until_idle()
    want = np.asarray(fitted.generate(PROMPT[None], 4, max_len=24))[0]
    np.testing.assert_array_equal(h2.result(), want)


def test_num_steps_zero_completes_without_slot(fitted):
    eng = ServingEngine(fitted, num_slots=1, max_len=24)
    h = eng.submit(PROMPT, 0)
    assert h.done and h.finish == "empty"
    np.testing.assert_array_equal(h.result(), PROMPT)
    assert eng.queue_depth == 0


# ---------------------------------------------------------------------------
# admission queue + backpressure
# ---------------------------------------------------------------------------

def test_queue_backpressure_sheds(fitted):
    eng = ServingEngine(fitted, num_slots=1, max_len=24, queue_capacity=2)
    eng.submit(PROMPT, 4)
    eng.submit(PROMPT, 4)
    with pytest.raises(QueueFull):
        eng.submit(PROMPT, 4, block=False)
    with pytest.raises(QueueFull):
        eng.submit(PROMPT, 4, timeout=0.05)  # blocking, bounded wait
    assert eng.stats["requests_rejected"] == 2
    eng.run_until_idle()
    assert eng.stats["requests_completed"] == 2


def test_blocking_submit_unblocks_when_queue_drains(fitted):
    eng = ServingEngine(fitted, num_slots=1, max_len=24, queue_capacity=1)
    eng.submit(PROMPT, 2)
    results = []

    def producer():
        results.append(eng.submit(PROMPT, 2, timeout=10.0))

    t = threading.Thread(target=producer)
    t.start()
    eng.run_until_idle()   # drains the queue, freeing capacity
    t.join(timeout=10.0)
    assert not t.is_alive() and len(results) == 1
    eng.run_until_idle()
    assert results[0].done


def test_submit_validation(fitted):
    eng = ServingEngine(fitted, num_slots=1, max_len=16)
    with pytest.raises(ValueError, match="exceeds the engine's max_len"):
        eng.submit(np.arange(10, dtype=np.int32) % VOCAB, 10)
    with pytest.raises(ValueError, match="1-D"):
        eng.submit(PROMPT[None], 4)
    with pytest.raises(ValueError, match="top_k"):
        eng.submit(PROMPT, 4, temperature=0.5, top_k=0)
    with pytest.raises(ValueError, match="vocabulary"):
        eng.submit(PROMPT, 4, eos_id=VOCAB + 3)
    with pytest.raises(ValueError, match="max_len"):
        ServingEngine(fitted, num_slots=1, max_len=64)  # > positional range


# ---------------------------------------------------------------------------
# background thread + wire server
# ---------------------------------------------------------------------------

def test_background_thread_drives_requests(fitted):
    with ServingEngine(fitted, num_slots=2, max_len=24) as eng:
        h = eng.submit(PROMPT, 6)
        assert h.wait(timeout=30.0)
    want = np.asarray(fitted.generate(PROMPT[None], 6, max_len=24))[0]
    np.testing.assert_array_equal(h.result(), want)


def test_wire_server_roundtrip_and_streaming(fitted, server_core):
    with ServingServer(ServingEngine(fitted, num_slots=2, max_len=24)) as srv:
        with ServingClient(*srv.addr) as c:
            rid = c.submit(PROMPT, 6, temperature=0.7, top_k=5, seed=11)
            chunks, final = [], None
            for tokens, done in c.stream(rid):
                chunks.append(tokens)
                if done is not None:
                    final = done
            want = np.asarray(fitted.generate(
                PROMPT[None], 6, temperature=0.7, top_k=5,
                rng=jax.random.PRNGKey(11), max_len=24))[0]
            np.testing.assert_array_equal(final["row"], want)
            # the streamed chunks concatenate to the emitted tokens
            np.testing.assert_array_equal(np.concatenate(chunks),
                                          want[len(PROMPT):])
            assert final["finish"] == "length"
            # one-call form on the same connection
            np.testing.assert_array_equal(c.generate(PROMPT, 6),
                np.asarray(fitted.generate(PROMPT[None], 6, max_len=24))[0])


def test_wire_server_backpressure_reply(fitted, server_core):
    eng = ServingEngine(fitted, num_slots=1, max_len=24, queue_capacity=1)
    with ServingServer(eng) as srv:
        with ServingClient(*srv.addr) as c:
            # saturate: the engine thread may drain some, so push until shed
            with pytest.raises(QueueFull):
                for _ in range(200):
                    c.submit(PROMPT, 12)
    assert eng.stats["requests_rejected"] >= 1


def test_wire_server_bad_request_reply(fitted, server_core):
    with ServingServer(ServingEngine(fitted, num_slots=1, max_len=16)) as srv:
        with ServingClient(*srv.addr) as c:
            with pytest.raises(ValueError, match="max_len"):
                c.submit(np.arange(12, dtype=np.int32) % VOCAB, 12)
            with pytest.raises(ValueError, match="unknown id"):
                list(c.stream(999))


# ---------------------------------------------------------------------------
# hot weight reload (stretch: training and serving share one deployment)
# ---------------------------------------------------------------------------

def test_hot_reload_pulls_fresh_center(fitted):
    from distkeras_tpu.parameter_servers import (DeltaParameterServer,
                                                 SocketParameterServer)
    blob = serialize_model(fitted.model, fitted.params)
    ps = SocketParameterServer(DeltaParameterServer(blob))
    ps.start()
    try:
        eng = ServingEngine(_fitted(), num_slots=2, max_len=24)
        eng.attach_ps("127.0.0.1", ps.port, every=1)
        before = [w.copy() for w in eng.model.get_weights(eng.params)]
        ps.ps.handle_commit(
            {"delta": [np.ones_like(w) for w in blob["weights"]]})
        eng.submit(PROMPT, 4)
        eng.run_until_idle()
        assert eng.stats["weight_reloads"] >= 1
        after = eng.model.get_weights(eng.params)
        assert any((np.asarray(a) != b).any()
                   for a, b in zip(after, before))
        eng.stop()
    finally:
        ps.stop()


# ---------------------------------------------------------------------------
# engine-backed ModelPredictor route
# ---------------------------------------------------------------------------

def test_model_predictor_engine_route(fitted):
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.predictors import ModelPredictor

    prompts = np.stack([PROMPT, PROMPT[::-1].copy(), (PROMPT + 1) % VOCAB])
    ds = Dataset({"features": prompts})
    eng = ServingEngine(fitted, num_slots=2, max_len=24)
    pred = ModelPredictor(fitted, engine=eng, num_steps=5,
                          generate_kwargs={"temperature": 0.6, "seed": 3})
    out = pred.predict(ds)["prediction"]
    assert out.shape == (3, len(PROMPT) + 5)
    for row, prompt in zip(out, prompts):  # per-request generate parity
        want = np.asarray(fitted.generate(
            prompt[None], 5, temperature=0.6,
            rng=jax.random.PRNGKey(3), max_len=24))[0]
        np.testing.assert_array_equal(row, want)
    assert eng._thread is None  # predictor stopped the thread it started


def test_model_predictor_default_path_unchanged(fitted):
    """No engine constructed → the original sharded-numpy forward, same
    values as Sequential.predict (the defaults-bit-identical gate)."""
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.predictors import ModelPredictor

    ds = Dataset({"features": np.stack([PROMPT, (PROMPT + 2) % VOCAB])})
    out = ModelPredictor(fitted, mesh=None).predict(ds)["prediction"]
    want = fitted.model.predict(fitted.params,
                                np.asarray(ds["features"]))
    np.testing.assert_array_equal(out, want)


def test_model_predictor_engine_needs_num_steps(fitted):
    from distkeras_tpu.predictors import ModelPredictor
    eng = ServingEngine(fitted, num_slots=1, max_len=24)
    with pytest.raises(ValueError, match="num_steps"):
        ModelPredictor(fitted, engine=eng)


# ---------------------------------------------------------------------------
# per-row decode substrate (the satellite fix in core/decode.py)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("positional", ["learned", "rope"])
def test_per_row_positions_match_scalar_decode(positional):
    fm = _fitted(seed=2, positional=positional)
    model, params = fm.model, fm.params
    prompt = np.array([[3, 4, 5, 6], [7, 8, 9, 1]], np.int32)
    want = np.asarray(fm.generate(prompt, 6, max_len=16))
    caches = decode.init_cache(model, 2, 16)
    logits, caches = decode._forward(model, params, caches,
                                     jnp.asarray(prompt), 0)
    tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
    got = [tok]
    pos = jnp.array([4, 4], jnp.int32)   # per-row vector, equal values
    step = jax.jit(lambda p, c, t, q: decode.decode_step(model, p, c, t, q))
    for i in range(5):
        lg, caches = step(params, caches, tok, pos + i)
        tok = jnp.argmax(lg, axis=-1).astype(jnp.int32)
        got.append(tok)
    np.testing.assert_array_equal(
        np.stack([np.asarray(t) for t in got], 1), want[:, 4:])


def test_per_row_multi_token_forward_matches_chain():
    """Per-row positions with L > 1 (PR 11's speculative verify): one
    batched forward over L tokens at each row's own offset produces the
    same logits as L single-token per-row steps — the substrate the
    engine's draft-then-verify round stands on."""
    fm = _fitted(seed=2)
    prompt = jnp.asarray([[3, 4, 5], [9, 2, 7]], jnp.int32)
    caches = decode.init_cache(fm.model, 2, 16)
    logits, caches = decode._forward(fm.model, fm.params, caches, prompt, 0)
    tok = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
    pos = jnp.array([3, 3], jnp.int32)
    chain, toks, cc = [], [tok], caches
    for i in range(3):
        lg, cc = decode.decode_step(fm.model, fm.params, cc, toks[-1],
                                    pos + i)
        chain.append(lg)
        toks.append(jnp.argmax(lg, -1).astype(jnp.int32))
    fed = jnp.stack(toks[:3], axis=1)                          # (2, 3)
    multi, _ = decode._forward(fm.model, fm.params, caches, fed, pos)
    for i in range(3):
        np.testing.assert_allclose(np.asarray(multi[:, i]),
                                   np.asarray(chain[i]),
                                   rtol=2e-5, atol=2e-5)


def test_batched_sampler_matches_scalar_rows():
    """sample_logits_batched row-for-row == sample_logits with that row's
    scalar params (the engine's bit-identity substrate)."""
    rng = np.random.default_rng(3)
    logits = jnp.asarray(rng.standard_normal((4, VOCAB)), jnp.float32)
    keys = jnp.stack([jax.random.PRNGKey(i) for i in range(4)])
    positions = jnp.array([3, 9, 1, 7])
    temp = jnp.array([0.0, 0.5, 0.7, 1.3], jnp.float32)
    topk = jnp.array([0, 4, 4, 0], jnp.int32)
    topp = jnp.array([0.0, 0.0, 0.9, 0.6], jnp.float32)
    got = np.asarray(jax.jit(decode.sample_logits_batched)(
        logits, positions, temp, keys, topk, topp))
    for r in range(4):
        want = decode.sample_logits(
            logits[r:r + 1], int(positions[r]), float(temp[r]),
            jax.random.PRNGKey(r),
            int(topk[r]) or None,
            float(topp[r]) or None)
        assert got[r] == int(np.asarray(want)[0]), f"row {r}"


def test_generate_unchanged_by_sampling_factor():
    """The factored sample_logits left generate's defaults bit-identical:
    two invocations and a pre/post-refactor spot value agree."""
    fm = _fitted(seed=4)
    a = np.asarray(fm.generate(PROMPT[None], 8, temperature=0.7, top_k=4,
                               top_p=0.9, rng=jax.random.PRNGKey(0)))
    b = np.asarray(fm.generate(PROMPT[None], 8, temperature=0.7, top_k=4,
                               top_p=0.9, rng=jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# paged-KV substrate (PR 12): block-table decode is a storage relayout
# ---------------------------------------------------------------------------

@pytest.mark.paged
def test_paged_decode_step_bit_identical_to_dense():
    """Raw substrate parity: the same prefill + decode chain through a
    dense (B, S) cache and through a flat block arena + block tables
    produces BIT-identical logits at every step (the paged serving
    engine's exactness rests on this)."""
    fm = _fitted(seed=6)
    model, params = fm.model, fm.params
    B, max_len, bs = 2, 16, 4
    nblocks = B * (max_len // bs)
    dense = decode.init_cache(model, B, max_len)
    arena = decode.init_paged_arena(model, nblocks, bs)
    bt = np.full((B, max_len // bs + 1), nblocks, np.int32)
    for r in range(B):
        bt[r, :max_len // bs] = np.arange(max_len // bs) + r * (
            max_len // bs)
    bt = jnp.asarray(bt)
    prompt = jnp.asarray(np.stack([PROMPT, PROMPT[::-1].copy()]))
    zero = jnp.zeros((B,), jnp.int32)
    ld, dense = decode._forward(model, params, dense, prompt, 0)
    pv = decode.PagedView(bt, bs, max_len, floor=zero,
                          ceil=jnp.full((B,), 4, jnp.int32),
                          qcap=jnp.full((B,), 3, jnp.int32))
    lp, arena = decode._forward(model, params, arena, prompt, zero,
                                paged=pv)
    np.testing.assert_array_equal(np.asarray(ld), np.asarray(lp))
    tok = jnp.argmax(ld[:, -1], axis=-1).astype(jnp.int32)
    pos = jnp.full((B,), 4, jnp.int32)
    pvd = decode.PagedView(bt, bs, max_len)
    for _ in range(6):
        ld, dense = decode.decode_step(model, params, dense, tok, pos)
        lp, arena = decode.decode_step(model, params, arena, tok, pos,
                                       paged=pvd)
        np.testing.assert_array_equal(np.asarray(ld), np.asarray(lp))
        tok = jnp.argmax(ld, axis=-1).astype(jnp.int32)
        pos = pos + 1


@pytest.mark.paged
def test_paged_write_floor_protects_shared_blocks():
    """The copy-on-write safety rail: writes below a row's ``floor`` land
    in the NULL block, so a sharer can run the full forward over a prompt
    whose prefix blocks belong to someone else without perturbing them."""
    fm = _fitted(seed=7)
    model, params = fm.model, fm.params
    bs = 4
    arena = decode.init_paged_arena(model, 4, bs)
    bt = jnp.asarray([[0, 1, 4]], np.int32)
    prompt = jnp.asarray(PROMPT[None])
    pv = decode.PagedView(bt, bs, 8, floor=jnp.full((1,), 4, jnp.int32),
                          ceil=jnp.full((1,), 8, jnp.int32))
    li = [i for i, c in enumerate(arena) if c is not None][0]
    before = np.asarray(arena[li]["k"][:bs])       # block 0 (the "shared")
    # the suffix forward starts AT the floor, exactly like a prefix-hit
    # admission: queries at positions 4..7, floor 4
    _, arena2 = decode._forward(model, params, arena, prompt,
                                jnp.full((1,), 4, jnp.int32), paged=pv)
    np.testing.assert_array_equal(np.asarray(arena2[li]["k"][:bs]), before)
    # while positions >= floor DID write their block (block id 1)
    assert np.abs(np.asarray(arena2[li]["k"][bs:2 * bs])).sum() > 0


@pytest.mark.paged
def test_paged_gather_layout():
    """ops.attention.paged_gather: entry (r, p) of the view is arena slot
    ``table[r, p // bs] * bs + p % bs``, null entries read the null
    block, and the table's trailing null column absorbs out-of-range
    logical blocks (the spec-lookahead clip)."""
    from distkeras_tpu.ops.attention import paged_gather
    bs, nblocks = 2, 3
    arena = jnp.arange((nblocks + 1) * bs, dtype=jnp.float32)
    bt = jnp.asarray([[2, 0, 3], [1, 3, 3]], np.int32)
    view = np.asarray(paged_gather(arena, bt, bs, 6))
    np.testing.assert_array_equal(view[0], [4, 5, 0, 1, 6, 7])
    np.testing.assert_array_equal(view[1], [2, 3, 6, 7, 6, 7])
