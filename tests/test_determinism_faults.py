"""Determinism and failure behavior.

SURVEY.md §5: the reference's PS applies commits racily (GIL-tolerated
hogwild) and its failure story is Spark task retry.  The SPMD rebuild is
deterministic by construction — assert it — and the host-PS path must
survive worker connection death the way the reference does (handler thread
exits silently, the server keeps serving).
"""

import threading

import numpy as np

from distkeras_tpu import ADAG, networking
from distkeras_tpu.core.model import serialize_model
from distkeras_tpu.parameter_servers import (DeltaParameterServer,
                                             SocketParameterServer)

from test_trainers import make_dataset, make_model


def test_spmd_training_is_bit_deterministic(eight_devices):
    """Two identical ADAG runs produce bit-identical weights (the reference's
    PS race cannot: commit interleaving varies run to run)."""

    def run():
        t = ADAG(make_model(), num_workers=8, batch_size=16, num_epoch=2,
                 communication_window=4, label_col="label_encoded",
                 worker_optimizer="adam", learning_rate=1e-3, seed=42)
        return t.train(make_dataset(seed=5), shuffle=True)

    w1 = run().get_weights()
    w2 = run().get_weights()
    for a, b in zip(w1, w2):
        np.testing.assert_array_equal(a, b)


def _start_ps():
    model = make_model()
    params = model.init(__import__("jax").random.PRNGKey(0), (16,))
    ps = DeltaParameterServer(serialize_model(model, params))
    server = SocketParameterServer(ps)
    server.start()
    return ps, server


def test_ps_survives_worker_death():
    """A worker that dies mid-protocol (EOF after opcode, torn frame) must
    not take down the PS or corrupt service for healthy workers."""
    ps, server = _start_ps()
    try:
        # victim 1: connects and vanishes immediately
        c1 = networking.connect("127.0.0.1", server.port)
        c1.close()

        # victim 2: sends a commit opcode then dies mid-frame
        c2 = networking.connect("127.0.0.1", server.port)
        networking.send_opcode(c2, b"c")
        c2.sendall(b"DKT1\x10\x00\x00\x00partial")  # torn frame
        c2.close()

        # victim 3: sends garbage opcode
        c3 = networking.connect("127.0.0.1", server.port)
        c3.sendall(b"Z")
        c3.close()

        # healthy worker: full pull + commit cycle still works
        h = networking.connect("127.0.0.1", server.port)
        networking.send_opcode(h, b"p")
        pulled = networking.recv_data(h)
        assert pulled["clock"] == 0
        delta = [np.ones_like(w) for w in pulled["weights"]]
        networking.send_opcode(h, b"c")
        networking.send_data(h, {"delta": delta, "clock": 0})
        networking.send_opcode(h, b"p")
        after = networking.recv_data(h)
        assert after["clock"] == 1
        np.testing.assert_allclose(after["weights"][0],
                                   pulled["weights"][0] + 1.0)
        networking.send_opcode(h, b"q")
        h.close()
    finally:
        server.stop()


def test_ps_concurrent_commits_all_land():
    """N threads commit concurrently; the clock counts every commit and the
    center equals the sum of all deltas (per-apply mutex: no torn writes —
    the deliberate divergence from the reference's lock-free apply)."""
    ps, server = _start_ps()
    n_threads, commits_each = 4, 8
    try:
        def worker():
            c = networking.connect("127.0.0.1", server.port)
            for _ in range(commits_each):
                networking.send_opcode(c, b"p")
                pulled = networking.recv_data(c)
                delta = [np.ones_like(w) for w in pulled["weights"]]
                networking.send_opcode(c, b"c")
                networking.send_data(c, {"delta": delta, "clock": 0})
            networking.send_opcode(c, b"q")
            c.close()

        before = [w.copy() for w in ps.center]
        threads = [threading.Thread(target=worker) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # commits are fire-and-forget: the handler may still be applying the
        # last frame after the client closed — wait for the clock to settle
        import time
        deadline = time.time() + 5.0
        while (ps.num_updates < n_threads * commits_each
               and time.time() < deadline):
            time.sleep(0.01)
        assert ps.num_updates == n_threads * commits_each
        np.testing.assert_allclose(
            ps.center[0], before[0] + n_threads * commits_each, atol=1e-5)
    finally:
        server.stop()


def test_stop_is_idempotent_and_unblocks():
    ps, server = _start_ps()
    accept_thread = server._accept_thread
    server.stop()
    server.stop()  # second stop must not raise
    accept_thread.join(timeout=5.0)
    assert not accept_thread.is_alive()


def test_stop_unblocks_idle_connected_handlers():
    """stop() must not hang or leak when workers are connected but idle —
    on the event core that means the I/O loop (which multiplexes every
    connection; there are no per-connection handler threads to join)
    drains the selector and closes all three registered connections,
    woken by the socketpair waker rather than the seed core's
    self-connection hack."""
    ps, server = _start_ps()
    conns = [networking.connect("127.0.0.1", server.port) for _ in range(3)]
    try:
        # let the event loop register all three connections
        import time
        deadline = time.time() + 5.0
        while server.live_connections < 3 and time.time() < deadline:
            time.sleep(0.01)
        assert server.live_connections == 3
        assert server._conn_threads == []  # one I/O thread, no per-conn ones
        io_thread = server._accept_thread
        t0 = time.time()
        server.stop()
        assert time.time() - t0 < 5.0  # no join-timeout burn
        assert not io_thread.is_alive()
        assert server.live_connections == 0
        # every registered connection was really closed: the clients see EOF
        for c in conns:
            c.settimeout(2.0)
            assert c.recv(1) == b""
    finally:
        server.stop()
        for c in conns:
            c.close()


def test_stop_logs_and_force_closes_leaked_handler(caplog):
    """An I/O loop wedged inside an apply outlives stop()'s join budget.
    That leak used to be silent; now stop() logs it and force-closes every
    registered connection plus the listener, so the wedged thread fails
    fast on its next socket op instead of writing to a live peer after
    teardown (and a same-address respawn is never blocked by the old
    listener)."""
    import logging
    import time

    release = threading.Event()

    class WedgedPS(DeltaParameterServer):
        def _apply(self, msg):
            release.wait(20.0)  # the wedge: the apply never returns
            super()._apply(msg)

    from distkeras_tpu.core.model import serialize_model as ser
    model = make_model()
    params = model.init(__import__("jax").random.PRNGKey(0), (16,))
    server = SocketParameterServer(WedgedPS(ser(model, params)))
    server.start()
    sock = networking.connect("127.0.0.1", server.port)
    try:
        networking.send_opcode(sock, b"c")
        networking.send_data(
            sock, {"delta": [np.zeros_like(w) for w in server.ps.center],
                   "clock": 0})
        deadline = time.time() + 5.0  # wait until the apply is wedged
        while not server.ps._lock.locked() and time.time() < deadline:
            time.sleep(0.01)
        assert server.ps._lock.locked()
        io_thread = server._accept_thread
        with caplog.at_level(logging.WARNING,
                             logger="distkeras_tpu.parameter_servers"):
            t0 = time.time()
            server.stop(join_timeout=0.2)
        assert time.time() - t0 < 5.0  # bounded, despite the wedge
        assert "still alive" in caplog.text  # the leak is reported
        release.set()  # un-wedge; the loop dies on its closed sockets
        io_thread.join(timeout=5.0)
        assert not io_thread.is_alive()
    finally:
        release.set()
        server.stop()
        sock.close()
