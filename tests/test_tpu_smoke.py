"""Real-hardware smoke tests (round-2 VERDICT weak #8: nothing but bench.py
ever touched the chip, so hardware regressions were invisible between bench
runs).

The suite's conftest pins this process to an 8-device virtual CPU mesh, so
each smoke test runs its payload in a SUBPROCESS with the cpu-forcing env
stripped — hitting whatever accelerator the sandbox exposes (one TPU chip
under the driver).  Auto-skips when no accelerator is reachable.

Run just these with ``pytest -m tpu``; they also run (or skip) in the
default suite.
"""

import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.tpu

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_clean(code: str, timeout: float = 420.0, skip_on_timeout=False):
    """Run ``code`` in a subprocess on the ambient (non-cpu-forced) backend.

    ``skip_on_timeout`` is for the availability PROBE only: a hung probe
    means the accelerator tunnel is down (it comes and goes in this
    sandbox), which is unreachable hardware, not a code regression.  Test
    payloads keep the default — once the probe proved the chip reachable, a
    hang there is a real on-chip regression and must fail, not skip.
    """
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    try:
        return subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True, timeout=timeout,
                              env=env)
    except subprocess.TimeoutExpired:
        if skip_on_timeout:
            pytest.skip(f"accelerator probe stalled (> {timeout:.0f}s): "
                        "tunnel down or backend hung")
        raise


@pytest.fixture(scope="module")
def tpu_available():
    out = _run_clean(
        "import jax; d = jax.devices()[0]; print('PLATFORM=' + d.platform)",
        timeout=45.0, skip_on_timeout=True)
    if out.returncode != 0 or "PLATFORM=" not in out.stdout:
        pytest.skip("no jax backend reachable for the smoke subprocess")
    platform = out.stdout.rsplit("PLATFORM=", 1)[1].strip()
    if platform == "cpu":
        pytest.skip("no accelerator: smoke subprocess fell back to cpu")
    return platform


def test_adag_round_on_chip(tpu_available):
    """One ADAG epoch (1-worker mesh) of the flagship ConvNet on the real
    chip: finite loss, finite weights."""
    out = _run_clean("""
import jax, numpy as np
from distkeras_tpu.models.zoo import mnist_convnet
from distkeras_tpu.parallel.mesh import get_mesh
from distkeras_tpu.parallel.spmd import SPMDEngine, shape_epoch_data

mesh = get_mesh()  # whatever the chip exposes (1 device under the driver)
n = mesh.devices.size
eng = SPMDEngine(mnist_convnet(), "categorical_crossentropy", "adam", mesh,
                 "adag", communication_window=2)
state = eng.init_state(jax.random.PRNGKey(0), (784,))
rng = np.random.default_rng(0)
x = rng.uniform(0, 1, (n * 2 * 64, 784)).astype(np.float32)
y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, len(x))]
xb, yb, mb, _ = shape_epoch_data(x, y, n, 2, 64)
state, losses = eng.run_epoch(state, xb, yb, mb, eng.worker_rngs(0))
losses = np.asarray(losses)
assert np.isfinite(losses).all(), losses
leaves = jax.tree_util.tree_leaves(jax.device_get(state.center))
assert all(np.isfinite(l).all() for l in leaves)
print("SMOKE-ADAG-OK", losses.mean())
""")
    assert out.returncode == 0, out.stderr[-2000:]
    assert "SMOKE-ADAG-OK" in out.stdout


def test_flash_attention_fwd_bwd_on_chip(tpu_available):
    """Pallas flash-attention forward AND fused backward on the real chip
    match the XLA reference attention (Mosaic lowering is stricter than the
    interpret mode the CPU suite uses)."""
    out = _run_clean("""
import jax, jax.numpy as jnp, numpy as np
from distkeras_tpu.ops.attention import attention, dot_product_attention
from distkeras_tpu.ops.flash_attention import flash_attention

rng = np.random.default_rng(0)
# cover the eligibility envelope: the classic lane-aligned shape, a small
# head_dim, and a single sub-128 block (bf16 sublane-tiled)
for shape in ((2, 256, 4, 128), (2, 256, 4, 64), (2, 112, 4, 64)):
    q, k, v = (jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
               for _ in range(3))
    flash = attention(q, k, v, causal=True, impl="pallas")
    ref = attention(q, k, v, causal=True, impl="xla")
    err = float(jnp.max(jnp.abs(flash.astype(jnp.float32)
                                - ref.astype(jnp.float32))))
    assert err < 0.05, (shape, err)  # bf16 tolerance

# GQA through the pallas dispatch (k/v repeated up to H inside attention())
q = jnp.asarray(rng.standard_normal((2, 256, 4, 64)), jnp.bfloat16)
k, v = (jnp.asarray(rng.standard_normal((2, 256, 2, 64)), jnp.bfloat16)
        for _ in range(2))
gerr = float(jnp.max(jnp.abs(
    attention(q, k, v, causal=True, impl="pallas").astype(jnp.float32)
    - attention(q, k, v, causal=True, impl="xla").astype(jnp.float32))))
assert gerr < 0.05, gerr

# sliding-window flash (out-of-window block skipping) on hardware
werr = float(jnp.max(jnp.abs(
    attention(q, k, v, causal=True, impl="pallas",
              window=96).astype(jnp.float32)
    - attention(q, k, v, causal=True, impl="xla",
                window=96).astype(jnp.float32))))
assert werr < 0.05, werr
print("SMOKE-FLASH-OK", err)

def loss_flash(q, k, v):
    return attention(q, k, v, causal=True,
                     impl="pallas").astype(jnp.float32).sum()
def loss_ref(q, k, v):
    return dot_product_attention(q, k, v,
                                 causal=True).astype(jnp.float32).sum()

# fused dq/dk/dv backward kernels across the same eligibility envelope the
# forward loop covers (d=64 and single sub-128 block shapes dispatch to the
# never-interpret-mode Mosaic lowering on hardware too)
for shape in ((2, 256, 4, 128), (2, 256, 4, 64), (2, 112, 4, 64)):
    q, k, v = (jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
               for _ in range(3))
    gf = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for name, a, b in zip("qkv", gf, gr):
        gerr = float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                     - b.astype(jnp.float32))))
        assert gerr < 0.125, (shape, name, gerr)
print("SMOKE-FLASH-BWD-OK")
""")
    assert out.returncode == 0, out.stderr[-2000:]
    assert "SMOKE-FLASH-OK" in out.stdout
    assert "SMOKE-FLASH-BWD-OK" in out.stdout


def test_longcontext_lm_on_chip(tpu_available):
    """The composed long-context stack (RoPE + GQA + sliding window,
    flash-eligible shapes) forwards on the real chip and generates through
    the rolling O(window) cache with tokens equal to the full cache."""
    out = _run_clean("""
import jax, numpy as np
from distkeras_tpu.models.zoo import transformer_lm
from distkeras_tpu.core.decode import generate

model = transformer_lm(vocab_size=64, seq_len=256, d_model=256,
                       num_heads=4, num_kv_heads=2, num_layers=2,
                       mlp_dim=512, positional="rope",
                       attention_window=32)
params = model.init(jax.random.PRNGKey(0))
toks = np.random.default_rng(0).integers(0, 64, (2, 256)).astype(np.int32)
logits = jax.jit(model.apply)(params, toks)
assert np.isfinite(np.asarray(logits, np.float32)).all()

# prompt 16 + 32 steps > window 32: the ring WRAPS on chip (slots evict)
prompt = toks[:, :16]
full = np.asarray(generate(model, params, prompt, 32))
rolled = np.asarray(generate(model, params, prompt, 32, rolling=True))
np.testing.assert_array_equal(full, rolled)

# round-4 decode surface: nucleus sampling through the rolling cache
sampled = np.asarray(generate(model, params, prompt, 16, temperature=0.8,
                              rng=jax.random.PRNGKey(1), top_k=16,
                              top_p=0.9, rolling=True))
assert sampled.shape == (2, 32) and ((0 <= sampled) & (sampled < 64)).all()
print("SMOKE-LONGCONTEXT-OK")
""")
    assert out.returncode == 0, out.stderr[-2000:]
    assert "SMOKE-LONGCONTEXT-OK" in out.stdout


def test_pipeline_1f1b_on_chip(tpu_available):
    """The 1F1B schedule compiles and steps on the real chip (1-device
    'stage' ring: the degenerate-but-real program), with a warmup+cosine
    scheduled optimizer — the round-4 training surface in one payload."""
    out = _run_clean("""
import jax, jax.numpy as jnp, numpy as np, optax
from jax.sharding import Mesh
from distkeras_tpu.core.optimizers import get_schedule
from distkeras_tpu.parallel.pp_transformer import PipelineTransformerLM

devs = np.array(jax.devices()[:1]).reshape(1, 1)
mesh = Mesh(devs, ("data", "stage"))
lm = PipelineTransformerLM(vocab_size=64, seq_len=64, d_model=64,
                           num_heads=2, num_layers=2, mlp_dim=128,
                           mesh=mesh, num_microbatches=2, schedule="1f1b")
params = lm.init(jax.random.PRNGKey(0))
tx = optax.adam(get_schedule("warmup_cosine", 1e-2, total_steps=4))
opt_state, step = lm.compile_train_step(tx, params)
rng = np.random.default_rng(0)
toks = jnp.asarray(rng.integers(0, 64, (4, 64)), jnp.int32)
labels = (toks + 1) % 64
for _ in range(4):
    params, opt_state, loss = step(params, opt_state, toks, labels)
assert np.isfinite(float(loss))
print("SMOKE-1F1B-OK")
""")
    assert out.returncode == 0, out.stderr[-2000:]
    assert "SMOKE-1F1B-OK" in out.stdout


def test_fused_ce_and_fsdp_on_chip(tpu_available):
    """Round-4 kernels on the real chip: the fused cross-entropy Pallas
    kernel (Mosaic lowering, value + grad vs the XLA oracle, ragged vocab
    included) and a ZeRO-3/FSDP train step on a 1-device mesh (the
    degenerate-but-real GSPMD program)."""
    out = _run_clean("""
import jax, jax.numpy as jnp, numpy as np, optax
from distkeras_tpu.ops.fused_ce import fused_softmax_cross_entropy

rng = np.random.default_rng(0)
def oracle(logits, labels):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]

# lane-aligned and ragged (T, V) shapes, f32 and bf16
for t, v, dtype in ((256, 1024, jnp.float32), (192, 1000, jnp.float32),
                    (256, 2048, jnp.bfloat16)):
    logits = jnp.asarray(rng.standard_normal((t, v)) * 3, dtype)
    labels = jnp.asarray(rng.integers(0, v, t), jnp.int32)
    got = jax.jit(fused_softmax_cross_entropy)(logits, labels)
    ref = oracle(logits, labels)
    err = float(jnp.max(jnp.abs(got - ref)))
    assert err < (0.05 if dtype == jnp.bfloat16 else 1e-4), (t, v, err)
    g = jax.jit(jax.grad(lambda lg: fused_softmax_cross_entropy(
        lg, labels).sum()))(logits)
    gr = jax.grad(lambda lg: oracle(lg, labels).sum())(
        logits.astype(jnp.float32))
    gerr = float(jnp.max(jnp.abs(g.astype(jnp.float32) - gr)))
    assert gerr < (0.05 if dtype == jnp.bfloat16 else 1e-4), (t, v, gerr)
print("SMOKE-FUSEDCE-OK")

# FSDP step (params+moments annotated data-sharded; 1-device degenerate)
from jax.sharding import Mesh
from distkeras_tpu.parallel.transformer import ParallelTransformerLM
mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1, 1),
            ("data", "seq", "model"))
lm = ParallelTransformerLM(vocab_size=256, seq_len=128, d_model=64,
                           num_heads=4, num_layers=2, mlp_dim=128,
                           mesh=mesh, fused_ce=True)
params = lm.init(jax.random.PRNGKey(0))
opt_state, step = lm.compile_train_step(optax.adam(1e-2), params, fsdp=True)
toks = jnp.asarray(rng.integers(0, 256, (8, 128)), jnp.int32)
labels = (toks + 1) % 256
for _ in range(3):
    params, opt_state, loss = step(params, opt_state, toks, labels)
assert np.isfinite(float(loss))
print("SMOKE-FSDP-OK")
""")
    assert out.returncode == 0, out.stderr[-2000:]
    assert "SMOKE-FUSEDCE-OK" in out.stdout
    assert "SMOKE-FSDP-OK" in out.stdout


def test_flash_inside_shard_map_on_chip(tpu_available):
    """Flash routed from INSIDE a shard_map region (the ulysses SP attend)
    compiles on hardware: pallas outputs must declare their varying mesh
    axes (ops/_vma.out_struct) or shard_map's vma checking rejects the
    kernel at trace time — regression for the round-4 fix."""
    out = _run_clean("""
import jax, jax.numpy as jnp, numpy as np
from jax.sharding import Mesh
from distkeras_tpu.parallel.ulysses import ulysses_self_attention
from distkeras_tpu.ops.attention import dot_product_attention

mesh = Mesh(np.array(jax.devices()[:1]), ("seq",))
rng = np.random.default_rng(0)
q, k, v = (jnp.asarray(rng.standard_normal((2, 256, 4, 64)), jnp.bfloat16)
           for _ in range(3))
# S=256 is flash-eligible, so the in-shard_map attend takes the kernel
out = ulysses_self_attention(q, k, v, mesh, "seq", causal=True)
ref = dot_product_attention(q, k, v, causal=True)
err = float(jnp.max(jnp.abs(out.astype(jnp.float32)
                            - ref.astype(jnp.float32))))
assert err < 0.05, err
print("SMOKE-FLASH-SHARDMAP-OK")
""")
    assert out.returncode == 0, out.stderr[-2000:]
    assert "SMOKE-FLASH-SHARDMAP-OK" in out.stdout


def test_int8_serving_on_chip(tpu_available):
    """Weight-only int8 decode on hardware: the dequantizing pytree leaf
    flows through the jitted forward + KV-cache decode step, logits stay
    within per-channel rounding error of full precision."""
    out = _run_clean("""
import jax, jax.numpy as jnp, numpy as np
from distkeras_tpu.core.decode import init_cache, jit_decode_step
from distkeras_tpu.core.quant import quantize_params, quantized_bytes
from distkeras_tpu.models.zoo import transformer_lm

model = transformer_lm(vocab_size=256, seq_len=128, d_model=128,
                       num_heads=4, num_layers=2, mlp_dim=256,
                       num_kv_heads=2)
params = model.init(jax.random.PRNGKey(0))
qparams = quantize_params(params)
assert quantized_bytes(qparams) < 0.5 * quantized_bytes(params)

x = jnp.asarray(np.random.default_rng(0).integers(0, 256, (4, 128)),
                jnp.int32)
full = jax.jit(lambda p, t: model.apply(p, t))(params, x)
quant = jax.jit(lambda p, t: model.apply(p, t))(qparams, x)
err = float(jnp.max(jnp.abs(full.astype(jnp.float32)
                            - quant.astype(jnp.float32))))
assert err < 0.5, err  # bf16 compute + int8 weights on random init
print("SMOKE-INT8-FWD-OK", err)

# the serving inner loop: jitted decode step over the quantized params
caches = init_cache(model, batch=4, max_len=128)
step = jit_decode_step(model)
tok = jnp.zeros((4,), jnp.int32)
for i in range(8):
    logits, caches = step(qparams, caches, tok, i)
    tok = jnp.argmax(logits, -1).astype(jnp.int32)
assert np.isfinite(np.asarray(logits)).all()
print("SMOKE-INT8-DECODE-OK")
""")
    assert out.returncode == 0, out.stderr[-2000:]
    assert "SMOKE-INT8-FWD-OK" in out.stdout
    assert "SMOKE-INT8-DECODE-OK" in out.stdout
