"""Replicated serving fleet behind one router — the "millions of users"
step past a single engine (ROADMAP item 3).

A :class:`ServingRouter` fronts N engine replicas — in-process
:class:`serving.ServingEngine` instances or remote
:class:`serving.ServingServer` addresses over the existing serving wire —
behind the unchanged client surface: ``submit`` returns a
:class:`serving.RequestHandle` proxy that streams (``next_chunk``) and
resolves (``result``) exactly like a bare engine's handle.

**Dispatch.**  The baseline policy is least-loaded: every replica
publishes a lock-free load snapshot (:meth:`serving.ServingEngine.load`
in-process, the ``SERVING_OP_STATS`` probe over the wire) and the router
picks the replica minimizing ``queue_depth + active``.  On top of it,
``affinity="prefix"`` (the default) adds SGLang-shaped cache-aware
routing: the prompt's leading paged blocks — the SAME block_size/boundary
rule the PR 12 radix trie matches on, full ``block_size``-token chunks
capped below the prompt length — hash to a replica by rendezvous
(highest-random-weight) hashing, so shared-prefix tenants consistently
land on the replica whose trie is already warm and fleet membership
changes only remap the groups that lost their replica.  A saturated
affine replica (no free slot AND a queue more than one slot-pool deeper
than the least-loaded's) spills to least-loaded — affinity is a
preference, not a hostage situation.

**Zero-loss failover.**  A replica killed mid-stream fails its requests
with the typed :class:`serving.EngineDead`; the router resubmits them to
another live replica under ``retry_policy`` (one
:class:`resilience.RetryPolicy`, the same machinery
``ServingClient.generate`` re-dials with — no second retry
implementation) with the request's ORIGINAL seed, and the replay skips
the tokens the client already saw: seeded sampling makes the resubmitted
stream bit-identical (the PR 8 contract), so an accepted request loses
nothing — not even its already-streamed prefix.

**Elasticity + blue/green.**  ``scale_up``/``scale_down`` grow and drain
the in-process fleet through the same ``respawn``/``drain`` machinery the
supervisors use (``autoscale_tick`` drives them from queue depth);
``rolling_swap`` runs PR 15's atomic generation swap one replica at a
time under live traffic, so some replica is always serving and every
response is attributable to exactly one ``(replica, generation)``.
``resilience.FleetSupervisor`` watches the in-process replicas through
the router's ``replace_engine`` seam.
"""

from __future__ import annotations

import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import networking
from . import resilience
from .serving import (Draining, EngineDead, QueueFull, RequestHandle,
                      ServingClient, ServingEngine, TenantPolicy)

__all__ = ["ServingRouter", "DEFAULT_RESUBMIT_POLICY"]


#: router→replica resubmission default: keep trying for a supervisor's
#: detection + restart window (the same shape as
#: ``resilience.DEFAULT_RECOVERY_POLICY``, tighter backoff — replicas are
#: local or LAN, and a queued resubmission holds a client stream open).
DEFAULT_RESUBMIT_POLICY = resilience.RetryPolicy(
    attempts=None, backoff=0.01, max_backoff=0.25, deadline=15.0)

#: faults that mean "this replica lost the request": typed engine death
#: (crash/wedge/drain-timeout) or the wire to it breaking.  The request
#: is resubmittable — seeded determinism makes the retry idempotent.
_REPLICA_LOST = (EngineDead, ConnectionError, OSError)

#: everything a resubmission attempt may transiently hit: a lost replica
#: again, or every OTHER replica momentarily full/draining.
_RESUBMIT_RETRY_ON = _REPLICA_LOST + (QueueFull, Draining)

#: event-relay scratch recv size: token-stream reply frames are small —
#: 64 KiB amortizes syscalls without hoarding per-stream buffers.
_RELAY_RECV_CHUNK = 1 << 16


class _EngineReplica:
    """One in-process replica: a unified :class:`ServingEngine` plus the
    router-side identity (uid, generation, draining flag) dispatch and
    attribution hang off.  Mutable fields are written only under the
    router's lock; relay threads read them without it (a stale read costs
    one wasted attempt, never correctness — attachment is re-checked by
    the submit itself)."""

    kind = "engine"

    def __init__(self, uid: int, engine: ServingEngine):
        self.uid = uid
        self.engine = engine
        self.generation = 0
        self.draining = False
        self.routed = 0

    def load(self) -> Dict[str, Any]:
        return self.engine.load()

    def close(self) -> None:
        pass


class _WireReplica:
    """One remote replica: a ``(host, port)`` :class:`serving.ServingServer`
    address.  Request traffic borrows streaming clients from the router's
    :class:`networking.ClientPool`; load probes ride a dedicated client
    (serialized under a probe lock — submitting threads race here) and
    cache for ``load_ttl`` so a dispatch burst costs one round-trip, not
    one per request.  An unreachable server answers probes with a
    synthetic ``dead`` snapshot and self-heals on the next successful
    dial."""

    kind = "wire"

    def __init__(self, uid: int, addr: Tuple[str, int],
                 load_ttl: float = 0.02):
        self.uid = uid
        self.addr = (str(addr[0]), int(addr[1]))
        self.load_ttl = float(load_ttl)
        self.generation = 0
        self.draining = False
        self.routed = 0
        self._probe: Optional[ServingClient] = None
        self._plock = threading.Lock()
        self._cached: Optional[Dict[str, Any]] = None
        self._cached_at = 0.0

    def load(self) -> Dict[str, Any]:
        with self._plock:
            now = time.monotonic()
            if (self._cached is not None
                    and now - self._cached_at < self.load_ttl):
                return dict(self._cached)
            try:
                if self._probe is None:
                    self._probe = ServingClient(*self.addr)
                snap = self._probe.load()
            except (ConnectionError, OSError):
                if self._probe is not None:
                    self._probe.close()
                    self._probe = None
                snap = {"queue_depth": 0, "slots_free": 0,
                        "slots_total": 0, "active": 0, "trie_blocks": 0,
                        "dead": True, "draining": False,
                        "unreachable": True}
            self._cached, self._cached_at = snap, now
            return dict(snap)

    def close(self) -> None:
        with self._plock:
            if self._probe is not None:
                self._probe.close()
                self._probe = None
            self._cached = None


class _RouterRequest:
    """One in-flight request's routing record: the client-facing proxy,
    the current attachment (replica + upstream handle in-process, or
    pooled client + server id over the wire), a cancel relay pointing at
    whichever replica owns the request right now, and the replay cursor
    (``relayed`` — tokens already pushed into the proxy, skipped when a
    resubmitted stream replays from token zero)."""

    __slots__ = ("proxy", "kw", "replica", "upstream", "client", "rid",
                 "cancel_fn", "cancelled", "relayed", "attached",
                 "resubmits", "thread")

    def __init__(self, proxy: RequestHandle, kw: Dict[str, Any]):
        self.proxy = proxy
        self.kw = kw
        self.replica = None
        self.upstream: Optional[RequestHandle] = None
        self.client: Optional[ServingClient] = None
        self.rid: Optional[int] = None
        self.cancel_fn: Optional[Callable[[], Any]] = None
        self.cancelled = False
        self.relayed = 0
        self.attached: Optional[Tuple[int, int]] = None
        self.resubmits = 0
        self.thread: Optional[threading.Thread] = None


class ServingRouter:
    """Route requests across a fleet of serving replicas (see the module
    docstring for the policy/failover/elasticity story).

    ``replicas`` are in-process unified :class:`serving.ServingEngine`
    instances; ``addrs`` are ``(host, port)`` remote
    :class:`serving.ServingServer` addresses.  Either may be empty, not
    both.  ``affinity`` is ``"prefix"`` (default), ``"least-loaded"``, or
    ``"random"`` (seeded — the control arm benchmarks compare against).
    ``block_size`` must match the replicas' paged block size for the
    affinity hash to align with their tries; by default it is read off
    the first in-process paged engine (16 otherwise).

    ``engine_factory`` (a zero-arg callable returning an UNSTARTED
    engine) enables ``scale_up``/``autoscale_tick``; without it the fleet
    is fixed-size.  ``retry_policy`` bounds failover resubmission.
    """

    def __init__(self, replicas: Optional[Sequence[ServingEngine]] = None,
                 addrs: Optional[Sequence[Tuple[str, int]]] = None, *,
                 affinity: str = "prefix", affinity_blocks: int = 2,
                 block_size: Optional[int] = None,
                 retry_policy: Optional[resilience.RetryPolicy] = None,
                 seed: int = 0, poll_s: float = 0.02,
                 load_ttl: float = 0.02,
                 engine_factory: Optional[Callable[[], ServingEngine]]
                 = None,
                 min_replicas: int = 1, max_replicas: int = 8,
                 scale_up_queue: int = 4,
                 max_idle_clients: int = 4,
                 tenants: Optional[Sequence[TenantPolicy]] = None):
        replicas = list(replicas or [])
        addrs = list(addrs or [])
        if not replicas and not addrs:
            raise ValueError("ServingRouter needs at least one replica: "
                             "pass replicas= (in-process engines) and/or "
                             "addrs= (remote ServingServer addresses)")
        if affinity not in ("prefix", "least-loaded", "random"):
            raise ValueError(f"unknown affinity policy {affinity!r}")
        for e in replicas:
            if e.role != "unified":
                raise ValueError(
                    "router replicas must be unified engines; got "
                    f"role={e.role!r} — front role-split engines with a "
                    "DisaggPair and serve THAT behind a ServingServer")
        self.affinity = affinity
        self.affinity_blocks = int(affinity_blocks)
        if block_size is None:
            paged = [e for e in replicas if e.paged]
            block_size = paged[0].block_size if paged else 16
        self.block_size = int(block_size)
        self.retry_policy = (DEFAULT_RESUBMIT_POLICY if retry_policy is None
                             else retry_policy)
        self.seed = int(seed)
        self.poll_s = float(poll_s)
        self.engine_factory = engine_factory
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.scale_up_queue = int(scale_up_queue)
        self._pool = networking.ClientPool(
            lambda addr: ServingClient(*addr),
            max_idle_per_addr=max_idle_clients)
        self._lock = threading.Lock()
        self._next_uid = 0
        self._replicas: List[Any] = []
        for e in replicas:
            self._replicas.append(_EngineReplica(self._next_uid, e))
            self._next_uid += 1
        for a in addrs:
            self._replicas.append(
                _WireReplica(self._next_uid, a, load_ttl=load_ttl))
            self._next_uid += 1
        #: fleet-level QoS (PR 18): the policies are the dispatch's tier
        #: map AND are cloned onto every in-process replica (fresh
        #: per-replica token buckets — a quota ``rate`` is therefore
        #: per-replica, so the fleet-wide rate scales with live replicas;
        #: see docs/serving.md).  Tenant-aware dispatch composes with
        #: prefix affinity: a batch-tier submission spills off an affine
        #: replica whose snapshot shows interactive requests waiting.
        self._tenants: Dict[str, TenantPolicy] = {}
        for p in (tenants or []):
            if not isinstance(p, TenantPolicy):
                raise ValueError(f"tenants= entries must be TenantPolicy, "
                                 f"got {type(p).__name__}")
            self._tenants[p.name] = p
        for rep in self._replicas:
            if rep.kind == "engine":
                for p in self._tenants.values():
                    rep.engine.register_tenant(p.clone())
        self._rng = np.random.default_rng(self.seed)  # "random" policy
        self._live: Dict[int, _RouterRequest] = {}
        #: shared event relay (PR 19): ONE selector loop pumps every
        #: in-flight stream — engine attachments via handle listeners,
        #: wire attachments via non-blocking reads over the bare-frame
        #: parser.  Threads are spent on failover recovery only, so the
        #: router's thread count is O(concurrent failures), not
        #: O(in-flight requests).  Lazily started on first submit.
        self._relay_loop: Optional[networking.EventLoop] = None
        self._attributions: Dict[int, Tuple[int, int]] = {}
        self._next_id = 0
        self._started = False
        self._draining = False
        #: router-level terminal accounting (replica counters double-count
        #: a resubmitted request — every attempt is a submission
        #: somewhere, but it is ONE client request) plus routing/fleet
        #: observables
        self.counters: Dict[str, int] = {
            "requests_submitted": 0, "requests_completed": 0,
            "requests_failed": 0, "requests_rejected": 0,
            "requests_cancelled": 0, "requests_expired": 0,
            "resubmissions": 0, "affinity_routed": 0,
            "affinity_spills": 0, "tenant_spills": 0,
            "generation_swaps": 0,
            "scale_ups": 0, "scale_downs": 0,
        }

    # ------------------------------------------------------------ lifecycle
    def warmup(self) -> "ServingRouter":
        for rep in self._engine_replicas():
            rep.engine.warmup()
        return self

    def start(self) -> "ServingRouter":
        with self._lock:
            self._started = True
        for rep in self._engine_replicas():
            rep.engine.start()
        return self

    def stop(self, join_timeout: float = 10.0) -> None:
        with self._lock:
            self._started = False
            threads = [r.thread for r in self._live.values()]
            reps = list(self._replicas)
        for rep in reps:
            if rep.kind == "engine":
                rep.engine.stop(join_timeout=join_timeout)
        deadline = time.monotonic() + join_timeout  # shared bound: N parked
        for t in threads:                           # relays cost one timeout,
            if t is not None:                       # not N of them
                t.join(timeout=max(0.0, deadline - time.monotonic()))
        self._ev_wait_idle(max(0.0, deadline - time.monotonic()))
        for rep in reps:
            rep.close()
        with self._lock:
            loop, self._relay_loop = self._relay_loop, None
        if loop is not None:
            loop.stop(join_timeout=max(0.5, deadline - time.monotonic()))
        self._pool.close()

    def _ev_wait_idle(self, timeout: float) -> None:
        """Bounded wait for loop-owned relays (in-flight requests with no
        failover thread to join) to retire: stopping/draining the engines
        makes their upstream handles terminal, and the shared loop pumps
        those final laps out asynchronously."""
        deadline = time.monotonic() + timeout
        while True:
            with self._lock:
                busy = any(r.thread is None for r in self._live.values())
            if not busy or time.monotonic() >= deadline:
                return
            time.sleep(0.005)

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful fleet drain: stop admission at the router, drain every
        in-process replica (queued + running requests finish; a drain
        timeout fails the stragglers typed, which the relays then
        resubmit nowhere — admission is closed — so they fail to the
        client typed too), then join the relay threads.  Wire replicas
        belong to another process and are not drained here."""
        with self._lock:
            self._draining = True
            reps = [r for r in self._replicas if r.kind == "engine"]
        clean = all([rep.engine.drain(timeout=timeout) for rep in reps])
        with self._lock:
            threads = [r.thread for r in self._live.values()]
        for t in threads:
            if t is not None:
                t.join(timeout=5.0)
        self._ev_wait_idle(5.0)
        return clean

    def __enter__(self) -> "ServingRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _engine_replicas(self) -> List[_EngineReplica]:
        with self._lock:
            return [r for r in self._replicas if r.kind == "engine"]

    # ------------------------------------------------------------- routing
    def _route_key(self, prompt: np.ndarray) -> Optional[bytes]:
        """The affinity hash input: the prompt's leading FULL paged blocks
        (at most ``affinity_blocks`` of them), under the trie's own
        boundary rule — matchable tokens are capped at ``p_len - 1``, so
        a prompt that cannot share even one full block routes by load
        instead of pinning a cold hash."""
        bs = self.block_size
        n = min(self.affinity_blocks, (len(prompt) - 1) // bs)
        if n <= 0:
            return None
        return np.asarray(prompt[:n * bs], np.int32).tobytes()

    @staticmethod
    def _score(load: Dict[str, Any]) -> int:
        return int(load.get("queue_depth", 0)) + int(load.get("active", 0))

    @staticmethod
    def _should_spill(affine: Dict[str, Any],
                      least: Dict[str, Any]) -> bool:
        """The affinity escape hatch: spill when the affine replica has no
        free slot AND its queue runs more than one full slot pool deeper
        than the least-loaded replica's — mild skew stays affine (that is
        the point of the warm trie), saturation does not."""
        return (int(affine.get("slots_free", 0)) == 0
                and int(affine.get("queue_depth", 0))
                > int(least.get("queue_depth", 0))
                + int(affine.get("slots_total", 1)))

    def _candidates(self) -> List[Tuple[Any, Dict[str, Any]]]:
        """Live routable replicas with their current load snapshots —
        draining/dead/unreachable ones are excluded (load probes run
        OUTSIDE the router lock; they may block on a wire round-trip)."""
        with self._lock:
            reps = [r for r in self._replicas if not r.draining]
        out = []
        for rep in reps:
            load = rep.load()
            if load.get("dead") or load.get("draining"):
                continue
            out.append((rep, load))
        return out

    def _tier_of(self, tenant: Optional[str]) -> str:
        pol = self._tenants.get("default" if tenant is None
                                else str(tenant))
        return "batch" if pol is None else pol.tier

    def _dispatch_order(self, prompt: np.ndarray,
                        tenant: Optional[str] = None
                        ) -> List[Tuple[Any, Dict[str, Any]]]:
        """Replicas in preference order for one admission attempt: the
        policy's pick first, the rest by ascending load (the fallback
        chain a full/refusing replica hands over to).  Tenant-aware QoS
        rides on top of prefix affinity: a BATCH-tier submission spills
        off an affine replica whose snapshot shows interactive requests
        queued — warm-trie reuse is not worth feeding the replica more
        preemption victims while its interactive tier is backlogged
        (interactive submissions keep their affinity; they are what the
        backlog drains into)."""
        cands = self._candidates()
        if not cands:
            raise EngineDead("no live serving replica in the fleet")
        by_load = sorted(cands, key=lambda rl: self._score(rl[1]))
        if self.affinity == "random":
            with self._lock:  # Generator state is not thread-safe
                i = int(self._rng.integers(len(cands)))
            pick = cands[i]
            rest = [rl for rl in by_load if rl[0] is not pick[0]]
            return [pick] + rest
        if self.affinity == "prefix":
            key = self._route_key(prompt)
            if key is not None:
                # rendezvous hashing: stable per (key, replica uid), so
                # membership changes only remap groups whose replica left
                pick = max(cands, key=lambda rl: zlib.crc32(
                    key + rl[0].uid.to_bytes(4, "little")))
                least = by_load[0]
                spill = None
                if (pick[0] is not least[0]
                        and self._should_spill(pick[1], least[1])):
                    spill = "affinity_spills"
                elif (pick[0] is not least[0]
                      and self._tier_of(tenant) == "batch"
                      and int(pick[1].get("queued_interactive", 0)) > 0):
                    spill = "tenant_spills"
                if spill is not None:
                    with self._lock:
                        self.counters[spill] += 1
                else:
                    with self._lock:
                        self.counters["affinity_routed"] += 1
                    rest = [rl for rl in by_load if rl[0] is not pick[0]]
                    return [pick] + rest
        return by_load

    # ----------------------------------------------------------- admission
    def submit(self, prompt, num_steps: int, block: bool = True,
               timeout: Optional[float] = None, **kw) -> RequestHandle:
        """Unified-engine ``submit`` surface over the fleet: route, admit
        on the chosen replica (falling back across refusals), and return
        a proxy handle whose stream relays the replica's tokens.  Typed
        rejections propagate exactly like a bare engine's: with every
        replica full, ``block=True`` keeps retrying admission until
        ``timeout`` then raises :class:`QueueFull`; ``block=False``
        raises immediately."""
        prompt = np.asarray(prompt, np.int32)
        with self._lock:
            if self._draining:
                self.counters["requests_rejected"] += 1
                raise Draining("serving router is draining; admission "
                               "stopped")
            self._next_id += 1
            rid = self._next_id
        proxy = RequestHandle(
            rid, prompt, int(num_steps),
            float(kw.get("temperature", 0.0)), kw.get("top_k"),
            kw.get("top_p"), kw.get("eos_id"), kw.get("pad_id"),
            None, deadline_s=kw.get("deadline_s"))
        rec = _RouterRequest(proxy, dict(kw))
        deadline = (None if timeout is None
                    else time.monotonic() + float(timeout))
        while True:
            try:
                self._admit_once(rec)
                break
            except QueueFull:
                with self._lock:
                    draining = self._draining
                if (not block or draining
                        or (deadline is not None
                            and time.monotonic() >= deadline)):
                    with self._lock:
                        self.counters["requests_rejected"] += 1
                    raise
                time.sleep(self.poll_s)
            except (Draining, EngineDead, ValueError):
                with self._lock:
                    self.counters["requests_rejected"] += 1
                raise
        with self._lock:
            self._live[proxy.id] = rec
            self.counters["requests_submitted"] += 1
        self._ev_watch(rec)
        return proxy

    def _admit_once(self, rec: _RouterRequest) -> None:
        """One admission attempt: walk the dispatch order until a replica
        accepts.  Raises the LAST typed refusal when every replica
        refused (so a fleet-wide backpressure surfaces as
        :class:`QueueFull`, a fleet-wide drain as :class:`Draining`)."""
        proxy = rec.proxy
        last: Optional[BaseException] = None
        for rep, _load in self._dispatch_order(proxy.prompt,
                                               rec.kw.get("tenant")):
            try:
                self._attach(rec, rep)
                return
            except (QueueFull, Draining, EngineDead) as e:
                last = e
        raise last if last is not None else EngineDead(
            "no live serving replica in the fleet")

    def _attach(self, rec: _RouterRequest, rep) -> None:
        """Admit ``rec`` on ``rep`` (non-blocking — a full replica refuses
        and the dispatch order moves on) and point the attachment +
        cancel relay at it.  The request keeps its ORIGINAL sampling
        seed on every attach: that is what makes a resubmitted stream
        bit-identical."""
        proxy = rec.proxy
        sub = dict(rec.kw)
        sub.pop("block", None)
        sub.pop("timeout", None)
        if rep.kind == "engine":
            h = rep.engine.submit(proxy.prompt, proxy.num_steps,
                                  block=False, **sub)
            with self._lock:
                rec.replica, rec.upstream = rep, h
                rec.client = rec.rid = None
                rec.attached = (rep.uid, rep.generation)
                rec.cancel_fn = (lambda e=rep.engine, hh=h: e.cancel(hh))
                rep.routed += 1
                if rec.cancelled:
                    rec.cancel_fn()
            return
        client = self._pool.acquire(rep.addr)
        try:
            rid = client.submit(proxy.prompt, proxy.num_steps, **sub)
        except (ConnectionError, OSError) as e:
            self._pool.discard(client)
            raise EngineDead(f"replica {rep.uid} at {rep.addr} "
                             f"unreachable: {e!r}") from e
        except (QueueFull, Draining, EngineDead, ValueError):
            self._pool.release(rep.addr, client)  # typed refusal: the
            raise                                 # transport is intact
        with self._lock:
            rec.replica, rec.client, rec.rid = rep, client, rid
            rec.upstream = None
            rec.attached = (rep.uid, rep.generation)
            rec.cancel_fn = (lambda c=client, r=rid:
                             c.cancel(r, await_ack=False))
            rep.routed += 1
            if rec.cancelled:
                rec.cancel_fn()

    # -------------------------------------------------------------- relays
    #
    # Steady state rides the shared event loop: an engine attachment's
    # handle listener wakes the loop per progress transition and the loop
    # pumps ``next_chunk(timeout=0)`` into the proxy; a wire attachment's
    # socket goes non-blocking and the loop decodes reply frames off a
    # bare-frame parser.  Only a LOST attachment spends a thread: the
    # failover thread re-runs the blocking resubmit+stream path under
    # ``retry_policy`` — the exact recovery contract the per-request
    # relay threads implemented, at O(failures) threads instead of
    # O(requests).

    def _ev_loop(self) -> networking.EventLoop:
        with self._lock:
            loop = self._relay_loop
            if loop is None or not loop.alive:
                loop = networking.EventLoop(name="dkt-router-relay")
                loop.start()
                self._relay_loop = loop
            return loop

    def _ev_watch(self, rec: _RouterRequest) -> None:
        """Hook a freshly-admitted request onto the shared relay."""
        loop = self._ev_loop()
        if rec.upstream is not None:
            h = rec.upstream
            h.set_listener(lambda: loop.call_soon(
                lambda: self._ev_pump_engine(rec, h)))
            # catch-up pump: progress that predates the listener
            loop.call_soon(lambda: self._ev_pump_engine(rec, h))
        else:
            loop.call_soon(lambda: self._ev_wire_begin(rec))

    def _ev_pump_engine(self, rec: _RouterRequest, h) -> None:
        """Loop-side engine relay: drain whatever the upstream handle has
        ready (never blocks), replaying nothing — this path only ever
        runs on a request's FIRST attachment, so the proxy is exactly
        ``rec.relayed`` tokens behind the upstream."""
        if rec.upstream is not h:
            return  # stale wake: the request failed over elsewhere
        while True:
            chunk, done = h.next_chunk(timeout=0)
            for t in chunk:
                rec.proxy._push(int(t))
                rec.relayed += 1
            if done:
                h.set_listener(None)
                rec.upstream = None  # claim the terminal transition: a
                # second queued pump (the listener fires per transition)
                # must not fail the same request over twice
                err = h.error
                if err is None:
                    self._retire(rec, finish=h.finish)
                elif isinstance(err, _REPLICA_LOST):
                    self._ev_failover(rec)  # EngineDead → resubmit
                elif isinstance(err, ValueError):
                    self._retire(rec, error=err)
                else:
                    self._retire(rec, error=EngineDead(str(err)))
                return
            if not len(chunk):
                return  # drained; the listener wakes us on more

    def _ev_wire_begin(self, rec: _RouterRequest) -> None:
        """Loop-side wire relay start: send the stream request, flip the
        pooled client's socket non-blocking, and register it — reply
        frames (no opcode byte) decode off a bare-frame parser."""
        client = rec.client
        try:
            networking.send_opcode(client.sock,
                                   networking.SERVING_OP_STREAM)
            networking.send_data(client.sock, {"id": int(rec.rid)},
                                 pool=client._send_pool)
            client.sock.setblocking(False)
        except (ConnectionError, OSError):
            self._pool.discard(client)
            self._ev_failover(rec)
            return
        with self._lock:
            loop = self._relay_loop
        if loop is None:  # stop() raced the registration
            self._pool.discard(client)
            return
        parser = networking.FrameParser(frame_ops=None)
        scratch = networking.BufferPool()
        loop.add(client.sock,
                 lambda mask: self._ev_wire_read(rec, parser, scratch))

    def _ev_wire_read(self, rec: _RouterRequest, parser, scratch) -> None:
        sock = rec.client.sock
        while True:
            target = parser.writable()
            fed_scratch = target is None
            if fed_scratch:
                target = memoryview(scratch.get(_RELAY_RECV_CHUNK))
            try:
                n = sock.recv_into(target)
            except (BlockingIOError, InterruptedError):
                return
            except (ConnectionError, OSError):
                self._ev_wire_lost(rec)
                return
            if not n:
                self._ev_wire_lost(rec)  # EOF mid-stream = lost replica
                return
            if fed_scratch:
                parser.feed(target[:n])
            else:
                parser.advance(n)
            try:
                for _op, msg in parser.messages():
                    if self._ev_wire_frame(rec, msg):
                        return  # stream detached (done / typed / lost)
            except ValueError:
                self._ev_wire_lost(rec)  # garbage frame = broken wire
                return

    def _ev_wire_frame(self, rec: _RouterRequest, msg) -> bool:
        """One reply frame, mirroring ``ServingClient.stream`` +
        ``_stream_wire``'s verdicts.  Returns True when the socket left
        the loop (stream over, typed death, or protocol error)."""
        if msg.get("error"):
            kind = msg.get("kind")
            if kind in ("engine_dead", "stall"):
                # typed death: the transport is intact, the engine
                # behind it is not — keep the connection, fail over
                self._ev_wire_detach(rec, keep=True)
                self._ev_failover(rec)
            else:
                self._ev_wire_detach(rec, keep=False)
                self._retire(rec, error=ValueError(str(msg["error"])))
            return True
        for t in msg["tokens"]:
            rec.proxy._push(int(t))
            rec.relayed += 1
        if msg["done"]:
            self._ev_wire_detach(rec, keep=True)
            self._retire(rec, finish=msg["finish"])
            return True
        return False

    def _ev_wire_detach(self, rec: _RouterRequest, keep: bool) -> None:
        """Unregister the wire attachment's socket; ``keep`` re-parks the
        client for reuse (socket back to blocking), else it is torn
        down."""
        client, rep = rec.client, rec.replica
        with self._lock:
            loop = self._relay_loop
        if loop is not None:
            loop.remove(client.sock)
        if keep:
            try:
                client.sock.setblocking(True)
            except OSError:
                keep = False
        if keep:
            self._pool.release(rep.addr, client)
        else:
            self._pool.discard(client)

    def _ev_wire_lost(self, rec: _RouterRequest) -> None:
        self._ev_wire_detach(rec, keep=False)
        self._ev_failover(rec)

    def _ev_failover(self, rec: _RouterRequest) -> None:
        """The attachment is gone (typed death or broken wire).  Retire a
        cancelled request; otherwise hand recovery to a transient thread
        — resubmission blocks (admission retries, backoff, a full
        re-stream with replay-skip), which must not stall the loop the
        OTHER N-1 streams are riding."""
        if rec.cancelled:
            self._retire(rec, finish="cancel")
            return
        t = threading.Thread(
            target=self._failover_relay, args=(rec,), daemon=True,
            name=f"dkt-router-failover-{rec.proxy.id}")
        with self._lock:
            rec.thread = t  # stop()/drain() join it like the old relays
        t.start()

    def _failover_relay(self, rec: _RouterRequest) -> None:
        """Failover thread: resubmit elsewhere under ``retry_policy`` —
        the ONE retry machinery ``ServingClient.generate`` also runs on —
        replaying the already-delivered prefix silently."""
        try:
            self.retry_policy.call(lambda: self._resubmit_once(rec),
                                   retry_on=_RESUBMIT_RETRY_ON)
        except _RESUBMIT_RETRY_ON as e:
            self._retire(rec, error=e if isinstance(e, EngineDead)
                         else EngineDead(f"request {rec.proxy.id}: every "
                                         f"resubmission failed ({e!r})"))
        except ValueError as e:
            self._retire(rec, error=e)

    def _resubmit_once(self, rec: _RouterRequest) -> None:
        """One failover attempt: re-route (the dead replica's load
        snapshot excludes it), re-admit with the original seed, and
        stream — skipping the ``rec.relayed`` tokens the client already
        has."""
        if rec.cancelled:
            self._retire(rec, finish="cancel")
            return
        self._admit_once(rec)
        with self._lock:
            self.counters["resubmissions"] += 1
        rec.resubmits += 1
        self._stream_once(rec)

    def _stream_once(self, rec: _RouterRequest) -> None:
        if rec.upstream is not None:
            self._stream_engine(rec)
        else:
            self._stream_wire(rec)

    def _stream_engine(self, rec: _RouterRequest) -> None:
        proxy, h = rec.proxy, rec.upstream
        skip = rec.relayed
        while True:
            chunk, done = h.next_chunk(timeout=self.poll_s)
            for t in chunk:
                if skip > 0:
                    skip -= 1
                    continue
                proxy._push(int(t))
                rec.relayed += 1
            if done:
                if h.error is not None:
                    raise h.error  # EngineDead → failover upstream
                self._retire(rec, finish=h.finish)
                return

    def _stream_wire(self, rec: _RouterRequest) -> None:
        proxy, rep = rec.proxy, rec.replica
        client, rid = rec.client, rec.rid
        skip = rec.relayed
        try:
            for tokens, done in client.stream(rid):
                for t in tokens:
                    if skip > 0:
                        skip -= 1
                        continue
                    proxy._push(int(t))
                    rec.relayed += 1
                if done is not None:
                    self._pool.release(rep.addr, client)
                    self._retire(rec, finish=done["finish"])
                    return
            raise ConnectionError("stream ended without a done frame")
        except EngineDead:
            # typed death frame: the transport is intact, the engine
            # behind it is not — keep the connection, fail over
            self._pool.release(rep.addr, client)
            raise
        except (ConnectionError, OSError):
            self._pool.discard(client)
            raise

    def _retire(self, rec: _RouterRequest, finish: Optional[str] = None,
                error: Optional[BaseException] = None) -> None:
        """Make the proxy terminal exactly once, book the router-level
        counter for its reason, and record the final ``(replica uid,
        generation)`` attribution."""
        proxy = rec.proxy
        if error is not None:
            exc = (error if isinstance(error, EngineDead)
                   else EngineDead(str(error)))
            counted = proxy._fail(exc)
            key = "requests_failed"
        else:
            counted = proxy._finish(finish)
            key = {"cancel": "requests_cancelled",
                   "deadline": "requests_expired"}.get(
                       finish, "requests_completed")
        with self._lock:
            if counted:
                self.counters[key] += 1
            if rec.attached is not None:
                self._attributions[proxy.id] = rec.attached
            self._live.pop(proxy.id, None)

    # ------------------------------------------------------------- controls
    def cancel(self, handle: RequestHandle) -> bool:
        """Cancel a proxy handle wherever its request currently lives.
        Returns False if it already finished."""
        with handle._cond:
            if handle.finish is not None:
                return False
        with self._lock:
            rec = self._live.get(handle.id)
            if rec is None or rec.proxy is not handle:
                return False
            rec.cancelled = True
            fn = rec.cancel_fn
        if fn is not None:
            try:
                fn()
            except (ConnectionError, OSError):
                pass  # replica gone: its death path retires the proxy
        return True

    def replace_engine(self, old: ServingEngine,
                       new: ServingEngine) -> None:
        """Swap a respawned engine into the fleet and bump the replica's
        generation (the ``resilience.FleetSupervisor`` restart seam and
        ``rolling_swap``'s per-replica move).  In-flight requests on the
        old engine fail through its death/drain path and resubmit."""
        with self._lock:
            for rep in self._replicas:
                if rep.kind == "engine" and rep.engine is old:
                    rep.engine = new
                    rep.generation += 1
                    self.counters["generation_swaps"] += 1
                    return
        raise ValueError("engine to replace is not part of this fleet")

    def rolling_swap(self, drain_timeout: Optional[float] = 10.0) -> int:
        """Fleet-wide blue/green under live traffic: per in-process
        replica, build its successor (``respawn_clone`` — PR 15's atomic
        generation-swap recipe), warm it, start it, swap it in (new
        admissions land on the successor from that instant), then drain
        the predecessor so its in-flight requests finish on the
        generation that accepted them.  One replica at a time — N−1
        replicas serve throughout.  Returns the number of replicas
        swapped; dead replicas are skipped (the supervisor owns those)."""
        swapped = 0
        for rep in self._engine_replicas():
            old = rep.engine
            if old.dead is not None:
                continue
            new = old.respawn_clone()
            new.warmup()
            with self._lock:
                started = self._started
            if started:
                new.start()
            self.replace_engine(old, new)
            old.drain(timeout=drain_timeout)
            swapped += 1
        return swapped

    # ----------------------------------------------------------- elasticity
    def scale_up(self) -> int:
        """Add one in-process replica through ``engine_factory`` (warmed,
        and started if the router is running).  Returns its uid."""
        if self.engine_factory is None:
            raise ValueError("scale_up needs engine_factory=")
        eng = self.engine_factory()
        for p in self._tenants.values():  # fleet QoS reaches new capacity
            eng.register_tenant(p.clone())
        eng.warmup()
        with self._lock:
            started = self._started
        if started:
            eng.start()
        with self._lock:
            rep = _EngineReplica(self._next_uid, eng)
            self._next_uid += 1
            self._replicas.append(rep)
            self.counters["scale_ups"] += 1
        return rep.uid

    def scale_down(self, uid: Optional[int] = None,
                   timeout: Optional[float] = 10.0) -> Optional[int]:
        """Drain one in-process replica out of the fleet: mark it
        draining (routing excludes it immediately), ``drain()`` it so
        queued + running requests finish — a drain timeout fails the
        stragglers typed and the relays resubmit them to the surviving
        replicas — then remove it.  ``uid=None`` picks the least-loaded
        replica.  Refuses (returns None) at ``min_replicas`` or when no
        in-process replica matches."""
        with self._lock:
            cands = [r for r in self._replicas
                     if r.kind == "engine" and not r.draining]
            if len([r for r in self._replicas if not r.draining]) \
                    <= self.min_replicas:
                return None
            if uid is not None:
                cands = [r for r in cands if r.uid == uid]
            if not cands:
                return None
            rep = min(cands, key=lambda r: self._score(r.engine.load()))
            rep.draining = True
        rep.engine.drain(timeout=timeout)
        with self._lock:
            if rep in self._replicas:
                self._replicas.remove(rep)
            self.counters["scale_downs"] += 1
        return rep.uid

    def autoscale_tick(self) -> Optional[str]:
        """One queue-depth-driven elasticity decision: mean queue depth
        across live replicas above ``scale_up_queue`` grows the fleet
        (bounded by ``max_replicas``); an entirely idle fleet (zero
        queued, zero active anywhere) shrinks it (bounded by
        ``min_replicas``).  Returns ``"up"``/``"down"``/None.  Call it
        from whatever cadence owns capacity — a loadgen loop, a cron, a
        supervisor thread."""
        cands = self._candidates()
        if not cands:
            return None
        loads = [l for _, l in cands]
        total_q = sum(int(l.get("queue_depth", 0)) for l in loads)
        total_active = sum(int(l.get("active", 0)) for l in loads)
        n = len(loads)
        if (total_q / n > self.scale_up_queue and n < self.max_replicas
                and self.engine_factory is not None):
            self.scale_up()
            return "up"
        if (total_q == 0 and total_active == 0 and n > self.min_replicas
                and any(r.kind == "engine" for r, _ in cands)):
            if self.scale_down(timeout=10.0) is not None:
                return "down"
        return None

    # ------------------------------------------------------------ telemetry
    def generation_of(self, handle: RequestHandle
                      ) -> Optional[Tuple[int, int]]:
        """The ``(replica uid, generation)`` that produced (or currently
        owns) this request — every response is attributable to exactly
        one generation (the blue/green audit surface)."""
        with self._lock:
            rec = self._live.get(handle.id)
            if rec is not None and rec.proxy is handle:
                return rec.attached
            return self._attributions.get(handle.id)

    def fleet_snapshot(self) -> List[Dict[str, Any]]:
        """One dict per replica: identity (uid/kind/generation/draining),
        the routed-request count, and the current load snapshot — the
        observability surface loadgen's per-replica skew report reads."""
        with self._lock:
            reps = list(self._replicas)
        out = []
        for rep in reps:
            load = rep.load()
            with self._lock:
                out.append({"uid": rep.uid, "kind": rep.kind,
                            "generation": rep.generation,
                            "draining": rep.draining,
                            "routed": rep.routed, "load": load})
        return out

    @property
    def engines(self) -> List[ServingEngine]:
        """The in-process replica engines (the ``FleetSupervisor`` and
        swap surface; wire replicas' engines live elsewhere)."""
        with self._lock:
            return [r.engine for r in self._replicas
                    if r.kind == "engine"]

    @property
    def num_replicas(self) -> int:
        with self._lock:
            return len([r for r in self._replicas if not r.draining])

    @property
    def stats(self) -> Dict[str, Any]:
        """Merged IN-PROCESS engine stats (numeric counters summed,
        sample lists concatenated — wire replicas report to their own
        process) with the request-level terminal counters OVERRIDDEN by
        the router's own: a resubmitted request is one client request,
        not one per attempt."""
        merged: Dict[str, Any] = {}
        for e in self.engines:
            for k, v in e.stats.items():
                if isinstance(v, bool) or not isinstance(
                        v, (int, float, list)):
                    merged.setdefault(k, v)
                elif isinstance(v, list):
                    merged.setdefault(k, [])
                    merged[k] = merged[k] + list(v)
                else:
                    merged[k] = merged.get(k, 0) + v
        with self._lock:
            merged.update(self.counters)
        return merged

    @property
    def kv_blocks_in_use(self) -> Optional[int]:
        """Summed across in-process replicas — the fleet-level zero-leak
        assertion surface."""
        vals = [e.kv_blocks_in_use for e in self.engines]
        vals = [v for v in vals if v is not None]
        return sum(vals) if vals else None

    @property
    def slot_occupancy(self) -> Optional[float]:
        """Mean occupancy across in-process replicas (None until any
        replica has decoded)."""
        vals = [e.slot_occupancy for e in self.engines]
        vals = [v for v in vals if v is not None]
        return sum(vals) / len(vals) if vals else None

    @property
    def queue_depth(self) -> int:
        return sum(e.queue_depth for e in self.engines)

    @property
    def max_len(self) -> int:
        lens = [e.max_len for e in self.engines]
        with self._lock:
            wire = [r for r in self._replicas if r.kind == "wire"]
        for rep in wire:
            ml = rep.load().get("max_len")
            if ml:
                lens.append(int(ml))
        return min(lens) if lens else 0

    @property
    def dead(self) -> Optional[BaseException]:
        """None while ANY replica is routable; the first dead replica's
        error once the whole fleet is gone (a single dead replica is a
        failover event, not a router death)."""
        first: Optional[BaseException] = None
        for e in self.engines:
            if e.dead is None:
                return None
            first = first or e.dead
        with self._lock:
            has_wire = any(r.kind == "wire" for r in self._replicas)
        if has_wire:
            return None  # remote liveness is the probe's to report
        return first
