"""Distributed inference (reference: ``distkeras/predictors.py``).

``ModelPredictor.predict(dataset)`` appends a ``prediction`` column holding
the model's dense output vector for every row — parity with the reference's
``ModelPredictor.predict(df)`` (SURVEY.md §3.3), but instead of deserializing
the model once per Spark partition and looping rows through ``model.predict``,
the forward pass is jitted once and run as large sharded batches across the
device mesh (batch-dim data parallelism over ICI).
"""

from __future__ import annotations

from typing import Optional, Union

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .core.model import Sequential, FittedModel
from .data.dataset import Dataset
from .parallel import mesh as mesh_lib


class Predictor:
    """Base class (reference: ``predictors.py :: Predictor``)."""

    def predict(self, dataset: Dataset) -> Dataset:  # pragma: no cover
        raise NotImplementedError


class ModelPredictor(Predictor):
    def __init__(self, keras_model: Union[FittedModel, Sequential],
                 features_col: str = "features",
                 output_col: str = "prediction",
                 batch_size: int = 1024, mesh=None):
        if isinstance(keras_model, FittedModel):
            self.model = keras_model.model
            self.params = keras_model.params
        else:
            raise TypeError(
                "ModelPredictor needs a FittedModel (a trained model with "
                "weights); got a bare Sequential spec")
        self.features_col = features_col
        self.output_col = output_col
        self.batch_size = int(batch_size)
        self.mesh = mesh

    def predict(self, dataset: Dataset) -> Dataset:
        x = np.asarray(dataset[self.features_col])
        mesh = self.mesh
        if mesh is None and len(jax.devices()) > 1:
            mesh = mesh_lib.get_mesh()
        if mesh is not None:
            preds = self._predict_sharded(x, mesh)
        else:
            preds = self.model.predict(self.params, x,
                                       batch_size=self.batch_size)
        return dataset.with_column(self.output_col, preds)

    def _predict_sharded(self, x: np.ndarray, mesh) -> np.ndarray:
        """Batch-parallel forward over the mesh: pad rows to a multiple of the
        worker count, shard the batch dim, run one jitted apply per chunk."""
        n_dev = mesh.devices.size
        chunk = self.batch_size * n_dev
        sharding = NamedSharding(mesh, P(mesh_lib.WORKER_AXIS))
        fn = jax.jit(lambda p, b: self.model.apply(p, b, train=False),
                     out_shardings=sharding)
        outs = []
        for i in range(0, len(x), chunk):
            block = x[i:i + chunk]
            pad = (-len(block)) % n_dev
            if pad:
                block = np.concatenate([block, block[-1:].repeat(pad, 0)])
            blk = jax.device_put(block, sharding)
            out = np.asarray(fn(self.params, blk))
            outs.append(out[:len(out) - pad] if pad else out)
        return np.concatenate(outs, axis=0)
