"""Distributed inference (reference: ``distkeras/predictors.py``).

``ModelPredictor.predict(dataset)`` appends a ``prediction`` column holding
the model's dense output vector for every row — parity with the reference's
``ModelPredictor.predict(df)`` (SURVEY.md §3.3), but instead of deserializing
the model once per Spark partition and looping rows through ``model.predict``,
the forward pass is jitted once and run as large sharded batches across the
device mesh (batch-dim data parallelism over ICI).
"""

from __future__ import annotations

from typing import Optional, Union

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .core.model import Sequential, FittedModel
from .data.dataset import Dataset
from .parallel import mesh as mesh_lib


class Predictor:
    """Base class (reference: ``predictors.py :: Predictor``)."""

    def predict(self, dataset: Dataset) -> Dataset:  # pragma: no cover
        raise NotImplementedError


class ModelPredictor(Predictor):
    """Batch inference over a dataset (reference parity), with an optional
    live-engine route for sequence models.

    Default (no ``engine``): the original jitted sharded-numpy forward —
    bit-identical to every prior release.  With ``engine`` (a
    ``serving.ServingEngine`` built on the same weights) and
    ``num_steps``, rows of ``features_col`` are treated as token prompts
    and routed through the continuous-batching engine: the output column
    holds each row's generated continuation (prompt + ``num_steps``
    tokens, the ``generate`` row shape), produced with the engine's slot
    pool instead of one dataset-sized forward.  ``generate_kwargs``
    (temperature/top_k/top_p/eos_id/pad_id/seed) pass through
    ``engine.submit`` per row — outputs match offline
    ``FittedModel.generate`` under the same seeds.
    """

    def __init__(self, keras_model: Union[FittedModel, Sequential],
                 features_col: str = "features",
                 output_col: str = "prediction",
                 batch_size: int = 1024, mesh=None,
                 engine=None, num_steps: Optional[int] = None,
                 generate_kwargs: Optional[dict] = None):
        if isinstance(keras_model, FittedModel):
            self.model = keras_model.model
            self.params = keras_model.params
        else:
            raise TypeError(
                "ModelPredictor needs a FittedModel (a trained model with "
                "weights); got a bare Sequential spec")
        self.features_col = features_col
        self.output_col = output_col
        self.batch_size = int(batch_size)
        self.mesh = mesh
        self.engine = engine
        if engine is not None and num_steps is None:
            raise ValueError("engine-backed prediction needs num_steps "
                             "(the continuation length per prompt row)")
        self.num_steps = None if num_steps is None else int(num_steps)
        self.generate_kwargs = dict(generate_kwargs or {})

    def predict(self, dataset: Dataset) -> Dataset:
        if self.engine is not None:
            return self._predict_engine(dataset)
        x = np.asarray(dataset[self.features_col])
        mesh = self.mesh
        if mesh is None and len(jax.devices()) > 1:
            mesh = mesh_lib.get_mesh()
        if mesh is not None:
            preds = self._predict_sharded(x, mesh)
        else:
            preds = self.model.predict(self.params, x,
                                       batch_size=self.batch_size)
        return dataset.with_column(self.output_col, preds)

    def _predict_engine(self, dataset: Dataset) -> Dataset:
        """Continuous-batching route: one engine request per prompt row
        (admission backpressure is honored by blocking submits), results
        reassembled in row order."""
        prompts = np.asarray(dataset[self.features_col])
        if prompts.ndim != 2:
            raise ValueError(
                f"engine-backed predict needs (rows, prompt_len) int "
                f"tokens in {self.features_col!r}, got shape "
                f"{prompts.shape}")
        was_running = self.engine._thread is not None
        self.engine.start()
        try:
            handles = [self.engine.submit(row, self.num_steps,
                                          **self.generate_kwargs)
                       for row in prompts.astype(np.int32)]
            rows = [h.result(timeout=600.0) for h in handles]
        finally:
            if not was_running:
                self.engine.stop()
        return dataset.with_column(self.output_col,
                                   np.stack(rows).astype(np.int32))

    def _predict_sharded(self, x: np.ndarray, mesh) -> np.ndarray:
        """Batch-parallel forward over the mesh: pad rows to a multiple of the
        worker count, shard the batch dim, run one jitted apply per chunk."""
        n_dev = mesh.devices.size
        chunk = self.batch_size * n_dev
        sharding = NamedSharding(mesh, P(mesh_lib.WORKER_AXIS))
        fn = jax.jit(lambda p, b: self.model.apply(p, b, train=False),
                     out_shardings=sharding)
        outs = []
        for i in range(0, len(x), chunk):
            block = x[i:i + chunk]
            pad = (-len(block)) % n_dev
            if pad:
                block = np.concatenate([block, block[-1:].repeat(pad, 0)])
            blk = jax.device_put(block, sharding)
            out = np.asarray(fn(self.params, blk))
            outs.append(out[:len(out) - pad] if pad else out)
        return np.concatenate(outs, axis=0)
