"""dklint runtime complement: live lock-order auditing.

The static pass (:mod:`.locks`) sees ``self._x`` locks inside one class;
it cannot see orders that run *across objects* reached through locals
(the serving engine touching a ``RequestHandle``'s condition while its
admission queue is involved, a supervisor probing a shard's apply lock).
:class:`OrderedLock` closes that gap at test time:

* every instrumented lock gets a stable name (its creation site),
* every acquire records ``held → new`` edges into a process-global
  acquisition-order graph **before** blocking (so a genuine inversion is
  reported instead of deadlocking the suite),
* any edge that closes a cycle is a :class:`LockOrderViolation` —
  collected on the auditor by default so swallowed-exception paths in
  product threads can't hide it; the chaos-suite fixture asserts
  ``auditor.violations == []`` at teardown.

:func:`audit_locks` patches ``threading.Lock`` / ``RLock`` /
``Condition`` with instrumented factories for the duration of a block,
so production modules are audited **unmodified** — locks created while
the patch is active are tracked, pre-existing locks are simply not.
``threading.Condition(some_ordered_lock)`` shares the wrapped lock's
identity, which reproduces the static pass's condition-owned-lock
grouping (``_not_full``/``_have_work`` are ``_qlock``).
"""

from __future__ import annotations

import sys
import threading
import traceback
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

import _thread

_REAL_LOCK = _thread.allocate_lock          # un-patchable originals
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition


class LockOrderViolation(RuntimeError):
    """A lock acquisition closed a cycle in the runtime order graph."""


def _creation_site(skip_prefixes: Tuple[str, ...]) -> str:
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if not fn.endswith(skip_prefixes) and "threading" not in fn:
            short = "/".join(fn.split("/")[-2:])
            return f"{short}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


class LockOrderAuditor:
    """Process-wide acquisition-order graph with on-the-fly cycle check."""

    def __init__(self, raise_on_violation: bool = False):
        self.raise_on_violation = raise_on_violation
        self._mu = _REAL_LOCK()
        self._edges: Dict[str, Dict[str, str]] = {}   # a -> b -> first site
        self._tls = threading.local()
        self.violations: List[str] = []

    # -- per-thread held stack
    def _held(self) -> List["OrderedLock"]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    # -- graph
    def _reachable(self, src: str, dst: str) -> Optional[List[str]]:
        """DFS path src→dst in the edge graph (cycle witness), or None."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            for nxt in self._edges.get(node, ()):  # insertion order: stable
                if nxt == dst:
                    return path + [dst]
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def before_acquire(self, lock: "OrderedLock") -> None:
        held = self._held()
        if not held:
            return
        # Fast path: every held→new edge already recorded means no new
        # bookkeeping.  The unlocked dict reads are a benign race — a miss
        # just sends us through the slow path, which re-checks under _mu.
        # Keeping stack formatting off this path matters: hot scheduler
        # loops nest acquires thousands of times a second.
        name, edges = lock.name, self._edges
        if all(h.name == name or name in edges.get(h.name, ())
               for h in held):
            return
        caller = sys._getframe(2)
        site = None
        with self._mu:
            for h in held:
                if h.name == lock.name:
                    continue                      # re-entry of the same lock
                row = self._edges.setdefault(h.name, {})
                if lock.name in row:
                    continue
                if site is None:                  # format once, only if new
                    site = "".join(traceback.format_stack(caller, limit=3))
                back = self._reachable(lock.name, h.name)
                row[lock.name] = site.strip().splitlines()[-1].strip() \
                    if site else "?"
                if back is not None:
                    cyc = " -> ".join(back + [lock.name]) \
                        if back[-1] != lock.name else " -> ".join(back)
                    msg = (f"lock-order inversion: acquiring {lock.name} "
                           f"while holding {h.name}, but the reverse order "
                           f"{cyc} was already observed\n  at:\n{site}")
                    self.violations.append(msg)
                    if self.raise_on_violation:
                        raise LockOrderViolation(msg)

    def on_acquired(self, lock: "OrderedLock") -> None:
        self._held().append(lock)

    def on_release(self, lock: "OrderedLock") -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):    # non-LIFO release is legal
            if held[i] is lock:
                del held[i]
                return

    def edges(self) -> Dict[str, Dict[str, str]]:
        with self._mu:
            return {a: dict(bs) for a, bs in self._edges.items()}


#: auditor used by instrumented locks that are not given one explicitly
_default_auditor: Optional[LockOrderAuditor] = None


class OrderedLock:
    """Drop-in ``threading.Lock``/``RLock`` wrapper feeding an auditor.

    The underlying primitive is real (``_thread.allocate_lock`` or a real
    ``RLock``), so blocking/timeout semantics are untouched; the wrapper
    only adds order bookkeeping around ``acquire``/``release``.
    """

    def __init__(self, name: Optional[str] = None,
                 auditor: Optional[LockOrderAuditor] = None,
                 reentrant: bool = False):
        self._inner = _REAL_RLOCK() if reentrant else _REAL_LOCK()
        self.name = name or _creation_site(("runtime.py",))
        self.auditor = auditor if auditor is not None else _default_auditor
        self.reentrant = reentrant

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        aud = self.auditor
        if aud is not None and blocking:
            aud.before_acquire(self)
        got = self._inner.acquire(blocking, timeout)
        if got and aud is not None:
            aud.on_acquired(self)
        return got

    def release(self) -> None:
        self._inner.release()
        if self.auditor is not None:
            self.auditor.on_release(self)

    def locked(self) -> bool:
        if self.reentrant:                      # pragma: no cover - parity
            raise AttributeError("locked() on an RLock wrapper")
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<OrderedLock {self.name}>"


def _make_condition(auditor: Optional[LockOrderAuditor],
                    lock=None) -> "threading.Condition":
    """A real ``threading.Condition`` over an :class:`OrderedLock`.

    ``Condition.wait`` releases/reacquires through the wrapper, so the
    held-stack stays truthful across waits; a condition built over an
    existing ordered lock shares that lock's name (group identity).
    """
    if lock is None:
        lock = OrderedLock(auditor=auditor,
                           name=_creation_site(("runtime.py",)))
    return _REAL_CONDITION(lock)


@contextmanager
def audit_locks(auditor: Optional[LockOrderAuditor] = None,
                raise_on_violation: bool = False):
    """Patch ``threading.Lock``/``RLock``/``Condition`` with instrumented
    factories for the duration of the block; yields the auditor.

    Opt-in by design: the chaos/resilience suites use the
    ``lock_order_audit`` conftest fixture, which wraps the test body in
    this context and asserts no violations at teardown.
    """
    global _default_auditor
    aud = auditor or LockOrderAuditor(raise_on_violation=raise_on_violation)
    saved = (threading.Lock, threading.RLock, threading.Condition,
             _default_auditor)
    _default_auditor = aud

    def _lock():
        return OrderedLock(auditor=aud)

    def _rlock():
        return OrderedLock(auditor=aud, reentrant=True)

    def _condition(lock=None):
        return _make_condition(aud, lock)

    threading.Lock = _lock
    threading.RLock = _rlock
    threading.Condition = _condition
    try:
        yield aud
    finally:
        (threading.Lock, threading.RLock, threading.Condition,
         _default_auditor) = saved
