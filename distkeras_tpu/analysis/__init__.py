"""dklint — concurrency + JAX-discipline static analysis for distkeras_tpu.

Static pass (pure ``ast``, no imports of the checked code):

* ``lock-discipline`` / ``lock-guards`` / ``lock-holds`` — per-class
  inference of which attributes are guarded by which lock, with
  machine-checked ``# guards:`` and ``# dklint: holds`` annotations.
* ``lock-order`` — interprocedural acquisition-order graph + cycles.
* ``jax-host-sync`` / ``jax-traced-branch`` / ``jax-donate`` — tracing
  and transfer discipline inside jit-reachable functions.
* ``wire-opcode`` / ``wire-codec`` — wire-protocol exhaustiveness.

Run it as ``python -m distkeras_tpu.analysis [paths] [--baseline FILE]
[--json]`` (or ``python scripts/lint.py``).  Findings are suppressable
only via ``analysis/baseline.toml``; the tier-1 test
``tests/test_analysis.py::test_package_has_zero_unbaselined_findings``
keeps the analyzer, the baseline, and the package in lockstep.

Runtime complement: :class:`~distkeras_tpu.analysis.runtime.OrderedLock`
and :func:`~distkeras_tpu.analysis.runtime.audit_locks` assert lock-order
acyclicity live under the chaos suites (``lock_order_audit`` fixture).
"""

from .core import (Finding, Report, default_baseline_path, load_baseline,
                   render_baseline, run_analysis)
from .runtime import (LockOrderAuditor, LockOrderViolation, OrderedLock,
                      audit_locks)

__all__ = [
    "Finding", "Report", "run_analysis", "load_baseline",
    "render_baseline", "default_baseline_path",
    "OrderedLock", "LockOrderAuditor", "LockOrderViolation", "audit_locks",
]
