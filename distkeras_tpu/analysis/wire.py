"""dklint rule family 4: wire-protocol exhaustiveness.

Two cheap-but-load-bearing audits over the framed socket protocols:

* ``wire-opcode`` — every module-level ``<NS>_OP_<NAME> = b"?"`` constant
  is collected into its ``<NS>`` namespace (``SERVING_OP_*``,
  ``PS_OP_*``, ...).  Two different names bound to the same byte within
  one namespace is always an error (one dispatch table cannot tell them
  apart); the same byte appearing in *different* namespaces is flagged
  too, because the only thing keeping it safe is the guarantee that the
  two protocols never share a socket — if that is true it belongs in
  ``baseline.toml`` with exactly that sentence as justification.

* ``wire-codec`` — the pytree codec marks node kinds with ``"__xx__"``
  dict tags.  Any function that *builds* a dict literal keyed by such a
  tag is an encoder; any function that *tests or subscripts* at least
  two distinct tags is a decoder path (the two-tag floor keeps
  ``__main__``-style incidental strings out).  Every tag any encoder in
  the module emits must be handled by **every** decoder path in that
  module — a node kind added to ``_encode_node`` but not to
  ``_expected_buffer_sizes`` is exactly the desync this rule exists for.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Sequence, Set, Tuple

from .core import Finding, ModuleInfo

_OPCONST_RE = re.compile(r"^([A-Z][A-Z0-9]*(?:_[A-Z0-9]+)*?)_OP_([A-Z0-9_]+)$")
_TAG_RE = re.compile(r"^__\w+__$")


def _opcode_findings(mods: Sequence[ModuleInfo]) -> List[Finding]:
    # (namespace, name) -> (value, mod, line)
    consts: Dict[Tuple[str, str], Tuple[bytes, ModuleInfo, int]] = {}
    for mod in mods:
        for node in mod.tree.body:
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            m = _OPCONST_RE.match(node.targets[0].id)
            if not m:
                continue
            if isinstance(node.value, ast.Constant) and \
                    isinstance(node.value.value, bytes):
                consts[(m.group(1), node.targets[0].id)] = (
                    node.value.value, mod, node.lineno)
    out: List[Finding] = []
    items = sorted(consts.items())
    for i, ((ns_a, name_a), (val_a, mod_a, line_a)) in enumerate(items):
        for (ns_b, name_b), (val_b, mod_b, line_b) in items[i + 1:]:
            if val_a != val_b:
                continue
            ident = f"wire-opcode:{name_a}<->{name_b}"
            if ns_a == ns_b:
                msg = (f"opcode collision inside namespace {ns_a}: "
                       f"{name_a} ({mod_a.rel}:{line_a}) and {name_b} "
                       f"({mod_b.rel}:{line_b}) are both {val_a!r} — one "
                       f"dispatch table cannot tell them apart")
            else:
                msg = (f"cross-namespace opcode collision: {name_a} "
                       f"({ns_a}, {mod_a.rel}:{line_a}) and {name_b} "
                       f"({ns_b}, {mod_b.rel}:{line_b}) are both {val_a!r} "
                       f"— safe only while the protocols never share a "
                       f"socket")
            out.append(Finding("wire-opcode", ident, mod_a.path, line_a,
                               msg))
    return out


def _func_iter(tree: ast.Module):
    """Yield (qualname, FunctionDef) for every function, nested included."""
    def walk(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}.{child.name}" if prefix else child.name
                yield qual, child
                yield from walk(child, qual)
            elif isinstance(child, ast.ClassDef):
                cq = f"{prefix}.{child.name}" if prefix else child.name
                yield from walk(child, cq)
            else:
                yield from walk(child, prefix)
    yield from walk(tree, "")


def _codec_findings(mods: Sequence[ModuleInfo]) -> List[Finding]:
    out: List[Finding] = []
    for mod in mods:
        encoded: Dict[str, Tuple[str, int]] = {}   # tag -> (encoder, line)
        decoders: List[Tuple[str, int, Set[str]]] = []
        for qual, fn in _func_iter(mod.tree):
            emits: Set[str] = set()
            handles: Set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Dict):
                    for k in node.keys:
                        if isinstance(k, ast.Constant) and \
                                isinstance(k.value, str) and \
                                _TAG_RE.match(k.value):
                            emits.add(k.value)
                elif isinstance(node, ast.Compare) and \
                        len(node.ops) == 1 and \
                        isinstance(node.ops[0], (ast.In, ast.NotIn)) and \
                        isinstance(node.left, ast.Constant) and \
                        isinstance(node.left.value, str) and \
                        _TAG_RE.match(node.left.value):
                    handles.add(node.left.value)
                elif isinstance(node, ast.Subscript):
                    sl = node.slice
                    if isinstance(sl, ast.Constant) and \
                            isinstance(sl.value, str) and \
                            _TAG_RE.match(sl.value):
                        handles.add(sl.value)
            for t in emits:
                encoded.setdefault(t, (qual, fn.lineno))
            if len(handles) >= 2 and not emits:
                decoders.append((qual, fn.lineno, handles))
        if not encoded or not decoders:
            continue
        for dq, dline, handles in decoders:
            for tag in sorted(encoded):
                if tag not in handles:
                    eq, eline = encoded[tag]
                    out.append(Finding(
                        "wire-codec",
                        f"wire-codec:{mod.rel}:{dq}:{tag}",
                        mod.path, dline,
                        f"codec node tag `{tag}` is emitted by {eq}() "
                        f"(line {eline}) but decoder path {dq}() never "
                        f"handles it — encode/decode desync"))
    return out


def check(mods: Sequence[ModuleInfo]) -> List[Finding]:
    return _opcode_findings(mods) + _codec_findings(mods)
