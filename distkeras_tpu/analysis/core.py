"""dklint core: findings, file walking, baseline handling, orchestration.

The analyzer is pure-``ast`` — it never imports the modules it checks, so
it runs in milliseconds on ``JAX_PLATFORMS=cpu`` CI and cannot be confused
by import-time side effects.  Each rule family lives in its own module
(:mod:`.locks`, :mod:`.jaxrules`, :mod:`.wire`); this module owns the
shared vocabulary:

* :class:`Finding` — one diagnostic.  Its ``ident`` is *line-number-free*
  (``rule:relpath:symbol``) so a baseline entry survives unrelated edits
  to the file above it.
* :func:`load_baseline` / :func:`render_baseline` — the only sanctioned
  suppression channel.  A finding disappears from the exit-code path only
  when ``analysis/baseline.toml`` carries its ``ident`` plus a one-line
  human justification; there are no inline ``# noqa``-style escapes.
* :func:`run_analysis` — parse once, run every family, apply the
  baseline, report stale baseline entries.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

#: Rule-family identifiers (the first component of every finding ident).
RULES = (
    "lock-discipline",   # attr accessed both under and outside its lock
    "lock-guards",       # drift against a ``# guards:`` annotation
    "lock-holds",        # call to a ``# dklint: holds`` method w/o the lock
    "lock-order",        # acquisition-order cycle
    "jax-host-sync",     # host materialization inside jit-reachable code
    "jax-traced-branch",  # Python if/while on a tracer-valued expression
    "jax-donate",        # cache-threading jit callsite missing donate_argnums
    "wire-opcode",       # opcode collision (same or cross namespace)
    "wire-codec",        # node tag encoded but not decoded by every decoder
)


@dataclass(frozen=True)
class Finding:
    """One diagnostic.  ``ident`` is the stable baseline key; ``line`` is
    presentation-only (it may drift between runs without invalidating a
    baseline entry)."""
    rule: str
    ident: str
    path: str       # path as given on the command line (for display)
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}\n" \
               f"    id: {self.ident}"

    def as_dict(self) -> Dict[str, object]:
        return {"rule": self.rule, "ident": self.ident, "path": self.path,
                "line": self.line, "message": self.message}


@dataclass
class ModuleInfo:
    """One parsed source file, shared by every rule family."""
    path: str           # filesystem path (display)
    rel: str            # path relative to its scan root (ident component)
    modkey: str         # dotted module key, e.g. ``core.decode``
    tree: ast.Module = field(repr=False, default=None)
    lines: List[str] = field(repr=False, default_factory=list)

    def src_line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def span_text(self, lo: int, hi: int) -> str:
        """Source text of lines ``lo..hi`` inclusive (annotation search)."""
        return "\n".join(self.lines[max(lo - 1, 0):hi])


def _modkey_for(rel: str) -> str:
    base = rel[:-3] if rel.endswith(".py") else rel
    parts = [p for p in base.split(os.sep) if p]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts) or base


def iter_py_files(paths: Sequence[str]) -> List[Tuple[str, str]]:
    """Expand CLI path arguments into ``(filesystem_path, rel)`` pairs.

    ``rel`` — the ident component — is relative to the argument that
    produced the file, so ``python -m distkeras_tpu.analysis distkeras_tpu``
    and ``... distkeras_tpu/`` yield identical idents regardless of CWD.
    """
    out: List[Tuple[str, str]] = []
    for arg in paths:
        arg = arg.rstrip(os.sep)
        if os.path.isfile(arg):
            out.append((arg, os.path.basename(arg)))
            continue
        for root, dirs, files in os.walk(arg):
            dirs[:] = sorted(d for d in dirs
                             if d not in ("__pycache__", ".git"))
            for fn in sorted(files):
                if fn.endswith(".py"):
                    fs = os.path.join(root, fn)
                    out.append((fs, os.path.relpath(fs, arg)))
    return out


def parse_modules(paths: Sequence[str]) -> List[ModuleInfo]:
    mods: List[ModuleInfo] = []
    for fs, rel in iter_py_files(paths):
        try:
            with open(fs, "r", encoding="utf-8") as f:
                src = f.read()
            tree = ast.parse(src, filename=fs)
        except (OSError, SyntaxError) as e:  # unreadable → loud, not silent
            raise RuntimeError(f"dklint: cannot analyze {fs}: {e}") from e
        mods.append(ModuleInfo(path=fs, rel=rel, modkey=_modkey_for(rel),
                               tree=tree, lines=src.splitlines()))
    return mods


# --------------------------------------------------------------- baseline
def _parse_toml(text: str) -> Dict[str, object]:
    """Parse TOML via stdlib ``tomllib`` (3.11+) or the vendored ``tomli``
    wheel baked into this image; as a last resort a minimal line parser
    that understands exactly the subset :func:`render_baseline` emits
    (``[[finding]]`` tables with string keys) — no new dependencies."""
    try:
        import tomllib as _toml          # Python >= 3.11
    except ImportError:
        try:
            import tomli as _toml        # the image ships tomli
        except ImportError:
            _toml = None
    if _toml is not None:
        return _toml.loads(text)
    findings: List[Dict[str, str]] = []
    cur: Optional[Dict[str, str]] = None
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line == "[[finding]]":
            cur = {}
            findings.append(cur)
        elif "=" in line and cur is not None:
            k, v = line.split("=", 1)
            v = v.strip()
            if v.startswith('"') and v.endswith('"'):
                v = v[1:-1].replace('\\"', '"').replace("\\\\", "\\")
            cur[k.strip()] = v
    return {"finding": findings}


def load_baseline(path: Optional[str]) -> Dict[str, str]:
    """``ident -> justification``.  Entries without a non-empty
    justification are rejected: the baseline is a reviewed ledger, not a
    mute button."""
    if path is None or not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = _parse_toml(f.read())
    out: Dict[str, str] = {}
    for ent in data.get("finding", []) or []:
        ident = str(ent.get("id", "")).strip()
        why = str(ent.get("justification", "")).strip()
        if not ident:
            raise ValueError(f"baseline {path}: entry missing 'id'")
        if not why:
            raise ValueError(
                f"baseline {path}: entry {ident!r} missing justification")
        out[ident] = why
    return out


def _toml_str(s: str) -> str:
    return '"' + s.replace("\\", "\\\\").replace('"', '\\"') + '"'


def render_baseline(entries: Dict[str, str]) -> str:
    """Serialize ``ident -> justification`` in the format
    :func:`load_baseline` reads (used by tests and ``--write-baseline``)."""
    parts = ["# dklint baseline — every entry is a reviewed suppression.",
             "# Remove entries as the underlying finding is fixed.", ""]
    for ident in sorted(entries):
        parts += ["[[finding]]",
                  f"id = {_toml_str(ident)}",
                  f"justification = {_toml_str(entries[ident])}", ""]
    return "\n".join(parts)


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.toml")


# ----------------------------------------------------------- orchestrator
@dataclass
class Report:
    findings: List[Finding]          # everything the rules produced
    unbaselined: List[Finding]       # findings with no baseline entry
    suppressed: List[Finding]        # findings covered by the baseline
    stale_baseline: List[str]        # baseline idents that matched nothing


def run_analysis(paths: Sequence[str],
                 baseline: Optional[str] = None) -> Report:
    """Run every rule family over ``paths`` and split the findings against
    the baseline file (``None`` → no suppression)."""
    from . import jaxrules, locks, wire
    mods = parse_modules(paths)
    findings: List[Finding] = []
    findings += locks.check(mods)
    findings += jaxrules.check(mods)
    findings += wire.check(mods)
    findings.sort(key=lambda f: (f.path, f.line, f.ident))
    base = load_baseline(baseline)
    seen = {f.ident for f in findings}
    unb = [f for f in findings if f.ident not in base]
    sup = [f for f in findings if f.ident in base]
    stale = sorted(i for i in base if i not in seen)
    return Report(findings=findings, unbaselined=unb, suppressed=sup,
                  stale_baseline=stale)
