"""CLI: ``python -m distkeras_tpu.analysis [paths] [--baseline FILE]``.

Exit code 0 — no unbaselined findings (stale baseline entries are
reported as warnings so the ledger shrinks as fixes land); 1 — at least
one unbaselined finding.  ``--json`` emits a machine-readable report for
CI annotation; ``--write-baseline`` freezes the current unbaselined set
(each entry still needs a human justification before it will load).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .core import (default_baseline_path, load_baseline, render_baseline,
                   run_analysis)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m distkeras_tpu.analysis",
        description="dklint: concurrency + JAX-discipline static analyzer")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/directories to analyze "
                         "(default: the distkeras_tpu package)")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="baseline TOML (default: analysis/baseline.toml; "
                         "'none' disables suppression)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the full report as JSON")
    ap.add_argument("--write-baseline", metavar="FILE", default=None,
                    help="write current unbaselined findings as a baseline "
                         "skeleton (justifications left empty on purpose)")
    args = ap.parse_args(argv)

    paths = args.paths or [os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))]
    baseline = args.baseline
    if baseline is None:
        baseline = default_baseline_path()
    elif baseline.lower() == "none":
        baseline = None

    report = run_analysis(paths, baseline=baseline)

    if args.write_baseline:
        entries = {f.ident: "" for f in report.unbaselined}
        with open(args.write_baseline, "w", encoding="utf-8") as f:
            f.write(render_baseline(entries))
        print(f"dklint: wrote {len(entries)} skeleton entries to "
              f"{args.write_baseline} (fill in justifications)")

    if args.as_json:
        print(json.dumps({
            "unbaselined": [f.as_dict() for f in report.unbaselined],
            "suppressed": [f.as_dict() for f in report.suppressed],
            "stale_baseline": report.stale_baseline,
        }, indent=2))
    else:
        for f in report.unbaselined:
            print(f.render())
        for ident in report.stale_baseline:
            print(f"warning: stale baseline entry (no longer found): "
                  f"{ident}", file=sys.stderr)
        n, s = len(report.unbaselined), len(report.suppressed)
        print(f"dklint: {n} unbaselined finding(s), {s} baselined, "
              f"{len(report.stale_baseline)} stale baseline entr(y/ies)")
    return 1 if report.unbaselined else 0


if __name__ == "__main__":
    sys.exit(main())
