"""dklint rule family 3: JAX tracing / transfer discipline.

Everything here keys off **jit roots** — functions wrapped by a
``jax.jit`` / ``jit`` / ``partial(jax.jit, ...)`` callsite or decorator.
The collector is lexical-scope aware because this codebase's dominant
idiom is a builder method that defines a nested ``step``/``prefill``
function and returns ``jax.jit(step, donate_argnums=...)``.

From each root, reachability follows plain ``name(...)`` calls through
the nested-scope chain, module globals, and package-local imports
(``from .core.decode import decode_step``), plus ``self.m(...)`` within
the defining class.  Inside every reachable function the rules are:

* ``jax-host-sync`` — ``.item()`` and ``jax.device_get`` calls flag
  unconditionally (nothing inside a traced region should synchronize);
  ``float(x)`` / ``int(x)`` / ``bool(x)`` / ``np.asarray(x)`` /
  ``np.array(x)`` flag only when ``x`` is **tracer-tainted**.
* ``jax-traced-branch`` — Python ``if``/``while`` on a tracer-tainted
  test (trace-time branching bakes one side into the compiled program,
  the retrace-guard class of bug).
* ``jax-donate`` — a jit callsite wrapping a function with a KV-cache
  parameter (``cache``/``caches``/``kv_caches``/``decode_state``) and no
  ``donate_argnums``/``donate_argnames``: cache threading without
  donation doubles peak HBM for the pool.

**Taint model** (the false-positive control): a function parameter is a
tracer candidate unless it is ``self``/``cls``, is listed in the jit
callsite's ``static_argnums``/``static_argnames``, carries a
``bool``/``int``/``str`` annotation, defaults to a ``bool``/``int``/
``str``/``None`` literal, or is one of the conventional trace-time
constants this repo threads everywhere (``model``, ``mesh``, ``config``,
``cfg``, ``rolling``, ``causal``, ``block_size``).  Shape math is not
taint: ``x.shape``/``x.dtype``/``x.ndim``/``x.size``, ``len(x)``,
``isinstance(x, ...)`` and ``x is None`` are all static under tracing.
Locals pick up taint through straight-line assignment (two passes, so
loop-carried taint converges).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, ModuleInfo

STATIC_PARAM_NAMES = {"self", "cls", "model", "mesh", "config", "cfg",
                      "rolling", "causal", "block_size"}
STATIC_ANNOTATIONS = {"bool", "int", "str"}
SHAPE_ATTRS = {"shape", "dtype", "ndim", "size"}
#: the only attribute accesses that keep tracer taint — everything else
#: (``mha.rope``, ``layer.use_bias``, …) is config plumbing, not data
ARRAY_ATTRS = {"T", "mT", "real", "imag", "at"}
#: method calls whose result stays tracer-valued when the receiver is
ARRAY_METHODS = {"sum", "any", "all", "min", "max", "mean", "prod",
                 "astype", "dot", "ravel", "reshape", "squeeze", "take",
                 "round", "clip", "set", "add", "get"}
STATIC_CALLS = {"len", "isinstance", "getattr", "hasattr", "type", "range",
                "enumerate", "zip", "callable"}
CACHE_PARAMS = {"cache", "caches", "kv_cache", "kv_caches", "decode_state"}
CASTS = {"float", "int", "bool"}


def _is_jit_expr(node: ast.AST) -> bool:
    """``jax.jit`` / ``jit`` as an expression (decorator or callee)."""
    if isinstance(node, ast.Attribute) and node.attr == "jit" and \
            isinstance(node.value, ast.Name) and node.value.id == "jax":
        return True
    return isinstance(node, ast.Name) and node.id == "jit"


def _jit_call_parts(call: ast.Call
                    ) -> Optional[Tuple[List[ast.expr], List[ast.keyword]]]:
    """If ``call`` is a jit application, return (args, all-keywords).

    Handles ``jax.jit(f, ...)`` and ``partial(jax.jit, ...)(f)`` /
    ``functools.partial(jax.jit, ...)(f)``.
    """
    if _is_jit_expr(call.func):
        return call.args, call.keywords
    fn = call.func
    if isinstance(fn, ast.Call):
        inner = fn.func
        is_partial = (isinstance(inner, ast.Name) and inner.id == "partial") \
            or (isinstance(inner, ast.Attribute) and inner.attr == "partial")
        if is_partial and fn.args and _is_jit_expr(fn.args[0]):
            return call.args, fn.keywords + call.keywords
    return None


@dataclass
class FuncRec:
    node: ast.AST                     # FunctionDef | Lambda
    modkey: str
    mod: ModuleInfo
    qual: str
    outer: Optional["FuncRec"]
    cls: Optional[str]                # owning class name, for self.m()
    nested: Dict[str, "FuncRec"] = field(default_factory=dict)
    static_params: Set[str] = field(default_factory=set)  # from jit kwargs


@dataclass
class _ModScan:
    mod: ModuleInfo
    toplevel: Dict[str, FuncRec] = field(default_factory=dict)
    methods: Dict[Tuple[str, str], FuncRec] = field(default_factory=dict)
    imports: Dict[str, Tuple[str, ...]] = field(default_factory=dict)
    roots: List[Tuple[FuncRec, List[ast.keyword]]] = field(
        default_factory=list)
    jit_sites: List[Tuple[ast.Call, Optional[FuncRec]]] = field(
        default_factory=list)


def _rel_modkey(modkey: str, level: int, module: Optional[str]) -> str:
    """Resolve a ``from``-import target to a scan-root-relative modkey."""
    if level == 0:
        if module is None:
            return ""
        parts = module.split(".")
        return ".".join(parts)
    pkg = modkey.split(".")[:-1] if modkey else []
    pkg = pkg[:len(pkg) - (level - 1)] if level > 1 else pkg
    tail = module.split(".") if module else []
    return ".".join(pkg + tail)


def _param_info(fn: ast.AST) -> Tuple[List[str], Set[str]]:
    """(ordered param names, heuristically-static param names)."""
    if isinstance(fn, ast.Lambda):
        a = fn.args
    else:
        a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    static: Set[str] = set()
    for p in a.posonlyargs + a.args + a.kwonlyargs:
        if p.arg in STATIC_PARAM_NAMES:
            static.add(p.arg)
        ann = p.annotation
        if isinstance(ann, ast.Subscript):       # Optional[int] & friends
            base = ann.value
            if (isinstance(base, ast.Name) and base.id == "Optional") or \
                    (isinstance(base, ast.Attribute)
                     and base.attr == "Optional"):
                ann = ann.slice
        if isinstance(ann, ast.Name) and ann.id in STATIC_ANNOTATIONS:
            static.add(p.arg)
    defaults = list(a.defaults)
    for name, d in zip(names[len(names) - len(defaults):], defaults):
        if isinstance(d, ast.Constant) and \
                isinstance(d.value, (bool, int, str, type(None))):
            static.add(name)
    for name, d in zip([p.arg for p in a.kwonlyargs], a.kw_defaults):
        if isinstance(d, ast.Constant) and \
                isinstance(d.value, (bool, int, str, type(None))):
            static.add(name)
    return names, static


# ----------------------------------------------------------- collection
class _Collector(ast.NodeVisitor):
    def __init__(self, scan: _ModScan):
        self.scan = scan
        self.stack: List[FuncRec] = []
        self.cls: Optional[str] = None

    # imports
    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        target = _rel_modkey(self.scan.mod.modkey, node.level, node.module)
        # strip an absolute package prefix ("distkeras_tpu.core" when the
        # scan root IS the package directory)
        for alias in node.names:
            name = alias.asname or alias.name
            self.scan.imports[name] = (target, alias.name)

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            name = alias.asname or alias.name.split(".")[0]
            self.scan.imports.setdefault(name, (alias.name, None))

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        prev, self.cls = self.cls, node.name
        for stmt in node.body:
            self.visit(stmt)
        self.cls = prev

    def _register(self, node: ast.AST, name: str) -> FuncRec:
        outer = self.stack[-1] if self.stack else None
        qual = (f"{outer.qual}.{name}" if outer
                else (f"{self.cls}.{name}" if self.cls else name))
        rec = FuncRec(node=node, modkey=self.scan.mod.modkey,
                      mod=self.scan.mod, qual=qual, outer=outer,
                      cls=self.cls)
        if outer is not None:
            outer.nested[name] = rec
        elif self.cls is not None:
            self.scan.methods[(self.cls, name)] = rec
        else:
            self.scan.toplevel[name] = rec
        return rec

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        rec = self._register(node, node.name)
        for dec in node.decorator_list:
            kws: Optional[List[ast.keyword]] = None
            if _is_jit_expr(dec):
                kws = []
            elif isinstance(dec, ast.Call):
                if _is_jit_expr(dec.func):
                    kws = dec.keywords
                else:
                    inner = dec.func
                    is_partial = (isinstance(inner, ast.Name)
                                  and inner.id == "partial") or \
                        (isinstance(inner, ast.Attribute)
                         and inner.attr == "partial")
                    if is_partial and dec.args and _is_jit_expr(dec.args[0]):
                        kws = dec.keywords
            if kws is not None:
                self.scan.roots.append((rec, kws))
        self.stack.append(rec)
        for stmt in node.body:
            self.visit(stmt)
        self.stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call) -> None:
        parts = _jit_call_parts(node)
        if parts is not None:
            args, kws = parts
            target: Optional[FuncRec] = None
            if args:
                tgt = args[0]
                if isinstance(tgt, ast.Lambda):
                    target = FuncRec(node=tgt, modkey=self.scan.mod.modkey,
                                     mod=self.scan.mod,
                                     qual=f"<lambda@{tgt.lineno}>",
                                     outer=self.stack[-1] if self.stack
                                     else None, cls=self.cls)
                elif isinstance(tgt, ast.Name):
                    target = self._resolve_local(tgt.id)
                elif isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id == "self" and self.cls is not None:
                    target = self.scan.methods.get((self.cls, tgt.attr))
            if target is not None:
                self.scan.roots.append((target, list(kws)))
            self.scan.jit_sites.append((node, target))
        self.generic_visit(node)

    def _resolve_local(self, name: str) -> Optional[FuncRec]:
        for rec in reversed(self.stack):
            if name in rec.nested:
                return rec.nested[name]
        return self.scan.toplevel.get(name)


def _apply_static_kwargs(rec: FuncRec, kws: List[ast.keyword]) -> None:
    names, _ = _param_info(rec.node)
    for kw in kws:
        if kw.arg == "static_argnums":
            vals = kw.value.elts if isinstance(
                kw.value, (ast.Tuple, ast.List)) else [kw.value]
            for v in vals:
                if isinstance(v, ast.Constant) and isinstance(v.value, int) \
                        and 0 <= v.value < len(names):
                    rec.static_params.add(names[v.value])
        elif kw.arg == "static_argnames":
            vals = kw.value.elts if isinstance(
                kw.value, (ast.Tuple, ast.List)) else [kw.value]
            for v in vals:
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    rec.static_params.add(v.value)


# ------------------------------------------------------------ taint check
class _Taint:
    def __init__(self, tracers: Set[str], static_fns: Set[str] = frozenset()):
        self.names = set(tracers)
        self.static_fns = static_fns

    def tainted(self, e: ast.AST) -> bool:
        if isinstance(e, ast.Name):
            return e.id in self.names
        if isinstance(e, ast.Constant):
            return False
        if isinstance(e, ast.Attribute):
            # only array-view attrs keep taint; `mha.rope`-style config
            # plumbing (and .shape/.dtype/.ndim/.size) is trace-static
            if e.attr in ARRAY_ATTRS:
                return self.tainted(e.value)
            return False
        if isinstance(e, ast.Call):
            fn = e.func
            if isinstance(fn, ast.Name):
                if fn.id in STATIC_CALLS or fn.id in self.static_fns:
                    return False
            if isinstance(fn, ast.Attribute):
                if fn.attr in SHAPE_ATTRS:
                    return False
                if fn.attr in ARRAY_METHODS and self.tainted(fn.value):
                    return True               # mask.any(), x.at[i].set(v)
            return any(self.tainted(a) for a in e.args) or \
                any(self.tainted(k.value) for k in e.keywords)
        if isinstance(e, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                   for op in e.ops):
                return False                  # identity/membership: pytree
            return self.tainted(e.left) or \
                any(self.tainted(c) for c in e.comparators)
        return any(self.tainted(c) for c in ast.iter_child_nodes(e))


def _static_predicates(tree: ast.Module) -> Set[str]:
    """Names of module functions whose every ``return`` value is
    trace-static even when all their params are tracers — structure
    probes like ``_kv_quantized(cache) -> "ks" in cache`` or
    ``_per_row(pos) -> pos.ndim == 1``.  Calls to them never carry
    taint.  Fixpoint over 3 passes so predicates may call predicates."""
    fns: List[ast.FunctionDef] = [
        n for n in ast.walk(tree)
        if isinstance(n, ast.FunctionDef)]
    static: Set[str] = set()
    for _ in range(3):
        nxt: Set[str] = set()
        for fn in fns:
            names, _ = _param_info(fn)
            t = _Taint(set(names), static_fns=static)
            rets = [n for n in ast.walk(fn) if isinstance(n, ast.Return)]
            if not rets or any(isinstance(n, (ast.Yield, ast.YieldFrom))
                               for n in ast.walk(fn)):
                continue
            if all(r.value is None or not t.tainted(r.value)
                   for r in rets):
                nxt.add(fn.name)
        if nxt == static:
            break
        static = nxt
    return static


def _local_taint(fn: ast.AST, tracers: Set[str],
                 static_fns: Set[str] = frozenset()) -> _Taint:
    t = _Taint(tracers, static_fns=static_fns)
    body = fn.body if isinstance(fn, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)) else []
    for _ in range(2):                      # loop-carried taint: 2 passes
        for node in ast.walk(ast.Module(body=list(body),
                                        type_ignores=[])):
            if isinstance(node, ast.Assign) and t.tainted(node.value):
                for tgt in node.targets:
                    for n in ast.walk(tgt):
                        if isinstance(n, ast.Name):
                            t.names.add(n.id)
            elif isinstance(node, ast.AugAssign) and \
                    isinstance(node.target, ast.Name) and \
                    t.tainted(node.value):
                t.names.add(node.target.id)
            elif isinstance(node, (ast.For, ast.comprehension)):
                it = node.iter
                if t.tainted(it):
                    tgt = node.target
                    for n in ast.walk(tgt):
                        if isinstance(n, ast.Name):
                            t.names.add(n.id)
    return t


def _short(e: ast.AST, limit: int = 48) -> str:
    try:
        s = ast.unparse(e)
    except Exception:                        # pragma: no cover
        s = "<expr>"
    s = " ".join(s.split())
    return s if len(s) <= limit else s[:limit] + "…"


# ------------------------------------------------------------ rule engine
class _RuleScan(ast.NodeVisitor):
    def __init__(self, rec: FuncRec, taint: _Taint, sink):
        self.rec = rec
        self.taint = taint
        self.sink = sink
        self.calls: List[Tuple[str, ...]] = []   # callee refs for reach.

    def _f(self, rule: str, tag: str, line: int, msg: str) -> None:
        rel, qual = self.rec.mod.rel, self.rec.qual
        self.sink(Finding(rule, f"{rule}:{rel}:{qual}:{tag}",
                          self.rec.mod.path, line, msg))

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass                                  # nested defs scanned on reach

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        qual = self.rec.qual
        if isinstance(fn, ast.Attribute):
            if fn.attr == "item" and not node.args:
                self._f("jax-host-sync", f"{_short(fn.value)}.item",
                        node.lineno,
                        f"`{_short(fn.value)}.item()` inside jit-reachable "
                        f"`{qual}` forces a device→host sync per trace")
            elif fn.attr == "device_get" and \
                    isinstance(fn.value, ast.Name) and fn.value.id == "jax":
                self._f("jax-host-sync", "device_get", node.lineno,
                        f"`jax.device_get` inside jit-reachable `{qual}` "
                        f"materializes on host mid-trace")
            elif fn.attr in ("asarray", "array") and \
                    isinstance(fn.value, ast.Name) and \
                    fn.value.id in ("np", "numpy", "onp") and node.args and \
                    self.taint.tainted(node.args[0]):
                self._f("jax-host-sync",
                        f"np.{fn.attr}({_short(node.args[0])})",
                        node.lineno,
                        f"`np.{fn.attr}` on tracer-valued "
                        f"`{_short(node.args[0])}` inside jit-reachable "
                        f"`{qual}` pulls the value to host")
            # self.m(...) reachability
            if isinstance(fn.value, ast.Name) and fn.value.id == "self":
                self.calls.append(("self", fn.attr))
            elif isinstance(fn.value, ast.Name):
                self.calls.append(("mod", fn.value.id, fn.attr))
        elif isinstance(fn, ast.Name):
            if fn.id in CASTS and len(node.args) == 1 and \
                    self.taint.tainted(node.args[0]):
                self._f("jax-host-sync",
                        f"{fn.id}({_short(node.args[0])})", node.lineno,
                        f"`{fn.id}()` on tracer-valued "
                        f"`{_short(node.args[0])}` inside jit-reachable "
                        f"`{qual}` concretizes the tracer")
            self.calls.append(("name", fn.id))
        self.generic_visit(node)

    def _branch(self, node, kw: str) -> None:
        if self.taint.tainted(node.test):
            self._f("jax-traced-branch", f"{kw}:{_short(node.test)}",
                    node.test.lineno,
                    f"Python `{kw}` on tracer-valued "
                    f"`{_short(node.test)}` in jit-reachable "
                    f"`{self.rec.qual}` — use lax.cond/select or hoist to "
                    f"a static argument")

    def visit_If(self, node: ast.If) -> None:
        self._branch(node, "if")
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._branch(node, "while")
        self.generic_visit(node)

    def visit_IfExp(self, node: ast.IfExp) -> None:
        if self.taint.tainted(node.test):
            self._f("jax-traced-branch", f"ifexp:{_short(node.test)}",
                    node.lineno,
                    f"conditional expression on tracer-valued "
                    f"`{_short(node.test)}` in jit-reachable "
                    f"`{self.rec.qual}`")
        self.generic_visit(node)


def _resolve_call(scan: _ModScan, scans: Dict[str, _ModScan],
                  rec: FuncRec, ref: Tuple[str, ...]) -> Optional[FuncRec]:
    if ref[0] == "self":
        if rec.cls is not None:
            return scan.methods.get((rec.cls, ref[1]))
        return None
    if ref[0] == "name":
        cur = rec
        while cur is not None:
            if ref[1] in cur.nested:
                return cur.nested[ref[1]]
            cur = cur.outer
        if ref[1] in scan.toplevel:
            return scan.toplevel[ref[1]]
        imp = scan.imports.get(ref[1])
        if imp and imp[1] is not None:
            return _lookup(scans, imp[0], imp[1])
        return None
    if ref[0] == "mod":
        imp = scan.imports.get(ref[1])
        if imp and imp[1] is None:           # module alias: mod.fn(...)
            return _lookup(scans, imp[0], ref[2])
        if imp and imp[1] is not None:       # from x import y; y.fn() — no
            return None
    return None


def _lookup(scans: Dict[str, _ModScan], modkey: str,
            fname: str) -> Optional[FuncRec]:
    sc = scans.get(modkey)
    if sc is None and "." in modkey:          # absolute import w/ pkg prefix
        sc = scans.get(modkey.split(".", 1)[1])
    if sc is None:
        return None
    return sc.toplevel.get(fname)


def check(mods: Sequence[ModuleInfo]) -> List[Finding]:
    scans: Dict[str, _ModScan] = {}
    static_preds: Dict[str, Set[str]] = {}
    for mod in mods:
        sc = _ModScan(mod=mod)
        _Collector(sc).visit(mod.tree)
        scans[mod.modkey] = sc
        static_preds[mod.modkey] = _static_predicates(mod.tree)

    for sc in scans.values():
        for rec, kws in sc.roots:
            _apply_static_kwargs(rec, kws)

    findings: List[Finding] = []
    seen_idents: Set[str] = set()

    def sink(f: Finding) -> None:
        if f.ident not in seen_idents:
            seen_idents.add(f.ident)
            findings.append(f)

    visited: Set[int] = set()
    work: List[Tuple[FuncRec, _ModScan]] = []
    for sc in scans.values():
        for rec, _ in sc.roots:
            work.append((rec, sc))
    while work:
        rec, sc = work.pop()
        if id(rec.node) in visited:
            continue
        visited.add(id(rec.node))
        names, static = _param_info(rec.node)
        tracers = set(names) - static - rec.static_params
        taint = _local_taint(rec.node, tracers,
                             static_preds.get(rec.modkey, frozenset()))
        rs = _RuleScan(rec, taint, sink)
        body = rec.node.body
        if isinstance(rec.node, ast.Lambda):
            rs.visit(rec.node.body)
        else:
            for stmt in body:
                rs.visit(stmt)
        for ref in rs.calls:
            nxt = _resolve_call(sc, scans, rec, ref)
            if nxt is not None:
                nxt_sc = scans.get(nxt.modkey, sc)
                work.append((nxt, nxt_sc))

    # donate rule: jit callsites over cache-threading functions
    for sc in scans.values():
        for call, target in sc.jit_sites:
            if target is None:
                continue
            _, kws = _jit_call_parts(call) or ([], [])
            if any(k.arg in ("donate_argnums", "donate_argnames")
                   for k in kws):
                continue
            names, _ = _param_info(target.node)
            hit = sorted(set(names) & CACHE_PARAMS)
            if hit:
                findings.append(Finding(
                    "jax-donate",
                    f"jax-donate:{sc.mod.rel}:{target.qual}",
                    sc.mod.path, call.lineno,
                    f"jit of `{target.qual}` threads KV state "
                    f"({','.join(hit)}) without donate_argnums — the pool "
                    f"is double-buffered every step"))
    return findings
