"""dklint rule families 1+2: lock discipline and lock-order cycles.

Both families work from one per-class scan:

* **Lock inventory** — ``self._x = threading.Lock()/RLock()/Condition()``
  assignments anywhere in the class.  A ``Condition(self._y)`` shares the
  identity of the lock it wraps, so acquiring ``self._not_full`` *is*
  acquiring ``self._qlock`` (the grouping the serving admission queue
  relies on).  Each group is keyed by its *root* attribute.

* **Annotations** — two machine-checked comment forms replace free-text
  lock prose:

  - ``self._lk = threading.Lock()  # guards: _a,_b`` — the listed
    attributes may only be touched while holding ``_lk``; any access
    outside it (``__init__`` excepted) is a ``lock-guards`` finding, and
    a listed attribute that no longer exists is a *stale* finding.
  - ``def _apply(self, ...):  # dklint: holds _lock`` — asserts the
    method is only called with ``_lock`` held.  Accesses inside then
    count as locked, and any *visible* same-class call site that does
    not hold the lock is a ``lock-holds`` finding.

* **Discipline inference** — in a class that spawns threads
  (``threading.Thread(...)`` anywhere in its methods, or an explicit
  ``# dklint: threaded`` on the class line), an unannotated attribute
  that is written somewhere outside ``__init__`` and is accessed both
  *under* a lock group and *outside any* lock group is a candidate race
  (``lock-discipline``).  Accesses inside nested functions/lambdas
  inherit the lock context of their definition site — a ``wait_for``
  predicate runs under its condition's lock; a thread target defined at
  top level runs under none.

* **Lock order** — a ``with self._a:`` nested (syntactically, or through
  same-module calls ``self.m()`` / ``self.attr.m()`` with a resolvable
  class) inside ``with self._b:`` adds the edge ``_b → _a`` to a global
  acquisition graph; any cycle is a ``lock-order`` finding.  Same-group
  re-entry is only reported for *syntactic* nesting of a non-reentrant
  ``Lock`` (interprocedural same-lock paths are usually conditional and
  would drown the signal in false positives — the runtime
  :class:`~distkeras_tpu.analysis.runtime.OrderedLock` auditor covers
  those, plus cross-object orders invisible to the AST).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from .core import Finding, ModuleInfo

LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "cond"}
LOCK_METHODS = {"acquire", "release", "locked", "wait", "wait_for",
                "notify", "notify_all"}
#: container-method calls treated as writes to the receiving attribute
MUTATORS = {"append", "appendleft", "pop", "popleft", "push", "add",
            "remove", "discard", "clear", "update", "extend", "insert",
            "setdefault", "popitem", "put", "rotate"}
SKIP_METHODS = {"__init__", "__del__"}

_GUARDS_RE = re.compile(r"#\s*guards:\s*([A-Za-z0-9_,/ \t]+)")
_HOLDS_RE = re.compile(r"#\s*dklint:\s*holds\s+([A-Za-z0-9_,/ \t]+)")
_THREADED_RE = re.compile(r"#\s*dklint:\s*threaded\b")


def _split_attrs(blob: str) -> List[str]:
    return [a for a in re.split(r"[,/\s]+", blob.strip()) if a]


def _self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _lock_ctor(call: ast.AST) -> Optional[str]:
    """'lock' | 'rlock' | 'cond' when ``call`` constructs a threading
    primitive (``threading.X(...)`` or bare ``X(...)``)."""
    if not isinstance(call, ast.Call):
        return None
    fn = call.func
    name = None
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name) \
            and fn.value.id == "threading":
        name = fn.attr
    elif isinstance(fn, ast.Name):
        name = fn.id
    return LOCK_CTORS.get(name)


@dataclass
class Access:
    attr: str
    kind: str                  # 'r' | 'w'
    held: FrozenSet[str]       # lock roots held at the access
    line: int
    method: str


@dataclass
class CallRec:
    callee: Tuple[str, ...]    # ('self', m) | ('attr', X, m)
    held: FrozenSet[str]
    line: int
    method: str


@dataclass
class ClassScan:
    name: str
    mod: ModuleInfo
    node: ast.ClassDef
    bases: List[str] = field(default_factory=list)
    lock_root: Dict[str, str] = field(default_factory=dict)
    lock_kind: Dict[str, str] = field(default_factory=dict)   # root -> kind
    lock_line: Dict[str, int] = field(default_factory=dict)
    guards: Dict[str, Tuple[Set[str], int]] = field(default_factory=dict)
    holds: Dict[str, Set[str]] = field(default_factory=dict)  # method->roots
    attr_types: Dict[str, str] = field(default_factory=dict)
    methods: Dict[str, ast.FunctionDef] = field(default_factory=dict)
    threaded: bool = False
    accesses: List[Access] = field(default_factory=list)
    calls: List[CallRec] = field(default_factory=list)
    acquires: Dict[str, Set[str]] = field(default_factory=dict)  # method->
    nest_edges: List[Tuple[str, str, int]] = field(default_factory=list)
    init_assigned: Set[str] = field(default_factory=set)

    def qual(self, attr: str) -> str:
        return f"{self.name}.{attr}"


# ------------------------------------------------------------- class scan
def _collect_class(mod: ModuleInfo, node: ast.ClassDef) -> ClassScan:
    cs = ClassScan(name=node.name, mod=mod, node=node)
    cs.bases = [b.id for b in node.bases if isinstance(b, ast.Name)]
    header = mod.span_text(node.lineno,
                           node.body[0].lineno if node.body else node.lineno)
    if _THREADED_RE.search(header):
        cs.threaded = True
    for item in node.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            cs.methods[item.name] = item

    # pass A: lock inventory + guards annotations + attr types + Thread use
    cond_wraps: Dict[str, str] = {}     # cond attr -> wrapped attr name
    for mname, fn in cs.methods.items():
        for stmt in ast.walk(fn):
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                tgt = _self_attr(stmt.targets[0])
                if tgt is None:
                    continue
                if mname == "__init__":
                    cs.init_assigned.add(tgt)
                kind = _lock_ctor(stmt.value)
                if kind is not None:
                    cs.lock_kind[tgt] = kind
                    cs.lock_line[tgt] = stmt.lineno
                    if kind == "cond" and stmt.value.args:
                        wrapped = _self_attr(stmt.value.args[0])
                        if wrapped is not None:
                            cond_wraps[tgt] = wrapped
                    m = _GUARDS_RE.search(mod.span_text(
                        stmt.lineno, stmt.end_lineno or stmt.lineno))
                    if m:
                        cs.guards[tgt] = (set(_split_attrs(m.group(1))),
                                          stmt.lineno)
                elif isinstance(stmt.value, ast.Call) and \
                        isinstance(stmt.value.func, ast.Name):
                    cs.attr_types[tgt] = stmt.value.func.id
            if isinstance(stmt, ast.Call):
                fnode = stmt.func
                if (isinstance(fnode, ast.Attribute)
                        and fnode.attr == "Thread") or \
                        (isinstance(fnode, ast.Name)
                         and fnode.id == "Thread"):
                    cs.threaded = True
    # resolve groups: a Condition wrapping a known lock shares its root
    for attr in cs.lock_kind:
        root = attr
        seen = set()
        while root in cond_wraps and cond_wraps[root] in cs.lock_kind \
                and root not in seen:
            seen.add(root)
            root = cond_wraps[root]
        cs.lock_root[attr] = root
    # guards annotations keyed by root
    cs.guards = {cs.lock_root.get(a, a): v for a, v in cs.guards.items()}

    # holds annotations
    for mname, fn in cs.methods.items():
        body_start = fn.body[0].lineno if fn.body else fn.lineno
        m = _HOLDS_RE.search(mod.span_text(fn.lineno, body_start))
        if m:
            roots = {cs.lock_root.get(a, a) for a in _split_attrs(m.group(1))}
            cs.holds[mname] = roots
    return cs


class _MethodWalker(ast.NodeVisitor):
    """Walks one method body tracking the set of held lock roots."""

    def __init__(self, cs: ClassScan, mname: str,
                 init_held: FrozenSet[str]):
        self.cs = cs
        self.mname = mname
        self.held: Tuple[str, ...] = tuple(sorted(init_held))

    # -- helpers
    def _rec(self, attr: str, kind: str, line: int) -> None:
        if attr in self.cs.lock_root:
            return                          # lock objects are not state
        self.cs.accesses.append(Access(attr, kind, frozenset(self.held),
                                       line, self.mname))

    def _acquire(self, root: str, line: int):
        for h in self.held:
            if h != root:
                self.cs.nest_edges.append((h, root, line))
            elif self.cs.lock_kind.get(root) == "lock" and \
                    root not in self.cs.holds.get(self.mname, ()):
                # syntactic re-entry of a non-reentrant Lock
                self.cs.nest_edges.append((root, root, line))
        self.cs.acquires.setdefault(self.mname, set()).add(root)
        self.held = tuple(sorted(set(self.held) | {root}))

    # -- lock-scoped blocks
    def visit_With(self, node: ast.With) -> None:
        saved = self.held
        for item in node.items:
            attr = _self_attr(item.context_expr)
            if attr is not None and attr in self.cs.lock_root:
                self._acquire(self.cs.lock_root[attr], item.context_expr.lineno)
            else:
                self.visit(item.context_expr)
                if item.optional_vars is not None:
                    self.visit(item.optional_vars)
        for stmt in node.body:
            self.visit(stmt)
        self.held = saved

    # -- nested functions inherit the lock context of their definition site
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        for stmt in node.body:
            self.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self.visit(node.body)

    # -- accesses
    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = _self_attr(node)
        if attr is not None:
            kind = "w" if isinstance(node.ctx, (ast.Store, ast.Del)) else "r"
            self._rec(attr, kind, node.lineno)
            return
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            attr = _self_attr(node.value)
            if attr is not None:
                self._rec(attr, "w", node.lineno)
                self.visit(node.slice)
                return
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Attribute):
            recv = fn.value
            # self.m(...)
            if isinstance(recv, ast.Name) and recv.id == "self":
                self.cs.calls.append(CallRec(("self", fn.attr),
                                             frozenset(self.held),
                                             node.lineno, self.mname))
                for a in node.args:
                    self.visit(a)
                for kw in node.keywords:
                    self.visit(kw.value)
                return
            # self.X.m(...)
            attr = _self_attr(recv)
            if attr is not None:
                if attr in self.cs.lock_root and fn.attr in LOCK_METHODS:
                    pass                        # explicit lock calls: see docs
                else:
                    kind = "w" if fn.attr in MUTATORS else "r"
                    self._rec(attr, kind, recv.lineno)
                    if attr in self.cs.attr_types:
                        self.cs.calls.append(
                            CallRec(("attr", attr, fn.attr),
                                    frozenset(self.held), node.lineno,
                                    self.mname))
                for a in node.args:
                    self.visit(a)
                for kw in node.keywords:
                    self.visit(kw.value)
                return
        self.generic_visit(node)


def _scan_methods(cs: ClassScan) -> None:
    for mname, fn in cs.methods.items():
        if mname in SKIP_METHODS:
            continue
        init_held = frozenset(cs.holds.get(mname, set()))
        w = _MethodWalker(cs, mname, init_held)
        for stmt in fn.body:
            w.visit(stmt)


# -------------------------------------------------- inheritance flattening
def _flatten(classes: Dict[str, ClassScan]) -> None:
    """Merge base-class scan data into same-module subclasses so inherited
    state (``ParameterServer.num_updates`` under ``SocketParameterServer``'s
    threads) is judged in the derived class's threading context."""
    order: List[str] = []
    seen: Set[str] = set()

    def visit(name: str) -> None:
        if name in seen or name not in classes:
            return
        seen.add(name)
        for b in classes[name].bases:
            visit(b)
        order.append(name)

    for name in classes:
        visit(name)
    for name in order:
        cs = classes[name]
        for b in cs.bases:
            if b not in classes:
                continue
            base = classes[b]
            cs.threaded = cs.threaded or base.threaded
            for a, r in base.lock_root.items():
                cs.lock_root.setdefault(a, r)
                cs.lock_kind.setdefault(r, base.lock_kind.get(r, "lock"))
            for r, g in base.guards.items():
                cs.guards.setdefault(r, g)
            for m, h in base.holds.items():
                cs.holds.setdefault(m, set()).update(h)
            for m, fn in base.methods.items():
                cs.methods.setdefault(m, fn)
            cs.init_assigned |= base.init_assigned
            # bring over accesses/calls/acquires made by inherited methods
            inherited = {m for m in base.methods
                         if m not in {x.name for x in cs.node.body
                                      if isinstance(x, ast.FunctionDef)}}
            cs.accesses += [a for a in base.accesses
                            if a.method in inherited]
            cs.calls += [c for c in base.calls if c.method in inherited]
            for m, acq in base.acquires.items():
                if m in inherited:
                    cs.acquires.setdefault(m, set()).update(acq)


# ------------------------------------------------------------ discipline
def _discipline(cs: ClassScan) -> List[Finding]:
    out: List[Finding] = []
    rel, cls = cs.mod.rel, cs.name
    guarded: Dict[str, str] = {}     # attr -> root
    for root, (attrs, line) in sorted(cs.guards.items()):
        for a in sorted(attrs):
            guarded[a] = root

    by_attr: Dict[str, List[Access]] = {}
    for a in cs.accesses:
        by_attr.setdefault(a.attr, []).append(a)

    # annotation-checked attrs: every access must hold the declared lock
    for attr, root in sorted(guarded.items()):
        accs = by_attr.get(attr, [])
        if not accs and attr not in cs.init_assigned:
            line = cs.guards[root][1]
            out.append(Finding(
                "lock-guards", f"lock-guards:{rel}:{cls}.{attr}:stale",
                cs.mod.path, line,
                f"`# guards:` on {cls}.{root} lists `{attr}`, but no such "
                f"attribute is assigned or accessed — stale annotation"))
            continue
        bad = sorted({a.line for a in accs if root not in a.held})
        if bad:
            shown = ",".join(map(str, bad[:6]))
            out.append(Finding(
                "lock-guards", f"lock-guards:{rel}:{cls}.{attr}",
                cs.mod.path, bad[0],
                f"{cls}.{attr} is declared `# guards: ...` by {root} "
                f"(line {cs.guards[root][1]}) but accessed without it at "
                f"line(s) {shown}"))

    if not cs.threaded:
        return out

    for attr in sorted(by_attr):
        if attr in guarded:
            continue
        accs = by_attr[attr]
        writes = [a for a in accs if a.kind == "w"]
        if not writes:
            continue                      # init-only / read-only state
        locked = [a for a in accs if a.held]
        unlocked = [a for a in accs if not a.held]
        if not locked or not unlocked:
            continue
        roots = sorted({r for a in locked for r in a.held})
        ul = sorted({a.line for a in unlocked})
        shown = ",".join(map(str, ul[:6])) + ("…" if len(ul) > 6 else "")
        detail = (f"under {roots[0]}" if len(roots) == 1
                  else f"under multiple locks ({'/'.join(roots)})")
        out.append(Finding(
            "lock-discipline", f"lock-discipline:{rel}:{cls}.{attr}",
            cs.mod.path, ul[0],
            f"{cls}.{attr} is accessed {detail} in {len(locked)} place(s) "
            f"but touched with no lock held at line(s) {shown} in a "
            f"thread-spawning class — candidate race (annotate the lock "
            f"with `# guards:` or take it)"))
    return out


# ------------------------------------------------------------- holds rule
def _holds_check(cs: ClassScan) -> List[Finding]:
    out: List[Finding] = []
    rel, cls = cs.mod.rel, cs.name
    for c in cs.calls:
        if c.callee[0] != "self":
            continue
        callee = c.callee[1]
        need = cs.holds.get(callee)
        if not need:
            continue
        missing = sorted(need - c.held)
        if missing:
            out.append(Finding(
                "lock-holds",
                f"lock-holds:{rel}:{cls}.{c.method}->{callee}",
                cs.mod.path, c.line,
                f"{cls}.{c.method} calls {callee}() (annotated "
                f"`# dklint: holds {','.join(sorted(need))}`) without "
                f"holding {','.join(missing)}"))
    return out


# ------------------------------------------------------------- lock order
def _order_edges(classes: Dict[str, ClassScan]
                 ) -> Dict[Tuple[str, str], Tuple[str, int]]:
    """Global acquisition-order edges ``(src, dst) -> (site, line)`` over
    node labels ``modkey.Class._root``."""
    # transitive closure of per-method acquire sets
    acq: Dict[Tuple[str, str], Set[str]] = {}
    for cname, cs in classes.items():
        for m, roots in cs.acquires.items():
            acq[(cname, m)] = {f"{cs.mod.modkey}.{cname}.{r}" for r in roots}
        for m in cs.methods:
            acq.setdefault((cname, m), set())
    changed = True
    while changed:
        changed = False
        for cname, cs in classes.items():
            for c in cs.calls:
                if c.callee[0] == "self":
                    key = (cname, c.callee[1])
                elif c.callee[0] == "attr":
                    tname = cs.attr_types.get(c.callee[1])
                    if tname not in classes:
                        continue
                    key = (tname, c.callee[2])
                else:
                    continue
                add = acq.get(key)
                if not add:
                    continue
                cur = acq.setdefault((cname, c.method), set())
                if not add <= cur:
                    cur |= add
                    changed = True

    edges: Dict[Tuple[str, str], Tuple[str, int]] = {}

    def put(src: str, dst: str, path: str, line: int) -> None:
        edges.setdefault((src, dst), (path, line))

    for cname, cs in classes.items():
        label = lambda r: f"{cs.mod.modkey}.{cname}.{r}"   # noqa: E731
        for src, dst, line in cs.nest_edges:
            put(label(src), label(dst), cs.mod.path, line)
        for c in cs.calls:
            if not c.held:
                continue
            if c.callee[0] == "self":
                key = (cname, c.callee[1])
            elif c.callee[0] == "attr":
                tname = cs.attr_types.get(c.callee[1])
                if tname not in classes:
                    continue
                key = (tname, c.callee[2])
            else:
                continue
            for dst in acq.get(key, ()):
                for h in c.held:
                    src = label(h)
                    if src != dst:      # interprocedural same-lock: runtime's
                        put(src, dst, cs.mod.path, c.line)
    return edges


def _cycles(edges: Dict[Tuple[str, str], Tuple[str, int]]
            ) -> List[List[str]]:
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    # Tarjan SCC
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    stack: List[str] = []
    on: Set[str] = set()
    out: List[List[str]] = []
    counter = [0]

    def strong(v: str) -> None:
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        for w in sorted(graph[v]):
            if w not in index:
                strong(w)
                low[v] = min(low[v], low[w])
            elif w in on:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                on.discard(w)
                comp.append(w)
                if w == v:
                    break
            if len(comp) > 1 or (len(comp) == 1
                                 and comp[0] in graph[comp[0]]):
                out.append(sorted(comp))

    for v in sorted(graph):
        if v not in index:
            strong(v)
    return out


def check(mods: Sequence[ModuleInfo]) -> List[Finding]:
    classes: Dict[str, ClassScan] = {}
    for mod in mods:
        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef):
                cs = _collect_class(mod, node)
                _scan_methods(cs)
                # first scan wins on (unlikely) cross-module name clashes
                classes.setdefault(node.name, cs)
    _flatten(classes)

    out: List[Finding] = []
    for cname in sorted(classes):
        cs = classes[cname]
        if not cs.lock_root:
            continue
        out += _discipline(cs)
        out += _holds_check(cs)

    edges = _order_edges(classes)
    for comp in _cycles(edges):
        sites = sorted({f"{p}:{ln}" for (a, b), (p, ln) in edges.items()
                        if a in comp and b in comp})
        ident = "lock-order:" + "<->".join(comp)
        first = min((ln for (a, b), (p, ln) in edges.items()
                     if a in comp and b in comp), default=0)
        path = next((p for (a, b), (p, ln) in edges.items()
                     if a in comp and b in comp), "?")
        if len(comp) == 1:
            msg = (f"non-reentrant lock {comp[0]} is acquired while "
                   f"already held (self-deadlock) at {', '.join(sites)}")
        else:
            msg = (f"lock acquisition-order cycle between "
                   f"{' and '.join(comp)} — inversion sites: "
                   f"{', '.join(sites)}")
        out.append(Finding("lock-order", ident, path, first, msg))
    return out
